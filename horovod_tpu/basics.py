"""Runtime lifecycle and identity API.

Reference: horovod/common/basics.py — HorovodBasics (init/shutdown/rank/size/
local_rank/..., built-with queries; SURVEY.md §2.4).  Where the reference
loads a per-framework shared library over ctypes, this module drives the
TPU-native core (native C++ when built, pure-Python local core otherwise)
and additionally owns the global device mesh.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from .context import HorovodContext
from .exceptions import HorovodInternalError
from .utils.env import Config, get_bool
from .utils.logging import get_logger
from .parallel import mesh as _mesh

log = get_logger()

# jax.distributed runtime state owned by this module.  The runtime is
# process-level: across hvd shutdown/init cycles with unchanged
# (coordinator, size, rank) it is simply reused; an elastic round that
# reassigns any of them tears it down and re-initializes (clearing XLA
# backends first — jax refuses to re-initialize once a backend exists).
_jax_distributed_up = False
_jax_dist_params = None


def init(comm=None, process_sets: Optional[Sequence] = None,
         config: Optional[Config] = None, build_mesh: bool = True) -> None:
    """Initialize Horovod.

    ``comm`` exists for signature parity with the reference (an MPI
    communicator there); passing a list of ranks restricts the world like a
    root communicator split would.  ``process_sets`` pre-registers process
    sets exactly like the reference's ``hvd.init(process_sets=...)``.
    """
    if HorovodContext.initialized():
        return
    # Elastic mode: the driver assigns rank/size per rendezvous round over
    # the coordinator connection before the core can start (SURVEY.md §3.5).
    if config is None and os.environ.get("HOROVOD_ELASTIC") == "1":
        from .elastic import client as _elastic_client

        _elastic_client.ensure_assignment()
    cfg = config or Config.from_env()
    if comm is not None and not isinstance(comm, (list, tuple)):
        raise ValueError(
            "comm must be None or a list of ranks; MPI communicators do not "
            "exist in the TPU build"
        )
    ctx = HorovodContext.init(cfg)

    # Optional multi-host JAX runtime wiring (TPU pods): the launcher sets
    # HOROVOD_JAX_DISTRIBUTED=1 plus coordinator env; analogous to how the
    # reference's launcher passes rendezvous env to Gloo (SURVEY.md §3.4).
    if get_bool("HOROVOD_JAX_DISTRIBUTED", False):  # pragma: no cover - pod only
        import jax

        # Cross-process collectives on the CPU platform (the no-TPU test
        # harness, SURVEY.md §4) need the gloo transport; TPU pods use ICI
        # and must keep the default.
        if "cpu" in str(getattr(jax.config, "jax_platforms", "") or ""):
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass
        global _jax_distributed_up, _jax_dist_params
        # The elastic generation epoch participates so every process of a
        # new generation re-initializes together (a survivor must not keep
        # a runtime whose coordination service already saw a peer die).
        params = (os.environ.get("HOROVOD_JAX_COORDINATOR"), cfg.size,
                  cfg.rank, os.environ.get("HOROVOD_ELASTIC_GENERATION"))
        if not (_jax_distributed_up and _jax_dist_params == params):
            if _jax_distributed_up:
                try:
                    jax.distributed.shutdown()
                except Exception as exc:
                    log.warning("jax.distributed shutdown failed: %s", exc)
                _jax_distributed_up = False
                # Cleared backends let initialize() pass its
                # backends_are_initialized() guard.  Try the public API
                # first; the private impl is a fallback for jax versions
                # where the alias was removed.
                cleared = False
                public = getattr(jax, "clear_backends", None)
                if public is not None:
                    try:
                        public()
                        cleared = True
                    except Exception as exc:
                        log.warning("jax.clear_backends failed: %s", exc)
                if not cleared:
                    try:
                        from jax._src import api as _jax_api

                        _jax_api.clear_backends()
                        cleared = True
                    except Exception as exc:
                        log.warning("clear_backends failed: %s", exc)
                if not cleared:
                    # Proceeding would hit initialize()'s backends-already-
                    # initialized error anyway — degrade explicitly with a
                    # named, actionable failure instead (ADVICE r2).
                    raise HorovodInternalError(
                        "elastic re-initialization could not clear jax "
                        "backends on this jax version; this process cannot "
                        "rejoin the new generation in-place and must be "
                        "restarted (the elastic driver respawns it)")
            jax.distributed.initialize(
                coordinator_address=params[0],
                num_processes=cfg.size,
                process_id=cfg.rank,
            )
            _jax_distributed_up = True
            _jax_dist_params = params

    if build_mesh:
        try:
            _mesh.build_global_mesh()
        except Exception as exc:
            # Under a multi-host runtime the mesh IS the data plane; hiding a
            # build failure would desync the pod silently, so fail hard.
            if get_bool("HOROVOD_JAX_DISTRIBUTED", False):
                raise RuntimeError(
                    f"global mesh build failed under jax.distributed: {exc}"
                ) from exc
            log.warning("global mesh not built: %s", exc)

    if process_sets:
        from .process_sets import add_process_set

        for ps in process_sets:
            add_process_set(ps)


def shutdown() -> None:
    # The jax.distributed runtime deliberately survives shutdown: it is
    # process-level, and the next init reuses it when (coordinator, size,
    # rank) are unchanged or re-initializes when they differ (elastic).
    HorovodContext.shutdown()
    _mesh.reset()


def is_initialized() -> bool:
    return HorovodContext.initialized()


def initialized() -> bool:  # reference alias
    return HorovodContext.initialized()


def rank() -> int:
    return HorovodContext.instance().core.rank()


def size() -> int:
    return HorovodContext.instance().core.size()


def local_rank() -> int:
    return HorovodContext.instance().cfg.local_rank


def local_size() -> int:
    return HorovodContext.instance().cfg.local_size


def cross_rank() -> int:
    return HorovodContext.instance().cfg.cross_rank


def cross_size() -> int:
    return HorovodContext.instance().cfg.cross_size


def is_homogeneous() -> bool:
    """True if every host runs the same number of ranks."""
    ctx = HorovodContext.instance()
    return ctx.cfg.size % max(ctx.cfg.local_size, 1) == 0


def num_devices() -> int:
    """Local JAX device count (TPU-build extension)."""
    import jax

    return jax.local_device_count()


# -- metrics ----------------------------------------------------------------

def metrics() -> dict:
    """Local metrics-registry snapshot: counters (cycle occupancy, fusion
    efficiency, stall warnings) and power-of-two-bucket histograms
    (negotiation wait, ring hop latency, shm fence wait).  On rank 0 the
    dict also carries ``cluster`` (per-rank snapshots aggregated by the
    coordinator) and ``straggler_report``.  A non-empty dump additionally
    carries ``plane_counters`` — the gspmd plane's Python-side
    selection/demotion counters (ops/gspmd_plane.py), rendered by
    ``metrics_prometheus()`` as ``hvd_plane_demotions_total{reason=...}``
    / ``hvd_plane_selected_total{plane=...}``.  Empty when the metrics
    plane is disabled or the backend has no native registry."""
    dump = HorovodContext.instance().core.metrics()
    if dump:
        try:
            from .ops.gspmd_plane import plane_counters
            pc = plane_counters()
        except Exception:
            pc = {}
        if pc:
            dump["plane_counters"] = pc
    return dump


def metrics_prometheus() -> str:
    """The same snapshot rendered in Prometheus text exposition format
    (``hvd_*`` families; see docs/observability.md for the naming scheme)."""
    from .utils.metrics import render_prometheus

    return render_prometheus(metrics())


def flight_record() -> dict:
    """Snapshot of this rank's flight-recorder ring — the always-on event
    black box (rendezvous, cycle sends/recvs, verdicts, ring hops, shm
    fences, aggregate frames, fault trips, aborts).  Keys: ``rank``,
    ``host``, ``slots``, ``dropped``, ``types`` (event-type legend) and
    ``events`` as ``[ts_us, seq, type, tid, a, b]`` rows, oldest first.
    Empty when HOROVOD_FLIGHT_RECORDER=off or the backend has no native
    recorder.  On abort the same payload is written per rank under
    HOROVOD_POSTMORTEM_DIR and merged by the coordinator into
    ``postmortem.json`` (render with ``tools/postmortem.py``)."""
    return HorovodContext.instance().core.flight_record()


def step_trace() -> dict:
    """Snapshot of this rank's causal step-trace ring — the fifth
    observability pillar.  Keys: ``rank``, ``world``, ``phases`` (the
    breakdown order: negotiation_wait / fusion / ring / fence / idle),
    ``steps`` as ``[step, start_us, end_us, <5 phase us>]`` rows, and on
    rank 0 ``fleet`` — per-step cross-rank phase sums with
    ``dominant_phase`` / ``dominant_rank`` attribution.  Empty when
    HOROVOD_STEP_TRACE=off or the backend has no native tracer.  The same
    payload is written to HOROVOD_POSTMORTEM_DIR as
    ``steptrace.<rank>.json`` at shutdown/abort for
    ``tools/critical_path.py``."""
    return HorovodContext.instance().core.step_trace()


def fleet_history() -> dict:
    """The coordinator's multi-resolution fleet history + anomaly log —
    the sixth observability pillar (fleet telemetry, protocol v11).
    Keys: ``schema`` (``fleethistory-v1``), ``columns`` (the sample row
    legend: ``[ts_us, step_p99_us, neg_p99_us, goodput_ppm,
    wire_ratio_ppm, steps]``), ``tiers`` (1 s / 10 s / 60 s downsampled
    rings, each ``{"period_s", "samples"}``) and ``anomalies`` (the
    streaming sentinel's log, newest last, each naming the series, the
    dominant rank and the z-score).  Meaningful on rank 0 (the only rank
    that ticks); empty when HOROVOD_FLEET_TELEMETRY=off or the backend
    has no native plane.  Fleet HISTOGRAMS (true cross-rank merges) live
    in ``metrics()["fleet"]``; this call serves their time axis."""
    return HorovodContext.instance().core.fleet_history()


# -- timeline ---------------------------------------------------------------

def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    HorovodContext.instance().core.start_timeline(file_path, mark_cycles)


def stop_timeline() -> None:
    HorovodContext.instance().core.stop_timeline()


def start_device_trace(logdir: str) -> None:
    """Start the XLA profiler (TensorBoard trace) — the on-device half of
    observability: the host timeline covers NEGOTIATE/data-plane phases,
    this covers the compiled XLA programs on the chip (SURVEY.md §5:
    timeline hand-off into jax.profiler)."""
    import jax

    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax

    jax.profiler.stop_trace()


# -- build-configuration queries (reference API parity) ---------------------

def mpi_threads_supported() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    # The socket controller fills Gloo's role (MPI-free CPU control+data
    # plane); report it under the reference's query for script parity.
    return True


def gloo_built() -> bool:
    return True


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def tpu_built() -> bool:
    """TPU-build extension: the XLA/ICI data plane is always available."""
    return True


def native_core_built() -> bool:
    """True if the C++ core library is importable."""
    try:
        from . import _core  # noqa: F401

        return True
    except Exception:
        return False
