"""Public collective API: hvd.allreduce / allgather / broadcast / alltoall /
reducescatter, in synchronous, async-handle, and grouped forms.

Reference analogs: horovod/torch/mpi_ops.py (allreduce_async_/synchronize/
poll handle API) and horovod/tensorflow/__init__.py (op wrappers); SURVEY.md
§2.4, §3.2.  The module name is kept for import parity, though no MPI exists
anywhere in this build.

Dispatch is dual, matching how the two execution worlds meet on TPU:

- **Traced** (argument is a JAX tracer, i.e. we are inside ``jit`` /
  ``shard_map``): the call compiles directly to an XLA collective over the
  named mesh axis (``horovod_tpu.ops.collectives``) — the ICI data plane.
- **Eager**: the call enqueues into the core runtime, which negotiates
  readiness across ranks, fuses, and executes — the Horovod spine
  (``horovod_tpu.context``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .context import HorovodContext
from .process_sets import ProcessSet, _resolve_psid
from .wire import OpType, ReduceOp, Average, Sum, Min, Max, Product, Adasum
from .ops import collectives as _jit_ops
from .parallel import mesh as _mesh


def _is_traced(x) -> bool:
    try:
        import jax

        return isinstance(x, jax.core.Tracer)
    except ImportError:  # pragma: no cover
        return False


def _axis(axis_name: Optional[str]) -> str:
    return axis_name if axis_name is not None else _mesh.mesh_axis_name()


def _traced_members(process_set) -> Optional[tuple]:
    """ProcessSet -> member axis indices for the traced (in-jit) path.

    The bridge: a ProcessSet's global ranks ARE axis indices over the
    reduction axis (the global mesh is built rank-ordered —
    parallel.mesh.build_global_mesh), so the traced collective masks its
    full-axis lowering to the member subset (ops.collectives._Subset).
    The global set (id 0) and None mean the whole axis.  Registration is
    only required for the eager spine; traced mode needs just the ranks,
    so unregistered sets work inside pure SPMD programs without hvd.init().
    """
    if process_set is None:
        return None
    from .process_sets import _GlobalProcessSet

    if isinstance(process_set, _GlobalProcessSet) \
            or process_set.process_set_id == 0:
        return None
    return tuple(process_set.ranks)


def _check_eager_args(axis_name) -> None:
    if axis_name is not None:
        raise ValueError(
            "axis_name is only meaningful inside jit/shard_map (traced mode); "
            "eager collectives take process_set= instead"
        )


def _resolve_op(op: Optional[ReduceOp], average: Optional[bool]) -> ReduceOp:
    if average is not None:
        if op is not None:
            raise ValueError("specify either op or the deprecated average=, not both")
        return ReduceOp.AVERAGE if average else ReduceOp.SUM
    return ReduceOp.AVERAGE if op is None else op


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def allreduce(tensor, average: Optional[bool] = None, name: Optional[str] = None,
              compression=None, op: Optional[ReduceOp] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: Optional[ProcessSet] = None,
              axis_name: Optional[str] = None):
    """Average (default) or otherwise reduce ``tensor`` across ranks."""
    rop = _resolve_op(op, average)
    if _is_traced(tensor):
        return _jit_ops.allreduce(tensor, _axis(axis_name), rop,
                                  prescale_factor, postscale_factor,
                                  member_ranks=_traced_members(process_set))
    _check_eager_args(axis_name)
    from .compression import NoneCompressor

    compression = compression or NoneCompressor
    compressed, ctx = compression.compress(tensor)
    handle = allreduce_async(compressed, name=name, op=rop,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor,
                             process_set=process_set)
    return compression.decompress(synchronize(handle), ctx)


def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                    process_set: Optional[ProcessSet] = None) -> int:
    rop = _resolve_op(op, average)
    return HorovodContext.instance().enqueue(
        tensor, OpType.ALLREDUCE, name=name, reduce_op=rop,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=_resolve_psid(process_set),
    )


# JAX arrays are immutable; the in-place variants exist for API parity and
# simply return the reduced value.
allreduce_ = allreduce
allreduce_async_ = allreduce_async


def grouped_allreduce(tensors: Sequence, average: Optional[bool] = None,
                      name: Optional[str] = None, op: Optional[ReduceOp] = None,
                      prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                      process_set: Optional[ProcessSet] = None,
                      axis_name: Optional[str] = None) -> List:
    """Allreduce a list of tensors as one atomic negotiation group
    (reference: group_table.cc grouped_allreduce)."""
    rop = _resolve_op(op, average)
    if tensors and _is_traced(tensors[0]):
        ax = _axis(axis_name)
        members = _traced_members(process_set)
        return [_jit_ops.allreduce(t, ax, rop, prescale_factor,
                                   postscale_factor, member_ranks=members)
                for t in tensors]
    _check_eager_args(axis_name)
    handles = grouped_allreduce_async(
        tensors, name=name, op=rop, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, process_set=process_set)
    return [synchronize(h) for h in handles]


def grouped_allreduce_async(tensors: Sequence, average: Optional[bool] = None,
                            name: Optional[str] = None,
                            op: Optional[ReduceOp] = None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: Optional[ProcessSet] = None) -> List[int]:
    rop = _resolve_op(op, average)
    ctx = HorovodContext.instance()
    # Unnamed groups fall back to the per-tensor deterministic auto-name
    # (context noname counter): names must MATCH across ranks for
    # negotiation, so a process-local id() would deadlock.  The group key
    # makes negotiation ATOMIC: the coordinator withholds the whole group
    # until every member is ready on every rank, then emits the members
    # contiguously (reference: group_table.cc).
    gkey = ctx.group_key_for(name)
    return [
        ctx.enqueue(t, OpType.ALLREDUCE,
                    name=f"{name}.{i}" if name else None, reduce_op=rop,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    process_set_id=_resolve_psid(process_set),
                    group_key=gkey, group_size=len(tensors))
        for i, t in enumerate(tensors)
    ]


grouped_allreduce_ = grouped_allreduce
grouped_allreduce_async_ = grouped_allreduce_async


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------

def allgather(tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None,
              axis_name: Optional[str] = None):
    """Concatenate each rank's tensor along dim 0 (ranks may differ in dim 0
    in eager mode; traced mode requires equal shapes — an XLA constraint)."""
    if _is_traced(tensor):
        return _jit_ops.allgather(tensor, _axis(axis_name),
                                  member_ranks=_traced_members(process_set))
    _check_eager_args(axis_name)
    return synchronize(allgather_async(tensor, name=name, process_set=process_set))


def allgather_async(tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    return HorovodContext.instance().enqueue(
        tensor, OpType.ALLGATHER, name=name,
        process_set_id=_resolve_psid(process_set),
    )


def grouped_allgather(tensors: Sequence, name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None,
                      axis_name: Optional[str] = None) -> List:
    """Allgather a list of tensors as one atomic negotiation group
    (reference: grouped_allgather, group_table.cc)."""
    if tensors and _is_traced(tensors[0]):
        ax = _axis(axis_name)
        members = _traced_members(process_set)
        return [_jit_ops.allgather(t, ax, member_ranks=members)
                for t in tensors]
    _check_eager_args(axis_name)
    return [synchronize(h) for h in grouped_allgather_async(
        tensors, name=name, process_set=process_set)]


def grouped_allgather_async(tensors: Sequence, name: Optional[str] = None,
                            process_set: Optional[ProcessSet] = None
                            ) -> List[int]:
    ctx = HorovodContext.instance()
    # See grouped_allreduce_async: names must match across ranks; the
    # group key makes the negotiation atomic.
    gkey = ctx.group_key_for(name)
    return [ctx.enqueue(t, OpType.ALLGATHER,
                        name=f"{name}.{i}" if name else None,
                        process_set_id=_resolve_psid(process_set),
                        group_key=gkey, group_size=len(tensors))
            for i, t in enumerate(tensors)]


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------

def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None,
              axis_name: Optional[str] = None):
    if _is_traced(tensor):
        return _jit_ops.broadcast(tensor, root_rank, _axis(axis_name),
                                  member_ranks=_traced_members(process_set))
    _check_eager_args(axis_name)
    return synchronize(
        broadcast_async(tensor, root_rank, name=name, process_set=process_set))


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    return HorovodContext.instance().enqueue(
        tensor, OpType.BROADCAST, name=name, root_rank=root_rank,
        process_set_id=_resolve_psid(process_set),
    )


broadcast_ = broadcast
broadcast_async_ = broadcast_async


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------

def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None,
             axis_name: Optional[str] = None):
    """Distribute slices of dim 0 to all ranks.

    Eager mode returns ``(received_tensor, received_splits)`` like the
    reference's torch binding; traced mode requires equal splits (static
    shapes) and returns just the tensor.
    """
    if _is_traced(tensor):
        if splits is not None:
            raise ValueError(
                "in-jit alltoall requires equal splits (XLA static shapes); "
                "omit the splits argument"
            )
        return _jit_ops.alltoall(tensor, _axis(axis_name),
                                 member_ranks=_traced_members(process_set))
    _check_eager_args(axis_name)
    return HorovodContext.instance().synchronize(
        alltoall_async(tensor, splits=splits, name=name, process_set=process_set))


def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    return HorovodContext.instance().enqueue(
        tensor, OpType.ALLTOALL, name=name, splits=splits,
        process_set_id=_resolve_psid(process_set),
    )


# ---------------------------------------------------------------------------
# reducescatter
# ---------------------------------------------------------------------------

def reducescatter(tensor, op: ReduceOp = ReduceOp.AVERAGE,
                  name: Optional[str] = None,
                  prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                  process_set: Optional[ProcessSet] = None,
                  axis_name: Optional[str] = None):
    if _is_traced(tensor):
        return _jit_ops.reducescatter(
            tensor, _axis(axis_name), op, prescale_factor, postscale_factor,
            member_ranks=_traced_members(process_set))
    _check_eager_args(axis_name)
    return synchronize(reducescatter_async(
        tensor, op=op, name=name, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, process_set=process_set))


def reducescatter_async(tensor, op: ReduceOp = ReduceOp.AVERAGE,
                        name: Optional[str] = None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0,
                        process_set: Optional[ProcessSet] = None) -> int:
    return HorovodContext.instance().enqueue(
        tensor, OpType.REDUCESCATTER, name=name, reduce_op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=_resolve_psid(process_set),
    )


def grouped_reducescatter(tensors: Sequence,
                          op: ReduceOp = ReduceOp.AVERAGE,
                          name: Optional[str] = None,
                          prescale_factor: float = 1.0,
                          postscale_factor: float = 1.0,
                          process_set: Optional[ProcessSet] = None,
                          axis_name: Optional[str] = None) -> List:
    """Reducescatter a list of tensors as one atomic negotiation group
    (reference: grouped_reducescatter, group_table.cc)."""
    if tensors and _is_traced(tensors[0]):
        ax = _axis(axis_name)
        members = _traced_members(process_set)
        return [_jit_ops.reducescatter(t, ax, op, prescale_factor,
                                       postscale_factor,
                                       member_ranks=members)
                for t in tensors]
    _check_eager_args(axis_name)
    return [synchronize(h) for h in grouped_reducescatter_async(
        tensors, op=op, name=name, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, process_set=process_set)]


def grouped_reducescatter_async(tensors: Sequence,
                                op: ReduceOp = ReduceOp.AVERAGE,
                                name: Optional[str] = None,
                                prescale_factor: float = 1.0,
                                postscale_factor: float = 1.0,
                                process_set: Optional[ProcessSet] = None
                                ) -> List[int]:
    ctx = HorovodContext.instance()
    # See grouped_allreduce_async: names must match across ranks; the
    # group key makes the negotiation atomic.
    gkey = ctx.group_key_for(name)
    return [ctx.enqueue(t, OpType.REDUCESCATTER,
                        name=f"{name}.{i}" if name else None,
                        reduce_op=op, prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                        process_set_id=_resolve_psid(process_set),
                        group_key=gkey, group_size=len(tensors))
            for i, t in enumerate(tensors)]


# ---------------------------------------------------------------------------
# barrier / handle management
# ---------------------------------------------------------------------------

def barrier(process_set: Optional[ProcessSet] = None) -> None:
    """Block until all ranks of the set reach the barrier
    (reference: horovod_barrier in operations.cc)."""
    ctx = HorovodContext.instance()
    h = ctx.enqueue(np.zeros((), dtype=np.float32), OpType.BARRIER,
                    process_set_id=_resolve_psid(process_set))
    ctx.synchronize(h)


def join() -> int:
    """Signal that this rank has no more collectives to submit; block until
    every rank has joined, then return the last rank that joined
    (reference: hvd.join in torch/mpi_ops.py — the uneven-batches
    mechanism).  While this rank waits, other ranks' sum/average allreduces
    and barriers proceed with a zero contribution from it; Average still
    divides by the full process-set size, matching the reference's
    documented join semantics."""
    ctx = HorovodContext.instance()
    with ctx._entries_lock:
        ctx._joined = True
    h = ctx.enqueue(np.zeros((), dtype=np.float32), OpType.JOIN,
                    name="__join__")
    return int(np.asarray(ctx.synchronize(h)))


def synchronize(handle: int):
    """Block until the async op behind ``handle`` completes; return its
    result (reference: horovod/torch/mpi_ops.py synchronize)."""
    return HorovodContext.instance().synchronize(handle)


def poll(handle: int) -> bool:
    """True if the async op behind ``handle`` has completed."""
    return HorovodContext.instance().poll(handle)
