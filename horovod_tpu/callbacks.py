"""Training-loop callbacks and LR schedule helpers.

Reference analogs (SURVEY.md §2.4): horovod/_keras/callbacks.py —
BroadcastGlobalVariablesCallback, MetricAverageCallback,
LearningRateWarmupCallback, LearningRateScheduleCallback.

TPU-native split: anything *schedule-shaped* becomes an optax schedule (it
compiles into the training step — no per-epoch Python callbacks mutating an
optimizer), while the cross-rank actions (broadcast at start, metric
averaging) stay imperative callbacks over the eager collective path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np
import optax

from . import basics
from .functions import broadcast_parameters
from .mpi_ops import allreduce
from .wire import ReduceOp


# ---------------------------------------------------------------------------
# Schedules (compiled into the step — the TPU-idiomatic form)
# ---------------------------------------------------------------------------

def warmup_schedule(base_lr: float, warmup_steps: int,
                    initial_factor: float = 1.0 / 3.0,
                    after: Optional[optax.Schedule] = None) -> optax.Schedule:
    """The Horovod-paper LR warmup (reference: LearningRateWarmupCallback):
    ramp from ``base_lr * initial_factor`` to ``size() * base_lr`` over
    ``warmup_steps``, then hand off to ``after`` (default: constant scaled
    LR).  Scaling by world size implements the linear-scaling rule the
    reference's docs prescribe for large-batch DP training.

    World size is read when the schedule is *evaluated/traced*, not when it
    is constructed, so building the schedule before ``hvd.init()`` still
    applies the scaling rule.
    """
    import jax.numpy as jnp

    steps = max(warmup_steps, 1)

    def schedule(step):
        size = basics.size() if basics.is_initialized() else 1
        scaled = base_lr * max(size, 1)
        start = base_lr * initial_factor
        frac = jnp.clip(jnp.asarray(step, jnp.float32) / steps, 0.0, 1.0)
        warm = start + (scaled - start) * frac
        if after is not None:
            tail = after(jnp.maximum(jnp.asarray(step) - steps, 0))
        else:
            tail = scaled
        return jnp.where(jnp.asarray(step) < steps, warm, tail)

    return schedule


def piecewise_schedule(base_lr: float,
                       multipliers: Dict[int, float]) -> optax.Schedule:
    """Epoch/step-indexed multiplier schedule (reference:
    LearningRateScheduleCallback with staircase=True): ``{step: mult}``
    applies ``base_lr * mult`` from that step on."""
    boundaries = sorted(multipliers)
    scales = {}
    prev = 1.0
    for b in boundaries:
        scales[b] = multipliers[b] / prev
        prev = multipliers[b]
    return optax.piecewise_constant_schedule(base_lr, scales)


# ---------------------------------------------------------------------------
# Imperative callbacks (eager collective path)
# ---------------------------------------------------------------------------

class BroadcastGlobalVariablesCallback:
    """Broadcast initial parameters/optimizer state from ``root_rank`` at the
    start of training (reference: BroadcastGlobalVariablesCallback /
    BroadcastGlobalVariablesHook)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, state: Any) -> Any:
        """``state`` is a pytree (params, opt state, ...); returns the
        synchronized pytree."""
        if self._done:
            return state
        self._done = True
        return broadcast_parameters(state, root_rank=self.root_rank,
                                    prefix="callback.broadcast")


class MetricAverageCallback:
    """Average logged metrics over ranks at epoch end (reference:
    MetricAverageCallback)."""

    def on_epoch_end(self, logs: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        for k, v in logs.items():
            arr = np.asarray(v, dtype=np.float64)
            out[k] = np.asarray(
                allreduce(arr, name=f"metric.{k}", op=ReduceOp.AVERAGE))
            if out[k].ndim == 0:
                out[k] = float(out[k])
        return out


class LearningRateWarmupCallback:
    """Object-form warmup for loops that read ``callback.lr(step)`` — thin
    wrapper over :func:`warmup_schedule` kept for reference-name parity."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: int = 1, verbose: bool = False):
        self.schedule = warmup_schedule(
            initial_lr, warmup_epochs * steps_per_epoch)
        self.verbose = verbose

    def lr(self, step: int) -> float:
        return float(self.schedule(step))


class LearningRateScheduleCallback:
    """Object-form piecewise schedule (reference-name parity)."""

    def __init__(self, initial_lr: float, multiplier,
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 steps_per_epoch: int = 1):
        if callable(multiplier):
            self._fn: Callable[[int], float] = \
                lambda step: initial_lr * multiplier(step // steps_per_epoch)
        else:
            self._fn = lambda step: initial_lr * multiplier
        self.start = start_epoch * steps_per_epoch
        self.end = end_epoch * steps_per_epoch if end_epoch else None
        self.initial_lr = initial_lr

    def lr(self, step: int) -> float:
        if step < self.start or (self.end is not None and step >= self.end):
            return self.initial_lr
        return float(self._fn(step))
