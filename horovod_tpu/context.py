"""Global Horovod context: handle table, executor thread, host data plane.

Reference analogs (SURVEY.md §2.1/§3.2): HorovodGlobalState (global_state.h),
HandleManager (torch/handle_manager.cc), the ops layer's fuse/unfuse logic
(ops/collective_operations.cc — MemcpyInFusionBuffer/MemcpyOutFusionBuffer)
and op execution (ops/operation_manager.cc).

The executor thread pops negotiated ``FusedResponse``s from the core backend
and runs the data plane:

- host arrays (numpy) → the core's fused host collectives (identity at np=1,
  TCP in multi-process mode),
- results are converted back to the framework type the caller handed in
  (JAX array in → JAX array out).

For device-resident SPMD collectives inside ``jit`` see
``horovod_tpu.ops.collectives`` — those never pass through this queue; they
compile straight to XLA collectives over ICI.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .exceptions import HorovodInternalError
from .ops.device_plane import DevicePlane
from .runtime import CoreBackend, FusedResponse, PyLocalCore, TensorEntry
from .utils.env import Config, get_bool
from .utils.logging import get_logger
from .wire import (DataType, OpType, ReduceOp, numpy_dtype,
                   validate_alltoall_splits, wire_dtype)

log = get_logger()

# Framework bindings register cleanup hooks here (torch/mpi_ops.py sweeps
# its handle table) so shutdown — including the fast-abort path after a
# peer failure — releases their bookkeeping: a post-abort re-init must not
# see stale in-place write-back targets from the dead job.
_shutdown_callbacks: List = []


def register_shutdown_callback(fn) -> None:
    """Register ``fn`` to run during :meth:`HorovodContext.shutdown`.

    Callbacks run after the core is down and pending handles are failed;
    exceptions are logged, never propagated (shutdown must always finish).
    Registration is idempotent by identity."""
    if fn not in _shutdown_callbacks:
        _shutdown_callbacks.append(fn)


_INT_TYPES = (
    DataType.UINT8, DataType.INT8, DataType.UINT16, DataType.INT16,
    DataType.INT32, DataType.INT64, DataType.BOOL,
)

# Pseudo process-set id keying the single shared device-plane executor lane
# (never collides with real psids, which are >= 0).
_DEVICE_LANE = -1


def _scale(arr: np.ndarray, factor: float) -> np.ndarray:
    """Scale a buffer by a scalar without extra copies.

    Integer tensors are rejected at enqueue (reference parity), so normally
    only float dtypes reach here.  f32/f64 scale in place; 16-bit floats
    widen to f32 for the multiply (the reference's CPU scale path also
    computes in higher precision); anything else defensively goes through
    f64 (exact for int64 magnitudes up to 2**53)."""
    if arr.dtype in (np.float32, np.float64):
        np.multiply(arr, arr.dtype.type(factor), out=arr)
        return arr
    if arr.itemsize == 2:
        return (arr.astype(np.float32) * np.float32(factor)).astype(arr.dtype)
    return (arr.astype(np.float64) * factor).astype(arr.dtype)


def _rows2d(a: np.ndarray) -> np.ndarray:
    """View as (rows, row_width) for the row-oriented plane calls.

    Not ``reshape(n, -1)``: numpy cannot infer -1 when n == 0, and a
    zero-row contribution is legal for ragged allgather (a rank whose
    sparse gradient touched no rows still participates)."""
    if a.ndim == 0:
        return a.reshape(1, 1)
    row = int(np.prod(a.shape[1:], dtype=np.int64)) if a.ndim > 1 else 1
    return a.reshape(a.shape[0], row)


class _FusionBuffer:
    """Reusable pack/unpack buffer for the host data plane.

    Reference: fusion_buffer_manager.cc — a preallocated per-device buffer
    that MemcpyInFusionBuffer packs gradients into so each cycle issues one
    collective with no per-cycle allocation.  Grows to the largest bucket
    seen (a single tensor may exceed HOROVOD_FUSION_THRESHOLD; it then forms
    a bucket of one)."""

    def __init__(self, initial_bytes: int = 0):
        self._buf = np.empty(int(initial_bytes), np.uint8)

    def view(self, dtype, count: int) -> np.ndarray:
        """A contiguous `count`-element view of the buffer as `dtype`."""
        dtype = np.dtype(dtype)
        nbytes = int(count) * dtype.itemsize
        if self._buf.nbytes < nbytes:
            self._buf = np.empty(nbytes, np.uint8)
        return self._buf[:nbytes].view(dtype)


def _select_backend(cfg: Config) -> CoreBackend:
    """Pick the native C++ core when available, pure-Python otherwise.

    Selection mirrors the reference's controller choice in
    InitializeHorovodOnce (operations.cc): env overrides first —
    HOROVOD_CONTROLLER=python or HVD_TPU_PURE_PY=1 force the pure-Python
    local core; any other value (auto/local/socket) prefers the native core.
    """
    force_python = cfg.force_pure_python or cfg.controller == "python"
    if not force_python:
        try:
            from ._core import NativeCore

            return NativeCore()
        except Exception as exc:  # pragma: no cover - build-environment dependent
            if cfg.size > 1 or cfg.controller == "socket":
                raise HorovodInternalError(
                    f"native core required for size={cfg.size} "
                    f"(controller={cfg.controller}) but unavailable: {exc}"
                ) from exc
            log.debug("native core unavailable (%s); using pure-Python local core", exc)
    if cfg.size > 1:
        raise HorovodInternalError(
            "pure-Python core only supports single-process mode"
        )
    return PyLocalCore()


class _ExecutorLane:
    """One finalization lane per process set (reference analog:
    thread_pool.cc + per-communicator NCCL streams).

    Responses for the SAME process set finalize strictly in negotiated
    order (single lane thread, FIFO queue); responses for different sets
    proceed concurrently — safe because every registered set rides its own
    data-channel sockets (socket_controller.cc EstablishChannel), so a
    slow host collective on one set cannot head-of-line-block another."""

    def __init__(self, ctx: "HorovodContext", psid: int):
        import queue

        self.psid = psid
        self._ctx = ctx
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name=f"hvd-lane-{psid}", daemon=True)
        self._thread.start()

    def submit(self, resp: FusedResponse) -> None:
        self._q.put(resp)

    def stop(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            resp = self._q.get()
            if resp is None or self._ctx._shutdown.is_set():
                return
            self._ctx._process_response(resp)


class HorovodContext:
    """Process-wide singleton created by ``hvd.init()``."""

    _instance: Optional["HorovodContext"] = None
    _instance_lock = threading.Lock()

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.core = _select_backend(cfg)
        self._entries: Dict[int, TensorEntry] = {}
        self._entries_lock = threading.Lock()
        self._inflight_names: set = set()
        self._deferred: Dict[str, List[TensorEntry]] = {}
        self._joined = False  # this rank called join() and awaits the rest
        self._handle_counter = itertools.count(1)
        self._noname_counter = itertools.count(0)
        # Grouped-call counter: unnamed groups need a key that MATCHES
        # across ranks; like the noname counter, determinism follows from
        # every rank issuing grouped calls in the same order.
        self._group_counter = itertools.count(0)
        self._shutdown = threading.Event()
        # One fusion buffer PER EXECUTOR LANE (thread-local): lanes finalize
        # different process sets' responses concurrently, each packing its
        # own buffer (reference: FusionBufferManager::GetBuffer per device;
        # thread_pool.cc's parallel finalization role).
        self._fusion_tls = threading.local()
        self._fusion_initial = min(cfg.fusion_threshold_bytes, 64 << 20)
        self.core.start(cfg)
        # Eager device data plane: executes responses negotiated
        # device=True as cached jitted fused XLA collectives (the NCCL-ops
        # analog; ops/device_plane.py).
        self.device_plane = DevicePlane(self.core, cfg)
        # Parallel lanes: one finalization thread per process set, so an
        # in-flight host collective on one set cannot head-of-line-block
        # independent traffic on another.  Requires per-set data channels
        # (NativeCore); the pure-Python fallback finalizes inline.
        self._use_lanes = (
            getattr(self.core, "parallel_lanes", False) and cfg.size > 1
            and get_bool("HOROVOD_EXECUTOR_LANES", True))
        self._lanes: Dict[int, "_ExecutorLane"] = {}
        # Live cockpit (HOROVOD_COCKPIT, rank 0 only): loopback HTTP
        # endpoint streaming the fleet's step attribution; None when off.
        from .cockpit import maybe_start_cockpit
        self.cockpit = maybe_start_cockpit(self)
        self._executor = threading.Thread(
            target=self._executor_loop, name="hvd-executor", daemon=True
        )
        self._executor.start()

    @property
    def _fusion(self) -> _FusionBuffer:
        buf = getattr(self._fusion_tls, "buf", None)
        if buf is None:
            buf = _FusionBuffer(self._fusion_initial)
            self._fusion_tls.buf = buf
        return buf

    # -- lifecycle ----------------------------------------------------------
    @classmethod
    def instance(cls) -> "HorovodContext":
        inst = cls._instance
        if inst is None:
            raise ValueError(
                "Horovod has not been initialized; run hvd.init() first."
            )
        return inst

    @classmethod
    def initialized(cls) -> bool:
        return cls._instance is not None

    @classmethod
    def init(cls, cfg: Optional[Config] = None) -> "HorovodContext":
        with cls._instance_lock:
            if cls._instance is not None:
                return cls._instance
            cls._instance = HorovodContext(cfg or Config.from_env())
            return cls._instance

    @classmethod
    def shutdown(cls) -> None:
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is None:
            return
        inst._shutdown.set()
        inst._executor.join(timeout=5.0)
        if getattr(inst, "cockpit", None) is not None:
            inst.cockpit.stop()
        inst.core.shutdown()
        # Fail any still-pending handles so blocked synchronize() callers
        # wake with an error instead of hanging forever.
        with inst._entries_lock:
            pending = [e for e in inst._entries.values() if not e.done.is_set()]
        for e in pending:
            e.error = "Horovod has been shut down"
            e.done.set()
        for fn in list(_shutdown_callbacks):
            try:
                fn()
            except Exception as exc:  # noqa: BLE001 - shutdown must finish
                log.warning("shutdown callback %r failed: %s", fn, exc)

    # -- enqueue ------------------------------------------------------------
    def enqueue(
        self,
        array,
        op: OpType,
        name: Optional[str] = None,
        reduce_op: ReduceOp = ReduceOp.SUM,
        root_rank: int = 0,
        splits=None,
        process_set_id: int = 0,
        prescale_factor: float = 1.0,
        postscale_factor: float = 1.0,
        group_key: str = "",
        group_size: int = 0,
    ) -> int:
        # Device-plane capability first: a device-resident jax.Array whose
        # op the plane serves never touches the host — the entry carries a
        # zero-memory shape/dtype proxy for negotiation metadata only, and
        # the announced device bit tells the coordinator this rank can
        # dispatch the jitted collective.
        dev_arr = self.device_plane.adopt(array, op, reduce_op, process_set_id)
        if dev_arr is not None:
            np_arr = np.broadcast_to(
                np.zeros((), numpy_dtype(wire_dtype(dev_arr.dtype))),
                tuple(dev_arr.shape))
            was_jax, orig_dtype = True, dev_arr.dtype
        else:
            np_arr, was_jax, orig_dtype = _to_host(array)
        dtype = wire_dtype(np_arr.dtype if orig_dtype is None else orig_dtype)
        if name is None:
            name = f"{op.name.lower()}.noname.{next(self._noname_counter)}"
        if dtype in _INT_TYPES:
            if reduce_op == ReduceOp.AVERAGE and op in (
                    OpType.ALLREDUCE, OpType.REDUCESCATTER):
                raise ValueError(
                    "hvd.Average is not supported for integer tensors; use hvd.Sum"
                )
            if prescale_factor != 1.0 or postscale_factor != 1.0:
                raise ValueError("pre/postscale not supported for integer tensors")
        if splits is not None:
            splits = np.ascontiguousarray(np.asarray(splits, dtype=np.int64))

        handle = next(self._handle_counter)
        entry = TensorEntry(
            handle=handle,
            name=name,
            op=op,
            array=np_arr,
            dtype=dtype,
            reduce_op=reduce_op,
            root_rank=root_rank,
            splits=splits,
            process_set_id=process_set_id,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            was_jax=was_jax,
            orig_dtype=orig_dtype,
            group_key=group_key,
            group_size=group_size,
            device_array=dev_arr,
        )
        with self._entries_lock:
            self._entries[handle] = entry
            if name in self._inflight_names:
                # Reference semantics: a second op with an in-flight name
                # queues behind the first (the negotiation layer keys by
                # name, so it is submitted once the first completes — safe
                # because every rank orders instances the same way).
                self._deferred.setdefault(name, []).append(entry)
                return handle
            self._inflight_names.add(name)
        self.core.enqueue(entry)
        return handle

    def group_key_for(self, name: Optional[str]) -> str:
        """Negotiation key for one grouped_* call (group_table.cc analog).
        Must match across ranks: named groups key on the name; unnamed ones
        on the deterministic grouped-call counter."""
        if name:
            return f"g.{name}"
        return f"g.anon.{next(self._group_counter)}"

    # -- completion ---------------------------------------------------------
    def poll(self, handle: int) -> bool:
        with self._entries_lock:
            entry = self._entries.get(handle)
        if entry is None:
            raise ValueError(f"unknown handle {handle}")
        return entry.done.is_set()

    def synchronize(self, handle: int):
        with self._entries_lock:
            entry = self._entries.get(handle)
        if entry is None:
            raise ValueError(f"unknown handle {handle}")
        entry.done.wait()
        with self._entries_lock:
            self._entries.pop(handle, None)
        if entry.error is not None:
            raise HorovodInternalError(entry.error)
        result = entry.result
        if entry.op == OpType.ALLTOALL:
            return _from_host(result, entry), entry.recv_splits
        return _from_host(result, entry)

    # -- executor / data plane ----------------------------------------------
    def _executor_loop(self) -> None:
        """Dispatcher: pop negotiated responses and either finalize inline
        (serial mode) or hand each to its process set's lane."""
        while not self._shutdown.is_set():
            resp = self.core.pop_response(timeout=0.05)
            if resp is None:
                continue
            # Join-state transitions must follow the GLOBAL negotiated
            # order, which only the dispatcher sees: stamp the current
            # joined flag on each response, and clear it when the JOIN
            # itself dispatches — a later lane finalizing an
            # earlier-negotiated collective still zero-participates.
            with self._entries_lock:
                resp.joined_at_dispatch = self._joined
                if resp.op == OpType.JOIN and not resp.error:
                    self._joined = False
            if resp.device and self._use_lanes:
                # ALL device-plane responses share ONE lane: XLA executes
                # collectives in per-device enqueue order, so every host
                # must enqueue them in the same (negotiated) global order —
                # two concurrent lanes whose rank meshes share devices
                # could otherwise enqueue in opposite orders on different
                # hosts and deadlock the ICI ring.  A dedicated lane (not
                # inline dispatch) also keeps a program-cache-miss compile
                # from head-of-line-blocking other sets' host traffic
                # behind the dispatcher.
                self._lane_for(_DEVICE_LANE).submit(resp)
            elif self._use_lanes:
                self._lane_for(resp.process_set_id).submit(resp)
            else:
                self._process_response(resp)
        for lane in list(self._lanes.values()):
            lane.stop()

    def _lane_for(self, psid: int) -> "_ExecutorLane":
        lane = self._lanes.get(psid)
        if lane is None:
            lane = _ExecutorLane(self, psid)
            self._lanes[psid] = lane
        return lane

    def remove_process_set(self, psid: int) -> None:
        """Remove a set from the core AND retire its executor lane (ids are
        never reused, so a leaked lane thread would accumulate forever)."""
        self.core.remove_process_set(psid)
        self.device_plane.invalidate(psid)
        lane = self._lanes.pop(psid, None)
        if lane is not None:
            lane.stop()

    def _process_response(self, resp: FusedResponse) -> None:
        """Finalize one response: collect entries, run the data plane, set
        completion.  Runs on the dispatcher (serial mode) or a lane thread
        (per-process-set lanes; ordering holds within each lane)."""
        self.core.set_current_seq(resp.seq)
        entries = []
        with self._entries_lock:
            for h in resp.handles:
                e = self._entries.get(h)
                if e is not None:
                    entries.append(e)
        if not entries:
            # Joined rank (hvd.join): no local tensors, but ring
            # collectives need every member — participate with zeros.
            # The dispatch-time stamp (not the live flag) decides: the
            # live flag may already be cleared by a JOIN that was
            # negotiated AFTER this response but dispatched to a faster
            # lane.
            if resp.joined_at_dispatch and not resp.error:
                try:
                    self._participate_absent(resp)
                except Exception as exc:  # noqa: BLE001
                    log.warning("zero-participation failed: %s", exc)
            return
        try:
            if resp.error:
                raise HorovodInternalError(resp.error)
            self._execute(resp, entries)
            for e in entries:
                e.done.set()
        except Exception as exc:  # noqa: BLE001 - propagate via handle
            if resp.op == OpType.JOIN:
                # A failed join (e.g. a peer shut down mid-join) must
                # not leave this rank zero-participating forever.
                with self._entries_lock:
                    self._joined = False
            for e in entries:
                e.error = str(exc)
                e.done.set()
        self._release_names(entries)

    def _release_names(self, entries: List[TensorEntry]) -> None:
        """After a name's instance completes, submit its next queued
        instance (duplicate-name queueing) or free the name."""
        to_enqueue = []
        with self._entries_lock:
            for e in entries:
                queued = self._deferred.get(e.name)
                if queued:
                    to_enqueue.append(queued.pop(0))
                    if not queued:
                        del self._deferred[e.name]
                else:
                    self._inflight_names.discard(e.name)
        for nxt in to_enqueue:
            self.core.enqueue(nxt)

    def _execute(self, resp: FusedResponse, entries: List[TensorEntry]) -> None:
        op = resp.op
        psid = resp.process_set_id
        if resp.device:
            # Negotiated device plane: EVERY rank announced capability, so
            # every rank dispatches the same cached jitted collective here.
            self.device_plane.execute(resp, entries)
            return
        # Host plane.  Negotiation may have demoted device-resident entries
        # (a host tensor or joined rank elsewhere): materialize their bytes
        # now — the only place an eager device array crosses to the host.
        for e in entries:
            if e.device_array is not None:
                e.array = _contig(np.asarray(e.device_array))
                self.device_plane.note_host_fallback(e.name)
        if op == OpType.ALLREDUCE:
            self._exec_allreduce(entries, psid)
        elif op == OpType.ALLGATHER:
            self._exec_allgather(entries, psid)
        elif op == OpType.BROADCAST:
            self._exec_broadcast(entries[0], psid)
        elif op == OpType.ALLTOALL:
            self._exec_alltoall(entries[0], psid)
        elif op == OpType.REDUCESCATTER:
            self._exec_reducescatter(entries[0], psid)
        elif op == OpType.BARRIER:
            self.core.barrier(psid)
            for e in entries:
                e.result = e.array
        elif op == OpType.JOIN:
            # Completion of the join itself: every rank joined; no data
            # moves.  The result is the last rank to join (reference:
            # join() return value).
            with self._entries_lock:
                self._joined = False
            for e in entries:
                e.result = np.int64(resp.last_joined)
        else:
            raise HorovodInternalError(f"unsupported op {op}")

    def _participate_absent(self, resp: FusedResponse) -> None:
        """Walk a collective this rank submitted nothing for (it joined):
        zero contribution for sum/average allreduce, plain participation
        for barriers.  The coordinator guarantees only these op types become
        ready while ranks are joined."""
        psid = resp.process_set_id
        if self.cfg.rank not in self.core.process_set_ranks(psid):
            return
        if resp.device:
            # Unreachable: the coordinator demotes every via-join response
            # to the host plane (socket_controller.cc CoordinatorCycle).
            raise HorovodInternalError(
                "joined rank received a device-plane response")
        if resp.op == OpType.ALLREDUCE:
            count = int(sum(resp.counts or []))
            zeros = np.zeros(count, numpy_dtype(resp.dtype))
            self.core.allreduce_buffer(zeros, psid, ReduceOp.SUM)
        elif resp.op == OpType.BARRIER:
            self.core.barrier(psid)
        elif resp.op == OpType.JOIN:
            pass  # our own join entry always exists locally
        else:
            raise HorovodInternalError(
                f"op {resp.op} cannot proceed with joined ranks")

    def _ps_size(self, psid: int) -> int:
        return len(self.core.process_set_ranks(psid))

    def _exec_allreduce(self, entries: List[TensorEntry], psid: int) -> None:
        # MemcpyInFusionBuffer analog: pack members into one contiguous buffer.
        dtype = entries[0].array.dtype
        reduce_op = entries[0].reduce_op
        if len(entries) == 1 and reduce_op != ReduceOp.ADASUM:
            # Single-tensor fast path: the fusion pack/unpack would be two
            # pure-overhead copies.  One owned copy (the user's input must
            # not be mutated; the plane reduces in place) is all that's
            # needed.
            e = entries[0]
            buf = np.array(e.array, dtype=dtype, copy=True, order="C")
            flat = buf.reshape(-1)
            if e.prescale_factor != 1.0:
                flat = _scale(flat, e.prescale_factor)
            wire_op = ReduceOp.SUM if reduce_op == ReduceOp.AVERAGE \
                else reduce_op
            flat = self.core.allreduce_buffer(flat, psid, wire_op)
            if reduce_op == ReduceOp.AVERAGE:
                n = self._ps_size(psid)
                if n > 1:
                    flat = _scale(flat, 1.0 / n)
            if e.postscale_factor != 1.0:
                flat = _scale(flat, e.postscale_factor)
            e.result = flat.reshape(e.array.shape)
            return
        # Pack into the preallocated fusion buffer — no per-cycle allocation.
        total = sum(e.array.size for e in entries)
        fused = self._fusion.view(dtype, total)
        off = 0
        for e in entries:
            n = e.array.size
            np.copyto(fused[off:off + n], e.array.ravel(), casting="no")
            off += n
        pre = entries[0].prescale_factor
        if pre != 1.0:
            fused = _scale(fused, pre)
        if reduce_op == ReduceOp.ADASUM and self._ps_size(psid) > 1:
            # Host-path Adasum: allgather every rank's fused buffer, then a
            # deterministic local pairwise-tree combine — every rank computes
            # the identical result (reference: adasum_mpi.cc uses MPI
            # point-to-point VHDD; the allgather form trades bandwidth for
            # the simpler host plane, fine at CPU-negotiation scale).
            # The combine runs PER TENSOR segment: adasum's dot/norm
            # coefficients are per-tensor in the reference too —
            # adasum(concat(a1,a2), ...) != concat(adasum(a1,...), ...).
            stacked, _ = self.core.allgather_buffer(
                fused.reshape(1, -1), psid)
            vectors = np.asarray(stacked, dtype=np.float64)
            segments = []
            offset = 0
            for e in entries:
                seg = vectors[:, offset:offset + e.array.size]
                segments.append(_adasum_tree(seg))
                offset += e.array.size
            fused = np.concatenate(segments).astype(dtype)
        else:
            wire_op = ReduceOp.SUM \
                if reduce_op in (ReduceOp.AVERAGE, ReduceOp.ADASUM) \
                else reduce_op
            fused = self.core.allreduce_buffer(fused, psid, wire_op)
            if reduce_op == ReduceOp.AVERAGE:
                n = self._ps_size(psid)
                if n > 1:
                    fused = _scale(fused, 1.0 / n)
        post = entries[0].postscale_factor
        if post != 1.0:
            fused = _scale(fused, post)
        # MemcpyOutFusionBuffer analog: results must own their memory — the
        # fusion buffer is reused by the next response.
        offset = 0
        for e in entries:
            n = e.array.size
            e.result = fused[offset:offset + n].reshape(e.array.shape).copy()
            offset += n

    def _exec_allgather(self, entries: List[TensorEntry], psid: int) -> None:
        if len(entries) == 1:
            e = entries[0]
            stacked, counts = self.core.allgather_buffer(
                _rows2d(e.array), psid)
            rest = e.array.shape[1:] if e.array.ndim else ()
            e.result = np.asarray(stacked).reshape(
                (int(np.sum(counts)),) + tuple(rest))
            return
        # Fused allgather (reference: AllgatherOp rides the fusion buffer
        # too): pack members length-prefixed into one payload, gather once,
        # then split each rank's block back into per-tensor segments.  The
        # prefix is required because allgather first dims vary per rank, so
        # the response metas cannot describe remote segment sizes.
        parts = []
        for e in entries:
            raw = np.ascontiguousarray(e.array).view(np.uint8).ravel()
            parts.append(np.frombuffer(
                np.int64(raw.nbytes).tobytes(), np.uint8))
            parts.append(raw)
        # Rows of one byte: rank blocks are ragged (per-rank first dims), so
        # the per-rank counts must come back in bytes, not in my-row units.
        packed = np.concatenate(parts)
        stacked, counts = self.core.allgather_buffer(
            packed.reshape(-1, 1), psid)
        flat = np.asarray(stacked).view(np.uint8).ravel()
        per_entry: List[List[np.ndarray]] = [[] for _ in entries]
        off = 0
        for rank_bytes in counts:
            end = off + int(rank_bytes)
            for i, e in enumerate(entries):
                n = int(flat[off:off + 8].view(np.int64)[0])
                off += 8
                per_entry[i].append(flat[off:off + n])
                off += n
            if off != end:
                raise HorovodInternalError(
                    "fused allgather block framing desynced")
        for i, e in enumerate(entries):
            rest = tuple(e.array.shape[1:]) if e.array.ndim else ()
            row_bytes = int(np.prod(rest, dtype=np.int64)) * e.array.itemsize \
                if rest else e.array.itemsize
            blob = np.concatenate(per_entry[i]) if per_entry[i] else \
                np.empty(0, np.uint8)
            total_rows = blob.nbytes // max(row_bytes, 1)
            e.result = blob.view(e.array.dtype).reshape(
                (total_rows,) + rest)

    def _exec_broadcast(self, e: TensorEntry, psid: int) -> None:
        e.result = self.core.broadcast_buffer(e.array, e.root_rank, psid)

    def _exec_alltoall(self, e: TensorEntry, psid: int) -> None:
        n = self._ps_size(psid)
        splits = validate_alltoall_splits(e.splits, e.array.shape[0], n)
        buf = _rows2d(e.array)
        out, recv_splits = self.core.alltoall_buffer(buf, splits, psid)
        rest = e.array.shape[1:]
        e.result = np.asarray(out).reshape((int(np.sum(recv_splits)),) + tuple(rest))
        e.recv_splits = np.asarray(recv_splits, dtype=np.int64)

    def _exec_reducescatter(self, e: TensorEntry, psid: int) -> None:
        # True ring reduce-scatter ((m-1)/m of the buffer on the wire,
        # half the allreduce-then-slice this used to do): the plane
        # reduces each rank's slice in place and we keep ours.  Slicing
        # rule matches the reference (ReducescatterOp): the first
        # (d0 % size) ranks receive one extra row.
        n = self._ps_size(psid)
        fused = e.array.ravel().copy()
        pre = e.prescale_factor
        if pre != 1.0:
            fused = _scale(fused, pre)
        wire_op = ReduceOp.SUM if e.reduce_op == ReduceOp.AVERAGE else e.reduce_op
        d0 = e.array.shape[0]
        row = fused.size // d0 if d0 else 0
        ranks = self.core.process_set_ranks(psid)
        my_pos = ranks.index(self.core.rank()) if self.core.rank() in ranks else 0
        base, extra = divmod(d0, n)
        slice_rows = [base + (1 if p < extra else 0) for p in range(n)]
        fused = self.core.reducescatter_buffer(
            fused, psid, wire_op, [r * row for r in slice_rows])
        start = (my_pos * base + min(my_pos, extra)) * row
        mine = fused[start:start + slice_rows[my_pos] * row]
        if e.reduce_op == ReduceOp.AVERAGE:
            mine = _scale(mine, 1.0 / max(n, 1))
        if e.postscale_factor != 1.0:
            mine = _scale(mine, e.postscale_factor)
        e.result = mine.reshape((slice_rows[my_pos],) + e.array.shape[1:])


def _adasum_pair(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Scale-invariant pairwise combine (reference: adasum/adasum.h):
    adasum(a, b) = (1 - a.b/(2|a|^2)) a + (1 - a.b/(2|b|^2)) b."""
    dot = float(np.dot(a, b))
    na = max(float(np.dot(a, a)), 1e-300)
    nb = max(float(np.dot(b, b)), 1e-300)
    return (1.0 - dot / (2.0 * na)) * a + (1.0 - dot / (2.0 * nb)) * b


def _adasum_tree(vectors: np.ndarray) -> np.ndarray:
    """Pairwise-tree Adasum over rank-major rows; handles non-power-of-two
    counts by passing the odd row through to the next level."""
    rows = [vectors[i].ravel() for i in range(vectors.shape[0])]
    while len(rows) > 1:
        nxt = [_adasum_pair(rows[i], rows[i + 1])
               for i in range(0, len(rows) - 1, 2)]
        if len(rows) % 2:
            nxt.append(rows[-1])
        rows = nxt
    return rows[0]


def _contig(a: np.ndarray) -> np.ndarray:
    # np.ascontiguousarray promotes 0-d to 1-d; preserve scalar shape.
    return a.copy() if a.ndim == 0 else np.ascontiguousarray(a)


def _to_host(array):
    """Convert a framework array to a contiguous host numpy buffer."""
    was_jax = False
    orig_dtype = None
    if not isinstance(array, np.ndarray):
        try:
            import jax

            if isinstance(array, jax.Array):
                was_jax = True
                orig_dtype = array.dtype  # bfloat16 survives via ml_dtypes
                return _contig(np.asarray(array)), was_jax, orig_dtype
        except ImportError:  # pragma: no cover
            pass
        array = np.asarray(array)
    return _contig(array), was_jax, orig_dtype


def _from_host(result: np.ndarray, entry: TensorEntry):
    if entry.device_array is not None and not isinstance(result, np.ndarray):
        return result  # device plane: already a device-resident jax.Array
    if not entry.was_jax:
        return result
    import jax.numpy as jnp

    return jnp.asarray(result, dtype=entry.orig_dtype)
