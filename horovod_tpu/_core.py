"""ctypes binding over the native core (libhvd_tpu_core.so).

Python analog of the reference's HorovodBasics ctypes facade
(horovod/common/basics.py; SURVEY.md §2.4), except the library here is the
TPU-native core (horovod_tpu/cpp/) rather than a per-framework build.  The
library is built on demand with `make` the first time it is needed.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

from .runtime import PROTOCOL_VERSION, CoreBackend, FusedResponse, TensorEntry
from .utils.env import Config, get_bool
from .utils.logging import get_logger
from .wire import DataType, OpType, ReduceOp, wire_dtype

log = get_logger()

_CPP_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "libhvd_tpu_core.so")

_LOG_LEVELS = {"trace": 0, "debug": 1, "info": 2, "warning": 3, "error": 4,
               "fatal": 5}

_build_lock = threading.Lock()
_lib = None


def _load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        # Always invoke make: it's incremental (no-op when up to date) and
        # guarantees source edits are never shadowed by a stale .so.
        try:
            subprocess.run(["make", "-s"], cwd=_CPP_DIR, check=True,
                           capture_output=True)
        except (subprocess.CalledProcessError, OSError) as exc:
            if not os.path.exists(_LIB_PATH):
                raise
            log.warning("native core rebuild failed (%s); using existing "
                        "library", exc)
        lib = ctypes.CDLL(_LIB_PATH)
        _declare(lib)
        _lib = lib
        return lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.hvd_init.restype = c.c_int
    lib.hvd_init.argtypes = [
        c.c_int, c.c_int, c.c_int, c.c_int,        # rank size local_rank local_size
        c.c_char_p, c.c_char_p, c.c_int,           # controller addr port
        c.c_double, c.c_longlong, c.c_int, c.c_int,  # cycle fusion cache autotune
        c.c_char_p, c.c_int, c.c_int,              # autotune_log hierarchical wire_comp
        c.c_int,                                   # qdev_comp (-1 = no device plane)
        c.c_int,                                   # qdev_sched (-1 = ring-only plane)
        c.c_int, c.c_char_p, c.c_double,           # metrics metrics_file interval
        c.c_char_p, c.c_int,                       # timeline mark
        c.c_double, c.c_double, c.c_int,           # stall_warn stall_shutdown log
        c.c_int, c.c_int, c.c_char_p,              # flight_on flight_slots postmortem_dir
        c.c_int,                                   # autopilot_port (0 = off)
        c.c_int, c.c_int,                          # step_trace_on step_trace_slots
        c.c_int,                                   # data_plane (-1 = no gspmd mesh)
    ]
    lib.hvd_shutdown.restype = c.c_int
    lib.hvd_is_initialized.restype = c.c_int
    lib.hvd_rank.restype = c.c_int
    lib.hvd_size.restype = c.c_int
    lib.hvd_local_rank.restype = c.c_int
    lib.hvd_local_size.restype = c.c_int
    lib.hvd_enqueue.restype = c.c_longlong
    lib.hvd_enqueue.argtypes = [
        c.c_longlong, c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_longlong,
        c.POINTER(c.c_longlong), c.c_int, c.c_int, c.c_int, c.c_double,
        c.c_double, c.POINTER(c.c_longlong), c.c_int, c.c_int, c.c_char_p,
        c.c_int,
    ]
    lib.hvd_pop_response.restype = c.c_int
    lib.hvd_pop_response.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.hvd_allreduce_buffer.restype = c.c_int
    lib.hvd_allreduce_buffer.argtypes = [
        c.c_longlong, c.c_void_p, c.c_longlong, c.c_int, c.c_int, c.c_int]
    lib.hvd_reducescatter_buffer.restype = c.c_int
    lib.hvd_reducescatter_buffer.argtypes = [
        c.c_longlong, c.c_void_p, c.c_longlong, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_longlong), c.c_int]
    lib.hvd_allgather_buffer.restype = c.c_int
    lib.hvd_allgather_buffer.argtypes = [
        c.c_longlong, c.c_void_p, c.c_longlong, c.c_int,
        c.POINTER(c.c_void_p), c.POINTER(c.c_longlong),
        c.POINTER(c.c_longlong), c.c_int, c.POINTER(c.c_int)]
    lib.hvd_broadcast_buffer.restype = c.c_int
    lib.hvd_broadcast_buffer.argtypes = [
        c.c_longlong, c.c_void_p, c.c_longlong, c.c_int, c.c_int]
    lib.hvd_alltoall_buffer.restype = c.c_int
    lib.hvd_alltoall_buffer.argtypes = [
        c.c_longlong, c.c_void_p, c.POINTER(c.c_longlong), c.c_int,
        c.c_longlong, c.c_int, c.POINTER(c.c_void_p),
        c.POINTER(c.c_longlong), c.POINTER(c.c_longlong), c.POINTER(c.c_int)]
    lib.hvd_barrier.restype = c.c_int
    lib.hvd_barrier.argtypes = [c.c_longlong, c.c_int]
    lib.hvd_free.argtypes = [c.c_void_p]
    lib.hvd_add_process_set.restype = c.c_int
    lib.hvd_add_process_set.argtypes = [c.POINTER(c.c_int), c.c_int]
    try:
        # Old-ABI tolerance: a stale .so predating QoS process-set weights
        # loses the weighted registration path; add_process_set(weight=...)
        # then falls back to the unweighted symbol (weight 1.0).
        lib.hvd_add_process_set2.restype = c.c_int
        lib.hvd_add_process_set2.argtypes = [
            c.POINTER(c.c_int), c.c_int, c.c_double]
    except AttributeError:
        pass
    lib.hvd_remove_process_set.restype = c.c_int
    lib.hvd_remove_process_set.argtypes = [c.c_int]
    lib.hvd_process_set_ranks.restype = c.c_int
    lib.hvd_process_set_ranks.argtypes = [c.c_int, c.POINTER(c.c_int), c.c_int]
    lib.hvd_negotiation_stats.argtypes = [
        c.POINTER(c.c_longlong), c.POINTER(c.c_longlong)]
    lib.hvd_data_plane_stats.argtypes = [
        c.POINTER(c.c_longlong), c.POINTER(c.c_longlong)]
    lib.hvd_data_plane_stats2.argtypes = [
        c.POINTER(c.c_longlong), c.POINTER(c.c_longlong),
        c.POINTER(c.c_longlong), c.POINTER(c.c_longlong)]
    try:
        # Old-ABI tolerance: a stale .so predating the v9 leader tree
        # loses ctrl_plane_stats() (degrades to zeros), nothing else.
        lib.hvd_ctrl_plane_stats.argtypes = [
            c.POINTER(c.c_longlong), c.POINTER(c.c_longlong),
            c.POINTER(c.c_longlong), c.POINTER(c.c_longlong)]
    except AttributeError:
        pass
    lib.hvd_start_timeline.argtypes = [c.c_char_p, c.c_int]
    lib.hvd_stop_timeline.argtypes = []
    try:
        # Old-ABI tolerance (same pattern as hvd_data_plane_stats2): a
        # stale .so that survived a failed rebuild predates the metrics
        # plane; metrics() then degrades to {} instead of raising.
        lib.hvd_metrics_dump.restype = c.c_int
        lib.hvd_metrics_dump.argtypes = [c.c_char_p, c.c_int]
    except AttributeError:
        pass
    lib.hvd_last_error.restype = c.c_char_p
    try:
        # Old-ABI tolerance: a stale .so predating the flight recorder
        # degrades flight_record() to {} instead of raising.
        lib.hvd_flight_record.restype = c.c_int
        lib.hvd_flight_record.argtypes = [c.c_char_p, c.c_int]
    except AttributeError:
        pass
    try:
        # Old-ABI tolerance: a stale .so predating causal step tracing
        # degrades step_trace() to {} instead of raising.
        lib.hvd_step_trace.restype = c.c_int
        lib.hvd_step_trace.argtypes = [c.c_char_p, c.c_int]
    except AttributeError:
        pass
    try:
        # Old-ABI tolerance: a stale .so predating the fleet-telemetry
        # plane degrades fleet_history() to {} instead of raising.
        lib.hvd_fleet_history.restype = c.c_int
        lib.hvd_fleet_history.argtypes = [c.c_char_p, c.c_int]
    except AttributeError:
        pass
    try:
        # Old-ABI tolerance: a stale .so predating the fault-injection
        # plane simply loses `horovodrun --fault-inject` pre-validation.
        lib.hvd_fault_spec_check.restype = c.c_char_p
        lib.hvd_fault_spec_check.argtypes = [c.c_char_p]
    except AttributeError:
        pass
    try:
        # Old-ABI tolerance: a stale .so predating the device-plane int8
        # codec loses the native byte counters (data_plane_stats() falls
        # back to the Python-side counters) and the qdev autotune poll.
        lib.hvd_device_plane_note.restype = None
        lib.hvd_device_plane_note.argtypes = [c.c_longlong, c.c_longlong]
        lib.hvd_device_plane_stats.restype = None
        lib.hvd_device_plane_stats.argtypes = [
            c.POINTER(c.c_longlong), c.POINTER(c.c_longlong)]
        lib.hvd_autotune_qdev.restype = c.c_int
        lib.hvd_autotune_qdev.argtypes = []
    except AttributeError:
        pass
    try:
        # Old-ABI tolerance: a stale .so predating the schedule coordinate
        # loses only the qdev-schedule autotune poll.
        lib.hvd_autotune_qsched.restype = c.c_int
        lib.hvd_autotune_qsched.argtypes = []
    except AttributeError:
        pass
    try:
        # Old-ABI tolerance: a stale .so predating the data-plane
        # coordinate loses only the plane autotune poll (and ignores the
        # trailing data_plane init argument — cdecl, caller-cleaned).
        lib.hvd_autotune_plane.restype = c.c_int
        lib.hvd_autotune_plane.argtypes = []
    except AttributeError:
        pass
    try:
        # Old-ABI tolerance: a stale .so predating the elastic-migration
        # plane loses the type-14 forensics and the generation gauge; the
        # migration protocol itself is Python-side and keeps working.
        lib.hvd_migrate_note.restype = None
        lib.hvd_migrate_note.argtypes = [c.c_int, c.c_longlong, c.c_int]
        lib.hvd_elastic_generation_set.restype = None
        lib.hvd_elastic_generation_set.argtypes = [c.c_longlong]
    except AttributeError:
        pass
    try:
        # Old-ABI tolerance: a stale .so predating compiled-collective
        # introspection loses the native gspmd byte counters
        # (data_plane_stats() falls back to the Python-side inventory
        # totals), the type-16 forensics and the step-trace plane tag.
        lib.hvd_gspmd_plane_note.restype = None
        lib.hvd_gspmd_plane_note.argtypes = [
            c.c_longlong, c.c_longlong, c.c_longlong]
        lib.hvd_gspmd_plane_stats.restype = None
        lib.hvd_gspmd_plane_stats.argtypes = [
            c.POINTER(c.c_longlong), c.POINTER(c.c_longlong)]
        lib.hvd_step_trace_note_plane.restype = None
        lib.hvd_step_trace_note_plane.argtypes = [c.c_int]
    except AttributeError:
        pass


class NativeCoreError(RuntimeError):
    pass


def check_fault_spec(spec: str) -> str:
    """Validate a HOROVOD_FAULT_INJECT spec against the native parser.

    Returns "" when well-formed, else the same actionable message
    hvd.init() would fail with.  An old .so without the entry point
    validates nothing (returns "").
    """
    lib = _load_library()
    if not hasattr(lib, "hvd_fault_spec_check"):
        return ""
    msg = lib.hvd_fault_spec_check(spec.encode())
    return msg.decode() if msg else ""


class NativeCore(CoreBackend):
    """The C++ core as a CoreBackend: negotiation, fusion, caching, stall
    inspection and the host data plane all run natively; Python only packs
    fusion buffers and runs device-side XLA programs."""

    name = "native"
    # Per-process-set data channels exist in the socket controller, so
    # responses for different sets may run on concurrent executor lanes.
    parallel_lanes = True

    def __init__(self):
        self._lib = _load_library()
        self._cfg: Optional[Config] = None
        self._seq_tls = threading.local()
        # Reused across pop_response calls (the executor polls every 50ms;
        # a fresh 1MB allocation per poll would churn ~20MB/s at idle).
        self._resp_cap = 1 << 16
        self._resp_buf = ctypes.create_string_buffer(self._resp_cap)

    # -- lifecycle ----------------------------------------------------------
    def start(self, cfg: Config) -> None:
        self._cfg = cfg
        controller = cfg.controller
        if controller in ("auto",):
            controller = "socket" if cfg.size > 1 else "local"
        # Device-plane codec: 0=none, 1=int8, 2=int4, 3=int8g from config;
        # -1 pins the autotuner's qdev arm when no jax device plane can
        # exist here.
        qdev = {"none": 0, "int8": 1, "int4": 2, "int8g": 3}.get(
            getattr(cfg, "wire_compression_device", "none"), 0)
        # Device-ring schedule: 0=ring, 1=bidi, 2=torus ("auto" resolves
        # from the world size); -1 pins the autotuner's schedule arm when
        # only the unidirectional ring is feasible (or no device plane).
        try:
            from .ops.collectives import resolve_device_schedule
            resolved = resolve_device_schedule(
                cfg.size, getattr(cfg, "device_schedule", "auto"))
        except Exception:
            resolved = "ring"
        qsched = {"ring": 0, "bidi": 1, "torus": 2}.get(resolved, 0)
        if cfg.size < 4:
            qsched = -1  # bidi needs chunks >= 2, torus needs factors
        # In-jit data plane: 0=eager, 1=gspmd from config ("auto" starts
        # eager and lets the tuner flip it); -1 pins the autotuner's plane
        # arm when no gspmd mesh can exist (no jax, a single device) or the
        # quantized device codec owns the traced reduction — the
        # compose-or-demote rule of ops/gspmd_plane.py.
        plane = {"auto": 0, "eager": 0, "gspmd": 1}.get(
            getattr(cfg, "data_plane", "auto"), 0)
        try:
            import jax  # noqa: F401
        except Exception:
            qdev = -1
            qsched = -1
            plane = -1
        else:
            if qdev > 0:
                plane = -1
            elif get_bool("HOROVOD_JAX_DISTRIBUTED", False):
                # jax.device_count() would initialize the backend here,
                # and basics.init() has not yet run
                # jax.distributed.initialize() (which must come first on
                # pods).  A distributed world's mesh spans >= 2 devices
                # whenever the world does, so pin from the world size.
                if cfg.size < 2:
                    plane = -1
            else:
                try:
                    if jax.device_count() < 2:
                        plane = -1
                except Exception:
                    plane = -1
        rc = self._lib.hvd_init(
            cfg.rank, cfg.size, cfg.local_rank, cfg.local_size,
            controller.encode(), cfg.rendezvous_addr.encode(),
            cfg.rendezvous_port, cfg.cycle_time_ms,
            cfg.fusion_threshold_bytes, cfg.cache_capacity,
            1 if cfg.autotune else 0,
            (cfg.autotune_log or "").encode(),
            1 if cfg.hierarchical_allreduce else 0,
            {"none": 0, "bf16": 1, "int8": 2, "int4": 3, "int8g": 4}.get(
                cfg.wire_compression, 0),
            qdev, qsched,
            1 if cfg.metrics_enabled else 0,
            (cfg.metrics_file or "").encode(),
            cfg.metrics_interval_s,
            (cfg.timeline_path or "").encode(),
            1 if cfg.timeline_mark_cycles else 0,
            cfg.stall_warning_s if cfg.stall_check_enabled else 0.0,
            cfg.stall_shutdown_s,
            _LOG_LEVELS.get(cfg.log_level, 3),
            1 if cfg.flight_recorder_enabled else 0,
            cfg.flight_recorder_slots,
            (cfg.postmortem_dir or "").encode(),
            cfg.autopilot_port,
            1 if cfg.step_trace_enabled else 0,
            cfg.step_trace_slots,
            plane,
        )
        if rc != 0:
            raise NativeCoreError(
                f"native core init failed (rc={rc}, control protocol "
                f"v{PROTOCOL_VERSION}): {self._last_error()}")
        if hasattr(self._lib, "hvd_elastic_generation_set"):
            # Publish the elastic generation the driver assigned us (0 for
            # non-elastic jobs) as the hvd_elastic_generation gauge.
            try:
                gen = int(os.environ.get("HOROVOD_ELASTIC_GENERATION", "0"))
            except ValueError:
                gen = 0
            self._lib.hvd_elastic_generation_set(gen)
        if qdev >= 0 and hasattr(self._lib, "hvd_device_plane_note"):
            # Mirror quantized-collective byte deltas into the native
            # metrics registry (hvd.metrics() / Prometheus exposure).
            try:
                from .ops import quantize as _qz
            except Exception:
                pass
            else:
                note = self._lib.hvd_device_plane_note
                _qz.set_native_byte_sink(
                    lambda raw, enc: note(int(raw), int(enc)))
        if hasattr(self._lib, "hvd_gspmd_plane_note"):
            # Mirror each gspmd trace's HLO collective inventory into the
            # native metrics registry (hvd.metrics() / Prometheus / flight
            # type 16) — once per trace, never per step.
            try:
                from .ops import hlo_inspect as _hi
            except Exception:
                pass
            else:
                gnote = self._lib.hvd_gspmd_plane_note
                _hi.set_native_sink(
                    lambda ops, raw, wire: gnote(int(ops), int(raw),
                                                 int(wire)))

    def step_trace_note_plane(self, plane: int) -> None:
        """Tag the step-trace ring with the data plane running the steps
        (-1 unknown, 0 eager, 1 gspmd).  Silently a no-op on a stale .so
        predating the entry point."""
        if hasattr(self._lib, "hvd_step_trace_note_plane"):
            self._lib.hvd_step_trace_note_plane(int(plane))

    def shutdown(self) -> None:
        if self._lib.hvd_is_initialized():
            self._lib.hvd_shutdown()

    def _last_error(self) -> str:
        msg = self._lib.hvd_last_error()
        return msg.decode() if msg else "unknown"

    def rank(self) -> int:
        return self._lib.hvd_rank()

    def size(self) -> int:
        return self._lib.hvd_size()

    # -- control plane ------------------------------------------------------
    def enqueue(self, entry: TensorEntry) -> None:
        shape = (ctypes.c_longlong * max(len(entry.array.shape), 1))(
            *entry.array.shape)
        if entry.splits is not None:
            splits = (ctypes.c_longlong * len(entry.splits))(
                *[int(s) for s in entry.splits])
            nsplits = len(entry.splits)
        else:
            splits = None
            nsplits = 0
        rc = self._lib.hvd_enqueue(
            entry.handle, entry.name.encode(), int(entry.op),
            int(entry.dtype), int(entry.reduce_op), entry.array.nbytes,
            shape, len(entry.array.shape), entry.process_set_id,
            entry.root_rank, entry.prescale_factor, entry.postscale_factor,
            splits, nsplits, 1 if entry.device_array is not None else 0,
            entry.group_key.encode(), entry.group_size)
        if rc == -2:
            raise ValueError(f"duplicate in-flight tensor name {entry.name!r}")
        if rc != 0:
            raise NativeCoreError(f"enqueue failed rc={rc}")

    def pop_response(self, timeout: float) -> Optional[FusedResponse]:
        n = self._lib.hvd_pop_response(self._resp_buf, self._resp_cap,
                                       int(timeout * 1000))
        while n == -2:  # buffer too small: the response stays queued; grow
            self._resp_cap *= 4
            self._resp_buf = ctypes.create_string_buffer(self._resp_cap)
            n = self._lib.hvd_pop_response(self._resp_buf, self._resp_cap, 0)
        if n <= 0:
            return None
        obj = json.loads(self._resp_buf.raw[:n].decode())
        self.set_current_seq(obj.get("seq", -1))
        return FusedResponse(
            op=OpType(obj["op"]),
            dtype=DataType(obj["dtype"]),
            process_set_id=obj["psid"],
            handles=list(obj["handles"]),
            error=obj["error"] or None,
            counts=obj.get("counts"),
            last_joined=obj.get("last_joined", -1),
            seq=obj.get("seq", -1),
            device=bool(obj.get("device", 0)),
        )

    def set_current_seq(self, seq: int) -> None:
        # thread-local: each executor lane tags its own collective's
        # frames (the C++ side mirrors this with a thread_local).
        self._seq_tls.seq = int(seq)

    @property
    def _current_seq(self) -> int:
        return getattr(self._seq_tls, "seq", -1)

    # -- process sets -------------------------------------------------------
    def add_process_set(self, ranks: Sequence[int],
                        weight: float = 1.0) -> int:
        arr = (ctypes.c_int * len(ranks))(*[int(r) for r in ranks])
        if weight != 1.0 and hasattr(self._lib, "hvd_add_process_set2"):
            psid = self._lib.hvd_add_process_set2(arr, len(ranks),
                                                  float(weight))
        else:
            psid = self._lib.hvd_add_process_set(arr, len(ranks))
        if psid < 0:
            raise NativeCoreError("add_process_set failed")
        return psid

    def remove_process_set(self, process_set_id: int) -> None:
        self._lib.hvd_remove_process_set(process_set_id)

    def process_set_ranks(self, process_set_id: int) -> List[int]:
        cap = max(self.size(), 1)
        out = (ctypes.c_int * cap)()
        n = self._lib.hvd_process_set_ranks(process_set_id, out, cap)
        if n < 0:
            raise ValueError(f"unknown process set id {process_set_id}")
        return [out[i] for i in range(n)]

    # -- host data plane ----------------------------------------------------
    def _check(self, rc: int, what: str) -> None:
        if rc != 0:
            from .exceptions import HorovodInternalError

            raise HorovodInternalError(
                f"{what} failed (rc={rc}): {self._last_error()}")

    def allreduce_buffer(self, buf: np.ndarray, psid: int,
                         reduce_op: ReduceOp) -> np.ndarray:
        buf = np.ascontiguousarray(buf)
        rc = self._lib.hvd_allreduce_buffer(
            self._current_seq, buf.ctypes.data_as(ctypes.c_void_p), buf.size,
            int(wire_dtype(buf.dtype)), int(reduce_op), psid)
        self._check(rc, "allreduce")
        return buf

    def reducescatter_buffer(self, buf: np.ndarray, psid: int,
                             reduce_op: ReduceOp, slice_counts) -> np.ndarray:
        """In-place ring reduce-scatter: on return this rank's slice
        (slice_counts[my_pos] elements at its offset) is fully reduced;
        the rest of buf is unspecified."""
        buf = np.ascontiguousarray(buf)
        arr = (ctypes.c_longlong * len(slice_counts))(*slice_counts)
        rc = self._lib.hvd_reducescatter_buffer(
            self._current_seq, buf.ctypes.data_as(ctypes.c_void_p), buf.size,
            int(wire_dtype(buf.dtype)), int(reduce_op), psid, arr,
            len(slice_counts))
        self._check(rc, "reducescatter")
        return buf

    def allgather_buffer(self, buf: np.ndarray, psid: int):
        buf = np.ascontiguousarray(buf)
        d0 = buf.shape[0] if buf.ndim else 1
        row_bytes = (buf.nbytes // d0) if d0 > 0 else int(
            np.prod(buf.shape[1:], dtype=np.int64) * buf.itemsize) or buf.itemsize
        out_ptr = ctypes.c_void_p()
        out_len = ctypes.c_longlong()
        cap = max(self.size(), 1)
        counts = (ctypes.c_longlong * cap)()
        n_counts = ctypes.c_int()
        rc = self._lib.hvd_allgather_buffer(
            self._current_seq, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
            psid, ctypes.byref(out_ptr), ctypes.byref(out_len), counts, cap,
            ctypes.byref(n_counts))
        self._check(rc, "allgather")
        try:
            # One copy, not two, and no per-length ctypes type-cache
            # growth: memmove the C buffer straight into a numpy-owned
            # array (the C side frees right after).
            flat = np.empty(out_len.value // buf.itemsize, dtype=buf.dtype)
            if out_len.value:
                ctypes.memmove(flat.ctypes.data, out_ptr, out_len.value)
        finally:
            self._lib.hvd_free(out_ptr)
        rows = flat.size // (row_bytes // buf.itemsize) if row_bytes else 0
        stacked = flat.reshape(rows, -1) if rows else flat.reshape(0, 1)
        row_counts = np.array(
            [counts[i] // row_bytes for i in range(n_counts.value)],
            dtype=np.int64)
        return stacked, row_counts

    def broadcast_buffer(self, buf: np.ndarray, root_rank: int,
                         psid: int) -> np.ndarray:
        buf = np.ascontiguousarray(buf).copy()
        rc = self._lib.hvd_broadcast_buffer(
            self._current_seq, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
            root_rank, psid)
        self._check(rc, "broadcast")
        return buf

    def alltoall_buffer(self, buf: np.ndarray, splits: np.ndarray,
                        psid: int):
        buf = np.ascontiguousarray(buf)
        d0 = buf.shape[0] if buf.ndim else 1
        row_bytes = (buf.nbytes // d0) if d0 > 0 else buf.itemsize
        csplits = (ctypes.c_longlong * len(splits))(*[int(s) for s in splits])
        out_ptr = ctypes.c_void_p()
        out_len = ctypes.c_longlong()
        cap = max(len(splits), 1)
        recv = (ctypes.c_longlong * cap)()
        n_recv = ctypes.c_int()
        rc = self._lib.hvd_alltoall_buffer(
            self._current_seq, buf.ctypes.data_as(ctypes.c_void_p), csplits,
            len(splits), row_bytes, psid, ctypes.byref(out_ptr),
            ctypes.byref(out_len), recv, ctypes.byref(n_recv))
        self._check(rc, "alltoall")
        try:
            # One copy, not two, and no per-length ctypes type-cache
            # growth: memmove the C buffer straight into a numpy-owned
            # array (the C side frees right after).
            flat = np.empty(out_len.value // buf.itemsize, dtype=buf.dtype)
            if out_len.value:
                ctypes.memmove(flat.ctypes.data, out_ptr, out_len.value)
        finally:
            self._lib.hvd_free(out_ptr)
        recv_splits = np.array([recv[i] for i in range(n_recv.value)],
                               dtype=np.int64)
        total_rows = int(recv_splits.sum())
        out = flat.reshape(total_rows, -1) if total_rows else flat.reshape(0, 1)
        return out, recv_splits

    def barrier(self, process_set_id: int) -> None:
        rc = self._lib.hvd_barrier(self._current_seq, process_set_id)
        self._check(rc, "barrier")

    # -- observability ------------------------------------------------------
    def negotiation_stats(self) -> dict:
        """Cumulative negotiation ctrl-channel payload bytes for this rank
        (the response-cache fast path's measurable effect: hits travel as
        16-byte (id, handle) pairs instead of full request metadata)."""
        sent = ctypes.c_longlong()
        recv = ctypes.c_longlong()
        self._lib.hvd_negotiation_stats(ctypes.byref(sent),
                                        ctypes.byref(recv))
        return {"ctrl_sent": sent.value, "ctrl_recv": recv.value}

    def ctrl_plane_stats(self) -> dict:
        """Cumulative negotiation ctrl-plane frame and payload-byte counters
        for this rank.  On the coordinator, ctrl_msgs_recv per cycle is the
        leader-tree (HOROVOD_CONTROL_TREE, protocol v9) acceptance metric:
        flat mode receives one frame per worker per cycle, tree mode one per
        local child plus one aggregate per remote host.  An old .so without
        the entry point returns zeros."""
        if not hasattr(self._lib, "hvd_ctrl_plane_stats"):
            return {"ctrl_msgs_sent": 0, "ctrl_msgs_recv": 0,
                    "ctrl_bytes_sent": 0, "ctrl_bytes_recv": 0}
        msgs_sent = ctypes.c_longlong()
        msgs_recv = ctypes.c_longlong()
        bytes_sent = ctypes.c_longlong()
        bytes_recv = ctypes.c_longlong()
        self._lib.hvd_ctrl_plane_stats(
            ctypes.byref(msgs_sent), ctypes.byref(msgs_recv),
            ctypes.byref(bytes_sent), ctypes.byref(bytes_recv))
        return {"ctrl_msgs_sent": msgs_sent.value,
                "ctrl_msgs_recv": msgs_recv.value,
                "ctrl_bytes_sent": bytes_sent.value,
                "ctrl_bytes_recv": bytes_recv.value}

    def data_plane_stats(self) -> dict:
        """Cumulative host-data-plane bytes sent by this rank, split by
        locality: to ranks on this host vs. across hosts.  The hierarchical
        allreduce's measurable effect is a shrinking cross-host share; wire
        compression's is wire bytes dropping below the raw (pre-codec)
        bytes, which the data_raw_* counters track.  device_raw /
        device_encoded are the analogous pair for the device plane's
        quantized in-jit ring (HOROVOD_WIRE_COMPRESSION=device=int8);
        gspmd_raw / gspmd_wire are the gspmd plane's — analytic payload
        vs. wire bytes of the compiler-inserted collectives inventoried
        at trace time (ops/hlo_inspect.py)."""
        local = ctypes.c_longlong()
        xhost = ctypes.c_longlong()
        raw_local = ctypes.c_longlong()
        raw_xhost = ctypes.c_longlong()
        self._lib.hvd_data_plane_stats2(
            ctypes.byref(local), ctypes.byref(xhost),
            ctypes.byref(raw_local), ctypes.byref(raw_xhost))
        dev_raw = dev_enc = 0
        if hasattr(self._lib, "hvd_device_plane_stats"):
            a = ctypes.c_longlong()
            b = ctypes.c_longlong()
            self._lib.hvd_device_plane_stats(ctypes.byref(a), ctypes.byref(b))
            dev_raw, dev_enc = a.value, b.value
        else:
            # Stale .so: the Python-side counters hold the same totals
            # (the native registry only ever sees forwarded deltas).
            try:
                from .ops import quantize as _qz
                dev_raw, dev_enc = _qz.device_byte_counters()
            except Exception:
                pass
        gspmd_raw = gspmd_wire = 0
        if hasattr(self._lib, "hvd_gspmd_plane_stats"):
            a = ctypes.c_longlong()
            b = ctypes.c_longlong()
            self._lib.hvd_gspmd_plane_stats(ctypes.byref(a), ctypes.byref(b))
            gspmd_raw, gspmd_wire = a.value, b.value
        else:
            # Stale .so: the Python-side inventory counters hold the same
            # totals (the native registry only ever sees forwarded notes).
            try:
                from .ops import hlo_inspect as _hi
                gspmd_raw, gspmd_wire = _hi.gspmd_byte_counters()
            except Exception:
                pass
        return {"data_sent_local": local.value,
                "data_sent_xhost": xhost.value,
                "data_raw_local": raw_local.value,
                "data_raw_xhost": raw_xhost.value,
                "device_raw": dev_raw,
                "device_encoded": dev_enc,
                "gspmd_raw": gspmd_raw,
                "gspmd_wire": gspmd_wire}

    _warned_no_metrics = False

    def metrics(self) -> dict:
        """Local metrics registry as a dict (counters + power-of-two-bucket
        histograms); on the coordinator the dump also carries the cluster
        view and the last straggler report.  An old .so without the entry
        point degrades to {} with a one-time warning."""
        if not hasattr(self._lib, "hvd_metrics_dump"):
            if not NativeCore._warned_no_metrics:
                NativeCore._warned_no_metrics = True
                log.warning("native core predates the metrics plane "
                            "(hvd_metrics_dump missing); metrics() returns {}")
            return {}
        cap = 1 << 16
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.hvd_metrics_dump(buf, cap)
        while n == -2:  # buffer too small: grow and retry
            cap *= 4
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.hvd_metrics_dump(buf, cap)
        if n <= 0:
            return {}
        return json.loads(buf.raw[:n].decode())

    def migrate_note(self, phase: int, nbytes: int,
                     source_rank: int = -1) -> None:
        """Record one elastic-migration phase natively: the migrate
        counters, a type-14 flight event, and a MIGRATE timeline instant.
        Silently a no-op on a stale .so predating the entry point."""
        if hasattr(self._lib, "hvd_migrate_note"):
            self._lib.hvd_migrate_note(int(phase), int(nbytes),
                                       int(source_rank))

    _warned_no_flight = False

    def flight_record(self) -> dict:
        """Snapshot of this rank's flight-recorder ring (the always-on event
        black box): {"rank", "host", "slots", "dropped", "types", "events"}
        where events are [ts_us, seq, type, tid, a, b] rows, oldest first.
        {} when the recorder is off (HOROVOD_FLIGHT_RECORDER=off) or the .so
        predates it."""
        if not hasattr(self._lib, "hvd_flight_record"):
            if not NativeCore._warned_no_flight:
                NativeCore._warned_no_flight = True
                log.warning("native core predates the flight recorder "
                            "(hvd_flight_record missing); flight_record() "
                            "returns {}")
            return {}
        cap = 1 << 16
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.hvd_flight_record(buf, cap)
        while n == -2:  # buffer too small: grow and retry
            cap *= 4
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.hvd_flight_record(buf, cap)
        if n <= 0:
            return {}
        return json.loads(buf.raw[:n].decode())

    _warned_no_steptrace = False

    def step_trace(self) -> dict:
        """Snapshot of this rank's causal step-trace ring: {"schema",
        "rank", "world", "phases", "steps", "fleet"} where steps are
        [step, start_us, end_us, <5 phase us>] rows and fleet (rank 0
        only) carries per-step cross-rank sums with dominant_phase /
        dominant_rank attribution.  {} when tracing is off
        (HOROVOD_STEP_TRACE=off) or the .so predates it."""
        if not hasattr(self._lib, "hvd_step_trace"):
            if not NativeCore._warned_no_steptrace:
                NativeCore._warned_no_steptrace = True
                log.warning("native core predates causal step tracing "
                            "(hvd_step_trace missing); step_trace() "
                            "returns {}")
            return {}
        cap = 1 << 16
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.hvd_step_trace(buf, cap)
        while n == -2:  # buffer too small: grow and retry
            cap *= 4
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.hvd_step_trace(buf, cap)
        if n <= 0:
            return {}
        return json.loads(buf.raw[:n].decode())

    _warned_no_fleet = False

    def fleet_history(self) -> dict:
        """The coordinator's multi-resolution fleet history + anomaly log
        (fleethistory-v1): {"schema", "columns", "tiers", "anomalies"}
        where tiers are {"period_s", "samples"} rings of
        [ts_us, step_p99_us, neg_p99_us, goodput_ppm, wire_ratio_ppm,
        steps] rows and anomalies is the sentinel's log, newest last.
        {} when the plane is off (HOROVOD_FLEET_TELEMETRY=off), on
        non-coordinator ranks before any tick, or on a .so predating it."""
        if not hasattr(self._lib, "hvd_fleet_history"):
            if not NativeCore._warned_no_fleet:
                NativeCore._warned_no_fleet = True
                log.warning("native core predates the fleet-telemetry plane "
                            "(hvd_fleet_history missing); fleet_history() "
                            "returns {}")
            return {}
        cap = 1 << 16
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.hvd_fleet_history(buf, cap)
        while n == -2:  # buffer too small: grow and retry
            cap *= 4
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.hvd_fleet_history(buf, cap)
        if n <= 0:
            return {}
        return json.loads(buf.raw[:n].decode())

    def start_timeline(self, path: str, mark_cycles: bool) -> None:
        self._lib.hvd_start_timeline(path.encode(), 1 if mark_cycles else 0)

    def stop_timeline(self) -> None:
        self._lib.hvd_stop_timeline()
