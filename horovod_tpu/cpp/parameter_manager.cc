#include "parameter_manager.h"

#include <algorithm>

#include "common.h"
#include "logging.h"

namespace hvdtpu {

namespace {
constexpr int64_t kMinFusion = 1 << 20;         // 1 MiB
constexpr int64_t kMaxFusion = 512LL << 20;     // 512 MiB
constexpr double kMinCycleMs = 0.2;
constexpr double kMaxCycleMs = 100.0;
}  // namespace

void ParameterManager::Initialize(int64_t fusion_threshold,
                                  double cycle_time_ms,
                                  const std::string& log_path) {
  fusion_ = best_fusion_ = fusion_threshold;
  cycle_ms_ = best_cycle_ = cycle_time_ms;
  window_start_ = MonotonicSeconds();
  active_ = true;
  if (!log_path.empty()) {
    log_ = std::fopen(log_path.c_str(), "w");
    if (log_) std::fputs("time_s,fusion_bytes,cycle_ms,score_bytes_per_s\n", log_);
  }
}

ParameterManager::~ParameterManager() {
  if (log_) std::fclose(log_);
}

void ParameterManager::RecordBytes(int64_t bytes) { bytes_ += bytes; }

void ParameterManager::Log(double score) {
  if (!log_) return;
  std::fprintf(log_, "%.3f,%lld,%.3f,%.1f\n", MonotonicSeconds(),
               static_cast<long long>(fusion_), cycle_ms_, score);
  std::fflush(log_);
}

void ParameterManager::Score(double score) {
  Log(score);
  if (warmup_windows_ > 0) {
    --warmup_windows_;
    best_score_ = std::max(best_score_, score);
    return;
  }
  if (score >= best_score_) {
    // Keep climbing in the same direction on the same knob.
    best_score_ = score;
    best_fusion_ = fusion_;
    best_cycle_ = cycle_ms_;
  } else {
    // Revert and move to the next knob/direction.
    fusion_ = best_fusion_;
    cycle_ms_ = best_cycle_;
    if (direction_ == 1) {
      direction_ = -1;
    } else {
      direction_ = 1;
      knob_ = (knob_ + 1) % 2;
    }
  }
  if (knob_ == 0) {
    int64_t next = direction_ > 0 ? fusion_ * 2 : fusion_ / 2;
    fusion_ = std::min(kMaxFusion, std::max(kMinFusion, next));
  } else {
    double next = direction_ > 0 ? cycle_ms_ * 2 : cycle_ms_ / 2;
    cycle_ms_ = std::min(kMaxCycleMs, std::max(kMinCycleMs, next));
  }
}

bool ParameterManager::Tick(int64_t* fusion_threshold, double* cycle_time_ms) {
  if (!active_) return false;
  double now = MonotonicSeconds();
  if (now - window_start_ < window_s_) return false;
  double score = static_cast<double>(bytes_) / (now - window_start_);
  bytes_ = 0;
  window_start_ = now;
  int64_t old_fusion = fusion_;
  double old_cycle = cycle_ms_;
  Score(score);
  *fusion_threshold = fusion_;
  *cycle_time_ms = cycle_ms_;
  return fusion_ != old_fusion || cycle_ms_ != old_cycle;
}

}  // namespace hvdtpu
