#include "parameter_manager.h"

#include <algorithm>
#include <cmath>

#include "common.h"
#include "logging.h"

namespace hvdtpu {

namespace {
constexpr int64_t kMinFusion = 1 << 20;      // 1 MiB
constexpr int64_t kMaxFusion = 512LL << 20;  // 512 MiB
constexpr double kMinCycleMs = 0.2;
constexpr double kMaxCycleMs = 100.0;
// log2 spans of the two knobs (normalize to the unit square).
const double kFusionSpan = std::log2(static_cast<double>(kMaxFusion) /
                                     static_cast<double>(kMinFusion));
const double kCycleSpan = std::log2(kMaxCycleMs / kMinCycleMs);

constexpr double kLengthscale = 0.3;  // RBF, unit-square coordinates
constexpr double kNoise = 1e-2;      // observation noise (normalized scores)
constexpr int kGrid = 24;            // EI candidate grid per axis
constexpr int kMaxTuneSamples = 40;  // GP sample cap (bounds O(n^3) refit)
constexpr int kMaxWindowsSinceBest = 12;  // plateau -> converge

double FusionToX(int64_t fusion) {
  double f = std::min<double>(std::max<double>(fusion, kMinFusion),
                              static_cast<double>(kMaxFusion));
  return std::log2(f / kMinFusion) / kFusionSpan;
}
int64_t XToFusion(double x) {
  double f = std::exp2(x * kFusionSpan) * kMinFusion;
  return std::min(kMaxFusion, std::max<int64_t>(
      kMinFusion, static_cast<int64_t>(f)));
}
double CycleToX(double ms) {
  double c = std::min(std::max(ms, kMinCycleMs), kMaxCycleMs);
  return std::log2(c / kMinCycleMs) / kCycleSpan;
}
double XToCycle(double x) {
  return std::min(kMaxCycleMs,
                  std::max(kMinCycleMs, std::exp2(x * kCycleSpan) *
                                            kMinCycleMs));
}

// Categorical coordinates enter the RBF at half scale: distance 0.5
// between adjacent categories keeps moderate correlation, so each arm
// borrows shape information from the others instead of starting cold.
// The 3-level wire-compression knob maps {none, bf16, int8} to
// {0, 0.5, 1}, so codec aggressiveness is ordinal in the kernel.
constexpr double kCatScale = 0.5;

// x4 <-> WireCodec: the GP works on {0, 0.5, 1}; the data plane wants
// {0, 1, 2}.
constexpr double kWireLevels[3] = {0.0, 0.5, 1.0};
int X4ToWire(double x4) { return x4 < 0.25 ? 0 : (x4 < 0.75 ? 1 : 2); }
double WireToX4(int wire) {
  return kWireLevels[std::min(2, std::max(0, wire))];
}

// x5 <-> device codec: {0, 1/3, 2/3, 1} for {none, int8, int4, int8g} —
// ordinal in codec aggressiveness so adjacent codecs share GP shape.
constexpr double kQdevLevels[4] = {0.0, 1.0 / 3.0, 2.0 / 3.0, 1.0};
int X5ToQdev(double x5) {
  return x5 < 1.0 / 6.0 ? 0 : (x5 < 0.5 ? 1 : (x5 < 5.0 / 6.0 ? 2 : 3));
}
double QdevToX5(int qdev) {
  return kQdevLevels[std::min(3, std::max(0, qdev))];
}

// x6 <-> device-ring schedule: {0, 0.5, 1} for {ring, bidi, torus} —
// ordinal in parallelism (one ICI direction, both, both axes of a torus).
constexpr double kSchedLevels[3] = {0.0, 0.5, 1.0};
int X6ToSched(double x6) { return x6 < 0.25 ? 0 : (x6 < 0.75 ? 1 : 2); }
double SchedToX6(int sched) {
  return kSchedLevels[std::min(2, std::max(0, sched))];
}

// x7 <-> data plane: {0, 1} for {eager explicit, gspmd compiler-inserted}
// — binary like the cache and hierarchical knobs.
constexpr double kPlaneLevels[2] = {0.0, 1.0};
int X7ToPlane(double x7) { return x7 < 0.5 ? 0 : 1; }
double PlaneToX7(int plane) {
  return kPlaneLevels[std::min(1, std::max(0, plane))];
}

double Rbf(double ax, double ay, double az, double aw, double av, double au,
           double at, double as, double bx, double by, double bz, double bw,
           double bv, double bu, double bt, double bs) {
  double dx = ax - bx, dy = ay - by, dz = kCatScale * (az - bz),
         dw = kCatScale * (aw - bw), dv = kCatScale * (av - bv),
         du = kCatScale * (au - bu), dt = kCatScale * (at - bt),
         ds = kCatScale * (as - bs);
  return std::exp(-(dx * dx + dy * dy + dz * dz + dw * dw + dv * dv +
                    du * du + dt * dt + ds * ds) /
                  (2 * kLengthscale * kLengthscale));
}

// Standard normal pdf/cdf for Expected Improvement.
double Phi(double z) { return 0.5 * std::erfc(-z / std::sqrt(2.0)); }
double phi(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

}  // namespace

// ---- BayesianOptimizer -----------------------------------------------------

void BayesianOptimizer::AddSample(double x0, double x1, double x2, double x3,
                                  double x4, double x5, double x6, double x7,
                                  double score) {
  xs_.push_back({x0, x1, x2, x3, x4, x5, x6, x7});
  ys_.push_back(score);
  y_max_ = std::max(y_max_, std::abs(score));
  FitGP();
}

void BayesianOptimizer::FitGP() {
  const int n = static_cast<int>(xs_.size());
  if (n == 0) return;
  const double denom = y_max_ > 0 ? y_max_ : 1.0;
  // K = k(X, X) + noise * I  (row-major), then lower Cholesky in place.
  chol_.assign(static_cast<size_t>(n) * n, 0.0);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j <= i; ++j) {
      double k = Rbf(xs_[i].x0, xs_[i].x1, xs_[i].x2, xs_[i].x3, xs_[i].x4,
                     xs_[i].x5, xs_[i].x6, xs_[i].x7, xs_[j].x0, xs_[j].x1,
                     xs_[j].x2, xs_[j].x3, xs_[j].x4, xs_[j].x5, xs_[j].x6,
                     xs_[j].x7);
      if (i == j) k += kNoise;
      chol_[i * n + j] = k;
    }
  }
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j <= i; ++j) {
      double sum = chol_[i * n + j];
      for (int k = 0; k < j; ++k) sum -= chol_[i * n + k] * chol_[j * n + k];
      if (i == j) {
        chol_[i * n + i] = std::sqrt(std::max(sum, 1e-12));
      } else {
        chol_[i * n + j] = sum / chol_[j * n + j];
      }
    }
  }
  // alpha = K^-1 y via two triangular solves.
  alpha_.assign(n, 0.0);
  for (int i = 0; i < n; ++i) {
    double sum = ys_[i] / denom;
    for (int k = 0; k < i; ++k) sum -= chol_[i * n + k] * alpha_[k];
    alpha_[i] = sum / chol_[i * n + i];
  }
  for (int i = n - 1; i >= 0; --i) {
    double sum = alpha_[i];
    for (int k = i + 1; k < n; ++k) sum -= chol_[k * n + i] * alpha_[k];
    alpha_[i] = sum / chol_[i * n + i];
  }
}

void BayesianOptimizer::Predict(double x0, double x1, double x2, double x3,
                                double x4, double x5, double x6, double x7,
                                double* mean, double* var) const {
  const int n = static_cast<int>(xs_.size());
  if (n == 0) {
    *mean = 0;
    *var = 1;
    return;
  }
  std::vector<double> kstar(n);
  for (int i = 0; i < n; ++i) {
    kstar[i] = Rbf(x0, x1, x2, x3, x4, x5, x6, x7, xs_[i].x0, xs_[i].x1,
                   xs_[i].x2, xs_[i].x3, xs_[i].x4, xs_[i].x5, xs_[i].x6,
                   xs_[i].x7);
  }
  double m = 0;
  for (int i = 0; i < n; ++i) m += kstar[i] * alpha_[i];
  // v = L^-1 k*; var = k(x,x) - v.v
  std::vector<double> v(n);
  for (int i = 0; i < n; ++i) {
    double sum = kstar[i];
    for (int k = 0; k < i; ++k) sum -= chol_[i * n + k] * v[k];
    v[i] = sum / chol_[i * n + i];
  }
  double vv = 0;
  for (int i = 0; i < n; ++i) vv += v[i] * v[i];
  *mean = m;
  *var = std::max(1e-12, 1.0 + kNoise - vv);
}

void BayesianOptimizer::Suggest(double* x0, double* x1, double* x2,
                                double* x3, double* x4, double* x5,
                                double* x6, double* x7) {
  // Seed phase: spread the first probes over the categories before
  // trusting the GP (the reference warms its GP with a fixed design too).
  // When x3/x4/x5/x6/x7 are pinned, their seed columns collapse to 0 so
  // no probe is wasted on a dead arm.  The x5 column walks all four codec
  // levels, the x6 column all three schedules, and the x7 column
  // alternates the two planes.
  static const double kSeeds[][8] = {
      {0.15, 0.15, 0, 0, 0, 0, 0, 0},
      {0.85, 0.15, 1, 1, 1, 1, 1, 1},
      {0.5, 0.5, 0, 1, 0.5, 1.0 / 3.0, 0.5, 1},
      {0.5, 0.5, 1, 0, 1, 2.0 / 3.0, 1, 0},
      {0.15, 0.85, 0, 1, 0.5, 1, 0.5, 1},
      {0.85, 0.85, 1, 0, 0, 2.0 / 3.0, 0, 0}};
  const int n = num_samples();
  if (n < 6) {
    *x0 = kSeeds[n][0];
    *x1 = kSeeds[n][1];
    *x2 = kSeeds[n][2];
    *x3 = tune_x3_ ? kSeeds[n][3] : 0.0;
    *x4 = tune_x4_ ? kSeeds[n][4] : 0.0;
    *x5 = tune_x5_ ? kSeeds[n][5] : 0.0;
    *x6 = tune_x6_ ? kSeeds[n][6] : 0.0;
    *x7 = tune_x7_ ? kSeeds[n][7] : 0.0;
    return;
  }
  const double denom = y_max_ > 0 ? y_max_ : 1.0;
  double best_y = *std::max_element(ys_.begin(), ys_.end()) / denom;
  double best_ei = -1, bx = 0.5, by = 0.5, bz = 1.0, bw = 0.0, bv = 0.0,
         bu = 0.0, bt = 0.0, bs = 0.0;
  const int cat3_max = tune_x3_ ? 1 : 0;
  const int cat4_max = tune_x4_ ? 2 : 0;
  const int cat5_max = tune_x5_ ? 3 : 0;
  const int cat6_max = tune_x6_ ? 2 : 0;
  const int cat7_max = tune_x7_ ? 1 : 0;
  for (int cat7 = 0; cat7 <= cat7_max; ++cat7) {
    for (int cat6 = 0; cat6 <= cat6_max; ++cat6) {
      for (int cat5 = 0; cat5 <= cat5_max; ++cat5) {
        for (int cat4 = 0; cat4 <= cat4_max; ++cat4) {
          for (int cat3 = 0; cat3 <= cat3_max; ++cat3) {
            for (int cat = 0; cat <= 1; ++cat) {
              for (int i = 0; i <= kGrid; ++i) {
                for (int j = 0; j <= kGrid; ++j) {
                  // Deterministic jitter decorrelates the grid across
                  // rounds.
                  rng_ = rng_ * 1664525u + 1013904223u;
                  double jx = ((rng_ >> 16) & 0xFF) / 255.0 - 0.5;
                  rng_ = rng_ * 1664525u + 1013904223u;
                  double jy = ((rng_ >> 16) & 0xFF) / 255.0 - 0.5;
                  double cx =
                      std::min(1.0, std::max(0.0, (i + 0.5 * jx) / kGrid));
                  double cy =
                      std::min(1.0, std::max(0.0, (j + 0.5 * jy) / kGrid));
                  double mean, var;
                  Predict(cx, cy, cat, cat3, kWireLevels[cat4],
                          kQdevLevels[cat5], kSchedLevels[cat6],
                          kPlaneLevels[cat7], &mean, &var);
                  double sd = std::sqrt(var);
                  double z = (mean - best_y - 0.01) / sd;
                  double ei = (mean - best_y - 0.01) * Phi(z) + sd * phi(z);
                  if (ei > best_ei) {
                    best_ei = ei;
                    bx = cx;
                    by = cy;
                    bz = cat;
                    bw = cat3;
                    bv = kWireLevels[cat4];
                    bu = kQdevLevels[cat5];
                    bt = kSchedLevels[cat6];
                    bs = kPlaneLevels[cat7];
                  }
                }
              }
            }
          }
        }
      }
    }
  }
  *x0 = bx;
  *x1 = by;
  *x2 = bz;
  *x3 = bw;
  *x4 = bv;
  *x5 = bu;
  *x6 = bt;
  *x7 = bs;
}

void BayesianOptimizer::Best(double* x0, double* x1, double* x2, double* x3,
                             double* x4, double* x5, double* x6, double* x7,
                             double* score) const {
  if (ys_.empty()) {
    *x0 = *x1 = 0.5;
    *x2 = 1.0;
    *x3 = 0.0;
    *x4 = 0.0;
    *x5 = 0.0;
    *x6 = 0.0;
    *x7 = 0.0;
    *score = 0;
    return;
  }
  size_t i = std::max_element(ys_.begin(), ys_.end()) - ys_.begin();
  *x0 = xs_[i].x0;
  *x1 = xs_[i].x1;
  *x2 = xs_[i].x2;
  *x3 = xs_[i].x3;
  *x4 = xs_[i].x4;
  *x5 = xs_[i].x5;
  *x6 = xs_[i].x6;
  *x7 = xs_[i].x7;
  *score = ys_[i];
}

// ---- ParameterManager ------------------------------------------------------

void ParameterManager::Initialize(int64_t fusion_threshold,
                                  double cycle_time_ms,
                                  const std::string& log_path,
                                  bool hierarchical, bool hier_tunable,
                                  int wire_comp, bool wire_tunable,
                                  int qdev_comp, bool qdev_tunable,
                                  int qdev_sched, bool sched_tunable,
                                  int data_plane, bool plane_tunable) {
  fusion_ = best_fusion_ = fusion_threshold;
  cycle_ms_ = best_cycle_ = cycle_time_ms;
  hier_tunable_ = hier_tunable;
  hier_use_ = best_hier_ = hier_tunable ? hierarchical : false;
  bo_.set_tune_x3(hier_tunable);
  wire_tunable_ = wire_tunable;
  wire_use_ = best_wire_ = wire_tunable ? wire_comp : 0;
  bo_.set_tune_x4(wire_tunable);
  qdev_tunable_ = qdev_tunable;
  qdev_use_ = best_qdev_ =
      qdev_tunable ? std::min(3, std::max(0, qdev_comp)) : 0;
  bo_.set_tune_x5(qdev_tunable);
  sched_tunable_ = sched_tunable;
  qdev_sched_use_ = best_qdev_sched_ =
      sched_tunable ? std::min(2, std::max(0, qdev_sched)) : 0;
  bo_.set_tune_x6(sched_tunable);
  plane_tunable_ = plane_tunable;
  plane_use_ = best_plane_ =
      plane_tunable ? std::min(1, std::max(0, data_plane)) : 0;
  bo_.set_tune_x7(plane_tunable);
  window_start_ = MonotonicSeconds();
  active_ = true;
  if (!log_path.empty()) {
    log_ = std::fopen(log_path.c_str(), "w");
    if (log_) {
      std::fputs(
          "time_s,fusion_bytes,cycle_ms,cache_use,hier,wire_comp,qdev,"
          "sched,plane,score_bytes_per_s\n",
          log_);
    }
  }
}

ParameterManager::~ParameterManager() {
  if (log_) std::fclose(log_);
}

void ParameterManager::RecordBytes(int64_t bytes) { bytes_ += bytes; }

void ParameterManager::Log(double score) {
  if (!log_) return;
  std::fprintf(log_, "%.3f,%lld,%.3f,%d,%d,%d,%d,%d,%d,%.1f\n",
               MonotonicSeconds(), static_cast<long long>(fusion_), cycle_ms_,
               cache_use_ ? 1 : 0, hier_use_ ? 1 : 0, wire_use_, qdev_use_,
               qdev_sched_use_, plane_use_, score);
  std::fflush(log_);
}

void ParameterManager::Score(double score) {
  Log(score);
  if (converged_) return;
  if (warmup_windows_ > 0) {
    // The first window mixes pre-traffic noise; don't teach the GP with it.
    --warmup_windows_;
    return;
  }
  bo_.AddSample(FusionToX(fusion_), CycleToX(cycle_ms_),
                cache_use_ ? 1.0 : 0.0, hier_use_ ? 1.0 : 0.0,
                WireToX4(wire_use_), QdevToX5(qdev_use_),
                SchedToX6(qdev_sched_use_), PlaneToX7(plane_use_), score);
  if (score > best_score_ * 1.02) {
    windows_since_best_ = 0;
  } else {
    ++windows_since_best_;
  }
  if (score > best_score_) {
    best_score_ = score;
    best_fusion_ = fusion_;
    best_cycle_ = cycle_ms_;
    best_cache_ = cache_use_;
    best_hier_ = hier_use_;
    best_wire_ = wire_use_;
    best_qdev_ = qdev_use_;
    best_qdev_sched_ = qdev_sched_use_;
    best_plane_ = plane_use_;
  }
  // Converge (reference: ParameterManager stops tuning once samples stop
  // improving): lock in the best configuration instead of exploring
  // forever — steady-state jobs must not pay EI-exploration throughput,
  // and the GP refit is O(n^3) in the sample count.
  if (bo_.num_samples() >= kMaxTuneSamples ||
      windows_since_best_ >= kMaxWindowsSinceBest) {
    converged_ = true;
    fusion_ = best_fusion_;
    cycle_ms_ = best_cycle_;
    cache_use_ = best_cache_;
    hier_use_ = best_hier_;
    wire_use_ = best_wire_;
    qdev_use_ = best_qdev_;
    qdev_sched_use_ = best_qdev_sched_;
    plane_use_ = best_plane_;
    HVD_LOG(INFO) << "autotune converged: fusion=" << fusion_
                  << " cycle_ms=" << cycle_ms_
                  << " announce_cache=" << (cache_use_ ? 1 : 0)
                  << " hierarchical=" << (hier_use_ ? 1 : 0)
                  << " wire_compression=" << wire_use_
                  << " qdev=" << qdev_use_
                  << " qdev_sched=" << qdev_sched_use_
                  << " plane=" << plane_use_;
    return;
  }
  double x0, x1, x2, x3, x4, x5, x6, x7;
  bo_.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6, &x7);
  fusion_ = XToFusion(x0);
  cycle_ms_ = XToCycle(x1);
  cache_use_ = x2 >= 0.5;
  hier_use_ = hier_tunable_ && x3 >= 0.5;
  wire_use_ = wire_tunable_ ? X4ToWire(x4) : 0;
  qdev_use_ = qdev_tunable_ ? X5ToQdev(x5) : 0;
  qdev_sched_use_ = sched_tunable_ ? X6ToSched(x6) : 0;
  plane_use_ = plane_tunable_ ? X7ToPlane(x7) : 0;
}

bool ParameterManager::Tick(int64_t* fusion_threshold, double* cycle_time_ms) {
  if (!active_) return false;
  double now = MonotonicSeconds();
  if (now - window_start_ < window_s_) return false;
  double score = static_cast<double>(bytes_) / (now - window_start_);
  bytes_ = 0;
  window_start_ = now;
  int64_t old_fusion = fusion_;
  double old_cycle = cycle_ms_;
  bool old_cache = cache_use_;
  bool old_hier = hier_use_;
  int old_wire = wire_use_;
  int old_qdev = qdev_use_;
  int old_sched = qdev_sched_use_;
  int old_plane = plane_use_;
  Score(score);
  *fusion_threshold = fusion_;
  *cycle_time_ms = cycle_ms_;
  // cache_use_/hier_use_/wire_use_/qdev_use_/qdev_sched_use_/plane_use_
  // participate: a categorical-only proposal must still be applied by the
  // caller, or the next window's GP sample would be labeled with a
  // setting that was never in effect.
  return fusion_ != old_fusion || cycle_ms_ != old_cycle ||
         cache_use_ != old_cache || hier_use_ != old_hier ||
         wire_use_ != old_wire || qdev_use_ != old_qdev ||
         qdev_sched_use_ != old_sched || plane_use_ != old_plane;
}

}  // namespace hvdtpu
