#include "step_trace.h"

#include <time.h>

#include <algorithm>
#include <cstdio>
#include <mutex>
#include <sstream>
#include <vector>

#include "metrics.h"

namespace hvdtpu {

namespace {

constexpr int kDefaultSlots = 256;
constexpr int kMinSlots = 16;
constexpr int kMaxSlots = 1 << 16;

const char* kPhaseNames[kStepPhases] = {"negotiation_wait", "fusion", "ring",
                                        "fence", "idle"};

struct StepRec {
  int64_t step_id = -1;
  int64_t start_us = 0;
  int64_t end_us = 0;
  int64_t phase_us[kStepPhases] = {0};
  int plane = -1;  // -1 unknown, 0 eager, 1 gspmd
};

// One fleet record per step id on the coordinator.  Keyed by
// step_id % slots with an id check: phase snapshots for step N arrive one
// or more cycles after the coordinator advanced past N, so records stay
// writable until the ring laps them.
struct FleetRec {
  int64_t step_id = -1;
  int64_t phase_us[kStepPhases] = {0};
  std::vector<int64_t> rank_lag_us;
  std::vector<int64_t> rank_neg_us;
  // A rank's trailer repeats the same snapshot every cycle until its next
  // step completes; only the first report per (rank, step) counts.
  std::vector<uint8_t> rank_reported;
  int reported = 0;
  int plane = -1;  // coordinator's plane tag when the record formed
};

struct State {
  int rank = 0;
  int world = 1;
  int slots = kDefaultSlots;
  std::string dump_path;

  // The forming step: lock-free accumulation, swapped out under `mu` once
  // per Advance.
  std::atomic<int64_t> cur_step{0};
  std::atomic<int64_t> cur_phase_us[kStepPhases] = {};
  std::atomic<int64_t> cur_start_us{0};
  // Sticky data-plane tag (StepTraceNotePlane): -1 unknown, 0 eager,
  // 1 gspmd.  Written once per trace, read once per Advance.
  std::atomic<int> cur_plane{-1};

  std::mutex mu;  // guards everything below
  std::vector<StepRec> ring;
  int64_t completed = 0;  // total steps ever closed
  StepRec last;
  std::vector<FleetRec> fleet;
  int64_t fleet_seen = 0;  // fleet records ever touched (dump ordering)
  // Cumulative fleet phase sums (every reported vector, never lapped) —
  // the goodput denominator — and the newest fleet step id reported, for
  // the sentinel's dominant-phase/rank attribution.
  int64_t fleet_phase_cum[kStepPhases] = {0};
  int64_t fleet_last_step = -1;
};

State& S() {
  static State* s = new State();
  return *s;
}

int64_t NowUs() {
  struct timespec ts;
  ::clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

// Locates (initializing if fresh) the fleet record for `step_id`; null for
// ids the ring has already lapped.  Caller holds s.mu.
FleetRec* FleetFor(State& s, int64_t step_id) {
  if (step_id < 0 || s.fleet.empty()) return nullptr;
  FleetRec& f = s.fleet[static_cast<size_t>(step_id) % s.fleet.size()];
  if (f.step_id == step_id) return &f;
  if (f.step_id > step_id) return nullptr;  // lapped: the report is stale
  f.step_id = step_id;
  std::fill(f.phase_us, f.phase_us + kStepPhases, 0);
  f.rank_lag_us.assign(s.world, 0);
  f.rank_neg_us.assign(s.world, 0);
  f.rank_reported.assign(s.world, 0);
  f.reported = 0;
  f.plane = s.cur_plane.load(std::memory_order_relaxed);
  ++s.fleet_seen;
  return &f;
}

// Dominant phase of a fleet phase vector: argmax excluding idle (a fleet
// of sleeping ranks is "idle", not mysteriously busy).
int DominantPhase(const int64_t* phase_us) {
  int best = -1;
  int64_t best_us = 0;
  for (int p = 0; p < kStepPhases; ++p) {
    if (p == kPhaseIdle) continue;
    if (phase_us[p] > best_us) {
      best_us = phase_us[p];
      best = p;
    }
  }
  return best >= 0 ? best : kPhaseIdle;
}

// Dominant rank: whoever the coordinator waited on — argmax announce lag,
// falling back to argmax per-rank negotiation wait; -1 when nothing
// distinguishes the ranks.
int DominantRank(const FleetRec& f) {
  int best = -1;
  int64_t best_us = 0;
  for (size_t r = 0; r < f.rank_lag_us.size(); ++r) {
    if (f.rank_lag_us[r] > best_us) {
      best_us = f.rank_lag_us[r];
      best = static_cast<int>(r);
    }
  }
  if (best >= 0) return best;
  for (size_t r = 0; r < f.rank_neg_us.size(); ++r) {
    if (f.rank_neg_us[r] > best_us) {
      best_us = f.rank_neg_us[r];
      best = static_cast<int>(r);
    }
  }
  return best;
}

void AppendFleetJson(std::ostringstream& os, const FleetRec& f) {
  os << "{\"step\":" << f.step_id << ",\"phase_us\":[";
  for (int p = 0; p < kStepPhases; ++p) {
    if (p) os << ',';
    os << f.phase_us[p];
  }
  os << "],\"lag_us\":[";
  for (size_t r = 0; r < f.rank_lag_us.size(); ++r) {
    if (r) os << ',';
    os << f.rank_lag_us[r];
  }
  os << "],\"reported\":" << f.reported << ",\"dominant_phase\":\""
     << StepPhaseName(DominantPhase(f.phase_us)) << "\",\"dominant_rank\":"
     << DominantRank(f) << ",\"plane\":" << f.plane << "}";
}

}  // namespace

const char* StepPhaseName(int phase) {
  if (phase < 0 || phase >= kStepPhases) return "?";
  return kPhaseNames[phase];
}

StepTraceGate& GlobalStepTraceGate() {
  static StepTraceGate* g = new StepTraceGate();
  return *g;
}

void InitStepTrace(bool enabled, int slots, const std::string& postmortem_dir,
                   int rank, int world) {
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  if (slots <= 0) slots = kDefaultSlots;
  int p = kMinSlots;
  while (p < slots && p < kMaxSlots) p <<= 1;
  s.rank = rank;
  s.world = world > 0 ? world : 1;
  s.slots = p;
  s.ring.assign(p, StepRec());
  s.fleet.assign(p, FleetRec());
  s.completed = 0;
  s.fleet_seen = 0;
  std::fill(s.fleet_phase_cum, s.fleet_phase_cum + kStepPhases, 0);
  s.fleet_last_step = -1;
  s.last = StepRec();
  s.cur_step.store(0, std::memory_order_relaxed);
  for (auto& a : s.cur_phase_us) a.store(0, std::memory_order_relaxed);
  s.cur_plane.store(-1, std::memory_order_relaxed);
  s.cur_start_us.store(NowUs(), std::memory_order_relaxed);
  std::string dir = postmortem_dir;
  auto pos = dir.find("{rank}");
  if (pos != std::string::npos) dir.replace(pos, 6, std::to_string(rank));
  s.dump_path =
      dir.empty() ? "" : dir + "/steptrace." + std::to_string(rank) + ".json";
  GlobalStepTraceGate().enabled.store(enabled, std::memory_order_relaxed);
}

void StepTraceAddPhaseUs(int phase, int64_t us) {
  if (!StepTraceOn()) return;
  if (phase < 0 || phase >= kStepPhases || us <= 0) return;
  S().cur_phase_us[phase].fetch_add(us, std::memory_order_relaxed);
}

void StepTraceNotePlane(int plane) {
  if (plane < -1 || plane > 1) return;
  S().cur_plane.store(plane, std::memory_order_relaxed);
}

void StepTraceAdvance(int64_t step_id) {
  if (!StepTraceOn()) return;
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  const int64_t cur = s.cur_step.load(std::memory_order_relaxed);
  if (step_id <= cur) return;  // duplicate trailer / stale id
  StepRec rec;
  rec.step_id = cur;
  rec.start_us = s.cur_start_us.load(std::memory_order_relaxed);
  rec.end_us = NowUs();
  for (int p = 0; p < kStepPhases; ++p) {
    // exchange, not load: attribution racing the swap lands on the next
    // step (a few microseconds of drift) instead of being double-counted.
    rec.phase_us[p] = s.cur_phase_us[p].exchange(0, std::memory_order_relaxed);
  }
  rec.plane = s.cur_plane.load(std::memory_order_relaxed);
  if (!s.ring.empty()) {
    s.ring[static_cast<size_t>(s.completed) % s.ring.size()] = rec;
  }
  ++s.completed;
  s.last = rec;
  if (MetricsOn()) {
    // The step-time distribution every rank contributes to the fleet
    // sketch (protocol v11): wall time of the step just closed.
    GlobalMetrics().step_time_us.ObserveUs(rec.end_us - rec.start_us);
  }
  s.cur_step.store(step_id, std::memory_order_relaxed);
  s.cur_start_us.store(rec.end_us, std::memory_order_relaxed);
}

int64_t StepTraceCurrentStep() {
  return S().cur_step.load(std::memory_order_relaxed);
}

bool StepTraceLastCompleted(int64_t* step_id, int64_t* phase_us) {
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  if (s.completed == 0) return false;
  *step_id = s.last.step_id;
  for (int p = 0; p < kStepPhases; ++p) phase_us[p] = s.last.phase_us[p];
  return true;
}

void StepTraceFleetPhases(int rank, int64_t step_id, const int64_t* phase_us) {
  if (!StepTraceOn()) return;
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  if (rank < 0 || rank >= s.world) return;
  FleetRec* f = FleetFor(s, step_id);
  if (f == nullptr || f->rank_reported[rank]) return;
  f->rank_reported[rank] = 1;
  for (int p = 0; p < kStepPhases; ++p) {
    f->phase_us[p] += phase_us[p];
    s.fleet_phase_cum[p] += phase_us[p];
  }
  f->rank_neg_us[rank] += phase_us[kPhaseNegotiation];
  ++f->reported;
  if (step_id > s.fleet_last_step) s.fleet_last_step = step_id;
}

void StepTraceFleetPhaseTotals(int64_t* out) {
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  for (int p = 0; p < kStepPhases; ++p) out[p] = s.fleet_phase_cum[p];
}

bool StepTraceFleetDominant(int64_t* step_id, int* phase, int* rank) {
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  if (s.fleet_last_step < 0 || s.fleet.empty()) return false;
  const FleetRec& f =
      s.fleet[static_cast<size_t>(s.fleet_last_step) % s.fleet.size()];
  if (f.step_id != s.fleet_last_step) return false;  // lapped meanwhile
  *step_id = f.step_id;
  *phase = DominantPhase(f.phase_us);
  *rank = DominantRank(f);
  return true;
}

int StepTraceFleetDominantRecentRank(int window) {
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  if (s.fleet_last_step < 0 || s.fleet.empty() || s.world <= 0) return -1;
  std::vector<int> votes(static_cast<size_t>(s.world), 0);
  const int64_t lo = std::max<int64_t>(0, s.fleet_last_step - window + 1);
  for (int64_t sid = s.fleet_last_step; sid >= lo; --sid) {
    const FleetRec& f = s.fleet[static_cast<size_t>(sid) % s.fleet.size()];
    if (f.step_id != sid) continue;  // lapped
    const int r = DominantRank(f);
    if (r >= 0 && r < s.world) ++votes[static_cast<size_t>(r)];
  }
  int best = -1, best_votes = 0;
  for (int r = 0; r < s.world; ++r) {
    if (votes[static_cast<size_t>(r)] > best_votes) {
      best_votes = votes[static_cast<size_t>(r)];
      best = r;
    }
  }
  return best;
}

void StepTraceFleetLagUs(int rank, int64_t lag_us) {
  if (!StepTraceOn()) return;
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  if (rank < 0 || rank >= s.world || lag_us < 0) return;
  FleetRec* f = FleetFor(s, s.cur_step.load(std::memory_order_relaxed));
  if (f == nullptr) return;
  f->rank_lag_us[rank] += lag_us;
}

std::string StepTraceDumpJson() {
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  std::ostringstream os;
  os << "{\"schema\":\"steptrace-v1\",\"rank\":" << s.rank
     << ",\"world\":" << s.world << ",\"slots\":" << s.slots
     << ",\"completed\":" << s.completed << ",\"phases\":[";
  for (int p = 0; p < kStepPhases; ++p) {
    if (p) os << ',';
    os << '"' << kPhaseNames[p] << '"';
  }
  os << "],\"steps\":[";
  const int64_t n = std::min<int64_t>(s.completed,
                                      static_cast<int64_t>(s.ring.size()));
  bool first = true;
  for (int64_t k = s.completed - n; k < s.completed; ++k) {
    const StepRec& r = s.ring[static_cast<size_t>(k) % s.ring.size()];
    if (!first) os << ',';
    first = false;
    os << '[' << r.step_id << ',' << r.start_us << ',' << r.end_us;
    for (int p = 0; p < kStepPhases; ++p) os << ',' << r.phase_us[p];
    // Trailing plane tag (steptrace-v1 stays the schema: consumers index
    // the phase columns positionally and tolerate extra elements).
    os << ',' << r.plane << ']';
  }
  os << "],\"fleet\":[";
  // Ascending step order: walk the ring sorted by id (ids are sparse in
  // the ring but unique), skipping never-written records.
  std::vector<const FleetRec*> recs;
  for (const auto& f : s.fleet) {
    if (f.step_id >= 0) recs.push_back(&f);
  }
  std::sort(recs.begin(), recs.end(),
            [](const FleetRec* a, const FleetRec* b) {
              return a->step_id < b->step_id;
            });
  for (size_t i = 0; i < recs.size(); ++i) {
    if (i) os << ',';
    AppendFleetJson(os, *recs[i]);
  }
  os << "]}";
  return os.str();
}

void StepTraceDumpToFile() {
  State& s = S();
  std::string path;
  {
    std::lock_guard<std::mutex> l(s.mu);
    path = s.dump_path;
  }
  if (path.empty()) return;
  const std::string json = StepTraceDumpJson();
  const std::string tmp = path + ".tmp";
  FILE* f = std::fopen(tmp.c_str(), "w");
  if (f == nullptr) return;
  std::fwrite(json.data(), 1, json.size(), f);
  std::fputc('\n', f);
  std::fclose(f);
  std::rename(tmp.c_str(), path.c_str());
}

void ResetStepTraceForTest() {
  State& s = S();
  GlobalStepTraceGate().enabled.store(false, std::memory_order_relaxed);
  std::lock_guard<std::mutex> l(s.mu);
  s.ring.clear();
  s.fleet.clear();
  s.completed = 0;
  s.fleet_seen = 0;
  std::fill(s.fleet_phase_cum, s.fleet_phase_cum + kStepPhases, 0);
  s.fleet_last_step = -1;
  s.last = StepRec();
  s.dump_path.clear();
  s.cur_step.store(0, std::memory_order_relaxed);
  for (auto& a : s.cur_phase_us) a.store(0, std::memory_order_relaxed);
  s.cur_plane.store(-1, std::memory_order_relaxed);
}

}  // namespace hvdtpu
