// In-process multi-rank controller selftest: negotiation + ring data plane
// + join + clean shutdown, with every rank on its own thread.
//
// Reference analog (SURVEY.md §5 "race detection"): the reference's thread
// safety is by design (single background thread owns comm state) and
// validated under load; this harness makes that checkable mechanically —
// built plain it is a C++ integration test, built with -fsanitize=thread
// (`make tsan_selftest`) it is the race detector over the controller,
// socket, and duplex-exchange paths.  Run by tests/single/test_tsan.py.

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "metrics.h"
#include "socket_controller.h"

namespace hvdtpu {
int GetLogLevel() { return 4; }  // errors only
void SetLogLevel(int) {}
}  // namespace hvdtpu

using namespace hvdtpu;

namespace {

constexpr int kRanks = 3;
constexpr int kCycles = 25;

std::atomic<int> failures{0};

void Fail(const char* what, int rank) {
  std::fprintf(stderr, "FAIL rank %d: %s\n", rank, what);
  failures.fetch_add(1);
}

void RankMain(int rank, int port) {
  CoreConfig cfg;
  cfg.rank = rank;
  cfg.size = kRanks;
  cfg.rendezvous_addr = "127.0.0.1";
  cfg.rendezvous_port = port;
  SocketController ctl(cfg);
  Status s = ctl.Initialize();
  if (!s.ok()) return Fail(s.reason.c_str(), rank);

  for (int cycle = 0; cycle < kCycles; ++cycle) {
    TensorRequest req;
    req.name = "t" + std::to_string(cycle);
    req.op = OpType::ALLREDUCE;
    req.dtype = DataType::FLOAT32;
    req.nbytes = 1024 * 4;
    req.shape = {1024};
    std::vector<TensorRequest> reqs{req};
    std::vector<Response> resps;
    s = ctl.ComputeResponses(reqs, &resps);
    if (!s.ok()) return Fail(s.reason.c_str(), rank);
    for (auto& r : resps) {
      if (!r.error.empty()) return Fail(r.error.c_str(), rank);
      ctl.SetCurrentSeq(r.seq);
      std::vector<float> buf(1024, static_cast<float>(rank + 1));
      s = ctl.AllreduceBuffer(buf.data(), 1024, DataType::FLOAT32,
                              ReduceOp::SUM, 0);
      if (!s.ok()) return Fail(s.reason.c_str(), rank);
      if (buf[0] != 6.0f || buf[1023] != 6.0f) {
        return Fail("wrong allreduce result", rank);
      }
      s = ctl.Barrier(0);
      if (!s.ok()) return Fail(s.reason.c_str(), rank);
    }
    // Empty cycles interleave (the steady state of a real job).
    std::vector<TensorRequest> none;
    s = ctl.ComputeResponses(none, &resps);
    if (!s.ok()) return Fail(s.reason.c_str(), rank);
  }
  ctl.Farewell();
  ctl.Shutdown();
}

}  // namespace

int main() {
  // Pick a free port for the rendezvous.
  int port;
  {
    Listener probe;
    if (!probe.Listen("127.0.0.1", 0)) {
      std::fprintf(stderr, "no free port\n");
      return 2;
    }
    port = probe.port();
  }
  // Metrics stay ON for the whole run: the rank threads increment the
  // global registry (ring hops from ChunkedStep, shm fence waits from
  // SockBarrier's >= kTagShmSize tags) while a dumper thread concurrently
  // snapshots it — the increment-while-dump and fence-observe paths the
  // TSan build must prove race-free.  The registry is relaxed atomics end
  // to end, so zero reports is the designed outcome, not luck.
  GlobalMetrics().enabled.store(true, std::memory_order_relaxed);
  std::atomic<bool> stop_dumper{false};
  std::atomic<long long> dumps{0};
  std::thread dumper([&] {
    while (!stop_dumper.load(std::memory_order_relaxed)) {
      std::string json = GlobalMetrics().DumpJson(/*rank=*/0, "");
      if (json.empty() || json.front() != '{' || json.back() != '}' ||
          json.find("\"shm_fence_us\"") == std::string::npos) {
        Fail("malformed concurrent metrics dump", -1);
        return;
      }
      dumps.fetch_add(1, std::memory_order_relaxed);
    }
  });
  std::vector<std::thread> threads;
  threads.reserve(kRanks);
  for (int r = 0; r < kRanks; ++r) {
    threads.emplace_back(RankMain, r, port);
  }
  for (auto& t : threads) t.join();
  stop_dumper.store(true, std::memory_order_relaxed);
  dumper.join();
  if (dumps.load() == 0) Fail("dumper thread never completed a dump", -1);
  // The data plane must have observed latency somewhere: shm fences when
  // the same-host shm plane engaged, ring hops when it fell back to TCP.
  const auto observed =
      GlobalMetrics().shm_fence_us.count.load(std::memory_order_relaxed) +
      GlobalMetrics().ring_hop_us.count.load(std::memory_order_relaxed);
  if (observed == 0) Fail("metrics-enabled run observed no fence/hop", -1);
  if (failures.load() != 0) {
    std::printf("FAIL (%d)\n", failures.load());
    return 1;
  }
  std::printf("PASS\n");
  return 0;
}
