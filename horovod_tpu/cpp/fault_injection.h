// Deterministic in-process fault injection for the native control and data
// planes.  The reference project only exercised failure paths from the
// outside (killed workers, mutated discovery files; SURVEY.md §3.5) — this
// plane lets a test drop, truncate, corrupt, delay, or kill at a named
// protocol site on an exact hit index, so every abort path in
// socket_controller.cc is reachable on demand and bit-for-bit repeatable.
//
// Spec (HOROVOD_FAULT_INJECT): comma-separated `site:cycle:rank:action[:arg]`
//   site   = rendezvous-accept | coordinator-recv | ring-send | ring-recv |
//            shm-fence | frame-header | leader-recv | super-recv
//   cycle  = '*' (every matching hit) or a 0-based hit index at that
//            (site, rank) — one-shot, latched once fired
//   rank   = '*' or the acting rank (for coordinator-side sites: the REMOTE
//            peer rank the coordinator is serving)
//   action = drop | truncate | delay (arg = ms) | corrupt-tag |
//            die (arg = optional once-latch flag-file path; if the file
//            already exists the rule is skipped, so a respawned elastic
//            worker does not crash-loop)
//
// Hook sites are guarded by one relaxed bool load (FaultInjectionOn), the
// same zero-cost-when-disabled discipline as MetricsOn() in metrics.h.
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <string>

namespace hvdtpu {

enum FaultSite : int {
  kFaultRendezvousAccept = 0,
  kFaultCoordinatorRecv = 1,
  kFaultRingSend = 2,
  kFaultRingRecv = 3,
  kFaultShmFence = 4,
  kFaultFrameHeader = 5,
  // v9 leader tree: a host leader receiving a child's CYCLE frame.  The
  // rank field is the REMOTE child rank (mirror of coordinator-recv).
  kFaultLeaderRecv = 6,
  // v12 adaptive depth: a mid-level super-leader receiving a downstream
  // leader's [-3] aggregate frame.  The rank field is the REMOTE child
  // leader rank; the coordinator's own gathers keep coordinator-recv.
  kFaultSuperRecv = 7,
  kNumFaultSites = 8,
};

enum class FaultAction : int {
  kNone = 0,
  kDrop,        // close the site's socket
  kTruncate,    // partial write then close (caller implements the cut)
  kDelay,       // sleep arg ms (handled inside FaultCheck)
  kCorruptTag,  // flip frame-header tag bits (caller implements)
  kDie,         // _exit(137), optionally latched by a flag file
};

struct FaultRule {
  FaultSite site = kFaultRendezvousAccept;
  int cycle = -1;  // -1 = '*': every matching hit; else 0-based hit index
  int rank = -1;   // -1 = '*': any rank
  FaultAction action = FaultAction::kNone;
  long long arg = 0;    // delay: milliseconds
  std::string arg_str;  // die: once-latch flag-file path
  std::atomic<bool> fired{false};
};

struct FaultInjector {
  std::atomic<bool> enabled{false};
  // deque, not vector: FaultRule holds an atomic and cannot be copied or
  // moved, and deque::emplace_back constructs in place without relocation.
  std::deque<FaultRule> rules;
  // Per-(site, rank) hit counters; out-of-range ranks clamp into the edge
  // slots so counting never writes out of bounds.
  static constexpr int kMaxTrackedRanks = 64;
  std::atomic<int64_t> hits[kNumFaultSites][kMaxTrackedRanks] = {};
};

FaultInjector& GlobalFaultInjector();

inline bool FaultInjectionOn() {
  return GlobalFaultInjector().enabled.load(std::memory_order_relaxed);
}

const char* FaultSiteName(FaultSite site);

// Parses `spec` into `rules` (append; may be null for validate-only).
// Returns "" on success or an actionable one-line error naming the bad
// entry and the valid vocabulary.
std::string ParseFaultSpec(const std::string& spec,
                           std::deque<FaultRule>* rules);

// Reads HOROVOD_FAULT_INJECT; empty/unset leaves injection disabled.
// Resets any rules from a previous init in this process (elastic re-init)
// so hit indices stay deterministic.  Returns "" or the parse error.
std::string InitFaultInjection();

// Records a hit at `site` for `rank` and returns the action the caller must
// apply (kNone, kDrop, kTruncate, kCorruptTag).  kDelay sleeps internally
// and kDie exits the process, so callers only need to handle the three
// socket-level actions; `arg` (when non-null) receives the rule's numeric
// argument.  Call only under FaultInjectionOn().
FaultAction FaultCheck(FaultSite site, int rank, long long* arg = nullptr);

}  // namespace hvdtpu
