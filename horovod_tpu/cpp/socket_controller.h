// Multi-process controller: rank-0 coordinator negotiation over TCP plus a
// full-mesh worker data plane running ring/tree algorithms.
//
// Reference analogs (SURVEY.md §2.1, §2.8, §3.2): controller.cc
// Controller::ComputeResponseList (rank-0 request intersection), gloo/
// (MPI-free CPU transport + rendezvous + full-mesh TCP pairs + ring
// collectives), response_cache.cc (bit-vector steady state),
// stall_inspector.cc (per-rank missing lists).
//
// Negotiation protocol (per cycle, lock-step, coordinator-rooted):
//   worker -> coord : CYCLE frame = [n_cached, cached_ids...,
//                                    n_requests, full requests...]
//   coord  -> worker: RESPONSES frame = [n, responses...]
// A tensor becomes ready when every rank of its process set has announced
// it; readiness order is deterministic, so the fused response list is
// byte-identical on every rank — which is what lets the TPU device path
// dispatch one cached fused XLA program per response with no further
// coordination.
//
// Data plane: every pair of ranks holds a TCP connection (established at
// Initialize via a coordinator-brokered address book — the Gloo full-mesh
// analog).  Collectives run *on the calling executor thread* of each
// member, in the globally negotiated order: ring allreduce (reduce-scatter
// + allgather phases, bandwidth-optimal O(bytes) per rank instead of the
// round-1 coordinator star's O(size*bytes) rank-0 ingress), ring
// allgather, binomial-tree broadcast, pairwise alltoall, dissemination
// barrier.  Host arrays only — the TPU path never touches these sockets.
#pragma once

#include <atomic>
#include <condition_variable>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <thread>
#include <utility>
#include <vector>

#include "common.h"
#include "controller.h"
#include "fleet_telemetry.h"
#include "flight_recorder.h"
#include "metrics.h"
#include "response_cache.h"
#include "shm_plane.h"
#include "socketio.h"
#include "wire_codec.h"

namespace hvdtpu {

class SocketController : public Controller {
 public:
  explicit SocketController(const CoreConfig& cfg);
  ~SocketController() override;

  Status Initialize() override;
  void Shutdown() override;
  void Farewell() override;
  // True when the peer ended the session deliberately (clean shutdown).
  bool peer_shutdown() const { return peer_shutdown_; }

  Status ComputeResponses(std::vector<TensorRequest>& new_requests,
                          std::vector<Response>* out) override;

  Status AllreduceBuffer(void* buf, int64_t count, DataType dtype, ReduceOp op,
                         int process_set_id) override;
  Status ReduceScatterBuffer(void* buf, int64_t count, DataType dtype,
                             ReduceOp op,
                             const std::vector<int64_t>& slice_counts,
                             int process_set_id) override;
  Status AllgatherBuffer(const void* in, int64_t nbytes, int process_set_id,
                         std::string* out,
                         std::vector<int64_t>* nbytes_per_rank) override;
  Status BroadcastBuffer(void* buf, int64_t nbytes, int root_rank,
                         int process_set_id) override;
  Status AlltoallBuffer(const void* in, const std::vector<int64_t>& splits,
                        int64_t row_bytes, int process_set_id,
                        std::string* out,
                        std::vector<int64_t>* recv_splits) override;
  Status Barrier(int process_set_id) override;

  std::string StallReport(double older_than_s) override;

  // Abort-reason plumbing (fast-abort propagation, protocol v8): the first
  // ABORT observed (coordinator broadcast or locally detected peer death)
  // latches a reason naming the culprit; WaitAbortReason blocks — bounded
  // by HOROVOD_ABORT_PROPAGATION_TIMEOUT, charged only once across stacked
  // waiters — so an executor whose own exchange failed FIRST still reports
  // the coordinator's culprit attribution instead of a bare socket error.
  std::string WaitAbortReason() override;
  std::string AbortReason();

  // Per-process-set data channels (the NCCL-communicator analog): a
  // dedicated socket mesh among the set's members, so collectives on
  // different process sets can run on CONCURRENT executor lanes without
  // interleaving frames on shared sockets.  Called from add_process_set
  // on every rank (symmetric registration is already the contract);
  // non-members return immediately.
  Status EstablishChannel(int psid) override;
  void RemoveChannel(int psid) override;

  // The executor lane calls this before each data-plane op to tag frames.
  // thread_local: each lane thread tags its own collective's frames.
  void SetCurrentSeq(int64_t seq) { current_seq_ = seq; }

  void NegotiationStats(int64_t* sent, int64_t* recv) const override {
    *sent = ctrl_sent_.load(std::memory_order_relaxed);
    *recv = ctrl_recv_.load(std::memory_order_relaxed);
  }

  // Ctrl-plane frame + byte counters (protocol v9).  On the coordinator the
  // msgs_recv rate per cycle is the leader-tree acceptance metric: flat mode
  // receives size-1 frames per cycle, tree mode local_children + hosts-1.
  void CtrlPlaneStats(int64_t* msgs_sent, int64_t* msgs_recv,
                      int64_t* bytes_sent, int64_t* bytes_recv) const override {
    *msgs_sent = ctrl_msgs_sent_.load(std::memory_order_relaxed);
    *msgs_recv = ctrl_msgs_recv_.load(std::memory_order_relaxed);
    *bytes_sent = ctrl_sent_.load(std::memory_order_relaxed);
    *bytes_recv = ctrl_recv_.load(std::memory_order_relaxed);
  }

  // Autotuned categorical knob: announce steady-state tensors via cache
  // ids (default) or as full requests.  Per-rank safe — inserts stay
  // deterministic either way, so cache ids never diverge across ranks.
  void SetAnnounceCache(bool v) {
    announce_cache_.store(v, std::memory_order_relaxed);
  }

  // Hierarchical allreduce knob (HOROVOD_HIERARCHICAL_ALLREDUCE / the
  // autotuner's second categorical).  Only the COORDINATOR's value feeds
  // the per-response hier bit, so per-rank divergence (autotune runs on
  // every rank) cannot split the plane.
  void SetHierarchical(bool v) {
    hierarchical_.store(v, std::memory_order_relaxed);
  }
  // True when the global process set can run the hierarchical composition
  // (>=2 hosts, >=1 host with co-located ranks, per-host shm agreed up).
  // core_api uses this to decide whether the autotuner should explore the
  // hierarchical coordinate at all.
  bool HierAvailable() { return HierFor(0) != nullptr; }

  // Wire-compression knob (HOROVOD_WIRE_COMPRESSION / the autotuner's
  // third categorical; 0=none, 1=bf16, 2=int8).  Like SetHierarchical,
  // only the COORDINATOR's value feeds the per-response wire_comp field.
  void SetWireCompression(int v) {
    wire_compression_.store(v, std::memory_order_relaxed);
  }
  // True when the global process set has a ring whose every hop crosses
  // hosts (the hier leader ring, or a flat ring with one rank per host
  // and no shm plane) — i.e. compression could ever engage.  core_api
  // uses this to pin the autotune coordinate, same rule as HierAvailable.
  bool WireCompAvailable();

  // Data-plane payload bytes sent, split by whether the destination rank
  // lives on this host (the hierarchical win is the xhost line dropping
  // to ~2N per host).  `raw_*` count the fp32-equivalent payload of the
  // same sends: wire < raw exactly when compression engaged, and
  // raw/wire is the measured compression ratio (docs/compression.md).
  void DataPlaneStats(int64_t* local, int64_t* xhost, int64_t* raw_local,
                      int64_t* raw_xhost) const {
    *local = data_sent_local_.load(std::memory_order_relaxed);
    *xhost = data_sent_xhost_.load(std::memory_order_relaxed);
    *raw_local = data_raw_local_.load(std::memory_order_relaxed);
    *raw_xhost = data_raw_xhost_.load(std::memory_order_relaxed);
  }

  // Coordinator-only JSON fragment for hvd_metrics_dump: the per-rank
  // cluster view built from the snapshots each worker piggybacks on its
  // CYCLE frame (protocol v7), plus the latest straggler attribution
  // report, plus the v11 fleet histogram view.  Workers return "".
  std::string ClusterMetricsJson();

  // Coordinator-only: distinct fleet-sketch sources currently stored (the
  // ctrl soak's tree+sketch arm asserts this equals its direct sources —
  // local children plus aggregate children — proving the tree kept
  // coordinator inbound O(fanout) at any depth).
  int FleetSourceCountForTest();
  // Coordinator-only: total negotiation-wait observations in the live
  // fleet sum (own capture + every stored source).  The in-process soak's
  // merge oracle: all np threads snapshot the SAME global registry, so the
  // fleet sum can never exceed np x the registry's own count unless a
  // subtree sketch was double-merged somewhere up the tree.
  int64_t FleetSumNegCountForTest();

  // Fleet-autopilot policy channel (coordinator only, armed by
  // cfg_.autopilot_port > 0): a driver-facing JSON-lines endpoint serving
  // the live straggler view ({"cmd":"poll"}) and accepting decision
  // records ({"cmd":"decision",...}) that land in the flight recorder,
  // the metrics registry, and — via the hook — the timeline.  The hook
  // is installed once at init (core_api), before the serve thread exists.
  void SetAutopilotDecisionHook(
      std::function<void(int action, int rank, const std::string& detail)>
          hook) {
    autopilot_hook_ = std::move(hook);
  }

 private:
  // Compact per-rank metrics snapshot, piggybacked worker->coordinator on
  // every CYCLE frame (protocol v7) and refreshed for rank 0 locally.
  // All values are cumulative since init; the straggler check differences
  // them per report window.
  struct RankMetricsSnapshot {
    int64_t neg_count = 0;
    int64_t neg_sum_us = 0;
    int64_t neg_p50_us = 0;
    int64_t neg_p99_us = 0;
    int64_t cycle_busy_us = 0;
    int64_t cycle_idle_us = 0;
    int64_t cycle_count = 0;
    double updated_at = 0;
  };
  // Coordinator-side straggler attribution: per-rank announce lag = how
  // long after a tensor's FIRST announcement this rank's own announcement
  // arrived (the rank consistently announcing last IS the straggler —
  // every other rank's negotiation wait measures the victim side, not the
  // culprit).  Checked every metrics_report_s_; ranks whose mean window
  // lag exceeds max(straggler_skew_ x fleet median, straggler_min_us_)
  // are named with host, p50/p99 and the fleet median.
  void RecordAnnounceLag(int rank, double lag_s);
  void MaybeStragglerReport(double now);
  void FillSelfSnapshot(double now);

  // -- fleet-autopilot policy channel (coordinator only) --------------------
  // Accept loop + per-connection JSON-lines service on policy_listener_;
  // runs on its own thread (started by Initialize when armed) so policy
  // polls never touch the negotiation cycle.
  void PolicyServeLoop();
  // {"v":1,"windows":N,"culprits":[...],"report":"...","size":S} under
  // metrics_mu_ — the driver-side engine diffs `windows` to count
  // consecutive flagged report windows per rank.
  std::string PolicyStatusJson();
  // Record one driver decision: flight event (kFlightAutopilot), metrics
  // counter, timeline instant via the hook, and an immediate flight dump
  // so the record survives the eviction teardown that usually follows.
  void RecordAutopilotDecision(int action, int rank,
                               const std::string& detail);

  std::mutex metrics_mu_;  // guards cluster_ + straggler_report_ (the
                           // background thread writes, hvd_metrics_dump
                           // reads from the Python thread)
  std::vector<RankMetricsSnapshot> cluster_;           // coordinator, by rank
  std::vector<std::unique_ptr<Histogram>> announce_lag_;  // coordinator
  // Cumulative (count, sum_us) per rank at the last report, for deltas.
  std::vector<std::pair<int64_t, int64_t>> announce_prev_;
  std::string straggler_report_;
  // Autopilot view of the straggler check (guarded by metrics_mu_ like
  // straggler_report_): total report windows evaluated so far and the
  // ranks flagged in the LAST window.  The driver-side policy engine
  // diffs `straggler_windows_` between polls to count consecutive flagged
  // windows without double-counting a window it already saw.
  int64_t straggler_windows_ = 0;
  std::vector<int> straggler_ranks_;
  double last_metrics_report_ = 0;
  // HOROVOD_METRICS_REPORT_SECONDS / HOROVOD_STRAGGLER_SKEW /
  // HOROVOD_STRAGGLER_MIN_MS (ctor reads the env, like ring_chunk_bytes_).
  double metrics_report_s_ = 30.0;
  double straggler_skew_ = 3.0;
  double straggler_min_us_ = 5000.0;

  // Negotiation ctrl-channel payload byte counters (background thread
  // writes, Python reads — relaxed atomics suffice for monotone counters).
  std::atomic<int64_t> ctrl_sent_{0};
  std::atomic<int64_t> ctrl_recv_{0};
  // Ctrl-channel frame counters (protocol v9): one increment per CYCLE /
  // RESPONSES / aggregate / abort frame moved on a negotiation link.
  std::atomic<int64_t> ctrl_msgs_sent_{0};
  std::atomic<int64_t> ctrl_msgs_recv_{0};
  // Data-plane payload byte counters keyed by destination host locality:
  // `data_sent_*` are bytes on the wire, `data_raw_*` the fp32-equivalent
  // payload (equal unless a compressed ring encoded the send).
  std::atomic<int64_t> data_sent_local_{0};
  std::atomic<int64_t> data_sent_xhost_{0};
  std::atomic<int64_t> data_raw_local_{0};
  std::atomic<int64_t> data_raw_xhost_{0};
  std::atomic<bool> announce_cache_{true};
  std::atomic<bool> hierarchical_{false};
  // Requested wire codec (WireCodec as int); the coordinator demotes
  // per-response where it cannot apply (see UpdateCachesAndSeq).
  std::atomic<int> wire_compression_{0};
  struct Pending {
    TensorRequest meta;
    std::set<int> announced;
    int64_t order = 0;      // arrival order at coordinator (determinism)
    double first_seen = 0;  // stall inspection
  };

  // -- negotiation ----------------------------------------------------------
  Status CoordinatorCycle(std::vector<TensorRequest>& new_requests,
                          std::vector<Response>* out);
  Status WorkerCycle(std::vector<TensorRequest>& new_requests,
                     std::vector<Response>* out);

  // -- leader-tree control plane (protocol v9; n-level since v12) -----------
  // Tree over the agreed host keys: the first rank of each host
  // (first-appearance order over rank order — the same election
  // MaybeSetupHier uses) is that host's leader.  Children exchange CYCLE /
  // RESPONSES frames with their leader; leaders merge child announcements
  // into ONE aggregate frame per host toward their parent and fan the
  // responses (and abort broadcasts) back down verbatim.  Protocol v12
  // generalizes the upper level: when the host-leader count exceeds
  // HOROVOD_CTRL_TREE_FANOUT, consecutive leaders are clustered under
  // mid-level "super-leaders" (the lowest rank of each cluster) that merge
  // their child leaders' [-3] aggregates into one frame upward, recursively,
  // until the coordinator's fan-in is <= fanout.  Rank 0 is always both the
  // coordinator and its own host's leader, so its host's children keep
  // their direct rendezvous ctrl sockets.  The engagement decision AND the
  // fanout/depth knobs are COORDINATOR-AUTHORITATIVE: they ride the
  // rendezvous book, so divergent HOROVOD_CONTROL_TREE* envs cannot split
  // the ring.
  struct CtrlTree {
    bool on = false;
    std::vector<int> leaders;      // per-host leader ranks (ascending)
    int my_leader = -1;            // leader of this rank's host
    std::vector<int> my_children;  // leader only: this host's other ranks
    // v12 adaptive depth.  parent_of maps every non-root LEADER node (host
    // leaders and super-leaders) to the rank its aggregate flows to (0 =
    // straight to the coordinator); identical on all ranks, so subtree
    // membership and ancestor chains are computable anywhere.  Workers'
    // negotiation parent stays my_leader.
    std::map<int, int> parent_of;
    int parent = -1;                // leader only: parent_of[rank]
    std::vector<int> agg_children;  // downstream leader ranks whose [-3]
                                    // aggregates THIS node gathers + merges
    int depth = 2;  // tree levels: coordinator=1, +1 per aggregation layer
  };
  // Engagement rule, pure function of the mode string + agreed host keys
  // (mirrored by runtime.compute_ctrl_tree for the Python-side unit tests):
  // "on" engages with >=2 hosts, "auto" additionally requires size >= 8,
  // single-host jobs always demote to the flat plane.
  static bool DecideCtrlTree(const std::string& mode,
                             const std::vector<std::string>& host_keys);
  // Build tree_ from host_keys_ (after the book agreed) per the decision,
  // clustering host leaders under super-leaders until every node's fan-in
  // is <= ctrl_tree_fanout_ (or exactly ctrl_tree_depth_ levels deep when
  // the override is set).  Pure function of (host_keys_, fanout, depth) so
  // every rank computes the identical topology.
  void ComputeCtrlTree(bool on);
  // All ranks whose aggregation path runs through `rank`: the rank itself,
  // its host's workers when it is a host leader, and recursively every
  // clustered leader below it.  {rank} for a plain worker.
  std::vector<int> SubtreeOf(int rank) const;
  // Coordinator, protocol v12: a departing leader's BYE releases its whole
  // subtree (v9 released only the leader's host).
  void DepartSubtree(int rank);
  // The chain of leader ranks relaying for `rank`, nearest first, stopping
  // before the coordinator: host leader, then each super-leader above it.
  // Empty for rank 0 and for direct children of the coordinator's host.
  std::vector<int> AncestorChain(int rank) const;
  // Establish the child->leader ctrl links: children of non-coordinator
  // hosts dial their leader's data listener with a kCtrlTreePsid HELLO
  // (the mesh pending-stash absorbs arrival skew, like channel HELLOs).
  Status SetupCtrlTreeLinks();
  bool IsTreeLeader() const {
    return tree_.on && tree_.my_leader == cfg_.rank;
  }
  // The ctrl socket toward this rank's negotiation parent: tree_parent_
  // when the parent is a non-coordinator node (a non-host-0 child's leader,
  // or a v12 leader's super-leader), the coordinator link otherwise.
  Socket& UpLink();
  // Leader's link to child `rank` (the coordinator's local children live
  // in ctrl_socks_); null when unknown/closed.
  Socket* TreeChildSock(int rank);
  // One leader negotiation cycle: gather every live child's frame (fault
  // site: leader-recv), merge cached announcements across the host, forward
  // one aggregate frame, fan the response back down, parse own copy.
  Status LeaderCycle(std::vector<TensorRequest>& new_requests,
                     std::vector<Response>* out);
  // The worker CYCLE frame body: cached pairs + full requests + v7 metrics
  // trailer (shared by WorkerCycle and the leader's own sub-frame).
  std::string BuildCycleFrame(const std::vector<TensorRequest>& new_requests);
  // Shared RESPONSES-frame tail parse (n already read, >= 0).
  void ParseResponsesTail(Reader* rd, int32_t n, std::vector<Response>* out);
  // Forward a responses-position frame verbatim to every live child;
  // returns false and names the child when a send fails (cycle path aborts
  // on that; abort/farewell fan-outs are best-effort and ignore it).
  bool FanDownToChildren(const std::string& frame, int* failed_child);
  // Leader failure path: send a FIN upward naming `culprit` (or forward a
  // child's own FIN frame verbatim) and await the coordinator's ABORT.
  Status LeaderFinUp(int culprit, const std::string& why,
                     const std::string* forward_frame);
  // Coordinator parse helpers, shared by the flat per-rank loop and the
  // per-subframe body of a leader aggregate.
  void ParseCachedPairs(int rank, int32_t n_cached, Reader* rd,
                        std::vector<Response>* errors);
  void ParseFullAndMetrics(int rank, int32_t n_full, Reader* rd,
                           std::vector<Response>* errors);
  // Parse a leader's [-3] aggregate frame; false = malformed (caller aborts
  // blaming the leader).
  bool ParseAggregate(int leader, Reader* rd, std::vector<Response>* errors);

  // -- fleet telemetry (protocol v11; fleet_telemetry.h) --------------------
  // Read the length-prefixed sketch section at the reader's cursor and
  // store it as `rank`'s cumulative sketch.  A malformed sketch is dropped
  // (never the frame); an empty section (sender's plane off) is a no-op.
  void ReadFleetSketch(int rank, Reader* rd);
  // Replace a source's last-known cumulative sketch (coordinator side).
  void StoreFleetSource(int rank, FleetSketch&& s);
  // The coordinator's live fleet view: its own registry capture plus every
  // stored source sketch.  Bucket-exact vs an offline merge of per-rank
  // dumps because each source's sketch is cumulative and replaced, never
  // added twice.
  FleetSketch FleetSum();
  // Leader lost its coordinator link: synthesize the ABORT the coordinator
  // can no longer deliver and fan it down so the subtree fails bounded.
  Status LeaderLostCoordinator(const std::string& what);
  // Ctrl-plane accounting: one frame of `bytes` moved on a negotiation
  // link (controller counters + the global metrics registry when enabled).
  void CountCtrlSend(int64_t bytes);
  void CountCtrlRecv(int64_t bytes);

  // Coordinator: last-known cumulative sketch per direct source (a worker
  // rank in flat mode; a local child or a remote leader's host sum in tree
  // mode).  Guarded by fleet_mu_: the background thread replaces entries,
  // hvd_metrics_dump sums them from the Python thread.
  std::mutex fleet_mu_;
  std::map<int, FleetSketch> fleet_sources_;
  // Leader only (background thread): last-known sketch per host member —
  // its own included — summed into the aggregate frame's sketch section.
  // Entries survive a child's BYE (which carries the child's FINAL sketch)
  // so the host sum stays exact after departures.
  std::map<int, FleetSketch> tree_child_sketches_;
  // Sender-side sketch throttles (kFleetEncodeIntervalS): a worker's
  // cycle-frame section and a leader's aggregate host sum each re-encode
  // at most once per interval; in-between frames carry an empty section.
  double fleet_last_encode_ = 0;
  double fleet_leader_last_encode_ = 0;
  // Coordinator-side fleet tick limiter (the sum is cheap but per-cycle
  // would still be 1000x more often than the 1 Hz history wants).
  double last_fleet_tick_ = 0;

  CtrlTree tree_;
  // Leader (non-coordinator): accepted child ctrl links, by child rank.
  std::map<int, Socket> tree_child_socks_;
  // Children that sent a clean BYE (leader-side mirror of departed_ranks_).
  std::set<int> tree_departed_children_;
  // The ctrl link to this rank's negotiation parent when that parent is not
  // the coordinator: a non-host-0 child's link to its host leader, or (v12)
  // a leader's link to its super-leader.
  Socket tree_parent_;
  // HOROVOD_CONTROL_TREE (auto|on|off) and HOROVOD_RENDEZVOUS_ACCEPTORS
  // (ctor reads the env; the coordinator's mode decides for everyone).
  std::string control_tree_mode_ = "auto";
  int rendezvous_acceptors_ = 4;
  // HOROVOD_CTRL_TREE_FANOUT (default 32, min 2): the per-node fan-in bound
  // the adaptive-depth pass targets.  HOROVOD_CONTROL_TREE_DEPTH (0 = auto):
  // force the tree to exactly this many levels (2 = the v9 flat-leader
  // shape) regardless of the fanout bound.  Both are coordinator-
  // authoritative — the agreed values ride the v12 rendezvous book.
  int ctrl_tree_fanout_ = 32;
  int ctrl_tree_depth_ = 0;

  // -- fast-abort propagation (protocol v8) ---------------------------------
  // Coordinator: broadcast ABORT(reason, culprit rank/host) on every live
  // ctrl socket (best-effort), latch the reason, and return the ABORTED
  // status every caller of the failed cycle sees.  Idempotent: only the
  // first call broadcasts.
  Status BroadcastAbortAndFail(int culprit_rank, const std::string& why);
  // First-writer-wins reason latch + wakeup for WaitAbortReason.
  void SetAbortReason(const std::string& reason);
  // Entry path when the executor observed a local data-plane failure
  // before the control plane did (aborted_ set, ComputeResponses called):
  // workers send a best-effort failure FIN and await the coordinator's
  // ABORT; the coordinator sweeps ctrl sockets for the culprit and
  // broadcasts.  Both are bounded by abort_timeout_s_.
  Status WorkerAbortHandshake();
  Status CoordinatorAbortSweep();
  // Parse the body of a [-2][kTagAbort]... frame (worker side): latches
  // the reason, observes propagation latency, returns the ABORTED status.
  Status HandleAbortFrame(Reader* rd);
  // -- abort-time forensics (flight recorder; flight_recorder.h) ------------
  // Worker: one [-4][kTagFlightDigest] frame carrying this rank's last-N
  // flight events up `sock` (the coordinator link, or the tree parent for
  // non-host-0 children — leaders forward child digests verbatim).  Sent
  // at most once (digest_sent_), right after a FIN or on ABORT receipt, so
  // forensics rides the existing abort exchange and never delays it.
  void SendFlightDigest(Socket& sock);
  // Coordinator: parse a digest frame body (tag already consumed) into
  // flight_digests_; false = malformed (frame is dropped, never fatal).
  bool StashFlightDigest(Reader* rd);
  // Coordinator: after broadcasting ABORT, poll live ctrl sockets for
  // digest frames until `deadline` (monotonic seconds) or every live rank
  // reported — bounded by the abort-propagation budget.
  void CollectFlightDigests(double deadline);
  // Leader: briefly poll child ctrl links and forward any [-4] digest
  // frames verbatim up the coordinator link (children of non-host-0
  // leaders have no direct path for their digests).  Best-effort and
  // bounded well inside the abort budget.
  void ForwardChildDigests();
  // Coordinator: merge own buffer + collected digests into
  // <postmortem_dir>/postmortem.json naming the culprit and the causal
  // event sequence.  No-op when HOROVOD_POSTMORTEM_DIR is unset.
  void WritePostmortem(int culprit_rank, const std::string& culprit_host,
                       const std::string& why);
  void Announce(int rank, TensorRequest req, std::vector<Response>* errors);
  void UpdateCachesAndSeq(std::vector<Response>* responses);

  // -- data plane (full mesh, caller-thread algorithms) ---------------------
  // Resolve a process set into its sorted member ranks + this rank's index.
  Status Members(int psid, std::vector<int>* members, int* my_idx) const;
  // One collective step: send `frame` to rank `send_to` while receiving a
  // frame from rank `recv_from` (deadlock-free duplex) over the given
  // channel's sockets.
  Status ExchangeStep(std::vector<Socket>& socks, int send_to,
                      const std::string& frame, int recv_from,
                      std::string* in);
  // Chunk-pipelined ring step (Gloo segmented-ring analog): payload flows
  // directly between the user buffer and the wire in `chunk_bytes` pieces,
  // `consume` runs per completed chunk (overlapping reduce with transfer),
  // and `recv_dest` receives the incoming segment in place.  Headers carry
  // the same [seq|tag] as ExchangeStep frames; mismatches abort the job.
  // `raw_len` is the fp32-equivalent payload size for byte accounting
  // (compressed rings send fewer wire bytes than they represent);
  // -1 means raw == wire (the uncompressed default).
  Status ChunkedStep(
      std::vector<Socket>& socks, int send_to, const char* send_base,
      int64_t send_len, int recv_from, int64_t recv_len, char* recv_dest,
      int32_t tag, int64_t chunk_bytes,
      const std::function<void(int64_t off, const char* data, int64_t len)>&
          consume,
      int64_t raw_len = -1);
  // Frame helpers: every data frame is [i64 seq][i32 tag][raw payload];
  // seq/tag mismatches mean the mesh desynced and abort the job.
  // Non-static: the frame-header fault-injection hook needs cfg_.rank.
  void PutFrameHeader(Writer* w, int64_t seq, int32_t tag);
  Status CheckFrameHeader(Reader* rd, int32_t tag, const char* what);

  Status RingAllreduce(std::vector<Socket>& socks, void* buf, int64_t count,
                       DataType dtype, ReduceOp op,
                       const std::vector<int>& members, int idx);
  // Ring allreduce with the payload wire-encoded on every hop (fp32
  // tensors only; docs/compression.md).  Reduce-scatter hops decode each
  // incoming chunk and ACCUMULATE IN FP32 (one quantization of error per
  // hop, never compounding re-quantization of partial sums); the
  // allgather phase encodes each finished segment once at its owner and
  // forwards those bytes verbatim, so every member decodes the identical
  // stream and results stay bit-identical across ranks.
  Status CompressedRingAllreduce(std::vector<Socket>& socks, void* buf,
                                 int64_t count, ReduceOp op,
                                 const std::vector<int>& members, int idx,
                                 WireCodec codec);
  // True when every adjacent hop of the flat ring over `members` crosses
  // hosts (one rank per host), i.e. a flat compressed ring never wastes
  // codec work on a same-host link.
  bool RingAllCrossHost(const std::vector<int>& members) const;
  // Shared pipelined ring reduce phase (m-1 hops, in-flight reduction
  // with partial-element carry): segment boundaries come from `offs`
  // (m+1 element offsets into buf), the schedule runs in `vidx` index
  // space (rank ends owning segment (vidx+1)%m), frames are tagged
  // tag_base+step.  Used by RingAllreduce phase 1 (equal split,
  // vidx=idx) and ReduceScatterBuffer (caller slices, vidx=idx-1).
  Status PipelinedReducePhase(std::vector<Socket>& socks,
                              const std::vector<int>& members, int idx,
                              int vidx, char* base,
                              const std::vector<int64_t>& offs,
                              DataType dtype, ReduceOp op, int32_t tag_base,
                              int64_t chunkb);
  // Build a socket mesh among `members` with HELLOs tagged by `psid`
  // (lower member dials, higher accepts); init uses psid 0 over all ranks.
  Status ConnectMesh(const std::vector<int>& members, int psid,
                     std::vector<Socket>* out);
  // The socket vector for a process set's data ops: the per-set channel
  // if one exists, the global full mesh otherwise.
  std::vector<Socket>& SocksFor(int psid);

  // -- shared-memory plane (same-host members; shm_plane.h) -----------------
  // Dissemination barrier over a channel's sockets with a distinct tag
  // base (the public Barrier() and the shm phase fences share this).
  Status SockBarrier(std::vector<Socket>& socks,
                     const std::vector<int>& members, int idx,
                     int32_t tag_base);
  bool MembersAllLocal(const std::vector<int>& members) const;
  // Open the set's shm region when all members share this host; the
  // open verdict is agreed across members (any failure -> everyone
  // falls back to the TCP ring).
  Status MaybeOpenShm(int psid, const std::vector<int>& members);
  ShmRegion* ShmFor(int psid);
  Status ShmAllreduce(ShmRegion& shm, std::vector<Socket>& socks,
                      const std::vector<int>& members, int idx, void* buf,
                      int64_t count, DataType dtype, ReduceOp op);
  Status ShmBroadcast(ShmRegion& shm, std::vector<Socket>& socks,
                      const std::vector<int>& members, int idx, int root_idx,
                      void* buf, int64_t nbytes);
  Status ShmAllgather(ShmRegion& shm, std::vector<Socket>& socks,
                      const std::vector<int>& members, int idx,
                      const void* in, int64_t nbytes, std::string* out,
                      std::vector<int64_t>* per_rank);
  Status ShmAlltoall(ShmRegion& shm, std::vector<Socket>& socks,
                     const std::vector<int>& members, int idx, const void* in,
                     const std::vector<int64_t>& splits, int64_t row_bytes,
                     std::string* out, std::vector<int64_t>* recv_splits);

  // -- hierarchical allreduce (shm-local reduce -> leader ring -> shm
  //    broadcast; see docs/hierarchical.md) ----------------------------------
  // Per-process-set hierarchical topology, derived from the agreed host
  // keys at Initialize/EstablishChannel time.  `ok` is a whole-set agreed
  // verdict (like the shm plane's): either every member holds a working
  // topology or nobody uses it.
  struct HierTopo {
    std::vector<int> local;    // my host's members (sorted global ranks)
    int local_idx = -1;        // my index in `local`
    std::vector<int> leaders;  // per-host leader ranks (ascending)
    int leader_idx = -1;       // my index in `leaders`, -1 if non-leader
    std::unique_ptr<ShmRegion> shm;  // host subgroup region (null if alone)
  };
  // The rank's agreed per-rank host identity (index i = rank i).  Filled
  // from the rendezvous book so every rank sees the same grouping — the
  // coordinator's mesh_addrs_ view differs from workers' and cannot be
  // used for this.
  static std::string HostKey(int rank, int size);
  // Build (or agree to skip) the hierarchical topology for a set.  Always
  // runs a whole-set handshake when the topology LOOKS applicable so a
  // per-rank failure (shm open, HOROVOD_SHM_DISABLE on one worker) demotes
  // every member together.
  Status MaybeSetupHier(int psid, const std::vector<int>& members);
  HierTopo* HierFor(int psid);
  Status HierAllreduce(HierTopo& topo, std::vector<Socket>& socks, void* buf,
                       int64_t count, DataType dtype, ReduceOp op,
                       WireCodec codec);
  // Record bytes pushed to rank `to` on the data plane (local vs x-host).
  // `raw_bytes` is the fp32-equivalent payload; the 2-arg form means
  // raw == wire (no compression on this send).
  void CountSend(int to, int64_t nbytes) { CountSend(to, nbytes, nbytes); }
  void CountSend(int to, int64_t wire_bytes, int64_t raw_bytes);

  // -- wiring ---------------------------------------------------------------
  bool is_coordinator() const { return cfg_.rank == 0; }

  // HOROVOD_RING_CHUNK_BYTES: ring-hop pipelining granularity (0 = legacy
  // whole-segment frames).  512 KiB measured best on the loopback sweep
  // (128k/256k/512k x socket-buffer sizes); the ctor only overrides this
  // from the env.
  int64_t ring_chunk_bytes_ = 1 << 19;

  // HOROVOD_WIRE_COMPRESSION_MIN_BYTES: responses whose fp32 payload is
  // below this stay raw — codec overhead beats the byte savings on tiny
  // tensors, and the autotuner's fused buckets clear it trivially.
  int64_t wire_comp_floor_ = 1 << 16;

  Listener listener_;       // coordinator: rendezvous/ctrl accept
  Listener data_listener_;  // every rank: mesh peer accept (ephemeral port)
  // Fleet autopilot (coordinator, cfg_.autopilot_port > 0): the driver-
  // facing policy listener and its serve thread.  policy_stop_ is the
  // thread's shutdown latch; the hook forwards decisions to the timeline.
  Listener policy_listener_;
  std::thread policy_thread_;
  std::atomic<bool> policy_stop_{false};
  std::function<void(int, int, const std::string&)> autopilot_hook_;
  // coordinator: per-worker ctrl sockets (index = rank, [0] unused)
  std::vector<Socket> ctrl_socks_;
  // worker: ctrl connection to the coordinator
  Socket coord_ctrl_;
  // full mesh: peer_socks_[r] is the data connection to rank r ([rank] unused)
  std::vector<Socket> peer_socks_;
  // mesh address book from Initialize, kept for later channel dials
  std::vector<std::string> mesh_addrs_;
  std::vector<int> mesh_ports_;
  // agreed per-rank host keys (rendezvous book, protocol v5): the ONLY
  // valid locality signal — mesh_addrs_[0] differs between coordinator
  // ("") and workers (the rendezvous address), so address-based host
  // grouping would diverge across ranks.
  std::vector<std::string> host_keys_;
  // psid -> hierarchical topology (only sets where it is applicable+agreed)
  std::map<int, HierTopo> hier_;
  // Per-seq coordinator plane decisions (the response's hier bit + wire
  // codec), recorded from each cycle's responses and consumed by
  // AllreduceBuffer (lanes are concurrent -> mutex).
  struct PlaneChoice {
    bool hier = false;
    WireCodec wire = WireCodec::kNone;
  };
  std::map<int64_t, PlaneChoice> plane_by_seq_;
  std::mutex hier_mu_;
  // psid -> per-set socket mesh (indexed by GLOBAL rank, like peer_socks_)
  std::map<int, std::vector<Socket>> channel_socks_;
  // psid -> shared-memory region (same-host member sets only)
  std::map<int, std::unique_ptr<ShmRegion>> shm_;
  // HELLOs that arrived for a channel this rank has not started
  // establishing yet (skew between ranks' add_process_set calls):
  // (peer rank, psid) -> accepted socket
  std::map<std::pair<int, int>, Socket> pending_channel_;
  std::mutex channels_mu_;  // guards channel_socks_ map shape
  // Serializes ConnectMesh/EstablishChannel (and Shutdown's pending-stash
  // cleanup): one establishment at a time, so a HELLO stashed for another
  // channel is always found by that channel's later drain pass.  Held
  // across the accept loop — never taken by data ops (SocksFor uses
  // channels_mu_ only), so in-flight collectives are not blocked.
  std::mutex mesh_mu_;

  ResponseCache cache_;
  std::map<std::string, Pending> pending_;  // coordinator only
  // Names recently failed by the coordinator: a straggler announcing one
  // later gets the error immediately instead of waiting forever on ranks
  // that already saw the failure.  Delivery is once per rank — a rank that
  // already received the error and announces the name AGAIN is making a
  // fresh, consistent resubmission (recurring tensor names like per-step
  // gradients) and must proceed normally.  Entries expire by time or once
  // every owed rank has been served; expired entries are swept each cycle.
  struct Tombstone {
    std::string error;
    double expiry = 0;
    std::set<int> owed;  // ranks that have not seen the error yet
  };
  std::map<std::string, Tombstone> error_tombstones_;
  void AddTombstone(const std::string& name, const std::string& error,
                    const std::set<int>& already_informed);
  std::set<int> joined_ranks_;              // hvd.join wildcard (coordinator)
  std::set<int> departed_ranks_;            // clean-exited workers
  int32_t last_joined_ = -1;
  bool peer_shutdown_ = false;
  // -- fast-abort state (protocol v8) --------------------------------------
  // abort_mu_ guards abort_reason_/abort_wait_deadline_; abort_cv_ wakes
  // WaitAbortReason when the reason latches.  The bools are only touched
  // from the single background (negotiation) thread.
  std::mutex abort_mu_;
  std::condition_variable abort_cv_;
  std::string abort_reason_;
  double abort_wait_deadline_ = 0;  // first WaitAbortReason sets it once
  bool fin_sent_ = false;           // worker failure FIN sent (send once)
  bool got_abort_ = false;          // coordinator's ABORT already received
  bool abort_broadcast_done_ = false;  // coordinator broadcast once
  bool digest_sent_ = false;        // flight digest sent upward (send once)
  // Coordinator: per-rank flight digests collected during the abort
  // exchange (background thread only, like the other abort bools).
  std::map<int, std::vector<FlightEvent>> flight_digests_;
  // HOROVOD_ABORT_PROPAGATION_TIMEOUT / HOROVOD_RENDEZVOUS_RETRIES /
  // HOROVOD_RENDEZVOUS_BACKOFF_BASE_MS (ctor reads the env).
  double abort_timeout_s_ = 2.0;
  int rendezvous_retries_ = 30;
  long long rendezvous_backoff_base_ms_ = 50;
  int64_t arrival_counter_ = 0;
  int64_t seq_counter_ = 0;   // global data-op sequence (all ranks agree)
  // seq for the next data op on this lane thread (thread_local so
  // concurrent per-process-set lanes tag their frames independently)
  static thread_local int64_t current_seq_;

  bool initialized_ = false;
  std::atomic<bool> aborted_{false};
};

}  // namespace hvdtpu
