// Multi-process controller: rank-0 coordinator negotiation over TCP plus a
// coordinator-rooted host data plane.
//
// Reference analogs (SURVEY.md §2.1, §3.2): controller.cc
// Controller::ComputeResponseList (rank-0 request intersection), gloo/
// (MPI-free CPU transport + rendezvous), response_cache.cc (bit-vector
// steady state), stall_inspector.cc (per-rank missing lists).
//
// Protocol (per negotiation cycle, lock-step):
//   worker -> coord : CYCLE frame = [n_cached, cached_ids...,
//                                    n_requests, full requests...]
//   coord  -> worker: RESPONSES frame = [n, responses...]
// A tensor becomes ready when every rank of its process set has announced
// it; readiness order is deterministic, so the fused response list is
// byte-identical on every rank — which is what lets the TPU device path
// dispatch one cached fused XLA program per response with no further
// coordination.
//
// Data plane: members send DATA frames (tagged by the response's global
// seq) to the coordinator's data service thread, which combines and
// replies.  Host arrays only — the TPU path never touches these sockets.
#pragma once

#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "controller.h"
#include "response_cache.h"
#include "socketio.h"

namespace hvdtpu {

class SocketController : public Controller {
 public:
  explicit SocketController(const CoreConfig& cfg);
  ~SocketController() override;

  Status Initialize() override;
  void Shutdown() override;

  Status ComputeResponses(std::vector<TensorRequest>& new_requests,
                          std::vector<Response>* out) override;

  Status AllreduceBuffer(void* buf, int64_t count, DataType dtype, ReduceOp op,
                         int process_set_id) override;
  Status AllgatherBuffer(const void* in, int64_t nbytes, int process_set_id,
                         std::string* out,
                         std::vector<int64_t>* nbytes_per_rank) override;
  Status BroadcastBuffer(void* buf, int64_t nbytes, int root_rank,
                         int process_set_id) override;
  Status AlltoallBuffer(const void* in, const std::vector<int64_t>& splits,
                        int64_t row_bytes, int process_set_id,
                        std::string* out,
                        std::vector<int64_t>* recv_splits) override;
  Status Barrier(int process_set_id) override;

  std::string StallReport(double older_than_s) override;

  // The executor calls this before each data-plane op to tag frames.
  void SetCurrentSeq(int64_t seq) { current_seq_ = seq; }

 private:
  struct Pending {
    TensorRequest meta;
    std::set<int> announced;
    int64_t order = 0;      // arrival order at coordinator (determinism)
    double first_seen = 0;  // stall inspection
  };

  // -- negotiation ----------------------------------------------------------
  Status CoordinatorCycle(std::vector<TensorRequest>& new_requests,
                          std::vector<Response>* out);
  Status WorkerCycle(std::vector<TensorRequest>& new_requests,
                     std::vector<Response>* out);
  void Announce(int rank, TensorRequest req, std::vector<Response>* errors);
  void UpdateCachesAndSeq(std::vector<Response>* responses);

  // -- data plane -----------------------------------------------------------
  struct DataOpHeader {
    int64_t seq = 0;
    OpType op = OpType::BARRIER;
    DataType dtype = DataType::FLOAT32;
    ReduceOp reduce_op = ReduceOp::SUM;
    int32_t process_set_id = 0;
    int32_t root_rank = 0;
    int64_t row_bytes = 0;
    std::vector<int64_t> splits;
  };
  struct DataOpState {
    DataOpHeader header;
    std::map<int, std::string> contributions;  // rank -> payload
    bool header_set = false;
  };
  // Executes a data op as a member (worker: over the socket; coordinator:
  // via the local channel to the data service thread).
  Status MemberDataOp(const DataOpHeader& h, const std::string& payload,
                      std::string* reply);
  void DataServiceLoop();
  void CompleteDataOp(DataOpState& st);
  static void ExecuteDataOp(const DataOpHeader& h,
                            const std::map<int, std::string>& contribs,
                            const std::vector<int>& members,
                            std::map<int, std::string>* replies);

  // -- wiring ---------------------------------------------------------------
  bool is_coordinator() const { return cfg_.rank == 0; }

  Listener listener_;
  // coordinator: per-worker sockets (index = rank, [0] unused)
  std::vector<Socket> ctrl_socks_;
  std::vector<Socket> data_socks_;
  // worker: connections to the coordinator
  Socket coord_ctrl_;
  Socket coord_data_;

  ResponseCache cache_;
  std::map<std::string, Pending> pending_;  // coordinator only
  int64_t arrival_counter_ = 0;
  int64_t seq_counter_ = 0;   // global data-op sequence (all ranks agree)
  int64_t current_seq_ = -1;  // seq for the next data op on this rank

  // coordinator data service
  std::thread data_thread_;
  std::mutex data_mu_;
  std::condition_variable data_cv_;
  std::map<int64_t, DataOpState> data_ops_;
  std::map<int64_t, std::map<int, std::string>> data_replies_;
  bool data_shutdown_ = false;
  // local (rank 0) contribution channel into the data service
  std::deque<std::pair<DataOpHeader, std::string>> local_contrib_;
  std::map<int64_t, std::string> local_reply_;
  std::map<int64_t, std::vector<int64_t>> reply_splits_;  // seq -> counts

  bool initialized_ = false;
  bool aborted_ = false;
};

}  // namespace hvdtpu
