// Always-on flight recorder: a lock-free per-thread ring buffer of compact
// binary events recorded at the sites the metrics plane already instruments
// (rendezvous, CYCLE send/recv, negotiation verdicts, ring hops, shm fences,
// leader-tree aggregates, fault-injection trips, abort frames).  The black
// box survives until the moment of death: on abort, fatal init error, or a
// fatal signal each rank dumps its buffer to HOROVOD_POSTMORTEM_DIR, and the
// coordinator merges surviving ranks' last-N-event digests into one
// postmortem.json (socket_controller.cc BroadcastAbortAndFail).
//
// Cost discipline matches metrics.h: every record site is guarded by a
// single relaxed bool load (FlightOn), and a record is a handful of relaxed
// atomic stores into a pre-allocated slot — no locks, no allocation, no
// syscalls beyond the vDSO clock read.  Slots are per-thread so writers
// never contend; the dump path reads the same atomics, so a dump racing a
// crash observes at worst one torn (self-labelled, droppable) event.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

// Event vocabulary.  Keep in sync with the "types" legend emitted by the
// dump paths (flight_recorder.cc kFlightTypesLegend) and decoded by
// tools/postmortem.py.
enum FlightType : int32_t {
  kFlightCtrlSend = 1,    // a = 0,            b = payload bytes
  kFlightCtrlRecv = 2,    // a = 0,            b = payload bytes
  kFlightRendezvous = 3,  // a = world size,   b = protocol version
  kFlightVerdict = 4,     // a = responses,    b = data-op seq after verdict
  kFlightRingHop = 5,     // a = frame tag,    b = bytes sent
  kFlightWireCodec = 6,   // a = codec id,     b = payload bytes
  kFlightShmFence = 7,    // a = fence tag,    b = 0
  kFlightShmMap = 8,      // a = 0 open/1 grow, b = capacity bytes
  kFlightTreeAgg = 9,     // a = child frames, b = aggregate bytes
  kFlightFaultTrip = 10,  // a = fault site,   b = action
  kFlightAbort = 11,      // a = culprit rank, b = 0 observed / 1 broadcast
  kFlightDigest = 12,     // a = source rank,  b = events carried
  kFlightAutopilot = 13,  // a = action code,  b = target rank
  kFlightMigrate = 14,    // a = phase<<8 | source rank (+1; 0 = none),
                          // b = payload bytes
  kFlightSentinel = 15,   // a = kind<<8 | rank (+1; 0 = fleet-wide),
                          // b = observed value (us or ppm)
  kFlightHloInspect = 16, // a = compiler-inserted collective op count,
                          // b = analytic wire bytes for the trace
};

struct FlightEvent {
  int64_t ts_us = 0;  // CLOCK_REALTIME microseconds (cross-rank comparable)
  uint64_t seq = 0;   // global record order on this rank
  int32_t type = 0;   // FlightType
  int32_t tid = 0;    // recorder thread slot (not the OS tid)
  int32_t a = 0;
  int64_t b = 0;
};

struct FlightRecorderState {
  std::atomic<bool> enabled{false};
};

FlightRecorderState& GlobalFlightRecorder();

// The per-site guard: one relaxed bool load when disabled, mirroring
// MetricsOn() in metrics.h.
inline bool FlightOn() {
  return GlobalFlightRecorder().enabled.load(std::memory_order_relaxed);
}

// Arms the recorder.  `slots` is rounded up to a power of two (default
// 4096); `postmortem_dir` may contain a literal "{rank}" (substituted like
// HOROVOD_METRICS_FILE) and enables crash dumps + fatal-signal handlers
// when non-empty.  Idempotent per init; elastic re-init re-arms in place.
void InitFlightRecorder(bool enabled, int slots,
                        const std::string& postmortem_dir, int rank);

// Records one event into the calling thread's ring.  Call only under
// FlightOn(); silently drops if the thread table (64 slots) is exhausted.
void FlightRecord(int32_t type, int32_t a, int64_t b);

// Last `n` events across all thread rings, oldest first (sorted by seq).
void FlightTail(int n, std::vector<FlightEvent>* out);

// Full buffer as one JSON object (same schema as the crash dump, events
// sorted by seq) — the hvd.flight_record() payload.
std::string FlightDumpJson();

// Async-signal-safe dump of the full buffer to FlightDumpPath() via
// tmp-file + rename (atomic: readers never see a partial file).  No-op
// when no postmortem dir is configured; safe to call from a signal
// handler, an abort path, and concurrently (single-flight latch).
void FlightDumpToFile();

// This rank's crash-dump path ("" when no postmortem dir is configured).
std::string FlightDumpPath();

// The rank-substituted postmortem directory ("" when unset) — where the
// coordinator writes the merged postmortem.json.
std::string FlightPostmortemDir();

// The static event-type legend (a JSON object literal), shared by the dump
// paths and the coordinator's merged postmortem.json.
const char* FlightTypesLegend();

// Events overwritten so far (ring wrapped past unread slots), summed over
// threads.
int64_t FlightDropped();

// Test-only: disarm, free rings, and forget registered threads.  Callers
// must quiesce every recording thread first — a record racing the reset
// would touch a freed ring.
void ResetFlightRecorderForTest();

}  // namespace hvdtpu
