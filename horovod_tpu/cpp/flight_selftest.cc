// Self-test for the flight recorder (flight_recorder.{h,cc}): ring
// wraparound accounting, multi-thread interleave with a concurrent dumper
// (the TSan target of the sanitizer matrix), atomic dump-to-file, and the
// dump-on-fatal-signal path via a forked child.  Build/run via `make
// flight_selftest` (plus tsan_/asan_/ubsan_ variants); wired into `make
// selftest`.

#include <signal.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "flight_recorder.h"

using namespace hvdtpu;

#define CHECK_TRUE(cond, what)                                      \
  do {                                                              \
    if (!(cond)) {                                                  \
      std::fprintf(stderr, "  CHECK failed: %s (%s:%d)\n", what,    \
                   __FILE__, __LINE__);                             \
      return false;                                                 \
    }                                                               \
  } while (0)

namespace {

std::string TempDir() {
  char tmpl[] = "/tmp/hvd_flight_XXXXXX";
  char* d = ::mkdtemp(tmpl);
  return d ? std::string(d) : std::string("/tmp");
}

bool TestBasicRecordAndTail() {
  ResetFlightRecorderForTest();
  InitFlightRecorder(true, 256, "", 3);
  CHECK_TRUE(FlightOn(), "recorder armed");
  for (int i = 0; i < 10; ++i) {
    FlightRecord(kFlightCtrlSend, i, 100 + i);
  }
  std::vector<FlightEvent> tail;
  FlightTail(4, &tail);
  CHECK_TRUE(tail.size() == 4, "tail length");
  for (size_t i = 1; i < tail.size(); ++i) {
    CHECK_TRUE(tail[i].seq > tail[i - 1].seq, "tail seq ascending");
  }
  CHECK_TRUE(tail.back().a == 9 && tail.back().b == 109, "last event payload");
  CHECK_TRUE(tail.back().type == kFlightCtrlSend, "event type");
  CHECK_TRUE(FlightDropped() == 0, "nothing dropped");
  CHECK_TRUE(FlightDumpPath().empty(), "no dump path without dir");
  return true;
}

bool TestWraparound() {
  ResetFlightRecorderForTest();
  InitFlightRecorder(true, 64, "", 0);  // kMinSlots floor
  const int kEvents = 200;
  for (int i = 0; i < kEvents; ++i) {
    FlightRecord(kFlightRingHop, i, 2 * i);
  }
  CHECK_TRUE(FlightDropped() == kEvents - 64, "dropped = overflow");
  std::vector<FlightEvent> tail;
  FlightTail(1 << 20, &tail);
  CHECK_TRUE(tail.size() == 64, "ring holds exactly slots events");
  // The survivors are the newest 64, contiguous and in order.
  for (size_t i = 0; i < tail.size(); ++i) {
    CHECK_TRUE(tail[i].a == kEvents - 64 + static_cast<int>(i),
               "survivor is newest window");
  }
  return true;
}

bool TestSlotRounding() {
  ResetFlightRecorderForTest();
  InitFlightRecorder(true, 100, "", 0);  // rounds up to 128
  for (int i = 0; i < 300; ++i) FlightRecord(kFlightShmFence, i, 0);
  CHECK_TRUE(FlightDropped() == 300 - 128, "slots rounded to power of two");
  return true;
}

bool TestMultiThreadInterleave() {
  ResetFlightRecorderForTest();
  InitFlightRecorder(true, 4096, "", 0);
  const int kThreads = 8;
  const int kPerThread = 500;
  std::atomic<bool> stop{false};
  // A concurrent dumper makes this the TSan workout: dump reads race
  // record writes on live rings and must stay data-race-free.
  std::thread dumper([&] {
    while (!stop.load(std::memory_order_relaxed)) {
      std::vector<FlightEvent> t;
      FlightTail(64, &t);
      std::string j = FlightDumpJson();
      if (j.empty()) break;
    }
  });
  std::vector<std::thread> workers;
  for (int t = 0; t < kThreads; ++t) {
    workers.emplace_back([t] {
      for (int i = 0; i < kPerThread; ++i) {
        FlightRecord(kFlightVerdict, t, i);
      }
    });
  }
  for (auto& w : workers) w.join();
  stop.store(true, std::memory_order_relaxed);
  dumper.join();
  std::vector<FlightEvent> all;
  FlightTail(1 << 20, &all);
  // The dumper thread registers a slot but records nothing; the main
  // thread may have stale events from a prior test? No — reset cleared.
  std::set<uint64_t> seqs;
  int per_thread_seen[64] = {0};
  for (const auto& ev : all) {
    CHECK_TRUE(seqs.insert(ev.seq).second, "global seq unique");
    if (ev.type == kFlightVerdict) per_thread_seen[ev.a % 64]++;
  }
  CHECK_TRUE(static_cast<int>(all.size()) == kThreads * kPerThread,
             "no events lost below capacity");
  for (int t = 0; t < kThreads; ++t) {
    CHECK_TRUE(per_thread_seen[t] == kPerThread, "per-thread count");
  }
  CHECK_TRUE(FlightDropped() == 0, "no wrap at this volume");
  return true;
}

bool TestDumpToFile() {
  ResetFlightRecorderForTest();
  std::string dir = TempDir();
  InitFlightRecorder(true, 128, dir + "/{rank}", 7);
  for (int i = 0; i < 20; ++i) FlightRecord(kFlightCtrlRecv, i, 3 * i);
  FlightDumpToFile();
  std::string path = FlightDumpPath();
  CHECK_TRUE(path == dir + "/7/flight.7.json", "rank-templated path");
  std::ifstream f(path);
  CHECK_TRUE(f.good(), "dump file exists");
  std::stringstream ss;
  ss << f.rdbuf();
  std::string text = ss.str();
  CHECK_TRUE(text.find("\"rank\":7") != std::string::npos, "rank field");
  CHECK_TRUE(text.find("\"types\":{") != std::string::npos, "types legend");
  CHECK_TRUE(text.find("\"events\":[[") != std::string::npos, "events body");
  CHECK_TRUE(text.back() == '}', "complete object");
  // Balanced-bracket sanity (the dump is machine-written, no strings
  // beyond host/legend literals).
  int depth = 0;
  for (char c : text) {
    if (c == '{' || c == '[') depth++;
    if (c == '}' || c == ']') depth--;
  }
  CHECK_TRUE(depth == 0, "balanced JSON");
  // In-memory dump agrees on the header fields.
  std::string mem = FlightDumpJson();
  CHECK_TRUE(mem.find("\"rank\":7") != std::string::npos, "mem dump rank");
  CHECK_TRUE(mem.find("\"dropped\":0") != std::string::npos, "mem dropped");
  return true;
}

bool TestDumpOnFatalSignal() {
  ResetFlightRecorderForTest();
  std::string dir = TempDir();
  pid_t pid = ::fork();
  CHECK_TRUE(pid >= 0, "fork");
  if (pid == 0) {
    // Child: arm with a postmortem dir (installs the fatal handlers),
    // record a little history, then die abruptly.  SIGABRT rather than
    // SIGSEGV: sanitizer runtimes own SIGSEGV and the recorder refuses
    // to trample non-default dispositions.
    InitFlightRecorder(true, 128, dir, 2);
    for (int i = 0; i < 5; ++i) FlightRecord(kFlightFaultTrip, i, 137);
    ::raise(SIGABRT);
    ::_exit(0);  // unreachable
  }
  int status = 0;
  CHECK_TRUE(::waitpid(pid, &status, 0) == pid, "waitpid");
  CHECK_TRUE(WIFSIGNALED(status) && WTERMSIG(status) == SIGABRT,
             "child died of SIGABRT");
  std::ifstream f(dir + "/flight.2.json");
  CHECK_TRUE(f.good(), "signal handler dumped the ring");
  std::stringstream ss;
  ss << f.rdbuf();
  std::string text = ss.str();
  CHECK_TRUE(text.find("\"rank\":2") != std::string::npos, "child rank");
  CHECK_TRUE(text.find(",10,") != std::string::npos, "fault_trip events");
  CHECK_TRUE(text.back() == '}', "atomic rename: never a partial file");
  return true;
}

bool TestReset() {
  ResetFlightRecorderForTest();
  InitFlightRecorder(true, 128, "", 0);
  FlightRecord(kFlightAbort, 1, 0);
  ResetFlightRecorderForTest();
  CHECK_TRUE(!FlightOn(), "disarmed after reset");
  std::vector<FlightEvent> tail;
  FlightTail(16, &tail);
  CHECK_TRUE(tail.empty(), "rings forgotten");
  // Threads re-register cleanly in the new epoch.
  InitFlightRecorder(true, 128, "", 0);
  FlightRecord(kFlightAbort, 2, 1);
  FlightTail(16, &tail);
  CHECK_TRUE(tail.size() == 1 && tail[0].a == 2, "fresh epoch records");
  return true;
}

}  // namespace

int main() {
  struct Case {
    const char* name;
    bool (*fn)();
  } cases[] = {
      {"basic_record_and_tail", TestBasicRecordAndTail},
      {"wraparound", TestWraparound},
      {"slot_rounding", TestSlotRounding},
      {"multi_thread_interleave", TestMultiThreadInterleave},
      {"dump_to_file", TestDumpToFile},
      {"dump_on_fatal_signal", TestDumpOnFatalSignal},
      {"reset", TestReset},
  };
  int failures = 0;
  for (const auto& c : cases) {
    std::fprintf(stderr, "[flight_selftest] %s...\n", c.name);
    if (!c.fn()) {
      std::fprintf(stderr, "[flight_selftest] %s FAILED\n", c.name);
      failures++;
    }
  }
  if (failures == 0) {
    std::printf("PASS\n");
    return 0;
  }
  std::printf("FAIL(%d)\n", failures);
  return 1;
}
