#include "controller.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <unordered_map>
#include <utility>

#include "logging.h"

namespace hvdtpu {

// ---- ProcessSetTable ------------------------------------------------------

void ProcessSetTable::InitGlobal(int world_size) {
  std::lock_guard<std::mutex> l(mu_);
  std::vector<int> all(world_size);
  for (int i = 0; i < world_size; ++i) all[i] = i;
  sets_[0] = all;
}

int ProcessSetTable::Add(const std::vector<int>& ranks) {
  return AddWeighted(ranks, 1.0);
}

int ProcessSetTable::AddWeighted(const std::vector<int>& ranks,
                                 double weight) {
  std::lock_guard<std::mutex> l(mu_);
  std::vector<int> sorted = ranks;
  std::sort(sorted.begin(), sorted.end());
  sorted.erase(std::unique(sorted.begin(), sorted.end()), sorted.end());
  int id = next_id_++;
  sets_[id] = sorted;
  // Clamp: a zero/negative weight would let the scheduler starve the set
  // outright, which is a deadlock (its members still block on the ring).
  weights_[id] = weight > 0.0 ? weight : 1.0;
  return id;
}

void ProcessSetTable::Remove(int id) {
  if (id == 0) return;
  std::lock_guard<std::mutex> l(mu_);
  sets_.erase(id);
  weights_.erase(id);
}

bool ProcessSetTable::Ranks(int id, std::vector<int>* out) const {
  std::lock_guard<std::mutex> l(mu_);
  auto it = sets_.find(id);
  if (it == sets_.end()) return false;
  *out = it->second;
  return true;
}

bool ProcessSetTable::Contains(int id, int rank) const {
  std::lock_guard<std::mutex> l(mu_);
  auto it = sets_.find(id);
  if (it == sets_.end()) return false;
  return std::binary_search(it->second.begin(), it->second.end(), rank);
}

double ProcessSetTable::Weight(int id) const {
  std::lock_guard<std::mutex> l(mu_);
  auto it = weights_.find(id);
  // Set 0 (the global set) and any pre-weight registration stay at 1.0.
  return it == weights_.end() ? 1.0 : it->second;
}

// ---- Fusion ---------------------------------------------------------------

std::vector<Response> FuseRequests(const std::vector<TensorRequest>& ready,
                                   int64_t fusion_threshold) {
  std::vector<Response> out;
  std::vector<const TensorRequest*> bucket;
  int64_t bucket_bytes = 0;

  auto flush = [&]() {
    if (bucket.empty()) return;
    Response r;
    r.op = bucket.front()->op;
    r.dtype = bucket.front()->dtype;
    r.process_set_id = bucket.front()->process_set_id;
    for (auto* t : bucket) {
      r.names.push_back(t->name);
      r.metas.push_back(*t);
    }
    out.push_back(std::move(r));
    bucket.clear();
    bucket_bytes = 0;
  };

  for (const auto& t : ready) {
    if (t.op == OpType::ALLREDUCE) {
      bool fusable = !bucket.empty() &&
                     bucket.front()->op == OpType::ALLREDUCE &&
                     bucket.front()->dtype == t.dtype &&
                     bucket.front()->process_set_id == t.process_set_id &&
                     bucket.front()->reduce_op == t.reduce_op &&
                     bucket.front()->prescale == t.prescale &&
                     bucket.front()->postscale == t.postscale &&
                     // device buckets stay pure: a fused response executes
                     // on exactly one data plane
                     bucket.front()->device == t.device &&
                     bucket_bytes + t.nbytes <= fusion_threshold;
      if (!fusable) flush();
      bucket.push_back(&t);
      bucket_bytes += t.nbytes;
    } else if (t.op == OpType::ALLGATHER) {
      // Allgathers fuse too (reference: AllgatherOp shares the fusion
      // buffer): the executor packs members length-prefixed, so only the
      // process set has to match.
      bool fusable = !bucket.empty() &&
                     bucket.front()->op == OpType::ALLGATHER &&
                     bucket.front()->process_set_id == t.process_set_id &&
                     bucket_bytes + t.nbytes <= fusion_threshold;
      if (!fusable) flush();
      bucket.push_back(&t);
      bucket_bytes += t.nbytes;
    } else {
      flush();
      Response r;
      r.op = t.op;
      r.dtype = t.dtype;
      r.process_set_id = t.process_set_id;
      r.names.push_back(t.name);
      r.metas.push_back(t);
      out.push_back(std::move(r));
    }
  }
  flush();
  return out;
}

// ---- LocalController ------------------------------------------------------

Status LocalController::Initialize() {
  process_sets_.InitGlobal(1);
  return Status::OK();
}

Status LocalController::ComputeResponses(
    std::vector<TensorRequest>& new_requests, std::vector<Response>* out) {
  // Atomic group gating at np=1: a grouped enqueue can race the cycle
  // drain mid-call, so members may arrive across drains — hold a group
  // until all group_size members are present (GateAndOrderGroups; the
  // SocketController coordinator applies the same rule cross-rank).
  for (auto& r : new_requests) held_.emplace_back(arrival_++, std::move(r));
  std::vector<TensorRequest> ready;
  std::vector<std::pair<int64_t, TensorRequest>> still_held;
  GateAndOrderGroups(
      std::move(held_), &still_held, &ready,
      [](const TensorRequest& r) -> const TensorRequest& { return r; });
  held_ = std::move(still_held);
  *out = FuseRequests(ready, cfg_.fusion_threshold);
  for (auto& r : *out) {
    // Single process: this rank is trivially the last (and only) joiner.
    if (r.op == OpType::JOIN) r.last_joined = 0;
  }
  return Status::OK();
}

// ---- typed reduction ------------------------------------------------------

namespace {

// bfloat16 <-> float conversion for host-side reduction.
inline float Bf16ToF32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}
inline uint16_t F32ToBf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // round-to-nearest-even like XLA
  uint32_t rounding_bias = ((bits >> 16) & 1) + 0x7FFF;
  return static_cast<uint16_t>((bits + rounding_bias) >> 16);
}
// IEEE fp16 conversion (scalar; host path only).
inline float F16ToF32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t mant = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {
      // subnormal
      int shift = 0;
      while (!(mant & 0x400)) {
        mant <<= 1;
        ++shift;
      }
      mant &= 0x3FF;
      bits = sign | ((127 - 15 - shift) << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}
inline uint16_t F32ToF16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xFF) - 127 + 15;
  uint32_t mant = bits & 0x7FFFFFu;
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7C00u);  // inf/overflow
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = mant >> shift;
    // round to nearest
    if ((mant >> (shift - 1)) & 1) half += 1;
    return static_cast<uint16_t>(sign | half);
  }
  uint16_t h = static_cast<uint16_t>(sign | (exp << 10) | (mant >> 13));
  if (mant & 0x1000) h += 1;  // round
  return h;
}

template <typename T>
void ReduceTyped(T* acc, const T* c, int64_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:  // averaging divide happens after the full reduce
    case ReduceOp::ADASUM:
      for (int64_t i = 0; i < n; ++i) acc[i] = static_cast<T>(acc[i] + c[i]);
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < n; ++i) acc[i] = std::min(acc[i], c[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < n; ++i) acc[i] = std::max(acc[i], c[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < n; ++i) acc[i] = static_cast<T>(acc[i] * c[i]);
      break;
  }
}

template <typename Cvt16ToF, typename F32To16>
void Reduce16(uint16_t* acc, const uint16_t* c, int64_t n, ReduceOp op,
              Cvt16ToF to_f32, F32To16 to_16) {
  for (int64_t i = 0; i < n; ++i) {
    float a = to_f32(acc[i]);
    float b = to_f32(c[i]);
    float r;
    switch (op) {
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      case ReduceOp::PRODUCT: r = a * b; break;
      default: r = a + b; break;
    }
    acc[i] = to_16(r);
  }
}

void ReduceBool(uint8_t* acc, const uint8_t* c, int64_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::MIN:
      for (int64_t i = 0; i < n; ++i) acc[i] = acc[i] & c[i];
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < n; ++i) acc[i] = acc[i] & c[i];
      break;
    default:  // SUM/MAX -> logical or
      for (int64_t i = 0; i < n; ++i) acc[i] = acc[i] | c[i];
      break;
  }
}

}  // namespace

void ReduceInto(void* acc, const void* contrib, int64_t count, DataType dtype,
                ReduceOp op) {
  switch (dtype) {
    case DataType::FLOAT32:
      ReduceTyped(static_cast<float*>(acc),
                  static_cast<const float*>(contrib), count, op);
      break;
    case DataType::FLOAT64:
      ReduceTyped(static_cast<double*>(acc),
                  static_cast<const double*>(contrib), count, op);
      break;
    case DataType::INT32:
      ReduceTyped(static_cast<int32_t*>(acc),
                  static_cast<const int32_t*>(contrib), count, op);
      break;
    case DataType::INT64:
      ReduceTyped(static_cast<int64_t*>(acc),
                  static_cast<const int64_t*>(contrib), count, op);
      break;
    case DataType::UINT8:
      ReduceTyped(static_cast<uint8_t*>(acc),
                  static_cast<const uint8_t*>(contrib), count, op);
      break;
    case DataType::INT8:
      ReduceTyped(static_cast<int8_t*>(acc),
                  static_cast<const int8_t*>(contrib), count, op);
      break;
    case DataType::UINT16:
      ReduceTyped(static_cast<uint16_t*>(acc),
                  static_cast<const uint16_t*>(contrib), count, op);
      break;
    case DataType::INT16:
      ReduceTyped(static_cast<int16_t*>(acc),
                  static_cast<const int16_t*>(contrib), count, op);
      break;
    case DataType::BOOL:
      ReduceBool(static_cast<uint8_t*>(acc),
                 static_cast<const uint8_t*>(contrib), count, op);
      break;
    case DataType::FLOAT16:
      Reduce16(static_cast<uint16_t*>(acc),
               static_cast<const uint16_t*>(contrib), count, op, F16ToF32,
               F32ToF16);
      break;
    case DataType::BFLOAT16:
      Reduce16(static_cast<uint16_t*>(acc),
               static_cast<const uint16_t*>(contrib), count, op, Bf16ToF32,
               F32ToBf16);
      break;
  }
}

}  // namespace hvdtpu
