// Minimal TCP framing + binary serialization for the control/data planes.
//
// TPU-native analog of the reference's wire layer (horovod/common/wire/ +
// gloo HTTP rendezvous; SURVEY.md §2.1 "Wire messages"): length-prefixed
// frames over blocking sockets, little-endian scalar encoding.
#pragma once

#include <cstdint>
#include <cstring>
#include <functional>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

// ---- byte buffer ----------------------------------------------------------

class Writer {
 public:
  void PutI32(int32_t v) { PutRaw(&v, 4); }
  void PutI64(int64_t v) { PutRaw(&v, 8); }
  void PutF64(double v) { PutRaw(&v, 8); }
  void PutU8(uint8_t v) { PutRaw(&v, 1); }
  void PutString(const std::string& s) {
    PutI32(static_cast<int32_t>(s.size()));
    PutRaw(s.data(), s.size());
  }
  void PutI64Vec(const std::vector<int64_t>& v) {
    PutI32(static_cast<int32_t>(v.size()));
    for (int64_t x : v) PutI64(x);
  }
  void PutRaw(const void* p, size_t n) {
    const char* c = static_cast<const char*>(p);
    buf_.insert(buf_.end(), c, c + n);
  }
  const std::string& data() const { return buf_; }
  std::string&& Take() { return std::move(buf_); }

 private:
  std::string buf_;
};

class Reader {
 public:
  Reader(const char* data, size_t size) : data_(data), size_(size) {}
  explicit Reader(const std::string& s) : data_(s.data()), size_(s.size()) {}
  int32_t GetI32() { int32_t v; Get(&v, 4); return v; }
  int64_t GetI64() { int64_t v; Get(&v, 8); return v; }
  double GetF64() { double v; Get(&v, 8); return v; }
  uint8_t GetU8() { uint8_t v; Get(&v, 1); return v; }
  std::string GetString() {
    int32_t n = GetI32();
    std::string s(data_ + pos_, n);
    pos_ += n;
    return s;
  }
  std::vector<int64_t> GetI64Vec() {
    int32_t n = GetI32();
    std::vector<int64_t> v(n);
    for (int32_t i = 0; i < n; ++i) v[i] = GetI64();
    return v;
  }
  bool ok() const { return ok_; }
  size_t remaining() const { return size_ - pos_; }
  // Zero-copy view of the unread tail (bulk data-plane payloads).
  const char* cursor() const { return data_ + pos_; }

 private:
  void Get(void* out, size_t n) {
    if (pos_ + n > size_) { ok_ = false; std::memset(out, 0, n); return; }
    std::memcpy(out, data_ + pos_, n);
    pos_ += n;
  }
  const char* data_;
  size_t size_;
  size_t pos_ = 0;
  bool ok_ = true;
};

// ---- request/response serialization ---------------------------------------

void SerializeRequest(const TensorRequest& r, Writer* w);
TensorRequest DeserializeRequest(Reader* r);
void SerializeResponse(const Response& r, Writer* w);
Response DeserializeResponse(Reader* r);

// ---- sockets --------------------------------------------------------------

// Blocking TCP socket with u32-length-prefixed frames.  All methods return
// false on peer close / error (callers treat that as ABORTED).
class Socket {
 public:
  Socket() = default;
  explicit Socket(int fd) : fd_(fd) {}
  ~Socket();
  Socket(const Socket&) = delete;
  Socket& operator=(const Socket&) = delete;
  Socket(Socket&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Socket& operator=(Socket&& o) noexcept;

  bool Connect(const std::string& addr, int port, double timeout_s);
  // Single connect attempt, no internal retry loop: the caller owns the
  // retry policy (rendezvous exponential backoff).  On failure last_errno()
  // holds the connect errno (resolve failures report EAGAIN — retryable,
  // DNS may come up after the worker).
  bool ConnectOnce(const std::string& addr, int port);
  int last_errno() const { return last_errno_; }
  bool SendFrame(const std::string& payload);
  bool RecvFrame(std::string* payload);
  // Raw (unframed) helpers for bulk data-plane payloads.
  bool SendAll(const void* p, size_t n);
  bool RecvAll(void* p, size_t n);
  // Peer IPv4 address ("1.2.3.4") of a connected socket, "" on error.
  std::string PeerAddr() const;
  // Kernel receive timeout; 0 restores blocking reads.  Used to bound the
  // rendezvous HELLO read so a connect-and-stay-silent stray cannot wedge
  // the accept loop.
  void SetRecvTimeout(double seconds);
  void Close();
  bool valid() const { return fd_ >= 0; }
  int fd() const { return fd_; }

 private:
  int fd_ = -1;
  int last_errno_ = 0;
};

// Whether a failed connect attempt is worth retrying: refused/timed-out/
// unreachable mean the peer may simply not be up yet (startup race);
// permission and address-family errors will never heal and must fail
// immediately with a named cause.
bool ConnectErrnoRetryable(int err);

// Simultaneously send one frame on `send_sock` and receive one frame from
// `recv_sock` without deadlocking — ring/pairwise collective steps have every
// member sending first, so blocking sends can gridlock once payloads exceed
// the kernel socket buffers (the reason Gloo's ring algorithms are
// event-driven).  The two sockets may be the same object (2-member ring).
// `cancelled` is polled between progress events; returning true aborts.
// Returns false on peer failure or cancellation.
bool DuplexExchange(Socket& send_sock, const std::string& out,
                    Socket& recv_sock, std::string* in,
                    const std::function<bool()>& cancelled);

// Chunk-pipelined duplex segment exchange: streams `send_len` payload bytes
// from `send_base` to `send_sock` as a sequence of length-prefixed chunk
// frames (u32 length | `header` bytes | payload), while receiving the
// peer's equally-framed stream of `recv_total` payload bytes from
// `recv_sock`.  This is the Gloo-style segmented ring step: because the
// payload is sent directly from the caller's buffer and received directly
// into `recv_dest` (or handed chunk-by-chunk to `on_chunk` for in-flight
// reduction), a ring hop costs zero full-segment copies and the reduce
// overlaps the wire transfer instead of waiting for the whole segment.
//
// - Each incoming chunk's header must byte-equal `header` (both ends of a
//   ring step carry the same [seq|tag]); on mismatch `err` carries the
//   got-header.  Bad frame lengths and transport failures are reported as
//   their own error kinds so desync messages name the real cause.
// - `recv_dest`, when non-null, receives payload bytes at their cumulative
//   offset (zero-copy).  Otherwise chunks land in an internal scratch and
//   `on_chunk(offset, data, len)` is invoked as each completes, in order.
// - The peer's chunk size is discovered per-frame, so the two ends may use
//   different NONZERO HOROVOD_RING_CHUNK_BYTES settings.  0 (the legacy
//   whole-segment protocol) is a different wire format and must be uniform
//   across ranks.
// - The two sockets may be the same object (2-member ring).
struct ChunkExchangeError {
  enum Kind { kNone, kTransport, kHeaderMismatch, kBadLength };
  Kind kind = kNone;
  std::string got_header;  // kHeaderMismatch: the peer's header bytes
  int64_t bad_length = 0;  // kBadLength: the offending payload length
};

bool ChunkedDuplexExchange(
    Socket& send_sock, const char* send_base, int64_t send_len,
    Socket& recv_sock, int64_t recv_total, int64_t chunk_bytes,
    const std::string& header, char* recv_dest,
    const std::function<void(int64_t off, const char* data, int64_t len)>&
        on_chunk,
    const std::function<bool()>& cancelled, ChunkExchangeError* err);

// Listening socket; Accept returns connected Sockets.
class Listener {
 public:
  // Binds to addr:port; if port==0 an ephemeral port is chosen and stored.
  bool Listen(const std::string& addr, int port);
  // Poll + accept, bounded by timeout_s; returns an invalid Socket on
  // timeout or when another thread won the connection.  Safe to call from
  // multiple threads at once (the fd is non-blocking, so losing racers get
  // EAGAIN, not a stuck ::accept) — the sharded rendezvous
  // (HOROVOD_RENDEZVOUS_ACCEPTORS) relies on this.
  Socket Accept(double timeout_s);
  int port() const { return port_; }
  void Close();
  ~Listener();

 private:
  int fd_ = -1;
  int port_ = 0;
};

}  // namespace hvdtpu
