// Self-test for the Bayesian autotuner on a synthetic score surface.
//
// Reference analog: test/parallel autotune coverage asserts tuning improves
// the score, not just that it runs (VERDICT r1 weak #5).  The surface mimics
// the real trade-off: throughput rises with fusion size up to a knee, falls
// when the cycle time is too small (negotiation overhead) or too large
// (idle waiting).  Run by tests/single/test_autotune_bayes.py.

#include <cmath>
#include <cstdio>
#include <cstdlib>

#include "parameter_manager.h"

// Logging hooks normally provided by core_api.cc.
namespace hvdtpu {
int GetLogLevel() { return 5; }
void SetLogLevel(int) {}
}  // namespace hvdtpu

using hvdtpu::BayesianOptimizer;

namespace {

// Peak at fusion_x = 0.7, cycle_x = 0.35 on the unit square.
double Surface(double x0, double x1, unsigned* rng) {
  double fx = x0 - 0.7, cx = x1 - 0.35;
  double base = std::exp(-(fx * fx) / 0.08 - (cx * cx) / 0.05);
  *rng = *rng * 1664525u + 1013904223u;
  double noise = (((*rng >> 16) & 0xFFFF) / 65535.0 - 0.5) * 0.05;
  return 1e9 * (base + noise);  // bytes/sec scale, 5% noise
}

}  // namespace

int main() {
  {
    BayesianOptimizer bo;
    // With the hierarchical, wire-compression, device-codec, and
    // device-schedule knobs pinned (no multi-host topology, no device
    // plane), the EI search must not waste probes on the dead arms.
    bo.set_tune_x3(false);
    bo.set_tune_x4(false);
    bo.set_tune_x5(false);
    bo.set_tune_x6(false);
    unsigned rng = 12345;
    // First probe: a deliberately bad corner (tiny fusion, huge cycle).
    double x0 = 0.05, x1 = 0.95, x2 = 0.0, x3 = 0.0, x4 = 0.0, x5 = 0.0,
           x6 = 0.0;
    double first_score = Surface(x0, x1, &rng);
    bo.AddSample(x0, x1, x2, x3, x4, x5, x6, first_score);
    for (int round = 0; round < 30; ++round) {
      bo.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6);
      if (x3 >= 0.5) {
        std::printf("FAIL: pinned x3 knob was explored\n");
        return 1;
      }
      if (x4 >= 0.25) {
        std::printf("FAIL: pinned x4 knob was explored\n");
        return 1;
      }
      if (x5 >= 1.0 / 6.0) {
        std::printf("FAIL: pinned x5 knob was explored\n");
        return 1;
      }
      if (x6 >= 0.25) {
        std::printf("FAIL: pinned x6 knob was explored\n");
        return 1;
      }
      bo.AddSample(x0, x1, x2, x3, x4, x5, x6, Surface(x0, x1, &rng));
    }
    double bx0, bx1, bx2, bx3, bx4, bx5, bx6, best;
    bo.Best(&bx0, &bx1, &bx2, &bx3, &bx4, &bx5, &bx6, &best);
    std::printf("first=%.3e best=%.3e at (%.2f, %.2f, %.0f)\n", first_score,
                best, bx0, bx1, bx2);
    // The optimum value is ~1e9; the bad corner scores ~0.  Require the
    // optimizer to have found at least 80% of the peak.
    if (best < 0.8e9) {
      std::printf("FAIL: best score did not approach the optimum\n");
      return 1;
    }
    if (best <= first_score * 2) {
      std::printf("FAIL: no improvement over the initial configuration\n");
      return 1;
    }
  }
  {
    // Categorical dimension: the same continuous surface, but category 1
    // (e.g. cache-announce on) scores 25% higher everywhere.  The
    // optimizer must converge onto category 1 (reference analog:
    // ParameterManager's categorical cache/hierarchical flags).
    BayesianOptimizer bo;
    bo.set_tune_x3(false);
    bo.set_tune_x4(false);
    bo.set_tune_x5(false);
    bo.set_tune_x6(false);
    unsigned rng = 777;
    double x0 = 0.05, x1 = 0.95, x2 = 0.0, x3 = 0.0, x4 = 0.0, x5 = 0.0,
           x6 = 0.0;
    bo.AddSample(x0, x1, x2, x3, x4, x5, x6, Surface(x0, x1, &rng));
    for (int round = 0; round < 30; ++round) {
      bo.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6);
      double s = Surface(x0, x1, &rng) * (x2 >= 0.5 ? 1.25 : 1.0);
      bo.AddSample(x0, x1, x2, x3, x4, x5, x6, s);
    }
    double bx0, bx1, bx2, bx3, bx4, bx5, bx6, best;
    bo.Best(&bx0, &bx1, &bx2, &bx3, &bx4, &bx5, &bx6, &best);
    std::printf("categorical best=%.3e at (%.2f, %.2f, cat=%.0f)\n", best,
                bx0, bx1, bx2);
    if (bx2 < 0.5) {
      std::printf("FAIL: categorical knob did not converge to the better "
                  "arm\n");
      return 1;
    }
    if (best < 0.8 * 1.25e9) {
      std::printf("FAIL: categorical surface peak not approached\n");
      return 1;
    }
  }
  {
    // Hierarchical arm: same surface, but the x3=1 arm (hierarchical
    // allreduce on a multi-host topology) scores 30% higher everywhere.
    // With the knob tunable, the optimizer must converge onto it.
    BayesianOptimizer bo;
    bo.set_tune_x4(false);
    bo.set_tune_x5(false);
    bo.set_tune_x6(false);
    unsigned rng = 4242;
    double x0 = 0.05, x1 = 0.95, x2 = 0.0, x3 = 0.0, x4 = 0.0, x5 = 0.0,
           x6 = 0.0;
    bo.AddSample(x0, x1, x2, x3, x4, x5, x6, Surface(x0, x1, &rng));
    for (int round = 0; round < 40; ++round) {
      bo.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6);
      double s = Surface(x0, x1, &rng) * (x3 >= 0.5 ? 1.3 : 1.0);
      bo.AddSample(x0, x1, x2, x3, x4, x5, x6, s);
    }
    double bx0, bx1, bx2, bx3, bx4, bx5, bx6, best;
    bo.Best(&bx0, &bx1, &bx2, &bx3, &bx4, &bx5, &bx6, &best);
    std::printf("hier best=%.3e at (%.2f, %.2f, cat=%.0f, hier=%.0f)\n",
                best, bx0, bx1, bx2, bx3);
    if (bx3 < 0.5) {
      std::printf("FAIL: hierarchical knob did not converge to the better "
                  "arm\n");
      return 1;
    }
    if (best < 0.8 * 1.3e9) {
      std::printf("FAIL: hierarchical surface peak not approached\n");
      return 1;
    }
  }
  {
    // Wire-compression arm: a 3-level categorical where the middle level
    // (bf16, x4=0.5) is best — halved wire bytes without int8's decode
    // cost on this synthetic surface.  The optimizer must find the
    // interior level, which a binary knob could not express.
    BayesianOptimizer bo;
    bo.set_tune_x5(false);
    bo.set_tune_x6(false);
    unsigned rng = 31337;
    double x0 = 0.05, x1 = 0.95, x2 = 0.0, x3 = 0.0, x4 = 0.0, x5 = 0.0,
           x6 = 0.0;
    bo.AddSample(x0, x1, x2, x3, x4, x5, x6, Surface(x0, x1, &rng));
    for (int round = 0; round < 40; ++round) {
      bo.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6);
      double mult = x4 < 0.25 ? 1.0 : (x4 < 0.75 ? 1.35 : 1.15);
      bo.AddSample(x0, x1, x2, x3, x4, x5, x6, Surface(x0, x1, &rng) * mult);
    }
    double bx0, bx1, bx2, bx3, bx4, bx5, bx6, best;
    bo.Best(&bx0, &bx1, &bx2, &bx3, &bx4, &bx5, &bx6, &best);
    std::printf("wire best=%.3e at (%.2f, %.2f, wire=%.2f)\n", best, bx0,
                bx1, bx4);
    if (bx4 < 0.25 || bx4 >= 0.75) {
      std::printf("FAIL: wire knob did not converge to the bf16 level\n");
      return 1;
    }
    if (best < 0.8 * 1.35e9) {
      std::printf("FAIL: wire surface peak not approached\n");
      return 1;
    }
  }
  {
    // Device-codec arm: a 4-level categorical {none, int8, int4, int8g}
    // where the interior int4 level (x5=2/3 — the deepest wire cut) is
    // best: 30% over none, ahead of int8's 15% and int8g's 20% on this
    // synthetic surface.  The optimizer must land on the interior codec
    // level, which the old binary knob could not express.
    BayesianOptimizer bo;
    bo.set_tune_x3(false);
    bo.set_tune_x4(false);
    bo.set_tune_x6(false);
    unsigned rng = 90210;
    double x0 = 0.05, x1 = 0.95, x2 = 0.0, x3 = 0.0, x4 = 0.0, x5 = 0.0,
           x6 = 0.0;
    bo.AddSample(x0, x1, x2, x3, x4, x5, x6, Surface(x0, x1, &rng));
    for (int round = 0; round < 60; ++round) {
      bo.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6);
      double mult = x5 < 1.0 / 6.0
                        ? 1.0
                        : (x5 < 0.5 ? 1.15 : (x5 < 5.0 / 6.0 ? 1.3 : 1.2));
      bo.AddSample(x0, x1, x2, x3, x4, x5, x6, Surface(x0, x1, &rng) * mult);
    }
    double bx0, bx1, bx2, bx3, bx4, bx5, bx6, best;
    bo.Best(&bx0, &bx1, &bx2, &bx3, &bx4, &bx5, &bx6, &best);
    std::printf("qdev best=%.3e at (%.2f, %.2f, qdev=%.2f)\n", best, bx0,
                bx1, bx5);
    if (bx5 < 0.5 || bx5 >= 5.0 / 6.0) {
      std::printf("FAIL: qdev knob did not converge to the int4 level\n");
      return 1;
    }
    if (best < 0.8 * 1.3e9) {
      std::printf("FAIL: qdev surface peak not approached\n");
      return 1;
    }
  }
  {
    // Device-schedule arm: a 3-level categorical {ring, bidi, torus} where
    // the middle bidi level (x6=0.5) is best — both ICI directions without
    // torus's second-axis latency on this synthetic surface.  Tuned
    // jointly with an active 4-level codec knob to exercise the full
    // qdev x schedule grid.
    BayesianOptimizer bo;
    bo.set_tune_x3(false);
    bo.set_tune_x4(false);
    unsigned rng = 60606;
    double x0 = 0.05, x1 = 0.95, x2 = 0.0, x3 = 0.0, x4 = 0.0, x5 = 0.0,
           x6 = 0.0;
    bo.AddSample(x0, x1, x2, x3, x4, x5, x6, Surface(x0, x1, &rng));
    for (int round = 0; round < 60; ++round) {
      bo.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6);
      double cmult = x5 < 1.0 / 6.0 ? 1.0 : 1.2;
      double smult = x6 < 0.25 ? 1.0 : (x6 < 0.75 ? 1.3 : 1.1);
      bo.AddSample(x0, x1, x2, x3, x4, x5, x6,
                   Surface(x0, x1, &rng) * cmult * smult);
    }
    double bx0, bx1, bx2, bx3, bx4, bx5, bx6, best;
    bo.Best(&bx0, &bx1, &bx2, &bx3, &bx4, &bx5, &bx6, &best);
    std::printf("sched best=%.3e at (%.2f, %.2f, qdev=%.2f, sched=%.2f)\n",
                best, bx0, bx1, bx5, bx6);
    if (bx6 < 0.25 || bx6 >= 0.75) {
      std::printf("FAIL: schedule knob did not converge to the bidi "
                  "level\n");
      return 1;
    }
    if (bx5 < 1.0 / 6.0) {
      std::printf("FAIL: codec knob did not engage alongside the "
                  "schedule\n");
      return 1;
    }
    if (best < 0.8 * 1.2 * 1.3e9) {
      std::printf("FAIL: schedule surface peak not approached\n");
      return 1;
    }
  }
  {
    // Data-plane arm, pinned: x7 defaults off (the 7-coordinate
    // compatibility overloads record every sample at x7 = 0), and stays
    // off under set_tune_x7(false) even for 8-coordinate callers — the EI
    // search must never leave the eager level.
    BayesianOptimizer bo;
    bo.set_tune_x3(false);
    bo.set_tune_x4(false);
    bo.set_tune_x5(false);
    bo.set_tune_x6(false);
    bo.set_tune_x7(false);
    unsigned rng = 1122;
    double x0 = 0.05, x1 = 0.95, x2 = 0.0, x3 = 0.0, x4 = 0.0, x5 = 0.0,
           x6 = 0.0, x7 = 0.0;
    bo.AddSample(x0, x1, x2, x3, x4, x5, x6, x7, Surface(x0, x1, &rng));
    for (int round = 0; round < 20; ++round) {
      bo.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6, &x7);
      if (x7 >= 0.5) {
        std::printf("FAIL: pinned x7 knob was explored\n");
        return 1;
      }
      bo.AddSample(x0, x1, x2, x3, x4, x5, x6, x7, Surface(x0, x1, &rng));
    }
  }
  {
    // Data-plane arm, active: the x7=1 arm (gspmd — collectives inserted
    // and overlapped by the compiler) scores 20% higher everywhere on
    // this synthetic surface.  With set_tune_x7(true) the optimizer must
    // converge onto the gspmd level.
    BayesianOptimizer bo;
    bo.set_tune_x3(false);
    bo.set_tune_x4(false);
    bo.set_tune_x5(false);
    bo.set_tune_x6(false);
    bo.set_tune_x7(true);
    unsigned rng = 20177;
    double x0 = 0.05, x1 = 0.95, x2 = 0.0, x3 = 0.0, x4 = 0.0, x5 = 0.0,
           x6 = 0.0, x7 = 0.0;
    bo.AddSample(x0, x1, x2, x3, x4, x5, x6, x7, Surface(x0, x1, &rng));
    for (int round = 0; round < 40; ++round) {
      bo.Suggest(&x0, &x1, &x2, &x3, &x4, &x5, &x6, &x7);
      double s = Surface(x0, x1, &rng) * (x7 >= 0.5 ? 1.2 : 1.0);
      bo.AddSample(x0, x1, x2, x3, x4, x5, x6, x7, s);
    }
    double bx0, bx1, bx2, bx3, bx4, bx5, bx6, bx7, best;
    bo.Best(&bx0, &bx1, &bx2, &bx3, &bx4, &bx5, &bx6, &bx7, &best);
    std::printf("plane best=%.3e at (%.2f, %.2f, plane=%.0f)\n", best, bx0,
                bx1, bx7);
    if (bx7 < 0.5) {
      std::printf("FAIL: plane knob did not converge to the gspmd arm\n");
      return 1;
    }
    if (best < 0.8 * 1.2e9) {
      std::printf("FAIL: plane surface peak not approached\n");
      return 1;
    }
  }
  std::printf("PASS\n");
  return 0;
}
