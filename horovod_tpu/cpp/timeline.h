// Chrome about:tracing timeline (reference: horovod/common/timeline.h —
// Timeline + TimelineWriter with a dedicated writer thread; SURVEY.md §5).
#pragma once

#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

namespace hvdtpu {

class Timeline {
 public:
  ~Timeline();
  void Start(const std::string& path, bool mark_cycles);
  void Stop();
  bool enabled() const { return enabled_; }

  // Rank recorded in the CLOCK_SYNC anchor event Start() emits, which
  // tools/merge_timeline.py uses to align per-rank traces (each rank's
  // ts is relative to its own Start; the anchor carries wall-clock us).
  void SetRank(int rank) { rank_ = rank; }

  // Phase events keyed by tensor name (B/E pairs on a per-tensor lane).
  void Begin(const std::string& tensor, const std::string& phase);
  void End(const std::string& tensor, const std::string& phase);
  void Instant(const std::string& name);
  // Instant with a caller-formed JSON object as Chrome-trace "args" (the
  // ABORT marker carries culprit metadata this way).
  void Instant(const std::string& name, const std::string& args_json);
  void MarkCycle();

 private:
  void Emit(std::string json_line);
  void WriterLoop();
  int64_t NowUs() const;

  bool enabled_ = false;
  bool mark_cycles_ = false;
  int rank_ = -1;
  double t0_ = 0.0;
  FILE* file_ = nullptr;
  bool first_event_ = true;
  std::thread writer_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  bool shutdown_ = false;
};

}  // namespace hvdtpu
