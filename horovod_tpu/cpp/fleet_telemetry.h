// Fleet telemetry plane (protocol v11; docs/observability.md sixth pillar):
// mergeable histogram sketches piggybacked on CYCLE frames, a coordinator-
// side multi-resolution history ring, goodput accounting, and a streaming
// anomaly sentinel.
//
// The metrics registry's power-of-two-bucket histograms are already
// mergeable (bucket counts add), so a "sketch" is nothing more than a
// non-atomic snapshot of those buckets, delta/varint-compressed onto the
// wire.  Workers ship their cumulative sketch on every CYCLE frame; host
// leaders (protocol v9 tree) sum child sketches into the aggregate frame so
// coordinator inbound stays O(hosts); the coordinator keeps the last-known
// sketch per source and sums them into true fleet histograms on demand.
// Because every sketch is cumulative-since-init and sources are replaced
// (never added twice), the fleet sum is bucket-exact equal to an offline
// merge of the per-rank HOROVOD_METRICS_FILE dumps.
//
// Cost discipline matches metrics.h / flight_recorder.h: every emit site is
// gated by one relaxed bool load (FleetTelemetryOn), encoding runs at most
// once per negotiation cycle on buckets already in cache, and the sentinel
// ticks at ~1 Hz on the coordinator only.
#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "metrics.h"

namespace hvdtpu {

// One mergeable histogram: the plain-integer image of metrics.h Histogram
// (same 28 power-of-two microsecond buckets, same [0,1us) bucket 0).
struct HistogramSketch {
  int64_t count = 0;
  int64_t sum_us = 0;
  int64_t buckets[Histogram::kNumBuckets] = {0};

  void Clear();
  // Snapshot-add a live registry histogram (relaxed loads — cumulative
  // counters, so a torn read is at worst one observation late).
  void AddFrom(const Histogram& h);
  void Merge(const HistogramSketch& o);
  // Conservative bucket-upper-bound quantile, mirroring
  // Histogram::QuantileUs so fleet p99s read on the same scale.
  int64_t QuantileUs(double q) const;
  // Same shape as Histogram::Json: {"count","sum_us","p50_us","p99_us",
  // "buckets"} — the Prometheus renderer treats fleet and local
  // histograms identically.
  std::string Json() const;
};

// The full per-source sketch riding a CYCLE frame: the four fleet latency
// families plus per-tenant negotiation wait.
struct FleetSketch {
  HistogramSketch negotiation_wait;
  HistogramSketch ring_hop;
  HistogramSketch step_time;
  HistogramSketch shm_fence;
  std::map<int, HistogramSketch> tenants;  // psid -> negotiation wait

  void Clear();
  void Merge(const FleetSketch& o);
  // Snapshot this process's registry (the worker-side emit path).
  void CaptureLocal();
  // Wire codec (sketch-v1): u8 version, four histograms, varint tenant
  // count + per-tenant psid/histogram.  Histograms are varint(count),
  // varint(sum_us), then 28 buckets delta-coded between consecutive
  // buckets (zigzag varint) — steady-state buckets are heavily
  // front-loaded, so deltas keep the trailer at tens of bytes.
  std::string Encode() const;
  // Replaces contents; false = malformed (caller drops the sketch, never
  // the frame).
  bool Decode(const char* data, size_t len);
  // {"negotiation_wait_us":{...},"ring_hop_us":{...},"step_time_us":{...},
  //  "shm_fence_us":{...},"tenants":{"psid":{...}}}
  std::string Json() const;
};

struct FleetTelemetryGate {
  std::atomic<bool> enabled{true};
};

FleetTelemetryGate& GlobalFleetTelemetry();

inline bool FleetTelemetryOn() {
  return GlobalFleetTelemetry().enabled.load(std::memory_order_relaxed);
}

// Arms the plane from HOROVOD_FLEET_TELEMETRY (default on; sketches only
// ride frames when the metrics plane is also enabled) and resets the
// history/sentinel state.  Reads HOROVOD_SENTINEL_ZSCORE for the
// detection threshold.  Called from hvd_init; elastic re-init re-arms.
void InitFleetTelemetry();

// One coordinator tick (rate-limited internally to ~1 Hz): append a
// history sample from the fleet sketch + the coordinator's data-plane
// byte counters, recompute goodput from the step-trace fleet phase
// totals, and run the sentinel over the new sample.  `wire_bytes` /
// `raw_bytes` are cumulative data-plane totals (wire < raw exactly when
// compression engaged).
void FleetTelemetryTick(const FleetSketch& fleet, int64_t wire_bytes,
                        int64_t raw_bytes);

// Multi-resolution history as one JSON object (fleethistory-v1): tier 0
// holds 1 s samples, tier 1 10 s, tier 2 60 s, each ring-bounded, plus
// the sentinel's anomaly log.  Sample rows are [ts_us, step_p99_us,
// neg_p99_us, goodput_ppm, wire_ratio_ppm, steps].
std::string FleetHistoryJson();

// The sentinel's anomaly log as a JSON array fragment ("[...]"), newest
// last, each {"seq","ts_us","kind","rank","value","baseline","score"}.
// Spliced into PolicyStatusJson so the autopilot sees advisories ahead of
// the consecutive-window eviction rule.
std::string FleetAnomaliesJson();

// Anomalies emitted since init (monotone; mirrors the
// sentinel_anomalies_total counter without requiring MetricsOn).
int64_t FleetAnomalyCount();

// Test-only: disarm and clear history/sentinel state.
void ResetFleetTelemetryForTest();

}  // namespace hvdtpu
