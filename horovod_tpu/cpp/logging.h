// Leveled stderr logging (reference: horovod/common/logging.h LOG macros).
#pragma once

#include <chrono>
#include <cstdio>
#include <ctime>
#include <sstream>
#include <string>

namespace hvdtpu {

enum LogLevel { TRACE = 0, DEBUG = 1, INFO = 2, WARNING = 3, ERROR = 4, FATAL = 5 };

int GetLogLevel();
void SetLogLevel(int level);

class LogMessage {
 public:
  LogMessage(int level, const char* file, int line) : level_(level) {
    stream_ << "[hvd-tpu-core] [" << LevelName(level) << "] ";
    (void)file;
    (void)line;
  }
  ~LogMessage() {
    if (level_ >= GetLogLevel()) {
      stream_ << "\n";
      std::fputs(stream_.str().c_str(), stderr);
      std::fflush(stderr);
    }
  }
  std::ostringstream& stream() { return stream_; }

 private:
  static const char* LevelName(int level) {
    switch (level) {
      case TRACE: return "trace";
      case DEBUG: return "debug";
      case INFO: return "info";
      case WARNING: return "warning";
      case ERROR: return "error";
      default: return "fatal";
    }
  }
  int level_;
  std::ostringstream stream_;
};

#define HVD_LOG(level) ::hvdtpu::LogMessage(::hvdtpu::level, __FILE__, __LINE__).stream()

}  // namespace hvdtpu
