#include "socket_controller.h"

#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>

#include <algorithm>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <thread>
#include <unordered_map>

#include "fault_injection.h"
#include "logging.h"
#include "step_trace.h"

namespace hvdtpu {

namespace {

constexpr double kConnectTimeoutS = 60.0;
// Rendezvous HELLO preamble.  The magic rejects stray/garbage connections;
// the version must be bumped whenever the negotiation wire format changes
// (requests, responses, cache frames) so mixed-build jobs fail with a
// named error instead of desynchronized garbled frames.
constexpr int32_t kProtocolMagic = 0x48565354;  // "HVST"
// v12: adaptive-depth leader tree — the rendezvous book's tree trailer
// grows the coordinator's agreed [i32 fanout][i32 depth] after the v9
// ctrl_tree bit, mid-level super-leaders merge downstream leaders' [-3]
// aggregates into one frame upward, and a departing leader's BYE (direct
// or forwarded as an aggregate rest) releases its whole SUBTREE at the
// coordinator (v9 released only the leader's host).  v11 added the
// fleet-telemetry sketch section — a length-prefixed cumulative
// histogram sketch between the cached pairs and the full requests of every
// CYCLE frame, after the [-3] sentinel of leader aggregates (host-summed),
// and trailing upward BYEs (the rank's FINAL sketch, so fleet histograms
// stay bucket-exact across clean shutdown).  v10 added the step-id trailer
// on RESPONSES + the marker-2 step snapshot on CYCLE frames; v9 the
// leader-tree control plane — the coordinator-authoritative ctrl_tree
// bit trailing the rendezvous book, the [-3] leader aggregate frame in the
// cycle position, and the culprit rank trailing failure FINs (v8 added
// ABORT control frames + the worker failure FIN sentinel, v7 the metrics
// snapshot trailer on worker CYCLE frames, v6 the wire_comp codec byte in
// responses, v5 the host key in the rendezvous HELLO/book + the hier bit
// in responses)
constexpr int32_t kProtocolVersion = 12;
// Mesh-HELLO psid for child->leader ctrl-tree links: negative, so it can
// never collide with a real process-set id (those start at 1) and always
// lands in the pending-channel stash when it races a mesh establishment.
constexpr int32_t kCtrlTreePsid = -7;
// v11: worker/leader sketch sections are THROTTLED to this interval — the
// coordinator only folds sketches at its 1 Hz tick, sketches are cumulative
// (last-known is always a valid snapshot), and encoding 4 series x 28
// buckets per negotiation cycle is pure waste at kHz cycle rates.  Frames
// in between carry an empty section, which ReadFleetSketch ignores,
// preserving the receiver's last-known.  BYE finals bypass the throttle.
constexpr double kFleetEncodeIntervalS = 1.0;

// Frame tags: catch mesh desync (a rank consuming a frame meant for another
// op/step) immediately instead of corrupting buffers.
constexpr int32_t kTagReduceScatter = 0x1000;
constexpr int32_t kTagReduceScatterOp = 0x1800;
constexpr int32_t kTagAllgatherPhase = 0x2000;
constexpr int32_t kTagAllgather = 0x4000;
constexpr int32_t kTagAllgatherSize = 0x4800;
constexpr int32_t kTagBroadcast = 0x5000;
constexpr int32_t kTagBroadcastChain = 0x5800;
constexpr int32_t kTagAlltoall = 0x6000;
constexpr int32_t kTagAlltoallSize = 0x6800;
constexpr int32_t kTagBarrier = 0x7000;
// Shared-memory plane phase fences (shm_plane.h): size exchange, write
// done, segments reduced, read done, region grow, open verdict.
constexpr int32_t kTagShmSize = 0x8000;
constexpr int32_t kTagShmWrite = 0x9000;
constexpr int32_t kTagShmMid = 0xA000;
constexpr int32_t kTagShmRead = 0xB000;
constexpr int32_t kTagShmGrow = 0xC000;
constexpr int32_t kTagShmOpen = 0xD000;
constexpr int32_t kTagShmVerdict = 0xE000;
// Hierarchical allreduce: per-host subgroup phase fences (write done,
// segments reduced, leader ring done, result read back, region grow) plus
// the whole-set open/verdict handshake at topology setup.
constexpr int32_t kTagHierWrite = 0xF000;
constexpr int32_t kTagHierMid = 0xF800;
constexpr int32_t kTagHierDone = 0x10000;
constexpr int32_t kTagHierRead = 0x10800;
constexpr int32_t kTagHierGrow = 0x11000;
constexpr int32_t kTagHierOpen = 0x11800;
constexpr int32_t kTagHierVerdict = 0x12000;
// Compressed-ring phases (wire_codec.h).  Distinct from the raw-ring tags
// so a codec split across ranks — which the coordinator's wire_comp bit
// makes impossible by construction — would still fail fast as a header
// mismatch rather than decode garbage.
constexpr int32_t kTagCompReduceScatter = 0x12800;
constexpr int32_t kTagCompAllgather = 0x13000;
// Fast-abort control frame (protocol v8): rides the ctrl channel in the
// responses position as [-2][kTagAbort][reason][culprit_rank][culprit_host]
// [f64 send wallclock]; the tag double-checks the sentinel parse.
constexpr int32_t kTagAbort = 0x13800;
// Flight-recorder digest (abort-time forensics): rides the ctrl channel in
// the cycle position as [-4][kTagFlightDigest][rank][n]
// [n x (i64 ts_us, i64 seq, i32 type, i32 tid, i32 a, i64 b)].  Best-effort
// and bounded by the abort budget — a dropped digest never delays the abort.
constexpr int32_t kTagFlightDigest = 0x14000;
// Last-N window a digest carries: enough causal context around the collapse
// without bloating the abort exchange (48 bytes/event -> ~6 KiB per rank).
constexpr int kFlightDigestEvents = 128;

// Fleet-autopilot decision action codes, carried in kFlightAutopilot
// events (a = action, b = rank) and on the policy channel's DECISION
// command.  Mirrored by horovod_tpu/runner/autopilot.py and decoded by
// tools/postmortem.py — keep the three in sync.
constexpr int kAutopilotActEvict = 1;
constexpr int kAutopilotActScaleUp = 2;
constexpr int kAutopilotActReadmit = 3;

// Bound on buffered, un-newline-terminated policy-channel input: the
// driver sends short single-line commands, so anything larger is garbage.
constexpr size_t kPolicyMaxLine = 65536;

// Broadcasts at least this large take the pipelined chain instead of the
// binomial tree.  A protocol constant: the algorithm choice must agree on
// every rank, so only nbytes/m and the pipelining-enabled switch may gate
// it — per-rank CHUNK SIZES may differ (the chain is a raw byte stream),
// but HOROVOD_RING_CHUNK_BYTES=0 (pipelining off) selects different wire
// protocols and must be uniform across ranks, as documented in socketio.h.
constexpr int64_t kBroadcastChainBytes = 1 << 20;

// Wall-clock seconds (system_clock): the abort-propagation latency spans
// PROCESSES, so the monotonic clock (per-process epoch) cannot measure it.
double WallSeconds() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

}  // namespace

thread_local int64_t SocketController::current_seq_ = -1;

SocketController::SocketController(const CoreConfig& cfg)
    : Controller(cfg), cache_(cfg.cache_capacity) {
  // HOROVOD_RING_CHUNK_BYTES (0 disables pipelining; clamped to 1 GiB —
  // the u32 chunk-frame length prefix cannot carry more).  Default lives
  // on the member initializer in socket_controller.h.
  if (const char* env = ::getenv("HOROVOD_RING_CHUNK_BYTES")) {
    char* end = nullptr;
    long long v = std::strtoll(env, &end, 10);
    if (end && *end == '\0' && v >= 0) {
      ring_chunk_bytes_ = std::min<long long>(v, 1LL << 30);
    }
  }
  // HOROVOD_WIRE_COMPRESSION_MIN_BYTES: payload floor below which the
  // coordinator demotes the wire codec to none (default 64 KiB).
  if (const char* env = ::getenv("HOROVOD_WIRE_COMPRESSION_MIN_BYTES")) {
    char* end = nullptr;
    long long v = std::strtoll(env, &end, 10);
    if (end && *end == '\0' && v >= 0) wire_comp_floor_ = v;
  }
  // Metrics-plane knobs (coordinator-side straggler attribution).
  if (const char* env = ::getenv("HOROVOD_METRICS_REPORT_SECONDS")) {
    char* end = nullptr;
    double v = std::strtod(env, &end);
    if (end && *end == '\0' && v > 0) metrics_report_s_ = v;
  }
  if (const char* env = ::getenv("HOROVOD_STRAGGLER_SKEW")) {
    char* end = nullptr;
    double v = std::strtod(env, &end);
    if (end && *end == '\0' && v > 1.0) straggler_skew_ = v;
  }
  if (const char* env = ::getenv("HOROVOD_STRAGGLER_MIN_MS")) {
    char* end = nullptr;
    double v = std::strtod(env, &end);
    if (end && *end == '\0' && v >= 0) straggler_min_us_ = v * 1000.0;
  }
  // Fast-abort propagation bound: how long a rank waits for the
  // coordinator's ABORT (culprit attribution) after observing a local
  // failure, before failing with its own less-specific reason.
  if (const char* env = ::getenv("HOROVOD_ABORT_PROPAGATION_TIMEOUT")) {
    char* end = nullptr;
    double v = std::strtod(env, &end);
    if (end && *end == '\0' && v > 0) abort_timeout_s_ = v;
  }
  // Rendezvous retry policy (worker->coordinator connect): attempts and
  // the exponential-backoff base; the overall budget stays bounded by
  // kConnectTimeoutS regardless.
  if (const char* env = ::getenv("HOROVOD_RENDEZVOUS_RETRIES")) {
    char* end = nullptr;
    long long v = std::strtoll(env, &end, 10);
    if (end && *end == '\0' && v > 0) {
      rendezvous_retries_ = static_cast<int>(std::min<long long>(v, 10000));
    }
  }
  if (const char* env = ::getenv("HOROVOD_RENDEZVOUS_BACKOFF_BASE_MS")) {
    char* end = nullptr;
    long long v = std::strtoll(env, &end, 10);
    if (end && *end == '\0' && v >= 0) rendezvous_backoff_base_ms_ = v;
  }
  // Leader-tree control plane (protocol v9).  Only the COORDINATOR's mode
  // matters — its decision rides the rendezvous book — but every rank
  // parses the env for symmetry; unrecognized values behave like "auto".
  if (const char* env = ::getenv("HOROVOD_CONTROL_TREE")) {
    std::string v = env;
    if (v == "auto" || v == "on" || v == "off") {
      control_tree_mode_ = v;
    } else if (!v.empty()) {
      HVD_LOG(WARNING) << "unrecognized HOROVOD_CONTROL_TREE=" << v
                       << " (expected auto|on|off); using auto";
    }
  }
  // v12 adaptive depth.  Fanout: the per-node fan-in bound the clustering
  // pass targets (min 2 — a 1-ary tree is a chain).  Depth: 0 = auto
  // (cluster until the bound holds), else force exactly this many levels
  // (2 = the v9 flat-leader shape).  Coordinator-authoritative, like the
  // mode: the agreed values ride the rendezvous book.
  if (const char* env = ::getenv("HOROVOD_CTRL_TREE_FANOUT")) {
    char* end = nullptr;
    long long v = std::strtoll(env, &end, 10);
    if (end && *end == '\0' && v >= 2) {
      ctrl_tree_fanout_ = static_cast<int>(std::min<long long>(v, 0x800));
    } else if (*env) {
      HVD_LOG(WARNING) << "ignoring HOROVOD_CTRL_TREE_FANOUT=" << env
                       << " (expected an integer >= 2)";
    }
  }
  if (const char* env = ::getenv("HOROVOD_CONTROL_TREE_DEPTH")) {
    std::string v = env;
    if (v == "auto" || v == "0") {
      ctrl_tree_depth_ = 0;
    } else {
      char* end = nullptr;
      long long d = std::strtoll(env, &end, 10);
      if (end && *end == '\0' && d >= 2 && d <= 8) {
        ctrl_tree_depth_ = static_cast<int>(d);
      } else if (!v.empty()) {
        HVD_LOG(WARNING) << "ignoring HOROVOD_CONTROL_TREE_DEPTH=" << v
                         << " (expected auto or an integer in [2, 8])";
      }
    }
  }
  // Rendezvous acceptor shards: N threads accepting HELLOs concurrently on
  // the coordinator's listener, so a thundering herd of np connects drains
  // in parallel instead of through one serial accept loop.
  if (const char* env = ::getenv("HOROVOD_RENDEZVOUS_ACCEPTORS")) {
    char* end = nullptr;
    long long v = std::strtoll(env, &end, 10);
    if (end && *end == '\0' && v > 0) {
      rendezvous_acceptors_ = static_cast<int>(std::min<long long>(v, 64));
    }
  }
  if (is_coordinator()) {
    cluster_.resize(cfg.size);
    announce_prev_.assign(cfg.size, {0, 0});
    announce_lag_.reserve(cfg.size);
    for (int i = 0; i < cfg.size; ++i) {
      announce_lag_.push_back(std::make_unique<Histogram>());
    }
  }
}

SocketController::~SocketController() { Shutdown(); }

Status SocketController::Initialize() {
  // Frame-tag families are spaced 0x800 apart and several data-plane
  // algorithms encode a step/member index into the tag — a mesh of 0x800+
  // members would alias the next family and silently weaken the desync
  // check the tags exist for.
  if (cfg_.size >= 0x800) {
    return Status::Error(
        StatusCode::INVALID_ARGUMENT,
        "socket controller supports at most 2047 ranks (frame-tag step "
        "encoding); shard the job into process sets or hosts");
  }
  process_sets_.InitGlobal(cfg_.size);
  // Every rank owns a mesh listener on an ephemeral port; the coordinator
  // brokers the address book (the Gloo rendezvous-store analog).
  if (!data_listener_.Listen("0.0.0.0", 0)) {
    return Status::Error(StatusCode::PRECONDITION_ERROR,
                         "failed to open mesh data listener");
  }
  peer_socks_.resize(cfg_.size);
  std::vector<std::string> addrs(cfg_.size);
  std::vector<int> ports(cfg_.size, 0);
  std::vector<std::string> hosts(cfg_.size);
  ports[cfg_.rank] = data_listener_.port();
  hosts[cfg_.rank] = HostKey(cfg_.rank, cfg_.size);
  // v9: coordinator-authoritative leader-tree verdict, carried in the book.
  bool ctrl_tree_decision = false;

  if (is_coordinator()) {
    if (!listener_.Listen("0.0.0.0", cfg_.rendezvous_port)) {
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "coordinator failed to listen on port " +
                               std::to_string(cfg_.rendezvous_port));
    }
    ctrl_socks_.resize(cfg_.size);
    // Sharded rendezvous (protocol v9): N acceptor threads drain the HELLO
    // herd concurrently off one non-blocking listener.  All book-keeping
    // happens under rv_mu; per-thread fatal findings land in rv_err and
    // stop every shard.  The worker-side exponential backoff (PR 5)
    // absorbs whatever the backlog still drops.
    const int acceptors =
        std::max(1, std::min(rendezvous_acceptors_, cfg_.size - 1));
    std::mutex rv_mu;
    std::string rv_err;
    int rv_needed = cfg_.size - 1;
    const double deadline = MonotonicSeconds() + kConnectTimeoutS;
    auto accept_shard = [&]() {
      while (true) {
        {
          std::lock_guard<std::mutex> l(rv_mu);
          if (rv_needed <= 0 || !rv_err.empty()) return;
        }
        if (MonotonicSeconds() > deadline) return;
        Socket s = listener_.Accept(0.2);
        if (!s.valid()) continue;
        // Bound the HELLO read: a connect-and-stay-silent stray must not
        // block this shard past the rendezvous deadline.
        s.SetRecvTimeout(5.0);
        std::string hello;
        if (!s.RecvFrame(&hello)) {
          HVD_LOG(WARNING) << "dropping silent/broken rendezvous connection "
                           << "from " << s.PeerAddr();
          continue;
        }
        Reader r(hello);
        int32_t magic = r.GetI32();
        if (magic != kProtocolMagic) {
          // Not one of ours (port scanner, stale client, or a pre-v2 build
          // whose HELLO starts with its rank): drop and keep waiting rather
          // than failing the whole rendezvous.
          HVD_LOG(WARNING)
              << "dropping rendezvous connection from " << s.PeerAddr()
              << " with bad protocol magic (stray client, or a worker from "
                 "an older horovod_tpu build)";
          continue;
        }
        int32_t version = r.GetI32();
        if (version != kProtocolVersion) {
          std::lock_guard<std::mutex> l(rv_mu);
          if (rv_err.empty()) {
            rv_err = "protocol version mismatch: coordinator v" +
                     std::to_string(kProtocolVersion) + ", worker v" +
                     std::to_string(version) +
                     " — all ranks must run the same horovod_tpu build";
          }
          return;
        }
        int rank = r.GetI32();
        int data_port = r.GetI32();
        std::string host_key = r.GetString();
        std::lock_guard<std::mutex> l(rv_mu);
        if (!r.ok() || rank <= 0 || rank >= cfg_.size ||
            ctrl_socks_[rank].valid()) {
          if (rv_err.empty()) rv_err = "bad HELLO from worker";
          return;
        }
        if (FaultInjectionOn()) {
          // Site rank = the REMOTE worker being accepted; drop closes its
          // connection so the worker exercises the rendezvous retry/backoff.
          FaultAction fa = FaultCheck(kFaultRendezvousAccept, rank);
          if (fa == FaultAction::kDrop || fa == FaultAction::kTruncate) {
            s.Close();
            continue;
          }
        }
        addrs[rank] = s.PeerAddr();
        ports[rank] = data_port;
        hosts[rank] = host_key;
        s.SetRecvTimeout(0);  // ctrl-channel reads are blocking again
        ctrl_socks_[rank] = std::move(s);
        --rv_needed;
      }
    };
    std::vector<std::thread> shards;
    shards.reserve(acceptors - 1);
    for (int i = 1; i < acceptors; ++i) shards.emplace_back(accept_shard);
    accept_shard();
    for (auto& t : shards) t.join();
    if (!rv_err.empty()) {
      return Status::Error(rv_err.find("mismatch") != std::string::npos
                               ? StatusCode::PRECONDITION_ERROR
                               : StatusCode::INVALID_ARGUMENT,
                           rv_err);
    }
    if (rv_needed > 0) {
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "rendezvous timeout waiting for workers");
    }
    // Broadcast the address book over the ctrl channel.  Host keys ride
    // along so every rank sees the SAME host grouping — workers cannot
    // derive it from addresses (their view of rank 0's address differs
    // from the coordinator's own).  v9 appends the coordinator's
    // authoritative ctrl_tree verdict: divergent HOROVOD_CONTROL_TREE
    // envs cannot split the ring into mixed flat/tree halves.
    const bool tree_on = DecideCtrlTree(control_tree_mode_, hosts);
    Writer book;
    for (int rank = 0; rank < cfg_.size; ++rank) {
      book.PutString(addrs[rank]);
      book.PutI32(ports[rank]);
      book.PutString(hosts[rank]);
    }
    book.PutI32(tree_on ? 1 : 0);
    // v12: the agreed fanout/depth ride behind the verdict so divergent
    // HOROVOD_CTRL_TREE_FANOUT / HOROVOD_CONTROL_TREE_DEPTH envs cannot
    // make ranks compute different topologies.
    book.PutI32(ctrl_tree_fanout_);
    book.PutI32(ctrl_tree_depth_);
    for (int rank = 1; rank < cfg_.size; ++rank) {
      ctrl_msgs_sent_.fetch_add(1, std::memory_order_relaxed);
      ctrl_sent_.fetch_add(static_cast<int64_t>(book.data().size()),
                           std::memory_order_relaxed);
      if (!ctrl_socks_[rank].SendFrame(book.data())) {
        return Status::Error(StatusCode::PRECONDITION_ERROR,
                             "failed to send address book to rank " +
                                 std::to_string(rank));
      }
    }
    ctrl_tree_decision = tree_on;
  } else {
    // Rendezvous with exponential backoff + deterministic jitter: refused/
    // dropped connections during startup (coordinator not listening yet,
    // an accept-side injected drop) are RETRYABLE; permission and
    // address-family errors are fatal immediately so a misconfigured job
    // fails in milliseconds, not after the full connect budget.  One
    // attempt spans connect + HELLO + book — a coordinator that accepts
    // and then drops us before the book must also re-enter the loop.
    std::string book;
    bool joined = false;
    const double deadline = MonotonicSeconds() + kConnectTimeoutS;
    long long delay_ms = rendezvous_backoff_base_ms_;
    for (int attempt = 0; attempt < rendezvous_retries_; ++attempt) {
      if (MonotonicSeconds() > deadline) break;
      if (attempt > 0) {
        // Exponential up to ~1 s, minus a deterministic per-rank jitter
        // (up to half the delay) so same-host workers de-collide without
        // non-reproducible randomness.
        long long d = std::min<long long>(delay_ms, 1000);
        if (d > 0) {
          d -= static_cast<long long>(
              (static_cast<unsigned long long>(cfg_.rank) * 2654435761ULL +
               static_cast<unsigned long long>(attempt)) %
              static_cast<unsigned long long>(d / 2 + 1));
          std::this_thread::sleep_for(std::chrono::milliseconds(d));
        }
        delay_ms = std::min<long long>(delay_ms * 2, 1000);
      }
      coord_ctrl_ = Socket();
      if (!coord_ctrl_.ConnectOnce(cfg_.rendezvous_addr,
                                   cfg_.rendezvous_port)) {
        if (!ConnectErrnoRetryable(coord_ctrl_.last_errno())) {
          return Status::Error(
              StatusCode::PRECONDITION_ERROR,
              "worker cannot reach coordinator at " + cfg_.rendezvous_addr +
                  ":" + std::to_string(cfg_.rendezvous_port) + ": " +
                  std::strerror(coord_ctrl_.last_errno()) +
                  " (fatal, not retrying)");
        }
        continue;
      }
      Writer hello;
      hello.PutI32(kProtocolMagic);
      hello.PutI32(kProtocolVersion);
      hello.PutI32(cfg_.rank);
      hello.PutI32(data_listener_.port());
      hello.PutString(hosts[cfg_.rank]);
      if (!coord_ctrl_.SendFrame(hello.data())) continue;
      if (!coord_ctrl_.RecvFrame(&book)) continue;
      joined = true;
      break;
    }
    if (!joined) {
      return Status::Error(
          StatusCode::PRECONDITION_ERROR,
          "worker failed to reach coordinator at " + cfg_.rendezvous_addr +
              ":" + std::to_string(cfg_.rendezvous_port) + " within " +
              std::to_string(rendezvous_retries_) + " attempts / " +
              std::to_string(static_cast<int>(kConnectTimeoutS)) + "s");
    }
    Reader r(book);
    for (int rank = 0; rank < cfg_.size; ++rank) {
      addrs[rank] = r.GetString();
      ports[rank] = r.GetI32();
      hosts[rank] = r.GetString();
    }
    // v9 trailer: the coordinator's ctrl_tree verdict.  The worker's own
    // HOROVOD_CONTROL_TREE is advisory only — obeying the book is what
    // keeps a mixed-env job from splitting into flat and tree halves.
    ctrl_tree_decision = (r.GetI32() == 1) && r.ok();
    // v12 trailer: the agreed fanout/depth — same authority rule.
    const int32_t agreed_fanout = r.GetI32();
    const int32_t agreed_depth = r.GetI32();
    if (r.ok()) {
      ctrl_tree_fanout_ = agreed_fanout;
      ctrl_tree_depth_ = agreed_depth;
    }
    if (!r.ok()) {
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "malformed rendezvous address book");
    }
    // Workers reach rank 0 by the address they rendezvoused through.
    addrs[0] = cfg_.rendezvous_addr;
  }

  // Keep the address book: per-process-set channel meshes dial through it
  // later (EstablishChannel).
  mesh_addrs_ = addrs;
  mesh_ports_ = ports;
  host_keys_ = hosts;
  ComputeCtrlTree(ctrl_tree_decision);
  std::vector<int> all_ranks(cfg_.size);
  for (int i = 0; i < cfg_.size; ++i) all_ranks[i] = i;
  if (!cfg_.ctrl_only) {
    // ctrl_only (C++ selftests) skips the O(n^2) data-plane mesh so an
    // in-process np=256 control-plane soak stays within fd/time budgets.
    Status s = ConnectMesh(all_ranks, /*psid=*/0, &peer_socks_);
    if (!s.ok()) return s;
    s = MaybeOpenShm(0, all_ranks);
    if (!s.ok()) return s;
    s = MaybeSetupHier(0, all_ranks);
    if (!s.ok()) return s;
  }
  Status ts = SetupCtrlTreeLinks();
  if (!ts.ok()) return ts;
  hierarchical_.store(cfg_.hierarchical, std::memory_order_relaxed);
  wire_compression_.store(cfg_.wire_compression, std::memory_order_relaxed);
  if (FlightOn()) {
    FlightRecord(kFlightRendezvous, cfg_.size, kProtocolVersion);
  }
  if (is_coordinator() && cfg_.autopilot_port > 0) {
    // Fleet-autopilot policy channel: loopback-only — the driver runs on
    // the coordinator's host, and the channel accepts decision records.
    if (!policy_listener_.Listen("127.0.0.1", cfg_.autopilot_port)) {
      HVD_LOG(WARNING) << "autopilot: failed to open policy listener on "
                          "port "
                       << cfg_.autopilot_port << "; policy channel disabled";
    } else {
      policy_stop_.store(false, std::memory_order_relaxed);
      policy_thread_ = std::thread([this] { PolicyServeLoop(); });
      HVD_LOG(INFO) << "autopilot: policy channel listening on port "
                    << policy_listener_.port();
    }
  }
  initialized_ = true;
  return Status::OK();
}

// ---- leader tree (protocol v9) --------------------------------------------

bool SocketController::DecideCtrlTree(const std::string& mode,
                                      const std::vector<std::string>& hosts) {
  if (mode == "off") return false;
  std::set<std::string> distinct(hosts.begin(), hosts.end());
  if (distinct.size() < 2) return false;  // single host: tree = pure overhead
  if (mode == "on") return true;
  // auto: multi-host AND big enough that per-rank coordinator fan-in is the
  // bottleneck worth an extra hop of latency.
  return hosts.size() >= 8;
}

void SocketController::ComputeCtrlTree(bool on) {
  tree_ = CtrlTree();
  if (!on) return;
  // Group ranks by host key in first-appearance order over rank order —
  // the SAME grouping MaybeSetupHier computes, so the ctrl tree and the
  // hierarchical data plane agree on what "a host" is.
  std::vector<std::vector<int>> groups;
  std::map<std::string, int> group_of;
  for (int r = 0; r < cfg_.size; ++r) {
    auto it = group_of.find(host_keys_[r]);
    if (it == group_of.end()) {
      group_of.emplace(host_keys_[r], static_cast<int>(groups.size()));
      groups.push_back({r});
    } else {
      groups[it->second].push_back(r);
    }
  }
  tree_.on = true;
  for (const auto& g : groups) {
    tree_.leaders.push_back(g[0]);
    if (group_of[host_keys_[cfg_.rank]] ==
        static_cast<int>(tree_.leaders.size()) - 1) {
      tree_.my_leader = g[0];
      if (g[0] == cfg_.rank) {
        tree_.my_children.assign(g.begin() + 1, g.end());
      }
    }
  }
  // v12 adaptive depth: while the coordinator would gather more than
  // `fanout` top-level nodes, partition the non-root top nodes (consecutive,
  // so clusters follow host order) into ceil(n/fanout) balanced clusters
  // and promote each cluster's lowest rank to super-leader.  Every pass
  // adds one aggregation level.  A forced depth d runs exactly d-2 passes
  // (stopping early only when a level has nothing left to cluster), so
  // HOROVOD_CONTROL_TREE_DEPTH=2 pins the v9 shape and =3 always inserts
  // one super-leader layer.  Deterministic and env-agreed, so every rank
  // computes the identical parent_of map.
  const int fanout = std::max(2, ctrl_tree_fanout_);
  std::vector<int> top = tree_.leaders;  // ascending; top[0] == 0
  int levels = 1;                        // aggregation layers so far
  while (true) {
    const int non_root = static_cast<int>(top.size()) - 1;
    const bool grow = (ctrl_tree_depth_ > 0)
                          ? (levels < ctrl_tree_depth_ - 1 && non_root > 1)
                          : (non_root > fanout);
    if (!grow) break;
    const int n_clusters = (non_root + fanout - 1) / fanout;
    std::vector<int> next = {0};
    for (int c = 0; c < n_clusters; ++c) {
      // Balanced split: cluster sizes differ by at most one.
      const int lo = 1 + static_cast<int>(
                             static_cast<int64_t>(c) * non_root / n_clusters);
      const int hi = 1 + static_cast<int>(static_cast<int64_t>(c + 1) *
                                          non_root / n_clusters);
      const int head = top[lo];
      next.push_back(head);
      for (int i = lo + 1; i < hi; ++i) tree_.parent_of[top[i]] = head;
    }
    top.swap(next);
    ++levels;
  }
  for (size_t i = 1; i < top.size(); ++i) tree_.parent_of[top[i]] = 0;
  tree_.depth = levels + 1;
  if (IsTreeLeader() && cfg_.rank != 0) {
    auto it = tree_.parent_of.find(cfg_.rank);
    tree_.parent = it == tree_.parent_of.end() ? 0 : it->second;
  }
  for (const auto& kv : tree_.parent_of) {
    if (kv.second == cfg_.rank && kv.first != cfg_.rank) {
      tree_.agg_children.push_back(kv.first);
    }
  }
  HVD_LOG(INFO) << "rank " << cfg_.rank << ": ctrl tree on, " << groups.size()
                << " hosts, depth " << tree_.depth << ", leader rank "
                << tree_.my_leader
                << (IsTreeLeader()
                        ? ", " + std::to_string(tree_.my_children.size()) +
                              " children, " +
                              std::to_string(tree_.agg_children.size()) +
                              " aggregate children, parent rank " +
                              std::to_string(cfg_.rank == 0 ? -1
                                                            : tree_.parent)
                        : "");
}

std::vector<int> SocketController::SubtreeOf(int rank) const {
  // A rank is in `rank`'s subtree when `rank` appears on its aggregation
  // path: itself -> its host leader -> parent_of chain -> coordinator.
  // O(size * depth); only walked on departure/abort paths, never per cycle.
  std::vector<int> out;
  if (!tree_.on) {
    out.push_back(rank);
    return out;
  }
  for (int r = 0; r < cfg_.size; ++r) {
    int node = r;
    // Hop from a worker to its host leader first (workers never appear in
    // parent_of; their parent is the host's first rank by construction).
    if (std::find(tree_.leaders.begin(), tree_.leaders.end(), node) ==
        tree_.leaders.end()) {
      for (int l : tree_.leaders) {
        if (host_keys_[l] == host_keys_[r]) {
          node = l;
          break;
        }
      }
    }
    bool under = (r == rank);
    int hops = 0;
    while (!under && node != 0 && hops++ <= cfg_.size) {
      if (node == rank) {
        under = true;
        break;
      }
      auto it = tree_.parent_of.find(node);
      node = it == tree_.parent_of.end() ? 0 : it->second;
    }
    if (under || node == rank) out.push_back(r);
  }
  return out;
}

void SocketController::DepartSubtree(int rank) {
  for (int r : SubtreeOf(rank)) departed_ranks_.insert(r);
}

std::vector<int> SocketController::AncestorChain(int rank) const {
  std::vector<int> out;
  if (!tree_.on || rank <= 0 || rank >= cfg_.size) return out;
  int node = rank;
  if (std::find(tree_.leaders.begin(), tree_.leaders.end(), node) ==
      tree_.leaders.end()) {
    for (int l : tree_.leaders) {
      if (host_keys_[l] == host_keys_[rank]) {
        node = l;
        break;
      }
    }
    if (node != rank && node != 0) out.push_back(node);
  }
  int hops = 0;
  while (node != 0 && hops++ <= cfg_.size) {
    auto it = tree_.parent_of.find(node);
    node = it == tree_.parent_of.end() ? 0 : it->second;
    if (node != 0) out.push_back(node);
  }
  return out;
}

Status SocketController::SetupCtrlTreeLinks() {
  if (!tree_.on) return Status::OK();
  if (is_coordinator() || cfg_.rank == tree_.my_leader) {
    // Leaders (and the coordinator, leader of host 0) accept ctrl-tree
    // HELLOs from this host's other ranks — and, v12, from downstream
    // leaders whose aggregates this node merges — on the mesh data
    // listener.  The coordinator's children of BOTH kinds keep their
    // rendezvous ctrl sockets, so it expects none here.
    int needed = static_cast<int>(tree_.my_children.size() +
                                  tree_.agg_children.size());
    if (is_coordinator()) needed = 0;
    auto expected_child = [&](int rank) {
      return std::find(tree_.my_children.begin(), tree_.my_children.end(),
                       rank) != tree_.my_children.end() ||
             std::find(tree_.agg_children.begin(), tree_.agg_children.end(),
                       rank) != tree_.agg_children.end();
    };
    // A child that finished its psid-0 mesh before this leader did may have
    // dialed already — ConnectMesh parked the unknown psid in the channel
    // stash.  Drain it before accepting fresh connections.
    if (needed > 0) {
      std::lock_guard<std::mutex> l(mesh_mu_);
      for (const auto* list : {&tree_.my_children, &tree_.agg_children}) {
        for (int c : *list) {
          auto it = pending_channel_.find({c, kCtrlTreePsid});
          if (it != pending_channel_.end()) {
            tree_child_socks_[c] = std::move(it->second);
            pending_channel_.erase(it);
            --needed;
          }
        }
      }
    }
    double deadline = MonotonicSeconds() + kConnectTimeoutS;
    while (needed > 0) {
      // A child's ctrl-tree HELLO can race a psid-0 mesh dial from the
      // same rank; ConnectMesh stashes unknown psids, and symmetrically we
      // stash a mesh HELLO... except psid-0 mesh setup already completed
      // before this call, so any arriving connection here is either a
      // ctrl-tree HELLO or a later channel dial (stash it).
      Socket s = data_listener_.Accept(1.0);
      if (!s.valid()) {
        if (MonotonicSeconds() > deadline) {
          return Status::Error(StatusCode::PRECONDITION_ERROR,
                               "ctrl-tree rendezvous timeout: leader rank " +
                                   std::to_string(cfg_.rank) + " still " +
                                   std::to_string(needed) + " children short");
        }
        continue;
      }
      s.SetRecvTimeout(5.0);
      std::string hello;
      if (!s.RecvFrame(&hello)) continue;
      Reader r(hello);
      int32_t rank = r.GetI32();
      int32_t psid = r.GetI32();
      if (!r.ok() || rank <= cfg_.rank || rank >= cfg_.size) {
        return Status::Error(StatusCode::INVALID_ARGUMENT,
                             "bad ctrl-tree HELLO at leader rank " +
                                 std::to_string(cfg_.rank));
      }
      s.SetRecvTimeout(0);
      if (psid != kCtrlTreePsid) {
        // A channel-mesh dial arriving early: park it for EstablishChannel.
        std::lock_guard<std::mutex> l(mesh_mu_);
        pending_channel_[{rank, psid}] = std::move(s);
        continue;
      }
      if (!expected_child(static_cast<int>(rank))) {
        return Status::Error(StatusCode::INVALID_ARGUMENT,
                             "ctrl-tree HELLO from rank " +
                                 std::to_string(rank) +
                                 " which is not a child of leader rank " +
                                 std::to_string(cfg_.rank));
      }
      tree_child_socks_[rank] = std::move(s);
      --needed;
    }
    // v12: a leader clustered under a super-leader dials its parent AFTER
    // its own subtree is linked up.  Dials flow strictly child -> lower-
    // ranked parent, so the chain completes bottom-up with no cycles.
    if (is_coordinator() || tree_.parent <= 0) return Status::OK();
  } else if (tree_.my_leader == 0) {
    return Status::OK();  // host-0 child: coord_ctrl_
  }
  // Dial this rank's negotiation parent (the host leader for a worker, the
  // super-leader for a clustered leader) on its mesh listener with a
  // ctrl-tree HELLO.  Child rank > parent rank always holds (the parent is
  // the first rank of its host / cluster), matching the mesh dial direction.
  const int parent = IsTreeLeader() ? tree_.parent : tree_.my_leader;
  Socket s;
  if (!s.Connect(mesh_addrs_[parent], mesh_ports_[parent],
                 kConnectTimeoutS)) {
    return Status::Error(StatusCode::PRECONDITION_ERROR,
                         "ctrl-tree connect to leader rank " +
                             std::to_string(parent) + " failed");
  }
  Writer hello;
  hello.PutI32(cfg_.rank);
  hello.PutI32(kCtrlTreePsid);
  if (!s.SendFrame(hello.data())) {
    return Status::Error(StatusCode::PRECONDITION_ERROR,
                         "ctrl-tree HELLO to leader rank " +
                             std::to_string(parent) + " failed");
  }
  tree_parent_ = std::move(s);
  return Status::OK();
}

Socket& SocketController::UpLink() {
  // The negotiation up-link: a node whose parent is a non-coordinator
  // (a tree child of a non-host-0 leader, or a v12 leader clustered under
  // a super-leader) talks to that parent; everyone else (flat mode, host-0
  // children, top-level leaders) talks straight to the coordinator.
  if (tree_.on && !is_coordinator() && tree_parent_.valid()) {
    return tree_parent_;
  }
  return coord_ctrl_;
}

Socket* SocketController::TreeChildSock(int rank) {
  if (is_coordinator() && tree_.my_leader == 0) {
    // Coordinator's own children live in ctrl_socks_ (rendezvous links).
    if (rank > 0 && rank < static_cast<int>(ctrl_socks_.size()) &&
        ctrl_socks_[rank].valid()) {
      return &ctrl_socks_[rank];
    }
    return nullptr;
  }
  auto it = tree_child_socks_.find(rank);
  if (it == tree_child_socks_.end() || !it->second.valid()) return nullptr;
  return &it->second;
}

Status SocketController::ConnectMesh(const std::vector<int>& members,
                                     int psid, std::vector<Socket>* out) {
  // Deterministic pairing: every member dials all lower members, then
  // accepts one connection from each higher member (their dials queue in
  // the listener backlog meanwhile, so the two phases cannot deadlock).
  // HELLO = [rank, psid]; psid 0 is the global init mesh, >0 a channel.
  std::lock_guard<std::mutex> mesh_lock(mesh_mu_);
  out->clear();
  out->resize(cfg_.size);
  std::set<int> member_set(members.begin(), members.end());
  for (int rank : members) {
    if (rank >= cfg_.rank) continue;
    Socket s;
    if (!s.Connect(mesh_addrs_[rank], mesh_ports_[rank], kConnectTimeoutS)) {
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "mesh connect to rank " + std::to_string(rank) +
                               " at " + mesh_addrs_[rank] + ":" +
                               std::to_string(mesh_ports_[rank]) +
                               " (psid " + std::to_string(psid) + ") failed");
    }
    Writer hello;
    hello.PutI32(cfg_.rank);
    hello.PutI32(psid);
    if (!s.SendFrame(hello.data())) {
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "mesh HELLO to rank " + std::to_string(rank) +
                               " failed");
    }
    (*out)[rank] = std::move(s);
  }
  int needed = 0;
  for (int rank : members) {
    if (rank <= cfg_.rank) continue;
    // Channel HELLOs may have arrived while this rank was establishing a
    // DIFFERENT channel (add_process_set call skew): drain the stash.
    auto it = pending_channel_.find({rank, psid});
    if (it != pending_channel_.end()) {
      (*out)[rank] = std::move(it->second);
      pending_channel_.erase(it);
    } else {
      ++needed;
    }
  }
  double deadline = MonotonicSeconds() + kConnectTimeoutS;
  while (needed > 0) {
    if (aborted_) {
      return Status::Error(StatusCode::ABORTED,
                           "controller shut down during mesh establishment");
    }
    if (MonotonicSeconds() > deadline) {
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "mesh accept timeout on rank " +
                               std::to_string(cfg_.rank) + " (psid " +
                               std::to_string(psid) + ")");
    }
    Socket s = data_listener_.Accept(1.0);
    if (!s.valid()) continue;
    std::string hello;
    if (!s.RecvFrame(&hello)) continue;
    Reader r(hello);
    int rank = r.GetI32();
    int got_psid = r.GetI32();
    if (!r.ok() || rank <= cfg_.rank || rank >= cfg_.size) {
      return Status::Error(StatusCode::INVALID_ARGUMENT,
                           "bad mesh HELLO (claimed rank " +
                               std::to_string(rank) + ")");
    }
    if (got_psid != psid || !member_set.count(rank)) {
      // A dial for a channel this rank has not started establishing yet;
      // stash it for that channel's ConnectMesh.
      pending_channel_[{rank, got_psid}] = std::move(s);
      continue;
    }
    if ((*out)[rank].valid()) {
      return Status::Error(StatusCode::INVALID_ARGUMENT,
                           "duplicate mesh HELLO from rank " +
                               std::to_string(rank));
    }
    (*out)[rank] = std::move(s);
    --needed;
  }
  return Status::OK();
}

Status SocketController::EstablishChannel(int psid) {
  if (psid == 0 || cfg_.size == 1 || !initialized_) return Status::OK();
  std::vector<int> members;
  if (!process_sets_.Ranks(psid, &members)) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "unknown process set " + std::to_string(psid));
  }
  if (std::find(members.begin(), members.end(), cfg_.rank) == members.end()) {
    return Status::OK();  // non-members hold no channel sockets
  }
  if (members.size() <= 1) return Status::OK();
  std::vector<Socket> socks;
  Status s = ConnectMesh(members, psid, &socks);
  if (!s.ok()) return s;
  {
    std::lock_guard<std::mutex> l(channels_mu_);
    channel_socks_[psid] = std::move(socks);
  }
  s = MaybeOpenShm(psid, members);
  if (!s.ok()) return s;
  return MaybeSetupHier(psid, members);
}

void SocketController::RemoveChannel(int psid) {
  std::lock_guard<std::mutex> l(channels_mu_);
  auto hh = hier_.find(psid);
  if (hh != hier_.end()) {
    if (hh->second.shm) hh->second.shm->Close(hh->second.local_idx == 0);
    hier_.erase(hh);
  }
  auto sh = shm_.find(psid);
  if (sh != shm_.end()) {
    std::vector<int> members;
    bool creator = process_sets_.Ranks(psid, &members) && !members.empty() &&
                   members[0] == cfg_.rank;
    sh->second->Close(creator);
    shm_.erase(sh);
  }
  auto it = channel_socks_.find(psid);
  if (it == channel_socks_.end()) return;
  for (auto& s : it->second) s.Close();
  channel_socks_.erase(it);
}

std::vector<Socket>& SocketController::SocksFor(int psid) {
  if (psid == 0) return peer_socks_;
  std::lock_guard<std::mutex> l(channels_mu_);
  auto it = channel_socks_.find(psid);
  // Map nodes are pointer-stable; a channel is only erased by
  // RemoveChannel, which the contract forbids while ops are in flight.
  return it == channel_socks_.end() ? peer_socks_ : it->second;
}

void SocketController::Farewell() {
  if (!initialized_ || aborted_) return;
  Writer w;
  w.PutI32(-1);  // BYE sentinel in the cycle-frame position
  if (is_coordinator()) {
    // The farewell DOWN to workers stays a bare [-1]: it rides the
    // RESPONSES position, where nothing parses past the sentinel.
    for (int rank = 1; rank < cfg_.size; ++rank) {
      if (ctrl_socks_[rank].valid() && !departed_ranks_.count(rank)) {
        ctrl_socks_[rank].SendFrame(w.data());
      }
    }
    return;
  }
  if (IsTreeLeader()) {
    // Release this host's children first ([-1] in the responses
    // position, same frame the coordinator's farewell would produce), so
    // none of them blocks on a leader that is about to close its links.
    FanDownToChildren(w.data(), nullptr);
  }
  // v11: the BYE UP the gather topology carries this rank's FINAL
  // cumulative sketch — captured here, after the last cycle's response
  // handling observed its waits — so a coordinator still cycling folds in
  // exactly what this rank's own metrics dump is about to record.  A
  // leader ships the whole host's sum: its own fresh capture plus every
  // child's last-known sketch (final, when the child BYEd through it).
  if (MetricsOn() && FleetTelemetryOn()) {
    FleetSketch own;
    own.CaptureLocal();
    if (IsTreeLeader()) {
      tree_child_sketches_[cfg_.rank] = std::move(own);
      FleetSketch host_sum;
      for (const auto& kv : tree_child_sketches_) host_sum.Merge(kv.second);
      w.PutString(host_sum.Encode());
    } else {
      w.PutString(own.Encode());
    }
  } else {
    w.PutString("");
  }
  UpLink().SendFrame(w.data());  // best effort; a leader forwards it up
}

void SocketController::Shutdown() {
  // The policy thread may exist even when Initialize failed later on, so
  // stop it before the initialized_ gate below.
  policy_stop_.store(true, std::memory_order_relaxed);
  if (policy_thread_.joinable()) policy_thread_.join();
  policy_listener_.Close();
  if (!initialized_) return;
  initialized_ = false;
  aborted_ = true;
  {
    // Expire any WaitAbortReason waiters: no ABORT is coming once the
    // sockets close, and teardown must not serve the propagation timeout.
    std::lock_guard<std::mutex> l(abort_mu_);
    abort_wait_deadline_ = -1;
  }
  abort_cv_.notify_all();
  coord_ctrl_.Close();
  tree_parent_.Close();
  for (auto& kv : tree_child_socks_) kv.second.Close();
  for (auto& s : ctrl_socks_) s.Close();
  for (auto& s : peer_socks_) s.Close();
  {
    std::lock_guard<std::mutex> l(channels_mu_);
    for (auto& kv : shm_) {
      std::vector<int> members;
      bool creator = process_sets_.Ranks(kv.first, &members) &&
                     !members.empty() && members[0] == cfg_.rank;
      kv.second->Close(creator);
    }
    shm_.clear();
    for (auto& kv : hier_) {
      if (kv.second.shm) kv.second.shm->Close(kv.second.local_idx == 0);
    }
    hier_.clear();
    for (auto& kv : channel_socks_)
      for (auto& s : kv.second) s.Close();
    channel_socks_.clear();
  }
  {
    // aborted_ is already set, so any in-flight ConnectMesh exits its
    // accept loop promptly and releases mesh_mu_.
    std::lock_guard<std::mutex> l(mesh_mu_);
    for (auto& kv : pending_channel_) kv.second.Close();
    pending_channel_.clear();
  }
  listener_.Close();
  data_listener_.Close();
}

// ---------------------------------------------------------------------------
// Negotiation
// ---------------------------------------------------------------------------

Status SocketController::ComputeResponses(
    std::vector<TensorRequest>& new_requests, std::vector<Response>* out) {
  if (aborted_) {
    // An executor lane observed a data-plane failure before the control
    // plane did.  Workers send a best-effort failure FIN and await the
    // coordinator's ABORT so the error names the culprit; the coordinator
    // sweeps its ctrl sockets for one and broadcasts.  Clean teardown
    // (farewell/Shutdown) keeps the plain fast path.
    if (peer_shutdown_ || !initialized_) {
      return Status::Error(StatusCode::ABORTED, "controller down");
    }
    return is_coordinator() ? CoordinatorAbortSweep()
                            : WorkerAbortHandshake();
  }
  const Status st = is_coordinator() ? CoordinatorCycle(new_requests, out)
                    : IsTreeLeader() ? LeaderCycle(new_requests, out)
                                     : WorkerCycle(new_requests, out);
  if (FlightOn() && st.ok() && !out->empty()) {
    // Negotiation verdict: how many responses this cycle fused, and the
    // data-op seq the plane advanced to (every rank records the same pair).
    FlightRecord(kFlightVerdict, static_cast<int32_t>(out->size()),
                 seq_counter_);
  }
  return st;
}

// ---------------------------------------------------------------------------
// Fast-abort propagation (protocol v8)
// ---------------------------------------------------------------------------

void SocketController::SetAbortReason(const std::string& reason) {
  {
    std::lock_guard<std::mutex> l(abort_mu_);
    if (abort_reason_.empty()) abort_reason_ = reason;
  }
  abort_cv_.notify_all();
}

std::string SocketController::AbortReason() {
  std::lock_guard<std::mutex> l(abort_mu_);
  return abort_reason_;
}

std::string SocketController::WaitAbortReason() {
  std::unique_lock<std::mutex> l(abort_mu_);
  if (!abort_reason_.empty()) return abort_reason_;
  // The wait budget is charged ONCE, at the first waiter: stacked executor
  // lanes blocking here serially must not multiply the propagation bound.
  if (abort_wait_deadline_ == 0) {
    abort_wait_deadline_ = MonotonicSeconds() + abort_timeout_s_;
  }
  while (abort_reason_.empty()) {
    const double left = abort_wait_deadline_ - MonotonicSeconds();
    if (left <= 0) break;
    abort_cv_.wait_for(l, std::chrono::duration<double>(left));
  }
  return abort_reason_;
}

Status SocketController::BroadcastAbortAndFail(int culprit_rank,
                                               const std::string& why) {
  aborted_ = true;
  std::string culprit_host;
  if (culprit_rank >= 0 &&
      culprit_rank < static_cast<int>(host_keys_.size())) {
    culprit_host = host_keys_[culprit_rank];
  }
  std::string msg = "collective aborted: " + why;
  if (culprit_rank >= 0) {
    msg += " (culprit rank " + std::to_string(culprit_rank) + ", host " +
           (culprit_host.empty() ? "?" : culprit_host) + ")";
  }
  if (!abort_broadcast_done_) {
    abort_broadcast_done_ = true;
    Writer w;
    w.PutI32(-2);  // ABORT sentinel in the responses position
    w.PutI32(kTagAbort);
    w.PutString(why);
    w.PutI32(culprit_rank);
    w.PutString(culprit_host);
    w.PutF64(WallSeconds());
    int notified = 0;
    for (int rank = 1; rank < cfg_.size; ++rank) {
      if (rank == culprit_rank || departed_ranks_.count(rank)) continue;
      if (!ctrl_socks_[rank].valid()) continue;
      if (ctrl_socks_[rank].SendFrame(w.data())) ++notified;
    }
    if (MetricsOn()) {
      GlobalMetrics().aborts_total.fetch_add(1, std::memory_order_relaxed);
    }
    HVD_LOG(ERROR) << "broadcast ABORT to " << notified
                   << " survivors: " << msg;
    SetAbortReason(msg);
    if (FlightOn()) {
      FlightRecord(kFlightAbort, culprit_rank, 1);  // b=1: we broadcast it
      // Forensics strictly AFTER the broadcast: survivors are already
      // unblocked, so digest collection spends the abort budget on the
      // coordinator alone and never widens the propagation bound.
      if (!FlightPostmortemDir().empty()) {
        CollectFlightDigests(MonotonicSeconds() + abort_timeout_s_);
        WritePostmortem(culprit_rank, culprit_host, msg);
      }
      FlightDumpToFile();
    }
  }
  return Status::Error(StatusCode::ABORTED, msg);
}

Status SocketController::HandleAbortFrame(Reader* rd) {
  aborted_ = true;
  got_abort_ = true;
  const int32_t tag = rd->GetI32();
  std::string why = rd->GetString();
  const int32_t culprit = rd->GetI32();
  const std::string host = rd->GetString();
  const double sent_ts = rd->GetF64();
  if (!rd->ok() || tag != kTagAbort) {
    const std::string msg = "malformed ABORT frame from coordinator";
    SetAbortReason(msg);
    return Status::Error(StatusCode::ABORTED, msg);
  }
  if (MetricsOn()) {
    auto& m = GlobalMetrics();
    m.aborts_total.fetch_add(1, std::memory_order_relaxed);
    // Cross-process latency: wall clock, clamped (hosts may skew).
    m.abort_propagation_us.ObserveSeconds(
        std::max(0.0, WallSeconds() - sent_ts));
  }
  std::string msg = "aborted by coordinator: " + why;
  if (culprit >= 0) {
    msg += " (culprit rank " + std::to_string(culprit) + ", host " +
           (host.empty() ? "?" : host) + ")";
  }
  SetAbortReason(msg);
  if (FlightOn()) {
    FlightRecord(kFlightAbort, culprit, 0);  // b=0: observed, not broadcast
    // Answer the coordinator's forensics solicitation: last-N digest up
    // the tree (leaders go direct), then relay any child digests, then
    // drop this rank's own black box.  All best-effort — the ABORTED
    // status below is already decided.
    SendFlightDigest(tree_parent_.valid() ? tree_parent_ : coord_ctrl_);
    ForwardChildDigests();
    FlightDumpToFile();
  }
  return Status::Error(StatusCode::ABORTED, msg);
}

Status SocketController::WorkerAbortHandshake() {
  {
    std::lock_guard<std::mutex> l(abort_mu_);
    if (!abort_reason_.empty()) {
      return Status::Error(StatusCode::ABORTED, abort_reason_);
    }
  }
  if (got_abort_ || !coord_ctrl_.valid()) {
    return Status::Error(StatusCode::ABORTED, "controller down");
  }
  if (!fin_sent_) {
    fin_sent_ = true;
    Writer w;
    w.PutI32(-2);  // failure FIN in the cycle-frame position
    w.PutString("rank " + std::to_string(cfg_.rank) +
                " observed a data-plane failure");
    w.PutI32(cfg_.rank);  // v9: explicit culprit so leaders forward losslessly
    // Up the tree AND direct to the coordinator: if this rank's leader is
    // the thing that died, the direct path still attributes the failure.
    if (tree_parent_.valid()) tree_parent_.SendFrame(w.data());
    coord_ctrl_.SendFrame(w.data());  // best effort
    if (FlightOn()) {
      // The digest rides right behind the FIN on the same link: the
      // coordinator's post-broadcast collection drains it from the
      // already-open socket, so the culprit's own last events (the most
      // valuable ones) make the postmortem too.
      SendFlightDigest(tree_parent_.valid() ? tree_parent_ : coord_ctrl_);
    }
  }
  // Drain the ctrl channels toward the coordinator's ABORT, bounded by the
  // propagation timeout.  Stale RESPONSES frames from the cycle in flight
  // when the failure hit are discarded.  The ABORT may arrive direct
  // (coord_ctrl_) or forwarded by this rank's leader (tree_parent_); a
  // leader running this handshake fans every terminal frame down to its
  // children before acting on it, so the subtree never waits out the
  // timeout just because its leader learned first.
  const double deadline = MonotonicSeconds() + abort_timeout_s_;
  while (MonotonicSeconds() < deadline) {
    pollfd pfds[2];
    Socket* socks[2];
    nfds_t npfd = 0;
    if (coord_ctrl_.valid()) {
      pfds[npfd] = pollfd{coord_ctrl_.fd(), POLLIN, 0};
      socks[npfd++] = &coord_ctrl_;
    }
    if (tree_parent_.valid()) {
      pfds[npfd] = pollfd{tree_parent_.fd(), POLLIN, 0};
      socks[npfd++] = &tree_parent_;
    }
    if (npfd == 0) break;
    const int rc = ::poll(pfds, npfd, 200);
    if (rc < 0) {
      if (errno == EINTR) continue;
      break;
    }
    if (rc == 0) continue;
    bool any_dead = false;
    for (nfds_t i = 0; i < npfd; ++i) {
      if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      std::string frame;
      if (!socks[i]->RecvFrame(&frame)) {
        socks[i]->Close();
        // The direct coordinator link dying means no ABORT is coming.
        if (socks[i] == &coord_ctrl_) any_dead = true;
        continue;
      }
      Reader rd(frame);
      const int32_t n = rd.GetI32();
      if (n == -1) {
        FanDownToChildren(frame, nullptr);
        peer_shutdown_ = true;
        const std::string msg = "coordinator shut down the job";
        SetAbortReason(msg);
        return Status::Error(StatusCode::ABORTED, msg);
      }
      if (n == -2) {
        FanDownToChildren(frame, nullptr);
        return HandleAbortFrame(&rd);
      }
    }
    if (any_dead) break;
  }
  const std::string msg =
      "data-plane failure on rank " + std::to_string(cfg_.rank) +
      " (no coordinator ABORT within " + std::to_string(abort_timeout_s_) +
      "s)";
  SetAbortReason(msg);
  // No ABORT ever arrived — the coordinator may be the thing that died.
  // Leave this rank's black box behind anyway.
  if (FlightOn()) FlightDumpToFile();
  return Status::Error(StatusCode::ABORTED, msg);
}

Status SocketController::CoordinatorAbortSweep() {
  {
    std::lock_guard<std::mutex> l(abort_mu_);
    if (!abort_reason_.empty()) {
      return Status::Error(StatusCode::ABORTED, abort_reason_);
    }
  }
  if (abort_broadcast_done_) {
    return Status::Error(StatusCode::ABORTED, "controller down");
  }
  // Find the culprit: poll the live ctrl sockets for a failure FIN or a
  // dead connection, bounded by the propagation timeout.  Normal CYCLE
  // frames from ranks that have not noticed yet are discarded — the job
  // is aborting either way.
  int culprit = -1;
  std::string why;
  const double deadline = MonotonicSeconds() + abort_timeout_s_;
  while (culprit < 0 && MonotonicSeconds() < deadline) {
    std::vector<pollfd> pfds;
    std::vector<int> ranks;
    for (int rank = 1; rank < cfg_.size; ++rank) {
      if (departed_ranks_.count(rank) || !ctrl_socks_[rank].valid()) continue;
      pfds.push_back(pollfd{ctrl_socks_[rank].fd(), POLLIN, 0});
      ranks.push_back(rank);
    }
    if (pfds.empty()) break;
    const int rc = ::poll(pfds.data(), static_cast<nfds_t>(pfds.size()), 200);
    if (rc < 0) {
      if (errno == EINTR) continue;
      break;
    }
    if (rc == 0) continue;
    for (size_t i = 0; i < pfds.size() && culprit < 0; ++i) {
      if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      const int rank = ranks[i];
      std::string frame;
      if (!ctrl_socks_[rank].RecvFrame(&frame)) {
        culprit = rank;
        why = "lost connection to rank " + std::to_string(rank);
        break;
      }
      Reader rd(frame);
      const int32_t n_cached = rd.GetI32();
      if (n_cached == -2) {  // failure FIN
        culprit = rank;
        why = rd.GetString();
        if (!rd.ok() || why.empty()) {
          why = "rank " + std::to_string(rank) + " reported a failure";
        }
        // v9: an explicit culprit trailer — a leader forwarding a child's
        // FIN is the SENDER but not the culprit.
        const int32_t c = rd.GetI32();
        if (rd.ok() && c >= 0 && c < cfg_.size) culprit = c;
        break;
      }
      if (n_cached == -1) departed_ranks_.insert(rank);
      // A digest racing the FIN (another rank noticed an ABORT first, or a
      // leader forwarded a child's): stash it now, before the broadcast.
      if (n_cached == -4) StashFlightDigest(&rd);
      // n_cached == -3 (a leader's aggregate from the cycle in flight) and
      // plain CYCLE frames are equally stale here: discard and keep polling.
    }
  }
  if (culprit < 0) why = "coordinator observed a local failure";
  return BroadcastAbortAndFail(culprit, why);
}

// ---------------------------------------------------------------------------
// Abort-time forensics (flight recorder; flight_recorder.h)
// ---------------------------------------------------------------------------

void SocketController::SendFlightDigest(Socket& sock) {
  if (digest_sent_ || !FlightOn() || !sock.valid()) return;
  digest_sent_ = true;
  std::vector<FlightEvent> tail;
  FlightTail(kFlightDigestEvents, &tail);
  Writer w;
  w.PutI32(-4);  // digest sentinel in the cycle-frame position
  w.PutI32(kTagFlightDigest);
  w.PutI32(cfg_.rank);
  w.PutI32(static_cast<int32_t>(tail.size()));
  for (const auto& ev : tail) {
    w.PutI64(ev.ts_us);
    w.PutI64(static_cast<int64_t>(ev.seq));
    w.PutI32(ev.type);
    w.PutI32(ev.tid);
    w.PutI32(ev.a);
    w.PutI64(ev.b);
  }
  sock.SendFrame(w.data());  // best effort: forensics never block the abort
}

bool SocketController::StashFlightDigest(Reader* rd) {
  const int32_t tag = rd->GetI32();
  const int32_t rank = rd->GetI32();
  const int32_t n = rd->GetI32();
  if (!rd->ok() || tag != kTagFlightDigest || rank < 0 ||
      rank >= cfg_.size || n < 0 || n > kFlightDigestEvents) {
    return false;
  }
  std::vector<FlightEvent> evs;
  evs.reserve(static_cast<size_t>(n));
  for (int32_t i = 0; i < n; ++i) {
    FlightEvent ev;
    ev.ts_us = rd->GetI64();
    ev.seq = static_cast<uint64_t>(rd->GetI64());
    ev.type = rd->GetI32();
    ev.tid = rd->GetI32();
    ev.a = rd->GetI32();
    ev.b = rd->GetI64();
    evs.push_back(ev);
  }
  if (!rd->ok()) return false;
  if (FlightOn()) {
    FlightRecord(kFlightDigest, rank, static_cast<int64_t>(evs.size()));
  }
  flight_digests_[rank] = std::move(evs);
  return true;
}

void SocketController::CollectFlightDigests(double deadline) {
  // Poll until the deadline or every reachable rank has reported.  A
  // rank's digest may arrive on any of its ANCESTORS' sockets (each relay
  // hop lands the forwarded frame on the relaying leader's own rendezvous
  // link — v12 trees relay through super-leaders too), so completion
  // counts ranks reported — never sockets drained — and every ancestor's
  // socket stays in the poll set while any rank below it is still
  // outstanding, even after that ancestor's own digest landed.
  while (MonotonicSeconds() < deadline) {
    std::set<int> poll_ranks;  // socket owners worth polling this round
    int outstanding = 0;
    for (int rank = 1; rank < cfg_.size; ++rank) {
      if (departed_ranks_.count(rank) || flight_digests_.count(rank)) {
        continue;
      }
      bool reachable = false;
      if (ctrl_socks_[rank].valid()) {
        poll_ranks.insert(rank);
        reachable = true;
      }
      // Host-0 children (leader 0 = the coordinator itself) only have
      // their direct sockets; remote ranks may report via any live
      // ancestor (host leader, then each super-leader above it).
      for (int l : AncestorChain(rank)) {
        if (l > 0 && l != rank && ctrl_socks_[l].valid()) {
          poll_ranks.insert(l);
          reachable = true;
        }
      }
      if (reachable) ++outstanding;  // unreachable: don't charge budget
    }
    if (outstanding == 0 || poll_ranks.empty()) return;
    std::vector<pollfd> pfds;
    std::vector<int> ranks;
    for (int rank : poll_ranks) {
      pfds.push_back(pollfd{ctrl_socks_[rank].fd(), POLLIN, 0});
      ranks.push_back(rank);
    }
    const double left = deadline - MonotonicSeconds();
    const int wait_ms =
        std::max(10, std::min(200, static_cast<int>(left * 1000)));
    const int rc =
        ::poll(pfds.data(), static_cast<nfds_t>(pfds.size()), wait_ms);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return;
    }
    if (rc == 0) continue;
    for (size_t i = 0; i < pfds.size(); ++i) {
      if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      const int rank = ranks[i];
      std::string frame;
      if (!ctrl_socks_[rank].RecvFrame(&frame)) {
        // The culprit (or another casualty) died before answering: close
        // so the next poll round stops charging the budget to it.
        ctrl_socks_[rank].Close();
        continue;
      }
      Reader rd(frame);
      const int32_t n = rd.GetI32();
      if (n == -4) {
        StashFlightDigest(&rd);
      } else if (n == -1) {
        departed_ranks_.insert(rank);
      }
      // Anything else (stale CYCLE/aggregate/FIN frames from the dying
      // cycle) is discarded: the broadcast already went out.
    }
  }
}

void SocketController::ForwardChildDigests() {
  // Relay upward on this node's own up-link: a host leader goes direct to
  // the coordinator (or, v12, to its super-leader, which relays again), so
  // every digest eventually lands on a rendezvous socket the coordinator
  // polls.
  Socket& up = UpLink();
  if (tree_child_socks_.empty() || !up.valid()) return;
  // Children received the fanned-down ABORT moments ago and answer within
  // milliseconds; cap the relay window well inside the abort budget so a
  // mute child never delays this leader's own teardown.
  const double deadline =
      MonotonicSeconds() + std::min(0.5, abort_timeout_s_ * 0.25);
  std::set<int> done;
  while (MonotonicSeconds() < deadline) {
    std::vector<pollfd> pfds;
    std::vector<int> ranks;
    for (auto& [rank, sock] : tree_child_socks_) {
      if (done.count(rank) || tree_departed_children_.count(rank)) continue;
      if (!sock.valid()) continue;
      pfds.push_back(pollfd{sock.fd(), POLLIN, 0});
      ranks.push_back(rank);
    }
    if (pfds.empty()) return;
    const int rc = ::poll(pfds.data(), static_cast<nfds_t>(pfds.size()), 50);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return;
    }
    if (rc == 0) continue;
    for (size_t i = 0; i < pfds.size(); ++i) {
      if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      const int rank = ranks[i];
      Socket* cs = TreeChildSock(rank);
      std::string frame;
      if (cs == nullptr || !cs->RecvFrame(&frame)) {
        done.insert(rank);
        continue;
      }
      Reader rd(frame);
      if (rd.GetI32() == -4) {
        up.SendFrame(frame);  // verbatim relay, best effort
        done.insert(rank);
      }
      // Stale frames (the child's in-flight CYCLE, an already-handled FIN)
      // are discarded; keep waiting for its digest until the window ends.
    }
  }
}

void SocketController::WritePostmortem(int culprit_rank,
                                       const std::string& culprit_host,
                                       const std::string& why) {
  const std::string dir = FlightPostmortemDir();
  if (dir.empty()) return;
  // The coordinator's own tail joins the collected digests so rank 0
  // appears in the merged view like everyone else.
  std::vector<FlightEvent> own;
  FlightTail(kFlightDigestEvents, &own);
  std::string out;
  out.reserve(1 << 16);
  out += "{\"schema\":\"hvd-postmortem-v1\"";
  out += ",\"protocol_version\":" + std::to_string(kProtocolVersion);
  out += ",\"world_size\":" + std::to_string(cfg_.size);
  out += ",\"abort_wall_time\":" + std::to_string(WallSeconds());
  out += ",\"culprit_rank\":" + std::to_string(culprit_rank);
  out += ",\"culprit_host\":\"" + JsonEscape(culprit_host) + "\"";
  out += ",\"reason\":\"" + JsonEscape(why) + "\"";
  out += ",\"types\":";
  out += FlightTypesLegend();
  // Per-rank last-seen negotiation state from the v7 metrics snapshots —
  // which cycle each rank had reached when it last reported.
  {
    std::lock_guard<std::mutex> l(metrics_mu_);
    if (!cluster_.empty()) {
      out += ",\"last_seen_cycles\":{";
      bool first = true;
      for (size_t r = 0; r < cluster_.size(); ++r) {
        if (cluster_[r].updated_at == 0) continue;
        if (!first) out += ",";
        first = false;
        out += "\"" + std::to_string(r) +
               "\":" + std::to_string(cluster_[r].cycle_count);
      }
      out += "}";
    }
  }
  out += ",\"ranks\":{";
  auto emit_rank = [&](int rank, const char* source,
                       const std::vector<FlightEvent>& evs, bool first) {
    if (!first) out += ",";
    std::string host =
        rank < static_cast<int>(host_keys_.size()) ? host_keys_[rank] : "";
    out += "\"" + std::to_string(rank) + "\":{\"source\":\"" + source +
           "\",\"host\":\"" + JsonEscape(host) + "\"";
    if (!evs.empty()) {
      out += ",\"last_ts_us\":" + std::to_string(evs.back().ts_us);
      out += ",\"last_seq\":" + std::to_string(evs.back().seq);
    }
    out += ",\"events\":[";
    bool fe = true;
    for (const auto& ev : evs) {
      if (!fe) out += ",";
      fe = false;
      out += "[" + std::to_string(ev.ts_us) + "," + std::to_string(ev.seq) +
             "," + std::to_string(ev.type) + "," + std::to_string(ev.tid) +
             "," + std::to_string(ev.a) + "," + std::to_string(ev.b) + "]";
    }
    out += "]}";
  };
  emit_rank(cfg_.rank, "local", own, true);
  std::vector<int> missing;
  for (int rank = 1; rank < cfg_.size; ++rank) {
    auto it = flight_digests_.find(rank);
    if (it != flight_digests_.end()) {
      emit_rank(rank, "digest", it->second, false);
    } else if (!departed_ranks_.count(rank)) {
      missing.push_back(rank);
    }
  }
  out += "}";
  out += ",\"missing_ranks\":[";
  for (size_t i = 0; i < missing.size(); ++i) {
    if (i) out += ",";
    out += std::to_string(missing[i]);
  }
  out += "]}";
  // tmp + rename: tooling polling the directory never reads a partial
  // bundle (same contract as the per-rank flight dumps).
  const std::string path = dir + "/postmortem.json";
  const std::string tmp = path + ".tmp";
  std::FILE* f = std::fopen(tmp.c_str(), "w");
  if (f == nullptr) return;
  std::fwrite(out.data(), 1, out.size(), f);
  std::fclose(f);
  std::rename(tmp.c_str(), path.c_str());
  HVD_LOG(ERROR) << "postmortem bundle written: " << path << " ("
                 << flight_digests_.size() << " digests, "
                 << missing.size() << " missing)";
}

void SocketController::Announce(int rank, TensorRequest req,
                                std::vector<Response>* errors) {
  // A name the coordinator recently failed: a rank still owed that error
  // (it had not announced when the failure was emitted) gets it now
  // instead of forming a pending entry that waits forever on ranks that
  // already moved on.  Ranks that have seen the error and announce the
  // name again are fresh, consistent resubmissions and fall through to
  // the normal path.  This check runs before any join bookkeeping so a
  // dead join round cannot re-register the announcer as joined.
  auto tomb = error_tombstones_.find(req.name);
  if (tomb != error_tombstones_.end() &&
      MonotonicSeconds() < tomb->second.expiry &&
      tomb->second.owed.count(rank)) {
    Response e;
    e.op = req.op;
    e.error = tomb->second.error;
    e.target_rank = rank;  // others may have resubmitted this name
    e.names.push_back(req.name);
    e.metas.push_back(req);
    errors->push_back(std::move(e));
    tomb->second.owed.erase(rank);
    if (tomb->second.owed.empty()) error_tombstones_.erase(tomb);
    return;
  }
  // hvd.join(): mark the rank as contributing zeros to every collective
  // until all ranks have joined (reference: JoinOp / the joined-rank
  // wildcard in ComputeResponseList).  The JOIN request itself still goes
  // through the normal pending table (fixed name => ready when the last
  // rank joins).
  if (req.op == OpType::JOIN) {
    joined_ranks_.insert(rank);
    last_joined_ = rank;
  }
  // Process-set registration happens on each rank's Python thread and may
  // race announcements arriving from faster ranks; an unknown process set
  // is therefore *deferred* (the tensor stays pending until the local
  // registration lands), not an error.  Membership is validated once the
  // set is known, at readiness-check time.
  std::vector<int> members;
  if (process_sets_.Ranks(req.process_set_id, &members) &&
      !std::binary_search(members.begin(), members.end(), rank)) {
    Response e;
    e.op = req.op;
    e.error = "rank " + std::to_string(rank) +
              " is not in process set of tensor " + req.name;
    e.names.push_back(req.name);
    e.metas.push_back(req);
    errors->push_back(std::move(e));
    return;
  }
  auto it = pending_.find(req.name);
  if (it == pending_.end()) {
    Pending p;
    p.meta = req;
    p.order = arrival_counter_++;
    p.first_seen = MonotonicSeconds();
    p.announced.insert(rank);
    pending_.emplace(req.name, std::move(p));
    RecordAnnounceLag(rank, 0.0);  // first announcer defines t=0
    return;
  }
  // Cross-rank consistency validation (reference: ComputeResponseList's
  // error construction for mismatched shapes/dtypes).
  Pending& p = it->second;
  std::string mismatch;
  if (p.meta.op != req.op) {
    mismatch = "operation type";
  } else if (p.meta.dtype != req.dtype) {
    mismatch = "dtype";
  } else if (p.meta.reduce_op != req.reduce_op) {
    mismatch = "reduce op";
  } else if (p.meta.process_set_id != req.process_set_id) {
    mismatch = "process set";
  } else if (p.meta.root_rank != req.root_rank) {
    mismatch = "root rank";
  } else if (p.meta.prescale != req.prescale ||
             p.meta.postscale != req.postscale) {
    mismatch = "scale factors";
  } else if (p.meta.group_key != req.group_key ||
             p.meta.group_size != req.group_size) {
    mismatch = "group membership";
  } else if (req.op == OpType::ALLREDUCE || req.op == OpType::BROADCAST ||
             req.op == OpType::REDUCESCATTER) {
    if (p.meta.shape != req.shape) mismatch = "shape";
  } else if (req.op == OpType::ALLGATHER || req.op == OpType::ALLTOALL) {
    // first dim may differ per rank; trailing dims must match
    if (std::vector<int64_t>(p.meta.shape.begin() +
                                 (p.meta.shape.empty() ? 0 : 1),
                             p.meta.shape.end()) !=
        std::vector<int64_t>(req.shape.begin() + (req.shape.empty() ? 0 : 1),
                             req.shape.end())) {
      mismatch = "trailing shape";
    }
  }
  if (!mismatch.empty()) {
    Response e;
    e.op = req.op;
    e.error = "Mismatched " + mismatch + " for tensor " + req.name +
              " across ranks";
    e.names.push_back(req.name);
    e.metas.push_back(p.meta);
    // The announcing rank receives this error through the cycle broadcast
    // (its handle maps by name) — it is informed, not owed a tombstone.
    std::set<int> informed = p.announced;
    informed.insert(rank);
    AddTombstone(req.name, e.error, informed);
    errors->push_back(std::move(e));
    pending_.erase(it);
    return;
  }
  // Device-plane coherence: the response's plane is the AND of every
  // rank's capability bit — deliberately NOT a mismatch error (a host
  // numpy on one rank simply demotes the collective to the host plane).
  p.meta.device = p.meta.device & req.device;
  if (p.announced.insert(rank).second) {
    // How long after the tensor's first announcement this rank's own
    // arrived: the culprit-side signal the straggler report ranks by.
    RecordAnnounceLag(rank, MonotonicSeconds() - p.first_seen);
  }
}

void SocketController::AddTombstone(const std::string& name,
                                    const std::string& error,
                                    const std::set<int>& already_informed) {
  std::vector<int> members;
  // Owed = process-set members that had not announced when the error was
  // emitted (their announce may still be in flight, or they may be
  // stragglers).  Ranks that announced get the error via their handles.
  auto it = pending_.find(name);
  int psid = it != pending_.end() ? it->second.meta.process_set_id : 0;
  if (!process_sets_.Ranks(psid, &members)) return;
  Tombstone t;
  t.error = error;
  t.expiry = MonotonicSeconds() + 60.0;
  for (int m : members) {
    if (!already_informed.count(m)) t.owed.insert(m);
  }
  if (!t.owed.empty()) error_tombstones_[name] = std::move(t);
}

Status SocketController::CoordinatorCycle(
    std::vector<TensorRequest>& new_requests, std::vector<Response>* out) {
  std::vector<Response> errors;
  // Sweep expired tombstones (bounded memory on long-running jobs).
  for (auto it = error_tombstones_.begin(); it != error_tombstones_.end();) {
    if (MonotonicSeconds() >= it->second.expiry) {
      it = error_tombstones_.erase(it);
    } else {
      ++it;
    }
  }
  // Own announcements first (deterministic: coordinator, then source order).
  for (auto& r : new_requests) Announce(0, std::move(r), &errors);
  // Gather sources.  Flat: every worker.  Tree: this host's children
  // (individual frames) plus the coordinator's aggregate children ([-3]
  // frames) — at depth 2 those are all other hosts' leaders (v9); at v12
  // depth >= 3 only the top-level super-leaders, which keeps coordinator
  // fan-in <= fanout at any host count.
  std::vector<int> sources;
  if (tree_.on) {
    sources = tree_.my_children;
    for (int l : tree_.agg_children) sources.push_back(l);
  } else {
    for (int rank = 1; rank < cfg_.size; ++rank) sources.push_back(rank);
  }
  for (int rank : sources) {
    if (departed_ranks_.count(rank)) continue;
    const bool is_leader_src =
        tree_.on && std::find(tree_.leaders.begin(), tree_.leaders.end(),
                              rank) != tree_.leaders.end();
    if (FaultInjectionOn()) {
      // Site rank = the REMOTE peer whose frame is being gathered; closing
      // its ctrl socket makes the recv below fail like a death.  In tree
      // mode the coordinator doubles as host 0's leader, so its own-host
      // children are leader-recv sites; remote leaders stay
      // coordinator-recv.
      const FaultSite site = (tree_.on && !is_leader_src)
                                 ? kFaultLeaderRecv
                                 : kFaultCoordinatorRecv;
      FaultAction fa = FaultCheck(site, rank);
      if (fa == FaultAction::kDrop || fa == FaultAction::kTruncate) {
        ctrl_socks_[rank].Close();
      }
    }
    std::string frame;
    if (!ctrl_socks_[rank].RecvFrame(&frame)) {
      return BroadcastAbortAndFail(
          rank, "lost connection to rank " + std::to_string(rank));
    }
    CountCtrlRecv(frame.size());
    Reader rd(frame);
    int32_t n_cached = rd.GetI32();
    if (n_cached == -1) {  // BYE: clean exit
      // v11: the BYE carries the sender's FINAL cumulative sketch (a
      // leader's: its whole host's sum).  Stored as the source's last
      // word, it keeps the fleet histograms bucket-exact after departure.
      ReadFleetSketch(rank, &rd);
      departed_ranks_.insert(rank);
      HVD_LOG(INFO) << "rank " << rank << " shut down cleanly";
      if (is_leader_src) {
        // A departing leader severs its subtree: any descendant still
        // running has lost its aggregation path, so the coordinator stops
        // expecting its announcements rather than hanging tensors on a
        // mute branch.  v12: the subtree is the whole branch below the
        // leader (its host, plus every clustered host under it when it
        // was a super-leader), not just its own host.
        for (int r : SubtreeOf(rank)) {
          if (r != rank && departed_ranks_.insert(r).second) {
            HVD_LOG(INFO) << "rank " << r << " departed with its leader "
                          << rank;
          }
        }
      }
      continue;
    }
    if (n_cached == -2) {  // failure FIN: the peer saw a failure first
      std::string why = rd.GetString();
      if (!rd.ok() || why.empty()) {
        why = "rank " + std::to_string(rank) + " reported a failure";
      }
      int culprit = rank;
      // v9: explicit culprit trailer (a leader forwards a child's FIN
      // verbatim — the sender is not the culprit).
      const int32_t c = rd.GetI32();
      if (rd.ok() && c >= 0 && c < cfg_.size) culprit = c;
      return BroadcastAbortAndFail(culprit, why);
    }
    if (n_cached == -3) {  // v9 leader aggregate
      if (!is_leader_src || !ParseAggregate(rank, &rd, &errors)) {
        return BroadcastAbortAndFail(rank,
                                     "malformed aggregate frame from rank " +
                                         std::to_string(rank));
      }
      continue;
    }
    ParseCachedPairs(rank, n_cached, &rd, &errors);
    // v11: the sender's cumulative telemetry sketch rides between the
    // cached pairs and the full requests.
    ReadFleetSketch(rank, &rd);
    ParseFullAndMetrics(rank, rd.GetI32(), &rd, &errors);
  }

  // Fusion phase: everything between the gather and the finished response
  // list — readiness collection, group gating, FuseRequests, QoS ordering,
  // cache/seq bookkeeping.  This is the coordinator's per-cycle "thinking"
  // span the step trace attributes to kPhaseFusion.
  const double fuse_t0 = StepTraceOn() ? MonotonicSeconds() : 0.0;
  // Collect ready tensors in deterministic (arrival-order) sequence.
  // Joined ranks (hvd.join) count as announced for every tensor — they
  // will participate with zero contributions.
  std::vector<std::pair<int64_t, std::string>> ready_names;
  std::vector<std::string> join_rejected;
  for (auto& kv : pending_) {
    std::vector<int> members;
    if (!process_sets_.Ranks(kv.second.meta.process_set_id, &members)) {
      continue;  // set not registered yet on this (coordinator) rank
    }
    bool ready = true;
    bool via_join = false;
    int departed = -1;
    for (int m : members) {
      if (departed_ranks_.count(m)) {
        departed = m;  // a member left: this tensor can never complete
        break;
      }
      if (!kv.second.announced.count(m)) {
        if (kv.second.meta.op != OpType::JOIN && joined_ranks_.count(m)) {
          via_join = true;
          continue;
        }
        ready = false;
        break;
      }
    }
    if (departed >= 0) {
      Response e;
      e.op = kv.second.meta.op;
      e.error = "tensor " + kv.first + " cannot complete: rank " +
                std::to_string(departed) + " has shut down";
      e.names.push_back(kv.first);
      e.metas.push_back(kv.second.meta);
      AddTombstone(kv.first, e.error, kv.second.announced);
      errors.push_back(std::move(e));
      join_rejected.push_back(kv.first);
      if (kv.second.meta.op == OpType::JOIN) {
        // The join round is dead: forget who joined, or stragglers would
        // keep zero-filling for ranks that think they aborted.
        joined_ranks_.clear();
        last_joined_ = -1;
      }
      continue;
    }
    if (!ready) continue;
    if (via_join) {
      // Zero contribution only makes sense for summing allreduces and
      // barriers (reference: Join supports allreduce/barrier; min/max/
      // product and data-bearing gathers have no neutral element here).
      const auto& meta = kv.second.meta;
      bool allowed =
          meta.op == OpType::BARRIER ||
          (meta.op == OpType::ALLREDUCE &&
           (meta.reduce_op == ReduceOp::SUM ||
            meta.reduce_op == ReduceOp::AVERAGE));
      if (!allowed) {
        Response e;
        e.op = meta.op;
        e.error = "tensor " + kv.first +
                  " became ready while some ranks had joined; only "
                  "sum/average allreduce and barrier may proceed after "
                  "hvd.join()";
        e.names.push_back(kv.first);
        e.metas.push_back(meta);
        AddTombstone(kv.first, e.error, kv.second.announced);
        errors.push_back(std::move(e));
        join_rejected.push_back(kv.first);
        continue;
      }
      // A joined rank zero-participates through the HOST plane (it has no
      // local tensor to place on a device); demote the whole collective so
      // every member walks the same ring.
      kv.second.meta.device = 0;
    }
    ready_names.emplace_back(kv.second.order, kv.first);
  }
  for (const auto& name : join_rejected) pending_.erase(name);
  // Atomic group gating (GateAndOrderGroups, group_table.cc analog):
  // members of incomplete groups are withheld — they simply REMAIN in
  // pending_ for a later cycle; complete groups come out contiguous.
  std::vector<std::string> ordered;
  std::vector<std::pair<int64_t, std::string>> withheld;
  GateAndOrderGroups(std::move(ready_names), &withheld, &ordered,
                     [this](const std::string& n) -> const TensorRequest& {
                       return pending_[n].meta;
                     });
  // JOIN completion must come after every via-join collective of the same
  // cycle: once a rank's executor processes the JOIN it stops zero-
  // participating, so a later-ordered via-join response would hang the
  // ring.  The partition is deterministic, so all ranks stay identical.
  std::stable_partition(
      ordered.begin(), ordered.end(), [this](const std::string& n) {
        auto it = pending_.find(n);
        return it != pending_.end() && it->second.meta.op != OpType::JOIN;
      });
  std::vector<TensorRequest> ready;
  ready.reserve(ordered.size());
  for (auto& name : ordered) {
    ready.push_back(pending_[name].meta);
    pending_.erase(name);
  }

  *out = FuseRequests(ready, cfg_.fusion_threshold);
  for (auto& r : *out) {
    if (r.op == OpType::JOIN) {
      // Everyone joined: report the last joiner and reset join state.
      r.last_joined = last_joined_;
      joined_ranks_.clear();
      last_joined_ = -1;
    }
  }
  // QoS tenant scheduling: order this cycle's fused responses by
  // descending process-set weight (stable, so equal-weight traffic —
  // including everything before the first add_process_set(weight=) —
  // keeps its deterministic arrival order).  Running BEFORE seq
  // assignment and the broadcast means every rank executes the same
  // weight-ordered schedule, so a heavy background tenant cannot push a
  // high-weight training set's collectives to the back of the cycle.
  std::stable_sort(out->begin(), out->end(),
                   [this](const Response& a, const Response& b) {
                     return process_sets_.Weight(a.process_set_id) >
                            process_sets_.Weight(b.process_set_id);
                   });
  out->insert(out->begin(), errors.begin(), errors.end());
  UpdateCachesAndSeq(out);
  if (fuse_t0 > 0.0) {
    StepTraceAddPhaseUs(
        kPhaseFusion,
        static_cast<int64_t>((MonotonicSeconds() - fuse_t0) * 1e6));
  }
  if (StepTraceOn()) {
    // A cycle that ships at least one real fused response closes a step.
    // The coordinator advances here; workers follow from the RESPONSES
    // trailer below, so every rank counts the same steps.
    bool step_work = false;
    for (const auto& r : *out) {
      if (r.error.empty() && !r.metas.empty()) {
        step_work = true;
        break;
      }
    }
    if (step_work) {
      StepTraceAdvance(StepTraceCurrentStep() + 1);
      int64_t sid = 0;
      int64_t phases[kStepPhases];
      if (StepTraceLastCompleted(&sid, phases)) {
        // The coordinator's own snapshot joins the fleet view directly —
        // its trailer never crosses a socket.
        StepTraceFleetPhases(0, sid, phases);
      }
    }
  }

  // Broadcast the identical response list down the gather topology: every
  // direct source gets one frame; tree leaders fan their copy out to their
  // children verbatim.  v10: an unconditional step-id trailer follows the
  // responses — the coordinator's current step (-1 when tracing is off) —
  // which workers use to advance their own step rings in lockstep.
  Writer w;
  w.PutI32(static_cast<int32_t>(out->size()));
  for (const auto& r : *out) SerializeResponse(r, &w);
  w.PutI64(StepTraceOn() ? StepTraceCurrentStep() : -1);
  const std::string payload = w.data();
  for (int rank : sources) {
    if (departed_ranks_.count(rank)) continue;
    CountCtrlSend(payload.size());
    if (!ctrl_socks_[rank].SendFrame(payload)) {
      return BroadcastAbortAndFail(rank,
                                   "failed to send responses to rank " +
                                       std::to_string(rank));
    }
  }
  if (MetricsOn()) {
    double now = MonotonicSeconds();
    FillSelfSnapshot(now);
    MaybeStragglerReport(now);
    // v11 fleet tick (~1 Hz): history sample + goodput + the anomaly
    // sentinel, fed the live fleet sum and the coordinator's data-plane
    // byte totals (raw/wire ratio drift is a sentinel series).
    if (FleetTelemetryOn() && now - last_fleet_tick_ >= 1.0) {
      last_fleet_tick_ = now;
      int64_t local = 0, xhost = 0, raw_local = 0, raw_xhost = 0;
      DataPlaneStats(&local, &xhost, &raw_local, &raw_xhost);
      FleetTelemetryTick(FleetSum(), local + xhost, raw_local + raw_xhost);
    }
  }
  return Status::OK();
}

void SocketController::RecordAnnounceLag(int rank, double lag_s) {
  if (StepTraceOn()) {
    // Announce lag is the dominant-rank signal: the coordinator waited
    // this long between the first announcement of a tensor and this
    // rank's, attributed to the step currently forming.
    StepTraceFleetLagUs(rank, static_cast<int64_t>(lag_s * 1e6));
  }
  if (!MetricsOn()) return;
  if (rank < 0 || rank >= static_cast<int>(announce_lag_.size())) return;
  announce_lag_[rank]->ObserveSeconds(lag_s);
}

void SocketController::FillSelfSnapshot(double now) {
  const auto& m = GlobalMetrics();
  RankMetricsSnapshot s;
  s.neg_count = m.negotiation_wait_us.count.load(std::memory_order_relaxed);
  s.neg_sum_us = m.negotiation_wait_us.sum_us.load(std::memory_order_relaxed);
  s.neg_p50_us = m.negotiation_wait_us.QuantileUs(0.5);
  s.neg_p99_us = m.negotiation_wait_us.QuantileUs(0.99);
  s.cycle_busy_us = m.cycle_busy_us.load(std::memory_order_relaxed);
  s.cycle_idle_us = m.cycle_idle_us.load(std::memory_order_relaxed);
  s.cycle_count = m.cycle_count.load(std::memory_order_relaxed);
  s.updated_at = now;
  std::lock_guard<std::mutex> l(metrics_mu_);
  if (!cluster_.empty()) cluster_[0] = s;
}

void SocketController::MaybeStragglerReport(double now) {
  if (cfg_.size < 2 || announce_lag_.empty()) return;
  if (now - last_metrics_report_ < metrics_report_s_) return;
  last_metrics_report_ = now;
  // Mean announce lag per rank over the window since the last report.
  std::vector<double> mean_us(cfg_.size, 0.0);
  std::vector<int64_t> window_count(cfg_.size, 0);
  int64_t any = 0;
  for (int r = 0; r < cfg_.size; ++r) {
    int64_t c = announce_lag_[r]->count.load(std::memory_order_relaxed);
    int64_t s = announce_lag_[r]->sum_us.load(std::memory_order_relaxed);
    int64_t dc = c - announce_prev_[r].first;
    int64_t ds = s - announce_prev_[r].second;
    announce_prev_[r] = {c, s};
    if (dc > 0) mean_us[r] = static_cast<double>(ds) / dc;
    window_count[r] = dc;
    any += dc;
  }
  if (any == 0) return;
  std::vector<double> sorted = mean_us;
  std::sort(sorted.begin(), sorted.end());
  double median = sorted[sorted.size() / 2];
  double threshold = std::max(straggler_skew_ * median, straggler_min_us_);
  std::ostringstream os;
  bool found = false;
  std::vector<int> flagged;
  for (int r = 0; r < cfg_.size; ++r) {
    if (window_count[r] == 0 || mean_us[r] <= threshold) continue;
    if (found) os << "; ";
    found = true;
    flagged.push_back(r);
    const std::string host =
        r < static_cast<int>(host_keys_.size()) ? host_keys_[r] : "?";
    os << "rank " << r << " (host " << host << "): negotiation lag mean="
       << static_cast<int64_t>(mean_us[r] / 1000) << "ms p50="
       << announce_lag_[r]->QuantileUs(0.5) / 1000 << "ms p99="
       << announce_lag_[r]->QuantileUs(0.99) / 1000
       << "ms vs fleet median " << static_cast<int64_t>(median / 1000)
       << "ms";
  }
  std::string report;
  if (found) {
    report = "straggler report: " + os.str();
    GlobalMetrics().straggler_reports_total.fetch_add(
        1, std::memory_order_relaxed);
    HVD_LOG(WARNING) << report;
  }
  // Every evaluated window (flagged or clean) advances the autopilot view:
  // the policy engine diffs `straggler_windows_` between polls, and a
  // clean window resetting straggler_ranks_ is what breaks an eviction
  // streak for a rank that recovered.
  std::lock_guard<std::mutex> l(metrics_mu_);
  ++straggler_windows_;
  straggler_ranks_ = std::move(flagged);
  if (!report.empty()) straggler_report_ = std::move(report);
}

std::string SocketController::ClusterMetricsJson() {
  if (!is_coordinator()) return "";
  std::ostringstream os;
  {
    std::lock_guard<std::mutex> l(metrics_mu_);
    os << "\"cluster\":{";
    for (size_t r = 0; r < cluster_.size(); ++r) {
      const auto& s = cluster_[r];
      if (r) os << ',';
      os << "\"" << r << "\":{\"neg_count\":" << s.neg_count
         << ",\"neg_sum_us\":" << s.neg_sum_us
         << ",\"neg_p50_us\":" << s.neg_p50_us
         << ",\"neg_p99_us\":" << s.neg_p99_us
         << ",\"cycle_busy_us\":" << s.cycle_busy_us
         << ",\"cycle_idle_us\":" << s.cycle_idle_us
         << ",\"cycle_count\":" << s.cycle_count
         << ",\"updated_at\":" << s.updated_at << "}";
    }
    os << "},\"straggler_report\":\"" << JsonEscape(straggler_report_) << "\"";
  }
  // v11: the live fleet view — this registry's capture plus every stored
  // source sketch — so hvd.metrics()["fleet"] and the Prometheus renderer
  // see true fleet histograms, not rank 0's.
  if (MetricsOn() && FleetTelemetryOn()) {
    os << ",\"fleet\":" << FleetSum().Json();
  }
  return os.str();
}

// ---------------------------------------------------------------------------
// Fleet-telemetry sketch plumbing (protocol v11; fleet_telemetry.h)
// ---------------------------------------------------------------------------

void SocketController::ReadFleetSketch(int rank, Reader* rd) {
  const std::string enc = rd->GetString();
  if (!rd->ok() || enc.empty()) return;
  FleetSketch s;
  // A sketch that fails to decode is dropped on its own — never the frame:
  // telemetry must not be able to abort a healthy job.
  if (s.Decode(enc.data(), enc.size())) StoreFleetSource(rank, std::move(s));
}

void SocketController::StoreFleetSource(int rank, FleetSketch&& s) {
  {
    std::lock_guard<std::mutex> l(fleet_mu_);
    fleet_sources_[rank] = std::move(s);
  }
  if (MetricsOn()) {
    GlobalMetrics().fleet_sketches_merged_total.fetch_add(
        1, std::memory_order_relaxed);
  }
}

FleetSketch SocketController::FleetSum() {
  FleetSketch fleet;
  if (MetricsOn() && FleetTelemetryOn()) fleet.CaptureLocal();
  std::lock_guard<std::mutex> l(fleet_mu_);
  for (const auto& kv : fleet_sources_) fleet.Merge(kv.second);
  return fleet;
}

int SocketController::FleetSourceCountForTest() {
  std::lock_guard<std::mutex> l(fleet_mu_);
  return static_cast<int>(fleet_sources_.size());
}

int64_t SocketController::FleetSumNegCountForTest() {
  return FleetSum().negotiation_wait.count;
}

// ---------------------------------------------------------------------------
// Fleet-autopilot policy channel (coordinator only)
// ---------------------------------------------------------------------------

std::string SocketController::PolicyStatusJson() {
  std::ostringstream os;
  std::lock_guard<std::mutex> l(metrics_mu_);
  os << "{\"v\":1,\"windows\":" << straggler_windows_ << ",\"culprits\":[";
  for (size_t i = 0; i < straggler_ranks_.size(); ++i) {
    if (i) os << ',';
    os << straggler_ranks_[i];
  }
  os << "],\"hosts\":[";
  // The coordinator's agreed host key per flagged rank: attribution the
  // driver feeds straight into the elastic blacklist (its own hostfile
  // names may differ from the rendezvous-agreed keys).
  for (size_t i = 0; i < straggler_ranks_.size(); ++i) {
    if (i) os << ',';
    const int r = straggler_ranks_[i];
    const std::string host =
        r >= 0 && r < static_cast<int>(host_keys_.size()) ? host_keys_[r]
                                                          : "";
    os << "\"" << JsonEscape(host) << "\"";
  }
  os << "],\"report\":\"" << JsonEscape(straggler_report_)
     // v11: the sentinel's anomaly log rides the same poll — an ADVISORY
     // signal the driver-side engine journals and may act on ahead of the
     // consecutive-window eviction rule.
     << "\",\"anomalies\":" << FleetAnomaliesJson()
     << ",\"size\":" << cfg_.size << "}";
  return os.str();
}

void SocketController::RecordAutopilotDecision(int action, int rank,
                                               const std::string& detail) {
  const char* name = action == kAutopilotActEvict      ? "evict"
                     : action == kAutopilotActScaleUp  ? "scale_up"
                     : action == kAutopilotActReadmit  ? "readmit"
                                                       : "unknown";
  GlobalMetrics().autopilot_decisions_total.fetch_add(
      1, std::memory_order_relaxed);
  if (FlightOn()) {
    FlightRecord(kFlightAutopilot, action, rank);
    // An eviction decision is usually followed by elastic teardown of this
    // very process: dump now so the record survives into the postmortem
    // bundle regardless of how the generation ends.
    FlightDumpToFile();
  }
  if (autopilot_hook_) autopilot_hook_(action, rank, detail);
  HVD_LOG(WARNING) << "autopilot decision: " << name << " rank=" << rank
                   << (detail.empty() ? "" : " (" + detail + ")");
}

void SocketController::PolicyServeLoop() {
  // One driver connection at a time (the autopilot keeps a single
  // persistent connection; a reconnect simply replaces it).  Commands are
  // newline-terminated text, replies one JSON line each:
  //   POLL                         -> PolicyStatusJson()
  //   DECISION <action> <rank> <detail...> -> {"ok":true}
  Socket client;
  std::string acc;
  while (!policy_stop_.load(std::memory_order_relaxed)) {
    if (!client.valid()) {
      client = policy_listener_.Accept(0.2);
      if (!client.valid()) continue;
      acc.clear();
    }
    struct pollfd p;
    p.fd = client.fd();
    p.events = POLLIN;
    p.revents = 0;
    const int rv = ::poll(&p, 1, 200);
    if (rv < 0 && errno != EINTR) {
      client.Close();
      continue;
    }
    if (rv <= 0) continue;
    char buf[4096];
    const ssize_t n = ::recv(client.fd(), buf, sizeof(buf), 0);
    if (n <= 0) {
      client.Close();
      continue;
    }
    acc.append(buf, static_cast<size_t>(n));
    size_t nl;
    while (client.valid() && (nl = acc.find('\n')) != std::string::npos) {
      std::string line = acc.substr(0, nl);
      acc.erase(0, nl + 1);
      if (!line.empty() && line.back() == '\r') line.pop_back();
      std::string reply;
      if (line == "POLL") {
        reply = PolicyStatusJson();
      } else if (line.rfind("DECISION ", 0) == 0) {
        int action = 0, rank = -1, consumed = 0;
        if (std::sscanf(line.c_str() + 9, "%d %d%n", &action, &rank,
                        &consumed) >= 2 &&
            action >= kAutopilotActEvict && action <= kAutopilotActReadmit) {
          std::string detail = line.substr(9 + consumed);
          if (!detail.empty() && detail.front() == ' ') detail.erase(0, 1);
          RecordAutopilotDecision(action, rank, detail);
          reply = "{\"ok\":true}";
        } else {
          reply = "{\"ok\":false,\"error\":\"malformed DECISION\"}";
        }
      } else {
        reply = "{\"ok\":false,\"error\":\"unknown command\"}";
      }
      reply.push_back('\n');
      if (!client.SendAll(reply.data(), reply.size())) client.Close();
    }
    if (acc.size() > kPolicyMaxLine) client.Close();  // runaway garbage
  }
}

std::string SocketController::BuildCycleFrame(
    const std::vector<TensorRequest>& new_requests) {
  Writer w;
  // Cache hits travel as (id, handle) pairs — the id is the reference's
  // bit-vector fast path; the per-submission handle rides along so a
  // tombstone error delivery can echo the announcing rank's own current
  // submission (not the stale handle stored in the cache by the first
  // announcer of an earlier negotiation).
  std::vector<std::pair<int64_t, int64_t>> cached;
  std::vector<const TensorRequest*> full;
  const bool use_cache = announce_cache_.load(std::memory_order_relaxed);
  for (const auto& r : new_requests) {
    int64_t id = use_cache ? cache_.Lookup(r) : -1;
    if (id >= 0) {
      cached.emplace_back(id, r.handle);
    } else {
      full.push_back(&r);
    }
  }
  w.PutI32(static_cast<int32_t>(cached.size()));
  for (auto& [id, handle] : cached) {
    w.PutI64(id);
    w.PutI64(handle);
  }
  // v11 sketch section: this rank's cumulative telemetry sketch, placed
  // between the cached pairs and the full requests so a leader can peel it
  // off cheaply while the rest of the tail forwards verbatim.  An empty
  // string when the plane (or the registry feeding it) is off — the
  // length prefix keeps the frame shape fixed either way.
  const double sk_now = MonotonicSeconds();
  if (MetricsOn() && FleetTelemetryOn() &&
      sk_now - fleet_last_encode_ >= kFleetEncodeIntervalS) {
    fleet_last_encode_ = sk_now;
    FleetSketch sk;
    sk.CaptureLocal();
    w.PutString(sk.Encode());
  } else {
    w.PutString("");
  }
  w.PutI32(static_cast<int32_t>(full.size()));
  for (const auto* r : full) SerializeRequest(*r, &w);
  // v7 trailer: piggyback this rank's metrics snapshot (cumulative) on
  // the cycle frame it sends anyway — the coordinator's cluster view
  // costs no extra round trips.  v10 extends it: marker 2 carries the
  // same 7 metric i64s (zeros when the registry is off) followed by this
  // rank's last completed step snapshot (step id + kStepPhases phase
  // sums), feeding the coordinator's fleet attribution.
  int64_t st_sid = 0;
  int64_t st_phases[kStepPhases];
  const bool has_step =
      StepTraceOn() && StepTraceLastCompleted(&st_sid, st_phases);
  if (MetricsOn() || has_step) {
    w.PutI32(has_step ? 2 : 1);
    if (MetricsOn()) {
      const auto& m = GlobalMetrics();
      w.PutI64(m.negotiation_wait_us.count.load(std::memory_order_relaxed));
      w.PutI64(m.negotiation_wait_us.sum_us.load(std::memory_order_relaxed));
      w.PutI64(m.negotiation_wait_us.QuantileUs(0.5));
      w.PutI64(m.negotiation_wait_us.QuantileUs(0.99));
      w.PutI64(m.cycle_busy_us.load(std::memory_order_relaxed));
      w.PutI64(m.cycle_idle_us.load(std::memory_order_relaxed));
      w.PutI64(m.cycle_count.load(std::memory_order_relaxed));
    } else {
      for (int i = 0; i < 7; ++i) w.PutI64(0);
    }
    if (has_step) {
      w.PutI64(st_sid);
      for (int p = 0; p < kStepPhases; ++p) w.PutI64(st_phases[p]);
    }
  } else {
    w.PutI32(0);
  }
  return std::string(w.data());
}

void SocketController::ParseResponsesTail(Reader* rd, int32_t n,
                                          std::vector<Response>* out) {
  out->clear();
  out->reserve(n);
  for (int32_t i = 0; i < n; ++i) out->push_back(DeserializeResponse(rd));
  // Local seq counter mirrors the coordinator's (sanity only) and caches are
  // updated from the metas carried by each response — identical on all
  // ranks, so cache ids agree without extra synchronisation.
  for (auto& r : *out) {
    if (r.error.empty()) {
      for (const auto& m : r.metas) cache_.Insert(m);
      if (r.seq >= 0) {
        seq_counter_ = r.seq + 1;
        if (r.hier || r.wire_comp != 0) {
          std::lock_guard<std::mutex> l(hier_mu_);
          plane_by_seq_[r.seq] = {r.hier,
                                  static_cast<WireCodec>(r.wire_comp)};
        }
      }
    }
  }
  // v10 step-id trailer: the coordinator's current step after this cycle
  // (-1 when tracing is off there).  Absent on pre-v10 coordinators —
  // tolerated so mixed builds don't tear the frame apart mid-upgrade.
  if (rd->remaining() >= 8) {
    const int64_t sid = rd->GetI64();
    if (rd->ok() && sid > StepTraceCurrentStep() && StepTraceOn()) {
      StepTraceAdvance(sid);
    }
  }
}

Status SocketController::WorkerCycle(std::vector<TensorRequest>& new_requests,
                                     std::vector<Response>* out) {
  const std::string payload = BuildCycleFrame(new_requests);
  Socket& up = UpLink();
  const bool via_leader = (&up == &tree_parent_);
  CountCtrlSend(payload.size());
  if (!up.SendFrame(payload)) {
    aborted_ = true;
    // A dead leader is not a dead job: the coordinator's direct ABORT
    // broadcast still reaches this rank on coord_ctrl_, so run the
    // handshake for real culprit attribution instead of guessing.
    if (via_leader) return WorkerAbortHandshake();
    return Status::Error(StatusCode::ABORTED, "lost coordinator (send)");
  }
  std::string frame;
  if (!up.RecvFrame(&frame)) {
    aborted_ = true;
    if (via_leader) return WorkerAbortHandshake();
    return Status::Error(StatusCode::ABORTED, "lost coordinator (recv)");
  }
  CountCtrlRecv(frame.size());
  Reader rd(frame);
  int32_t n = rd.GetI32();
  if (n == -1) {  // coordinator farewell: the job is ending deliberately
    peer_shutdown_ = true;
    aborted_ = true;
    // Latch the reason so WaitAbortReason callers return immediately
    // instead of burning the propagation timeout at clean teardown.
    SetAbortReason("coordinator shut down the job");
    return Status::Error(StatusCode::ABORTED,
                         "coordinator shut down the job");
  }
  if (n == -2) {  // coordinator ABORT broadcast (protocol v8)
    return HandleAbortFrame(&rd);
  }
  ParseResponsesTail(&rd, n, out);
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Leader tree cycles (protocol v9)
// ---------------------------------------------------------------------------

void SocketController::ParseCachedPairs(int rank, int32_t n_cached, Reader* rd,
                                        std::vector<Response>* errors) {
  for (int32_t i = 0; i < n_cached; ++i) {
    int64_t id = rd->GetI64();
    int64_t handle = rd->GetI64();
    TensorRequest req;
    if (cache_.Get(id, &req)) {
      req.handle = handle;  // the announcer's own current submission
      Announce(rank, std::move(req), errors);
    } else {
      Response e;
      e.error = "response cache divergence: unknown cache id " +
                std::to_string(id) + " from rank " + std::to_string(rank);
      errors->push_back(std::move(e));
    }
  }
}

void SocketController::ParseFullAndMetrics(int rank, int32_t n_full,
                                           Reader* rd,
                                           std::vector<Response>* errors) {
  for (int32_t i = 0; i < n_full; ++i) {
    Announce(rank, DeserializeRequest(rd), errors);
  }
  // v7 trailer: the rank's piggybacked metrics snapshot (cumulative;
  // marker 0 when nothing piggybacks).  v10 marker 2 appends the rank's
  // last completed step snapshot; its metric slots are zero-filled when
  // the sender's registry is off, so cluster_ only stores real ones
  // (cycle_count > 0 — a live registry always counts cycles).
  int32_t has_metrics = rd->GetI32();
  if (has_metrics == 1 || has_metrics == 2) {
    RankMetricsSnapshot s;
    s.neg_count = rd->GetI64();
    s.neg_sum_us = rd->GetI64();
    s.neg_p50_us = rd->GetI64();
    s.neg_p99_us = rd->GetI64();
    s.cycle_busy_us = rd->GetI64();
    s.cycle_idle_us = rd->GetI64();
    s.cycle_count = rd->GetI64();
    s.updated_at = MonotonicSeconds();
    if (s.cycle_count > 0) {
      std::lock_guard<std::mutex> l(metrics_mu_);
      if (rank >= 0 && rank < static_cast<int>(cluster_.size())) {
        cluster_[rank] = s;
      }
    }
  }
  if (has_metrics == 2) {
    const int64_t sid = rd->GetI64();
    int64_t phases[kStepPhases];
    for (int p = 0; p < kStepPhases; ++p) phases[p] = rd->GetI64();
    if (rd->ok() && StepTraceOn()) {
      StepTraceFleetPhases(rank, sid, phases);
    }
  }
}

bool SocketController::ParseAggregate(int leader, Reader* rd,
                                      std::vector<Response>* errors) {
  // v9 aggregate: [n_groups] { [i64 cache_id][i32 k] k x ([i32 rank]
  // [i64 handle]) } [n_rest] { [i32 rank][string rest] } — the leader's
  // host-merged cached announcements, then each member's un-merged frame
  // tail (full requests + metrics trailer), or its whole BYE frame.
  // v11 prepends the leader's host-summed sketch section, stored under the
  // leader's rank so coordinator fleet state stays O(hosts).
  ReadFleetSketch(leader, rd);
  const int32_t n_groups = rd->GetI32();
  if (!rd->ok() || n_groups < 0) return false;
  for (int32_t g = 0; g < n_groups; ++g) {
    const int64_t id = rd->GetI64();
    const int32_t k = rd->GetI32();
    if (!rd->ok() || k < 0) return false;
    TensorRequest cached_req;
    const bool known = cache_.Get(id, &cached_req);
    for (int32_t i = 0; i < k; ++i) {
      const int32_t rank = rd->GetI32();
      const int64_t handle = rd->GetI64();
      if (!rd->ok() || rank < 0 || rank >= cfg_.size) return false;
      if (known) {
        TensorRequest req = cached_req;
        req.handle = handle;
        Announce(rank, std::move(req), errors);
      } else {
        Response e;
        e.error = "response cache divergence: unknown cache id " +
                  std::to_string(id) + " from rank " + std::to_string(rank);
        errors->push_back(std::move(e));
      }
    }
  }
  const int32_t n_rest = rd->GetI32();
  if (!rd->ok() || n_rest < 0) return false;
  for (int32_t i = 0; i < n_rest; ++i) {
    const int32_t rank = rd->GetI32();
    if (!rd->ok() || rank < 0 || rank >= cfg_.size) return false;
    const std::string rest = rd->GetString();
    if (!rd->ok()) return false;
    Reader rr(rest);
    const int32_t first = rr.GetI32();
    if (first == -1) {  // the member's BYE, forwarded by its leader
      // v11: the forwarded BYE's trailing sketch is deliberately SKIPPED —
      // the leader folded the child's final sketch into its own host sum,
      // so reading it here would double-count the host.  v12: when the
      // departing rank is itself a leader (a super-leader forwarded a
      // child leader's BYE), its whole subtree departs with it — those
      // ranks have lost their aggregation path.
      DepartSubtree(rank);
      HVD_LOG(INFO) << "rank " << rank << " shut down cleanly (via leader "
                    << leader << ")";
      continue;
    }
    if (first < 0) return false;
    ParseFullAndMetrics(rank, first, &rr, errors);
    if (!rr.ok()) return false;
  }
  return rd->ok();
}

bool SocketController::FanDownToChildren(const std::string& frame,
                                         int* failed_child) {
  bool ok = true;
  for (auto& [rank, sock] : tree_child_socks_) {
    if (tree_departed_children_.count(rank) || !sock.valid()) continue;
    CountCtrlSend(frame.size());
    if (!sock.SendFrame(frame)) {
      if (failed_child) *failed_child = rank;
      ok = false;
    }
  }
  return ok;
}

Status SocketController::LeaderFinUp(int culprit, const std::string& why,
                                     const std::string* forward_frame) {
  aborted_ = true;
  if (!fin_sent_) {
    fin_sent_ = true;
    // Up the TREE first (v12: a clustered leader's parent is a super-
    // leader whose gather loop relays the FIN hop by hop until it lands
    // on a rendezvous socket the coordinator reads in-cycle), plus a
    // best-effort direct copy so attribution survives a dead ancestor.
    Socket& up = UpLink();
    const std::string* frame = forward_frame;
    Writer w;
    if (frame == nullptr) {
      w.PutI32(-2);  // failure FIN in the cycle-frame position
      w.PutString(why);
      w.PutI32(culprit);
    }
    const std::string& payload = frame != nullptr ? *frame : w.data();
    if (up.valid()) up.SendFrame(payload);  // best effort
    if (&up != &coord_ctrl_ && coord_ctrl_.valid()) {
      coord_ctrl_.SendFrame(payload);
    }
  }
  // Await the coordinator's ABORT (and fan it down to surviving children)
  // so every rank of this subtree reports the same culprit.
  return WorkerAbortHandshake();
}

Status SocketController::LeaderCycle(std::vector<TensorRequest>& new_requests,
                                     std::vector<Response>* out) {
  // An empty member tail is [n_full=0][has_metrics=0]: skip it in the
  // aggregate — idle ranks then cost 12 bytes (rank + empty pair list)
  // instead of a whole frame.
  static const std::string kEmptyTail(8, '\0');
  const std::string own = BuildCycleFrame(new_requests);
  // id -> (rank, handle) announcements merged across this host.  std::map
  // keeps aggregate bytes deterministic.
  std::map<int64_t, std::vector<std::pair<int32_t, int64_t>>> groups;
  std::vector<std::pair<int32_t, std::string>> rests;
  auto merge_frame = [&](int32_t rank, const std::string& frame) -> bool {
    Reader rd(frame);
    const int32_t n_cached = rd.GetI32();
    if (!rd.ok() || n_cached < 0) return false;
    for (int32_t i = 0; i < n_cached; ++i) {
      const int64_t id = rd.GetI64();
      const int64_t handle = rd.GetI64();
      groups[id].emplace_back(rank, handle);
    }
    if (!rd.ok()) return false;
    // v11: peel the member's sketch out of the frame — the leader sums
    // every member's into ONE aggregate sketch so coordinator inbound
    // stays O(hosts) — leaving the rest (full requests + metrics
    // trailer) to forward verbatim, sketch-free.
    const std::string enc = rd.GetString();
    if (!rd.ok()) return false;
    if (!enc.empty()) {
      FleetSketch s;
      if (s.Decode(enc.data(), enc.size())) {
        tree_child_sketches_[rank] = std::move(s);
      }
    }
    std::string rest(rd.cursor(), rd.remaining());
    if (rest != kEmptyTail) rests.emplace_back(rank, std::move(rest));
    return true;
  };
  // v12: a super-leader merges a downstream leader's whole [-3] aggregate
  // — subtree-summed sketch (replaces that child's last-known, keeping the
  // running sum bucket-exact), cached groups unioned by id, rests appended
  // verbatim — into the same `groups`/`rests` its worker children feed.
  auto merge_aggregate = [&](int32_t child, const std::string& frame) -> bool {
    Reader rd(frame);
    if (rd.GetI32() != -3 || !rd.ok()) return false;
    const std::string enc = rd.GetString();
    if (!rd.ok()) return false;
    if (!enc.empty()) {
      FleetSketch s;
      if (s.Decode(enc.data(), enc.size())) {
        tree_child_sketches_[child] = std::move(s);
      }
    }
    const int32_t n_groups = rd.GetI32();
    if (!rd.ok() || n_groups < 0) return false;
    for (int32_t g = 0; g < n_groups; ++g) {
      const int64_t id = rd.GetI64();
      const int32_t k = rd.GetI32();
      if (!rd.ok() || k < 0) return false;
      for (int32_t i = 0; i < k; ++i) {
        const int32_t rank = rd.GetI32();
        const int64_t handle = rd.GetI64();
        if (!rd.ok() || rank < 0 || rank >= cfg_.size) return false;
        groups[id].emplace_back(rank, handle);
      }
    }
    const int32_t n_rest = rd.GetI32();
    if (!rd.ok() || n_rest < 0) return false;
    for (int32_t i = 0; i < n_rest; ++i) {
      const int32_t rank = rd.GetI32();
      if (!rd.ok() || rank < 0 || rank >= cfg_.size) return false;
      std::string rest = rd.GetString();
      if (!rd.ok()) return false;
      rests.emplace_back(rank, std::move(rest));
    }
    return rd.ok();
  };
  merge_frame(cfg_.rank, own);
  int32_t merged_frames = 1;  // own frame
  // Gather this host's workers first, then (v12) downstream leaders'
  // aggregates.  One flat list keeps the failure handling identical: a
  // dead link, BYE, or FIN from either kind takes the same path.
  std::vector<std::pair<int, bool>> gather;  // (child rank, is aggregate)
  for (int c : tree_.my_children) gather.emplace_back(c, false);
  for (int c : tree_.agg_children) gather.emplace_back(c, true);
  for (const auto& [child, is_agg] : gather) {
    if (tree_departed_children_.count(child)) continue;
    Socket* cs = TreeChildSock(child);
    if (cs == nullptr) continue;
    if (FaultInjectionOn()) {
      // Site rank = the REMOTE child whose frame this leader is gathering;
      // closing the link makes the recv below fail like a child death.
      // Worker children are leader-recv sites; downstream leaders' links
      // are the v12 super-recv sites.
      FaultAction fa =
          FaultCheck(is_agg ? kFaultSuperRecv : kFaultLeaderRecv, child);
      if (fa == FaultAction::kDrop || fa == FaultAction::kTruncate) {
        cs->Close();
      }
    }
    std::string frame;
    if (!cs->RecvFrame(&frame)) {
      return LeaderFinUp(child,
                         "leader rank " + std::to_string(cfg_.rank) +
                             " lost connection to rank " +
                             std::to_string(child),
                         nullptr);
    }
    CountCtrlRecv(frame.size());
    Reader rd(frame);
    const int32_t first = rd.GetI32();
    if (first == -1) {  // child BYE: forward the whole frame as its tail
      // v11: keep the child's FINAL sketch so the running sum stays exact
      // after it departs (a leader child's BYE carries its whole subtree's
      // final sum).  The coordinator skips the sketch on the forwarded
      // BYE — this node's aggregate already carries it.
      const std::string enc = rd.GetString();
      if (rd.ok() && !enc.empty()) {
        FleetSketch s;
        if (s.Decode(enc.data(), enc.size())) {
          tree_child_sketches_[child] = std::move(s);
        }
      }
      tree_departed_children_.insert(child);
      rests.emplace_back(child, frame);
      continue;
    }
    if (first == -2) {  // child failure FIN: forward verbatim, abort
      std::string why = rd.GetString();
      int culprit = child;
      const int32_t c = rd.GetI32();
      if (rd.ok() && c >= 0 && c < cfg_.size) culprit = c;
      if (!rd.ok() || why.empty()) {
        why = "rank " + std::to_string(child) + " reported a failure";
      }
      return LeaderFinUp(culprit, why, &frame);
    }
    if (is_agg ? !merge_aggregate(child, frame)
               : !merge_frame(child, frame)) {
      return LeaderFinUp(child,
                         (is_agg ? "malformed aggregate frame from rank "
                                 : "malformed cycle frame from rank ") +
                             std::to_string(child),
                         nullptr);
    }
    ++merged_frames;
  }
  // Tree-aggregate merge: the leader's share of the fusion phase (the
  // coordinator's fuse/gate span is measured in CoordinatorCycle).
  const double agg_t0 = StepTraceOn() ? MonotonicSeconds() : 0.0;
  Writer w;
  w.PutI32(-3);  // leader aggregate sentinel in the cycle-frame position
  // v11: ONE subtree-summed sketch per aggregate — own + every member's
  // last-known (a map entry per member only exists once its frame carried
  // a non-empty section, so an all-off subtree writes an empty string).
  // v12: entries under downstream-leader ranks already hold their whole
  // subtree's sum, and rank keys are disjoint across subtrees, so one flat
  // Merge stays bucket-exact at any depth.
  const double hs_now = MonotonicSeconds();
  if (tree_child_sketches_.empty() ||
      hs_now - fleet_leader_last_encode_ < kFleetEncodeIntervalS) {
    w.PutString("");
  } else {
    fleet_leader_last_encode_ = hs_now;
    FleetSketch subtree_sum;
    for (const auto& kv : tree_child_sketches_) subtree_sum.Merge(kv.second);
    w.PutString(subtree_sum.Encode());
  }
  w.PutI32(static_cast<int32_t>(groups.size()));
  for (const auto& [id, members] : groups) {
    w.PutI64(id);
    w.PutI32(static_cast<int32_t>(members.size()));
    for (const auto& [rank, handle] : members) {
      w.PutI32(rank);
      w.PutI64(handle);
    }
  }
  w.PutI32(static_cast<int32_t>(rests.size()));
  for (const auto& [rank, rest] : rests) {
    w.PutI32(rank);
    w.PutString(rest);
  }
  if (agg_t0 > 0.0) {
    StepTraceAddPhaseUs(
        kPhaseFusion,
        static_cast<int64_t>((MonotonicSeconds() - agg_t0) * 1e6));
  }
  if (FlightOn()) {
    // One aggregate frame per tree node per cycle: how many child frames
    // this leader merged (its own included; downstream leaders' aggregates
    // count as one each) and the bytes pushed upward.
    FlightRecord(kFlightTreeAgg, merged_frames,
                 static_cast<int64_t>(w.data().size()));
  }
  // v12: clustered leaders push to their super-leader, super-leaders (and
  // host 0's fused leader/coordinator path, which never reaches here) to
  // the coordinator.  Losing a super-leader is NOT losing the coordinator:
  // the rendezvous link is still up, so FIN through it and let the
  // coordinator attribute the death; only the top of the chain synthesizes
  // the ABORT itself.
  Socket& up = UpLink();
  CountCtrlSend(w.data().size());
  if (!up.SendFrame(w.data())) {
    if (tree_.parent > 0) {
      return LeaderFinUp(tree_.parent,
                         "leader rank " + std::to_string(cfg_.rank) +
                             " lost its super-leader rank " +
                             std::to_string(tree_.parent) + " (send)",
                         nullptr);
    }
    aborted_ = true;
    return LeaderLostCoordinator("lost coordinator (send)");
  }
  std::string resp;
  if (!up.RecvFrame(&resp)) {
    if (tree_.parent > 0) {
      return LeaderFinUp(tree_.parent,
                         "leader rank " + std::to_string(cfg_.rank) +
                             " lost its super-leader rank " +
                             std::to_string(tree_.parent) + " (recv)",
                         nullptr);
    }
    aborted_ = true;
    return LeaderLostCoordinator("lost coordinator (recv)");
  }
  CountCtrlRecv(resp.size());
  // Fan the coordinator's frame down BEFORE parsing: children unblock in
  // parallel with this rank's own deserialization, and terminal frames
  // (farewell, ABORT) reach the subtree even when this leader errors out.
  int failed_child = -1;
  if (!FanDownToChildren(resp, &failed_child)) {
    return LeaderFinUp(failed_child,
                       "leader rank " + std::to_string(cfg_.rank) +
                           " failed to forward responses to rank " +
                           std::to_string(failed_child),
                       nullptr);
  }
  Reader rd(resp);
  const int32_t n = rd.GetI32();
  if (n == -1) {
    peer_shutdown_ = true;
    aborted_ = true;
    SetAbortReason("coordinator shut down the job");
    return Status::Error(StatusCode::ABORTED,
                         "coordinator shut down the job");
  }
  if (n == -2) return HandleAbortFrame(&rd);
  ParseResponsesTail(&rd, n, out);
  return Status::OK();
}

Status SocketController::LeaderLostCoordinator(const std::string& what) {
  // The subtree's only path to the coordinator is gone: synthesize the
  // ABORT the coordinator can no longer send, so children fail within the
  // propagation bound instead of blocking on a mute leader.
  Writer w;
  w.PutI32(-2);
  w.PutI32(kTagAbort);
  w.PutString("leader rank " + std::to_string(cfg_.rank) +
              " lost the coordinator");
  w.PutI32(-1);        // no culprit rank: the coordinator itself is gone
  w.PutString("");     // culprit host unknown
  w.PutF64(WallSeconds());
  FanDownToChildren(w.data(), nullptr);
  const std::string msg = what;
  SetAbortReason(msg);
  return Status::Error(StatusCode::ABORTED, msg);
}

void SocketController::CountCtrlSend(int64_t bytes) {
  ctrl_msgs_sent_.fetch_add(1, std::memory_order_relaxed);
  ctrl_sent_.fetch_add(bytes, std::memory_order_relaxed);
  if (MetricsOn()) {
    auto& m = GlobalMetrics();
    m.ctrl_msgs_sent.fetch_add(1, std::memory_order_relaxed);
    m.ctrl_bytes_sent.fetch_add(bytes, std::memory_order_relaxed);
  }
  if (FlightOn()) FlightRecord(kFlightCtrlSend, 0, bytes);
}

void SocketController::CountCtrlRecv(int64_t bytes) {
  ctrl_msgs_recv_.fetch_add(1, std::memory_order_relaxed);
  ctrl_recv_.fetch_add(bytes, std::memory_order_relaxed);
  if (MetricsOn()) {
    auto& m = GlobalMetrics();
    m.ctrl_msgs_recv.fetch_add(1, std::memory_order_relaxed);
    m.ctrl_bytes_recv.fetch_add(bytes, std::memory_order_relaxed);
  }
  if (FlightOn()) FlightRecord(kFlightCtrlRecv, 0, bytes);
}

void SocketController::UpdateCachesAndSeq(std::vector<Response>* responses) {
  const bool hier_on = hierarchical_.load(std::memory_order_relaxed);
  const int wire_on = wire_compression_.load(std::memory_order_relaxed);
  for (auto& r : *responses) {
    if (!r.error.empty()) continue;
    bool all_cached = true;
    for (const auto& m : r.metas) {
      if (cache_.Lookup(m) < 0) all_cached = false;
      cache_.Insert(m);
    }
    r.cache_hit = all_cached;
    r.seq = seq_counter_++;
    // Plane decisions (coordinator only, carried in the response).  The
    // device bit follows ResponseToJson's AND — a single host-bound
    // member demotes the whole response to the host plane.
    if (r.op == OpType::ALLREDUCE && !r.metas.empty()) {
      bool device = true;
      int64_t total_bytes = 0;
      for (const auto& m : r.metas) {
        device = device && m.device != 0;
        total_bytes += m.nbytes;
      }
      // Hierarchical: host-plane allreduces on sets whose agreed topology
      // qualifies.
      if (hier_on && !device && HierFor(r.process_set_id) != nullptr) {
        r.hier = true;
      }
      // Wire codec: demoted (left 0) for non-fp32 dtypes, device-plane
      // ops, payloads under the floor, and topologies with any same-host
      // ring hop — hierarchical compresses its leader ring (the shm-local
      // planes stay raw), a flat ring only when every hop crosses hosts.
      if (wire_on != 0 && !device && r.dtype == DataType::FLOAT32 &&
          total_bytes >= wire_comp_floor_) {
        bool applies;
        if (r.hier) {
          applies = true;  // only the cross-host leader ring compresses
        } else {
          // The agreed host keys predict the members' plane choice (shm
          // only opens when all keys match), so this coordinator-side
          // check is a pure function of the rendezvous book.
          std::vector<int> members;
          applies = process_sets_.Ranks(r.process_set_id, &members) &&
                    members.size() >= 2 && RingAllCrossHost(members);
        }
        if (applies) r.wire_comp = wire_on;
      }
    }
    if (r.hier || r.wire_comp != 0) {
      std::lock_guard<std::mutex> l(hier_mu_);
      plane_by_seq_[r.seq] = {r.hier, static_cast<WireCodec>(r.wire_comp)};
    }
  }
}

std::string SocketController::StallReport(double older_than_s) {
  if (!is_coordinator()) return "";
  double now = MonotonicSeconds();
  std::ostringstream os;
  // Per-group ready counts: a grouped tensor announced by every rank can
  // still stall on MISSING group members (submitted nowhere) — report the
  // group shortfall, not an empty rank list.
  std::unordered_map<std::string, int32_t> gcount;
  for (const auto& kv : pending_) {
    if (!kv.second.meta.group_key.empty()) {
      gcount[kv.second.meta.group_key]++;
    }
  }
  for (const auto& kv : pending_) {
    if (now - kv.second.first_seen < older_than_s) continue;
    std::vector<int> members;
    process_sets_.Ranks(kv.second.meta.process_set_id, &members);
    std::vector<int> waiting;
    for (int m : members) {
      if (!kv.second.announced.count(m)) waiting.push_back(m);
    }
    const auto& meta = kv.second.meta;
    if (waiting.empty() && !meta.group_key.empty() &&
        gcount[meta.group_key] < meta.group_size) {
      os << kv.first << " (group " << meta.group_key << " incomplete: "
         << gcount[meta.group_key] << "/" << meta.group_size
         << " members submitted); ";
      continue;
    }
    os << kv.first << " (waiting on ranks:";
    for (int m : waiting) os << " " << m;
    os << "); ";
  }
  return os.str();
}

// ---------------------------------------------------------------------------
// Data plane: full-mesh ring/tree/pairwise algorithms on the caller thread
// ---------------------------------------------------------------------------

Status SocketController::Members(int psid, std::vector<int>* members,
                                 int* my_idx) const {
  if (!process_sets_.Ranks(psid, members)) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "unknown process set " + std::to_string(psid));
  }
  auto it = std::find(members->begin(), members->end(), cfg_.rank);
  if (it == members->end()) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "rank " + std::to_string(cfg_.rank) +
                             " not in process set " + std::to_string(psid));
  }
  *my_idx = static_cast<int>(it - members->begin());
  return Status::OK();
}

void SocketController::PutFrameHeader(Writer* w, int64_t seq, int32_t tag) {
  if (FaultInjectionOn() &&
      FaultCheck(kFaultFrameHeader, cfg_.rank) == FaultAction::kCorruptTag) {
    tag ^= 0x5A5A;  // the receiver must fail fast on the header mismatch
  }
  w->PutI64(seq);
  w->PutI32(tag);
}

Status SocketController::CheckFrameHeader(Reader* rd, int32_t tag,
                                          const char* what) {
  int64_t seq = rd->GetI64();
  int32_t got = rd->GetI32();
  if (!rd->ok() || seq != current_seq_ || got != tag) {
    aborted_ = true;
    return Status::Error(StatusCode::ABORTED,
                         std::string("data plane desync in ") + what +
                             ": expected seq " +
                             std::to_string(current_seq_) + " tag " +
                             std::to_string(tag) + ", got seq " +
                             std::to_string(seq) + " tag " +
                             std::to_string(got));
  }
  return Status::OK();
}

Status SocketController::ExchangeStep(std::vector<Socket>& socks, int send_to,
                                      const std::string& frame,
                                      int recv_from, std::string* in) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  if (FaultInjectionOn()) {
    FaultAction fa = FaultCheck(kFaultRingSend, cfg_.rank);
    if (fa == FaultAction::kDrop) {
      socks[send_to].Close();
    } else if (fa == FaultAction::kTruncate) {
      // Length prefix + half the payload, then cut: the peer sees a
      // mid-frame EOF instead of a clean close.
      uint32_t len = static_cast<uint32_t>(frame.size());
      socks[send_to].SendAll(&len, 4);
      socks[send_to].SendAll(frame.data(), frame.size() / 2);
      socks[send_to].Close();
    }
    fa = FaultCheck(kFaultRingRecv, cfg_.rank);
    if (fa == FaultAction::kDrop || fa == FaultAction::kTruncate) {
      socks[recv_from].Close();
    }
  }
  CountSend(send_to, static_cast<int64_t>(frame.size()));
  if (!DuplexExchange(socks[send_to], frame, socks[recv_from], in,
                      [this] { return aborted_.load(); })) {
    aborted_ = true;
    return Status::Error(StatusCode::ABORTED,
                         "data plane exchange failed (send->" +
                             std::to_string(send_to) + ", recv<-" +
                             std::to_string(recv_from) + ")");
  }
  return Status::OK();
}

Status SocketController::ChunkedStep(
    std::vector<Socket>& socks, int send_to, const char* send_base,
    int64_t send_len, int recv_from, int64_t recv_len, char* recv_dest,
    int32_t tag, int64_t chunk_bytes,
    const std::function<void(int64_t, const char*, int64_t)>& consume,
    int64_t raw_len) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  Writer w;
  PutFrameHeader(&w, current_seq_, tag);
  const int64_t hdr = static_cast<int64_t>(w.data().size());
  if (FaultInjectionOn()) {
    FaultAction fa = FaultCheck(kFaultRingSend, cfg_.rank);
    if (fa == FaultAction::kDrop) {
      socks[send_to].Close();
    } else if (fa == FaultAction::kTruncate) {
      // Frame a full first chunk but deliver only half its payload, then
      // cut: the peer dies mid-chunk, not at a frame boundary.
      const int64_t cb = chunk_bytes > 0 ? chunk_bytes : (1 << 19);
      const int64_t chunk = std::min<int64_t>(send_len, cb);
      uint32_t flen = static_cast<uint32_t>(hdr + chunk);
      socks[send_to].SendAll(&flen, 4);
      socks[send_to].SendAll(w.data().data(), w.data().size());
      if (chunk > 0) socks[send_to].SendAll(send_base, chunk / 2);
      socks[send_to].Close();
    }
    fa = FaultCheck(kFaultRingRecv, cfg_.rank);
    if (fa == FaultAction::kDrop || fa == FaultAction::kTruncate) {
      socks[recv_from].Close();
    }
  }
  CountSend(send_to, send_len + hdr,
            (raw_len < 0 ? send_len : raw_len) + hdr);
  if (FlightOn()) FlightRecord(kFlightRingHop, tag, send_len + hdr);
  const double hop_t0 =
      (MetricsOn() || StepTraceOn()) ? MonotonicSeconds() : 0.0;
  ChunkExchangeError err;
  if (!ChunkedDuplexExchange(socks[send_to], send_base, send_len,
                             socks[recv_from], recv_len, chunk_bytes,
                             w.data(), recv_dest, consume,
                             [this] { return aborted_.load(); }, &err)) {
    aborted_ = true;
    if (err.kind == ChunkExchangeError::kHeaderMismatch) {
      Reader rd(err.got_header);
      int64_t seq = rd.GetI64();
      int32_t got = rd.GetI32();
      return Status::Error(
          StatusCode::ABORTED,
          "data plane desync in pipelined ring: expected seq " +
              std::to_string(current_seq_) + " tag " + std::to_string(tag) +
              ", got seq " + std::to_string(seq) + " tag " +
              std::to_string(got));
    }
    if (err.kind == ChunkExchangeError::kBadLength) {
      return Status::Error(
          StatusCode::ABORTED,
          "data plane desync in pipelined ring: bad chunk length " +
              std::to_string(err.bad_length) + " (seq " +
              std::to_string(current_seq_) + " tag " + std::to_string(tag) +
              ")");
    }
    return Status::Error(StatusCode::ABORTED,
                         "pipelined ring exchange failed (send->" +
                             std::to_string(send_to) + ", recv<-" +
                             std::to_string(recv_from) + ")");
  }
  if (hop_t0 > 0.0) {
    const double hop_s = MonotonicSeconds() - hop_t0;
    if (MetricsOn()) GlobalMetrics().ring_hop_us.ObserveSeconds(hop_s);
    StepTraceAddPhaseUs(kPhaseRing, static_cast<int64_t>(hop_s * 1e6));
  }
  return Status::OK();
}

Status SocketController::PipelinedReducePhase(
    std::vector<Socket>& socks, const std::vector<int>& members, int idx,
    int vidx, char* base, const std::vector<int64_t>& offs, DataType dtype,
    ReduceOp op, int32_t tag_base, int64_t chunkb) {
  const int m = static_cast<int>(members.size());
  const int item = ItemSize(dtype);
  const int next = members[(idx + 1) % m];
  const int prev = members[(idx - 1 + m) % m];
  std::vector<char> scratch;
  for (int s2 = 0; s2 < m - 1; ++s2) {
    const int send_c = ((vidx - s2) % m + m) % m;
    const int recv_c = ((vidx - s2 - 1) % m + m) % m;
    const int64_t rbytes = (offs[recv_c + 1] - offs[recv_c]) * item;
    if (static_cast<int64_t>(scratch.size()) < rbytes) {
      scratch.resize(static_cast<size_t>(rbytes));
    }
    char* seg = base + offs[recv_c] * item;
    int64_t reduced = 0;
    auto consume = [&](int64_t off, const char* /*data*/, int64_t nb) {
      // Reduce every fully-received element so far; the peer's chunking
      // need not be element-aligned (its HOROVOD_RING_CHUNK_BYTES may
      // differ), so carry any partial element to the next chunk.
      const int64_t avail = (off + nb) / item * item;
      if (avail > reduced) {
        ReduceInto(seg + reduced, scratch.data() + reduced,
                   (avail - reduced) / item, dtype, op);
        reduced = avail;
      }
    };
    Status st = ChunkedStep(socks, next,
                            base + offs[send_c] * item,
                            (offs[send_c + 1] - offs[send_c]) * item, prev,
                            rbytes, scratch.data(), tag_base + s2, chunkb,
                            consume);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status SocketController::RingAllreduce(std::vector<Socket>& socks, void* buf,
                                       int64_t count, DataType dtype,
                                       ReduceOp op,
                                       const std::vector<int>& members,
                                       int idx) {
  const int m = static_cast<int>(members.size());
  if (m == 1) return Status::OK();
  char* base = static_cast<char*>(buf);
  const int item = ItemSize(dtype);
  const int64_t chunk = count / m, rem = count % m;
  auto start = [&](int c) { return c * chunk + std::min<int64_t>(c, rem); };
  auto len = [&](int c) { return start(c + 1) - start(c); };
  const int next = members[(idx + 1) % m];
  const int prev = members[(idx - 1 + m) % m];

  if (ring_chunk_bytes_ > 0) {
    // Pipelined (Gloo segmented-ring) path: each hop streams the segment
    // in element-aligned chunks straight from/into the user buffer —
    // no full-segment copies — and reduces each received chunk while the
    // kernel keeps moving later chunks, so compute overlaps the wire.
    const int64_t chunkb =
        std::max<int64_t>(item, ring_chunk_bytes_ / item * item);
    // Phase 1: ring reduce-scatter with in-flight reduction.
    std::vector<int64_t> offs(m + 1, 0);
    for (int c = 0; c < m; ++c) offs[c + 1] = start(c + 1);
    Status st = PipelinedReducePhase(socks, members, idx, idx, base, offs,
                                     dtype, op, kTagReduceScatter, chunkb);
    if (!st.ok()) return st;
    // Phase 2: ring allgather, received straight into place (zero-copy in
    // both directions).
    for (int s = 0; s < m - 1; ++s) {
      const int send_c = ((idx + 1 - s) % m + m) % m;
      const int recv_c = ((idx - s) % m + m) % m;
      Status st = ChunkedStep(socks, next, base + start(send_c) * item,
                              len(send_c) * item, prev, len(recv_c) * item,
                              base + start(recv_c) * item,
                              kTagAllgatherPhase + s, chunkb, nullptr);
      if (!st.ok()) return st;
    }
    return Status::OK();
  }

  // Legacy whole-segment path (HOROVOD_RING_CHUNK_BYTES=0).
  // Phase 1: ring reduce-scatter.  After m-1 steps this rank holds the
  // fully reduced chunk (idx+1)%m.
  for (int s = 0; s < m - 1; ++s) {
    const int send_c = ((idx - s) % m + m) % m;
    const int recv_c = ((idx - s - 1) % m + m) % m;
    Writer w;
    PutFrameHeader(&w, current_seq_, kTagReduceScatter + s);
    w.PutRaw(base + start(send_c) * item, len(send_c) * item);
    std::string in;
    Status st = ExchangeStep(socks, next, w.data(), prev, &in);
    if (!st.ok()) return st;
    Reader rd(in);
    st = CheckFrameHeader(&rd, kTagReduceScatter + s, "ring reduce-scatter");
    if (!st.ok()) return st;
    if (static_cast<int64_t>(rd.remaining()) != len(recv_c) * item) {
      aborted_ = true;
      return Status::Error(StatusCode::ABORTED,
                           "ring reduce-scatter chunk size mismatch");
    }
    ReduceInto(base + start(recv_c) * item, rd.cursor(), len(recv_c), dtype,
               op);
  }
  // Phase 2: ring allgather of the reduced chunks.
  for (int s = 0; s < m - 1; ++s) {
    const int send_c = ((idx + 1 - s) % m + m) % m;
    const int recv_c = ((idx - s) % m + m) % m;
    Writer w;
    PutFrameHeader(&w, current_seq_, kTagAllgatherPhase + s);
    w.PutRaw(base + start(send_c) * item, len(send_c) * item);
    std::string in;
    Status st = ExchangeStep(socks, next, w.data(), prev, &in);
    if (!st.ok()) return st;
    Reader rd(in);
    st = CheckFrameHeader(&rd, kTagAllgatherPhase + s, "ring allgather");
    if (!st.ok()) return st;
    if (static_cast<int64_t>(rd.remaining()) != len(recv_c) * item) {
      aborted_ = true;
      return Status::Error(StatusCode::ABORTED,
                           "ring allgather chunk size mismatch");
    }
    std::memcpy(base + start(recv_c) * item, rd.cursor(), len(recv_c) * item);
  }
  return Status::OK();
}

bool SocketController::RingAllCrossHost(const std::vector<int>& members) const {
  const int m = static_cast<int>(members.size());
  if (m < 2 || host_keys_.empty()) return false;
  for (int i = 0; i < m; ++i) {
    if (host_keys_[members[i]] == host_keys_[members[(i + 1) % m]]) {
      return false;
    }
  }
  return true;
}

bool SocketController::WireCompAvailable() {
  if (HierFor(0) != nullptr) return true;  // leader ring is all-cross-host
  if (ShmFor(0) != nullptr) return false;  // shm plane: no wire at all
  std::vector<int> all(cfg_.size);
  for (int i = 0; i < cfg_.size; ++i) all[i] = i;
  return RingAllCrossHost(all);
}

Status SocketController::CompressedRingAllreduce(
    std::vector<Socket>& socks, void* buf, int64_t count, ReduceOp op,
    const std::vector<int>& members, int idx, WireCodec codec) {
  const int m = static_cast<int>(members.size());
  if (m == 1) return Status::OK();
  if (codec == WireCodec::kNone) {
    return RingAllreduce(socks, buf, count, DataType::FLOAT32, op, members,
                         idx);
  }
  if (FlightOn()) {
    FlightRecord(kFlightWireCodec, static_cast<int32_t>(codec),
                 count * static_cast<int64_t>(sizeof(float)));
  }
  float* base = static_cast<float*>(buf);
  const int64_t chunk = count / m, rem = count % m;
  auto start = [&](int c) { return c * chunk + std::min<int64_t>(c, rem); };
  auto len = [&](int c) { return start(c + 1) - start(c); };
  const int next = members[(idx + 1) % m];
  const int prev = members[(idx - 1 + m) % m];
  // The compressed ring is always chunk-pipelined (the legacy
  // whole-segment path predates it and stays raw); chunk boundaries are
  // byte-level, the decode carry below handles partial int8 blocks.
  const int64_t chunkb =
      ring_chunk_bytes_ > 0 ? ring_chunk_bytes_ : (1 << 19);
  const int64_t maxseg = chunk + (rem > 0 ? 1 : 0);
  std::vector<char> enc_send(
      static_cast<size_t>(WireEncodedBytes(codec, maxseg)));
  std::vector<char> enc_recv(enc_send.size());
  std::vector<float> stage(static_cast<size_t>(maxseg));

  // Phase 1: reduce-scatter.  Every hop re-encodes the CURRENT fp32
  // partial sums (one fresh quantization per hop) and the receiver
  // decodes to fp32 before accumulating — so after m-1 hops each element
  // carries at most (m-1) single-quantization errors, never an error of
  // a quantized partial sum re-quantized.
  for (int s = 0; s < m - 1; ++s) {
    const int send_c = ((idx - s) % m + m) % m;
    const int recv_c = ((idx - s - 1) % m + m) % m;
    const int64_t selems = len(send_c), relems = len(recv_c);
    WireEncode(codec, base + start(send_c), selems, enc_send.data());
    float* seg = base + start(recv_c);
    int64_t decoded = 0;
    auto consume = [&](int64_t off, const char* /*data*/, int64_t nb) {
      // Decode every fully-received element so far (the peer's chunking
      // is byte-, not block-aligned; carry partial blocks forward).
      const int64_t avail = WireDecodableElems(codec, off + nb, relems);
      if (avail > decoded) {
        WireDecodeRange(codec, enc_recv.data(), relems, decoded, avail,
                        stage.data());
        ReduceInto(seg + decoded, stage.data(), avail - decoded,
                   DataType::FLOAT32, op);
        decoded = avail;
      }
    };
    Status st = ChunkedStep(socks, next, enc_send.data(),
                            WireEncodedBytes(codec, selems), prev,
                            WireEncodedBytes(codec, relems), enc_recv.data(),
                            kTagCompReduceScatter + s, chunkb, consume,
                            /*raw_len=*/4 * selems);
    if (!st.ok()) return st;
  }

  // Phase 2: allgather.  The owner of each finished segment encodes it
  // ONCE; every later hop forwards those encoded bytes verbatim and the
  // owner itself decodes its own encoding — so all m members decode the
  // identical stream and the results are bit-identical across ranks
  // (one quantization total in this phase, regardless of ring length).
  const int own_c = (idx + 1) % m;
  WireEncode(codec, base + start(own_c), len(own_c), enc_send.data());
  WireDecodeRange(codec, enc_send.data(), len(own_c), 0, len(own_c), stage.data());
  std::memcpy(base + start(own_c), stage.data(),
              static_cast<size_t>(4 * len(own_c)));
  for (int s = 0; s < m - 1; ++s) {
    const int send_c = ((idx + 1 - s) % m + m) % m;
    const int recv_c = ((idx - s) % m + m) % m;
    const int64_t relems = len(recv_c);
    float* seg = base + start(recv_c);
    int64_t decoded = 0;
    auto consume = [&](int64_t off, const char* /*data*/, int64_t nb) {
      const int64_t avail = WireDecodableElems(codec, off + nb, relems);
      if (avail > decoded) {
        WireDecodeRange(codec, enc_recv.data(), relems, decoded, avail,
                        seg + decoded);
        decoded = avail;
      }
    };
    Status st = ChunkedStep(socks, next, enc_send.data(),
                            WireEncodedBytes(codec, len(send_c)), prev,
                            WireEncodedBytes(codec, relems), enc_recv.data(),
                            kTagCompAllgather + s, chunkb, consume,
                            /*raw_len=*/4 * len(send_c));
    if (!st.ok()) return st;
    // What we just received is exactly what we forward next hop
    // (send_c at step s+1 == recv_c at step s): swap, don't re-encode.
    std::swap(enc_send, enc_recv);
  }
  return Status::OK();
}

Status SocketController::AllreduceBuffer(void* buf, int64_t count,
                                         DataType dtype, ReduceOp op,
                                         int psid) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  std::vector<int> members;
  int idx;
  Status st = Members(psid, &members, &idx);
  if (!st.ok()) return st;
  if (members.size() > 1) {
    // Plane refinement: engaged only when THIS seq's response carried the
    // coordinator's hier bit / wire codec (recorded in the cycle), so the
    // choice is identical on every member.  Direct calls (seq -1,
    // selftests) and unmarked seqs keep today's behavior.
    PlaneChoice plane;
    {
      std::lock_guard<std::mutex> l(hier_mu_);
      auto it = plane_by_seq_.find(current_seq_);
      if (it != plane_by_seq_.end()) {
        plane = it->second;
        plane_by_seq_.erase(it);
      }
    }
    if (plane.hier) {
      if (HierTopo* topo = HierFor(psid)) {
        return HierAllreduce(*topo, SocksFor(psid), buf, count, dtype, op,
                             plane.wire);
      }
    }
    if (ShmRegion* shm = ShmFor(psid)) {
      return ShmAllreduce(*shm, SocksFor(psid), members, idx, buf, count,
                          dtype, op);
    }
    if (plane.wire != WireCodec::kNone && dtype == DataType::FLOAT32) {
      return CompressedRingAllreduce(SocksFor(psid), buf, count, op, members,
                                     idx, plane.wire);
    }
  }
  return RingAllreduce(SocksFor(psid), buf, count, dtype, op, members, idx);
}

Status SocketController::ReduceScatterBuffer(
    void* buf, int64_t count, DataType dtype, ReduceOp op,
    const std::vector<int64_t>& slice_counts, int psid) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  std::vector<int> members;
  int idx;
  Status st = Members(psid, &members, &idx);
  if (!st.ok()) return st;
  const int m = static_cast<int>(members.size());
  if (static_cast<int>(slice_counts.size()) != m) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "reducescatter slice_counts length != set size");
  }
  int64_t total = 0;
  for (int64_t c : slice_counts) total += c;
  if (total != count) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "reducescatter slice_counts do not sum to count");
  }
  if (m == 1) return Status::OK();
  if (ShmRegion* shm = ShmFor(psid)) {
    // Same-host: the shm allreduce is one region write + segment reduce
    // per member; the caller slices.  (A slice-only shm variant would
    // save only the readback of the other slices.)
    return ShmAllreduce(*shm, SocksFor(psid), members, idx, buf, count,
                        dtype, op);
  }
  // Ring reduce-scatter over the CALLER's slice boundaries (the Horovod
  // row-split rule), phase 1 of the ring allreduce only: each rank moves
  // (m-1)/m of the buffer instead of the allreduce's 2(m-1)/m.  The
  // schedule runs in a shifted index space (vidx = idx-1) so this rank
  // finishes owning ITS slice (the standard ring leaves rank j with
  // chunk j+1).  This op always uses the chunked wire format — it has no
  // legacy framing, so per-rank HOROVOD_RING_CHUNK_BYTES (even 0) stays
  // interoperable.
  char* base = static_cast<char*>(buf);
  const int item = ItemSize(dtype);
  std::vector<int64_t> offs(m + 1, 0);
  for (int c = 0; c < m; ++c) offs[c + 1] = offs[c] + slice_counts[c];
  const int vidx = (idx - 1 + m) % m;
  const int64_t want = ring_chunk_bytes_ > 0 ? ring_chunk_bytes_
                                             : (int64_t{1} << 19);
  const int64_t chunkb = std::max<int64_t>(item, want / item * item);
  return PipelinedReducePhase(SocksFor(psid), members, idx, vidx, base,
                              offs, dtype, op, kTagReduceScatterOp, chunkb);
}

Status SocketController::AllgatherBuffer(const void* in, int64_t nbytes,
                                         int psid, std::string* out,
                                         std::vector<int64_t>* per_rank) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  std::vector<int> members;
  int idx;
  Status st = Members(psid, &members, &idx);
  if (!st.ok()) return st;
  const int m = static_cast<int>(members.size());
  if (m == 1) {
    out->assign(static_cast<const char*>(in), nbytes);
    per_rank->assign(1, nbytes);
    return Status::OK();
  }
  std::vector<Socket>& socks = SocksFor(psid);
  if (ShmRegion* shm = ShmFor(psid)) {
    return ShmAllgather(*shm, socks, members, idx, in, nbytes, out,
                        per_rank);
  }
  const int next = members[(idx + 1) % m];
  const int prev = members[(idx - 1 + m) % m];

  if (ring_chunk_bytes_ > 0) {
    // Pipelined path: a cheap size ring first (8-byte frames on the same
    // schedule), then m-1 chunk-pipelined hops whose payloads stream
    // straight between the output concatenation's block slots — zero
    // block copies, reduce-free cousin of the pipelined ring allreduce.
    //
    // Tradeoff: the up-front size ring adds m-1 tiny serialized steps vs
    // the legacy in-band path.  The ragged zero-copy layout needs every
    // size before the output can be allocated, a payload-size switch
    // would desync (nbytes legally differs per rank), and for small
    // allgathers the negotiation round trip dominates those 8-byte hops
    // anyway; large ones win back block-sized copies per hop.
    std::vector<int64_t> sizes(m, 0);
    sizes[idx] = nbytes;
    for (int s2 = 0; s2 < m - 1; ++s2) {
      const int send_b = ((idx - s2) % m + m) % m;
      const int recv_b = ((idx - s2 - 1) % m + m) % m;
      Writer w;
      PutFrameHeader(&w, current_seq_, kTagAllgatherSize + s2);
      w.PutI64(sizes[send_b]);
      std::string in_frame;
      st = ExchangeStep(socks, next, w.data(), prev, &in_frame);
      if (!st.ok()) return st;
      Reader rd(in_frame);
      st = CheckFrameHeader(&rd, kTagAllgatherSize + s2, "allgather sizes");
      if (!st.ok()) return st;
      sizes[recv_b] = rd.GetI64();
      if (!rd.ok() || sizes[recv_b] < 0) {
        aborted_ = true;
        return Status::Error(StatusCode::ABORTED,
                             "allgather size ring desync");
      }
    }
    std::vector<int64_t> offs(m + 1, 0);
    for (int b = 0; b < m; ++b) offs[b + 1] = offs[b] + sizes[b];
    out->resize(static_cast<size_t>(offs[m]));
    char* base = out->empty() ? nullptr : &(*out)[0];
    if (nbytes > 0) std::memcpy(base + offs[idx], in, nbytes);
    for (int s2 = 0; s2 < m - 1; ++s2) {
      const int send_b = ((idx - s2) % m + m) % m;
      const int recv_b = ((idx - s2 - 1) % m + m) % m;
      st = ChunkedStep(socks, next, base + offs[send_b], sizes[send_b],
                       prev, sizes[recv_b], base + offs[recv_b],
                       kTagAllgather + s2, ring_chunk_bytes_, nullptr);
      if (!st.ok()) return st;
    }
    per_rank->assign(sizes.begin(), sizes.end());
    return Status::OK();
  }

  // Legacy whole-block path (HOROVOD_RING_CHUNK_BYTES=0): per-rank sizes
  // carried in-band; step s passes block (idx - s) along the ring.
  std::vector<std::string> blocks(m);
  blocks[idx].assign(static_cast<const char*>(in), nbytes);
  for (int s = 0; s < m - 1; ++s) {
    const int send_b = ((idx - s) % m + m) % m;
    const int recv_b = ((idx - s - 1) % m + m) % m;
    Writer w;
    PutFrameHeader(&w, current_seq_, kTagAllgather + s);
    w.PutRaw(blocks[send_b].data(), blocks[send_b].size());
    std::string frame;
    st = ExchangeStep(socks, next, w.data(), prev, &frame);
    if (!st.ok()) return st;
    Reader rd(frame);
    st = CheckFrameHeader(&rd, kTagAllgather + s, "allgather");
    if (!st.ok()) return st;
    blocks[recv_b].assign(rd.cursor(), rd.remaining());
  }
  out->clear();
  per_rank->clear();
  for (int b = 0; b < m; ++b) {
    per_rank->push_back(static_cast<int64_t>(blocks[b].size()));
    out->append(blocks[b]);
  }
  return Status::OK();
}

Status SocketController::BroadcastBuffer(void* buf, int64_t nbytes,
                                         int root_rank, int psid) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  std::vector<int> members;
  int idx;
  Status st = Members(psid, &members, &idx);
  if (!st.ok()) return st;
  const int m = static_cast<int>(members.size());
  if (m == 1) return Status::OK();
  std::vector<Socket>& socks = SocksFor(psid);
  auto root_it = std::find(members.begin(), members.end(), root_rank);
  if (root_it == members.end()) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "broadcast root " + std::to_string(root_rank) +
                             " not in process set");
  }
  const int root_idx = static_cast<int>(root_it - members.begin());
  if (ShmRegion* shm = ShmFor(psid)) {
    return ShmBroadcast(*shm, socks, members, idx, root_idx, buf, nbytes);
  }
  const int vrank = (idx - root_idx + m) % m;

  // Large payloads: pipelined chain in vrank order.  Every member sends
  // nbytes exactly once and chunks stream hop to hop through kernel
  // socket buffers, so all hops overlap and wall time approaches one
  // N/B transfer — the binomial tree costs the root N*log2(m) egress
  // and serializes tree levels per whole buffer.  Payloads this large
  // are the broadcast_parameters case this path exists for; small
  // payloads keep the tree's fewer hop latencies.
  if (ring_chunk_bytes_ > 0 && m > 2 && nbytes >= kBroadcastChainBytes) {
    char* base = static_cast<char*>(buf);
    const int src =
        vrank > 0 ? members[(root_idx + vrank - 1) % m] : -1;
    const int nxt = vrank + 1 < m ? members[(root_idx + vrank + 1) % m] : -1;
    Socket* next_sock = nxt >= 0 ? &socks[nxt] : nullptr;
    // Geometry header: [seq|tag|nbytes] hops ahead of the raw chunk
    // stream so a size mismatch aborts before any payload bytes land.
    if (src >= 0) {
      std::string frame;
      if (!socks[src].RecvFrame(&frame)) {
        aborted_ = true;
        // Mirror of the send-side fail-fast: our downstream is blocked in
        // RecvAll with no abort polling; closing its socket propagates the
        // failure down the chain immediately instead of leaving it wedged
        // until job-level teardown.
        if (next_sock) next_sock->Close();
        return Status::Error(StatusCode::ABORTED,
                             "broadcast chain recv from rank " +
                                 std::to_string(src) + " failed");
      }
      Reader rd(frame);
      st = CheckFrameHeader(&rd, kTagBroadcastChain, "broadcast chain");
      if (!st.ok()) {
        // Our upstream is mid-SendAll of the raw stream with no abort
        // polling; closing the socket fails it fast instead of letting it
        // block on full kernel buffers until process teardown.  The
        // downstream is symmetric: it blocks in RecvAll.
        socks[src].Close();
        if (next_sock) next_sock->Close();
        return st;
      }
      int64_t peer_bytes = rd.GetI64();
      if (!rd.ok() || peer_bytes != nbytes) {
        aborted_ = true;
        socks[src].Close();
        if (next_sock) next_sock->Close();
        return Status::Error(StatusCode::ABORTED,
                             "broadcast size mismatch across ranks");
      }
    }
    if (next_sock) {
      Writer w;
      PutFrameHeader(&w, current_seq_, kTagBroadcastChain);
      w.PutI64(nbytes);
      CountSend(nxt, static_cast<int64_t>(w.data().size()) + nbytes);
      if (!next_sock->SendFrame(w.data())) {
        aborted_ = true;
        return Status::Error(StatusCode::ABORTED,
                             "broadcast chain header send failed");
      }
    }
    for (int64_t off = 0; off < nbytes; off += ring_chunk_bytes_) {
      const int64_t n = std::min<int64_t>(ring_chunk_bytes_, nbytes - off);
      if (src >= 0 && !socks[src].RecvAll(base + off, n)) {
        aborted_ = true;
        // Fail the blocked downstream RecvAll fast (see header path).
        if (next_sock) next_sock->Close();
        return Status::Error(StatusCode::ABORTED,
                             "broadcast chain recv from rank " +
                                 std::to_string(src) + " failed");
      }
      if (next_sock && !next_sock->SendAll(base + off, n)) {
        aborted_ = true;
        // Same fail-fast rule as the header paths: our upstream has no
        // abort polling inside SendAll, so cut its stream rather than
        // letting it block on full kernel buffers.
        if (src >= 0) socks[src].Close();
        return Status::Error(StatusCode::ABORTED,
                             "broadcast chain send failed");
      }
    }
    return Status::OK();
  }

  // Binomial tree: log2(m) rounds; parent sends after it has the payload.
  int mask = 1;
  while (mask < m) {
    if (vrank & mask) {
      const int src = members[(root_idx + vrank - mask) % m];
      std::string frame;
      if (!socks[src].RecvFrame(&frame)) {
        aborted_ = true;
        return Status::Error(StatusCode::ABORTED,
                             "broadcast recv from rank " +
                                 std::to_string(src) + " failed");
      }
      Reader rd(frame);
      st = CheckFrameHeader(&rd, kTagBroadcast, "broadcast");
      if (!st.ok()) return st;
      if (static_cast<int64_t>(rd.remaining()) != nbytes) {
        aborted_ = true;
        return Status::Error(StatusCode::ABORTED,
                             "broadcast size mismatch across ranks");
      }
      std::memcpy(buf, rd.cursor(), nbytes);
      break;
    }
    mask <<= 1;
  }
  mask >>= 1;
  while (mask > 0) {
    if (vrank + mask < m) {
      const int dst = members[(root_idx + vrank + mask) % m];
      Writer w;
      PutFrameHeader(&w, current_seq_, kTagBroadcast);
      w.PutRaw(buf, nbytes);
      CountSend(dst, static_cast<int64_t>(w.data().size()));
      if (!socks[dst].SendFrame(w.data())) {
        aborted_ = true;
        return Status::Error(StatusCode::ABORTED,
                             "broadcast send to rank " + std::to_string(dst) +
                                 " failed");
      }
    }
    mask >>= 1;
  }
  return Status::OK();
}

Status SocketController::AlltoallBuffer(const void* in,
                                        const std::vector<int64_t>& splits,
                                        int64_t row_bytes, int psid,
                                        std::string* out,
                                        std::vector<int64_t>* recv_splits) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  std::vector<int> members;
  int idx;
  Status st = Members(psid, &members, &idx);
  if (!st.ok()) return st;
  const int m = static_cast<int>(members.size());
  if (static_cast<int>(splits.size()) != m) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "alltoall splits length != process set size");
  }
  std::vector<Socket>& socks = SocksFor(psid);
  if (m > 1) {
    if (ShmRegion* shm = ShmFor(psid)) {
      return ShmAlltoall(*shm, socks, members, idx, in, splits, row_bytes,
                         out, recv_splits);
    }
  }
  const char* base = static_cast<const char*>(in);
  std::vector<int64_t> offs(m + 1, 0);
  for (int j = 0; j < m; ++j) offs[j + 1] = offs[j] + splits[j];

  if (ring_chunk_bytes_ > 0) {
    // Pipelined path (same shape as the pipelined allgather): a pairwise
    // row-count exchange first — the ragged output layout needs every
    // count before it can be allocated — then chunk-pipelined pairwise
    // hops that stream each peer's rows straight into the output
    // concatenation's slot, with zero block copies.
    std::vector<int64_t> rows_from(m, 0);
    rows_from[idx] = splits[idx];
    for (int d = 1; d < m; ++d) {
      const int to_i = (idx + d) % m;
      const int from_i = (idx - d + m) % m;
      Writer w;
      PutFrameHeader(&w, current_seq_, kTagAlltoallSize + d);
      w.PutI64(splits[to_i]);
      std::string frame;
      st = ExchangeStep(socks, members[to_i], w.data(), members[from_i],
                        &frame);
      if (!st.ok()) return st;
      Reader rd(frame);
      st = CheckFrameHeader(&rd, kTagAlltoallSize + d, "alltoall sizes");
      if (!st.ok()) return st;
      rows_from[from_i] = rd.GetI64();
      if (!rd.ok() || rows_from[from_i] < 0) {
        aborted_ = true;
        return Status::Error(StatusCode::ABORTED,
                             "alltoall size exchange desync");
      }
    }
    std::vector<int64_t> roffs(m + 1, 0);
    for (int j = 0; j < m; ++j) roffs[j + 1] = roffs[j] + rows_from[j];
    out->resize(static_cast<size_t>(roffs[m] * row_bytes));
    char* obase = out->empty() ? nullptr : &(*out)[0];
    if (splits[idx] > 0) {
      std::memcpy(obase + roffs[idx] * row_bytes,
                  base + offs[idx] * row_bytes, splits[idx] * row_bytes);
    }
    for (int d = 1; d < m; ++d) {
      const int to_i = (idx + d) % m;
      const int from_i = (idx - d + m) % m;
      st = ChunkedStep(socks, members[to_i], base + offs[to_i] * row_bytes,
                       splits[to_i] * row_bytes, members[from_i],
                       rows_from[from_i] * row_bytes,
                       obase + roffs[from_i] * row_bytes, kTagAlltoall + d,
                       ring_chunk_bytes_, nullptr);
      if (!st.ok()) return st;
    }
    recv_splits->assign(rows_from.begin(), rows_from.end());
    return Status::OK();
  }

  // Legacy whole-block path (HOROVOD_RING_CHUNK_BYTES=0).
  std::vector<std::string> recv_bufs(m);
  std::vector<int64_t> rows_from(m, 0);
  recv_bufs[idx].assign(base + offs[idx] * row_bytes,
                        splits[idx] * row_bytes);
  rows_from[idx] = splits[idx];
  // Pairwise exchange: round d trades with the member d positions away in
  // each direction; the duplex step keeps the cycle deadlock-free.
  for (int d = 1; d < m; ++d) {
    const int to_i = (idx + d) % m;
    const int from_i = (idx - d + m) % m;
    Writer w;
    PutFrameHeader(&w, current_seq_, kTagAlltoall + d);
    w.PutI64(splits[to_i]);
    w.PutRaw(base + offs[to_i] * row_bytes, splits[to_i] * row_bytes);
    std::string frame;
    st = ExchangeStep(socks, members[to_i], w.data(), members[from_i],
                      &frame);
    if (!st.ok()) return st;
    Reader rd(frame);
    st = CheckFrameHeader(&rd, kTagAlltoall + d, "alltoall");
    if (!st.ok()) return st;
    int64_t rows = rd.GetI64();
    if (static_cast<int64_t>(rd.remaining()) != rows * row_bytes) {
      aborted_ = true;
      return Status::Error(StatusCode::ABORTED,
                           "alltoall payload size mismatch");
    }
    recv_bufs[from_i].assign(rd.cursor(), rd.remaining());
    rows_from[from_i] = rows;
  }
  out->clear();
  recv_splits->assign(rows_from.begin(), rows_from.end());
  for (int j = 0; j < m; ++j) out->append(recv_bufs[j]);
  return Status::OK();
}

Status SocketController::Barrier(int psid) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  std::vector<int> members;
  int idx;
  Status st = Members(psid, &members, &idx);
  if (!st.ok()) return st;
  return SockBarrier(SocksFor(psid), members, idx, kTagBarrier);
}

Status SocketController::SockBarrier(std::vector<Socket>& socks,
                                     const std::vector<int>& members,
                                     int idx, int32_t tag_base) {
  const int m = static_cast<int>(members.size());
  // Fence-wait metric: only the shm/hier phase fences (tag families at or
  // above kTagShmSize) — the public Barrier() is a user-visible collective,
  // not plane bookkeeping.
  const double fence_t0 =
      tag_base >= kTagShmSize && (MetricsOn() || StepTraceOn())
          ? MonotonicSeconds()
          : 0.0;
  if (FlightOn() && tag_base >= kTagShmSize) {
    FlightRecord(kFlightShmFence, tag_base, 0);
  }
  if (FaultInjectionOn()) {
    // shm-fence faults target the FENCE (not a specific peer socket):
    // drop/truncate close the next-neighbor link the first round uses, so
    // the whole fence collapses deterministically.
    FaultAction fa = FaultCheck(kFaultShmFence, cfg_.rank);
    if (fa == FaultAction::kDrop || fa == FaultAction::kTruncate) {
      if (m > 1) socks[members[(idx + 1) % m]].Close();
    }
  }
  // Dissemination barrier: ceil(log2(m)) duplex rounds.
  for (int k = 1; k < m; k <<= 1) {
    const int to = members[(idx + k) % m];
    const int from = members[(idx - k + m) % m];
    Writer w;
    PutFrameHeader(&w, current_seq_, tag_base + k);
    std::string frame;
    Status st = ExchangeStep(socks, to, w.data(), from, &frame);
    if (!st.ok()) return st;
    Reader rd(frame);
    st = CheckFrameHeader(&rd, tag_base + k, "barrier");
    if (!st.ok()) return st;
  }
  if (fence_t0 > 0.0) {
    const double fence_s = MonotonicSeconds() - fence_t0;
    if (MetricsOn()) GlobalMetrics().shm_fence_us.ObserveSeconds(fence_s);
    StepTraceAddPhaseUs(kPhaseFence, static_cast<int64_t>(fence_s * 1e6));
  }
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Shared-memory plane (same-host members; see shm_plane.h)
// ---------------------------------------------------------------------------

bool SocketController::MembersAllLocal(const std::vector<int>& members) const {
  const char* disable = ::getenv("HOROVOD_SHM_DISABLE");
  if (disable && disable[0] == '1') return false;
  // The agreed host keys are the locality signal (identical on every rank,
  // honors the fake-host overrides); the loopback-address test remains as
  // a belt-and-braces check against a spoofed key colliding across real
  // hosts.
  for (int r : members) {
    if (r == cfg_.rank) continue;
    if (host_keys_[r] != host_keys_[cfg_.rank]) return false;
    const std::string& a = mesh_addrs_[r];
    if (a.rfind("127.", 0) != 0 && a != "localhost" && a != "::1") {
      return false;
    }
  }
  return true;
}

Status SocketController::MaybeOpenShm(int psid,
                                      const std::vector<int>& members) {
  const int m = static_cast<int>(members.size());
  if (m <= 1) return Status::OK();
  // The ATTEMPT decision itself must be agreed, not just the open result:
  // per-rank env/address views can diverge (HOROVOD_SHM_DISABLE set on one
  // worker only), and a rank that silently skips the handshake would
  // deadlock the ranks that run it.  So every member always runs the
  // handshake; a non-attempting member simply votes no.
  bool attempt = MembersAllLocal(members) &&
                 static_cast<int64_t>(m) * m * 8 <= ShmRegion::kHeaderBytes;
  auto it = std::find(members.begin(), members.end(), cfg_.rank);
  const int idx = static_cast<int>(it - members.begin());
  const bool creator = idx == 0;
  std::vector<Socket>& socks = SocksFor(psid);
  auto region = std::make_unique<ShmRegion>();
  std::string name =
      "/hvd_" + std::to_string(cfg_.rendezvous_port) + "_" +
      std::to_string(psid);
  Status open_st = Status::OK();
  if (creator && attempt) {
    open_st = region->Open(name, true);
  }
  Status st = SockBarrier(socks, members, idx, kTagShmOpen);
  if (!st.ok()) return st;
  if (!creator && attempt) {
    open_st = region->Open(name, false);
  }
  if (!attempt) {
    open_st = Status::Error(StatusCode::PRECONDITION_ERROR, "not attempted");
  }
  // Agree on the verdict: members send their flag to the set root, which
  // ANDs and broadcasts it back — either everyone uses the region or
  // everyone falls back to the TCP ring (a split plane would deadlock).
  uint8_t ok = open_st.ok() ? 1 : 0;
  if (creator) {
    uint8_t all_ok = ok;
    for (int j = 1; j < m; ++j) {
      std::string frame;
      if (!socks[members[j]].RecvFrame(&frame)) all_ok = 0;
      Reader rd(frame);
      int64_t seq = rd.GetI64();
      int32_t tag = rd.GetI32();
      (void)seq;
      if (!rd.ok() || tag != kTagShmVerdict || rd.remaining() < 1 ||
          rd.cursor()[0] == 0) {
        all_ok = 0;
      }
    }
    for (int j = 1; j < m; ++j) {
      Writer w;
      PutFrameHeader(&w, current_seq_, kTagShmVerdict);
      w.PutRaw(&all_ok, 1);
      if (!socks[members[j]].SendFrame(w.data())) {
        return Status::Error(StatusCode::ABORTED, "shm verdict send failed");
      }
    }
    ok = all_ok;
  } else {
    Writer w;
    PutFrameHeader(&w, current_seq_, kTagShmVerdict);
    w.PutRaw(&ok, 1);
    if (!socks[members[0]].SendFrame(w.data())) {
      return Status::Error(StatusCode::ABORTED, "shm verdict send failed");
    }
    std::string frame;
    if (!socks[members[0]].RecvFrame(&frame)) {
      return Status::Error(StatusCode::ABORTED, "shm verdict recv failed");
    }
    Reader rd(frame);
    rd.GetI64();
    int32_t tag = rd.GetI32();
    ok = (rd.ok() && tag == kTagShmVerdict && rd.remaining() >= 1)
             ? static_cast<uint8_t>(rd.cursor()[0])
             : 0;
  }
  if (!ok) {
    region->Close(creator);
    HVD_LOG(INFO) << "shm plane unavailable for psid " << psid
                  << "; using the TCP ring";
    return Status::OK();
  }
  std::lock_guard<std::mutex> l(channels_mu_);
  shm_[psid] = std::move(region);
  return Status::OK();
}

ShmRegion* SocketController::ShmFor(int psid) {
  std::lock_guard<std::mutex> l(channels_mu_);
  auto it = shm_.find(psid);
  return it == shm_.end() ? nullptr : it->second.get();
}

Status SocketController::ShmAllreduce(ShmRegion& shm,
                                      std::vector<Socket>& socks,
                                      const std::vector<int>& members,
                                      int idx, void* buf, int64_t count,
                                      DataType dtype, ReduceOp op) {
  const int m = static_cast<int>(members.size());
  const int item = ItemSize(dtype);
  const int64_t nbytes = count * item;
  auto grow_barrier = [&] {
    return SockBarrier(socks, members, idx, kTagShmGrow);
  };
  Status st = shm.EnsureCapacity((m + 1) * nbytes, idx == 0, grow_barrier);
  if (!st.ok()) return st;
  char* slots = shm.data();
  char* result = slots + m * nbytes;
  std::memcpy(slots + idx * nbytes, buf, nbytes);
  st = SockBarrier(socks, members, idx, kTagShmWrite);
  if (!st.ok()) return st;
  // Each member reduces segment `idx` across all slots into the result
  // area (same segmentation math as the TCP ring).
  const int64_t chunk = count / m, rem = count % m;
  auto start = [&](int c) { return c * chunk + std::min<int64_t>(c, rem); };
  const int64_t seg_off = start(idx) * item;
  const int64_t seg_len = (start(idx + 1) - start(idx));
  if (seg_len > 0) {
    std::memcpy(result + seg_off, slots + seg_off, seg_len * item);
    for (int j = 1; j < m; ++j) {
      ReduceInto(result + seg_off, slots + j * nbytes + seg_off, seg_len,
                 dtype, op);
    }
  }
  st = SockBarrier(socks, members, idx, kTagShmMid);
  if (!st.ok()) return st;
  std::memcpy(buf, result, nbytes);
  // Trailing fence: the next op's writes must not land while a peer is
  // still reading the result area.
  return SockBarrier(socks, members, idx, kTagShmRead);
}

Status SocketController::ShmBroadcast(ShmRegion& shm,
                                      std::vector<Socket>& socks,
                                      const std::vector<int>& members,
                                      int idx, int root_idx, void* buf,
                                      int64_t nbytes) {
  auto grow_barrier = [&] {
    return SockBarrier(socks, members, idx, kTagShmGrow);
  };
  Status st = shm.EnsureCapacity(nbytes, idx == 0, grow_barrier);
  if (!st.ok()) return st;
  if (idx == root_idx) std::memcpy(shm.data(), buf, nbytes);
  st = SockBarrier(socks, members, idx, kTagShmWrite);
  if (!st.ok()) return st;
  if (idx != root_idx) std::memcpy(buf, shm.data(), nbytes);
  return SockBarrier(socks, members, idx, kTagShmRead);
}

Status SocketController::ShmAllgather(ShmRegion& shm,
                                      std::vector<Socket>& socks,
                                      const std::vector<int>& members,
                                      int idx, const void* in, int64_t nbytes,
                                      std::string* out,
                                      std::vector<int64_t>* per_rank) {
  const int m = static_cast<int>(members.size());
  auto grow_barrier = [&] {
    return SockBarrier(socks, members, idx, kTagShmGrow);
  };
  int64_t* hdr = reinterpret_cast<int64_t*>(shm.header());
  hdr[idx] = nbytes;
  Status st = SockBarrier(socks, members, idx, kTagShmSize);
  if (!st.ok()) return st;
  // Offsets snapshot the header before any growth remaps the region.
  std::vector<int64_t> offs(m + 1, 0);
  for (int j = 0; j < m; ++j) offs[j + 1] = offs[j] + hdr[j];
  st = shm.EnsureCapacity(offs[m], idx == 0, grow_barrier);
  if (!st.ok()) return st;
  std::memcpy(shm.data() + offs[idx], in, nbytes);
  st = SockBarrier(socks, members, idx, kTagShmWrite);
  if (!st.ok()) return st;
  out->clear();
  per_rank->clear();
  out->reserve(offs[m]);
  for (int j = 0; j < m; ++j) {
    per_rank->push_back(offs[j + 1] - offs[j]);
    out->append(shm.data() + offs[j], offs[j + 1] - offs[j]);
  }
  return SockBarrier(socks, members, idx, kTagShmRead);
}

Status SocketController::ShmAlltoall(ShmRegion& shm,
                                     std::vector<Socket>& socks,
                                     const std::vector<int>& members, int idx,
                                     const void* in,
                                     const std::vector<int64_t>& splits,
                                     int64_t row_bytes, std::string* out,
                                     std::vector<int64_t>* recv_splits) {
  const int m = static_cast<int>(members.size());
  auto grow_barrier = [&] {
    return SockBarrier(socks, members, idx, kTagShmGrow);
  };
  int64_t* hdr = reinterpret_cast<int64_t*>(shm.header());
  for (int j = 0; j < m; ++j) hdr[idx * m + j] = splits[j];
  Status st = SockBarrier(socks, members, idx, kTagShmSize);
  if (!st.ok()) return st;
  // Snapshot the geometry BEFORE any growth: EnsureCapacity remaps the
  // region, so the header pointer must not be dereferenced after it.
  std::vector<int64_t> rows(hdr, hdr + m * m);
  // Row-major (src, dst) chunk offsets over the agreed geometry.
  std::vector<int64_t> offs(m * m + 1, 0);
  for (int k = 0; k < m * m; ++k) {
    offs[k + 1] = offs[k] + rows[k] * row_bytes;
  }
  st = shm.EnsureCapacity(offs[m * m], idx == 0, grow_barrier);
  if (!st.ok()) return st;
  const char* base = static_cast<const char*>(in);
  std::vector<int64_t> local_offs(m + 1, 0);
  for (int j = 0; j < m; ++j) local_offs[j + 1] = local_offs[j] + splits[j];
  for (int j = 0; j < m; ++j) {
    std::memcpy(shm.data() + offs[idx * m + j],
                base + local_offs[j] * row_bytes, splits[j] * row_bytes);
  }
  st = SockBarrier(socks, members, idx, kTagShmWrite);
  if (!st.ok()) return st;
  out->clear();
  recv_splits->clear();
  for (int i = 0; i < m; ++i) {
    const int64_t k = i * m + idx;
    recv_splits->push_back(rows[k]);
    out->append(shm.data() + offs[k], rows[k] * row_bytes);
  }
  return SockBarrier(socks, members, idx, kTagShmRead);
}

// ---------------------------------------------------------------------------
// Hierarchical allreduce: shm-local reduce -> leader ring -> shm broadcast
// (reference analog: NCCLHierarchicalAllreduce, SURVEY.md §2.2; the Awan
// et al. intra-node-reduce / inter-node-exchange design)
// ---------------------------------------------------------------------------

std::string SocketController::HostKey(int rank, int size) {
  // Explicit per-rank override first (the reference env name).
  if (const char* env = ::getenv("HOROVOD_HOSTNAME")) {
    if (env[0]) return env;
  }
  // Test hook: HOROVOD_HIER_FAKE_HOSTS=n partitions the job into n blocks
  // of consecutive ranks so one machine can emulate a multi-host topology
  // (mirrors real deployments, where consecutive ranks share a host).
  if (const char* env = ::getenv("HOROVOD_HIER_FAKE_HOSTS")) {
    char* end = nullptr;
    long n = std::strtol(env, &end, 10);
    if (end && *end == '\0' && n > 1 && size > 0) {
      int64_t h = static_cast<int64_t>(rank) * n / size;
      return "fakehost-" + std::to_string(h);
    }
  }
  char buf[256] = {0};
  if (::gethostname(buf, sizeof(buf) - 1) != 0) return "unknown-host";
  return buf;
}

void SocketController::CountSend(int to, int64_t wire_bytes,
                                 int64_t raw_bytes) {
  if (to < 0 || to >= static_cast<int>(host_keys_.size())) return;
  if (host_keys_[to] == host_keys_[cfg_.rank]) {
    data_sent_local_.fetch_add(wire_bytes, std::memory_order_relaxed);
    data_raw_local_.fetch_add(raw_bytes, std::memory_order_relaxed);
  } else {
    data_sent_xhost_.fetch_add(wire_bytes, std::memory_order_relaxed);
    data_raw_xhost_.fetch_add(raw_bytes, std::memory_order_relaxed);
  }
}

Status SocketController::MaybeSetupHier(int psid,
                                        const std::vector<int>& members) {
  const int m = static_cast<int>(members.size());
  if (m <= 1) return Status::OK();
  // Group members by agreed host key, first-appearance order over the
  // sorted member list: identical on every rank, and each group's first
  // member (its leader) ascends with the group index.
  std::vector<std::vector<int>> groups;
  std::map<std::string, int> group_of;
  for (int r : members) {
    auto it = group_of.find(host_keys_[r]);
    if (it == group_of.end()) {
      group_of.emplace(host_keys_[r], static_cast<int>(groups.size()));
      groups.push_back({r});
    } else {
      groups[it->second].push_back(r);
    }
  }
  size_t max_group = 0;
  for (const auto& grp : groups) max_group = std::max(max_group, grp.size());
  // Topology applicability is a pure function of the agreed book, so an
  // agreed skip here cannot desync: the composition only pays off with
  // >=2 hosts and at least one host holding co-located ranks.  The
  // degenerate 1-rank-per-host job never builds a topology and stays on
  // the flat ring by construction.
  if (groups.size() < 2 || max_group < 2) return Status::OK();

  HierTopo topo;
  const int my_group = group_of[host_keys_[cfg_.rank]];
  topo.local = groups[my_group];
  topo.local_idx = static_cast<int>(
      std::find(topo.local.begin(), topo.local.end(), cfg_.rank) -
      topo.local.begin());
  for (const auto& grp : groups) topo.leaders.push_back(grp[0]);
  auto lit = std::find(topo.leaders.begin(), topo.leaders.end(), cfg_.rank);
  topo.leader_idx = lit == topo.leaders.end()
                        ? -1
                        : static_cast<int>(lit - topo.leaders.begin());

  auto mit = std::find(members.begin(), members.end(), cfg_.rank);
  const int idx = static_cast<int>(mit - members.begin());
  std::vector<Socket>& socks = SocksFor(psid);

  // The intra-host phases need the subgroup shm region; per-rank state
  // (HOROVOD_SHM_DISABLE, an shm_open failure) may diverge, so every
  // member always runs the whole-set handshake and a single no vote
  // demotes the entire set back to the flat ring.
  const char* disable = ::getenv("HOROVOD_SHM_DISABLE");
  const bool attempt = !(disable && disable[0] == '1');
  const bool creator = topo.local_idx == 0;
  Status open_st = Status::OK();
  std::string name;
  if (topo.local.size() > 1) {
    topo.shm = std::make_unique<ShmRegion>();
    name = "/hvd_" + std::to_string(cfg_.rendezvous_port) + "_" +
           std::to_string(psid) + "_h" + std::to_string(my_group);
    if (creator && attempt) open_st = topo.shm->Open(name, true);
  }
  Status st = SockBarrier(socks, members, idx, kTagHierOpen);
  if (!st.ok()) return st;
  if (topo.shm && !creator && attempt) open_st = topo.shm->Open(name, false);
  if (topo.shm && !attempt) {
    open_st = Status::Error(StatusCode::PRECONDITION_ERROR, "not attempted");
  }
  // Whole-set agreed verdict through the set root (same shape as the shm
  // plane's): either every member keeps the topology or nobody does.
  uint8_t ok = open_st.ok() ? 1 : 0;
  if (idx == 0) {
    uint8_t all_ok = ok;
    for (int j = 1; j < m; ++j) {
      std::string frame;
      if (!socks[members[j]].RecvFrame(&frame)) all_ok = 0;
      Reader rd(frame);
      rd.GetI64();
      int32_t tag = rd.GetI32();
      if (!rd.ok() || tag != kTagHierVerdict || rd.remaining() < 1 ||
          rd.cursor()[0] == 0) {
        all_ok = 0;
      }
    }
    for (int j = 1; j < m; ++j) {
      Writer w;
      PutFrameHeader(&w, current_seq_, kTagHierVerdict);
      w.PutRaw(&all_ok, 1);
      if (!socks[members[j]].SendFrame(w.data())) {
        return Status::Error(StatusCode::ABORTED, "hier verdict send failed");
      }
    }
    ok = all_ok;
  } else {
    Writer w;
    PutFrameHeader(&w, current_seq_, kTagHierVerdict);
    w.PutRaw(&ok, 1);
    if (!socks[members[0]].SendFrame(w.data())) {
      return Status::Error(StatusCode::ABORTED, "hier verdict send failed");
    }
    std::string frame;
    if (!socks[members[0]].RecvFrame(&frame)) {
      return Status::Error(StatusCode::ABORTED, "hier verdict recv failed");
    }
    Reader rd(frame);
    rd.GetI64();
    int32_t tag = rd.GetI32();
    ok = (rd.ok() && tag == kTagHierVerdict && rd.remaining() >= 1)
             ? static_cast<uint8_t>(rd.cursor()[0])
             : 0;
  }
  if (!ok) {
    if (topo.shm) topo.shm->Close(creator);
    HVD_LOG(INFO) << "hierarchical allreduce unavailable for psid " << psid
                  << "; staying on the flat ring";
    return Status::OK();
  }
  HVD_LOG(INFO) << "hierarchical topology for psid " << psid << ": "
                << groups.size() << " hosts, " << topo.local.size()
                << " local member(s), leader rank " << topo.leaders[my_group];
  std::lock_guard<std::mutex> l(channels_mu_);
  hier_.emplace(psid, std::move(topo));
  return Status::OK();
}

SocketController::HierTopo* SocketController::HierFor(int psid) {
  std::lock_guard<std::mutex> l(channels_mu_);
  auto it = hier_.find(psid);
  return it == hier_.end() ? nullptr : &it->second;
}

Status SocketController::HierAllreduce(HierTopo& topo,
                                       std::vector<Socket>& socks, void* buf,
                                       int64_t count, DataType dtype,
                                       ReduceOp op, WireCodec codec) {
  const int ml = static_cast<int>(topo.local.size());
  const int item = ItemSize(dtype);
  const int64_t nbytes = count * item;
  char* ringbuf = static_cast<char*>(buf);
  if (ml > 1) {
    // Phase 1: shm-local reduce into the region's result area.  Same
    // layout and fences as ShmAllreduce (ml write slots + result), with
    // the segment reduce split across local members.
    ShmRegion& shm = *topo.shm;
    auto grow_barrier = [&] {
      return SockBarrier(socks, topo.local, topo.local_idx, kTagHierGrow);
    };
    Status st = shm.EnsureCapacity((ml + 1) * nbytes, topo.local_idx == 0,
                                   grow_barrier);
    if (!st.ok()) return st;
    char* slots = shm.data();
    char* result = slots + ml * nbytes;
    std::memcpy(slots + topo.local_idx * nbytes, buf, nbytes);
    st = SockBarrier(socks, topo.local, topo.local_idx, kTagHierWrite);
    if (!st.ok()) return st;
    const int64_t chunk = count / ml, rem = count % ml;
    auto start = [&](int c) { return c * chunk + std::min<int64_t>(c, rem); };
    const int64_t seg_off = start(topo.local_idx) * item;
    const int64_t seg_len = start(topo.local_idx + 1) - start(topo.local_idx);
    if (seg_len > 0) {
      std::memcpy(result + seg_off, slots + seg_off, seg_len * item);
      for (int j = 1; j < ml; ++j) {
        ReduceInto(result + seg_off, slots + j * nbytes + seg_off, seg_len,
                   dtype, op);
      }
    }
    st = SockBarrier(socks, topo.local, topo.local_idx, kTagHierMid);
    if (!st.ok()) return st;
    // The leader runs the cross-host ring directly on the shm result area.
    ringbuf = result;
  }
  // Phase 2: leader-only chunk-pipelined ring across hosts.  This is the
  // whole win: each host moves ~2N over the wire instead of every rank's
  // 2(np-1)/np*N.  Non-leaders skip straight to the fence.
  if (topo.leader_idx >= 0) {
    // Every leader-ring hop crosses hosts, so this is where the wire
    // codec engages (the shm-local phases above/below stay raw fp32).
    Status st =
        (codec != WireCodec::kNone && dtype == DataType::FLOAT32)
            ? CompressedRingAllreduce(socks, ringbuf, count, op,
                                      topo.leaders, topo.leader_idx, codec)
            : RingAllreduce(socks, ringbuf, count, dtype, op, topo.leaders,
                            topo.leader_idx);
    if (!st.ok()) return st;
  }
  if (ml > 1) {
    // Phase 3: shm-local broadcast — wait for the leader's ring, then
    // every local member copies the globally reduced result out.
    Status st = SockBarrier(socks, topo.local, topo.local_idx, kTagHierDone);
    if (!st.ok()) return st;
    std::memcpy(buf, topo.shm->data() + ml * nbytes, nbytes);
    // Trailing fence: the next op's slot writes must not land while a
    // peer is still reading the result area.
    return SockBarrier(socks, topo.local, topo.local_idx, kTagHierRead);
  }
  return Status::OK();
}

}  // namespace hvdtpu
