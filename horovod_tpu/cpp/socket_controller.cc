#include "socket_controller.h"

#include <poll.h>

#include <algorithm>
#include <cstring>
#include <sstream>

#include "logging.h"

namespace hvdtpu {

namespace {

constexpr double kConnectTimeoutS = 60.0;

}  // namespace

// Serialization of the data-plane frame header.
static void WriteDataHeader(Writer* w, int rank, int64_t seq, OpType op,
                            DataType dtype, ReduceOp rop, int psid, int root,
                            int64_t row_bytes,
                            const std::vector<int64_t>& splits) {
  w->PutI32(rank);
  w->PutI64(seq);
  w->PutI32(static_cast<int32_t>(op));
  w->PutI32(static_cast<int32_t>(dtype));
  w->PutI32(static_cast<int32_t>(rop));
  w->PutI32(psid);
  w->PutI32(root);
  w->PutI64(row_bytes);
  w->PutI64Vec(splits);
}

SocketController::SocketController(const CoreConfig& cfg)
    : Controller(cfg), cache_(cfg.cache_capacity) {}

SocketController::~SocketController() { Shutdown(); }

Status SocketController::Initialize() {
  process_sets_.InitGlobal(cfg_.size);
  if (is_coordinator()) {
    if (!listener_.Listen("0.0.0.0", cfg_.rendezvous_port)) {
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "coordinator failed to listen on port " +
                               std::to_string(cfg_.rendezvous_port));
    }
    ctrl_socks_.resize(cfg_.size);
    data_socks_.resize(cfg_.size);
    int needed = 2 * (cfg_.size - 1);
    double deadline = MonotonicSeconds() + kConnectTimeoutS;
    while (needed > 0) {
      if (MonotonicSeconds() > deadline) {
        return Status::Error(StatusCode::PRECONDITION_ERROR,
                             "rendezvous timeout waiting for workers");
      }
      Socket s = listener_.Accept(1.0);
      if (!s.valid()) continue;
      std::string hello;
      if (!s.RecvFrame(&hello)) continue;
      Reader r(hello);
      int rank = r.GetI32();
      int channel = r.GetI32();
      if (rank <= 0 || rank >= cfg_.size || (channel != 0 && channel != 1)) {
        return Status::Error(StatusCode::INVALID_ARGUMENT,
                             "bad HELLO from worker");
      }
      if (channel == 0) {
        ctrl_socks_[rank] = std::move(s);
      } else {
        data_socks_[rank] = std::move(s);
      }
      --needed;
    }
    data_shutdown_ = false;
    data_thread_ = std::thread([this] { DataServiceLoop(); });
  } else {
    if (!coord_ctrl_.Connect(cfg_.rendezvous_addr, cfg_.rendezvous_port,
                             kConnectTimeoutS) ||
        !coord_data_.Connect(cfg_.rendezvous_addr, cfg_.rendezvous_port,
                             kConnectTimeoutS)) {
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "worker failed to reach coordinator at " +
                               cfg_.rendezvous_addr + ":" +
                               std::to_string(cfg_.rendezvous_port));
    }
    Writer hello_ctrl;
    hello_ctrl.PutI32(cfg_.rank);
    hello_ctrl.PutI32(0);
    Writer hello_data;
    hello_data.PutI32(cfg_.rank);
    hello_data.PutI32(1);
    if (!coord_ctrl_.SendFrame(hello_ctrl.data()) ||
        !coord_data_.SendFrame(hello_data.data())) {
      return Status::Error(StatusCode::PRECONDITION_ERROR, "HELLO failed");
    }
  }
  initialized_ = true;
  return Status::OK();
}

void SocketController::Shutdown() {
  if (!initialized_) return;
  initialized_ = false;
  aborted_ = true;
  {
    std::lock_guard<std::mutex> l(data_mu_);
    data_shutdown_ = true;
    data_cv_.notify_all();
  }
  coord_ctrl_.Close();
  coord_data_.Close();
  for (auto& s : ctrl_socks_) s.Close();
  for (auto& s : data_socks_) s.Close();
  listener_.Close();
  if (data_thread_.joinable()) data_thread_.join();
}

// ---------------------------------------------------------------------------
// Negotiation
// ---------------------------------------------------------------------------

Status SocketController::ComputeResponses(
    std::vector<TensorRequest>& new_requests, std::vector<Response>* out) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  return is_coordinator() ? CoordinatorCycle(new_requests, out)
                          : WorkerCycle(new_requests, out);
}

void SocketController::Announce(int rank, TensorRequest req,
                                std::vector<Response>* errors) {
  // Process-set registration happens on each rank's Python thread and may
  // race announcements arriving from faster ranks; an unknown process set
  // is therefore *deferred* (the tensor stays pending until the local
  // registration lands), not an error.  Membership is validated once the
  // set is known, at readiness-check time.
  std::vector<int> members;
  if (process_sets_.Ranks(req.process_set_id, &members) &&
      !std::binary_search(members.begin(), members.end(), rank)) {
    Response e;
    e.error = "rank " + std::to_string(rank) +
              " is not in process set of tensor " + req.name;
    e.names.push_back(req.name);
    e.metas.push_back(req);
    errors->push_back(std::move(e));
    return;
  }
  auto it = pending_.find(req.name);
  if (it == pending_.end()) {
    Pending p;
    p.meta = req;
    p.order = arrival_counter_++;
    p.first_seen = MonotonicSeconds();
    p.announced.insert(rank);
    pending_.emplace(req.name, std::move(p));
    return;
  }
  // Cross-rank consistency validation (reference: ComputeResponseList's
  // error construction for mismatched shapes/dtypes).
  Pending& p = it->second;
  std::string mismatch;
  if (p.meta.op != req.op) {
    mismatch = "operation type";
  } else if (p.meta.dtype != req.dtype) {
    mismatch = "dtype";
  } else if (p.meta.reduce_op != req.reduce_op) {
    mismatch = "reduce op";
  } else if (p.meta.process_set_id != req.process_set_id) {
    mismatch = "process set";
  } else if (p.meta.root_rank != req.root_rank) {
    mismatch = "root rank";
  } else if (p.meta.prescale != req.prescale ||
             p.meta.postscale != req.postscale) {
    mismatch = "scale factors";
  } else if (req.op == OpType::ALLREDUCE || req.op == OpType::BROADCAST ||
             req.op == OpType::REDUCESCATTER) {
    if (p.meta.shape != req.shape) mismatch = "shape";
  } else if (req.op == OpType::ALLGATHER || req.op == OpType::ALLTOALL) {
    // first dim may differ per rank; trailing dims must match
    if (std::vector<int64_t>(p.meta.shape.begin() +
                                 (p.meta.shape.empty() ? 0 : 1),
                             p.meta.shape.end()) !=
        std::vector<int64_t>(req.shape.begin() + (req.shape.empty() ? 0 : 1),
                             req.shape.end())) {
      mismatch = "trailing shape";
    }
  }
  if (!mismatch.empty()) {
    Response e;
    e.error = "Mismatched " + mismatch + " for tensor " + req.name +
              " across ranks";
    e.names.push_back(req.name);
    e.metas.push_back(p.meta);
    errors->push_back(std::move(e));
    pending_.erase(it);
    return;
  }
  p.announced.insert(rank);
}

Status SocketController::CoordinatorCycle(
    std::vector<TensorRequest>& new_requests, std::vector<Response>* out) {
  std::vector<Response> errors;
  // Own announcements first (deterministic: coordinator, then rank order).
  for (auto& r : new_requests) Announce(0, std::move(r), &errors);
  for (int rank = 1; rank < cfg_.size; ++rank) {
    std::string frame;
    if (!ctrl_socks_[rank].RecvFrame(&frame)) {
      aborted_ = true;
      return Status::Error(StatusCode::ABORTED,
                           "lost connection to rank " + std::to_string(rank));
    }
    Reader rd(frame);
    int32_t n_cached = rd.GetI32();
    for (int32_t i = 0; i < n_cached; ++i) {
      int64_t id = rd.GetI64();
      TensorRequest req;
      if (cache_.Get(id, &req)) {
        Announce(rank, std::move(req), &errors);
      } else {
        Response e;
        e.error = "response cache divergence: unknown cache id " +
                  std::to_string(id) + " from rank " + std::to_string(rank);
        errors.push_back(std::move(e));
      }
    }
    int32_t n_full = rd.GetI32();
    for (int32_t i = 0; i < n_full; ++i) {
      Announce(rank, DeserializeRequest(&rd), &errors);
    }
  }

  // Collect ready tensors in deterministic (arrival-order) sequence.
  std::vector<std::pair<int64_t, std::string>> ready_names;
  for (auto& kv : pending_) {
    std::vector<int> members;
    if (!process_sets_.Ranks(kv.second.meta.process_set_id, &members)) {
      continue;  // set not registered yet on this (coordinator) rank
    }
    bool ready = true;
    for (int m : members) {
      if (!kv.second.announced.count(m)) {
        ready = false;
        break;
      }
    }
    if (ready) ready_names.emplace_back(kv.second.order, kv.first);
  }
  std::sort(ready_names.begin(), ready_names.end());
  std::vector<TensorRequest> ready;
  ready.reserve(ready_names.size());
  for (auto& [ord, name] : ready_names) {
    ready.push_back(pending_[name].meta);
    pending_.erase(name);
  }

  *out = FuseRequests(ready, cfg_.fusion_threshold);
  out->insert(out->begin(), errors.begin(), errors.end());
  UpdateCachesAndSeq(out);

  // Broadcast the identical response list to every worker.
  Writer w;
  w.PutI32(static_cast<int32_t>(out->size()));
  for (const auto& r : *out) SerializeResponse(r, &w);
  const std::string payload = w.data();
  for (int rank = 1; rank < cfg_.size; ++rank) {
    if (!ctrl_socks_[rank].SendFrame(payload)) {
      aborted_ = true;
      return Status::Error(StatusCode::ABORTED,
                           "failed to send responses to rank " +
                               std::to_string(rank));
    }
  }
  return Status::OK();
}

Status SocketController::WorkerCycle(std::vector<TensorRequest>& new_requests,
                                     std::vector<Response>* out) {
  Writer w;
  // Cache hits travel as bare ids (the reference's bit-vector fast path).
  std::vector<int64_t> cached;
  std::vector<const TensorRequest*> full;
  for (const auto& r : new_requests) {
    int64_t id = cache_.Lookup(r);
    if (id >= 0) {
      cached.push_back(id);
    } else {
      full.push_back(&r);
    }
  }
  w.PutI32(static_cast<int32_t>(cached.size()));
  for (int64_t id : cached) w.PutI64(id);
  w.PutI32(static_cast<int32_t>(full.size()));
  for (const auto* r : full) SerializeRequest(*r, &w);
  if (!coord_ctrl_.SendFrame(w.data())) {
    aborted_ = true;
    return Status::Error(StatusCode::ABORTED, "lost coordinator (send)");
  }
  std::string frame;
  if (!coord_ctrl_.RecvFrame(&frame)) {
    aborted_ = true;
    return Status::Error(StatusCode::ABORTED, "lost coordinator (recv)");
  }
  Reader rd(frame);
  int32_t n = rd.GetI32();
  out->clear();
  out->reserve(n);
  for (int32_t i = 0; i < n; ++i) out->push_back(DeserializeResponse(&rd));
  // Local seq counter mirrors the coordinator's (sanity only) and caches are
  // updated from the metas carried by each response — identical on all
  // ranks, so cache ids agree without extra synchronisation.
  for (auto& r : *out) {
    if (r.error.empty()) {
      for (const auto& m : r.metas) cache_.Insert(m);
      if (r.seq >= 0) seq_counter_ = r.seq + 1;
    }
  }
  return Status::OK();
}

void SocketController::UpdateCachesAndSeq(std::vector<Response>* responses) {
  for (auto& r : *responses) {
    if (!r.error.empty()) continue;
    bool all_cached = true;
    for (const auto& m : r.metas) {
      if (cache_.Lookup(m) < 0) all_cached = false;
      cache_.Insert(m);
    }
    r.cache_hit = all_cached;
    r.seq = seq_counter_++;
  }
}

std::string SocketController::StallReport(double older_than_s) {
  if (!is_coordinator()) return "";
  double now = MonotonicSeconds();
  std::ostringstream os;
  for (const auto& kv : pending_) {
    if (now - kv.second.first_seen < older_than_s) continue;
    std::vector<int> members;
    process_sets_.Ranks(kv.second.meta.process_set_id, &members);
    os << kv.first << " (waiting on ranks:";
    for (int m : members) {
      if (!kv.second.announced.count(m)) os << " " << m;
    }
    os << "); ";
  }
  return os.str();
}

// ---------------------------------------------------------------------------
// Data plane
// ---------------------------------------------------------------------------

Status SocketController::MemberDataOp(const DataOpHeader& h,
                                      const std::string& payload,
                                      std::string* reply) {
  if (aborted_) return Status::Error(StatusCode::ABORTED, "controller down");
  if (is_coordinator()) {
    {
      std::lock_guard<std::mutex> l(data_mu_);
      local_contrib_.emplace_back(h, payload);
      data_cv_.notify_all();
    }
    std::unique_lock<std::mutex> l(data_mu_);
    data_cv_.wait(l, [&] {
      return data_shutdown_ || local_reply_.count(h.seq) > 0;
    });
    if (data_shutdown_ && !local_reply_.count(h.seq)) {
      return Status::Error(StatusCode::ABORTED, "shutdown during data op");
    }
    *reply = std::move(local_reply_[h.seq]);
    local_reply_.erase(h.seq);
    return Status::OK();
  }
  Writer w;
  WriteDataHeader(&w, cfg_.rank, h.seq, h.op, h.dtype, h.reduce_op,
                  h.process_set_id, h.root_rank, h.row_bytes, h.splits);
  w.PutString(payload);
  if (!coord_data_.SendFrame(w.data())) {
    aborted_ = true;
    return Status::Error(StatusCode::ABORTED, "data plane send failed");
  }
  if (!coord_data_.RecvFrame(reply)) {
    aborted_ = true;
    return Status::Error(StatusCode::ABORTED, "data plane recv failed");
  }
  return Status::OK();
}

void SocketController::DataServiceLoop() {
  std::vector<pollfd> pfds;
  std::vector<int> pfd_ranks;
  for (int rank = 1; rank < cfg_.size; ++rank) {
    pfds.push_back(pollfd{data_socks_[rank].fd(), POLLIN, 0});
    pfd_ranks.push_back(rank);
  }
  while (true) {
    // Drain local (rank 0) contributions.
    {
      std::lock_guard<std::mutex> l(data_mu_);
      if (data_shutdown_) return;
      while (!local_contrib_.empty()) {
        auto [h, payload] = std::move(local_contrib_.front());
        local_contrib_.pop_front();
        DataOpState& st = data_ops_[h.seq];
        st.header = h;
        st.header_set = true;
        st.contributions[0] = std::move(payload);
      }
    }
    // Poll worker sockets.
    if (!pfds.empty()) {
      int rc = ::poll(pfds.data(), pfds.size(), 20);
      if (rc > 0) {
        for (size_t i = 0; i < pfds.size(); ++i) {
          if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
          std::string frame;
          if (!data_socks_[pfd_ranks[i]].RecvFrame(&frame)) {
            // Worker gone: fail all outstanding ops it belonged to.
            std::lock_guard<std::mutex> l(data_mu_);
            if (data_shutdown_) return;
            aborted_ = true;
            data_shutdown_ = true;
            data_cv_.notify_all();
            return;
          }
          Reader rd(frame);
          DataOpHeader h;
          int rank = rd.GetI32();
          h.seq = rd.GetI64();
          h.op = static_cast<OpType>(rd.GetI32());
          h.dtype = static_cast<DataType>(rd.GetI32());
          h.reduce_op = static_cast<ReduceOp>(rd.GetI32());
          h.process_set_id = rd.GetI32();
          h.root_rank = rd.GetI32();
          h.row_bytes = rd.GetI64();
          h.splits = rd.GetI64Vec();
          std::string payload = rd.GetString();
          std::lock_guard<std::mutex> l(data_mu_);
          DataOpState& st = data_ops_[h.seq];
          st.header = h;
          st.header_set = true;
          st.contributions[rank] = std::move(payload);
        }
      }
    } else {
      // Single-process-set-of-one corner: nothing to poll, just pace.
      std::unique_lock<std::mutex> l(data_mu_);
      data_cv_.wait_for(l, std::chrono::milliseconds(5), [this] {
        return data_shutdown_ || !local_contrib_.empty();
      });
      if (data_shutdown_) return;
      continue;
    }
    // Complete any ops whose member set is fully present.
    std::vector<int64_t> done;
    {
      std::lock_guard<std::mutex> l(data_mu_);
      for (auto& kv : data_ops_) {
        DataOpState& st = kv.second;
        if (!st.header_set) continue;
        std::vector<int> members;
        if (!process_sets_.Ranks(st.header.process_set_id, &members)) continue;
        bool complete = true;
        for (int m : members) {
          if (!st.contributions.count(m)) {
            complete = false;
            break;
          }
        }
        if (complete) done.push_back(kv.first);
      }
    }
    for (int64_t seq : done) {
      DataOpState st;
      {
        std::lock_guard<std::mutex> l(data_mu_);
        st = std::move(data_ops_[seq]);
        data_ops_.erase(seq);
      }
      CompleteDataOp(st);
    }
  }
}

void SocketController::ExecuteDataOp(
    const DataOpHeader& h, const std::map<int, std::string>& contribs,
    const std::vector<int>& members, std::map<int, std::string>* replies) {
  // Uniform reply frame: [i64 meta vec][payload string].
  auto make_reply = [](const std::vector<int64_t>& meta,
                       const std::string& payload) {
    Writer w;
    w.PutI64Vec(meta);
    w.PutString(payload);
    return w.Take();
  };
  switch (h.op) {
    case OpType::ALLREDUCE:
    case OpType::REDUCESCATTER: {
      std::string acc = contribs.at(members.front());
      int item = ItemSize(h.dtype);
      int64_t count = static_cast<int64_t>(acc.size()) / item;
      for (size_t i = 1; i < members.size(); ++i) {
        const std::string& c = contribs.at(members[i]);
        ReduceInto(&acc[0], c.data(), count, h.dtype, h.reduce_op);
      }
      std::string reply = make_reply({}, acc);
      for (int m : members) (*replies)[m] = reply;
      break;
    }
    case OpType::ALLGATHER: {
      std::string all;
      std::vector<int64_t> counts;
      for (int m : members) {
        const std::string& c = contribs.at(m);
        counts.push_back(static_cast<int64_t>(c.size()));
        all += c;
      }
      std::string reply = make_reply(counts, all);
      for (int m : members) (*replies)[m] = reply;
      break;
    }
    case OpType::BROADCAST: {
      const std::string& payload = contribs.at(h.root_rank);
      std::string reply = make_reply({}, payload);
      for (int m : members) (*replies)[m] = reply;
      break;
    }
    case OpType::ALLTOALL: {
      // splits live per-contribution: we re-read them from each sender's
      // header copy — but headers are per-op here, so senders pack their
      // splits at the front of the payload instead.
      // Payload layout: [i64 n][splits...][bytes]
      std::map<int, std::vector<int64_t>> splits;
      std::map<int, std::string> bufs;
      for (int m : members) {
        Reader rd(contribs.at(m));
        splits[m] = rd.GetI64Vec();
        bufs[m] = rd.GetString();
      }
      for (size_t j = 0; j < members.size(); ++j) {
        int dest = members[j];
        std::string out;
        std::vector<int64_t> recv_splits;
        for (int src : members) {
          const auto& sp = splits[src];
          int64_t offset_rows = 0;
          for (size_t k = 0; k < j; ++k) offset_rows += sp[k];
          int64_t rows = sp[j];
          out.append(bufs[src].data() + offset_rows * h.row_bytes,
                     rows * h.row_bytes);
          recv_splits.push_back(rows);
        }
        (*replies)[dest] = make_reply(recv_splits, out);
      }
      break;
    }
    case OpType::BARRIER:
    case OpType::JOIN: {
      std::string reply = make_reply({}, "");
      for (int m : members) (*replies)[m] = reply;
      break;
    }
  }
}

void SocketController::CompleteDataOp(DataOpState& st) {
  std::vector<int> members;
  process_sets_.Ranks(st.header.process_set_id, &members);
  std::map<int, std::string> replies;
  ExecuteDataOp(st.header, st.contributions, members, &replies);
  for (auto& [rank, reply] : replies) {
    if (rank == 0) {
      std::lock_guard<std::mutex> l(data_mu_);
      local_reply_[st.header.seq] = std::move(reply);
      data_cv_.notify_all();
    } else {
      if (!data_socks_[rank].SendFrame(reply)) {
        HVD_LOG(WARNING) << "data reply to rank " << rank << " failed";
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Public data-plane API (called from the Python executor thread)
// ---------------------------------------------------------------------------

namespace {
// Parse the uniform reply frame.
void ParseReply(const std::string& reply, std::vector<int64_t>* meta,
                std::string* payload) {
  Reader rd(reply);
  *meta = rd.GetI64Vec();
  *payload = rd.GetString();
}
}  // namespace

Status SocketController::AllreduceBuffer(void* buf, int64_t count,
                                         DataType dtype, ReduceOp op,
                                         int psid) {
  DataOpHeader h;
  h.seq = current_seq_;
  h.op = OpType::ALLREDUCE;
  h.dtype = dtype;
  h.reduce_op = op;
  h.process_set_id = psid;
  int64_t nbytes = count * ItemSize(dtype);
  std::string payload(static_cast<const char*>(buf), nbytes);
  std::string reply;
  Status s = MemberDataOp(h, payload, &reply);
  if (!s.ok()) return s;
  std::vector<int64_t> meta;
  std::string out;
  ParseReply(reply, &meta, &out);
  std::memcpy(buf, out.data(), nbytes);
  return Status::OK();
}

Status SocketController::AllgatherBuffer(const void* in, int64_t nbytes,
                                         int psid, std::string* out,
                                         std::vector<int64_t>* per_rank) {
  DataOpHeader h;
  h.seq = current_seq_;
  h.op = OpType::ALLGATHER;
  h.process_set_id = psid;
  std::string payload(static_cast<const char*>(in), nbytes);
  std::string reply;
  Status s = MemberDataOp(h, payload, &reply);
  if (!s.ok()) return s;
  ParseReply(reply, per_rank, out);
  return Status::OK();
}

Status SocketController::BroadcastBuffer(void* buf, int64_t nbytes,
                                         int root_rank, int psid) {
  DataOpHeader h;
  h.seq = current_seq_;
  h.op = OpType::BROADCAST;
  h.process_set_id = psid;
  h.root_rank = root_rank;
  std::string payload;
  if (cfg_.rank == root_rank) {
    payload.assign(static_cast<const char*>(buf), nbytes);
  }
  std::string reply;
  Status s = MemberDataOp(h, payload, &reply);
  if (!s.ok()) return s;
  std::vector<int64_t> meta;
  std::string out;
  ParseReply(reply, &meta, &out);
  if (static_cast<int64_t>(out.size()) != nbytes) {
    return Status::Error(StatusCode::INVALID_ARGUMENT,
                         "broadcast size mismatch across ranks");
  }
  std::memcpy(buf, out.data(), nbytes);
  return Status::OK();
}

Status SocketController::AlltoallBuffer(const void* in,
                                        const std::vector<int64_t>& splits,
                                        int64_t row_bytes, int psid,
                                        std::string* out,
                                        std::vector<int64_t>* recv_splits) {
  DataOpHeader h;
  h.seq = current_seq_;
  h.op = OpType::ALLTOALL;
  h.process_set_id = psid;
  h.row_bytes = row_bytes;
  int64_t rows = 0;
  for (auto v : splits) rows += v;
  Writer w;
  w.PutI64Vec(splits);
  w.PutString(std::string(static_cast<const char*>(in), rows * row_bytes));
  std::string reply;
  Status s = MemberDataOp(h, w.data(), &reply);
  if (!s.ok()) return s;
  ParseReply(reply, recv_splits, out);
  return Status::OK();
}

Status SocketController::Barrier(int psid) {
  DataOpHeader h;
  h.seq = current_seq_;
  h.op = OpType::BARRIER;
  h.process_set_id = psid;
  std::string reply;
  return MemberDataOp(h, "", &reply);
}

}  // namespace hvdtpu
