// Controller: readiness negotiation + fusion + process sets + data plane.
//
// Reference: horovod/common/controller.h (Controller::ComputeResponseList),
// process_set.h (ProcessSetTable); SURVEY.md §2.1.  Two implementations:
// LocalController (single process — everything is immediately ready) and
// SocketController (rank-0 coordinator over TCP with response-cache
// bit-vectors and a coordinator-rooted host data plane, the Gloo-CPU-path
// analog).  On TPU pods the *device* data plane is XLA-over-ICI (driven from
// Python); the controller's job is to keep hosts in lockstep so every host
// dispatches the same fused XLA program.
#pragma once

#include <algorithm>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

#include "common.h"

namespace hvdtpu {

// Atomic group gating shared by LocalController and the coordinator
// (reference: group_table.cc — GroupTable): members of incomplete groups
// are withheld into `still_held`; everything else lands in `ready` sorted
// so each complete group sits CONTIGUOUSLY at its first member's arrival
// position (so members fuse together and other traffic cannot
// interleave).  `meta(payload)` yields the TensorRequest describing an
// item.  Fast path: with no grouped items in flight this is just the
// arrival-order sort + move the pre-group code did.
template <typename T, typename MetaFn>
void GateAndOrderGroups(std::vector<std::pair<int64_t, T>>&& items,
                        std::vector<std::pair<int64_t, T>>* still_held,
                        std::vector<T>* ready, MetaFn meta) {
  still_held->clear();
  ready->clear();
  bool any_group = false;
  for (const auto& it : items) {
    if (!meta(it.second).group_key.empty()) {
      any_group = true;
      break;
    }
  }
  if (!any_group) {
    std::sort(items.begin(), items.end(),
              [](const auto& a, const auto& b) { return a.first < b.first; });
    for (auto& [ord, t] : items) ready->push_back(std::move(t));
    return;
  }
  std::unordered_map<std::string, std::pair<int32_t, int64_t>> gstate;
  for (const auto& [ord, t] : items) {
    const auto& m = meta(t);
    if (m.group_key.empty()) continue;
    auto it = gstate.emplace(m.group_key, std::make_pair(0, ord)).first;
    it->second.first++;
    it->second.second = std::min(it->second.second, ord);
  }
  std::vector<std::pair<std::pair<int64_t, int64_t>, T>> keyed;
  keyed.reserve(items.size());
  for (auto& [ord, t] : items) {
    const auto& m = meta(t);
    if (m.group_key.empty()) {
      keyed.push_back({{ord, ord}, std::move(t)});
    } else if (gstate[m.group_key].first < m.group_size) {
      still_held->emplace_back(ord, std::move(t));
    } else {
      keyed.push_back({{gstate[m.group_key].second, ord}, std::move(t)});
    }
  }
  std::sort(keyed.begin(), keyed.end(),
            [](const auto& a, const auto& b) { return a.first < b.first; });
  for (auto& [k, t] : keyed) ready->push_back(std::move(t));
}

class ProcessSetTable {
 public:
  void InitGlobal(int world_size);
  int Add(const std::vector<int>& ranks);
  // QoS variant: `weight` orders fused-response scheduling on the
  // coordinator (higher first; the global set is pinned at 1.0).  The
  // plain Add defaults every set to weight 1.0.
  int AddWeighted(const std::vector<int>& ranks, double weight);
  void Remove(int id);
  bool Ranks(int id, std::vector<int>* out) const;
  bool Contains(int id, int rank) const;
  double Weight(int id) const;

 private:
  mutable std::mutex mu_;
  std::map<int, std::vector<int>> sets_;
  std::map<int, double> weights_;
  int next_id_ = 1;
};

// Deterministic fusion: group consecutive ready allreduces that share
// (dtype, process set, reduce op, pre/postscale) into buckets bounded by
// fusion_threshold bytes (reference: fusion_buffer_manager.h + the bucketing
// in Controller::ComputeResponseList).  Identical input order on every rank
// yields byte-identical responses.
std::vector<Response> FuseRequests(const std::vector<TensorRequest>& ready,
                                   int64_t fusion_threshold);

class Controller {
 public:
  explicit Controller(const CoreConfig& cfg) : cfg_(cfg) {}
  virtual ~Controller() = default;

  virtual Status Initialize() = 0;
  virtual void Shutdown() {}
  // Clean-exit notification sent before Shutdown(): workers tell the
  // coordinator they are leaving, the coordinator tells the workers —
  // turning teardown races into expected, quiet events (reference: the
  // DONE/shutdown message in the reference's controller protocol).
  virtual void Farewell() {}

  // One negotiation cycle: feed newly enqueued local requests, receive the
  // globally agreed (identical on all ranks) response list.
  virtual Status ComputeResponses(std::vector<TensorRequest>& new_requests,
                                  std::vector<Response>* out) = 0;

  // Host data plane over fused contiguous buffers.
  virtual Status AllreduceBuffer(void* buf, int64_t count, DataType dtype,
                                 ReduceOp op, int process_set_id) = 0;
  // Reduce-scatter: on return, this rank's slice (slice_counts[my_pos]
  // elements at its offset within buf) is fully reduced; other regions of
  // buf are unspecified.  Default: full allreduce (correct everywhere,
  // 2x the optimal wire bytes — SocketController overrides with a ring
  // phase that moves (m-1)/m of the buffer instead of 2(m-1)/m).
  virtual Status ReduceScatterBuffer(void* buf, int64_t count,
                                     DataType dtype, ReduceOp op,
                                     const std::vector<int64_t>& slice_counts,
                                     int process_set_id) {
    (void)slice_counts;
    return AllreduceBuffer(buf, count, dtype, op, process_set_id);
  }
  virtual Status AllgatherBuffer(const void* in, int64_t nbytes,
                                 int process_set_id, std::string* out,
                                 std::vector<int64_t>* nbytes_per_rank) = 0;
  virtual Status BroadcastBuffer(void* buf, int64_t nbytes, int root_rank,
                                 int process_set_id) = 0;
  virtual Status AlltoallBuffer(const void* in,
                                const std::vector<int64_t>& splits,
                                int64_t row_bytes, int process_set_id,
                                std::string* out,
                                std::vector<int64_t>* recv_splits) = 0;
  virtual Status Barrier(int process_set_id) = 0;

  int rank() const { return cfg_.rank; }
  int size() const { return cfg_.size; }
  ProcessSetTable& process_sets() { return process_sets_; }

  // Per-process-set data channel lifecycle (see SocketController): the
  // default is a no-op — LocalController's data plane is identity and
  // needs no sockets.
  virtual Status EstablishChannel(int psid) { return Status::OK(); }
  virtual void RemoveChannel(int psid) {}

  // Coordinator-side stall report: tensor -> ranks that have not announced
  // it yet (reference: stall_inspector.cc per-rank missing lists).
  virtual std::string StallReport(double older_than_s) { return ""; }

  // Blocks (bounded by the abort-propagation timeout) until this rank has
  // learned why the job is aborting — the coordinator's ABORT broadcast
  // names the culprit rank/host — and returns that reason, or "" if none
  // arrived in time.  Local controller: no peers, nothing to wait for.
  virtual std::string WaitAbortReason() { return ""; }

  // Cumulative negotiation ctrl-channel payload bytes (sent, received) by
  // this rank — the cache bit-vector fast path's measurable effect: cache
  // hits travel as 16-byte (id, handle) pairs instead of full request
  // metadata.  Local controller: zero (no sockets).
  virtual void NegotiationStats(int64_t* sent, int64_t* recv) const {
    *sent = 0;
    *recv = 0;
  }

  // Ctrl-plane traffic counters: frames and payload bytes this rank sent /
  // received on negotiation links (coordinator, leader-tree parent, and —
  // on leaders — child links).  On the coordinator this is the choke-point
  // measurement the v9 leader tree exists to shrink: messages per cycle
  // drop from O(ranks) to O(local ranks + hosts).  Local controller: zero.
  virtual void CtrlPlaneStats(int64_t* msgs_sent, int64_t* msgs_recv,
                              int64_t* bytes_sent, int64_t* bytes_recv) const {
    *msgs_sent = 0;
    *msgs_recv = 0;
    *bytes_sent = 0;
    *bytes_recv = 0;
  }

 protected:
  CoreConfig cfg_;
  ProcessSetTable process_sets_;
};

// Single-process controller: negotiation is trivial, data plane is identity.
class LocalController : public Controller {
 public:
  explicit LocalController(const CoreConfig& cfg) : Controller(cfg) {}
  Status Initialize() override;
  Status ComputeResponses(std::vector<TensorRequest>& new_requests,
                          std::vector<Response>* out) override;
  Status AllreduceBuffer(void*, int64_t, DataType, ReduceOp, int) override {
    return Status::OK();
  }
  Status AllgatherBuffer(const void* in, int64_t nbytes, int,
                         std::string* out,
                         std::vector<int64_t>* nbytes_per_rank) override {
    out->assign(static_cast<const char*>(in), nbytes);
    nbytes_per_rank->assign(1, nbytes);
    return Status::OK();
  }
  Status BroadcastBuffer(void*, int64_t, int, int) override {
    return Status::OK();
  }
  Status AlltoallBuffer(const void* in, const std::vector<int64_t>& splits,
                        int64_t row_bytes, int, std::string* out,
                        std::vector<int64_t>* recv_splits) override {
    int64_t rows = 0;
    for (auto s : splits) rows += s;
    out->assign(static_cast<const char*>(in), rows * row_bytes);
    *recv_splits = splits;
    return Status::OK();
  }
  Status Barrier(int) override { return Status::OK(); }

 private:
  // Grouped requests held until every member of the group has arrived
  // (a grouped enqueue can race the cycle drain mid-call; atomicity must
  // hold at np=1 too — group_table.cc analog).
  std::vector<std::pair<int64_t, TensorRequest>> held_;
  int64_t arrival_ = 0;
};

// Typed elementwise reduction into `acc` (used by the socket data plane).
void ReduceInto(void* acc, const void* contrib, int64_t count, DataType dtype,
                ReduceOp op);

}  // namespace hvdtpu
