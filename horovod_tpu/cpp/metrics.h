// Lock-cheap metrics registry for the native core (reference analog:
// horovod/common/timeline instrumentation points + the per-op stats the
// upstream autotuner consumes; SURVEY.md §5).  All counters and histogram
// buckets are relaxed atomics — instrumentation points are a single
// fetch_add on the hot path, and every site is guarded by MetricsOn() so
// a disabled registry costs one relaxed bool load.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <mutex>
#include <string>

namespace hvdtpu {

// Fixed power-of-two microsecond buckets.  Bucket 0 holds [0, 1us);
// bucket b (1 <= b < kBuckets-1) holds [2^(b-1), 2^b) us; the last
// bucket is the +Inf overflow.  2^26 us ≈ 67 s upper finite bound.
struct Histogram {
  static constexpr int kNumBuckets = 28;
  std::atomic<int64_t> buckets[kNumBuckets];
  std::atomic<int64_t> count{0};
  std::atomic<int64_t> sum_us{0};

  Histogram() {
    for (auto& b : buckets) b.store(0, std::memory_order_relaxed);
  }

  void ObserveUs(int64_t us) {
    if (us < 0) us = 0;
    int b = 0;
    while (b < kNumBuckets - 1 && us >= (int64_t{1} << b)) ++b;
    buckets[b].fetch_add(1, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
    sum_us.fetch_add(us, std::memory_order_relaxed);
  }

  void ObserveSeconds(double s) {
    ObserveUs(static_cast<int64_t>(s * 1e6));
  }

  // Upper bound of the bucket holding the q-quantile (conservative:
  // the true quantile is <= the returned value, within one power of 2).
  int64_t QuantileUs(double q) const;

  void Reset() {
    for (auto& b : buckets) b.store(0, std::memory_order_relaxed);
    count.store(0, std::memory_order_relaxed);
    sum_us.store(0, std::memory_order_relaxed);
  }

  // {"count":N,"sum_us":S,"p50_us":..,"p99_us":..,"buckets":[..]}
  std::string Json() const;
};

struct MetricsRegistry {
  std::atomic<bool> enabled{false};

  // Background-loop cycle occupancy: one tick = one sleep (idle) plus
  // the negotiation work that follows it (busy).
  std::atomic<int64_t> cycle_count{0};
  std::atomic<int64_t> cycle_busy_us{0};
  std::atomic<int64_t> cycle_idle_us{0};

  // Fusion efficiency: tensors and payload bytes per delivered fused
  // response.
  std::atomic<int64_t> responses_total{0};
  std::atomic<int64_t> tensors_fused_total{0};
  std::atomic<int64_t> bytes_fused_total{0};

  // Stall inspector fires (coordinator logs a missing-rank report) and
  // straggler attribution reports emitted.
  std::atomic<int64_t> stall_warnings_total{0};
  std::atomic<int64_t> straggler_reports_total{0};

  // Failure plane: ABORT frames sent/observed and fault-injection rule
  // fires (fault_injection.h).
  std::atomic<int64_t> aborts_total{0};
  std::atomic<int64_t> faults_injected_total{0};

  // Fleet-autopilot decisions recorded on the coordinator's policy
  // channel (evict / scale / readmit), regardless of driver outcome.
  std::atomic<int64_t> autopilot_decisions_total{0};

  // Fleet telemetry plane (protocol v11; fleet_telemetry.h): child/leader
  // sketches merged into the coordinator's fleet view, and anomalies the
  // sentinel emitted.
  std::atomic<int64_t> fleet_sketches_merged_total{0};
  std::atomic<int64_t> sentinel_anomalies_total{0};

  // Device-plane (in-jit / eager-XLA) collective payload accounting,
  // reported by the Python side per quantized dispatch: raw fp32 ring
  // bytes the collective WOULD have moved vs the int8 block-scaled bytes
  // it did move.  Uncompressed device collectives report nothing (XLA
  // moves those bytes without telling us), so the pair measures the
  // codec's ratio, not total device traffic.
  std::atomic<int64_t> device_raw_bytes{0};
  std::atomic<int64_t> device_encoded_bytes{0};

  // GSPMD-plane (compiler-inserted) collective accounting, reported by
  // the Python-side HLO inspector once per inspected trace
  // (ops/hlo_inspect.py): the number of collectives XLA emitted, the
  // analytic raw payload bytes they cover, and the analytic wire bytes a
  // ring schedule moves for them.  A compiled program cannot count at
  // run time, so — like the device_* pair above — these tick per trace,
  // not per step.
  std::atomic<int64_t> gspmd_collectives_total{0};
  std::atomic<int64_t> gspmd_raw_bytes{0};
  std::atomic<int64_t> gspmd_wire_bytes{0};
  std::atomic<int64_t> gspmd_traces_total{0};

  // Control-plane traffic (protocol v9): negotiation frames and payload
  // bytes moved on this rank's ctrl links.  On the coordinator,
  // ctrl_msgs_recv per cycle is the leader-tree acceptance metric —
  // O(ranks) flat vs O(local ranks + hosts) with the tree engaged.
  std::atomic<int64_t> ctrl_msgs_sent{0};
  std::atomic<int64_t> ctrl_msgs_recv{0};
  std::atomic<int64_t> ctrl_bytes_sent{0};
  std::atomic<int64_t> ctrl_bytes_recv{0};

  // Elastic state-migration plane (docs/elastic.md "Zero-downtime
  // migration"): replication refreshes, shard handoffs and their payload
  // bytes, and checkpoint fallbacks taken when peer shards could not
  // cover a loss.
  std::atomic<int64_t> migrate_events_total{0};
  std::atomic<int64_t> migrate_bytes_total{0};
  std::atomic<int64_t> migrate_fallbacks_total{0};

  // Gauges (last-written value, not monotone): the elastic generation
  // this rank most recently joined, so dashboards can correlate
  // migrate/abort counters with re-formations.
  std::atomic<int64_t> elastic_generation{0};
  // Goodput as parts-per-million of fleet wall time spent in the ring
  // phase (fleet_telemetry.cc recomputes it per tick; Prometheus renders
  // it as the hvd_goodput_ratio fraction).
  std::atomic<int64_t> goodput_ratio_ppm{0};

  // Latency distributions.
  Histogram negotiation_wait_us;  // enqueue -> fused response mapped back
  Histogram ring_hop_us;          // one pipelined chunk exchange step
  Histogram shm_fence_us;         // shm/hier dissemination-barrier fences
  Histogram abort_propagation_us;  // coordinator ABORT send -> worker observe
  Histogram step_time_us;          // completed causal-step wall time

  // Per-tenant (process-set) fused-response accounting.  Tenants are a
  // cold, small map (one entry per registered process set), so a plain
  // mutex is fine: the record site runs once per delivered response, not
  // per ring hop, and only when MetricsOn().
  struct TenantStats {
    int64_t responses = 0;
    int64_t tensors = 0;
    int64_t bytes = 0;
    Histogram negotiation_wait_us;
  };

  void RecordTenant(int psid, int64_t tensors, int64_t bytes);
  void RecordTenantWaitUs(int psid, int64_t wait_us);
  // Visit each tenant's negotiation-wait histogram under the tenants
  // lock (the fleet-sketch capture path; Histogram is non-copyable).
  void ForEachTenantWait(
      const std::function<void(int, const Histogram&)>& fn) const;

  void Reset();

  // Full registry as one JSON object.  extra_json, when non-empty, is a
  // pre-rendered fragment (e.g. the coordinator's cluster view) spliced
  // into the object as additional top-level members; it must start with
  // a comma-free `"key":...` sequence.
  std::string DumpJson(int rank, const std::string& extra_json) const;

 private:
  mutable std::mutex tenants_mu_;
  std::map<int, TenantStats> tenants_;
};

MetricsRegistry& GlobalMetrics();

inline bool MetricsOn() {
  return GlobalMetrics().enabled.load(std::memory_order_relaxed);
}

// Elastic-migration phase codes, carried in the type-14 flight event's
// `a` field as phase << 8 | (source_rank + 1).  Keep in sync with
// horovod_tpu/elastic/migrate.py PHASE_* and tools/postmortem.py
// _MIGRATE_PHASES.
enum MigratePhase : int {
  kMigrateReplicate = 1,   // periodic shard refresh onto ring neighbors
  kMigrateManifest = 2,    // post-reformation shard-manifest allgather
  kMigrateTransfer = 3,    // targeted shard transfers to claimants
  kMigrateReassemble = 4,  // per-rank state reassembly from shards
  kMigrateFallback = 5,    // replication could not cover; checkpoint path
};

// Shared note point for the migration plane, callable from the extern-C
// ABI and the in-process selftests alike: bumps the migrate counters
// (under MetricsOn) and records a type-14 flight event (under FlightOn).
// `source_rank` < 0 means "no specific peer".
void NoteMigration(int phase, int64_t bytes, int source_rank);

// Shared note point for the compiled-HLO introspection layer, callable
// from the extern-C ABI before or without hvd_init (the registry is
// process-global): bumps the gspmd_* counters unconditionally (like the
// device_plane byte pair — data_plane_stats() serves them with the
// metrics plane off) and records a type-16 flight event carrying the op
// count and the analytic wire bytes (under FlightOn).
void NoteHloInspect(int64_t ops, int64_t raw_bytes, int64_t wire_bytes);

// JSON string-body escaping shared by the timeline writer, the metrics
// dump, and the error-string paths: quotes, backslashes, and all control
// characters (< 0x20) become legal JSON escapes, so arbitrary tensor
// names cannot corrupt a trace or dump.
std::string JsonEscape(const std::string& s);

}  // namespace hvdtpu
