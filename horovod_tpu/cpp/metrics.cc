#include "metrics.h"

#include <cstdio>
#include <sstream>

#include "flight_recorder.h"

namespace hvdtpu {

int64_t Histogram::QuantileUs(double q) const {
  int64_t n = count.load(std::memory_order_relaxed);
  if (n <= 0) return 0;
  int64_t target = static_cast<int64_t>(q * n);
  if (target < 1) target = 1;
  if (target > n) target = n;
  int64_t cum = 0;
  for (int b = 0; b < kNumBuckets; ++b) {
    cum += buckets[b].load(std::memory_order_relaxed);
    if (cum >= target) return int64_t{1} << b;
  }
  return int64_t{1} << (kNumBuckets - 1);
}

std::string Histogram::Json() const {
  std::ostringstream os;
  os << "{\"count\":" << count.load(std::memory_order_relaxed)
     << ",\"sum_us\":" << sum_us.load(std::memory_order_relaxed)
     << ",\"p50_us\":" << QuantileUs(0.5)
     << ",\"p99_us\":" << QuantileUs(0.99) << ",\"buckets\":[";
  for (int b = 0; b < kNumBuckets; ++b) {
    if (b) os << ',';
    os << buckets[b].load(std::memory_order_relaxed);
  }
  os << "]}";
  return os.str();
}

void MetricsRegistry::RecordTenant(int psid, int64_t tensors, int64_t bytes) {
  std::lock_guard<std::mutex> l(tenants_mu_);
  TenantStats& t = tenants_[psid];
  t.responses += 1;
  t.tensors += tensors;
  t.bytes += bytes;
}

void MetricsRegistry::RecordTenantWaitUs(int psid, int64_t wait_us) {
  std::lock_guard<std::mutex> l(tenants_mu_);
  tenants_[psid].negotiation_wait_us.ObserveUs(wait_us);
}

void MetricsRegistry::ForEachTenantWait(
    const std::function<void(int, const Histogram&)>& fn) const {
  std::lock_guard<std::mutex> l(tenants_mu_);
  for (const auto& kv : tenants_) fn(kv.first, kv.second.negotiation_wait_us);
}

void MetricsRegistry::Reset() {
  cycle_count.store(0, std::memory_order_relaxed);
  cycle_busy_us.store(0, std::memory_order_relaxed);
  cycle_idle_us.store(0, std::memory_order_relaxed);
  responses_total.store(0, std::memory_order_relaxed);
  tensors_fused_total.store(0, std::memory_order_relaxed);
  bytes_fused_total.store(0, std::memory_order_relaxed);
  stall_warnings_total.store(0, std::memory_order_relaxed);
  straggler_reports_total.store(0, std::memory_order_relaxed);
  aborts_total.store(0, std::memory_order_relaxed);
  faults_injected_total.store(0, std::memory_order_relaxed);
  autopilot_decisions_total.store(0, std::memory_order_relaxed);
  fleet_sketches_merged_total.store(0, std::memory_order_relaxed);
  sentinel_anomalies_total.store(0, std::memory_order_relaxed);
  device_raw_bytes.store(0, std::memory_order_relaxed);
  device_encoded_bytes.store(0, std::memory_order_relaxed);
  gspmd_collectives_total.store(0, std::memory_order_relaxed);
  gspmd_raw_bytes.store(0, std::memory_order_relaxed);
  gspmd_wire_bytes.store(0, std::memory_order_relaxed);
  gspmd_traces_total.store(0, std::memory_order_relaxed);
  ctrl_msgs_sent.store(0, std::memory_order_relaxed);
  ctrl_msgs_recv.store(0, std::memory_order_relaxed);
  ctrl_bytes_sent.store(0, std::memory_order_relaxed);
  ctrl_bytes_recv.store(0, std::memory_order_relaxed);
  migrate_events_total.store(0, std::memory_order_relaxed);
  migrate_bytes_total.store(0, std::memory_order_relaxed);
  migrate_fallbacks_total.store(0, std::memory_order_relaxed);
  elastic_generation.store(0, std::memory_order_relaxed);
  goodput_ratio_ppm.store(0, std::memory_order_relaxed);
  negotiation_wait_us.Reset();
  ring_hop_us.Reset();
  shm_fence_us.Reset();
  abort_propagation_us.Reset();
  step_time_us.Reset();
  {
    std::lock_guard<std::mutex> l(tenants_mu_);
    tenants_.clear();
  }
}

std::string MetricsRegistry::DumpJson(int rank,
                                      const std::string& extra_json) const {
  std::ostringstream os;
  os << "{\"enabled\":"
     << (enabled.load(std::memory_order_relaxed) ? "true" : "false")
     << ",\"rank\":" << rank << ",\"counters\":{"
     << "\"cycle_count\":" << cycle_count.load(std::memory_order_relaxed)
     << ",\"cycle_busy_us\":" << cycle_busy_us.load(std::memory_order_relaxed)
     << ",\"cycle_idle_us\":" << cycle_idle_us.load(std::memory_order_relaxed)
     << ",\"responses_total\":"
     << responses_total.load(std::memory_order_relaxed)
     << ",\"tensors_fused_total\":"
     << tensors_fused_total.load(std::memory_order_relaxed)
     << ",\"bytes_fused_total\":"
     << bytes_fused_total.load(std::memory_order_relaxed)
     << ",\"stall_warnings_total\":"
     << stall_warnings_total.load(std::memory_order_relaxed)
     << ",\"straggler_reports_total\":"
     << straggler_reports_total.load(std::memory_order_relaxed)
     << ",\"aborts_total\":" << aborts_total.load(std::memory_order_relaxed)
     << ",\"faults_injected_total\":"
     << faults_injected_total.load(std::memory_order_relaxed)
     << ",\"autopilot_decisions_total\":"
     << autopilot_decisions_total.load(std::memory_order_relaxed)
     << ",\"fleet_sketches_merged_total\":"
     << fleet_sketches_merged_total.load(std::memory_order_relaxed)
     << ",\"sentinel_anomalies_total\":"
     << sentinel_anomalies_total.load(std::memory_order_relaxed)
     << ",\"device_raw_bytes\":"
     << device_raw_bytes.load(std::memory_order_relaxed)
     << ",\"device_encoded_bytes\":"
     << device_encoded_bytes.load(std::memory_order_relaxed)
     << ",\"gspmd_collectives_total\":"
     << gspmd_collectives_total.load(std::memory_order_relaxed)
     << ",\"gspmd_raw_bytes\":"
     << gspmd_raw_bytes.load(std::memory_order_relaxed)
     << ",\"gspmd_wire_bytes\":"
     << gspmd_wire_bytes.load(std::memory_order_relaxed)
     << ",\"gspmd_traces_total\":"
     << gspmd_traces_total.load(std::memory_order_relaxed)
     << ",\"ctrl_msgs_sent\":"
     << ctrl_msgs_sent.load(std::memory_order_relaxed)
     << ",\"ctrl_msgs_recv\":"
     << ctrl_msgs_recv.load(std::memory_order_relaxed)
     << ",\"ctrl_bytes_sent\":"
     << ctrl_bytes_sent.load(std::memory_order_relaxed)
     << ",\"ctrl_bytes_recv\":"
     << ctrl_bytes_recv.load(std::memory_order_relaxed)
     << ",\"migrate_events_total\":"
     << migrate_events_total.load(std::memory_order_relaxed)
     << ",\"migrate_bytes_total\":"
     << migrate_bytes_total.load(std::memory_order_relaxed)
     << ",\"migrate_fallbacks_total\":"
     << migrate_fallbacks_total.load(std::memory_order_relaxed)
     << "},\"gauges\":{"
     << "\"elastic_generation\":"
     << elastic_generation.load(std::memory_order_relaxed)
     << ",\"goodput_ratio_ppm\":"
     << goodput_ratio_ppm.load(std::memory_order_relaxed)
     << "},\"histograms\":{"
     << "\"negotiation_wait_us\":" << negotiation_wait_us.Json()
     << ",\"ring_hop_us\":" << ring_hop_us.Json()
     << ",\"shm_fence_us\":" << shm_fence_us.Json()
     << ",\"abort_propagation_us\":" << abort_propagation_us.Json()
     << ",\"step_time_us\":" << step_time_us.Json() << "}";
  {
    // Per-tenant (process-set) accounting, keyed by psid.  Rendered even
    // when empty so consumers need no presence check.
    std::lock_guard<std::mutex> l(tenants_mu_);
    os << ",\"tenants\":{";
    bool first = true;
    for (const auto& kv : tenants_) {
      if (!first) os << ',';
      first = false;
      os << "\"" << kv.first << "\":{\"responses\":" << kv.second.responses
         << ",\"tensors\":" << kv.second.tensors
         << ",\"bytes\":" << kv.second.bytes
         << ",\"negotiation_wait_us\":" << kv.second.negotiation_wait_us.Json()
         << "}";
    }
    os << "}";
  }
  if (!extra_json.empty()) os << ',' << extra_json;
  os << "}";
  return os.str();
}

MetricsRegistry& GlobalMetrics() {
  static MetricsRegistry registry;
  return registry;
}

void NoteMigration(int phase, int64_t bytes, int source_rank) {
  if (MetricsOn()) {
    MetricsRegistry& m = GlobalMetrics();
    m.migrate_events_total.fetch_add(1, std::memory_order_relaxed);
    if (bytes > 0)
      m.migrate_bytes_total.fetch_add(bytes, std::memory_order_relaxed);
    if (phase == kMigrateFallback)
      m.migrate_fallbacks_total.fetch_add(1, std::memory_order_relaxed);
  }
  if (FlightOn()) {
    // a = phase << 8 | (source_rank + 1); 0 in the low byte means "no
    // specific peer".  Ranks past 254 saturate rather than bleed into
    // the phase bits.
    int src = source_rank < 0 ? 0 : (source_rank >= 254 ? 255
                                                        : source_rank + 1);
    FlightRecord(kFlightMigrate, (phase << 8) | src, bytes);
  }
}

void NoteHloInspect(int64_t ops, int64_t raw_bytes, int64_t wire_bytes) {
  MetricsRegistry& m = GlobalMetrics();
  m.gspmd_traces_total.fetch_add(1, std::memory_order_relaxed);
  if (ops > 0)
    m.gspmd_collectives_total.fetch_add(ops, std::memory_order_relaxed);
  if (raw_bytes > 0)
    m.gspmd_raw_bytes.fetch_add(raw_bytes, std::memory_order_relaxed);
  if (wire_bytes > 0)
    m.gspmd_wire_bytes.fetch_add(wire_bytes, std::memory_order_relaxed);
  if (FlightOn()) {
    // a = op count (an inspected trace holds a handful of collectives,
    // far under 2^31), b = the trace's analytic wire bytes.
    int32_t a = ops > INT32_MAX ? INT32_MAX : static_cast<int32_t>(ops);
    FlightRecord(kFlightHloInspect, a, wire_bytes);
  }
}

std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    unsigned char u = static_cast<unsigned char>(c);
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (u < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", u);
          out += buf;
        } else {
          out.push_back(c);
        }
    }
  }
  return out;
}

}  // namespace hvdtpu
