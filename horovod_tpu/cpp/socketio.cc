#include "socketio.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdlib>
#include <thread>

#include "logging.h"

namespace hvdtpu {

double MonotonicSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---- request/response serialization ---------------------------------------

void SerializeRequest(const TensorRequest& r, Writer* w) {
  // handle rides the wire so tombstone error deliveries can echo the owed
  // rank's own submission id back to it (core_api matches it against the
  // outstanding entry to drop stale deliveries after a resubmission).
  w->PutI64(r.handle);
  w->PutString(r.name);
  w->PutI32(static_cast<int32_t>(r.op));
  w->PutI32(static_cast<int32_t>(r.dtype));
  w->PutI32(static_cast<int32_t>(r.reduce_op));
  w->PutI64(r.nbytes);
  w->PutI64Vec(r.shape);
  w->PutI32(r.process_set_id);
  w->PutI32(r.root_rank);
  w->PutF64(r.prescale);
  w->PutF64(r.postscale);
  w->PutI64Vec(r.splits);
  w->PutI32(r.device);
  w->PutString(r.group_key);
  w->PutI32(r.group_size);
}

TensorRequest DeserializeRequest(Reader* r) {
  TensorRequest t;
  t.handle = r->GetI64();
  t.name = r->GetString();
  t.op = static_cast<OpType>(r->GetI32());
  t.dtype = static_cast<DataType>(r->GetI32());
  t.reduce_op = static_cast<ReduceOp>(r->GetI32());
  t.nbytes = r->GetI64();
  t.shape = r->GetI64Vec();
  t.process_set_id = r->GetI32();
  t.root_rank = r->GetI32();
  t.prescale = r->GetF64();
  t.postscale = r->GetF64();
  t.splits = r->GetI64Vec();
  t.device = r->GetI32();
  t.group_key = r->GetString();
  t.group_size = r->GetI32();
  return t;
}

void SerializeResponse(const Response& r, Writer* w) {
  w->PutI32(static_cast<int32_t>(r.op));
  w->PutI32(static_cast<int32_t>(r.dtype));
  w->PutI32(r.process_set_id);
  w->PutString(r.error);
  w->PutU8(r.cache_hit ? 1 : 0);
  w->PutU8(r.hier ? 1 : 0);
  w->PutU8(static_cast<uint8_t>(r.wire_comp));
  w->PutI64(r.seq);
  w->PutI32(r.last_joined);
  w->PutI32(r.target_rank);
  w->PutI32(static_cast<int32_t>(r.metas.size()));
  for (const auto& m : r.metas) SerializeRequest(m, w);
}

Response DeserializeResponse(Reader* r) {
  Response resp;
  resp.op = static_cast<OpType>(r->GetI32());
  resp.dtype = static_cast<DataType>(r->GetI32());
  resp.process_set_id = r->GetI32();
  resp.error = r->GetString();
  resp.cache_hit = r->GetU8() != 0;
  resp.hier = r->GetU8() != 0;
  resp.wire_comp = r->GetU8();
  resp.seq = r->GetI64();
  resp.last_joined = r->GetI32();
  resp.target_rank = r->GetI32();
  int32_t n = r->GetI32();
  resp.metas.reserve(n);
  for (int32_t i = 0; i < n; ++i) {
    resp.metas.push_back(DeserializeRequest(r));
    resp.names.push_back(resp.metas.back().name);
  }
  return resp;
}

// ---- Socket ---------------------------------------------------------------

namespace {

// HOROVOD_SOCKET_BUFFER_BYTES: kernel send/recv buffer size for data-plane
// sockets (0 = leave the kernel default).  Oversized buffers hurt on
// cache-constrained hosts (more cold in-flight bytes), so this stays a
// deliberate knob rather than a hardcoded maximum.
void TuneDataSocketBuffers(int fd) {
  static const int bufsz = [] {
    if (const char* env = ::getenv("HOROVOD_SOCKET_BUFFER_BYTES")) {
      char* end = nullptr;
      long long v = std::strtoll(env, &end, 10);
      if (end && *end == '\0' && v >= 0) {
        // Clamp: setsockopt takes int, and the kernel caps at
        // net.core.{w,r}mem_max anyway.
        return static_cast<int>(std::min<long long>(v, 1 << 30));
      }
    }
    return 0;
  }();
  if (bufsz > 0) {
    ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
    ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
  }
}

}  // namespace

Socket::~Socket() { Close(); }

Socket& Socket::operator=(Socket&& o) noexcept {
  if (this != &o) {
    Close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

void Socket::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Socket::Connect(const std::string& addr, int port, double timeout_s) {
  // Rendezvous addresses may be hostnames (TPU-VM pod metadata hands out
  // names, not IPs); resolution is retried inside the deadline loop because
  // DNS may come up after the worker does, exactly like the listener may.
  sockaddr_in resolved{};
  resolved.sin_family = AF_INET;
  resolved.sin_port = htons(static_cast<uint16_t>(port));
  bool have_addr = ::inet_pton(AF_INET, addr.c_str(), &resolved.sin_addr) == 1;
  double deadline = MonotonicSeconds() + timeout_s;
  while (MonotonicSeconds() < deadline) {
    if (!have_addr) {
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (::getaddrinfo(addr.c_str(), nullptr, &hints, &res) == 0 && res) {
        resolved.sin_addr =
            reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
        ::freeaddrinfo(res);
        have_addr = true;
      } else {
        HVD_LOG(DEBUG) << "cannot resolve host '" << addr << "' (will retry)";
        std::this_thread::sleep_for(std::chrono::milliseconds(200));
        continue;
      }
    }
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return false;
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    TuneDataSocketBuffers(fd);
    sockaddr_in sa = resolved;
    if (::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) == 0) {
      fd_ = fd;
      return true;
    }
    ::close(fd);
    // Rendezvous race: the coordinator may not be listening yet.
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  return false;
}

bool Socket::ConnectOnce(const std::string& addr, int port) {
  last_errno_ = 0;
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, addr.c_str(), &sa.sin_addr) != 1) {
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(addr.c_str(), nullptr, &hints, &res) != 0 || !res) {
      // Name resolution may come up after the worker does, exactly like
      // the listener: report it as retryable.
      last_errno_ = EAGAIN;
      return false;
    }
    sa.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    ::freeaddrinfo(res);
  }
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    last_errno_ = errno;
    return false;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  TuneDataSocketBuffers(fd);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    last_errno_ = errno;
    ::close(fd);
    return false;
  }
  fd_ = fd;
  return true;
}

bool ConnectErrnoRetryable(int err) {
  switch (err) {
    case ECONNREFUSED:
    case ETIMEDOUT:
    case EHOSTUNREACH:
    case ENETUNREACH:
    case EAGAIN:
    case EINTR:
      return true;
    default:
      return false;
  }
}

bool Socket::SendAll(const void* p, size_t n) {
  const char* c = static_cast<const char*>(p);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd_, c + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    sent += static_cast<size_t>(r);
  }
  return true;
}

bool Socket::RecvAll(void* p, size_t n) {
  char* c = static_cast<char*>(p);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd_, c + got, n - got, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    got += static_cast<size_t>(r);
  }
  return true;
}

bool Socket::SendFrame(const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  if (!SendAll(&len, 4)) return false;
  return payload.empty() || SendAll(payload.data(), payload.size());
}

bool Socket::RecvFrame(std::string* payload) {
  uint32_t len = 0;
  if (!RecvAll(&len, 4)) return false;
  payload->resize(len);
  if (len == 0) return true;
  return RecvAll(&(*payload)[0], len);
}

std::string Socket::PeerAddr() const {
  sockaddr_in sa{};
  socklen_t slen = sizeof(sa);
  if (::getpeername(fd_, reinterpret_cast<sockaddr*>(&sa), &slen) != 0) {
    return "";
  }
  char buf[INET_ADDRSTRLEN] = {0};
  if (!::inet_ntop(AF_INET, &sa.sin_addr, buf, sizeof(buf))) return "";
  return buf;
}

void Socket::SetRecvTimeout(double seconds) {
  timeval tv{};
  tv.tv_sec = static_cast<time_t>(seconds);
  tv.tv_usec = static_cast<suseconds_t>((seconds - tv.tv_sec) * 1e6);
  ::setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
}

namespace {

bool SetNonblocking(int fd, bool on) {
  int flags = ::fcntl(fd, F_GETFL, 0);
  if (flags < 0) return false;
  flags = on ? (flags | O_NONBLOCK) : (flags & ~O_NONBLOCK);
  return ::fcntl(fd, F_SETFL, flags) == 0;
}

}  // namespace

bool DuplexExchange(Socket& send_sock, const std::string& out,
                    Socket& recv_sock, std::string* in,
                    const std::function<bool()>& cancelled) {
  const int sfd = send_sock.fd();
  const int rfd = recv_sock.fd();
  if (sfd < 0 || rfd < 0) return false;

  // Outgoing: 4-byte length prefix + payload (matches Send/RecvFrame).
  std::string sbuf;
  sbuf.reserve(4 + out.size());
  uint32_t slen = static_cast<uint32_t>(out.size());
  sbuf.append(reinterpret_cast<const char*>(&slen), 4);
  sbuf += out;
  size_t sent = 0;

  // Incoming state machine: length prefix, then payload.
  uint32_t rlen = 0;
  size_t rlen_got = 0;
  size_t rgot = 0;
  bool rlen_done = false;
  in->clear();

  if (!SetNonblocking(sfd, true)) return false;
  if (rfd != sfd && !SetNonblocking(rfd, true)) {
    SetNonblocking(sfd, false);
    return false;
  }
  bool ok = true;
  while (ok && (sent < sbuf.size() || !rlen_done || rgot < rlen)) {
    if (cancelled && cancelled()) {
      ok = false;
      break;
    }
    pollfd pfds[2];
    int n = 0;
    const bool want_send = sent < sbuf.size();
    const bool want_recv = !rlen_done || rgot < rlen;
    if (sfd == rfd) {
      pfds[n++] = pollfd{
          sfd,
          static_cast<short>((want_send ? POLLOUT : 0) |
                             (want_recv ? POLLIN : 0)),
          0};
    } else {
      if (want_send) pfds[n++] = pollfd{sfd, POLLOUT, 0};
      if (want_recv) pfds[n++] = pollfd{rfd, POLLIN, 0};
    }
    int rc = ::poll(pfds, n, 200);  // short: re-check cancellation
    if (rc < 0) {
      if (errno == EINTR) continue;
      ok = false;
      break;
    }
    if (rc == 0) continue;  // peer may still be computing toward this step
    for (int i = 0; i < n && ok; ++i) {
      if (pfds[i].revents & POLLNVAL) {
        ok = false;
        break;
      }
      // POLLERR/POLLHUP with a pending send: attempt the send so the socket
      // error surfaces instead of spinning on a dead peer.
      if ((pfds[i].revents & (POLLOUT | POLLERR | POLLHUP)) && want_send &&
          pfds[i].fd == sfd) {
        ssize_t w = ::send(pfds[i].fd, sbuf.data() + sent, sbuf.size() - sent,
                           MSG_NOSIGNAL);
        if (w > 0) {
          sent += static_cast<size_t>(w);
        } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                   errno != EINTR) {
          ok = false;
          break;
        }
      }
      if ((pfds[i].revents & (POLLIN | POLLHUP | POLLERR)) && want_recv &&
          pfds[i].fd == rfd) {
        if (!rlen_done) {
          ssize_t r = ::recv(pfds[i].fd,
                             reinterpret_cast<char*>(&rlen) + rlen_got,
                             4 - rlen_got, 0);
          if (r > 0) {
            rlen_got += static_cast<size_t>(r);
            if (rlen_got == 4) {
              rlen_done = true;
              in->resize(rlen);
            }
          } else if (r == 0 || (errno != EAGAIN && errno != EWOULDBLOCK &&
                                errno != EINTR)) {
            ok = false;
            break;
          }
        } else if (rgot < rlen) {
          ssize_t r = ::recv(pfds[i].fd, &(*in)[rgot], rlen - rgot, 0);
          if (r > 0) {
            rgot += static_cast<size_t>(r);
          } else if (r == 0 || (errno != EAGAIN && errno != EWOULDBLOCK &&
                                errno != EINTR)) {
            ok = false;
            break;
          }
        }
      }
    }
  }
  SetNonblocking(sfd, false);
  if (rfd != sfd) SetNonblocking(rfd, false);
  return ok;
}

bool ChunkedDuplexExchange(
    Socket& send_sock, const char* send_base, int64_t send_len,
    Socket& recv_sock, int64_t recv_total, int64_t chunk_bytes,
    const std::string& header, char* recv_dest,
    const std::function<void(int64_t off, const char* data, int64_t len)>&
        on_chunk,
    const std::function<bool()>& cancelled, ChunkExchangeError* err) {
  const int sfd = send_sock.fd();
  const int rfd = recv_sock.fd();
  if (err) *err = ChunkExchangeError{ChunkExchangeError::kTransport, "", 0};
  if (sfd < 0 || rfd < 0) return false;
  if (chunk_bytes <= 0) chunk_bytes = 1 << 19;
  const size_t hdr_n = header.size();

  // Send state: per chunk, a small prefix+header scratch, then payload
  // straight out of the caller's buffer (no segment-sized copies).
  std::string shdr;
  size_t shdr_sent = 0;
  int64_t schunk_start = 0;  // payload offset of the current chunk
  int64_t schunk_len = 0;
  int64_t schunk_sent = 0;
  bool schunk_active = false;
  auto arm_send_chunk = [&](int64_t start) {
    if (start >= send_len) {
      schunk_active = false;
      return;
    }
    schunk_start = start;
    schunk_len = std::min<int64_t>(chunk_bytes, send_len - start);
    uint32_t flen = static_cast<uint32_t>(hdr_n + schunk_len);
    shdr.assign(reinterpret_cast<const char*>(&flen), 4);
    shdr += header;
    shdr_sent = 0;
    schunk_sent = 0;
    schunk_active = true;
  };
  arm_send_chunk(0);

  // Recv state machine: frame length prefix -> header -> payload.  The
  // payload length comes from the peer's framing, so the two ends may run
  // different chunk sizes.
  int64_t recv_done = 0;
  uint32_t rlen = 0;
  size_t rlen_got = 0;
  std::string rhdr(hdr_n, '\0');
  size_t rhdr_got = 0;
  int64_t rchunk_len = 0;
  int64_t rchunk_got = 0;
  bool rframe_known = false;  // prefix + header fully read
  std::vector<char> scratch;

  if (!SetNonblocking(sfd, true)) return false;
  if (rfd != sfd && !SetNonblocking(rfd, true)) {
    SetNonblocking(sfd, false);
    return false;
  }
  bool ok = true;
  while (ok && (schunk_active || recv_done < recv_total)) {
    if (cancelled && cancelled()) {
      ok = false;
      break;
    }
    pollfd pfds[2];
    int n = 0;
    const bool want_send = schunk_active;
    const bool want_recv = recv_done < recv_total;
    if (sfd == rfd) {
      pfds[n++] = pollfd{
          sfd,
          static_cast<short>((want_send ? POLLOUT : 0) |
                             (want_recv ? POLLIN : 0)),
          0};
    } else {
      if (want_send) pfds[n++] = pollfd{sfd, POLLOUT, 0};
      if (want_recv) pfds[n++] = pollfd{rfd, POLLIN, 0};
    }
    int rc = ::poll(pfds, n, 200);  // short: re-check cancellation
    if (rc < 0) {
      if (errno == EINTR) continue;
      ok = false;
      break;
    }
    if (rc == 0) continue;  // peer may still be computing toward this step
    for (int i = 0; i < n && ok; ++i) {
      if (pfds[i].revents & POLLNVAL) {
        ok = false;
        break;
      }
      if ((pfds[i].revents & (POLLOUT | POLLERR | POLLHUP)) && want_send &&
          pfds[i].fd == sfd && schunk_active) {
        if (shdr_sent < shdr.size()) {
          ssize_t w = ::send(pfds[i].fd, shdr.data() + shdr_sent,
                             shdr.size() - shdr_sent, MSG_NOSIGNAL);
          if (w > 0) {
            shdr_sent += static_cast<size_t>(w);
          } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                     errno != EINTR) {
            ok = false;
            break;
          }
        }
        if (shdr_sent == shdr.size() && schunk_sent < schunk_len) {
          ssize_t w = ::send(
              pfds[i].fd, send_base + schunk_start + schunk_sent,
              static_cast<size_t>(schunk_len - schunk_sent), MSG_NOSIGNAL);
          if (w > 0) {
            schunk_sent += w;
          } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                     errno != EINTR) {
            ok = false;
            break;
          }
        }
        if (shdr_sent == shdr.size() && schunk_sent == schunk_len) {
          arm_send_chunk(schunk_start + schunk_len);
        }
      }
      if ((pfds[i].revents & (POLLIN | POLLHUP | POLLERR)) && want_recv &&
          pfds[i].fd == rfd) {
        // Drain the length prefix AND the header within one wakeup (they
        // are tiny and nearly always arrive in the same segment) — an
        // if/else ladder here would cost an extra poll round-trip per
        // chunk frame.  1 = complete, 0 = would block, -1 = error/EOF.
        auto drain = [&](char* dst, size_t want, size_t& got) -> int {
          while (got < want) {
            ssize_t r = ::recv(pfds[i].fd, dst + got, want - got, 0);
            if (r > 0) {
              got += static_cast<size_t>(r);
              continue;
            }
            if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                          errno == EINTR)) {
              return 0;
            }
            return -1;
          }
          return 1;
        };
        int pr = 1;
        if (rlen_got < 4) {
          pr = drain(reinterpret_cast<char*>(&rlen), 4, rlen_got);
        }
        if (pr > 0 && rhdr_got < hdr_n) {
          pr = drain(&rhdr[0], hdr_n, rhdr_got);
        }
        if (pr < 0) {
          ok = false;
          break;
        }
        if (!rframe_known && rlen_got == 4 && rhdr_got == hdr_n) {
          if (rhdr != header) {
            if (err) {
              err->kind = ChunkExchangeError::kHeaderMismatch;
              err->got_header = rhdr;
            }
            ok = false;
            break;
          }
          rchunk_len = static_cast<int64_t>(rlen) -
                       static_cast<int64_t>(hdr_n);
          if (rchunk_len <= 0 || rchunk_len > recv_total - recv_done) {
            if (err) {
              err->kind = ChunkExchangeError::kBadLength;
              err->bad_length = rchunk_len;
            }
            ok = false;
            break;
          }
          rchunk_got = 0;
          rframe_known = true;
          if (!recv_dest &&
              static_cast<int64_t>(scratch.size()) < rchunk_len) {
            scratch.resize(static_cast<size_t>(rchunk_len));
          }
        }
        if (rframe_known && rchunk_got < rchunk_len) {
          char* dest = recv_dest ? recv_dest + recv_done + rchunk_got
                                 : scratch.data() + rchunk_got;
          ssize_t r = ::recv(pfds[i].fd, dest,
                             static_cast<size_t>(rchunk_len - rchunk_got),
                             0);
          if (r > 0) {
            rchunk_got += r;
          } else if (r == 0 || (errno != EAGAIN && errno != EWOULDBLOCK &&
                                errno != EINTR)) {
            ok = false;
            break;
          }
        }
        if (rframe_known && rchunk_got == rchunk_len) {
          // Chunk complete: consume it now, overlapping the reduce with
          // whatever the kernel keeps receiving into socket buffers.
          if (on_chunk) {
            on_chunk(recv_done,
                     recv_dest ? recv_dest + recv_done : scratch.data(),
                     rchunk_len);
          }
          recv_done += rchunk_len;
          rlen_got = 0;
          rhdr_got = 0;
          rframe_known = false;
        }
      }
    }
  }
  SetNonblocking(sfd, false);
  if (rfd != sfd) SetNonblocking(rfd, false);
  if (ok && err) err->kind = ChunkExchangeError::kNone;
  return ok;
}

// ---- Listener -------------------------------------------------------------

Listener::~Listener() { Close(); }

void Listener::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Listener::Listen(const std::string& addr, int port) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) return false;
  int one = 1;
  ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, addr.c_str(), &sa.sin_addr) != 1) return false;
  if (::bind(fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    HVD_LOG(ERROR) << "bind(" << addr << ":" << port << ") failed: " << errno;
    return false;
  }
  socklen_t slen = sizeof(sa);
  ::getsockname(fd_, reinterpret_cast<sockaddr*>(&sa), &slen);
  port_ = ntohs(sa.sin_port);
  // Non-blocking listener: Accept's poll() provides the wait, and a losing
  // racer among concurrent acceptor threads (the sharded rendezvous) gets
  // EAGAIN back instead of blocking inside ::accept with no connection
  // left.  Backlog 512: an np=512 rendezvous herd SYNs all at once; the
  // worker-side exponential backoff absorbs whatever still overflows.
  int flags = ::fcntl(fd_, F_GETFL, 0);
  if (flags >= 0) ::fcntl(fd_, F_SETFL, flags | O_NONBLOCK);
  return ::listen(fd_, 512) == 0;
}

Socket Listener::Accept(double timeout_s) {
  pollfd pfd{fd_, POLLIN, 0};
  int rc = ::poll(&pfd, 1, static_cast<int>(timeout_s * 1000));
  if (rc <= 0) return Socket();
  int cfd = ::accept(fd_, nullptr, nullptr);
  if (cfd < 0) return Socket();
  int one = 1;
  ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  TuneDataSocketBuffers(cfd);
  return Socket(cfd);
}

}  // namespace hvdtpu
