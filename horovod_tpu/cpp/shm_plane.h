// Shared-memory data plane for same-host members.
//
// When every member of a process set lives on one host (the common
// single-host multi-worker layout, and every localhost test), moving
// tensor bytes through loopback TCP costs kernel copies on both sides of
// every hop — on a CPU-bound host the ring tops out far below memcpy
// speed.  The reference stack solves this with shm transports (Gloo's shm
// path; NCCL's intra-node shm channels; SURVEY.md §2.8) — this is the
// TPU-native core's equivalent for its host (eager) plane.
//
// One POSIX shm region per process set.  Ops are collective and ordered
// per set (the executor lane serializes them), so the region is a simple
// phase-structured scratch: members write, barrier over the set's
// socket channel, read, barrier.  The trailing barrier makes the next
// op's writes safe.  Growth is collective and deterministic: every member
// computes the same required size, so all agree when to remap.
#pragma once

#include <cstdint>
#include <functional>
#include <string>

#include "common.h"

namespace hvdtpu {

class ShmRegion {
 public:
  // Fixed header for per-member sizes (allgather/alltoall geometry):
  // alltoall needs m*m int64s; 16KB covers m up to 45, far beyond
  // single-host worker counts.
  static constexpr int64_t kHeaderBytes = 16 * 1024;

  ~ShmRegion();

  // Creator (lowest member) unlinks any stale region and creates; the
  // caller must barrier between the creator's Open and the others'.
  Status Open(const std::string& name, bool creator);

  // Ensure capacity for `data_bytes` beyond the header.  `barrier` is a
  // socket barrier over the set; it runs only on the grow path (twice:
  // once so no reader still uses the old mapping, once so nobody maps
  // before the creator's ftruncate).  Every member must call with the
  // same `data_bytes`.
  Status EnsureCapacity(int64_t data_bytes, bool creator,
                        const std::function<Status()>& barrier);

  char* header() { return static_cast<char*>(map_); }
  char* data() { return static_cast<char*>(map_) + kHeaderBytes; }
  bool valid() const { return map_ != nullptr; }

  void Close(bool unlink);

 private:
  std::string name_;
  int fd_ = -1;
  void* map_ = nullptr;
  int64_t cap_ = 0;  // total mapped bytes (header + data)
  bool creator_ = false;  // this process ran the O_CREAT|O_EXCL open
                          // (teardown unlinks on EVERY member — see the
                          // destructor comment in shm_plane.cc)
};

}  // namespace hvdtpu
