#include "response_cache.h"

#include <sstream>

namespace hvdtpu {

std::string ResponseCache::Signature(const TensorRequest& r) {
  std::ostringstream os;
  // The device bit is deliberately NOT part of the signature: entries are
  // inserted with the coordinator-ANDed bit while lookups use the local
  // capability bit, so including it would permanently miss for any
  // device-capable rank in a host-demoted collective (the steady-state
  // fallback the cache matters most for).  A cache hit replays the STORED
  // negotiated bit; the Python executor tolerates either direction of a
  // stale bit (device_put on a replayed device=1, host materialization on
  // a replayed device=0).
  os << r.name << '|' << static_cast<int>(r.op) << '|'
     << static_cast<int>(r.dtype) << '|' << static_cast<int>(r.reduce_op)
     << '|' << r.process_set_id << '|' << r.root_rank << '|' << r.prescale
     << '|' << r.postscale << '|' << r.group_key << '|'
     << r.group_size << '|';
  for (auto d : r.shape) os << d << ',';
  os << '|';
  for (auto s : r.splits) os << s << ',';
  return os.str();
}

int64_t ResponseCache::Lookup(const TensorRequest& r) const {
  auto it = by_sig_.find(Signature(r));
  return it == by_sig_.end() ? -1 : it->second;
}

bool ResponseCache::Get(int64_t id, TensorRequest* out) const {
  auto it = by_id_.find(id);
  if (it == by_id_.end()) return false;
  *out = it->second;
  return true;
}

void ResponseCache::Insert(const TensorRequest& r) {
  if (capacity_ <= 0) return;
  std::string sig = Signature(r);
  if (by_sig_.count(sig)) return;
  while (static_cast<int>(fifo_.size()) >= capacity_) {
    int64_t victim = fifo_.front();
    fifo_.pop_front();
    auto it = by_id_.find(victim);
    if (it != by_id_.end()) {
      by_sig_.erase(Signature(it->second));
      by_id_.erase(it);
    }
  }
  int64_t id = next_id_++;
  by_sig_[sig] = id;
  by_id_[id] = r;
  fifo_.push_back(id);
}

void ResponseCache::Clear() {
  by_sig_.clear();
  by_id_.clear();
  fifo_.clear();
}

}  // namespace hvdtpu
