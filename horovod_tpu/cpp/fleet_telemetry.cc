#include "fleet_telemetry.h"

#include <time.h>

#include <algorithm>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <sstream>

#include "flight_recorder.h"
#include "step_trace.h"

namespace hvdtpu {

namespace {

constexpr uint8_t kSketchVersion = 1;
// Bound a decoded tenant map: a malformed frame must not allocate
// unboundedly.  Real jobs hold a handful of process sets.
constexpr uint64_t kMaxTenants = 4096;

// History tiers: 1 s x 120 (2 min live), 10 s x 120 (20 min), 60 s x 240
// (4 h) — enough span for the sentinel's "when did it start drifting"
// question without unbounded growth.
constexpr int kTierCount = 3;
constexpr int kTierPeriodS[kTierCount] = {1, 10, 60};
constexpr int kTierCap[kTierCount] = {120, 120, 240};

// Sentinel defaults: robust-ish EWMA z-score with a warmup so the first
// noisy samples never fire, and a per-kind cooldown so one sustained
// regression reads as one anomaly, not a sample-rate alarm storm.
constexpr int kSentinelWarmup = 10;
constexpr int kSentinelCooldownTicks = 10;
constexpr double kDefaultZScore = 4.0;
constexpr double kEwmaAlpha = 0.1;
constexpr int kMaxAnomalies = 64;
constexpr int kSentinelDominantWindow = 8;

// Sentinel series kinds (flight type-15 `a` upper byte; mirror in
// tools/postmortem.py _SENTINEL_KINDS).
enum SentinelKind : int {
  kSentinelStepP99 = 1,
  kSentinelGoodput = 2,
  kSentinelWireRatio = 3,
};

const char* SentinelKindName(int kind) {
  switch (kind) {
    case kSentinelStepP99: return "step_p99";
    case kSentinelGoodput: return "goodput";
    case kSentinelWireRatio: return "wire_ratio";
    default: return "?";
  }
}

struct Sample {
  int64_t ts_us = 0;
  int64_t step_p99_us = 0;
  int64_t neg_p99_us = 0;
  int64_t goodput_ppm = 0;
  int64_t wire_ratio_ppm = 0;
  int64_t steps = 0;  // cumulative fleet step_time count
};

struct Anomaly {
  int64_t seq = 0;
  int64_t ts_us = 0;
  int kind = 0;
  int rank = -1;
  int64_t value = 0;
  int64_t baseline = 0;
  double score = 0;
};

// One EWMA mean/variance tracker per watched series.  Warmup samples are
// buffered and the baseline is seeded from their median/MAD, not their
// mean/variance: the first ticks of a job carry cold-start transients
// (first negotiation, compile) orders of magnitude above steady state,
// and folding even two of them into an EWMA variance inflates the
// standard deviation for minutes — long enough to mask a real anomaly
// from a z-score that should read >10 sigma.
struct Ewma {
  double mean = 0;
  double var = 0;
  int n = 0;
  int cooldown = 0;
  double warm_buf[kSentinelWarmup] = {0};

  // Returns the z-score of `x` against the pre-update baseline, then
  // folds `x` in.  0 while warming up.
  double Push(double x) {
    if (n < kSentinelWarmup) {
      warm_buf[n] = x;
      ++n;
      if (n == kSentinelWarmup) SeedFromWarmup();
      if (cooldown > 0) --cooldown;
      return 0;
    }
    double z = 0;
    double sd = std::sqrt(var);
    if (sd > 1e-9) z = (x - mean) / sd;
    double d = x - mean;
    mean += kEwmaAlpha * d;
    var = (1 - kEwmaAlpha) * (var + kEwmaAlpha * d * d);
    ++n;
    if (cooldown > 0) --cooldown;
    return z;
  }

  void SeedFromWarmup() {
    double v[kSentinelWarmup];
    std::copy(warm_buf, warm_buf + kSentinelWarmup, v);
    std::sort(v, v + kSentinelWarmup);
    const double med = (v[kSentinelWarmup / 2] +
                        v[(kSentinelWarmup - 1) / 2]) / 2.0;
    double dev[kSentinelWarmup];
    for (int i = 0; i < kSentinelWarmup; ++i) dev[i] = std::fabs(v[i] - med);
    std::sort(dev, dev + kSentinelWarmup);
    const double mad = (dev[kSentinelWarmup / 2] +
                        dev[(kSentinelWarmup - 1) / 2]) / 2.0;
    mean = med;
    // 1.4826*MAD estimates sigma for a normal core; the relative floor
    // keeps z finite when every warmup sample hashed to one histogram
    // bucket (MAD = 0 exactly), which is the common case for a stable
    // power-of-two p99.
    const double sd = std::max(1.4826 * mad, 0.05 * std::fabs(med) + 1.0);
    var = sd * sd;
  }
};

struct Tier {
  std::vector<Sample> ring;
  int64_t pushed = 0;  // samples ever pushed (ring index = pushed % cap)
};

struct State {
  std::mutex mu;
  Tier tiers[kTierCount];
  int64_t last_tick_us = 0;
  double zscore_threshold = kDefaultZScore;
  Ewma ewma_step_p99;
  Ewma ewma_goodput;
  Ewma ewma_wire_ratio;
  std::vector<Anomaly> anomalies;  // bounded log, newest last
  std::atomic<int64_t> anomaly_seq{0};
};

State& S() {
  static State* s = new State();
  return *s;
}

int64_t NowUs() {
  struct timespec ts;
  ::clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

// -- varint codec ------------------------------------------------------------
// LEB128 unsigned varint + zigzag for the (possibly negative) bucket
// deltas.  socketio.h's Writer/Reader speak fixed-width ints only; the
// sketch section is the one place compactness matters, so the codec lives
// here with the sketch.

void PutVarint(std::string* out, uint64_t v) {
  while (v >= 0x80) {
    out->push_back(static_cast<char>((v & 0x7f) | 0x80));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

bool GetVarint(const char** p, const char* end, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*p < end && shift < 64) {
    uint8_t byte = static_cast<uint8_t>(**p);
    ++*p;
    v |= static_cast<uint64_t>(byte & 0x7f) << shift;
    if ((byte & 0x80) == 0) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

uint64_t ZigZag(int64_t v) {
  return (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
}

int64_t UnZigZag(uint64_t v) {
  return static_cast<int64_t>(v >> 1) ^ -static_cast<int64_t>(v & 1);
}

void EncodeHist(std::string* out, const HistogramSketch& h) {
  PutVarint(out, static_cast<uint64_t>(h.count));
  PutVarint(out, static_cast<uint64_t>(h.sum_us));
  int64_t prev = 0;
  for (int b = 0; b < Histogram::kNumBuckets; ++b) {
    PutVarint(out, ZigZag(h.buckets[b] - prev));
    prev = h.buckets[b];
  }
}

bool DecodeHist(const char** p, const char* end, HistogramSketch* h) {
  uint64_t v = 0;
  if (!GetVarint(p, end, &v)) return false;
  h->count = static_cast<int64_t>(v);
  if (!GetVarint(p, end, &v)) return false;
  h->sum_us = static_cast<int64_t>(v);
  int64_t prev = 0;
  for (int b = 0; b < Histogram::kNumBuckets; ++b) {
    if (!GetVarint(p, end, &v)) return false;
    prev += UnZigZag(v);
    if (prev < 0) return false;  // bucket counts are nonnegative
    h->buckets[b] = prev;
  }
  return true;
}

void AppendSample(std::ostringstream& os, const Sample& smp) {
  os << '[' << smp.ts_us << ',' << smp.step_p99_us << ',' << smp.neg_p99_us
     << ',' << smp.goodput_ppm << ',' << smp.wire_ratio_ppm << ','
     << smp.steps << ']';
}

void AppendAnomaly(std::ostringstream& os, const Anomaly& a) {
  os << "{\"seq\":" << a.seq << ",\"ts_us\":" << a.ts_us << ",\"kind\":\""
     << SentinelKindName(a.kind) << "\",\"rank\":" << a.rank
     << ",\"value\":" << a.value << ",\"baseline\":" << a.baseline
     << ",\"score\":" << a.score << "}";
}

// Fold tier `t`'s most recent `n` samples into one downsampled sample:
// max for the latency p99s (a spike must survive downsampling), min for
// goodput (the worst window is the interesting one), last for the
// cumulative columns.  Caller holds s.mu.
Sample Downsample(const Tier& tier, int cap, int n) {
  Sample out;
  for (int64_t k = tier.pushed - n; k < tier.pushed; ++k) {
    const Sample& smp = tier.ring[static_cast<size_t>(k % cap)];
    if (out.ts_us == 0) {
      out = smp;
      continue;
    }
    out.ts_us = smp.ts_us;
    out.step_p99_us = std::max(out.step_p99_us, smp.step_p99_us);
    out.neg_p99_us = std::max(out.neg_p99_us, smp.neg_p99_us);
    out.goodput_ppm = std::min(out.goodput_ppm, smp.goodput_ppm);
    out.wire_ratio_ppm = smp.wire_ratio_ppm;
    out.steps = smp.steps;
  }
  return out;
}

void PushTier(State& s, int t, const Sample& smp) {
  Tier& tier = s.tiers[t];
  if (tier.ring.empty()) tier.ring.assign(kTierCap[t], Sample());
  tier.ring[static_cast<size_t>(tier.pushed % kTierCap[t])] = smp;
  ++tier.pushed;
  // Cascade: every period ratio's worth of pushes folds one sample into
  // the next tier (10 x 1 s -> 10 s, 6 x 10 s -> 60 s).
  if (t + 1 < kTierCount) {
    int ratio = kTierPeriodS[t + 1] / kTierPeriodS[t];
    if (tier.pushed % ratio == 0) {
      PushTier(s, t + 1,
               Downsample(tier, kTierCap[t],
                          std::min<int64_t>(ratio, tier.pushed)));
    }
  }
}

// One sentinel check: push `x` into the tracker, emit an anomaly when the
// z-score clears the threshold in the regression direction.  `direction`
// +1 flags increases (latency), -1 decreases (goodput); 0 either way.
// Caller holds s.mu.
void SentinelCheck(State& s, Ewma& ew, int kind, int direction, double x,
                   int rank, int64_t ts_us) {
  int64_t baseline = static_cast<int64_t>(ew.mean);
  bool warm = ew.n >= kSentinelWarmup && ew.cooldown == 0;
  double z = ew.Push(x);
  if (!warm) return;
  bool fired = direction > 0 ? z > s.zscore_threshold
               : direction < 0
                   ? z < -s.zscore_threshold
                   : std::fabs(z) > s.zscore_threshold;
  if (!fired) return;
  ew.cooldown = kSentinelCooldownTicks;
  Anomaly a;
  a.seq = s.anomaly_seq.fetch_add(1, std::memory_order_relaxed) + 1;
  a.ts_us = ts_us;
  a.kind = kind;
  a.rank = rank;
  a.value = static_cast<int64_t>(x);
  a.baseline = baseline;
  a.score = z;
  s.anomalies.push_back(a);
  if (s.anomalies.size() > kMaxAnomalies) {
    s.anomalies.erase(s.anomalies.begin());
  }
  if (MetricsOn()) {
    GlobalMetrics().sentinel_anomalies_total.fetch_add(
        1, std::memory_order_relaxed);
  }
  if (FlightOn()) {
    // a = kind << 8 | (rank + 1); 0 in the low byte means "no rank
    // attribution" (fleet-wide series like goodput).
    int r = rank < 0 ? 0 : (rank >= 254 ? 255 : rank + 1);
    FlightRecord(kFlightSentinel, (kind << 8) | r, a.value);
  }
}

}  // namespace

// -- HistogramSketch ---------------------------------------------------------

void HistogramSketch::Clear() {
  count = 0;
  sum_us = 0;
  std::memset(buckets, 0, sizeof(buckets));
}

void HistogramSketch::AddFrom(const Histogram& h) {
  count += h.count.load(std::memory_order_relaxed);
  sum_us += h.sum_us.load(std::memory_order_relaxed);
  for (int b = 0; b < Histogram::kNumBuckets; ++b) {
    buckets[b] += h.buckets[b].load(std::memory_order_relaxed);
  }
}

void HistogramSketch::Merge(const HistogramSketch& o) {
  count += o.count;
  sum_us += o.sum_us;
  for (int b = 0; b < Histogram::kNumBuckets; ++b) buckets[b] += o.buckets[b];
}

int64_t HistogramSketch::QuantileUs(double q) const {
  if (count <= 0) return 0;
  int64_t target = static_cast<int64_t>(q * count);
  if (target < 1) target = 1;
  if (target > count) target = count;
  int64_t cum = 0;
  for (int b = 0; b < Histogram::kNumBuckets; ++b) {
    cum += buckets[b];
    if (cum >= target) return int64_t{1} << b;
  }
  return int64_t{1} << (Histogram::kNumBuckets - 1);
}

std::string HistogramSketch::Json() const {
  std::ostringstream os;
  os << "{\"count\":" << count << ",\"sum_us\":" << sum_us
     << ",\"p50_us\":" << QuantileUs(0.5) << ",\"p99_us\":" << QuantileUs(0.99)
     << ",\"buckets\":[";
  for (int b = 0; b < Histogram::kNumBuckets; ++b) {
    if (b) os << ',';
    os << buckets[b];
  }
  os << "]}";
  return os.str();
}

// -- FleetSketch -------------------------------------------------------------

void FleetSketch::Clear() {
  negotiation_wait.Clear();
  ring_hop.Clear();
  step_time.Clear();
  shm_fence.Clear();
  tenants.clear();
}

void FleetSketch::Merge(const FleetSketch& o) {
  negotiation_wait.Merge(o.negotiation_wait);
  ring_hop.Merge(o.ring_hop);
  step_time.Merge(o.step_time);
  shm_fence.Merge(o.shm_fence);
  for (const auto& kv : o.tenants) tenants[kv.first].Merge(kv.second);
}

void FleetSketch::CaptureLocal() {
  Clear();
  MetricsRegistry& m = GlobalMetrics();
  negotiation_wait.AddFrom(m.negotiation_wait_us);
  ring_hop.AddFrom(m.ring_hop_us);
  step_time.AddFrom(m.step_time_us);
  shm_fence.AddFrom(m.shm_fence_us);
  m.ForEachTenantWait([this](int psid, const Histogram& h) {
    tenants[psid].AddFrom(h);
  });
}

std::string FleetSketch::Encode() const {
  std::string out;
  out.reserve(64);
  out.push_back(static_cast<char>(kSketchVersion));
  EncodeHist(&out, negotiation_wait);
  EncodeHist(&out, ring_hop);
  EncodeHist(&out, step_time);
  EncodeHist(&out, shm_fence);
  PutVarint(&out, tenants.size());
  for (const auto& kv : tenants) {
    PutVarint(&out, static_cast<uint64_t>(kv.first));
    EncodeHist(&out, kv.second);
  }
  return out;
}

bool FleetSketch::Decode(const char* data, size_t len) {
  Clear();
  if (len < 1 || static_cast<uint8_t>(data[0]) != kSketchVersion) return false;
  const char* p = data + 1;
  const char* end = data + len;
  if (!DecodeHist(&p, end, &negotiation_wait)) return false;
  if (!DecodeHist(&p, end, &ring_hop)) return false;
  if (!DecodeHist(&p, end, &step_time)) return false;
  if (!DecodeHist(&p, end, &shm_fence)) return false;
  uint64_t n = 0;
  if (!GetVarint(&p, end, &n) || n > kMaxTenants) return false;
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t psid = 0;
    if (!GetVarint(&p, end, &psid)) return false;
    if (!DecodeHist(&p, end, &tenants[static_cast<int>(psid)])) return false;
  }
  return p == end;
}

std::string FleetSketch::Json() const {
  std::ostringstream os;
  os << "{\"negotiation_wait_us\":" << negotiation_wait.Json()
     << ",\"ring_hop_us\":" << ring_hop.Json()
     << ",\"step_time_us\":" << step_time.Json()
     << ",\"shm_fence_us\":" << shm_fence.Json() << ",\"tenants\":{";
  bool first = true;
  for (const auto& kv : tenants) {
    if (!first) os << ',';
    first = false;
    os << '"' << kv.first << "\":{\"negotiation_wait_us\":" << kv.second.Json()
       << '}';
  }
  os << "}}";
  return os.str();
}

// -- plane lifecycle / tick --------------------------------------------------

FleetTelemetryGate& GlobalFleetTelemetry() {
  static FleetTelemetryGate* g = new FleetTelemetryGate();
  return *g;
}

void InitFleetTelemetry() {
  State& s = S();
  bool on = true;
  const char* env = std::getenv("HOROVOD_FLEET_TELEMETRY");
  if (env != nullptr) {
    std::string v(env);
    on = !(v == "0" || v == "off" || v == "false");
  }
  {
    std::lock_guard<std::mutex> l(s.mu);
    for (auto& tier : s.tiers) {
      tier.ring.clear();
      tier.pushed = 0;
    }
    s.last_tick_us = 0;
    s.ewma_step_p99 = Ewma();
    s.ewma_goodput = Ewma();
    s.ewma_wire_ratio = Ewma();
    s.anomalies.clear();
    s.zscore_threshold = kDefaultZScore;
    const char* z = std::getenv("HOROVOD_SENTINEL_ZSCORE");
    if (z != nullptr) {
      char* endp = nullptr;
      double parsed = std::strtod(z, &endp);
      if (endp != z && parsed > 0) s.zscore_threshold = parsed;
    }
  }
  GlobalFleetTelemetry().enabled.store(on, std::memory_order_relaxed);
}

void FleetTelemetryTick(const FleetSketch& fleet, int64_t wire_bytes,
                        int64_t raw_bytes) {
  if (!FleetTelemetryOn()) return;
  State& s = S();
  const int64_t now = NowUs();
  std::lock_guard<std::mutex> l(s.mu);
  if (now - s.last_tick_us < 1000000) return;  // ~1 Hz
  s.last_tick_us = now;

  Sample smp;
  smp.ts_us = now;
  smp.step_p99_us = fleet.step_time.QuantileUs(0.99);
  smp.neg_p99_us = fleet.negotiation_wait.QuantileUs(0.99);
  smp.steps = fleet.step_time.count;
  smp.wire_ratio_ppm =
      raw_bytes > 0 ? wire_bytes * 1000000 / raw_bytes : 1000000;

  // Goodput: ring (bytes actually moving) over the fleet's total
  // attributed wall time — negotiation, fusion, fence and idle are all
  // overhead against it (docs/observability.md "Goodput").
  int64_t phases[kStepPhases] = {0};
  StepTraceFleetPhaseTotals(phases);
  int64_t total = 0;
  for (int p = 0; p < kStepPhases; ++p) total += phases[p];
  smp.goodput_ppm = total > 0 ? phases[kPhaseRing] * 1000000 / total : 0;
  if (MetricsOn()) {
    GlobalMetrics().goodput_ratio_ppm.store(smp.goodput_ppm,
                                            std::memory_order_relaxed);
  }

  PushTier(s, 0, smp);

  // The sentinel attributes latency anomalies to the rank the step-trace
  // fleet view blames by majority vote over the newest complete steps
  // (single-step attribution is noisy — an announce lag can land on the
  // neighbouring forming step); fleet-wide series (goodput, wire ratio)
  // carry no rank.
  int dom_rank = StepTraceFleetDominantRecentRank(kSentinelDominantWindow);
  if (smp.steps > 0) {
    SentinelCheck(s, s.ewma_step_p99, kSentinelStepP99, +1,
                  static_cast<double>(smp.step_p99_us), dom_rank, now);
  }
  if (total > 0) {
    SentinelCheck(s, s.ewma_goodput, kSentinelGoodput, -1,
                  static_cast<double>(smp.goodput_ppm), -1, now);
  }
  if (raw_bytes > 0) {
    SentinelCheck(s, s.ewma_wire_ratio, kSentinelWireRatio, 0,
                  static_cast<double>(smp.wire_ratio_ppm), -1, now);
  }
}

std::string FleetHistoryJson() {
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  std::ostringstream os;
  os << "{\"schema\":\"fleethistory-v1\",\"now_us\":" << NowUs()
     << ",\"columns\":[\"ts_us\",\"step_p99_us\",\"neg_p99_us\","
        "\"goodput_ppm\",\"wire_ratio_ppm\",\"steps\"],\"tiers\":[";
  for (int t = 0; t < kTierCount; ++t) {
    if (t) os << ',';
    const Tier& tier = s.tiers[t];
    const int64_t n =
        std::min<int64_t>(tier.pushed, static_cast<int64_t>(kTierCap[t]));
    os << "{\"period_s\":" << kTierPeriodS[t] << ",\"samples\":[";
    bool first = true;
    for (int64_t k = tier.pushed - n; k < tier.pushed; ++k) {
      if (!first) os << ',';
      first = false;
      AppendSample(os, tier.ring[static_cast<size_t>(k % kTierCap[t])]);
    }
    os << "]}";
  }
  os << "],\"anomalies\":";
  bool first = true;
  os << '[';
  for (const auto& a : s.anomalies) {
    if (!first) os << ',';
    first = false;
    AppendAnomaly(os, a);
  }
  os << "]}";
  return os.str();
}

std::string FleetAnomaliesJson() {
  State& s = S();
  std::lock_guard<std::mutex> l(s.mu);
  std::ostringstream os;
  os << '[';
  bool first = true;
  for (const auto& a : s.anomalies) {
    if (!first) os << ',';
    first = false;
    AppendAnomaly(os, a);
  }
  os << ']';
  return os.str();
}

int64_t FleetAnomalyCount() {
  return S().anomaly_seq.load(std::memory_order_relaxed);
}

void ResetFleetTelemetryForTest() {
  State& s = S();
  GlobalFleetTelemetry().enabled.store(false, std::memory_order_relaxed);
  std::lock_guard<std::mutex> l(s.mu);
  for (auto& tier : s.tiers) {
    tier.ring.clear();
    tier.pushed = 0;
  }
  s.last_tick_us = 0;
  s.ewma_step_p99 = Ewma();
  s.ewma_goodput = Ewma();
  s.ewma_wire_ratio = Ewma();
  s.anomalies.clear();
  s.anomaly_seq.store(0, std::memory_order_relaxed);
}

}  // namespace hvdtpu
