// Shared types for the native core.
//
// TPU-native re-implementation of the reference core's message/type layer
// (horovod/common/common.h, message.h — DataType, Request/Response types;
// SURVEY.md §2.1).  Enum values are ABI shared with horovod_tpu/wire.py —
// keep them in sync.
#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

namespace hvdtpu {

enum class OpType : int32_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  ALLTOALL = 3,
  REDUCESCATTER = 4,
  BARRIER = 5,
  JOIN = 6,
};

enum class ReduceOp : int32_t {
  AVERAGE = 0,
  SUM = 1,
  MIN = 2,
  MAX = 3,
  PRODUCT = 4,
  ADASUM = 5,
};

enum class DataType : int32_t {
  UINT8 = 0,
  INT8 = 1,
  INT32 = 2,
  INT64 = 3,
  FLOAT16 = 4,
  FLOAT32 = 5,
  FLOAT64 = 6,
  BOOL = 7,
  BFLOAT16 = 8,
  UINT16 = 9,
  INT16 = 10,
};

inline int ItemSize(DataType t) {
  switch (t) {
    case DataType::UINT8:
    case DataType::INT8:
    case DataType::BOOL:
      return 1;
    case DataType::UINT16:
    case DataType::INT16:
    case DataType::FLOAT16:
    case DataType::BFLOAT16:
      return 2;
    case DataType::INT32:
    case DataType::FLOAT32:
      return 4;
    case DataType::INT64:
    case DataType::FLOAT64:
      return 8;
  }
  return 1;
}

enum class StatusCode : int32_t {
  OK = 0,
  UNKNOWN_ERROR = 1,
  PRECONDITION_ERROR = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
  IN_PROGRESS = 5,
};

struct Status {
  StatusCode code = StatusCode::OK;
  std::string reason;
  bool ok() const { return code == StatusCode::OK; }
  static Status OK() { return Status{}; }
  static Status Error(StatusCode c, std::string r) { return Status{c, std::move(r)}; }
};

// One enqueued collective request (reference: Request in message.h +
// TensorTableEntry in common.h).  The core never owns tensor *data* — the
// data plane moves bytes (socket path) or is an XLA program (device path);
// the core owns *negotiation metadata* only.
struct TensorRequest {
  int64_t handle = 0;          // per-process handle (Python side registry)
  std::string name;            // globally unique key for negotiation
  OpType op = OpType::ALLREDUCE;
  DataType dtype = DataType::FLOAT32;
  ReduceOp reduce_op = ReduceOp::SUM;
  int64_t nbytes = 0;          // payload size (fusion accounting)
  std::vector<int64_t> shape;  // for cross-rank validation
  int32_t process_set_id = 0;
  int32_t root_rank = 0;       // broadcast
  double prescale = 1.0;
  double postscale = 1.0;
  std::vector<int64_t> splits; // alltoall send splits
  // 1 when the submitting rank can execute this tensor on the device data
  // plane (a device-resident jax.Array + a ready rank mesh).  The
  // coordinator ANDs the flag across ranks so every rank deterministically
  // picks the same plane — the analog of the reference's device-id
  // coherence that decides NCCL vs CPU ops (message.h Request::device).
  int32_t device = 0;
  // Atomic grouped negotiation (reference: group_table.cc — GroupTable):
  // tensors sharing a non-empty key become ready all-or-nothing (the
  // coordinator withholds the group until group_size members are ready on
  // every rank) and are emitted contiguously, so they fuse together and
  // never interleave with other traffic.
  std::string group_key;
  int32_t group_size = 0;
  double enqueued_at = 0.0;    // monotonic seconds (stall inspection)
};

// A negotiated unit of work: one tensor or a fused bucket of allreduces
// (reference: Response in message.h).
struct Response {
  OpType op = OpType::ALLREDUCE;
  DataType dtype = DataType::FLOAT32;
  int32_t process_set_id = 0;
  std::vector<std::string> names;    // global agreement keyed by name
  std::vector<TensorRequest> metas;  // full metadata (cache determinism)
  std::vector<int64_t> handles;      // local handles (filled per rank)
  std::string error;                 // non-empty -> deliver failure
  bool cache_hit = false;
  int64_t seq = -1;  // global data-op sequence (tags data-plane frames)
  // Coordinator-decided plane refinement for host-plane allreduces: when
  // set, every member runs the hierarchical composition (shm-local reduce
  // to a per-host leader, leader-only cross-host ring, shm-local
  // broadcast) instead of the flat all-rank ring.  Carried in the
  // serialized response so the choice can never diverge across ranks —
  // a split plane would deadlock the data plane.
  bool hier = false;
  // Coordinator-decided wire codec for the cross-host ring hops of this
  // response (0=none, 1=bf16, 2=int8 — hvd::WireCodec).  Rides the
  // serialized response for the same reason as `hier`: a codec split
  // across ranks would be a framing mismatch on the data plane.  Demoted
  // to 0 for non-fp32 dtypes, device-plane ops, sub-floor payloads, and
  // topologies where any ring hop stays on-host (docs/compression.md).
  int32_t wire_comp = 0;
  int32_t last_joined = -1;  // JOIN responses: the last rank to join
  // When >= 0, only this rank acts on the response (tombstone error
  // deliveries: the name may have been consistently resubmitted by other
  // ranks, whose fresh handles must not absorb the stale error).  The
  // response list stays byte-identical on every rank; handling is what
  // differs, deterministically.
  int32_t target_rank = -1;
};

struct CoreConfig {
  int rank = 0;
  int size = 1;
  int local_rank = 0;
  int local_size = 1;
  std::string controller = "auto";   // local | socket
  std::string rendezvous_addr = "127.0.0.1";
  int rendezvous_port = 0;
  double cycle_time_ms = 1.0;
  int64_t fusion_threshold = 64LL * 1024 * 1024;
  int cache_capacity = 1024;
  bool autotune = false;
  std::string autotune_log;
  // HOROVOD_HIERARCHICAL_ALLREDUCE: compose shm-local reduce + leader-only
  // cross-host ring + shm-local broadcast for sets spanning hosts with
  // co-located ranks.  Only the coordinator's value matters (the decision
  // rides in each response), so per-rank divergence is harmless.
  bool hierarchical = false;
  // HOROVOD_WIRE_COMPRESSION: codec for cross-host ring hops (0=none,
  // 1=bf16, 2=int8, 3=int4, 4=int8g — hvdtpu::WireCodec).
  // Coordinator-authoritative like `hierarchical`.
  int wire_compression = 0;
  // HOROVOD_WIRE_COMPRESSION device= plane: codec for in-jit / eager-XLA
  // device collectives (0=none, 1=int8, 2=int4, 3=int8g; -1 = no device
  // plane, autotune arm pinned).  Enforced on the Python side; stored
  // here so the autotuner's qdev coordinate starts from the configured
  // value.
  int qdev_compression = 0;
  // HOROVOD_DEVICE_SCHEDULE: device-ring schedule (0=ring, 1=bidi,
  // 2=torus; -1 = schedule arm pinned — no device plane or a member count
  // that only admits the unidirectional ring).  Enforced on the Python
  // side like qdev_compression.
  int qdev_schedule = 0;
  // HOROVOD_DATA_PLANE: in-jit gradient-exchange plane (0=eager explicit
  // collectives, 1=gspmd compiler-inserted; -1 = plane arm pinned — no
  // multi-device mesh, or the quantized device codec owns the traced
  // reduction).  Enforced on the Python side (ops/gspmd_plane.py); stored
  // here so the autotuner's plane coordinate starts from the configured
  // value.
  int data_plane = 0;
  // HOROVOD_METRICS / HOROVOD_METRICS_FILE: enable the native metrics
  // registry; when metrics_file is non-empty the background loop writes a
  // JSON snapshot there every metrics_interval_s (a `{rank}` placeholder
  // is substituted, else `.<rank>` is appended — np>1 runs on one host
  // would otherwise clobber a shared path).
  bool metrics = false;
  std::string metrics_file;
  double metrics_interval_s = 10.0;
  std::string timeline_path;
  bool timeline_mark_cycles = false;
  double stall_warn_s = 60.0;
  double stall_shutdown_s = 0.0;
  int log_level = 2;  // 0=trace .. 5=fatal
  // HOROVOD_AUTOPILOT_PORT (driver-internal): when > 0 the coordinator
  // opens a driver-facing policy listener on this port serving the live
  // cluster view (straggler windows, counters) and accepting autopilot
  // decision records.  0 disables — the default, costing nothing.
  int autopilot_port = 0;
  // HOROVOD_STEP_TRACE / HOROVOD_STEP_TRACE_SLOTS: causal step tracing —
  // per-step phase attribution recorded into a per-rank ring (step_trace.h)
  // and aggregated fleet-wide on the coordinator from CYCLE trailers.  On
  // by default (a site pays a relaxed fetch_add); when off, one relaxed
  // bool load per site, same bar as the flight recorder.
  bool step_trace = true;
  int step_trace_slots = 256;
  // C++-selftest-only (never ABI-exposed): skip the O(n^2) data-plane mesh,
  // shm, and hierarchical setup so in-process control-plane soaks can run
  // hundreds of ranks within fd/time budgets.  Data-plane ops are invalid
  // under this flag.
  bool ctrl_only = false;
};

double MonotonicSeconds();

}  // namespace hvdtpu
