// Response cache: steady-state negotiation shortcut.
//
// Reference: horovod/common/response_cache.h (ResponseCache /
// CacheCoordinator; SURVEY.md §2.1).  After a tensor has been negotiated
// once, every rank holds an identical cache entry for its signature; on the
// next submission a rank announces only the entry's integer id (a "cache
// bit") instead of the full request metadata.  Entries are inserted when a
// response is emitted — a globally ordered event — so ids and FIFO eviction
// stay deterministic across ranks without extra synchronisation (the
// reference re-synchronises an LRU order instead; FIFO avoids that round).
#pragma once

#include <cstdint>
#include <deque>
#include <string>
#include <unordered_map>

#include "common.h"

namespace hvdtpu {

class ResponseCache {
 public:
  explicit ResponseCache(int capacity) : capacity_(capacity) {}

  static std::string Signature(const TensorRequest& r);

  // Returns cache id or -1.
  int64_t Lookup(const TensorRequest& r) const;
  bool Get(int64_t id, TensorRequest* out) const;

  // Insert after a response for this request was emitted (deterministic
  // global order).  No-op if already present or capacity is 0.
  void Insert(const TensorRequest& r);
  void Clear();

  int64_t size() const { return static_cast<int64_t>(by_sig_.size()); }

 private:
  int capacity_;
  int64_t next_id_ = 0;
  std::unordered_map<std::string, int64_t> by_sig_;
  std::unordered_map<int64_t, TensorRequest> by_id_;
  std::deque<int64_t> fifo_;  // insertion order for eviction
};

}  // namespace hvdtpu
