// Self-test for the metrics registry (histogram bucketing, quantile
// bounds, dump validity) and the timeline's JSON emission (hostile tensor
// names: quotes, backslashes, control characters, and kilobyte-long names
// that used to truncate the old fixed snprintf buffers mid-object).
// Run via `make selftest` and tests/single/test_native_selftests.py.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>

#include "metrics.h"
#include "timeline.h"

// Logging hooks normally provided by core_api.cc.
namespace hvdtpu {
int GetLogLevel() { return 5; }
void SetLogLevel(int) {}
}  // namespace hvdtpu

using hvdtpu::GlobalMetrics;
using hvdtpu::Histogram;
using hvdtpu::JsonEscape;
using hvdtpu::Timeline;

namespace {

// Minimal structural JSON validator: balanced containers, legal string
// escapes, no raw control characters inside strings.  Enough to prove a
// trace/dump would survive a real parser without linking one.
bool ValidJson(const std::string& s, std::string* why) {
  std::string stack;
  bool in_string = false;
  for (size_t i = 0; i < s.size(); ++i) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    if (in_string) {
      if (c < 0x20) {
        *why = "raw control char inside string at offset " +
               std::to_string(i);
        return false;
      }
      if (c == '\\') {
        if (i + 1 >= s.size()) {
          *why = "dangling backslash";
          return false;
        }
        char n = s[i + 1];
        if (std::strchr("\"\\/bfnrtu", n) == nullptr) {
          *why = std::string("illegal escape \\") + n;
          return false;
        }
        i += (n == 'u') ? 5 : 1;
      } else if (c == '"') {
        in_string = false;
      }
      continue;
    }
    switch (c) {
      case '"': in_string = true; break;
      case '{': case '[': stack.push_back(static_cast<char>(c)); break;
      case '}':
        if (stack.empty() || stack.back() != '{') {
          *why = "unbalanced } at offset " + std::to_string(i);
          return false;
        }
        stack.pop_back();
        break;
      case ']':
        if (stack.empty() || stack.back() != '[') {
          *why = "unbalanced ] at offset " + std::to_string(i);
          return false;
        }
        stack.pop_back();
        break;
      default: break;
    }
  }
  if (in_string) {
    *why = "unterminated string";
    return false;
  }
  if (!stack.empty()) {
    *why = "unclosed containers: " + stack;
    return false;
  }
  return true;
}

std::string ReadFile(const std::string& path) {
  std::ifstream f(path);
  std::ostringstream os;
  os << f.rdbuf();
  return os.str();
}

#define CHECK(cond, msg)                          \
  do {                                            \
    if (!(cond)) {                                \
      std::printf("FAIL: %s\n", msg);             \
      return 1;                                   \
    }                                             \
  } while (0)

}  // namespace

int main() {
  // -- JsonEscape ----------------------------------------------------------
  {
    std::string nasty = "w[\"0\"]\\path\nend\ttab";
    nasty.push_back('\x01');
    std::string esc = JsonEscape(nasty);
    CHECK(esc == "w[\\\"0\\\"]\\\\path\\nend\\ttab\\u0001",
          "JsonEscape output mismatch");
    std::string why;
    CHECK(ValidJson("{\"k\":\"" + esc + "\"}", &why),
          "escaped string does not form valid JSON");
  }

  // -- Histogram bucketing + quantiles -------------------------------------
  {
    Histogram h;
    h.ObserveUs(0);
    CHECK(h.buckets[0].load() == 1, "0us must land in bucket 0");
    h.ObserveUs(1);   // [1,2) -> bucket 1
    h.ObserveUs(3);   // [2,4) -> bucket 2
    CHECK(h.buckets[1].load() == 1 && h.buckets[2].load() == 1,
          "power-of-two bucket placement wrong");
    h.Reset();
    for (int i = 0; i < 1000; ++i) h.ObserveUs(1000);  // bucket ub 1024
    CHECK(h.count.load() == 1000 && h.sum_us.load() == 1000000,
          "count/sum accounting wrong");
    CHECK(h.QuantileUs(0.5) == 1024 && h.QuantileUs(0.99) == 1024,
          "quantile must return the occupied bucket's upper bound");
    h.ObserveUs(200000);  // one 200ms outlier: p50 unchanged, p99 unchanged
    CHECK(h.QuantileUs(0.5) == 1024, "median moved on a single outlier");
    CHECK(h.QuantileUs(1.0) == 262144, "max quantile must see the outlier");
    // Overflow bucket: beyond the largest finite upper bound.
    Histogram o;
    o.ObserveUs(int64_t{1} << 40);
    CHECK(o.buckets[Histogram::kNumBuckets - 1].load() == 1,
          "huge value must land in the overflow bucket");
    std::string why;
    CHECK(ValidJson(h.Json(), &why), "histogram JSON invalid");
  }

  // -- Registry dump -------------------------------------------------------
  {
    auto& m = GlobalMetrics();
    m.Reset();
    m.enabled.store(true);
    m.cycle_count.fetch_add(7);
    m.cycle_busy_us.fetch_add(123);
    m.responses_total.fetch_add(2);
    m.tensors_fused_total.fetch_add(50);
    m.bytes_fused_total.fetch_add(1 << 20);
    m.negotiation_wait_us.ObserveUs(500);
    std::string dump = m.DumpJson(3, "");
    std::string why;
    CHECK(ValidJson(dump, &why), "registry dump invalid JSON");
    CHECK(dump.find("\"rank\":3") != std::string::npos, "rank missing");
    CHECK(dump.find("\"cycle_count\":7") != std::string::npos,
          "counter missing from dump");
    CHECK(dump.find("\"negotiation_wait_us\":{\"count\":1") !=
              std::string::npos,
          "histogram missing from dump");
    // Extra fragment splices as additional top-level members.
    std::string with_extra = m.DumpJson(0, "\"cluster\":{},\"x\":1");
    CHECK(ValidJson(with_extra, &why), "dump with extra fragment invalid");
    CHECK(with_extra.find("\"cluster\":{}") != std::string::npos,
          "extra fragment not spliced");
    m.enabled.store(false);
    m.Reset();
  }

  // -- Timeline emission with hostile tensor names -------------------------
  {
    std::string path = "/tmp/hvd_metrics_selftest_timeline.json";
    Timeline t;
    t.SetRank(2);
    t.Start(path, /*mark_cycles=*/true);
    std::string nasty = "w[\"0\"]\\b\n";
    t.Begin(nasty, "NEGOTIATE");
    t.End(nasty, "NEGOTIATE");
    std::string huge(2000, 'x');  // old 512-byte buffer truncated this
    huge += "\"tail";
    t.Begin(huge, "NEGOTIATE");
    t.End(huge, "NEGOTIATE");
    t.MarkCycle();
    t.Instant("RENDEZVOUS");
    t.Stop();
    std::string trace = ReadFile(path);
    std::remove(path.c_str());
    CHECK(!trace.empty(), "timeline wrote nothing");
    std::string why;
    if (!ValidJson(trace, &why)) {
      std::printf("FAIL: timeline trace invalid JSON: %s\n", why.c_str());
      return 1;
    }
    CHECK(trace.find("w[\\\"0\\\"]\\\\b\\n") != std::string::npos,
          "hostile tensor name not escaped in trace");
    CHECK(trace.find(huge.substr(0, 1900)) != std::string::npos,
          "long tensor name truncated");
    CHECK(trace.find("\"CLOCK_SYNC\"") != std::string::npos &&
              trace.find("\"rank\":2") != std::string::npos,
          "CLOCK_SYNC anchor with rank missing");
    CHECK(trace.find("\"RENDEZVOUS\"") != std::string::npos,
          "RENDEZVOUS instant missing");
  }

  std::printf("PASS\n");
  return 0;
}
