// C API + background cycle loop: the heart of the native core.
//
// Reference: horovod/common/operations.cc (horovod_init / EnqueueTensor* /
// InitializeHorovodOnce / BackgroundThreadLoop / RunLoopOnce) and
// global_state.h (HorovodGlobalState); SURVEY.md §2.1, §3.1-3.2.
//
// The Python layer (horovod_tpu/_core.py) drives this over ctypes:
//   hvd_enqueue(...)        -> framework thread submits named tensors
//   background thread       -> negotiates + fuses every cycle
//   hvd_pop_response(...)   -> executor thread pops fused responses (JSON)
//   hvd_*_buffer(...)       -> executor runs the host data plane
// Device (TPU) responses are executed in Python as jitted XLA collectives;
// the core guarantees every rank pops byte-identical response lists.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <sstream>
#include <thread>
#include <unordered_map>

#include "common.h"
#include "controller.h"
#include "fault_injection.h"
#include "fleet_telemetry.h"
#include "flight_recorder.h"
#include "logging.h"
#include "metrics.h"
#include "parameter_manager.h"
#include "socket_controller.h"
#include "step_trace.h"
#include "timeline.h"

namespace hvdtpu {

namespace {

int g_log_level = WARNING;

struct GlobalState {
  CoreConfig cfg;
  std::unique_ptr<Controller> controller;

  struct Outstanding {
    int64_t handle;
    double enqueued_at;  // for the stall-shutdown watchdog
  };

  std::mutex queue_mu;
  std::vector<TensorRequest> queue;
  std::unordered_map<std::string, Outstanding> outstanding;  // by name

  std::mutex out_mu;
  std::condition_variable out_cv;
  std::deque<std::string> out_responses;  // JSON lines for Python

  std::thread background;
  std::atomic<bool> shutdown{false};
  std::atomic<bool> background_done{false};
  std::atomic<bool> aborted{false};
  std::atomic<bool> join_inflight{false};

  Timeline timeline;
  ParameterManager params;
  std::atomic<int64_t> fusion_threshold{64LL << 20};
  double cycle_ms = 1.0;
  double last_stall_check = 0.0;
  std::string metrics_path;  // per-rank resolved HOROVOD_METRICS_FILE
  double last_metrics_write = 0.0;

  std::mutex err_mu;
  std::string last_error;
};

GlobalState* g = nullptr;

// Init failures tear down `g` before returning, which would leave
// hvd_last_error() answering "not initialized" — losing the reason
// (e.g. a malformed HOROVOD_FAULT_INJECT parse error) exactly when the
// caller needs it.  Failed-init reasons park here instead.
std::mutex init_err_mu;
std::string init_error;

void SetInitError(const std::string& msg) {
  std::lock_guard<std::mutex> l(init_err_mu);
  init_error = msg;
}

void SetLastError(const std::string& msg) {
  std::lock_guard<std::mutex> l(g->err_mu);
  g->last_error = msg;
}

std::string ResponseToJson(const Response& r) {
  std::ostringstream os;
  os << "{\"op\":" << static_cast<int>(r.op)
     << ",\"dtype\":" << static_cast<int>(r.dtype)
     << ",\"psid\":" << r.process_set_id << ",\"seq\":" << r.seq
     << ",\"cache_hit\":" << (r.cache_hit ? 1 : 0)
     << ",\"last_joined\":" << r.last_joined << ",\"error\":\""
     << JsonEscape(r.error) << "\",\"handles\":[";
  for (size_t i = 0; i < r.handles.size(); ++i) {
    if (i) os << ',';
    os << r.handles[i];
  }
  os << "]";
  // Negotiated data plane: 1 only when EVERY rank announced device
  // capability for every member (the coordinator ANDs the bits), so all
  // ranks dispatch the same cached jitted collective.
  bool device = !r.metas.empty();
  for (const auto& m : r.metas) device = device && m.device != 0;
  os << ",\"device\":" << (device ? 1 : 0);
  // Per-member element counts + reduce op: a joined rank has no local
  // entries yet must still walk the ring with a zero buffer of the right
  // size (hvd.join zero-contribution semantics).
  if (!r.metas.empty()) {
    os << ",\"counts\":[";
    for (size_t i = 0; i < r.metas.size(); ++i) {
      if (i) os << ',';
      os << r.metas[i].nbytes / ItemSize(r.metas[i].dtype);
    }
    os << "]";
  }
  os << "}";
  return os.str();
}

void DeliverResponse(const Response& r) {
  std::lock_guard<std::mutex> l(g->out_mu);
  g->out_responses.push_back(ResponseToJson(r));
  g->out_cv.notify_all();
}

void FailAllOutstanding(const std::string& reason) {
  Response err;
  err.error = reason;
  {
    std::lock_guard<std::mutex> l(g->queue_mu);
    for (auto& kv : g->outstanding) err.handles.push_back(kv.second.handle);
    g->outstanding.clear();
    for (auto& r : g->queue) err.handles.push_back(r.handle);
    g->queue.clear();
  }
  if (!err.handles.empty()) DeliverResponse(err);
}

std::string ControllerMetricsJson() {
  auto* sc = dynamic_cast<SocketController*>(g->controller.get());
  return sc ? sc->ClusterMetricsJson() : std::string();
}

// The registry's ctrl_* counters only accumulate while MetricsOn(), but the
// controller's own counters always run — a dump taken after metrics were
// toggled (or requested with metrics off) would render stale zeros.  Store
// the authoritative controller totals into the registry before rendering.
void SyncCtrlCountersToRegistry() {
  auto* sc = dynamic_cast<SocketController*>(g->controller.get());
  if (sc == nullptr) return;
  int64_t ms = 0, mr = 0, bs = 0, br = 0;
  sc->CtrlPlaneStats(&ms, &mr, &bs, &br);
  auto& m = GlobalMetrics();
  m.ctrl_msgs_sent.store(ms, std::memory_order_relaxed);
  m.ctrl_msgs_recv.store(mr, std::memory_order_relaxed);
  m.ctrl_bytes_sent.store(bs, std::memory_order_relaxed);
  m.ctrl_bytes_recv.store(br, std::memory_order_relaxed);
}

// Atomic (write-then-rename) so a reader never sees a torn snapshot.
void WriteMetricsFile() {
  SyncCtrlCountersToRegistry();
  std::string json =
      GlobalMetrics().DumpJson(g->cfg.rank, ControllerMetricsJson());
  std::string tmp = g->metrics_path + ".tmp";
  FILE* f = std::fopen(tmp.c_str(), "w");
  if (!f) return;
  std::fwrite(json.data(), 1, json.size(), f);
  std::fputc('\n', f);
  std::fclose(f);
  std::rename(tmp.c_str(), g->metrics_path.c_str());
}

void BackgroundLoop() {
  auto& cfg = g->cfg;
  double stall_period = cfg.stall_warn_s > 0 ? cfg.stall_warn_s : 60.0;
  while (!g->shutdown.load()) {
    double sleep_start = MonotonicSeconds();
    std::this_thread::sleep_for(
        std::chrono::microseconds(static_cast<int64_t>(g->cycle_ms * 1000)));
    double work_start = MonotonicSeconds();
    if (MetricsOn()) {
      auto& mreg = GlobalMetrics();
      mreg.cycle_count.fetch_add(1, std::memory_order_relaxed);
      mreg.cycle_idle_us.fetch_add(
          static_cast<int64_t>((work_start - sleep_start) * 1e6),
          std::memory_order_relaxed);
    }
    if (StepTraceOn()) {
      StepTraceAddPhaseUs(
          kPhaseIdle,
          static_cast<int64_t>((work_start - sleep_start) * 1e6));
    }
    g->timeline.MarkCycle();

    std::vector<TensorRequest> newreqs;
    {
      std::lock_guard<std::mutex> l(g->queue_mu);
      newreqs.swap(g->queue);
    }
    if (g->aborted.load()) {
      if (!newreqs.empty()) {
        Response err;
        err.error = "Horovod controller has been aborted";
        for (auto& r : newreqs) err.handles.push_back(r.handle);
        DeliverResponse(err);
      }
      continue;
    }

    std::vector<Response> responses;
    Status s = g->controller->ComputeResponses(newreqs, &responses);
    if (!s.ok()) {
      if (g->shutdown.load()) break;
      g->aborted.store(true);
      SetLastError(s.reason);
      auto* sc = dynamic_cast<SocketController*>(g->controller.get());
      if (sc && sc->peer_shutdown()) {
        // Deliberate peer exit: only noteworthy if work was pending.
        bool pending;
        {
          std::lock_guard<std::mutex> l(g->queue_mu);
          pending = !g->outstanding.empty() || !newreqs.empty();
        }
        if (pending) {
          HVD_LOG(WARNING) << "peer shut down with collectives pending: "
                           << s.reason;
        } else {
          HVD_LOG(INFO) << s.reason;
        }
      } else {
        HVD_LOG(ERROR) << "negotiation failed: " << s.reason;
        // Mark the abort on the trace so a merged multi-rank timeline shows
        // when each survivor learned of the failure; the args carry the
        // culprit attribution for merge_timeline.py / postmortem.py.
        g->timeline.Instant("ABORT",
                            "{\"reason\":\"" + JsonEscape(s.reason) + "\"}");
        // Belt and braces: every socket abort path already dumped, but
        // aborts that never touched the abort machinery (cache divergence,
        // local controller) still leave their black box here.
        if (FlightOn()) FlightDumpToFile();
        if (StepTraceOn()) StepTraceDumpToFile();
      }
      FailAllOutstanding("Horovod negotiation failed: " + s.reason);
      continue;
    }

    int64_t bytes = 0;
    for (auto& r : responses) {
      if (r.target_rank >= 0 && r.target_rank != g->cfg.rank) {
        continue;  // targeted delivery (tombstone error for another rank)
      }
      // Map globally agreed names to this rank's local handles.
      std::lock_guard<std::mutex> l(g->queue_mu);
      for (const auto& name : r.names) {
        auto it = g->outstanding.find(name);
        if (it == g->outstanding.end()) continue;
        if (r.target_rank == g->cfg.rank && !r.error.empty() &&
            !r.metas.empty() &&
            r.metas.front().handle != it->second.handle) {
          // Stale tombstone delivery: the submission it refers to (echoed
          // back by handle in the meta) was already failed by the cycle
          // broadcast; the outstanding entry is a fresh, consistent
          // resubmission that must not absorb the old error.
          continue;
        }
        r.handles.push_back(it->second.handle);
        if (MetricsOn() || StepTraceOn()) {
          // Same span the timeline's NEGOTIATE B/E pair measures, so the
          // registry total and the trace agree.
          const int64_t wait_us = static_cast<int64_t>(
              (MonotonicSeconds() - it->second.enqueued_at) * 1e6);
          if (MetricsOn()) {
            GlobalMetrics().negotiation_wait_us.ObserveUs(wait_us);
            // Per-tenant latency: the same wait attributed to the
            // response's process set, the QoS scheduling signal
            // hvd.metrics() exposes.
            GlobalMetrics().RecordTenantWaitUs(r.process_set_id, wait_us);
          }
          StepTraceAddPhaseUs(kPhaseNegotiation, wait_us);
        }
        g->outstanding.erase(it);
        g->timeline.End(name, "NEGOTIATE");
      }
      for (const auto& m : r.metas) bytes += m.nbytes;
    }
    bool step_work = false;  // did this cycle ship a real fused response?
    for (const auto& r : responses) {
      if (r.target_rank >= 0 && r.target_rank != g->cfg.rank) continue;
      if (!r.error.empty() && r.handles.empty()) {
        if (r.names.empty()) {
          // Errors naming no tensor at all (response-cache divergence)
          // would otherwise vanish: fail the whole job so every blocked
          // synchronize() wakes with the reason.
          g->aborted.store(true);
          SetLastError(r.error);
          HVD_LOG(ERROR) << "negotiation error: " << r.error;
          FailAllOutstanding("Horovod negotiation error: " + r.error);
        }
        // else: a named-tensor error this rank never submitted (e.g. the
        // join guard rejecting another rank's op) — the owning ranks get
        // it on their handles; nothing to do here.
      } else if (!r.handles.empty() || g->join_inflight.load()) {
        // Handle-less non-error responses matter only to a rank with a
        // join in flight: it holds no tensors for the collectives that
        // keep flowing, yet must still walk the ring with zero
        // contributions (the Python executor decides membership).  Without
        // a local join, uninvolved ranks drop them in C++ as before.
        if (r.op == OpType::JOIN && !r.handles.empty()) {
          g->join_inflight.store(false);
        }
        if (MetricsOn() && !r.metas.empty()) {
          auto& mreg = GlobalMetrics();
          int64_t rbytes = 0;
          for (const auto& m : r.metas) rbytes += m.nbytes;
          mreg.responses_total.fetch_add(1, std::memory_order_relaxed);
          mreg.tensors_fused_total.fetch_add(
              static_cast<int64_t>(r.metas.size()), std::memory_order_relaxed);
          mreg.bytes_fused_total.fetch_add(rbytes, std::memory_order_relaxed);
          // The same counters, attributed to the response's process set —
          // the per-tenant baseline the QoS accounting reports against.
          mreg.RecordTenant(r.process_set_id,
                            static_cast<int64_t>(r.metas.size()), rbytes);
        }
        if (r.error.empty() && !r.metas.empty()) step_work = true;
        DeliverResponse(r);
      }
    }
    if (step_work && StepTraceOn() &&
        dynamic_cast<SocketController*>(g->controller.get()) == nullptr) {
      // np=1 (local controller): no coordinator trailer will ever arrive,
      // so close the step here with the same "shipped real work" rule the
      // socket coordinator uses, and feed the fleet view directly so the
      // cockpit's /state breakdown works single-process too.
      StepTraceAdvance(StepTraceCurrentStep() + 1);
      int64_t sid = 0;
      int64_t phases[kStepPhases];
      if (StepTraceLastCompleted(&sid, phases)) {
        StepTraceFleetPhases(0, sid, phases);
      }
    }
    if (bytes > 0) g->params.RecordBytes(bytes);

    int64_t fusion = g->fusion_threshold.load();
    double cycle = g->cycle_ms;
    if (g->params.Tick(&fusion, &cycle)) {
      g->fusion_threshold.store(fusion);
      g->cycle_ms = cycle;
      g->cfg.fusion_threshold = fusion;
      // Categorical knob: worker-side cache announce (safe per rank —
      // inserts stay deterministic either way).
      auto* sc = dynamic_cast<SocketController*>(g->controller.get());
      if (sc) {
        sc->SetAnnounceCache(g->params.announce_cache());
        // Coordinator-only knobs: the hierarchical/wire-codec decisions
        // ride in each serialized response, so applying them on every
        // rank is harmless.
        sc->SetHierarchical(g->params.hierarchical());
        sc->SetWireCompression(g->params.wire_compression());
      }
      HVD_LOG(DEBUG) << "autotune: fusion=" << fusion << " cycle_ms=" << cycle
                     << " announce_cache=" << g->params.announce_cache()
                     << " hierarchical=" << g->params.hierarchical()
                     << " wire_compression=" << g->params.wire_compression()
                     << " qdev=" << g->params.qdev()
                     << " qdev_sched=" << g->params.qdev_sched();
    }

    double now = MonotonicSeconds();
    if (cfg.stall_warn_s > 0 && now - g->last_stall_check > stall_period) {
      g->last_stall_check = now;
      std::string report = g->controller->StallReport(cfg.stall_warn_s);
      if (!report.empty()) {
        if (MetricsOn()) {
          GlobalMetrics().stall_warnings_total.fetch_add(
              1, std::memory_order_relaxed);
        }
        HVD_LOG(WARNING)
            << "Stall detected: tensors submitted on some ranks but not "
               "others: "
            << report;
      }
      int n = 0;
      double oldest_age = 0.0;
      {
        std::lock_guard<std::mutex> l(g->queue_mu);
        for (auto& kv : g->outstanding) {
          ++n;
          oldest_age = std::max(oldest_age, now - kv.second.enqueued_at);
        }
      }
      if (n > 0 && g->cfg.size == 1) {
        HVD_LOG(WARNING) << "Stall: " << n
                         << " tensor(s) pending negotiation locally";
      }
      // Stall-shutdown watchdog (reference: HOROVOD_STALL_SHUTDOWN_TIME_
      // SECONDS aborts the job once a tensor has been stuck this long).
      if (cfg.stall_shutdown_s > 0 && oldest_age > cfg.stall_shutdown_s) {
        g->aborted.store(true);
        std::string msg =
            "stalled for more than " + std::to_string(cfg.stall_shutdown_s) +
            "s waiting for negotiation (one or more ranks never submitted a "
            "matching tensor); shutting down";
        SetLastError(msg);
        HVD_LOG(ERROR) << msg;
        g->timeline.Instant("ABORT",
                            "{\"reason\":\"" + JsonEscape(msg) + "\"}");
        if (FlightOn()) FlightDumpToFile();
        if (StepTraceOn()) StepTraceDumpToFile();
        FailAllOutstanding("Horovod stall shutdown: " + msg);
      }
    }
    if (MetricsOn()) {
      GlobalMetrics().cycle_busy_us.fetch_add(
          static_cast<int64_t>((MonotonicSeconds() - work_start) * 1e6),
          std::memory_order_relaxed);
    }
    if (!g->metrics_path.empty() &&
        MonotonicSeconds() - g->last_metrics_write >= cfg.metrics_interval_s) {
      g->last_metrics_write = MonotonicSeconds();
      WriteMetricsFile();
    }
  }
  g->background_done.store(true);
}

}  // namespace

int GetLogLevel() { return g_log_level; }
void SetLogLevel(int level) { g_log_level = level; }

}  // namespace hvdtpu

using namespace hvdtpu;

extern "C" {

int hvd_init(int rank, int size, int local_rank, int local_size,
             const char* controller, const char* addr, int port,
             double cycle_ms, long long fusion, int cache_cap, int autotune,
             const char* autotune_log, int hierarchical, int wire_compression,
             int qdev_compression, int qdev_schedule,
             int metrics_enabled, const char* metrics_file,
             double metrics_interval_s, const char* timeline_path,
             int timeline_mark_cycles, double stall_warn_s,
             double stall_shutdown_s, int log_level, int flight_enabled,
             int flight_slots, const char* postmortem_dir,
             int autopilot_port, int step_trace_on, int step_trace_slots,
             int data_plane) {
  if (g != nullptr) return -1;
  SetInitError("");  // a fresh attempt must not inherit a stale reason
  g = new GlobalState();
  auto& cfg = g->cfg;
  cfg.rank = rank;
  cfg.size = size;
  cfg.local_rank = local_rank;
  cfg.local_size = local_size;
  cfg.controller = controller ? controller : "auto";
  cfg.rendezvous_addr = addr ? addr : "127.0.0.1";
  cfg.rendezvous_port = port;
  cfg.cycle_time_ms = cycle_ms;
  cfg.fusion_threshold = fusion;
  cfg.cache_capacity = cache_cap;
  cfg.autotune = autotune != 0;
  cfg.autotune_log = autotune_log ? autotune_log : "";
  cfg.hierarchical = hierarchical != 0;
  cfg.wire_compression =
      wire_compression >= 0 && wire_compression <= 4 ? wire_compression : 0;
  // Device-plane codec (0=none, 1=int8, 2=int4, 3=int8g).  -1 means the
  // caller has no device plane at all (no jax mesh): the knob is then
  // pinned for the autotuner, not merely off.
  cfg.qdev_compression =
      qdev_compression >= -1 && qdev_compression <= 3 ? qdev_compression : 0;
  // Device-ring schedule (0=ring, 1=bidi, 2=torus).  -1 pins the autotune
  // arm: no device plane, or a member count that only admits the
  // unidirectional ring.
  cfg.qdev_schedule =
      qdev_schedule >= -1 && qdev_schedule <= 2 ? qdev_schedule : 0;
  // In-jit gradient-exchange plane (0=eager, 1=gspmd).  -1 pins the
  // autotune arm: no multi-device mesh, or the quantized codec owns the
  // traced reduction (the compose-or-demote rule of ops/gspmd_plane.py).
  cfg.data_plane = data_plane >= -1 && data_plane <= 1 ? data_plane : 0;
  cfg.metrics_file = metrics_file ? metrics_file : "";
  cfg.metrics = metrics_enabled != 0 || !cfg.metrics_file.empty();
  cfg.metrics_interval_s = metrics_interval_s > 0 ? metrics_interval_s : 10.0;
  cfg.timeline_path = timeline_path ? timeline_path : "";
  cfg.timeline_mark_cycles = timeline_mark_cycles != 0;
  cfg.stall_warn_s = stall_warn_s;
  cfg.stall_shutdown_s = stall_shutdown_s;
  cfg.autopilot_port = autopilot_port > 0 ? autopilot_port : 0;
  cfg.step_trace = step_trace_on != 0;
  cfg.step_trace_slots = step_trace_slots > 0 ? step_trace_slots : 256;
  SetLogLevel(log_level);
  g->cycle_ms = cycle_ms > 0 ? cycle_ms : 1.0;
  g->fusion_threshold.store(fusion);

  // Fault injection (HOROVOD_FAULT_INJECT) arms before any thread exists so
  // hit counters are deterministic from the first frame.  A malformed spec
  // fails init loudly: silently running a chaos test with zero faults armed
  // would pass for the wrong reason.
  {
    std::string ferr = InitFaultInjection();
    if (!ferr.empty()) {
      SetInitError(ferr);
      HVD_LOG(ERROR) << "init failed: " << ferr;
      delete g;
      g = nullptr;
      return -2;
    }
  }

  // The registry is process-global (instrumentation points sit below the
  // GlobalState), so re-init within one process starts from zero.
  GlobalMetrics().Reset();
  GlobalMetrics().enabled.store(cfg.metrics, std::memory_order_relaxed);
  if (!cfg.metrics_file.empty()) {
    std::string p = cfg.metrics_file;
    auto pos = p.find("{rank}");
    if (pos != std::string::npos) {
      p.replace(pos, 6, std::to_string(cfg.rank));
    } else {
      p += "." + std::to_string(cfg.rank);
    }
    g->metrics_path = p;
  }
  g->timeline.SetRank(cfg.rank);

  // Flight recorder arms BEFORE the controller exists: the rendezvous is
  // the first event worth keeping, and an init failure below still leaves
  // a black box behind.
  InitFlightRecorder(flight_enabled != 0, flight_slots,
                     postmortem_dir ? postmortem_dir : "", cfg.rank);
  // Step tracing arms alongside it (same postmortem dir for the abort-time
  // steptrace.<rank>.json dump) so the first negotiated step is attributed.
  InitStepTrace(cfg.step_trace, cfg.step_trace_slots,
                postmortem_dir ? postmortem_dir : "", cfg.rank, cfg.size);
  // Fleet telemetry (v11) arms with them: HOROVOD_FLEET_TELEMETRY gates
  // the sketch sections, history ring, goodput gauge and the sentinel;
  // elastic re-init re-arms with fresh history/sentinel state.
  InitFleetTelemetry();

  if (cfg.size > 1 || cfg.controller == "socket") {
    g->controller = std::make_unique<SocketController>(cfg);
    // Autopilot decisions accepted on the policy channel land on the
    // timeline as instants (the flight/metrics records happen inside the
    // controller).  Installed before Initialize starts the serve thread.
    static_cast<SocketController*>(g->controller.get())
        ->SetAutopilotDecisionHook(
            [](int action, int rank, const std::string& detail) {
              if (g == nullptr) return;
              g->timeline.Instant(
                  "AUTOPILOT", "{\"action\":" + std::to_string(action) +
                                   ",\"rank\":" + std::to_string(rank) +
                                   ",\"detail\":\"" + JsonEscape(detail) +
                                   "\"}");
            });
  } else {
    g->controller = std::make_unique<LocalController>(cfg);
  }
  Status s = g->controller->Initialize();
  if (!s.ok()) {
    SetInitError(s.reason);
    HVD_LOG(ERROR) << "init failed: " << s.reason;
    // A fatal init error is a postmortem moment too (the rank may have
    // recorded a partial rendezvous before dying).
    if (FlightOn()) FlightDumpToFile();
    GlobalMetrics().enabled.store(false, std::memory_order_relaxed);
    delete g;
    g = nullptr;
    return -2;
  }
  if (!cfg.timeline_path.empty()) {
    g->timeline.Start(cfg.timeline_path, cfg.timeline_mark_cycles);
    // Every rank leaves controller Initialize() through the rendezvous
    // handshake's closing fences within the same instant, so this event
    // is merge_timeline.py's cross-rank alignment anchor.
    g->timeline.Instant("RENDEZVOUS");
  }
  if (cfg.autotune) {
    // The hierarchical knob is tunable only when the wired-up topology can
    // act on it (>= 2 hosts with >= 1 multi-rank host and working shm);
    // otherwise it is pinned off so the GP never explores a dead arm.
    auto* sc = dynamic_cast<SocketController*>(g->controller.get());
    bool hier_tunable = sc != nullptr && sc->HierAvailable();
    // Same pinning rule for the wire codec: tunable only when some ring
    // hop actually crosses hosts (the leader ring, or an all-cross-host
    // flat ring).
    bool wire_tunable = sc != nullptr && sc->WireCompAvailable();
    // Device-plane codec coordinate: tunable only when the Python side
    // reported a usable device plane (qdev >= 0); -1 pins the arm.
    bool qdev_tunable = cfg.qdev_compression >= 0;
    int qdev_comp = cfg.qdev_compression >= 0 ? cfg.qdev_compression : 0;
    // Device-ring schedule coordinate: pinned alongside qdev, and also
    // when the Python side reported only the unidirectional ring is
    // feasible for the plane's member count (-1).
    bool sched_tunable = qdev_tunable && cfg.qdev_schedule >= 0;
    int qdev_sched = cfg.qdev_schedule >= 0 ? cfg.qdev_schedule : 0;
    // Data-plane coordinate: tunable only when the Python side reported a
    // usable gspmd mesh (data_plane >= 0); -1 pins the arm to eager.
    bool plane_tunable = cfg.data_plane >= 0;
    int plane0 = cfg.data_plane >= 0 ? cfg.data_plane : 0;
    g->params.Initialize(fusion, g->cycle_ms, cfg.autotune_log,
                         cfg.hierarchical, hier_tunable,
                         cfg.wire_compression, wire_tunable,
                         qdev_comp, qdev_tunable, qdev_sched, sched_tunable,
                         plane0, plane_tunable);
  }
  g->background = std::thread(BackgroundLoop);
  return 0;
}

int hvd_shutdown() {
  if (g == nullptr) return -1;
  g->shutdown.store(true);
  // Let the background loop finish its current cycle before touching the
  // sockets (every rank replies every cycle, so this is normally bounded
  // by the cycle time), then send the clean-exit notice — teardown stops
  // looking like a peer crash on the other ranks.  If a peer has wedged
  // (alive TCP, no frames), the loop stays blocked in recv: after a grace
  // period force the sockets closed so shutdown always terminates.
  double deadline = MonotonicSeconds() + 2.0;
  while (!g->background_done.load() && MonotonicSeconds() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  if (g->background_done.load()) {
    g->controller->Farewell();
    g->controller->Shutdown();
  } else {
    g->controller->Shutdown();  // unblocks the recv; no farewell possible
  }
  if (g->background.joinable()) g->background.join();
  FailAllOutstanding("Horovod has been shut down");
  // Final snapshot so short runs (shorter than the interval) still leave
  // a complete metrics file behind.
  if (!g->metrics_path.empty()) WriteMetricsFile();
  // Same courtesy for the step trace: a clean exit leaves the attribution
  // behind for tools/critical_path.py without requiring an abort.
  if (StepTraceOn()) StepTraceDumpToFile();
  GlobalStepTraceGate().enabled.store(false, std::memory_order_relaxed);
  GlobalMetrics().enabled.store(false, std::memory_order_relaxed);
  g->timeline.Stop();
  {
    std::lock_guard<std::mutex> l(g->out_mu);
    g->out_cv.notify_all();
  }
  delete g;
  g = nullptr;
  return 0;
}

int hvd_is_initialized() { return g != nullptr ? 1 : 0; }
int hvd_rank() { return g ? g->cfg.rank : -1; }
int hvd_size() { return g ? g->cfg.size : -1; }
int hvd_local_rank() { return g ? g->cfg.local_rank : -1; }
int hvd_local_size() { return g ? g->cfg.local_size : -1; }

long long hvd_enqueue(long long handle, const char* name, int op, int dtype,
                      int reduce_op, long long nbytes, const long long* shape,
                      int ndim, int psid, int root_rank, double prescale,
                      double postscale, const long long* splits, int nsplits,
                      int device, const char* group_key, int group_size) {
  if (g == nullptr) return -1;
  TensorRequest r;
  r.handle = handle;
  r.name = name;
  r.op = static_cast<OpType>(op);
  r.dtype = static_cast<DataType>(dtype);
  r.reduce_op = static_cast<ReduceOp>(reduce_op);
  r.nbytes = nbytes;
  r.shape.assign(shape, shape + ndim);
  r.process_set_id = psid;
  r.root_rank = root_rank;
  r.prescale = prescale;
  r.postscale = postscale;
  r.device = device != 0 ? 1 : 0;
  if (group_key && group_key[0]) {
    r.group_key = group_key;
    r.group_size = group_size;
  }
  if (splits && nsplits > 0) r.splits.assign(splits, splits + nsplits);
  r.enqueued_at = MonotonicSeconds();
  if (r.op == OpType::JOIN) g->join_inflight.store(true);
  {
    std::lock_guard<std::mutex> l(g->queue_mu);
    if (g->outstanding.count(r.name)) return -2;  // duplicate in flight
    g->outstanding[r.name] = {handle, r.enqueued_at};
    g->queue.push_back(std::move(r));
  }
  g->timeline.Begin(name, "NEGOTIATE");
  return 0;
}

// Returns: >0 = JSON length written, 0 = timeout, -1 = not initialized,
// -2 = buffer too small (len stored in *needed).
int hvd_pop_response(char* buf, int cap, int timeout_ms) {
  if (g == nullptr) return -1;
  std::unique_lock<std::mutex> l(g->out_mu);
  if (g->out_responses.empty()) {
    g->out_cv.wait_for(l, std::chrono::milliseconds(timeout_ms));
  }
  if (g->out_responses.empty()) return 0;
  const std::string& s = g->out_responses.front();
  if (static_cast<int>(s.size()) + 1 > cap) return -2;
  std::memcpy(buf, s.data(), s.size());
  buf[s.size()] = '\0';
  int n = static_cast<int>(s.size());
  g->out_responses.pop_front();
  return n;
}

static void SetSeq(long long seq) {
  auto* sc = dynamic_cast<SocketController*>(g->controller.get());
  if (sc) sc->SetCurrentSeq(seq);
}

static int StatusToInt(const Status& s) {
  if (s.ok()) return 0;
  std::string reason = s.reason;
  if (s.code == StatusCode::ABORTED) {
    // A data-plane socket failure only says "peer died"; the coordinator's
    // ABORT broadcast (bounded wait) names the culprit rank/host.  Fold it
    // in so the HorovodInternalError the executor raises is actionable.
    std::string why = g->controller->WaitAbortReason();
    if (!why.empty() && reason.find(why) == std::string::npos) {
      reason += " [" + why + "]";
    }
  }
  SetLastError(reason);
  return -static_cast<int>(s.code);
}

int hvd_allreduce_buffer(long long seq, void* buf, long long count, int dtype,
                         int reduce_op, int psid) {
  if (g == nullptr) return -1;
  SetSeq(seq);
  g->timeline.Begin("seq." + std::to_string(seq), "DATA_ALLREDUCE");
  Status s = g->controller->AllreduceBuffer(
      buf, count, static_cast<DataType>(dtype),
      static_cast<ReduceOp>(reduce_op), psid);
  g->timeline.End("seq." + std::to_string(seq), "DATA_ALLREDUCE");
  return StatusToInt(s);
}

int hvd_reducescatter_buffer(long long seq, void* buf, long long count,
                             int dtype, int reduce_op, int psid,
                             const long long* slice_counts, int n_slices) {
  if (g == nullptr) return -1;
  SetSeq(seq);
  std::vector<int64_t> slices(slice_counts, slice_counts + n_slices);
  g->timeline.Begin("seq." + std::to_string(seq), "DATA_REDUCESCATTER");
  Status s = g->controller->ReduceScatterBuffer(
      buf, count, static_cast<DataType>(dtype),
      static_cast<ReduceOp>(reduce_op), slices, psid);
  g->timeline.End("seq." + std::to_string(seq), "DATA_REDUCESCATTER");
  return StatusToInt(s);
}

// Allgather: returns malloc'd buffer in *out (caller frees via hvd_free).
int hvd_allgather_buffer(long long seq, const void* in, long long nbytes,
                         int psid, void** out, long long* out_len,
                         long long* counts, int counts_cap, int* n_counts) {
  if (g == nullptr) return -1;
  SetSeq(seq);
  std::string gathered;
  std::vector<int64_t> per_rank;
  Status s =
      g->controller->AllgatherBuffer(in, nbytes, psid, &gathered, &per_rank);
  if (!s.ok()) return StatusToInt(s);
  if (static_cast<int>(per_rank.size()) > counts_cap) return -3;
  char* mem = static_cast<char*>(std::malloc(gathered.size()));
  std::memcpy(mem, gathered.data(), gathered.size());
  *out = mem;
  *out_len = static_cast<long long>(gathered.size());
  for (size_t i = 0; i < per_rank.size(); ++i) counts[i] = per_rank[i];
  *n_counts = static_cast<int>(per_rank.size());
  return 0;
}

int hvd_broadcast_buffer(long long seq, void* buf, long long nbytes, int root,
                         int psid) {
  if (g == nullptr) return -1;
  SetSeq(seq);
  return StatusToInt(g->controller->BroadcastBuffer(buf, nbytes, root, psid));
}

int hvd_alltoall_buffer(long long seq, const void* in, const long long* splits,
                        int nsplits, long long row_bytes, int psid, void** out,
                        long long* out_len, long long* recv_splits,
                        int* n_recv) {
  if (g == nullptr) return -1;
  SetSeq(seq);
  std::vector<int64_t> sp(splits, splits + nsplits);
  std::string received;
  std::vector<int64_t> rsp;
  Status s = g->controller->AlltoallBuffer(in, sp, row_bytes, psid, &received,
                                           &rsp);
  if (!s.ok()) return StatusToInt(s);
  char* mem = static_cast<char*>(std::malloc(received.size()));
  std::memcpy(mem, received.data(), received.size());
  *out = mem;
  *out_len = static_cast<long long>(received.size());
  for (size_t i = 0; i < rsp.size(); ++i) recv_splits[i] = rsp[i];
  *n_recv = static_cast<int>(rsp.size());
  return 0;
}

int hvd_barrier(long long seq, int psid) {
  if (g == nullptr) return -1;
  SetSeq(seq);
  return StatusToInt(g->controller->Barrier(psid));
}

void hvd_free(void* p) { std::free(p); }

int hvd_add_process_set(const int* ranks, int n) {
  if (g == nullptr) return -1;
  std::vector<int> v(ranks, ranks + n);
  int id = g->controller->process_sets().Add(v);
  // Dedicated data channel (per-set socket mesh) so this set's collectives
  // can run on their own executor lane, concurrent with other sets'.
  Status s = g->controller->EstablishChannel(id);
  if (!s.ok()) {
    // EstablishChannel can fail after the channel sockets were inserted
    // (the shm handshake runs last): close them too.
    g->controller->RemoveChannel(id);
    g->controller->process_sets().Remove(id);
    SetLastError("process set channel establishment failed: " + s.reason);
    return -4;
  }
  return id;
}

// QoS variant: `weight` orders the coordinator's fused-response schedule
// (higher weight first; the global set is pinned at 1.0).  The unweighted
// export above keeps its ABI for older callers.
int hvd_add_process_set2(const int* ranks, int n, double weight) {
  if (g == nullptr) return -1;
  std::vector<int> v(ranks, ranks + n);
  int id = g->controller->process_sets().AddWeighted(v, weight);
  Status s = g->controller->EstablishChannel(id);
  if (!s.ok()) {
    g->controller->RemoveChannel(id);
    g->controller->process_sets().Remove(id);
    SetLastError("process set channel establishment failed: " + s.reason);
    return -4;
  }
  return id;
}

int hvd_remove_process_set(int id) {
  if (g == nullptr) return -1;
  g->controller->RemoveChannel(id);
  g->controller->process_sets().Remove(id);
  return 0;
}

int hvd_process_set_ranks(int id, int* out, int cap) {
  if (g == nullptr) return -1;
  std::vector<int> ranks;
  if (!g->controller->process_sets().Ranks(id, &ranks)) return -2;
  if (static_cast<int>(ranks.size()) > cap) return -3;
  for (size_t i = 0; i < ranks.size(); ++i) out[i] = ranks[i];
  return static_cast<int>(ranks.size());
}

void hvd_negotiation_stats(long long* sent, long long* recv) {
  if (g == nullptr) {
    *sent = *recv = 0;
    return;
  }
  int64_t s = 0, r = 0;
  g->controller->NegotiationStats(&s, &r);
  *sent = s;
  *recv = r;
}

// Ctrl-plane frame + byte counters (protocol v9): on the coordinator,
// msgs_recv per negotiation cycle is the leader-tree acceptance metric —
// O(ranks) flat vs O(local ranks + hosts) with the tree engaged.
void hvd_ctrl_plane_stats(long long* msgs_sent, long long* msgs_recv,
                          long long* bytes_sent, long long* bytes_recv) {
  *msgs_sent = *msgs_recv = *bytes_sent = *bytes_recv = 0;
  if (g == nullptr) return;
  int64_t ms = 0, mr = 0, bs = 0, br = 0;
  g->controller->CtrlPlaneStats(&ms, &mr, &bs, &br);
  *msgs_sent = ms;
  *msgs_recv = mr;
  *bytes_sent = bs;
  *bytes_recv = br;
}

// Data-plane byte accounting split by locality (host plane only): bytes
// sent to ranks sharing this rank's host key vs. bytes crossing hosts.
// Lets tests assert the hierarchical composition actually shrinks
// cross-host traffic instead of trusting the topology log.
void hvd_data_plane_stats(long long* local, long long* xhost) {
  *local = *xhost = 0;
  if (g == nullptr) return;
  auto* sc = dynamic_cast<SocketController*>(g->controller.get());
  if (sc == nullptr) return;
  int64_t l = 0, x = 0, rl = 0, rx = 0;
  sc->DataPlaneStats(&l, &x, &rl, &rx);
  *local = l;
  *xhost = x;
}

// Extended form: `raw_*` are the fp32-equivalent payload bytes of the
// same sends (wire == raw unless a compressed ring encoded them), so
// raw/wire is the measured compression ratio.  The 2-arg export above
// keeps its ABI for older callers.
void hvd_data_plane_stats2(long long* local, long long* xhost,
                           long long* raw_local, long long* raw_xhost) {
  *local = *xhost = *raw_local = *raw_xhost = 0;
  if (g == nullptr) return;
  auto* sc = dynamic_cast<SocketController*>(g->controller.get());
  if (sc == nullptr) return;
  int64_t l = 0, x = 0, rl = 0, rx = 0;
  sc->DataPlaneStats(&l, &x, &rl, &rx);
  *local = l;
  *xhost = x;
  *raw_local = rl;
  *raw_xhost = rx;
}

// Device-plane (in-jit / eager-XLA) quantized-collective byte accounting.
// The Python side calls note() once per quantized dispatch with the raw
// fp32 ring bytes the collective would have moved and the int8-encoded
// bytes it did move; stats() reads both back.  raw/encoded is the
// measured device-codec ratio (uncompressed device collectives report
// nothing — XLA moves those bytes without telling us).
void hvd_device_plane_note(long long raw_bytes, long long encoded_bytes) {
  auto& m = GlobalMetrics();
  if (raw_bytes > 0) {
    m.device_raw_bytes.fetch_add(raw_bytes, std::memory_order_relaxed);
  }
  if (encoded_bytes > 0) {
    m.device_encoded_bytes.fetch_add(encoded_bytes,
                                     std::memory_order_relaxed);
  }
}

void hvd_device_plane_stats(long long* raw_bytes, long long* encoded_bytes) {
  auto& m = GlobalMetrics();
  *raw_bytes = m.device_raw_bytes.load(std::memory_order_relaxed);
  *encoded_bytes = m.device_encoded_bytes.load(std::memory_order_relaxed);
}

// GSPMD-plane (compiler-inserted collective) accounting, reported by the
// Python HLO inspector (ops/hlo_inspect.py) once per inspected trace:
// the number of collectives XLA emitted, their analytic raw payload
// bytes, and the analytic ring wire bytes.  Like the device-plane pair,
// these tick per trace, never per step — a compiled program cannot count
// at run time.  Callable before/without init (the registry is
// process-global); the timeline instant needs a live core.
void hvd_gspmd_plane_note(long long ops, long long raw_bytes,
                          long long wire_bytes) {
  NoteHloInspect(ops, raw_bytes, wire_bytes);
  if (g != nullptr) {
    g->timeline.Instant(
        "HLO_INSPECT", "{\"collectives\":" + std::to_string(ops) +
                           ",\"raw_bytes\":" + std::to_string(raw_bytes) +
                           ",\"wire_bytes\":" + std::to_string(wire_bytes) +
                           "}");
  }
}

void hvd_gspmd_plane_stats(long long* raw_bytes, long long* wire_bytes) {
  auto& m = GlobalMetrics();
  *raw_bytes = m.gspmd_raw_bytes.load(std::memory_order_relaxed);
  *wire_bytes = m.gspmd_wire_bytes.load(std::memory_order_relaxed);
}

// Tags the forming causal steps with the data plane running them
// (0 eager, 1 gspmd, -1 unknown) — noted by the optimizer at trace time,
// stamped into each closing step record and the coordinator's fleet
// records, surfaced by tools/critical_path.py and the cockpit.
void hvd_step_trace_note_plane(int plane) {
  StepTraceNotePlane(plane);
}

// The autotuner's current device-plane codec decision (0=none, 1=int8,
// 2=int4, 3=int8g; -1 = not initialized).  The Python side polls it
// between steps and re-traces with the quantized ring when it flips — the
// device plane's analog of SetWireCompression on the host ring.
int hvd_autotune_qdev() {
  if (g == nullptr) return -1;
  return g->params.qdev();
}

// The autotuner's current device-ring schedule decision (0=ring, 1=bidi,
// 2=torus; -1 = not initialized).  Polled together with
// hvd_autotune_qdev().
int hvd_autotune_qsched() {
  if (g == nullptr) return -1;
  return g->params.qdev_sched();
}

// The autotuner's current data-plane decision (0=eager, 1=gspmd; -1 = not
// initialized).  Polled like hvd_autotune_qdev(): the flip takes effect
// at the next DistributedOptimizer construction/trace, never mid-step.
int hvd_autotune_plane() {
  if (g == nullptr) return -1;
  return g->params.plane();
}

// Full local metrics registry as one JSON object; on the coordinator the
// dump also carries the aggregated cluster view (per-rank piggybacked
// snapshots) and the latest straggler attribution report.
// Returns: >0 = JSON length written, -1 = not initialized, -2 = buffer
// too small (caller grows and retries, same convention as
// hvd_pop_response).
int hvd_metrics_dump(char* buf, int cap) {
  if (g == nullptr) return -1;
  SyncCtrlCountersToRegistry();
  std::string json =
      GlobalMetrics().DumpJson(g->cfg.rank, ControllerMetricsJson());
  if (static_cast<int>(json.size()) + 1 > cap) return -2;
  std::memcpy(buf, json.data(), json.size());
  buf[json.size()] = '\0';
  return static_cast<int>(json.size());
}

// This rank's full flight-recorder buffer as one JSON object (the same
// schema as the crash dumps under HOROVOD_POSTMORTEM_DIR).  Returns:
// >0 = JSON length written, 0 = recorder disabled, -1 = not initialized,
// -2 = buffer too small (caller grows and retries, same convention as
// hvd_metrics_dump).
int hvd_flight_record(char* buf, int cap) {
  if (g == nullptr) return -1;
  if (!FlightOn()) return 0;
  std::string json = FlightDumpJson();
  if (static_cast<int>(json.size()) + 1 > cap) return -2;
  std::memcpy(buf, json.data(), json.size());
  buf[json.size()] = '\0';
  return static_cast<int>(json.size());
}

// Same contract as hvd_flight_record: -1 not initialized, 0 tracing off,
// -2 buffer too small (caller doubles and retries), else JSON length.
int hvd_step_trace(char* buf, int cap) {
  if (g == nullptr) return -1;
  if (!StepTraceOn()) return 0;
  std::string json = StepTraceDumpJson();
  if (static_cast<int>(json.size()) + 1 > cap) return -2;
  std::memcpy(buf, json.data(), json.size());
  buf[json.size()] = '\0';
  return static_cast<int>(json.size());
}

// The coordinator's multi-resolution fleet history + anomaly log
// (fleethistory-v1; fleet_telemetry.h).  Same contract as hvd_step_trace:
// -1 not initialized, 0 plane off, -2 buffer too small (caller doubles
// and retries), else JSON length.
int hvd_fleet_history(char* buf, int cap) {
  if (g == nullptr) return -1;
  if (!FleetTelemetryOn()) return 0;
  std::string json = FleetHistoryJson();
  if (static_cast<int>(json.size()) + 1 > cap) return -2;
  std::memcpy(buf, json.data(), json.size());
  buf[json.size()] = '\0';
  return static_cast<int>(json.size());
}

void hvd_start_timeline(const char* path, int mark_cycles) {
  if (g) g->timeline.Start(path, mark_cycles != 0);
}

void hvd_stop_timeline() {
  if (g) g->timeline.Stop();
}

const char* hvd_last_error() {
  if (g == nullptr) {
    std::lock_guard<std::mutex> l(init_err_mu);
    return init_error.empty() ? "not initialized" : init_error.c_str();
  }
  std::lock_guard<std::mutex> l(g->err_mu);
  return g->last_error.c_str();
}

// Validate a HOROVOD_FAULT_INJECT spec without arming anything: returns ""
// when well-formed, else the same actionable message init would fail with.
// Lets horovodrun --fault-inject reject typos before spawning np workers.
const char* hvd_fault_spec_check(const char* spec) {
  static thread_local std::string err;
  err = ParseFaultSpec(spec ? spec : "", nullptr);
  return err.c_str();
}

// Elastic-migration forensic note (docs/elastic.md "Zero-downtime
// migration"): one call per migration phase on each participating rank.
// Routes through the shared NoteMigration (metrics counters + flight
// type 14) and lands a MIGRATE instant on the host timeline.  A fallback
// phase forces a flight dump like an autopilot decision does — the
// checkpoint path it announces usually follows a generation teardown.
void hvd_migrate_note(int phase, long long bytes, int source_rank) {
  NoteMigration(phase, bytes, source_rank);
  if (g != nullptr) {
    g->timeline.Instant(
        "MIGRATE", "{\"phase\":" + std::to_string(phase) +
                       ",\"bytes\":" + std::to_string(bytes) +
                       ",\"source_rank\":" + std::to_string(source_rank) +
                       "}");
  }
  if (phase == kMigrateFallback && FlightOn() &&
      !FlightPostmortemDir().empty()) {
    FlightDumpToFile();
  }
}

// Publishes the elastic generation this rank joined (from the driver's
// assignment) as a metrics gauge, so scrapes can correlate migrate/abort
// counters with re-formations.  Callable before/without init — the
// registry is process-global.
void hvd_elastic_generation_set(long long generation) {
  GlobalMetrics().elastic_generation.store(generation,
                                           std::memory_order_relaxed);
}

}  // extern "C"
