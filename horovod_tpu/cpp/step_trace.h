// Causal step tracing: the fifth observability pillar (docs/observability.md).
//
// Every background-loop cycle that ships real work advances a coordinator-
// assigned step id (carried in the v10 control frames), and the sites the
// flight recorder already instruments attribute their elapsed time to the
// current step's phase vector:
//
//   negotiation_wait  enqueue -> response delivery (the victim-side signal)
//   fusion            coordinator fuse/gate + leader tree aggregation
//   ring              host data-plane ring hops (pipelined exchange steps)
//   fence             socket barriers sequencing the shm plane
//   idle              background-loop sleep
//
// Completed steps land in a per-rank ring; the last completed record
// piggybacks on the next CYCLE frame (protocol v10 trailer) so the
// coordinator can aggregate a fleet view per step — phase sums across
// ranks, per-rank announce lag, and the derived dominant phase / dominant
// rank the live cockpit and tools/critical_path.py report.
//
// Cost discipline (same bar as the flight recorder): when disabled every
// site pays ONE relaxed atomic bool load and a branch.  When enabled a
// site pays a relaxed fetch_add on the current phase vector; only the
// once-per-step Advance takes a lock.  Standalone on purpose (no repo
// deps beyond the standard library) so it joins the selftest builds.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>

namespace hvdtpu {

// Phase indices of a step's breakdown vector.  Order is ABI: the CYCLE
// trailer, the JSON dumps and tools/critical_path.py all index by it.
enum StepPhase : int32_t {
  kPhaseNegotiation = 0,
  kPhaseFusion = 1,
  kPhaseRing = 2,
  kPhaseFence = 3,
  kPhaseIdle = 4,
  kStepPhases = 5,
};

// "negotiation_wait" / "fusion" / "ring" / "fence" / "idle" (or "?" out
// of range) — the names every JSON surface uses.
const char* StepPhaseName(int phase);

struct StepTraceGate {
  std::atomic<bool> enabled{false};
};
StepTraceGate& GlobalStepTraceGate();

// The one check every instrumentation site pays when tracing is off.
inline bool StepTraceOn() {
  return GlobalStepTraceGate().enabled.load(std::memory_order_relaxed);
}

// `slots` rounds up to a power of two (bounded); `postmortem_dir` ("" =
// no file dumps) gets a `{rank}` substitution like the flight recorder's;
// `world` sizes the coordinator's per-rank fleet vectors.
void InitStepTrace(bool enabled, int slots, const std::string& postmortem_dir,
                   int rank, int world);

// Attribute `us` microseconds to `phase` of the step currently forming.
// Callable from any thread (relaxed fetch_add).
void StepTraceAddPhaseUs(int phase, int64_t us);

// Tag the steps being formed with the data plane running them: -1
// unknown, 0 eager, 1 gspmd (compiler-inserted collectives).  Sticky
// until changed — the optimizer notes it once per trace, not per step.
// Closed steps carry the tag as a trailing element of their dump row and
// fleet records inherit the coordinator's current tag, so
// tools/critical_path.py and the cockpit can attribute steps to a plane.
void StepTraceNotePlane(int plane);

// Close the forming step into the ring and start `step_id`.  Workers call
// it when the RESPONSES trailer's step id moves past their own; the
// coordinator when a cycle ships real work.  Ids must be monotonic;
// stale/equal ids are ignored.
void StepTraceAdvance(int64_t step_id);
int64_t StepTraceCurrentStep();

// Snapshot of the most recently completed step for the CYCLE trailer:
// false until a first step completes.  `phase_us` must hold kStepPhases.
bool StepTraceLastCompleted(int64_t* step_id, int64_t* phase_us);

// Coordinator-side fleet aggregation, fed from the CYCLE trailers (phase
// snapshots) and the announce path (per-rank lag, attributed to the step
// the coordinator is currently forming).
void StepTraceFleetPhases(int rank, int64_t step_id, const int64_t* phase_us);
void StepTraceFleetLagUs(int rank, int64_t lag_us);

// Cumulative fleet phase totals since init (every phase vector ever fed
// to StepTraceFleetPhases, summed) — the goodput denominator
// (fleet_telemetry.cc).  `out` must hold kStepPhases; zeros when tracing
// is off or nothing reported yet.
void StepTraceFleetPhaseTotals(int64_t* out);

// Attribution for the sentinel: the dominant phase / rank of the newest
// fleet record any rank has reported into.  False when no fleet data
// arrived (then outputs are untouched).
bool StepTraceFleetDominant(int64_t* step_id, int* phase, int* rank);

// Majority-vote attribution over the newest `window` complete fleet
// records: per-step dominant-rank readings are noisy (an announce lag can
// land on the neighbouring forming step and blame a victim waiting in
// negotiation), so the sentinel votes across a short window instead of
// trusting one step.  -1 when no fleet record carries an attribution.
int StepTraceFleetDominantRecentRank(int window);

// Full dump: {"schema":"steptrace-v1","rank","world","phases",
// "steps":[[step,start_us,end_us,<5 phase us>],...],"fleet":[{...}]}.
// The fleet array is non-empty only where fleet data arrived (rank 0).
std::string StepTraceDumpJson();

// Atomic write-then-rename to <postmortem_dir>/steptrace.<rank>.json; a
// no-op without a postmortem dir.  Not async-signal-safe (takes the ring
// lock) — called at clean shutdown and from abort paths, never from
// signal handlers.
void StepTraceDumpToFile();

void ResetStepTraceForTest();

}  // namespace hvdtpu
