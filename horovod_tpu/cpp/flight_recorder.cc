#include "flight_recorder.h"

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <string.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <initializer_list>
#include <mutex>

namespace hvdtpu {

namespace {

constexpr int kMaxFlightThreads = 64;
constexpr int kDefaultSlots = 4096;
constexpr int kMinSlots = 64;
constexpr int kMaxSlots = 1 << 20;
constexpr int kMaxPath = 768;

// Static legend so the dump paths never format strings at crash time.
// Keep in sync with FlightType in flight_recorder.h.
const char kFlightTypesLegend[] =
    "{\"1\":\"ctrl_send\",\"2\":\"ctrl_recv\",\"3\":\"rendezvous\","
    "\"4\":\"verdict\",\"5\":\"ring_hop\",\"6\":\"wire_codec\","
    "\"7\":\"shm_fence\",\"8\":\"shm_map\",\"9\":\"tree_aggregate\","
    "\"10\":\"fault_trip\",\"11\":\"abort\",\"12\":\"digest\","
    "\"13\":\"autopilot\",\"14\":\"migrate\",\"15\":\"sentinel\","
    "\"16\":\"hloinspect\"}";

// One ring slot.  Four atomics (not a raw struct) so a dump racing a
// record is a data-race-free torn read at worst — the consumer sorts by
// seq and tolerates one inconsistent tail event.
struct Slot {
  std::atomic<int64_t> ts_us{0};
  std::atomic<uint64_t> seq{0};
  // type(16) << 48 | tid(16) << 32 | (uint32_t)a
  std::atomic<uint64_t> meta{0};
  std::atomic<int64_t> b{0};
};

struct ThreadRing {
  std::atomic<Slot*> ring{nullptr};
  std::atomic<uint64_t> head{0};  // total events ever recorded here
};

struct State {
  std::atomic<uint64_t> seq{0};
  std::atomic<int> nthreads{0};
  // Bumped by ResetFlightRecorderForTest so threads with a cached slot
  // index re-register instead of touching a freed ring.
  std::atomic<uint32_t> epoch{1};
  std::atomic<uint32_t> mask{kDefaultSlots - 1};
  std::atomic<int> slots{kDefaultSlots};
  std::atomic<int> rank{0};
  ThreadRing threads[kMaxFlightThreads];
  // Fixed buffers: the signal-handler dump may not allocate.
  char dump_path[kMaxPath] = {0};
  char tmp_path[kMaxPath] = {0};
  char postmortem_dir[kMaxPath] = {0};
  char host[128] = {0};
  std::atomic<bool> dumping{false};
  std::mutex init_mu;
  bool handlers_installed = false;
};

State& S() {
  // Never destroyed, and allocated exactly once — at init time: handlers
  // install strictly after the first S() call, so the signal path only
  // ever takes the already-initialized fast path.
  // lint: sigsafe-ok(one-time init allocation precedes handler install)
  static State* s = new State();
  return *s;
}

int64_t NowUs() {
  struct timespec ts;
  ::clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

// Claims (once per epoch) this thread's ring slot; -1 when the table is
// full.  The ring is allocated here, outside any record hot path.
int ThreadSlot() {
  static thread_local uint32_t cached_epoch = 0;
  static thread_local int cached_idx = -1;
  State& s = S();
  uint32_t ep = s.epoch.load(std::memory_order_acquire);
  if (cached_epoch == ep) return cached_idx;
  int idx = s.nthreads.fetch_add(1, std::memory_order_relaxed);
  if (idx >= kMaxFlightThreads) {
    s.nthreads.store(kMaxFlightThreads, std::memory_order_relaxed);
    cached_epoch = ep;
    cached_idx = -1;
    return -1;
  }
  Slot* ring = new Slot[s.slots.load(std::memory_order_relaxed)];
  s.threads[idx].head.store(0, std::memory_order_relaxed);
  s.threads[idx].ring.store(ring, std::memory_order_release);
  cached_epoch = ep;
  cached_idx = idx;
  return idx;
}

// Buffered fd writer using only async-signal-safe calls (write) and
// hand-rolled integer formatting.
struct SafeWriter {
  int fd = -1;
  char buf[4096];
  size_t len = 0;

  void Flush() {
    size_t off = 0;
    while (off < len) {
      ssize_t w = ::write(fd, buf + off, len - off);
      if (w < 0) {
        if (errno == EINTR) continue;
        break;
      }
      off += static_cast<size_t>(w);
    }
    len = 0;
  }
  void Raw(const char* p, size_t n) {
    while (n > 0) {
      if (len == sizeof(buf)) Flush();
      size_t take = sizeof(buf) - len;
      if (take > n) take = n;
      ::memcpy(buf + len, p, take);
      len += take;
      p += take;
      n -= take;
    }
  }
  void Str(const char* sz) { Raw(sz, ::strlen(sz)); }
  void U64(unsigned long long u) {
    char tmp[24];
    int i = 24;
    if (u == 0) tmp[--i] = '0';
    while (u) {
      tmp[--i] = static_cast<char>('0' + u % 10);
      u /= 10;
    }
    Raw(tmp + i, 24 - i);
  }
  void I64(long long v) {
    if (v < 0) {
      Str("-");
      U64(static_cast<unsigned long long>(-(v + 1)) + 1);
    } else {
      U64(static_cast<unsigned long long>(v));
    }
  }
};

void WriteDumpTo(SafeWriter& w) {
  State& s = S();
  w.Str("{\"rank\":");
  w.I64(s.rank.load(std::memory_order_relaxed));
  w.Str(",\"host\":\"");
  w.Str(s.host);
  w.Str("\",\"slots\":");
  w.I64(s.slots.load(std::memory_order_relaxed));
  w.Str(",\"dropped\":");
  w.I64(FlightDropped());
  w.Str(",\"types\":");
  w.Str(kFlightTypesLegend);
  w.Str(",\"events\":[");
  uint32_t mask = s.mask.load(std::memory_order_relaxed);
  int nt = std::min(s.nthreads.load(std::memory_order_acquire),
                    kMaxFlightThreads);
  bool first = true;
  for (int t = 0; t < nt; ++t) {
    Slot* ring = s.threads[t].ring.load(std::memory_order_acquire);
    if (ring == nullptr) continue;
    uint64_t head = s.threads[t].head.load(std::memory_order_acquire);
    uint64_t n = head;
    if (n > static_cast<uint64_t>(mask) + 1) n = mask + 1;
    for (uint64_t k = head - n; k < head; ++k) {
      Slot& sl = ring[k & mask];
      uint64_t meta = sl.meta.load(std::memory_order_relaxed);
      if (!first) w.Str(",");
      first = false;
      w.Str("[");
      w.I64(sl.ts_us.load(std::memory_order_relaxed));
      w.Str(",");
      w.U64(sl.seq.load(std::memory_order_relaxed));
      w.Str(",");
      w.I64(static_cast<int>(meta >> 48));
      w.Str(",");
      w.I64(static_cast<int>((meta >> 32) & 0xffff));
      w.Str(",");
      w.I64(static_cast<int32_t>(static_cast<uint32_t>(meta & 0xffffffffu)));
      w.Str(",");
      w.I64(sl.b.load(std::memory_order_relaxed));
      w.Str("]");
    }
  }
  w.Str("]}");
}

void FatalSignalHandler(int sig) {
  FlightDumpToFile();
  // SA_RESETHAND restored the default disposition; re-raise so the
  // process still dies with the original signal (and core-dump rules).
  ::raise(sig);
}

void InstallFatalHandlers() {
  State& s = S();
  if (s.handlers_installed) return;
  s.handlers_installed = true;
  struct sigaction sa;
  ::memset(&sa, 0, sizeof(sa));
  sa.sa_handler = FatalSignalHandler;
  sa.sa_flags = SA_RESETHAND | SA_NODEFER;
  ::sigemptyset(&sa.sa_mask);
  for (int sig : {SIGSEGV, SIGBUS, SIGABRT, SIGFPE, SIGILL}) {
    struct sigaction old;
    if (::sigaction(sig, nullptr, &old) != 0) continue;
    // Never trample an existing handler (sanitizer runtimes, embedders,
    // test harnesses): only claim signals at their default disposition.
    if ((old.sa_flags & SA_SIGINFO) == 0 && old.sa_handler == SIG_DFL) {
      ::sigaction(sig, &sa, nullptr);
    }
  }
}

void CollectEvents(std::vector<FlightEvent>* out) {
  State& s = S();
  uint32_t mask = s.mask.load(std::memory_order_relaxed);
  int nt = std::min(s.nthreads.load(std::memory_order_acquire),
                    kMaxFlightThreads);
  for (int t = 0; t < nt; ++t) {
    Slot* ring = s.threads[t].ring.load(std::memory_order_acquire);
    if (ring == nullptr) continue;
    uint64_t head = s.threads[t].head.load(std::memory_order_acquire);
    uint64_t n = head;
    if (n > static_cast<uint64_t>(mask) + 1) n = mask + 1;
    for (uint64_t k = head - n; k < head; ++k) {
      Slot& sl = ring[k & mask];
      uint64_t meta = sl.meta.load(std::memory_order_relaxed);
      FlightEvent ev;
      ev.ts_us = sl.ts_us.load(std::memory_order_relaxed);
      ev.seq = sl.seq.load(std::memory_order_relaxed);
      ev.type = static_cast<int32_t>(meta >> 48);
      ev.tid = static_cast<int32_t>((meta >> 32) & 0xffff);
      ev.a = static_cast<int32_t>(static_cast<uint32_t>(meta & 0xffffffffu));
      ev.b = sl.b.load(std::memory_order_relaxed);
      out->push_back(ev);
    }
  }
  std::sort(out->begin(), out->end(),
            [](const FlightEvent& x, const FlightEvent& y) {
              return x.seq < y.seq;
            });
}

}  // namespace

FlightRecorderState& GlobalFlightRecorder() {
  static FlightRecorderState* st = new FlightRecorderState();
  return *st;
}

void InitFlightRecorder(bool enabled, int slots,
                        const std::string& postmortem_dir, int rank) {
  State& s = S();
  std::lock_guard<std::mutex> l(s.init_mu);
  if (slots <= 0) slots = kDefaultSlots;
  int p = kMinSlots;
  while (p < slots && p < kMaxSlots) p <<= 1;
  s.slots.store(p, std::memory_order_relaxed);
  s.mask.store(static_cast<uint32_t>(p - 1), std::memory_order_relaxed);
  s.rank.store(rank, std::memory_order_relaxed);
  if (::gethostname(s.host, sizeof(s.host) - 1) != 0) {
    ::strncpy(s.host, "unknown", sizeof(s.host) - 1);
  }
  s.host[sizeof(s.host) - 1] = 0;
  for (char* c = s.host; *c; ++c) {
    // The host lands inside a JSON string built at crash time with no
    // escaper — keep it trivially safe.
    if (*c == '"' || *c == '\\' || static_cast<unsigned char>(*c) < 0x20) {
      *c = '_';
    }
  }
  std::string dir = postmortem_dir;
  auto pos = dir.find("{rank}");
  if (pos != std::string::npos) dir.replace(pos, 6, std::to_string(rank));
  s.postmortem_dir[0] = 0;
  s.dump_path[0] = 0;
  s.tmp_path[0] = 0;
  if (!dir.empty()) {
    ::mkdir(dir.c_str(), 0777);  // best-effort; EEXIST is the common case
    std::string path = dir + "/flight." + std::to_string(rank) + ".json";
    std::string tmp = path + ".tmp";
    if (tmp.size() < kMaxPath) {
      ::strncpy(s.postmortem_dir, dir.c_str(), kMaxPath - 1);
      ::strncpy(s.dump_path, path.c_str(), kMaxPath - 1);
      ::strncpy(s.tmp_path, tmp.c_str(), kMaxPath - 1);
    }
  }
  GlobalFlightRecorder().enabled.store(enabled, std::memory_order_relaxed);
  if (enabled && s.dump_path[0] != 0) InstallFatalHandlers();
}

void FlightRecord(int32_t type, int32_t a, int64_t b) {
  State& s = S();
  int idx = ThreadSlot();
  if (idx < 0) return;
  ThreadRing& tr = s.threads[idx];
  Slot* ring = tr.ring.load(std::memory_order_relaxed);
  if (ring == nullptr) return;
  uint64_t h = tr.head.load(std::memory_order_relaxed);
  Slot& sl = ring[h & s.mask.load(std::memory_order_relaxed)];
  uint64_t seq = s.seq.fetch_add(1, std::memory_order_relaxed);
  sl.ts_us.store(NowUs(), std::memory_order_relaxed);
  sl.seq.store(seq, std::memory_order_relaxed);
  sl.meta.store((static_cast<uint64_t>(static_cast<uint16_t>(type)) << 48) |
                    (static_cast<uint64_t>(static_cast<uint16_t>(idx)) << 32) |
                    static_cast<uint32_t>(a),
                std::memory_order_relaxed);
  sl.b.store(b, std::memory_order_relaxed);
  tr.head.store(h + 1, std::memory_order_release);
}

void FlightTail(int n, std::vector<FlightEvent>* out) {
  out->clear();
  if (n <= 0) return;
  std::vector<FlightEvent> all;
  CollectEvents(&all);
  size_t keep = std::min(static_cast<size_t>(n), all.size());
  out->assign(all.end() - keep, all.end());
}

std::string FlightDumpJson() {
  State& s = S();
  std::vector<FlightEvent> all;
  CollectEvents(&all);
  std::string out = "{\"rank\":" +
                    std::to_string(s.rank.load(std::memory_order_relaxed)) +
                    ",\"host\":\"" + s.host + "\",\"slots\":" +
                    std::to_string(s.slots.load(std::memory_order_relaxed)) +
                    ",\"dropped\":" + std::to_string(FlightDropped()) +
                    ",\"types\":" + kFlightTypesLegend + ",\"events\":[";
  bool first = true;
  for (const auto& ev : all) {
    if (!first) out += ",";
    first = false;
    out += "[" + std::to_string(ev.ts_us) + "," + std::to_string(ev.seq) +
           "," + std::to_string(ev.type) + "," + std::to_string(ev.tid) +
           "," + std::to_string(ev.a) + "," + std::to_string(ev.b) + "]";
  }
  out += "]}";
  return out;
}

void FlightDumpToFile() {
  State& s = S();
  if (s.dump_path[0] == 0) return;
  bool expected = false;
  // acquire on the winning latch: the dumper must observe every ring
  // write published (release) by recorder threads before it started;
  // failure needs no ordering (the loser just returns).
  if (!s.dumping.compare_exchange_strong(expected, true,
                                         std::memory_order_acquire,
                                         std::memory_order_relaxed)) {
    return;
  }
  int fd = ::open(s.tmp_path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd >= 0) {
    SafeWriter w;
    w.fd = fd;
    WriteDumpTo(w);
    w.Flush();
    ::close(fd);
    ::rename(s.tmp_path, s.dump_path);
  }
  // release: the completed dump (file rename included) must be visible
  // before the next dumper can win the latch above.
  s.dumping.store(false, std::memory_order_release);
}

std::string FlightDumpPath() { return S().dump_path; }

std::string FlightPostmortemDir() { return S().postmortem_dir; }

const char* FlightTypesLegend() { return kFlightTypesLegend; }

int64_t FlightDropped() {
  State& s = S();
  uint64_t cap = static_cast<uint64_t>(s.mask.load(std::memory_order_relaxed)) + 1;
  int nt = std::min(s.nthreads.load(std::memory_order_acquire),
                    kMaxFlightThreads);
  int64_t dropped = 0;
  for (int t = 0; t < nt; ++t) {
    uint64_t head = s.threads[t].head.load(std::memory_order_relaxed);
    if (head > cap) dropped += static_cast<int64_t>(head - cap);
  }
  return dropped;
}

void ResetFlightRecorderForTest() {
  State& s = S();
  std::lock_guard<std::mutex> l(s.init_mu);
  GlobalFlightRecorder().enabled.store(false, std::memory_order_relaxed);
  // Invalidate every thread's cached slot BEFORE freeing rings; callers
  // guarantee no record is in flight.
  s.epoch.fetch_add(1, std::memory_order_acq_rel);
  int nt = std::min(s.nthreads.load(std::memory_order_acquire),
                    kMaxFlightThreads);
  for (int t = 0; t < nt; ++t) {
    Slot* ring = s.threads[t].ring.exchange(nullptr,
                                            std::memory_order_acq_rel);
    delete[] ring;
    s.threads[t].head.store(0, std::memory_order_relaxed);
  }
  s.nthreads.store(0, std::memory_order_relaxed);
  s.seq.store(0, std::memory_order_relaxed);
  s.dump_path[0] = 0;
  s.tmp_path[0] = 0;
  s.postmortem_dir[0] = 0;
  s.dumping.store(false, std::memory_order_relaxed);
}

}  // namespace hvdtpu
