#include "fault_injection.h"

#include <fcntl.h>
#include <unistd.h>

#include <chrono>
#include <cstdlib>
#include <thread>
#include <vector>

#include "flight_recorder.h"
#include "logging.h"
#include "metrics.h"

namespace hvdtpu {

FaultInjector& GlobalFaultInjector() {
  // Leaked singleton (never destroyed): hook sites on detached threads may
  // run during process teardown, after static destructors would have fired.
  static FaultInjector* injector = new FaultInjector();
  return *injector;
}

namespace {

const char* const kSiteNames[kNumFaultSites] = {
    "rendezvous-accept", "coordinator-recv", "ring-send",  "ring-recv",
    "shm-fence",         "frame-header",     "leader-recv", "super-recv"};

constexpr const char* kValidSites =
    "rendezvous-accept, coordinator-recv, ring-send, ring-recv, shm-fence, "
    "frame-header, leader-recv, super-recv";
constexpr const char* kValidActions =
    "drop, truncate, delay (arg = ms), corrupt-tag, die (arg = optional "
    "flag-file path)";

std::vector<std::string> Split(const std::string& s, char sep) {
  std::vector<std::string> out;
  size_t start = 0;
  while (true) {
    size_t pos = s.find(sep, start);
    if (pos == std::string::npos) {
      out.push_back(s.substr(start));
      return out;
    }
    out.push_back(s.substr(start, pos - start));
    start = pos + 1;
  }
}

bool ParseSite(const std::string& s, FaultSite* out) {
  for (int i = 0; i < kNumFaultSites; ++i) {
    if (s == kSiteNames[i]) {
      *out = static_cast<FaultSite>(i);
      return true;
    }
  }
  return false;
}

// '*' -> -1; else a non-negative decimal integer.
bool ParseStarInt(const std::string& s, int* out) {
  if (s == "*") {
    *out = -1;
    return true;
  }
  if (s.empty()) return false;
  char* end = nullptr;
  long long v = std::strtoll(s.c_str(), &end, 10);
  if (!end || *end != '\0' || v < 0 || v > 1 << 28) return false;
  *out = static_cast<int>(v);
  return true;
}

const char* ActionName(FaultAction a) {
  switch (a) {
    case FaultAction::kNone: return "none";
    case FaultAction::kDrop: return "drop";
    case FaultAction::kTruncate: return "truncate";
    case FaultAction::kDelay: return "delay";
    case FaultAction::kCorruptTag: return "corrupt-tag";
    case FaultAction::kDie: return "die";
  }
  return "?";
}

}  // namespace

const char* FaultSiteName(FaultSite site) {
  if (site < 0 || site >= kNumFaultSites) return "?";
  return kSiteNames[site];
}

std::string ParseFaultSpec(const std::string& spec,
                           std::deque<FaultRule>* rules) {
  for (const std::string& entry : Split(spec, ',')) {
    if (entry.empty()) continue;
    std::vector<std::string> f = Split(entry, ':');
    if (f.size() < 4) {
      return "fault spec entry '" + entry +
             "': expected site:cycle:rank:action[:arg]";
    }
    FaultSite site;
    if (!ParseSite(f[0], &site)) {
      return "fault spec entry '" + entry + "': unknown site '" + f[0] +
             "' (valid sites: " + kValidSites + ")";
    }
    int cycle, rank;
    if (!ParseStarInt(f[1], &cycle)) {
      return "fault spec entry '" + entry + "': cycle '" + f[1] +
             "' must be '*' or a non-negative hit index";
    }
    if (!ParseStarInt(f[2], &rank)) {
      return "fault spec entry '" + entry + "': rank '" + f[2] +
             "' must be '*' or a non-negative rank";
    }
    FaultAction action;
    if (f[3] == "drop") {
      action = FaultAction::kDrop;
    } else if (f[3] == "truncate") {
      action = FaultAction::kTruncate;
    } else if (f[3] == "delay") {
      action = FaultAction::kDelay;
    } else if (f[3] == "corrupt-tag") {
      action = FaultAction::kCorruptTag;
    } else if (f[3] == "die") {
      action = FaultAction::kDie;
    } else {
      return "fault spec entry '" + entry + "': unknown action '" + f[3] +
             "' (valid actions: " + kValidActions + ")";
    }
    // Rejoin fields[4:] on ':' so die's flag-file path may contain colons.
    std::string arg_str;
    for (size_t i = 4; i < f.size(); ++i) {
      if (i > 4) arg_str += ':';
      arg_str += f[i];
    }
    long long arg = 0;
    if (action == FaultAction::kDelay) {
      char* end = nullptr;
      arg = arg_str.empty() ? -1 : std::strtoll(arg_str.c_str(), &end, 10);
      if (arg_str.empty() || !end || *end != '\0' || arg < 0) {
        return "fault spec entry '" + entry +
               "': delay requires a numeric millisecond arg (e.g. "
               "ring-send:*:1:delay:250)";
      }
    } else if (action != FaultAction::kDie && !arg_str.empty()) {
      return "fault spec entry '" + entry + "': action '" + f[3] +
             "' takes no arg";
    }
    if (rules) {
      rules->emplace_back();  // FaultRule holds an atomic: fill in place
      FaultRule& r = rules->back();
      r.site = site;
      r.cycle = cycle;
      r.rank = rank;
      r.action = action;
      r.arg = arg;
      r.arg_str = arg_str;
    }
  }
  return "";
}

std::string InitFaultInjection() {
  FaultInjector& inj = GlobalFaultInjector();
  // Re-init in the same process (post-abort hvd.init) starts from a clean
  // slate so hit indices stay deterministic.  Safe: called from hvd_init
  // before the background/executor threads exist.
  inj.enabled.store(false, std::memory_order_relaxed);
  inj.rules.clear();
  for (auto& site_hits : inj.hits) {
    for (auto& h : site_hits) h.store(0, std::memory_order_relaxed);
  }
  const char* env = std::getenv("HOROVOD_FAULT_INJECT");
  if (!env || !*env) return "";
  std::string err = ParseFaultSpec(env, &inj.rules);
  if (!err.empty()) return err;
  if (!inj.rules.empty()) {
    inj.enabled.store(true, std::memory_order_relaxed);
    HVD_LOG(WARNING) << "fault injection enabled: " << env;
  }
  return "";
}

FaultAction FaultCheck(FaultSite site, int rank, long long* arg) {
  FaultInjector& inj = GlobalFaultInjector();
  int slot = rank;
  if (slot < 0) slot = 0;
  if (slot >= FaultInjector::kMaxTrackedRanks) {
    slot = FaultInjector::kMaxTrackedRanks - 1;
  }
  const int64_t hit =
      inj.hits[site][slot].fetch_add(1, std::memory_order_relaxed);
  for (auto& rule : inj.rules) {
    if (rule.site != site) continue;
    if (rule.rank >= 0 && rule.rank != rank) continue;
    if (rule.action == FaultAction::kNone) continue;
    if (rule.cycle >= 0) {
      if (hit != rule.cycle) continue;
      bool expected = false;
      // relaxed both ways: the once-latch needs only RMW atomicity
      // (exactly one winner); no payload is published through the flag.
      if (!rule.fired.compare_exchange_strong(expected, true,
                                              std::memory_order_relaxed,
                                              std::memory_order_relaxed)) {
        continue;
      }
    }
    if (rule.action == FaultAction::kDie && !rule.arg_str.empty()) {
      // Once-latch: fire only if we can create the flag file.  A respawned
      // elastic worker finds it already present and keeps running.
      int fd = ::open(rule.arg_str.c_str(), O_CREAT | O_EXCL | O_WRONLY,
                      0644);
      if (fd < 0) continue;
      ::close(fd);
    }
    if (MetricsOn()) {
      GlobalMetrics().faults_injected_total.fetch_add(
          1, std::memory_order_relaxed);
    }
    HVD_LOG(WARNING) << "fault injection: " << ActionName(rule.action)
                     << " at " << FaultSiteName(site) << " rank " << rank
                     << " hit " << hit;
    if (FlightOn()) {
      FlightRecord(kFlightFaultTrip, static_cast<int32_t>(site),
                   static_cast<int64_t>(rule.action));
    }
    switch (rule.action) {
      case FaultAction::kDelay:
        std::this_thread::sleep_for(std::chrono::milliseconds(rule.arg));
        return FaultAction::kDelay;
      case FaultAction::kDie:
        // The injected death is the postmortem's whole subject: leave the
        // black box behind before vanishing.
        if (FlightOn()) FlightDumpToFile();
        _exit(137);
      default:
        if (arg) *arg = rule.arg;
        return rule.action;
    }
  }
  return FaultAction::kNone;
}

}  // namespace hvdtpu
