// Wire-compression codecs for the cross-host chunk ring (docs/compression.md).
//
// Four codecs, all fp32-in / fp32-out with full-precision accumulation on
// the receive side (the ring never adds quantized values together):
//
//  - bf16: truncate each fp32 to its high 16 bits.  The exponent field is
//    copied exactly, so no bf16-encodable magnitude can overflow; values
//    already representable in bf16 round-trip bit-exactly.
//  - int8: per-256-element block scale (EQuARX-style).  Block layout on the
//    wire is [4-byte little-endian fp32 scale][one int8 per element]; the
//    last block of a tensor may be short.  scale = max|x|/127, so the
//    per-element error is bounded by scale/2 (round-to-nearest).
//  - int4: the same 256-element block scale with 4-bit codes, two per byte
//    (element 2i in the low nibble, 2i+1 in the high nibble).  scale =
//    max|x|/7; per-element error bounded by scale/2.  ~0.13x the raw bytes.
//  - int8g: two-level scales (EQuARX's dynamic block scaling).  One fp32
//    scale per 4096-element GROUP (kWireGroup) plus one uint8 sub-scale
//    per 256-element block: group scale = max|group|/127, sub-scale byte
//    s = min(255, nearbyint(max|block|/max|group| * kWireSubDenom)),
//    effective block scale = group_scale * s/kWireSubDenom.  The sub-scale
//    denominator is a power of two (256) on purpose: scaling by 2^-8
//    commutes exactly with fp32 rounding, so the effective scale is
//    bit-identical no matter how encoder/decoder (or a compiled traced
//    mirror) associate the multiply — required for cross-rank bit-identity
//    when encoded bytes are forwarded verbatim.  Group layout on the wire
//    is [4-byte fp32 group scale][one sub-scale byte per block][one int8
//    code per element].  Fine-grained per-block scaling at ~1/4 of int8's
//    scale overhead.
//
// The encoded stream is position-independent per element: byte offsets are
// pure functions of the element index, so a receiver can decode any prefix
// of elements as chunks arrive (WireDecodableElems / WireDecodeRange) and
// the allgather phase can forward encoded bytes verbatim for cross-rank
// bit-identity.
//
// Shared edge semantics for every block-scaled codec: the max|x| scan uses
// `a > maxabs`, so NaN elements never win (an all-NaN block/group keeps
// scale 0 and encodes zeros); a block/group whose max is inf stores a
// non-finite scale with zero codes (decode yields NaN via inf*0 rather
// than inventing values); a NaN element inside an otherwise-finite block
// clamps to the positive code bound (std::min/std::max operand order).
//
// Header-only so the selftests link it without extra objects.

#ifndef HVD_TPU_WIRE_CODEC_H_
#define HVD_TPU_WIRE_CODEC_H_

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>

namespace hvdtpu {

enum class WireCodec : int32_t {
  kNone = 0,
  kBf16 = 1,
  kInt8 = 2,
  kInt4 = 3,
  kInt8g = 4,
};

// Block geometry: one scale record per 256 elements (int8/int4), one fp32
// group scale per 4096 elements with uint8 sub-scales (int8g), 4-bit codes
// clamp to +/-kWireInt4Max.  Mirrored as traced math by
// horovod_tpu/ops/quantize.py (WIRE_BLOCK / WIRE_SCALE_BYTES / WIRE_GROUP /
// WIRE_INT4_MAX / WIRE_CODEC_IDS) for the device-plane quantized ring;
// tools/hvd_lint.py enforces the two stay in sync.
constexpr int64_t kWireBlock = 256;
constexpr int64_t kWireScaleBytes = 4;
constexpr int64_t kWireGroup = 4096;
constexpr int64_t kWireInt4Max = 7;
constexpr int64_t kWireSubDenom = 256;

// Encoded size in bytes of `count` fp32 elements under `codec`.
inline int64_t WireEncodedBytes(WireCodec codec, int64_t count) {
  switch (codec) {
    case WireCodec::kBf16:
      return 2 * count;
    case WireCodec::kInt8: {
      const int64_t blocks = (count + kWireBlock - 1) / kWireBlock;
      return blocks * kWireScaleBytes + count;
    }
    case WireCodec::kInt4: {
      const int64_t blocks = (count + kWireBlock - 1) / kWireBlock;
      return blocks * kWireScaleBytes + (count + 1) / 2;
    }
    case WireCodec::kInt8g: {
      const int64_t groups = (count + kWireGroup - 1) / kWireGroup;
      const int64_t blocks = (count + kWireBlock - 1) / kWireBlock;
      return groups * kWireScaleBytes + blocks + count;
    }
    case WireCodec::kNone:
    default:
      return 4 * count;
  }
}

namespace wire_internal {

// NaN-proof max|x| over [src, src+n): `a > maxabs` never lets NaN win.
inline float MaxAbs(const float* src, int64_t n) {
  float maxabs = 0.0f;
  for (int64_t i = 0; i < n; ++i) {
    const float a = std::fabs(src[i]);
    if (a > maxabs) maxabs = a;
  }
  return maxabs;
}

}  // namespace wire_internal

// Encode `count` fp32 elements from `src` into `dst`
// (WireEncodedBytes(codec, count) bytes).
inline void WireEncode(WireCodec codec, const float* src, int64_t count,
                       char* dst) {
  if (codec == WireCodec::kBf16) {
    uint16_t* out = reinterpret_cast<uint16_t*>(dst);
    for (int64_t i = 0; i < count; ++i) {
      uint32_t bits;
      std::memcpy(&bits, src + i, 4);
      out[i] = static_cast<uint16_t>(bits >> 16);
    }
    return;
  }
  if (codec == WireCodec::kInt8) {
    for (int64_t b0 = 0; b0 < count; b0 += kWireBlock) {
      const int64_t n = std::min(kWireBlock, count - b0);
      const float maxabs = wire_internal::MaxAbs(src + b0, n);
      const float scale = maxabs / 127.0f;
      std::memcpy(dst, &scale, kWireScaleBytes);
      int8_t* q = reinterpret_cast<int8_t*>(dst + kWireScaleBytes);
      if (scale > 0.0f && std::isfinite(scale)) {
        const float inv = 1.0f / scale;
        for (int64_t i = 0; i < n; ++i) {
          const float v = std::nearbyintf(src[b0 + i] * inv);
          q[i] = static_cast<int8_t>(
              std::max(-127.0f, std::min(127.0f, v)));
        }
      } else {
        // All-zero block (or non-finite scale from inf/nan input: encode
        // zeros rather than propagate garbage — matching the clamp above).
        std::memset(q, 0, static_cast<size_t>(n));
      }
      dst += kWireScaleBytes + n;
    }
    return;
  }
  if (codec == WireCodec::kInt4) {
    const float qmax = static_cast<float>(kWireInt4Max);
    for (int64_t b0 = 0; b0 < count; b0 += kWireBlock) {
      const int64_t n = std::min(kWireBlock, count - b0);
      const float maxabs = wire_internal::MaxAbs(src + b0, n);
      const float scale = maxabs / qmax;
      std::memcpy(dst, &scale, kWireScaleBytes);
      uint8_t* q = reinterpret_cast<uint8_t*>(dst + kWireScaleBytes);
      const int64_t nbytes = (n + 1) / 2;
      if (scale > 0.0f && std::isfinite(scale)) {
        const float inv = 1.0f / scale;
        std::memset(q, 0, static_cast<size_t>(nbytes));
        for (int64_t i = 0; i < n; ++i) {
          const float v = std::nearbyintf(src[b0 + i] * inv);
          const int code = static_cast<int>(std::max(-qmax, std::min(qmax, v)));
          const uint8_t nib = static_cast<uint8_t>(code) & 0x0F;
          q[i / 2] |= (i & 1) ? static_cast<uint8_t>(nib << 4) : nib;
        }
      } else {
        std::memset(q, 0, static_cast<size_t>(nbytes));
      }
      dst += kWireScaleBytes + nbytes;
    }
    return;
  }
  if (codec == WireCodec::kInt8g) {
    for (int64_t g0 = 0; g0 < count; g0 += kWireGroup) {
      const int64_t gn = std::min(kWireGroup, count - g0);
      const int64_t nblk = (gn + kWireBlock - 1) / kWireBlock;
      const float gmax = wire_internal::MaxAbs(src + g0, gn);
      const float gscale = gmax / 127.0f;
      std::memcpy(dst, &gscale, kWireScaleBytes);
      uint8_t* sub = reinterpret_cast<uint8_t*>(dst + kWireScaleBytes);
      int8_t* q = reinterpret_cast<int8_t*>(dst + kWireScaleBytes + nblk);
      if (gscale > 0.0f && std::isfinite(gscale)) {
        for (int64_t b = 0; b < nblk; ++b) {
          const int64_t b0 = b * kWireBlock;
          const int64_t n = std::min(kWireBlock, gn - b0);
          const float bmax = wire_internal::MaxAbs(src + g0 + b0, n);
          // bmax <= gmax, so the ratio is in [0, 1]; the block holding
          // gmax rounds to kWireSubDenom and clamps to 255 (its max
          // element still encodes as code 127 after round).
          const float ratio = bmax / gmax;
          const uint8_t s = static_cast<uint8_t>(std::min(
              255.0f,
              std::nearbyintf(ratio * static_cast<float>(kWireSubDenom))));
          sub[b] = s;
          if (s > 0) {
            const float eff =
                gscale * (static_cast<float>(s) /
                          static_cast<float>(kWireSubDenom));
            const float inv = 1.0f / eff;
            for (int64_t i = 0; i < n; ++i) {
              const float v = std::nearbyintf(src[g0 + b0 + i] * inv);
              q[b0 + i] = static_cast<int8_t>(
                  std::max(-127.0f, std::min(127.0f, v)));
            }
          } else {
            // All-zero / all-NaN block inside a finite group, or a block
            // whose max rounds below the sub-scale resolution: codes 0.
            std::memset(q + b0, 0, static_cast<size_t>(n));
          }
        }
      } else {
        std::memset(sub, 0, static_cast<size_t>(nblk));
        std::memset(q, 0, static_cast<size_t>(gn));
      }
      dst += kWireScaleBytes + nblk + gn;
    }
    return;
  }
  std::memcpy(dst, src, static_cast<size_t>(4 * count));
}

// Decode elements [elem_lo, elem_hi) of an encoded stream that carries
// `count` elements total.  `src` points at the START of the encoded stream
// (not at elem_lo); `dst` receives elem_hi - elem_lo fp32 values.
inline void WireDecodeRange(WireCodec codec, const char* src, int64_t count,
                            int64_t elem_lo, int64_t elem_hi, float* dst) {
  if (codec == WireCodec::kBf16) {
    const uint16_t* in = reinterpret_cast<const uint16_t*>(src) + elem_lo;
    for (int64_t i = 0; i < elem_hi - elem_lo; ++i) {
      const uint32_t bits = static_cast<uint32_t>(in[i]) << 16;
      std::memcpy(dst + i, &bits, 4);
    }
    return;
  }
  if (codec == WireCodec::kInt8) {
    for (int64_t e = elem_lo; e < elem_hi;) {
      const int64_t blk = e / kWireBlock;
      const int64_t in_blk = e % kWireBlock;
      const int64_t blk_end = std::min((blk + 1) * kWireBlock, elem_hi);
      const char* base =
          src + blk * (kWireScaleBytes + kWireBlock) + kWireScaleBytes;
      float scale;
      std::memcpy(&scale,
                  src + blk * (kWireScaleBytes + kWireBlock), 4);
      const int8_t* q = reinterpret_cast<const int8_t*>(base) + in_blk;
      for (int64_t i = 0; e + i < blk_end; ++i) {
        dst[e + i - elem_lo] = scale * static_cast<float>(q[i]);
      }
      e = blk_end;
    }
    return;
  }
  if (codec == WireCodec::kInt4) {
    const int64_t per_block = kWireScaleBytes + kWireBlock / 2;
    for (int64_t e = elem_lo; e < elem_hi;) {
      const int64_t blk = e / kWireBlock;
      const int64_t blk_end = std::min((blk + 1) * kWireBlock, elem_hi);
      float scale;
      std::memcpy(&scale, src + blk * per_block, 4);
      const uint8_t* q = reinterpret_cast<const uint8_t*>(
          src + blk * per_block + kWireScaleBytes);
      for (int64_t i = e; i < blk_end; ++i) {
        const int64_t in_blk = i % kWireBlock;
        const uint8_t b = q[in_blk / 2];
        const int nib = (in_blk & 1) ? (b >> 4) & 0x0F : b & 0x0F;
        // Sign-extend the nibble: [-8, 7] (codes only use [-7, 7]).
        const int code = (nib ^ 8) - 8;
        dst[i - elem_lo] = scale * static_cast<float>(code);
      }
      e = blk_end;
    }
    return;
  }
  if (codec == WireCodec::kInt8g) {
    for (int64_t e = elem_lo; e < elem_hi;) {
      const int64_t grp = e / kWireGroup;
      const int64_t g0 = grp * kWireGroup;
      const int64_t gn = std::min(kWireGroup, count - g0);
      const int64_t nblk = (gn + kWireBlock - 1) / kWireBlock;
      const int64_t grp_end = std::min(g0 + gn, elem_hi);
      // Only the LAST group of a stream may be short, so full-group
      // offsets stay pure functions of the element index.
      const char* base = src + grp * (kWireScaleBytes + kWireGroup / kWireBlock +
                                      kWireGroup);
      float gscale;
      std::memcpy(&gscale, base, 4);
      const uint8_t* sub =
          reinterpret_cast<const uint8_t*>(base + kWireScaleBytes);
      const int8_t* q =
          reinterpret_cast<const int8_t*>(base + kWireScaleBytes + nblk);
      for (int64_t i = e; i < grp_end; ++i) {
        const int64_t ig = i - g0;
        const float eff =
            gscale * (static_cast<float>(sub[ig / kWireBlock]) /
                      static_cast<float>(kWireSubDenom));
        dst[i - elem_lo] = eff * static_cast<float>(q[ig]);
      }
      e = grp_end;
    }
    return;
  }
  (void)count;
  std::memcpy(dst, src + 4 * elem_lo,
              static_cast<size_t>(4 * (elem_hi - elem_lo)));
}

// How many leading elements of a `total_elems`-element encoded stream are
// fully decodable once `bytes_received` prefix bytes have arrived.  Used by
// the ring's incremental consume path (chunk boundaries are byte-, not
// block-aligned).
inline int64_t WireDecodableElems(WireCodec codec, int64_t bytes_received,
                                  int64_t total_elems) {
  int64_t n;
  switch (codec) {
    case WireCodec::kBf16:
      n = bytes_received / 2;
      break;
    case WireCodec::kInt8: {
      const int64_t per_block = kWireScaleBytes + kWireBlock;
      const int64_t full = bytes_received / per_block;
      const int64_t rem = bytes_received % per_block;
      n = full * kWireBlock +
          std::max<int64_t>(0, rem - kWireScaleBytes);
      break;
    }
    case WireCodec::kInt4: {
      const int64_t per_block = kWireScaleBytes + kWireBlock / 2;
      const int64_t full = bytes_received / per_block;
      const int64_t rem = bytes_received % per_block;
      n = full * kWireBlock +
          std::max<int64_t>(0, (rem - kWireScaleBytes) * 2);
      break;
    }
    case WireCodec::kInt8g: {
      const int64_t per_group =
          kWireScaleBytes + kWireGroup / kWireBlock + kWireGroup;
      const int64_t full_groups = total_elems / kWireGroup;
      if (bytes_received >= full_groups * per_group) {
        // The prefix covers every complete group; the remainder lands in
        // the short tail group, whose header carries only as many
        // sub-scale bytes as it has blocks.
        const int64_t tail = total_elems - full_groups * kWireGroup;
        const int64_t nblk = (tail + kWireBlock - 1) / kWireBlock;
        const int64_t rem = bytes_received - full_groups * per_group;
        n = full_groups * kWireGroup +
            std::max<int64_t>(0, rem - (kWireScaleBytes + nblk));
      } else {
        const int64_t header = kWireScaleBytes + kWireGroup / kWireBlock;
        const int64_t full = bytes_received / per_group;
        const int64_t rem = bytes_received % per_group;
        n = full * kWireGroup + std::max<int64_t>(0, rem - header);
      }
      break;
    }
    case WireCodec::kNone:
    default:
      n = bytes_received / 4;
      break;
  }
  return std::min(n, total_elems);
}

}  // namespace hvdtpu

#endif  // HVD_TPU_WIRE_CODEC_H_
