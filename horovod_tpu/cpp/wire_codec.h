// Wire-compression codecs for the cross-host chunk ring (docs/compression.md).
//
// Two codecs, both fp32-in / fp32-out with full-precision accumulation on
// the receive side (the ring never adds quantized values together):
//
//  - bf16: truncate each fp32 to its high 16 bits.  The exponent field is
//    copied exactly, so no bf16-encodable magnitude can overflow; values
//    already representable in bf16 round-trip bit-exactly.
//  - int8: per-256-element block scale (EQuARX-style).  Block layout on the
//    wire is [4-byte little-endian fp32 scale][one int8 per element]; the
//    last block of a tensor may be short.  scale = max|x|/127, so the
//    per-element error is bounded by scale/2 (round-to-nearest).
//
// The encoded stream is position-independent per element: byte offsets are
// pure functions of the element index, so a receiver can decode any prefix
// of elements as chunks arrive (WireDecodableElems / WireDecodeRange) and
// the allgather phase can forward encoded bytes verbatim for cross-rank
// bit-identity.
//
// Header-only so the selftests link it without extra objects.

#ifndef HVD_TPU_WIRE_CODEC_H_
#define HVD_TPU_WIRE_CODEC_H_

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>

namespace hvdtpu {

enum class WireCodec : int32_t {
  kNone = 0,
  kBf16 = 1,
  kInt8 = 2,
};

// int8 block geometry: one fp32 scale per 256 elements.  Mirrored as
// traced math by horovod_tpu/ops/quantize.py (WIRE_BLOCK /
// WIRE_SCALE_BYTES / WIRE_CODEC_IDS) for the device-plane quantized ring;
// tools/hvd_lint.py enforces the two stay in sync.
constexpr int64_t kWireBlock = 256;
constexpr int64_t kWireScaleBytes = 4;

// Encoded size in bytes of `count` fp32 elements under `codec`.
inline int64_t WireEncodedBytes(WireCodec codec, int64_t count) {
  switch (codec) {
    case WireCodec::kBf16:
      return 2 * count;
    case WireCodec::kInt8: {
      const int64_t blocks = (count + kWireBlock - 1) / kWireBlock;
      return blocks * kWireScaleBytes + count;
    }
    case WireCodec::kNone:
    default:
      return 4 * count;
  }
}

// Encode `count` fp32 elements from `src` into `dst`
// (WireEncodedBytes(codec, count) bytes).
inline void WireEncode(WireCodec codec, const float* src, int64_t count,
                       char* dst) {
  if (codec == WireCodec::kBf16) {
    uint16_t* out = reinterpret_cast<uint16_t*>(dst);
    for (int64_t i = 0; i < count; ++i) {
      uint32_t bits;
      std::memcpy(&bits, src + i, 4);
      out[i] = static_cast<uint16_t>(bits >> 16);
    }
    return;
  }
  if (codec == WireCodec::kInt8) {
    for (int64_t b0 = 0; b0 < count; b0 += kWireBlock) {
      const int64_t n = std::min(kWireBlock, count - b0);
      float maxabs = 0.0f;
      for (int64_t i = 0; i < n; ++i) {
        const float a = std::fabs(src[b0 + i]);
        if (a > maxabs) maxabs = a;
      }
      const float scale = maxabs / 127.0f;
      std::memcpy(dst, &scale, kWireScaleBytes);
      int8_t* q = reinterpret_cast<int8_t*>(dst + kWireScaleBytes);
      if (scale > 0.0f && std::isfinite(scale)) {
        const float inv = 1.0f / scale;
        for (int64_t i = 0; i < n; ++i) {
          const float v = std::nearbyintf(src[b0 + i] * inv);
          q[i] = static_cast<int8_t>(
              std::max(-127.0f, std::min(127.0f, v)));
        }
      } else {
        // All-zero block (or non-finite scale from inf/nan input: encode
        // zeros rather than propagate garbage — matching the clamp above).
        std::memset(q, 0, static_cast<size_t>(n));
      }
      dst += kWireScaleBytes + n;
    }
    return;
  }
  std::memcpy(dst, src, static_cast<size_t>(4 * count));
}

// Decode elements [elem_lo, elem_hi) of an encoded stream that carries
// `count` elements total.  `src` points at the START of the encoded stream
// (not at elem_lo); `dst` receives elem_hi - elem_lo fp32 values.
inline void WireDecodeRange(WireCodec codec, const char* src, int64_t count,
                            int64_t elem_lo, int64_t elem_hi, float* dst) {
  (void)count;
  if (codec == WireCodec::kBf16) {
    const uint16_t* in = reinterpret_cast<const uint16_t*>(src) + elem_lo;
    for (int64_t i = 0; i < elem_hi - elem_lo; ++i) {
      const uint32_t bits = static_cast<uint32_t>(in[i]) << 16;
      std::memcpy(dst + i, &bits, 4);
    }
    return;
  }
  if (codec == WireCodec::kInt8) {
    for (int64_t e = elem_lo; e < elem_hi;) {
      const int64_t blk = e / kWireBlock;
      const int64_t in_blk = e % kWireBlock;
      const int64_t blk_end = std::min((blk + 1) * kWireBlock, elem_hi);
      const char* base =
          src + blk * (kWireScaleBytes + kWireBlock) + kWireScaleBytes;
      float scale;
      std::memcpy(&scale,
                  src + blk * (kWireScaleBytes + kWireBlock), 4);
      const int8_t* q = reinterpret_cast<const int8_t*>(base) + in_blk;
      for (int64_t i = 0; e + i < blk_end; ++i) {
        dst[e + i - elem_lo] = scale * static_cast<float>(q[i]);
      }
      e = blk_end;
    }
    return;
  }
  std::memcpy(dst, src + 4 * elem_lo,
              static_cast<size_t>(4 * (elem_hi - elem_lo)));
}

// How many leading elements of a `total_elems`-element encoded stream are
// fully decodable once `bytes_received` prefix bytes have arrived.  Used by
// the ring's incremental consume path (chunk boundaries are byte-, not
// block-aligned).
inline int64_t WireDecodableElems(WireCodec codec, int64_t bytes_received,
                                  int64_t total_elems) {
  int64_t n;
  switch (codec) {
    case WireCodec::kBf16:
      n = bytes_received / 2;
      break;
    case WireCodec::kInt8: {
      const int64_t per_block = kWireScaleBytes + kWireBlock;
      const int64_t full = bytes_received / per_block;
      const int64_t rem = bytes_received % per_block;
      n = full * kWireBlock +
          std::max<int64_t>(0, rem - kWireScaleBytes);
      break;
    }
    case WireCodec::kNone:
    default:
      n = bytes_received / 4;
      break;
  }
  return std::min(n, total_elems);
}

}  // namespace hvdtpu

#endif  // HVD_TPU_WIRE_CODEC_H_
