#include "timeline.h"

#include <functional>

#include "common.h"

namespace hvdtpu {

namespace {
std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out.push_back('\\');
      out.push_back(c);
    } else if (static_cast<unsigned char>(c) < 0x20) {
      out += ' ';
    } else {
      out.push_back(c);
    }
  }
  return out;
}
}  // namespace

Timeline::~Timeline() { Stop(); }

void Timeline::Start(const std::string& path, bool mark_cycles) {
  std::lock_guard<std::mutex> l(mu_);
  if (enabled_) return;
  file_ = std::fopen(path.c_str(), "w");
  if (!file_) return;
  std::fputs("[\n", file_);
  first_event_ = true;
  t0_ = MonotonicSeconds();
  mark_cycles_ = mark_cycles;
  shutdown_ = false;
  enabled_ = true;
  writer_ = std::thread([this] { WriterLoop(); });
}

void Timeline::Stop() {
  {
    std::lock_guard<std::mutex> l(mu_);
    if (!enabled_) return;
    shutdown_ = true;
    cv_.notify_all();
  }
  if (writer_.joinable()) writer_.join();
  {
    std::lock_guard<std::mutex> l(mu_);
    if (file_) {
      std::fputs("\n]\n", file_);
      std::fclose(file_);
      file_ = nullptr;
    }
    enabled_ = false;
  }
}

int64_t Timeline::NowUs() const {
  return static_cast<int64_t>((MonotonicSeconds() - t0_) * 1e6);
}

void Timeline::Emit(std::string json_line) {
  std::lock_guard<std::mutex> l(mu_);
  if (!enabled_) return;
  queue_.push_back(std::move(json_line));
  cv_.notify_one();
}

void Timeline::WriterLoop() {
  for (;;) {
    std::deque<std::string> batch;
    {
      std::unique_lock<std::mutex> l(mu_);
      cv_.wait(l, [this] { return shutdown_ || !queue_.empty(); });
      batch.swap(queue_);
      if (batch.empty() && shutdown_) return;
    }
    std::lock_guard<std::mutex> l(mu_);
    if (!file_) return;
    for (auto& ev : batch) {
      if (!first_event_) std::fputs(",\n", file_);
      std::fputs(ev.c_str(), file_);
      first_event_ = false;
    }
    std::fflush(file_);
  }
}

void Timeline::Begin(const std::string& tensor, const std::string& phase) {
  if (!enabled_) return;
  int64_t tid = static_cast<int64_t>(std::hash<std::string>{}(tensor) & 0x7fffffff);
  char buf[512];
  std::snprintf(buf, sizeof(buf),
                "{\"name\":\"%s\",\"ph\":\"B\",\"ts\":%lld,\"pid\":0,"
                "\"tid\":%lld,\"args\":{\"tensor\":\"%s\"}}",
                JsonEscape(phase).c_str(), static_cast<long long>(NowUs()),
                static_cast<long long>(tid), JsonEscape(tensor).c_str());
  Emit(buf);
}

void Timeline::End(const std::string& tensor, const std::string& phase) {
  if (!enabled_) return;
  int64_t tid = static_cast<int64_t>(std::hash<std::string>{}(tensor) & 0x7fffffff);
  char buf[256];
  std::snprintf(buf, sizeof(buf),
                "{\"name\":\"%s\",\"ph\":\"E\",\"ts\":%lld,\"pid\":0,\"tid\":%lld}",
                JsonEscape(phase).c_str(), static_cast<long long>(NowUs()),
                static_cast<long long>(tid));
  Emit(buf);
}

void Timeline::Instant(const std::string& name) {
  if (!enabled_) return;
  char buf[256];
  std::snprintf(buf, sizeof(buf),
                "{\"name\":\"%s\",\"ph\":\"i\",\"ts\":%lld,\"pid\":0,\"tid\":0,"
                "\"s\":\"p\"}",
                JsonEscape(name).c_str(), static_cast<long long>(NowUs()));
  Emit(buf);
}

void Timeline::MarkCycle() {
  if (mark_cycles_) Instant("CYCLE");
}

}  // namespace hvdtpu
