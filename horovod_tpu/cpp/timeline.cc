#include "timeline.h"

#include <chrono>
#include <functional>

#include "common.h"
#include "metrics.h"

namespace hvdtpu {

Timeline::~Timeline() { Stop(); }

void Timeline::Start(const std::string& path, bool mark_cycles) {
  std::lock_guard<std::mutex> l(mu_);
  if (enabled_) return;
  file_ = std::fopen(path.c_str(), "w");
  if (!file_) return;
  std::fputs("[\n", file_);
  first_event_ = true;
  t0_ = MonotonicSeconds();
  mark_cycles_ = mark_cycles;
  shutdown_ = false;
  enabled_ = true;
  // Anchor event: wall clock at trace ts≈0, so merge_timeline.py can put
  // per-rank traces on one axis.  Pushed straight onto the queue — Emit()
  // would re-take mu_.
  int64_t unix_us = std::chrono::duration_cast<std::chrono::microseconds>(
                        std::chrono::system_clock::now().time_since_epoch())
                        .count();
  queue_.push_back("{\"name\":\"CLOCK_SYNC\",\"ph\":\"i\",\"ts\":0,"
                   "\"pid\":0,\"tid\":0,\"s\":\"p\",\"args\":{\"rank\":" +
                   std::to_string(rank_) + ",\"unix_us\":" +
                   std::to_string(unix_us) + "}}");
  writer_ = std::thread([this] { WriterLoop(); });
}

void Timeline::Stop() {
  {
    std::lock_guard<std::mutex> l(mu_);
    if (!enabled_) return;
    shutdown_ = true;
    cv_.notify_all();
  }
  if (writer_.joinable()) writer_.join();
  {
    std::lock_guard<std::mutex> l(mu_);
    if (file_) {
      std::fputs("\n]\n", file_);
      std::fclose(file_);
      file_ = nullptr;
    }
    enabled_ = false;
  }
}

int64_t Timeline::NowUs() const {
  return static_cast<int64_t>((MonotonicSeconds() - t0_) * 1e6);
}

void Timeline::Emit(std::string json_line) {
  std::lock_guard<std::mutex> l(mu_);
  if (!enabled_) return;
  queue_.push_back(std::move(json_line));
  cv_.notify_one();
}

void Timeline::WriterLoop() {
  for (;;) {
    std::deque<std::string> batch;
    {
      std::unique_lock<std::mutex> l(mu_);
      cv_.wait(l, [this] { return shutdown_ || !queue_.empty(); });
      batch.swap(queue_);
      if (batch.empty() && shutdown_) return;
    }
    std::lock_guard<std::mutex> l(mu_);
    if (!file_) return;
    for (auto& ev : batch) {
      if (!first_event_) std::fputs(",\n", file_);
      std::fputs(ev.c_str(), file_);
      first_event_ = false;
    }
    std::fflush(file_);
  }
}

void Timeline::Begin(const std::string& tensor, const std::string& phase) {
  if (!enabled_) return;
  int64_t tid = static_cast<int64_t>(std::hash<std::string>{}(tensor) & 0x7fffffff);
  Emit("{\"name\":\"" + JsonEscape(phase) +
       "\",\"ph\":\"B\",\"ts\":" + std::to_string(NowUs()) +
       ",\"pid\":0,\"tid\":" + std::to_string(tid) +
       ",\"args\":{\"tensor\":\"" + JsonEscape(tensor) + "\"}}");
}

void Timeline::End(const std::string& tensor, const std::string& phase) {
  if (!enabled_) return;
  int64_t tid = static_cast<int64_t>(std::hash<std::string>{}(tensor) & 0x7fffffff);
  Emit("{\"name\":\"" + JsonEscape(phase) +
       "\",\"ph\":\"E\",\"ts\":" + std::to_string(NowUs()) +
       ",\"pid\":0,\"tid\":" + std::to_string(tid) + "}");
}

void Timeline::Instant(const std::string& name) {
  if (!enabled_) return;
  Emit("{\"name\":\"" + JsonEscape(name) +
       "\",\"ph\":\"i\",\"ts\":" + std::to_string(NowUs()) +
       ",\"pid\":0,\"tid\":0,\"s\":\"p\"}");
}

void Timeline::Instant(const std::string& name,
                       const std::string& args_json) {
  if (!enabled_) return;
  if (args_json.empty()) {
    Instant(name);
    return;
  }
  // `args_json` is a complete JSON object literal the caller formed (the
  // ABORT instant carries culprit rank/host for merge_timeline.py).
  Emit("{\"name\":\"" + JsonEscape(name) +
       "\",\"ph\":\"i\",\"ts\":" + std::to_string(NowUs()) +
       ",\"pid\":0,\"tid\":0,\"s\":\"p\",\"args\":" + args_json + "}");
}

void Timeline::MarkCycle() {
  if (mark_cycles_) Instant("CYCLE");
}

}  // namespace hvdtpu
