// Fuzz harness for ChunkedDuplexExchange, the chunk-pipelined duplex
// primitive under the ring/chain data plane (socketio.cc).
//
// Two threads on a socketpair run randomized-geometry exchanges — payload
// lengths from 0 to several MiB (remainder chunks, empty streams), chunk
// sizes differing per side (mixed HOROVOD_RING_CHUNK_BYTES interop), both
// recv modes (direct-dest and scratch + on_chunk) — and every received
// byte is verified against the sender's pattern.  Error paths are driven
// explicitly: header mismatch, and cancellation mid-stream (no hang).
//
// Reference analog (SURVEY.md §5, sanitizers/selftests): mechanical
// validation of the wire primitive apart from the full controller.

#include <sys/socket.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

#include "socketio.h"

namespace hvdtpu {
int GetLogLevel() { return 4; }  // errors only
void SetLogLevel(int) {}
}  // namespace hvdtpu

using namespace hvdtpu;

namespace {

std::atomic<int> failures{0};

void Fail(const char* what, int round) {
  std::fprintf(stderr, "FAIL round %d: %s\n", round, what);
  failures.fetch_add(1);
}

// Deterministic per-(seed, offset) byte pattern both sides can compute.
char PatternByte(unsigned seed, int64_t off) {
  return static_cast<char>((seed * 131 + off * 7 + (off >> 9)) & 0xFF);
}

std::vector<char> MakePattern(unsigned seed, int64_t n) {
  std::vector<char> v(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) v[static_cast<size_t>(i)] =
      PatternByte(seed, i);
  return v;
}

bool CheckPattern(const char* data, unsigned seed, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    if (data[i] != PatternByte(seed, i)) return false;
  }
  return true;
}

struct SidePlan {
  int64_t send_len;
  int64_t chunk;
  bool direct_dest;  // receive straight into the buffer vs on_chunk scratch
};

void RunSide(Socket* sock, unsigned my_seed, unsigned peer_seed,
             const SidePlan& mine, const SidePlan& theirs,
             const std::string& header, int round) {
  std::vector<char> out = MakePattern(my_seed, mine.send_len);
  std::vector<char> in(static_cast<size_t>(theirs.send_len));
  int64_t consumed = 0;
  ChunkExchangeError err;
  bool ok;
  if (mine.direct_dest) {
    ok = ChunkedDuplexExchange(*sock, out.data(), mine.send_len, *sock,
                               theirs.send_len, mine.chunk, header,
                               in.data(), nullptr, nullptr, &err);
  } else {
    ok = ChunkedDuplexExchange(
        *sock, out.data(), mine.send_len, *sock, theirs.send_len, mine.chunk,
        header, nullptr,
        [&](int64_t off, const char* data, int64_t n) {
          if (off != consumed) Fail("out-of-order chunk", round);
          std::memcpy(in.data() + off, data, static_cast<size_t>(n));
          consumed += n;
        },
        nullptr, &err);
  }
  if (!ok) return Fail("exchange returned false", round);
  if (err.kind != ChunkExchangeError::kNone) {
    return Fail("err.kind set on success", round);
  }
  if (!mine.direct_dest && consumed != theirs.send_len) {
    return Fail("on_chunk did not consume the full stream", round);
  }
  if (!CheckPattern(in.data(), peer_seed, theirs.send_len)) {
    return Fail("payload corrupted", round);
  }
}

bool MakePair(Socket* a, Socket* b) {
  int fds[2];
  if (::socketpair(AF_UNIX, SOCK_STREAM, 0, fds) != 0) return false;
  *a = Socket(fds[0]);
  *b = Socket(fds[1]);
  return true;
}

void FuzzRounds() {
  std::mt19937 rng(0xC0FFEE);
  auto rand_len = [&](int64_t lo, int64_t hi) {
    return std::uniform_int_distribution<int64_t>(lo, hi)(rng);
  };
  for (int round = 0; round < 40; ++round) {
    Socket a, b;
    if (!MakePair(&a, &b)) return Fail("socketpair", round);
    // Geometry mix: tiny chunks over big payloads, chunk > payload,
    // zero-length streams in either/both directions, uneven sides.
    SidePlan pa{rand_len(0, 3) == 0 ? 0 : rand_len(1, 3 << 20),
                rand_len(1, 4) == 1 ? rand_len(100, 5000)
                                    : rand_len(1 << 14, 1 << 20),
                (rng() & 1) != 0};
    SidePlan pb{rand_len(0, 3) == 0 ? 0 : rand_len(1, 3 << 20),
                rand_len(1, 4) == 1 ? rand_len(100, 5000)
                                    : rand_len(1 << 14, 1 << 20),
                (rng() & 1) != 0};
    std::string header = "hdr" + std::to_string(round);
    unsigned sa = rng(), sb = rng();
    std::thread ta(RunSide, &a, sa, sb, pa, pb, header, round);
    RunSide(&b, sb, sa, pb, pa, header, round);
    ta.join();
  }
}

void HeaderMismatch() {
  Socket a, b;
  if (!MakePair(&a, &b)) return Fail("socketpair", -1);
  std::vector<char> pay(1 << 16, 'x');
  auto side = [&](Socket* s, const std::string& hdr) {
    std::vector<char> in(pay.size());
    ChunkExchangeError err;
    bool ok = ChunkedDuplexExchange(*s, pay.data(), (int64_t)pay.size(), *s,
                                    (int64_t)pay.size(), 1 << 12, hdr,
                                    in.data(), nullptr, nullptr, &err);
    if (ok) Fail("header mismatch not detected", -1);
    if (err.kind != ChunkExchangeError::kHeaderMismatch) {
      Fail("wrong error kind for header mismatch", -1);
    }
  };
  std::thread t(side, &a, std::string("AAAA9999"));
  side(&b, std::string("BBBB9999"));
  t.join();
}

void Cancellation() {
  Socket a, b;
  if (!MakePair(&a, &b)) return Fail("socketpair", -2);
  // Peer never sends: the side must notice the cancel flag and abort
  // within a poll interval instead of hanging.
  std::vector<char> in(1 << 16);
  std::atomic<bool> cancel{false};
  std::thread flipper([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(150));
    cancel = true;
  });
  ChunkExchangeError err;
  bool ok = ChunkedDuplexExchange(a, nullptr, 0, a, (int64_t)in.size(),
                                  1 << 12, "h", in.data(), nullptr,
                                  [&] { return cancel.load(); }, &err);
  flipper.join();
  if (ok) Fail("cancelled exchange reported success", -2);
  if (err.kind != ChunkExchangeError::kTransport) {
    Fail("wrong error kind for cancellation", -2);
  }
}

}  // namespace

int main() {
  FuzzRounds();
  HeaderMismatch();
  Cancellation();
  if (failures.load() != 0) {
    std::fprintf(stderr, "%d failure(s)\n", failures.load());
    return 1;
  }
  std::printf("PASS chunk_exchange_selftest\n");
  return 0;
}
