// Fuzz harness for ChunkedDuplexExchange, the chunk-pipelined duplex
// primitive under the ring/chain data plane (socketio.cc).
//
// Two threads on a socketpair run randomized-geometry exchanges — payload
// lengths from 0 to several MiB (remainder chunks, empty streams), chunk
// sizes differing per side (mixed HOROVOD_RING_CHUNK_BYTES interop), both
// recv modes (direct-dest and scratch + on_chunk) — and every received
// byte is verified against the sender's pattern.  Error paths are driven
// explicitly: header mismatch, and cancellation mid-stream (no hang).
//
// Reference analog (SURVEY.md §5, sanitizers/selftests): mechanical
// validation of the wire primitive apart from the full controller.

#include <sys/socket.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

#include "socketio.h"
#include "wire_codec.h"

namespace hvdtpu {
int GetLogLevel() { return 4; }  // errors only
void SetLogLevel(int) {}
}  // namespace hvdtpu

using namespace hvdtpu;

namespace {

std::atomic<int> failures{0};

void Fail(const char* what, int round) {
  std::fprintf(stderr, "FAIL round %d: %s\n", round, what);
  failures.fetch_add(1);
}

// Deterministic per-(seed, offset) byte pattern both sides can compute.
char PatternByte(unsigned seed, int64_t off) {
  return static_cast<char>((seed * 131 + off * 7 + (off >> 9)) & 0xFF);
}

std::vector<char> MakePattern(unsigned seed, int64_t n) {
  std::vector<char> v(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) v[static_cast<size_t>(i)] =
      PatternByte(seed, i);
  return v;
}

bool CheckPattern(const char* data, unsigned seed, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    if (data[i] != PatternByte(seed, i)) return false;
  }
  return true;
}

struct SidePlan {
  int64_t send_len;
  int64_t chunk;
  bool direct_dest;  // receive straight into the buffer vs on_chunk scratch
};

void RunSide(Socket* sock, unsigned my_seed, unsigned peer_seed,
             const SidePlan& mine, const SidePlan& theirs,
             const std::string& header, int round) {
  std::vector<char> out = MakePattern(my_seed, mine.send_len);
  std::vector<char> in(static_cast<size_t>(theirs.send_len));
  int64_t consumed = 0;
  ChunkExchangeError err;
  bool ok;
  if (mine.direct_dest) {
    ok = ChunkedDuplexExchange(*sock, out.data(), mine.send_len, *sock,
                               theirs.send_len, mine.chunk, header,
                               in.data(), nullptr, nullptr, &err);
  } else {
    ok = ChunkedDuplexExchange(
        *sock, out.data(), mine.send_len, *sock, theirs.send_len, mine.chunk,
        header, nullptr,
        [&](int64_t off, const char* data, int64_t n) {
          if (off != consumed) Fail("out-of-order chunk", round);
          std::memcpy(in.data() + off, data, static_cast<size_t>(n));
          consumed += n;
        },
        nullptr, &err);
  }
  if (!ok) return Fail("exchange returned false", round);
  if (err.kind != ChunkExchangeError::kNone) {
    return Fail("err.kind set on success", round);
  }
  if (!mine.direct_dest && consumed != theirs.send_len) {
    return Fail("on_chunk did not consume the full stream", round);
  }
  if (!CheckPattern(in.data(), peer_seed, theirs.send_len)) {
    return Fail("payload corrupted", round);
  }
}

bool MakePair(Socket* a, Socket* b) {
  int fds[2];
  if (::socketpair(AF_UNIX, SOCK_STREAM, 0, fds) != 0) return false;
  *a = Socket(fds[0]);
  *b = Socket(fds[1]);
  return true;
}

void FuzzRounds() {
  std::mt19937 rng(0xC0FFEE);
  auto rand_len = [&](int64_t lo, int64_t hi) {
    return std::uniform_int_distribution<int64_t>(lo, hi)(rng);
  };
  for (int round = 0; round < 40; ++round) {
    Socket a, b;
    if (!MakePair(&a, &b)) return Fail("socketpair", round);
    // Geometry mix: tiny chunks over big payloads, chunk > payload,
    // zero-length streams in either/both directions, uneven sides.
    SidePlan pa{rand_len(0, 3) == 0 ? 0 : rand_len(1, 3 << 20),
                rand_len(1, 4) == 1 ? rand_len(100, 5000)
                                    : rand_len(1 << 14, 1 << 20),
                (rng() & 1) != 0};
    SidePlan pb{rand_len(0, 3) == 0 ? 0 : rand_len(1, 3 << 20),
                rand_len(1, 4) == 1 ? rand_len(100, 5000)
                                    : rand_len(1 << 14, 1 << 20),
                (rng() & 1) != 0};
    std::string header = "hdr" + std::to_string(round);
    unsigned sa = rng(), sb = rng();
    std::thread ta(RunSide, &a, sa, sb, pa, pb, header, round);
    RunSide(&b, sb, sa, pb, pa, header, round);
    ta.join();
  }
}

void HeaderMismatch() {
  Socket a, b;
  if (!MakePair(&a, &b)) return Fail("socketpair", -1);
  std::vector<char> pay(1 << 16, 'x');
  auto side = [&](Socket* s, const std::string& hdr) {
    std::vector<char> in(pay.size());
    ChunkExchangeError err;
    bool ok = ChunkedDuplexExchange(*s, pay.data(), (int64_t)pay.size(), *s,
                                    (int64_t)pay.size(), 1 << 12, hdr,
                                    in.data(), nullptr, nullptr, &err);
    if (ok) Fail("header mismatch not detected", -1);
    if (err.kind != ChunkExchangeError::kHeaderMismatch) {
      Fail("wrong error kind for header mismatch", -1);
    }
  };
  std::thread t(side, &a, std::string("AAAA9999"));
  side(&b, std::string("BBBB9999"));
  t.join();
}

void Cancellation() {
  Socket a, b;
  if (!MakePair(&a, &b)) return Fail("socketpair", -2);
  // Peer never sends: the side must notice the cancel flag and abort
  // within a poll interval instead of hanging.
  std::vector<char> in(1 << 16);
  std::atomic<bool> cancel{false};
  std::thread flipper([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(150));
    cancel = true;
  });
  ChunkExchangeError err;
  bool ok = ChunkedDuplexExchange(a, nullptr, 0, a, (int64_t)in.size(),
                                  1 << 12, "h", in.data(), nullptr,
                                  [&] { return cancel.load(); }, &err);
  flipper.join();
  if (ok) Fail("cancelled exchange reported success", -2);
  if (err.kind != ChunkExchangeError::kTransport) {
    Fail("wrong error kind for cancellation", -2);
  }
}

// ---- wire_codec.h: the codec layer the compressed ring rides ------------

// bf16 truncation is exact for values already representable in bf16
// (mantissa fits in 7 bits): round-tripping them must be bit-identical.
void CodecBf16RoundTrip() {
  const float vals[] = {0.0f,     -0.0f, 1.0f,      -1.0f,   0.5f,
                        2.0f,     -2.5f, 1024.0f,   -0.125f, 3.140625f,
                        65536.0f, 0x1p100f, -0x1p-100f, 0.0078125f};
  const int64_t n = sizeof(vals) / sizeof(vals[0]);
  std::vector<char> enc(
      static_cast<size_t>(WireEncodedBytes(WireCodec::kBf16, n)));
  std::vector<float> dec(static_cast<size_t>(n));
  WireEncode(WireCodec::kBf16, vals, n, enc.data());
  WireDecodeRange(WireCodec::kBf16, enc.data(), n, 0, n, dec.data());
  for (int64_t i = 0; i < n; ++i) {
    if (std::memcmp(&dec[i], &vals[i], 4) != 0) {
      Fail("bf16 round-trip not exact for representable value", -3);
      return;
    }
  }
  // Non-representable values still land within one bf16 ulp (truncation:
  // error < 2^-7 relative).
  const float odd[] = {3.14159265f, 1.0001f, -123.456f, 7.7777e-5f};
  const int64_t m = sizeof(odd) / sizeof(odd[0]);
  WireEncode(WireCodec::kBf16, odd, m, enc.data());
  WireDecodeRange(WireCodec::kBf16, enc.data(), m, 0, m, dec.data());
  for (int64_t i = 0; i < m; ++i) {
    if (std::fabs(dec[i] - odd[i]) > std::fabs(odd[i]) * (1.0f / 128.0f)) {
      Fail("bf16 truncation error exceeds one ulp bound", -3);
      return;
    }
  }
}

// int8 block scaling: |decode(encode(x)) - x| <= scale/2 per element,
// where scale = blockmax/127; partial last blocks and random-access
// decode (block-unaligned ranges) must agree with a full decode.
void CodecInt8ErrorBound() {
  std::mt19937 rng(0xBEEF);
  std::uniform_real_distribution<float> mag(-50.f, 50.f);
  // 3 full blocks + a partial one, plus an all-zero block in the middle.
  const int64_t n = 3 * kWireBlock + 77;
  std::vector<float> src(static_cast<size_t>(n));
  for (auto& v : src) v = mag(rng);
  for (int64_t i = kWireBlock; i < 2 * kWireBlock; ++i) src[i] = 0.0f;
  std::vector<char> enc(
      static_cast<size_t>(WireEncodedBytes(WireCodec::kInt8, n)));
  WireEncode(WireCodec::kInt8, src.data(), n, enc.data());
  std::vector<float> dec(static_cast<size_t>(n));
  WireDecodeRange(WireCodec::kInt8, enc.data(), n, 0, n, dec.data());
  for (int64_t b0 = 0; b0 < n; b0 += kWireBlock) {
    const int64_t bn = std::min(kWireBlock, n - b0);
    float maxabs = 0.f;
    for (int64_t i = 0; i < bn; ++i) {
      maxabs = std::max(maxabs, std::fabs(src[b0 + i]));
    }
    const float scale = maxabs / 127.0f;
    for (int64_t i = 0; i < bn; ++i) {
      if (std::fabs(dec[b0 + i] - src[b0 + i]) > scale * 0.5f + 1e-12f) {
        Fail("int8 block-scale error exceeds scale/2", -4);
        return;
      }
    }
  }
  // Incremental decode (the ring's consume path): byte-level prefixes +
  // block-unaligned ranges must reproduce the full decode exactly.
  int64_t decoded = 0;
  std::vector<float> inc(static_cast<size_t>(n));
  for (int64_t bytes = 0; bytes <= WireEncodedBytes(WireCodec::kInt8, n);
       bytes += 97) {
    const int64_t avail = WireDecodableElems(WireCodec::kInt8, bytes, n);
    if (avail < decoded) {
      Fail("WireDecodableElems not monotone", -4);
      return;
    }
    if (avail > decoded) {
      WireDecodeRange(WireCodec::kInt8, enc.data(), n, decoded, avail,
                      inc.data() + decoded);
      decoded = avail;
    }
  }
  const int64_t tail = WireDecodableElems(
      WireCodec::kInt8, WireEncodedBytes(WireCodec::kInt8, n), n);
  if (tail > decoded) {
    WireDecodeRange(WireCodec::kInt8, enc.data(), n, decoded, tail,
                    inc.data() + decoded);
    decoded = tail;
  }
  if (decoded != n ||
      std::memcmp(inc.data(), dec.data(), static_cast<size_t>(4 * n)) != 0) {
    Fail("incremental int8 decode diverges from full decode", -4);
  }
}

// fp32 ring accumulation: simulating the reduce-scatter phase (each hop
// contributes decode(encode(x_i)) into an fp32 accumulator), the total
// error stays within hops x the single-quantization bound — the property
// that makes the compressed ring's error linear in ring size instead of
// compounding (re-quantizing partial sums would square it away).
void CodecRingAccumulationBound() {
  std::mt19937 rng(0x5EED);
  std::uniform_real_distribution<float> mag(-10.f, 10.f);
  const int hops = 7;  // ring of 8: 7 reduce-scatter contributions
  const int64_t n = 2 * kWireBlock + 33;
  std::vector<double> exact(static_cast<size_t>(n), 0.0);
  std::vector<float> acc(static_cast<size_t>(n), 0.0f);
  std::vector<double> bound(static_cast<size_t>(n), 0.0);
  std::vector<char> enc(
      static_cast<size_t>(WireEncodedBytes(WireCodec::kInt8, n)));
  std::vector<float> dec(static_cast<size_t>(n));
  for (int h = 0; h < hops; ++h) {
    std::vector<float> x(static_cast<size_t>(n));
    for (auto& v : x) v = mag(rng);
    WireEncode(WireCodec::kInt8, x.data(), n, enc.data());
    WireDecodeRange(WireCodec::kInt8, enc.data(), n, 0, n, dec.data());
    for (int64_t i = 0; i < n; ++i) {
      exact[i] += x[i];
      acc[i] += dec[i];  // fp32 accumulate of the decoded contribution
    }
    for (int64_t b0 = 0; b0 < n; b0 += kWireBlock) {
      const int64_t bn = std::min(kWireBlock, n - b0);
      float maxabs = 0.f;
      for (int64_t i = 0; i < bn; ++i) {
        maxabs = std::max(maxabs, std::fabs(x[b0 + i]));
      }
      for (int64_t i = 0; i < bn; ++i) {
        bound[b0 + i] += maxabs / 127.0 * 0.5;  // scale/2 per hop
      }
    }
  }
  for (int64_t i = 0; i < n; ++i) {
    // Tiny slack for the fp32 summation itself (7 adds of ~10-magnitude
    // values: machine-epsilon territory next to the quantization bound).
    if (std::fabs(acc[i] - exact[i]) > bound[i] + 1e-4) {
      Fail("ring accumulation error exceeds hops x scale/2", -5);
      return;
    }
  }
}

// int4 block scaling: |decode(encode(x)) - x| <= scale/2 per element with
// scale = blockmax/kWireInt4Max, nibble pack/unpack exact, and the
// incremental consume path agreeing with a full decode byte-for-byte.
void CodecInt4ErrorBound() {
  std::mt19937 rng(0xCAFE);
  std::uniform_real_distribution<float> mag(-50.f, 50.f);
  // Full blocks + an ODD-length partial block (a lone low nibble in the
  // last packed byte), plus an all-zero block.
  const int64_t n = 3 * kWireBlock + 77;
  std::vector<float> src(static_cast<size_t>(n));
  for (auto& v : src) v = mag(rng);
  for (int64_t i = kWireBlock; i < 2 * kWireBlock; ++i) src[i] = 0.0f;
  std::vector<char> enc(
      static_cast<size_t>(WireEncodedBytes(WireCodec::kInt4, n)));
  WireEncode(WireCodec::kInt4, src.data(), n, enc.data());
  std::vector<float> dec(static_cast<size_t>(n));
  WireDecodeRange(WireCodec::kInt4, enc.data(), n, 0, n, dec.data());
  for (int64_t b0 = 0; b0 < n; b0 += kWireBlock) {
    const int64_t bn = std::min(kWireBlock, n - b0);
    float maxabs = 0.f;
    for (int64_t i = 0; i < bn; ++i) {
      maxabs = std::max(maxabs, std::fabs(src[b0 + i]));
    }
    const float scale = maxabs / static_cast<float>(kWireInt4Max);
    for (int64_t i = 0; i < bn; ++i) {
      if (std::fabs(dec[b0 + i] - src[b0 + i]) > scale * 0.5f + 1e-12f) {
        Fail("int4 block-scale error exceeds scale/2", -4);
        return;
      }
    }
  }
  // Incremental decode across byte-level prefixes (nibble-granular tail).
  int64_t decoded = 0;
  std::vector<float> inc(static_cast<size_t>(n));
  for (int64_t bytes = 0; bytes <= WireEncodedBytes(WireCodec::kInt4, n);
       bytes += 13) {
    const int64_t avail = WireDecodableElems(WireCodec::kInt4, bytes, n);
    if (avail < decoded) {
      Fail("int4 WireDecodableElems not monotone", -4);
      return;
    }
    if (avail > decoded) {
      WireDecodeRange(WireCodec::kInt4, enc.data(), n, decoded, avail,
                      inc.data() + decoded);
      decoded = avail;
    }
  }
  const int64_t tail = WireDecodableElems(
      WireCodec::kInt4, WireEncodedBytes(WireCodec::kInt4, n), n);
  if (tail > decoded) {
    WireDecodeRange(WireCodec::kInt4, enc.data(), n, decoded, tail,
                    inc.data() + decoded);
    decoded = tail;
  }
  if (decoded != n ||
      std::memcmp(inc.data(), dec.data(), static_cast<size_t>(4 * n)) != 0) {
    Fail("incremental int4 decode diverges from full decode", -4);
  }
}

// int8g two-level scaling: |decode(encode(x)) - x| <= eff/2 per element
// where eff = gscale * sub/kWireSubDenom is the per-block effective scale
// actually stored on the wire; a short last group and an all-zero block
// inside a finite group must round-trip; incremental decode must agree
// with the full decode.
void CodecInt8gErrorBound() {
  std::mt19937 rng(0xD00D);
  std::uniform_real_distribution<float> mag(-50.f, 50.f);
  // One full group + a short group with a partial block; zero out one
  // block inside the full group (sub-scale byte 0 path).
  const int64_t n = kWireGroup + 5 * kWireBlock + 77;
  std::vector<float> src(static_cast<size_t>(n));
  for (auto& v : src) v = mag(rng);
  for (int64_t i = 3 * kWireBlock; i < 4 * kWireBlock; ++i) src[i] = 0.0f;
  // Spread magnitudes so sub-scales actually vary within a group.
  for (int64_t i = 0; i < n; ++i) {
    if ((i / kWireBlock) % 3 == 1) src[i] *= 0.01f;
  }
  std::vector<char> enc(
      static_cast<size_t>(WireEncodedBytes(WireCodec::kInt8g, n)));
  WireEncode(WireCodec::kInt8g, src.data(), n, enc.data());
  std::vector<float> dec(static_cast<size_t>(n));
  WireDecodeRange(WireCodec::kInt8g, enc.data(), n, 0, n, dec.data());
  for (int64_t g0 = 0; g0 < n; g0 += kWireGroup) {
    const int64_t gn = std::min(kWireGroup, n - g0);
    float gmax = 0.f;
    for (int64_t i = 0; i < gn; ++i) {
      gmax = std::max(gmax, std::fabs(src[g0 + i]));
    }
    const float gscale = gmax / 127.0f;
    for (int64_t b0 = 0; b0 < gn; b0 += kWireBlock) {
      const int64_t bn = std::min(kWireBlock, gn - b0);
      float bmax = 0.f;
      for (int64_t i = 0; i < bn; ++i) {
        bmax = std::max(bmax, std::fabs(src[g0 + b0 + i]));
      }
      const float s = std::min(
          255.0f,
          std::nearbyintf(bmax / gmax * static_cast<float>(kWireSubDenom)));
      const float eff = gscale * (s / static_cast<float>(kWireSubDenom));
      // Sub-scale rounding can sit eff slightly under bmax/127; allow the
      // corresponding clipping slack (<= gscale/kWireSubDenom per unit
      // code, codes bounded by 127).
      const float slack =
          127.0f * std::max(0.0f, bmax / 127.0f - eff) + 1e-12f;
      for (int64_t i = 0; i < bn; ++i) {
        if (std::fabs(dec[g0 + b0 + i] - src[g0 + b0 + i]) >
            eff * 0.5f + slack) {
          Fail("int8g two-level error exceeds eff/2", -4);
          return;
        }
      }
    }
  }
  int64_t decoded = 0;
  std::vector<float> inc(static_cast<size_t>(n));
  for (int64_t bytes = 0; bytes <= WireEncodedBytes(WireCodec::kInt8g, n);
       bytes += 97) {
    const int64_t avail = WireDecodableElems(WireCodec::kInt8g, bytes, n);
    if (avail < decoded) {
      Fail("int8g WireDecodableElems not monotone", -4);
      return;
    }
    if (avail > decoded) {
      WireDecodeRange(WireCodec::kInt8g, enc.data(), n, decoded, avail,
                      inc.data() + decoded);
      decoded = avail;
    }
  }
  const int64_t tail = WireDecodableElems(
      WireCodec::kInt8g, WireEncodedBytes(WireCodec::kInt8g, n), n);
  if (tail > decoded) {
    WireDecodeRange(WireCodec::kInt8g, enc.data(), n, decoded, tail,
                    inc.data() + decoded);
    decoded = tail;
  }
  if (decoded != n ||
      std::memcmp(inc.data(), dec.data(), static_cast<size_t>(4 * n)) != 0) {
    Fail("incremental int8g decode diverges from full decode", -4);
  }
}

}  // namespace

int main() {
  FuzzRounds();
  HeaderMismatch();
  Cancellation();
  CodecBf16RoundTrip();
  CodecInt8ErrorBound();
  CodecInt4ErrorBound();
  CodecInt8gErrorBound();
  CodecRingAccumulationBound();
  if (failures.load() != 0) {
    std::fprintf(stderr, "%d failure(s)\n", failures.load());
    return 1;
  }
  std::printf("PASS chunk_exchange_selftest\n");
  return 0;
}
