// Chaos selftest: the full SocketController stack driven with
// HOROVOD_FAULT_INJECT armed, one scenario per named protocol site.
//
// Each scenario asserts the robustness contract of the fast-abort design
// (docs/elastic.md "Failure detection & bounds"): injected drops,
// truncations, and corrupted tags make every rank fail FAST with a
// culprit-naming reason — never hang — while benign injections (delays)
// and healed ones (rendezvous drop + backoff retry) leave results
// bit-correct.  Built plain it is an integration test; built with
// -fsanitize=thread/address/undefined (`make tsan_chaos_selftest` etc.) it
// proves the abort paths themselves are race- and UB-free, which matters
// because they run concurrently with executor lanes mid-collapse.  Run by
// tests/single/test_native_selftests.py.
//
// Hit indices for the data-plane sites are CALIBRATED, not hardcoded: a
// clean run with a never-firing rule armed counts how many times each site
// fires during Initialize (the shm-verdict handshake runs barrier fences
// even when shm is disabled), and later scenarios target `base + 0`, the
// first post-init hit.  This keeps the selftest correct when the init
// handshake gains or loses a fence.

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <thread>
#include <vector>

#include "fault_injection.h"
#include "flight_recorder.h"
#include "metrics.h"
#include "socket_controller.h"

namespace hvdtpu {
int GetLogLevel() { return 4; }  // errors only
void SetLogLevel(int) {}
}  // namespace hvdtpu

using namespace hvdtpu;

namespace {

constexpr int kRanks = 3;

std::atomic<int> failures{0};

void Fail(const char* scenario, int rank, const std::string& what) {
  std::fprintf(stderr, "FAIL [%s] rank %d: %s\n", scenario, rank,
               what.c_str());
  failures.fetch_add(1);
}

int FreePort() {
  Listener probe;
  if (!probe.Listen("127.0.0.1", 0)) return -1;
  return probe.port();
}

struct RankOutcome {
  bool init_ok = false;
  bool completed = false;  // every cycle finished cleanly
  std::string reason;      // abort reason (failure paths) / init error
  double handshake_s = 0;  // failed data op -> reason latched
  int64_t base_hits[kNumFaultSites] = {0};  // own-slot hits after init
};

// One in-process rank.  The failure path mirrors core_api.cc exactly: a
// failed data op is followed by one more ComputeResponses (the abort
// handshake — worker FIN / coordinator sweep + broadcast), and the reason
// the Python layer would surface comes from WaitAbortReason().
void ChaosRank(const char* scenario, int rank, int size, int port, int cycles,
               bool do_barrier, RankOutcome* out) {
  CoreConfig cfg;
  cfg.rank = rank;
  cfg.size = size;
  cfg.rendezvous_addr = "127.0.0.1";
  cfg.rendezvous_port = port;
  SocketController ctl(cfg);
  Status s = ctl.Initialize();
  if (!s.ok()) {
    out->reason = s.reason;
    return;
  }
  out->init_ok = true;
  auto& inj = GlobalFaultInjector();
  for (int site = 0; site < kNumFaultSites; ++site) {
    out->base_hits[site] =
        inj.hits[site][rank].load(std::memory_order_relaxed);
  }
  for (int cycle = 0; s.ok() && cycle < cycles; ++cycle) {
    TensorRequest req;
    req.name = "c" + std::to_string(cycle);
    req.op = OpType::ALLREDUCE;
    req.dtype = DataType::FLOAT32;
    req.nbytes = 1024 * 4;
    req.shape = {1024};
    std::vector<TensorRequest> reqs{req};
    std::vector<Response> resps;
    s = ctl.ComputeResponses(reqs, &resps);
    for (size_t i = 0; s.ok() && i < resps.size(); ++i) {
      Response& r = resps[i];
      if (!r.error.empty()) {
        s = Status::Error(StatusCode::ABORTED, r.error);
        break;
      }
      ctl.SetCurrentSeq(r.seq);
      std::vector<float> buf(1024, static_cast<float>(rank + 1));
      s = ctl.AllreduceBuffer(buf.data(), 1024, DataType::FLOAT32,
                              ReduceOp::SUM, 0);
      const float want = static_cast<float>(size * (size + 1) / 2);
      if (s.ok() && (buf[0] != want || buf[1023] != want)) {
        Fail(scenario, rank, "wrong allreduce result");
        s = Status::Error(StatusCode::ABORTED, "wrong allreduce result");
      }
      if (s.ok() && do_barrier) s = ctl.Barrier(0);
    }
  }
  if (s.ok()) {
    ctl.Farewell();
    ctl.Shutdown();
    out->completed = true;
    return;
  }
  const double t0 = MonotonicSeconds();
  std::vector<TensorRequest> none;
  std::vector<Response> ignored;
  ctl.ComputeResponses(none, &ignored);
  out->reason = ctl.WaitAbortReason();
  if (out->reason.empty()) out->reason = s.reason;
  out->handshake_s = MonotonicSeconds() - t0;
  ctl.Shutdown();
}

std::vector<RankOutcome> RunScenario(const char* name, const std::string& spec,
                                     int cycles, bool do_barrier,
                                     int size = kRanks) {
  std::vector<RankOutcome> out(size);
  ::setenv("HOROVOD_FAULT_INJECT", spec.c_str(), 1);
  std::string err = InitFaultInjection();
  if (!err.empty()) {
    Fail(name, -1, "unexpected spec error: " + err);
    return out;
  }
  int port = FreePort();
  if (port < 0) {
    Fail(name, -1, "no free port");
    return out;
  }
  std::vector<std::thread> threads;
  threads.reserve(size);
  for (int r = 0; r < size; ++r) {
    threads.emplace_back(ChaosRank, name, r, size, port, cycles, do_barrier,
                         &out[r]);
  }
  for (auto& t : threads) t.join();
  return out;
}

void ExpectAllAborted(const char* name,
                      const std::vector<RankOutcome>& out,
                      double bound_s) {
  for (int r = 0; r < static_cast<int>(out.size()); ++r) {
    if (out[r].completed) {
      Fail(name, r, "completed cleanly despite the injected fault");
    } else if (out[r].reason.empty()) {
      Fail(name, r, "aborted without a reason");
    } else if (out[r].init_ok && out[r].handshake_s > bound_s) {
      Fail(name, r,
           "abort handshake took " + std::to_string(out[r].handshake_s) +
               "s (bound " + std::to_string(bound_s) + "s)");
    }
  }
}

}  // namespace

int main() {
  // Force the TCP ring so the ring-send/ring-recv/frame-header sites are
  // on the data path (the shm handshake still runs and votes no), shrink
  // the abort bound and rendezvous backoff to keep the run fast, and keep
  // metrics ON so the abort counters/histogram are exercised concurrently
  // with the collapsing planes (what the sanitizer builds must prove safe).
  ::setenv("HOROVOD_SHM_DISABLE", "1", 1);
  ::setenv("HOROVOD_ABORT_PROPAGATION_TIMEOUT", "1", 1);
  ::setenv("HOROVOD_RENDEZVOUS_BACKOFF_BASE_MS", "10", 1);
  GlobalMetrics().enabled.store(true, std::memory_order_relaxed);

  // --- spec parser: valid accepted, malformed rejected with a message ----
  if (!ParseFaultSpec("ring-send:*:1:delay:250,frame-header:3:0:corrupt-tag",
                      nullptr)
           .empty()) {
    Fail("parse", -1, "valid spec rejected");
  }
  const char* bad[] = {
      "nosite:*:*:drop",        "ring-send:*:*",
      "ring-send:x:*:drop",     "ring-send:*:x:drop",
      "ring-send:*:*:explode",  "ring-send:*:*:delay",
      "ring-send:*:*:drop:arg",
  };
  for (const char* b : bad) {
    if (ParseFaultSpec(b, nullptr).empty()) {
      Fail("parse", -1, std::string("malformed spec accepted: ") + b);
    }
  }

  // --- calibration: armed-but-never-firing rule, clean lockstep run ------
  auto cal = RunScenario("calibrate", "frame-header:200000000:0:drop",
                         /*cycles=*/3, /*do_barrier=*/true);
  for (int r = 0; r < kRanks; ++r) {
    if (!cal[r].completed) {
      Fail("calibrate", r, "did not complete: " + cal[r].reason);
    }
  }
  if (failures.load() != 0) {
    std::printf("FAIL (%d)\n", failures.load());
    return 1;
  }
  const int64_t rs1 = cal[1].base_hits[kFaultRingSend];
  const int64_t rr2 = cal[2].base_hits[kFaultRingRecv];
  const int64_t fh1 = cal[1].base_hits[kFaultFrameHeader];
  const int64_t sf1 = cal[1].base_hits[kFaultShmFence];
  if (rs1 <= 0 || fh1 <= 0) {
    Fail("calibrate", 1, "init fences never hit the ring/frame hooks");
  }

  // --- rendezvous-accept drop: the worker's backoff retry heals it -------
  auto rz = RunScenario("rendezvous", "rendezvous-accept:0:1:drop",
                        /*cycles=*/2, /*do_barrier=*/false);
  for (int r = 0; r < kRanks; ++r) {
    if (!rz[r].completed) {
      Fail("rendezvous", r, "did not recover from the dropped HELLO: " +
                                rz[r].reason);
    }
  }

  // --- delay: benign, results stay bit-correct, counter observes it ------
  const int64_t faults_before =
      GlobalMetrics().faults_injected_total.load(std::memory_order_relaxed);
  auto dl = RunScenario(
      "delay", "ring-send:" + std::to_string(rs1) + ":1:delay:100",
      /*cycles=*/2, /*do_barrier=*/false);
  for (int r = 0; r < kRanks; ++r) {
    if (!dl[r].completed) {
      Fail("delay", r, "delay injection broke the job: " + dl[r].reason);
    }
  }
  if (GlobalMetrics().faults_injected_total.load(std::memory_order_relaxed) <=
      faults_before) {
    Fail("delay", -1, "faults_injected_total never incremented");
  }

  // --- corrupt-tag: every rank fails fast, bounded, no hang --------------
  ExpectAllAborted(
      "corrupt-tag",
      RunScenario("corrupt-tag",
                  "frame-header:" + std::to_string(fh1) + ":1:corrupt-tag",
                  /*cycles=*/2, /*do_barrier=*/false),
      /*bound_s=*/6.0);

  // --- ring-recv drop: dead data socket mid-ring -------------------------
  ExpectAllAborted(
      "ring-recv",
      RunScenario("ring-recv",
                  "ring-recv:" + std::to_string(rr2) + ":2:drop",
                  /*cycles=*/2, /*do_barrier=*/false),
      /*bound_s=*/6.0);

  // --- coordinator-recv drop: the ABORT broadcast names the culprit ------
  const int64_t prop_before =
      GlobalMetrics().abort_propagation_us.count.load(
          std::memory_order_relaxed);
  auto cd = RunScenario("coordinator-recv", "coordinator-recv:0:1:drop",
                        /*cycles=*/2, /*do_barrier=*/false);
  ExpectAllAborted("coordinator-recv", cd, /*bound_s=*/6.0);
  if (cd[2].init_ok && cd[2].reason.find("rank 1") == std::string::npos) {
    Fail("coordinator-recv", 2,
         "survivor's reason does not name the culprit: " + cd[2].reason);
  }
  if (GlobalMetrics().abort_propagation_us.count.load(
          std::memory_order_relaxed) <= prop_before) {
    Fail("coordinator-recv", -1,
         "abort_propagation_us never observed the broadcast latency");
  }

  // --- shm-fence drop: the dissemination fence collapses -----------------
  ExpectAllAborted(
      "shm-fence",
      RunScenario("shm-fence",
                  "shm-fence:" + std::to_string(sf1) + ":1:drop",
                  /*cycles=*/2, /*do_barrier=*/true),
      /*bound_s=*/6.0);

  // --- leader-recv drop: v9 leader tree, a host leader (NOT the
  // coordinator) loses its child mid-cycle.  np=4 over 2 fake hosts puts
  // ranks {2,3} on host 1 with rank 2 as their leader; dropping child 3's
  // cycle frame at leader 2 kills that link, the leader's FIN climbs to
  // the coordinator with the culprit, and every rank — including the
  // orphaned child, which drains the direct ABORT off its coordinator
  // link — aborts bounded with rank 3 named through the tree.
  ::setenv("HOROVOD_HIER_FAKE_HOSTS", "2", 1);
  ::setenv("HOROVOD_CONTROL_TREE", "on", 1);
  auto lr = RunScenario("leader-recv", "leader-recv:0:3:drop",
                        /*cycles=*/2, /*do_barrier=*/false, /*size=*/4);
  ::unsetenv("HOROVOD_CONTROL_TREE");
  ::unsetenv("HOROVOD_HIER_FAKE_HOSTS");
  ExpectAllAborted("leader-recv", lr, /*bound_s=*/6.0);
  if (lr[1].init_ok && lr[1].reason.find("rank 3") == std::string::npos) {
    Fail("leader-recv", 1,
         "worker on the healthy host does not name the culprit through "
         "the tree: " + lr[1].reason);
  }
  if (lr[3].init_ok && lr[3].reason.empty()) {
    Fail("leader-recv", 3, "orphaned child aborted without a reason");
  }

  // --- migration: forensic planes written concurrently with a collapse --
  // A hammer thread drives NoteMigration (replication refreshes plus a
  // migration's manifest/transfer/reassemble phases) while an injected
  // ring drop collapses the job.  The sanitizer builds prove the type-14
  // flight path and the hvd_migrate_* counters are race- and UB-free
  // against the abort machinery (exactly the moment a real migration
  // observes); the plain build asserts the events landed with the
  // documented a/b encoding.
  InitFlightRecorder(true, 4096, "", 0);
  const int64_t mig_before =
      GlobalMetrics().migrate_events_total.load(std::memory_order_relaxed);
  std::atomic<bool> mig_stop{false};
  std::thread mig_hammer([&mig_stop] {
    int64_t n = 0;
    while (!mig_stop.load(std::memory_order_relaxed)) {
      NoteMigration(kMigrateReplicate, 4096, -1);
      NoteMigration(kMigrateManifest, 3, -1);
      NoteMigration(kMigrateTransfer, 4096, static_cast<int>(n % kRanks));
      NoteMigration(kMigrateReassemble, 4096, 1);
      ++n;
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });
  ExpectAllAborted(
      "migrate",
      RunScenario("migrate", "ring-recv:" + std::to_string(rr2) + ":2:drop",
                  /*cycles=*/2, /*do_barrier=*/false),
      /*bound_s=*/6.0);
  mig_stop.store(true);
  mig_hammer.join();
  NoteMigration(kMigrateFallback, 0, -1);
  MetricsRegistry& mm = GlobalMetrics();
  if (mm.migrate_events_total.load(std::memory_order_relaxed) <= mig_before) {
    Fail("migrate", -1, "migrate_events_total never advanced");
  }
  if (mm.migrate_bytes_total.load(std::memory_order_relaxed) <= 0) {
    Fail("migrate", -1, "migrate_bytes_total never accumulated");
  }
  if (mm.migrate_fallbacks_total.load(std::memory_order_relaxed) < 1) {
    Fail("migrate", -1, "migrate_fallbacks_total missed the fallback");
  }
  std::vector<FlightEvent> mig_tail;
  FlightTail(4096, &mig_tail);
  bool saw_transfer = false;
  for (const FlightEvent& e : mig_tail) {
    if (e.type != kFlightMigrate) continue;
    const int phase = e.a >> 8;
    const int src = (e.a & 0xFF) - 1;
    if (phase < kMigrateReplicate || phase > kMigrateFallback) {
      Fail("migrate", -1, "type-14 event with out-of-range phase " +
                              std::to_string(phase));
    }
    if (phase == kMigrateTransfer && src >= 0 && e.b == 4096) {
      saw_transfer = true;
    }
  }
  if (!saw_transfer) {
    Fail("migrate", -1, "no transfer-phase type-14 event recorded");
  }
  ResetFlightRecorderForTest();

  ::unsetenv("HOROVOD_FAULT_INJECT");
  InitFaultInjection();
  if (failures.load() != 0) {
    std::printf("FAIL (%d)\n", failures.load());
    return 1;
  }
  std::printf("PASS\n");
  return 0;
}
