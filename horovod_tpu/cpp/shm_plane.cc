#include "shm_plane.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>

#include "flight_recorder.h"
#include "logging.h"

namespace hvdtpu {

namespace {
constexpr int64_t kGrowQuantum = 2 << 20;  // 2 MiB ftruncate granularity

int64_t RoundUp(int64_t n) {
  return (n + kGrowQuantum - 1) / kGrowQuantum * kGrowQuantum;
}
}  // namespace

// The destructor unlinks unconditionally (not only for the creator): if
// the creating rank is SIGKILLed mid-job, the next elastic generation
// opens a differently-named region (new rendezvous port), so nobody would
// ever unlink the orphan — the survivors' teardown must.  Unlinking a
// name other members still have mapped is safe (POSIX keeps the mapping),
// and a later same-named incarnation re-creates after its own
// stale-unlink, so a racing unlink at worst downgrades that set to the
// TCP ring via the AND-voted open verdict.
ShmRegion::~ShmRegion() { Close(true); }

Status ShmRegion::Open(const std::string& name, bool creator) {
  name_ = name;
  creator_ = creator;
  if (creator) {
    ::shm_unlink(name.c_str());  // stale region from a killed job
    fd_ = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  } else {
    fd_ = ::shm_open(name.c_str(), O_RDWR, 0600);
  }
  if (fd_ < 0) {
    return Status::Error(StatusCode::PRECONDITION_ERROR,
                         "shm_open(" + name + ") failed");
  }
  int64_t initial = RoundUp(kHeaderBytes + kGrowQuantum);
  if (creator && ::ftruncate(fd_, initial) != 0) {
    Close(true);
    return Status::Error(StatusCode::PRECONDITION_ERROR,
                         "ftruncate(" + name + ") failed");
  }
  if (!creator) {
    struct stat st {};
    if (::fstat(fd_, &st) != 0 || st.st_size < initial) {
      Close(false);
      return Status::Error(StatusCode::PRECONDITION_ERROR,
                           "shm region " + name + " has unexpected size");
    }
  }
  map_ = ::mmap(nullptr, initial, PROT_READ | PROT_WRITE, MAP_SHARED, fd_, 0);
  if (map_ == MAP_FAILED) {
    map_ = nullptr;
    Close(creator);
    return Status::Error(StatusCode::PRECONDITION_ERROR,
                         "mmap(" + name + ") failed");
  }
  cap_ = initial;
  if (FlightOn()) FlightRecord(kFlightShmMap, 0, cap_);
  return Status::OK();
}

Status ShmRegion::EnsureCapacity(int64_t data_bytes, bool creator,
                                 const std::function<Status()>& barrier) {
  int64_t required = kHeaderBytes + data_bytes;
  if (required <= cap_) return Status::OK();
  int64_t new_cap = RoundUp(std::max(required, cap_ * 2));
  // No reader may still use the old mapping, and nobody may remap before
  // the creator's ftruncate: two barriers bracket the grow.
  Status st = barrier();
  if (!st.ok()) return st;
  if (creator && ::ftruncate(fd_, new_cap) != 0) {
    return Status::Error(StatusCode::PRECONDITION_ERROR,
                         "shm grow ftruncate(" + name_ + ") failed");
  }
  st = barrier();
  if (!st.ok()) return st;
  ::munmap(map_, cap_);
  map_ = ::mmap(nullptr, new_cap, PROT_READ | PROT_WRITE, MAP_SHARED, fd_, 0);
  if (map_ == MAP_FAILED) {
    map_ = nullptr;
    return Status::Error(StatusCode::PRECONDITION_ERROR,
                         "shm grow mmap(" + name_ + ") failed");
  }
  cap_ = new_cap;
  if (FlightOn()) FlightRecord(kFlightShmMap, 1, cap_);
  return Status::OK();
}

void ShmRegion::Close(bool unlink) {
  if (map_ != nullptr) {
    ::munmap(map_, cap_);
    map_ = nullptr;
  }
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
  if (unlink && !name_.empty()) ::shm_unlink(name_.c_str());
  cap_ = 0;
}

}  // namespace hvdtpu
