// Online autotuning of fusion threshold and cycle time.
//
// Reference: horovod/common/parameter_manager.h (ParameterManager +
// BayesianOptimization over fusion threshold / cycle time with a Gaussian
// process and Expected Improvement; SURVEY.md §2.1).  This build implements
// the same joint optimization natively: the 2-D knob space is normalized to
// the unit square in log2 scale, a GP with RBF kernel is fit to the scored
// windows (small dense Cholesky — the sample count is the number of 2-second
// windows, so the cost is trivial), and the next configuration maximizes EI
// over a candidate grid.  Score = negotiated tensor bytes per second, logged
// to HOROVOD_AUTOTUNE_LOG exactly as the reference does.
#pragma once

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace hvdtpu {

// Gaussian-process regression + Expected Improvement over two continuous
// knobs on the unit square plus six CATEGORICAL knobs (reference:
// ParameterManager also tunes categorical flags like cache/hierarchical
// allreduce — categorical coordinates in the same GP are the cheap
// TPU-native form; x2 = announce-cache {0,1}, x3 = hierarchical allreduce
// {0,1}, x4 = wire compression {0, 0.5, 1} for {none, bf16, int8},
// x5 = device-plane codec {0, 1/3, 2/3, 1} for {none, int8, int4, int8g}
// (ordinal in codec aggressiveness like x4), x6 = device-ring schedule
// {0, 0.5, 1} for {ring, bidi, torus}, x7 = data plane {0, 1} for
// {eager explicit collectives, gspmd compiler-inserted}).
// Exposed for the synthetic-surface self-test (autotune_selftest.cc).
class BayesianOptimizer {
 public:
  // Observations are (x in [0,1]^2, x2/x3/x7 in {0,1}, x4/x6 in {0,0.5,1},
  // x5 in {0,1/3,2/3,1}, score); scores are internally max-normalized so
  // the kernel scales stay dimensionless.
  void AddSample(double x0, double x1, double x2, double x3, double x4,
                 double x5, double x6, double x7, double score);
  // Pre-plane-coordinate form (x7 = 0, the eager plane) — keeps the
  // selftest's historical call sites and any 7-coordinate caller exact.
  void AddSample(double x0, double x1, double x2, double x3, double x4,
                 double x5, double x6, double score) {
    AddSample(x0, x1, x2, x3, x4, x5, x6, 0.0, score);
  }
  // Next point to try: argmax EI over a jittered grid x the categorical
  // levels.  Falls back to latin-square-ish seed points for the first few
  // calls.
  void Suggest(double* x0, double* x1, double* x2, double* x3, double* x4,
               double* x5, double* x6, double* x7);
  void Suggest(double* x0, double* x1, double* x2, double* x3, double* x4,
               double* x5, double* x6) {
    double x7;
    Suggest(x0, x1, x2, x3, x4, x5, x6, &x7);
  }
  // Best observed sample.
  void Best(double* x0, double* x1, double* x2, double* x3, double* x4,
            double* x5, double* x6, double* x7, double* score) const;
  void Best(double* x0, double* x1, double* x2, double* x3, double* x4,
            double* x5, double* x6, double* score) const {
    double x7;
    Best(x0, x1, x2, x3, x4, x5, x6, &x7, score);
  }
  int num_samples() const { return static_cast<int>(xs_.size()); }
  // When the x3 knob cannot take effect (topology not hierarchical), pin
  // it to 0 so the EI search does not waste half its grid on a dead arm.
  void set_tune_x3(bool v) { tune_x3_ = v; }
  // Same pinning rule for x4 (wire compression: no all-cross-host ring).
  void set_tune_x4(bool v) { tune_x4_ = v; }
  // Same pinning rule for x5 (device-plane codec: no usable device plane).
  void set_tune_x5(bool v) { tune_x5_ = v; }
  // Same pinning rule for x6 (device-ring schedule: no device plane, or a
  // member count that admits only the unidirectional ring).
  void set_tune_x6(bool v) { tune_x6_ = v; }
  // Same pinning rule for x7 (data plane: no multi-device mesh, or the
  // quantized device codec owns the traced reduction).  Unlike x3..x6 this
  // knob defaults OFF: the 7-coordinate compatibility overloads record
  // every sample at x7 = 0, so exploring x7 without an 8-coordinate caller
  // would chase predictions no sample can ever confirm.
  void set_tune_x7(bool v) { tune_x7_ = v; }

 private:
  void FitGP();
  void Predict(double x0, double x1, double x2, double x3, double x4,
               double x5, double x6, double x7, double* mean,
               double* var) const;

  struct Pt {
    double x0, x1, x2, x3, x4, x5, x6, x7;
  };
  std::vector<Pt> xs_;
  std::vector<double> ys_;      // raw scores
  std::vector<double> alpha_;   // K^-1 y_norm
  std::vector<double> chol_;    // Cholesky factor of K (row-major lower)
  double y_max_ = 0;
  unsigned rng_ = 0x9e3779b9u;
  bool tune_x3_ = true;
  bool tune_x4_ = true;
  bool tune_x5_ = true;
  bool tune_x6_ = true;
  bool tune_x7_ = false;  // opt-in: see set_tune_x7
};

class ParameterManager {
 public:
  // hierarchical: initial value of the hierarchical-allreduce knob.
  // hier_tunable: whether the data plane can act on it at all (a
  // hierarchical topology exists); when false the knob is pinned off and
  // the GP never explores that arm.  wire_comp / wire_tunable: same pair
  // for the wire-compression codec (0=none, 1=bf16, 2=int8), pinned when
  // no all-cross-host ring exists.  qdev_comp / qdev_tunable: same pair
  // for the device-plane codec (0=none, 1=int8, 2=int4, 3=int8g), pinned
  // when the process has no usable jax device plane.  qdev_sched /
  // sched_tunable: same pair for the device-ring schedule (0=ring,
  // 1=bidi, 2=torus), pinned alongside qdev or when the plane's member
  // count admits only the unidirectional ring.  data_plane /
  // plane_tunable: same pair for the in-jit gradient-exchange plane
  // (0=eager, 1=gspmd), pinned when no multi-device mesh exists or the
  // quantized device codec owns the traced reduction.
  void Initialize(int64_t fusion_threshold, double cycle_time_ms,
                  const std::string& log_path, bool hierarchical = false,
                  bool hier_tunable = false, int wire_comp = 0,
                  bool wire_tunable = false, int qdev_comp = 0,
                  bool qdev_tunable = false, int qdev_sched = 0,
                  bool sched_tunable = false, int data_plane = 0,
                  bool plane_tunable = false);
  ~ParameterManager();

  // Record bytes covered by emitted responses.
  void RecordBytes(int64_t bytes);

  // Called every cycle; returns true when parameters changed.
  bool Tick(int64_t* fusion_threshold, double* cycle_time_ms);

  // Test hook: force a window boundary with an externally supplied score.
  void ScoreWindowForTest(double score) { Score(score); }
  int64_t fusion() const { return fusion_; }
  double cycle_ms() const { return cycle_ms_; }
  double best_score() const { return best_score_; }
  // Categorical knob: should workers announce steady-state tensors via
  // response-cache ids?  (Per-rank safe: announcing full requests never
  // desyncs the deterministic cache-insert order.)
  bool announce_cache() const { return cache_use_; }
  // Categorical knob: hierarchical allreduce (shm-local reduce ->
  // leader-only cross-host ring -> shm-local broadcast).  Coordinator-only:
  // the decision rides in each serialized response, so only the
  // coordinator's copy of this knob matters.
  bool hierarchical() const { return hier_use_; }
  // Categorical knob: wire-compression codec for cross-host ring hops
  // (0=none, 1=bf16, 2=int8 — hvdtpu::WireCodec).  Coordinator-only for
  // the same reason as hierarchical().
  int wire_compression() const { return wire_use_; }
  // Categorical knob: device-plane codec (0=none, 1=int8, 2=int4,
  // 3=int8g — ops/quantize.py's DEVICE_WIRE_CODECS order).  The Python
  // side polls it and flips the in-jit/eager quantized ring on the next
  // trace; per-rank consistent because config (and therefore the tunable
  // bit) is rank-uniform.
  int qdev() const { return qdev_use_; }
  // Categorical knob: device-ring schedule (0=ring, 1=bidi, 2=torus —
  // ops/collectives.py's resolve_device_schedule codomain).  Polled by
  // the Python side together with qdev().
  int qdev_sched() const { return qdev_sched_use_; }
  // Categorical knob: in-jit gradient-exchange plane (0=eager, 1=gspmd —
  // ops/gspmd_plane.py's resolve_plane codomain).  Polled like qdev():
  // per-rank consistent because the tunable bit is rank-uniform, and a
  // flip only takes effect at the next optimizer construction/trace.
  int plane() const { return plane_use_; }

 private:
  void Score(double score);
  void Log(double score);

  bool active_ = false;
  int64_t bytes_ = 0;
  double window_start_ = 0;
  double window_s_ = 2.0;

  int64_t fusion_ = 0;
  double cycle_ms_ = 1.0;
  bool cache_use_ = true;
  bool hier_use_ = false;
  bool hier_tunable_ = false;
  int wire_use_ = 0;
  bool wire_tunable_ = false;
  int qdev_use_ = 0;
  bool qdev_tunable_ = false;
  int qdev_sched_use_ = 0;
  bool sched_tunable_ = false;
  int plane_use_ = 0;
  bool plane_tunable_ = false;
  double best_score_ = -1;
  int64_t best_fusion_ = 0;
  double best_cycle_ = 1.0;
  bool best_cache_ = true;
  bool best_hier_ = false;
  int best_wire_ = 0;
  int best_qdev_ = 0;
  int best_qdev_sched_ = 0;
  int best_plane_ = 0;
  int warmup_windows_ = 1;
  int windows_since_best_ = 0;
  bool converged_ = false;
  BayesianOptimizer bo_;
  FILE* log_ = nullptr;
};

}  // namespace hvdtpu
