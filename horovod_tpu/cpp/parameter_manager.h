// Online autotuning of fusion threshold and cycle time.
//
// Reference: horovod/common/parameter_manager.h (ParameterManager with
// Bayesian optimization; SURVEY.md §2.1).  This build uses coordinate-wise
// hill climbing on the same score (negotiated tensor bytes per second),
// which converges for the two monotone-ish knobs involved and needs no
// linear-algebra dependency; the tuned values flow back into the cycle loop
// exactly as in the reference (HOROVOD_AUTOTUNE / HOROVOD_AUTOTUNE_LOG).
#pragma once

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace hvdtpu {

class ParameterManager {
 public:
  void Initialize(int64_t fusion_threshold, double cycle_time_ms,
                  const std::string& log_path);
  ~ParameterManager();

  // Record bytes covered by emitted responses.
  void RecordBytes(int64_t bytes);

  // Called every cycle; returns true when parameters changed.
  bool Tick(int64_t* fusion_threshold, double* cycle_time_ms);

 private:
  void Score(double score);
  void Log(double score);

  bool active_ = false;
  int64_t bytes_ = 0;
  double window_start_ = 0;
  double window_s_ = 2.0;

  int64_t fusion_ = 0;
  double cycle_ms_ = 1.0;
  int knob_ = 0;       // 0: fusion, 1: cycle
  int direction_ = 1;  // +1 double, -1 halve
  double best_score_ = -1;
  int64_t best_fusion_ = 0;
  double best_cycle_ = 1.0;
  int warmup_windows_ = 1;
  FILE* log_ = nullptr;
};

}  // namespace hvdtpu
