// Control-plane soak: 256 in-process ranks (threads + loopback sockets)
// driving the negotiation lock-step with CoreConfig.ctrl_only, which skips
// the O(n^2) data mesh / shm / hierarchy so one machine can hold np=256.
//
// Two phases over 16 fake hosts (HOROVOD_HIER_FAKE_HOSTS):
//   flat  (HOROVOD_CONTROL_TREE=off): every worker talks to rank 0.
//   tree  (HOROVOD_CONTROL_TREE=on):  host leaders aggregate, so rank 0
//         sees (local ranks - 1) + (hosts - 1) frames per cycle.
// The acceptance assert is the tentpole claim made mechanically checkable:
// coordinator inbound control messages per cycle drop O(n) -> O(hosts),
// i.e. flat >= 8x tree at 256 ranks / 16 hosts (255 vs 30 = 8.5x).
//
// Rendezvous runs with HOROVOD_RENDEZVOUS_ACCEPTORS=8 so the 255-way HELLO
// herd also soaks the sharded acceptor path.  Built with the sanitizer
// matrix (`make tsan_ctrl_soak_selftest` etc.) this proves the leader
// cycle, aggregate parsing, and counter paths race-free at scale.  Run by
// tests/single/test_native_selftests.py and `make selftest`.

#include <sys/resource.h>

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "fleet_telemetry.h"
#include "metrics.h"
#include "socket_controller.h"

namespace hvdtpu {
int GetLogLevel() { return 4; }  // errors only
void SetLogLevel(int) {}
}  // namespace hvdtpu

using namespace hvdtpu;

namespace {

int failures = 0;

void Fail(const char* phase, int rank, const std::string& what) {
  std::fprintf(stderr, "FAIL [%s] rank %d: %s\n", phase, rank, what.c_str());
  ++failures;
}

int FreePort() {
  Listener probe;
  if (!probe.Listen("127.0.0.1", 0)) return -1;
  return probe.port();
}

// When set, every rank notes one replication refresh per negotiation cycle
// — the soak's migration-aware row: 256 concurrent NoteMigration writers
// against the live control plane.
std::atomic<bool> g_migrate{false};

// Reusable rendezvous-style barrier: the main thread participates so it can
// snapshot the coordinator's counters while every rank thread is parked
// between negotiation phases (no cycle in flight).
class Barrier {
 public:
  explicit Barrier(int n) : n_(n) {}
  void Wait() {
    std::unique_lock<std::mutex> lk(mu_);
    const int gen = gen_;
    if (++count_ == n_) {
      count_ = 0;
      ++gen_;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] { return gen != gen_; });
    }
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  const int n_;
  int count_ = 0;
  int gen_ = 0;
};

struct Phase {
  Barrier init, start, done, exit_;
  explicit Phase(int n) : init(n), start(n), done(n), exit_(n) {}
};

void SoakRank(const char* phase_name, int rank, int size, int port,
              int cycles, Phase* ph, SocketController** slot,
              std::string* err) {
  CoreConfig cfg;
  cfg.rank = rank;
  cfg.size = size;
  cfg.rendezvous_addr = "127.0.0.1";
  cfg.rendezvous_port = port;
  cfg.ctrl_only = true;
  SocketController ctl(cfg);
  *slot = &ctl;
  Status s = ctl.Initialize();
  if (!s.ok()) {
    *err = "init: " + s.reason;
    *slot = nullptr;
  }
  ph->init.Wait();
  ph->start.Wait();
  if (err->empty()) {
    for (int cycle = 0; cycle < cycles; ++cycle) {
      TensorRequest req;
      req.name = "soak" + std::to_string(cycle);
      req.op = OpType::ALLREDUCE;
      req.dtype = DataType::FLOAT32;
      req.nbytes = 4 * 16;
      req.shape = {16};
      std::vector<TensorRequest> reqs{req};
      std::vector<Response> resps;
      s = ctl.ComputeResponses(reqs, &resps);
      if (!s.ok()) {
        *err = "cycle " + std::to_string(cycle) + ": " + s.reason;
        break;
      }
      if (resps.size() != 1 || !resps[0].error.empty()) {
        *err = "cycle " + std::to_string(cycle) + ": bad response";
        break;
      }
      if (g_migrate.load(std::memory_order_relaxed)) {
        NoteMigration(kMigrateReplicate, req.nbytes, -1);
      }
    }
  }
  ph->done.Wait();
  ph->exit_.Wait();
  if (err->empty()) ctl.Farewell();
  ctl.Shutdown();
  *slot = nullptr;
}

// Runs one negotiation phase at `size` ranks and returns the coordinator's
// inbound control messages per cycle (measured between two full-quiescence
// barriers, so rendezvous and farewell traffic never pollute the number).
// `fleet_sources`, when non-null, receives the coordinator's stored
// fleet-sketch source count at the same quiescent point.
int64_t RunPhase(const char* name, const char* tree_mode, int size,
                 int cycles, int* fleet_sources = nullptr) {
  ::setenv("HOROVOD_CONTROL_TREE", tree_mode, 1);
  const int port = FreePort();
  if (port < 0) {
    Fail(name, -1, "no free port");
    return -1;
  }
  Phase ph(size + 1);
  std::vector<SocketController*> ctls(size, nullptr);
  std::vector<std::string> errs(size);
  std::vector<std::thread> threads;
  threads.reserve(size);
  for (int r = 0; r < size; ++r) {
    threads.emplace_back(SoakRank, name, r, size, port, cycles, &ph,
                         &ctls[r], &errs[r]);
  }
  ph.init.Wait();
  int64_t ms0 = 0, mr0 = 0, bs0 = 0, br0 = 0;
  if (ctls[0]) ctls[0]->CtrlPlaneStats(&ms0, &mr0, &bs0, &br0);
  ph.start.Wait();
  ph.done.Wait();
  int64_t ms1 = 0, mr1 = 0, bs1 = 0, br1 = 0;
  if (ctls[0]) ctls[0]->CtrlPlaneStats(&ms1, &mr1, &bs1, &br1);
  if (fleet_sources != nullptr && ctls[0]) {
    *fleet_sources = ctls[0]->FleetSourceCountForTest();
  }
  ph.exit_.Wait();
  for (auto& t : threads) t.join();
  for (int r = 0; r < size; ++r) {
    if (!errs[r].empty()) Fail(name, r, errs[r]);
  }
  if (failures != 0) return -1;
  const int64_t recv_per_cycle = (mr1 - mr0) / cycles;
  std::printf(
      "[%s] np=%d cycles=%d coordinator: recv %lld msgs/cycle "
      "(%lld bytes/cycle), sent %lld msgs/cycle\n",
      name, size, cycles, static_cast<long long>(recv_per_cycle),
      static_cast<long long>((br1 - br0) / cycles),
      static_cast<long long>((ms1 - ms0) / cycles));
  return recv_per_cycle;
}

}  // namespace

int main() {
  // 256 in-process ranks keep both ends of every control socket in one
  // process; don't depend on the caller's `ulimit -n`.
  struct rlimit rl;
  if (::getrlimit(RLIMIT_NOFILE, &rl) == 0 && rl.rlim_cur < rl.rlim_max) {
    rl.rlim_cur = rl.rlim_max;
    ::setrlimit(RLIMIT_NOFILE, &rl);
  }
  // CTRL_SOAK_NP / CTRL_SOAK_HOSTS let a developer push this to
  // np=512 by hand; CI runs the 256/16 acceptance geometry.
  int np = 256;
  int hosts = 16;
  if (const char* env = ::getenv("CTRL_SOAK_NP")) np = std::atoi(env);
  if (const char* env = ::getenv("CTRL_SOAK_HOSTS")) hosts = std::atoi(env);
  if (np < 16 || hosts < 2 || np % hosts != 0) {
    std::fprintf(stderr, "bad soak geometry np=%d hosts=%d\n", np, hosts);
    return 1;
  }
  ::setenv("HOROVOD_HIER_FAKE_HOSTS", std::to_string(hosts).c_str(), 1);
  ::setenv("HOROVOD_RENDEZVOUS_ACCEPTORS", "8", 1);
  ::setenv("HOROVOD_RENDEZVOUS_BACKOFF_BASE_MS", "10", 1);
  ::setenv("HOROVOD_ABORT_PROPAGATION_TIMEOUT", "2", 1);

  const int cycles = 3;
  const int64_t flat = RunPhase("flat", "off", np, cycles);
  const int64_t tree = RunPhase("tree", "on", np, cycles);
  if (failures == 0 && (flat < 0 || tree <= 0)) {
    Fail("soak", -1, "phase produced no measurement");
  }
  if (failures == 0) {
    // Flat: one frame from each of the other np-1 ranks per cycle.
    if (flat < np - 1) {
      Fail("flat", 0,
           "coordinator saw " + std::to_string(flat) +
               " msgs/cycle, expected >= " + std::to_string(np - 1));
    }
    // Tree: local children + remote leaders only.
    const int64_t tree_expect = (np / hosts - 1) + (hosts - 1);
    if (tree != tree_expect) {
      Fail("tree", 0,
           "coordinator saw " + std::to_string(tree) +
               " msgs/cycle, expected " + std::to_string(tree_expect));
    }
    // The acceptance bar: O(n) -> O(hosts) is at least an 8x cut here.
    if (tree > 0 && flat < 8 * tree) {
      Fail("soak", -1,
           "flat/tree ratio " + std::to_string(flat) + "/" +
               std::to_string(tree) + " is below the required 8x");
    }
  }

  // Migration-aware row: the same tree geometry with every rank noting a
  // peer-shard replication refresh per cycle.  Proves np=256 concurrent
  // NoteMigration writers are race-free against the live control plane
  // (sanitizer builds) and that forensic noting does not perturb the
  // per-cycle control-message shape.
  if (failures == 0) {
    GlobalMetrics().enabled.store(true, std::memory_order_relaxed);
    const int64_t mig0 =
        GlobalMetrics().migrate_events_total.load(std::memory_order_relaxed);
    g_migrate.store(true, std::memory_order_relaxed);
    const int64_t tree_mig = RunPhase("tree+migrate", "on", np, cycles);
    g_migrate.store(false, std::memory_order_relaxed);
    const int64_t mig_delta =
        GlobalMetrics().migrate_events_total.load(std::memory_order_relaxed) -
        mig0;
    const int64_t tree_expect = (np / hosts - 1) + (hosts - 1);
    if (mig_delta < static_cast<int64_t>(np) * cycles) {
      Fail("tree+migrate", -1,
           "migrate_events_total advanced " + std::to_string(mig_delta) +
               ", expected >= " + std::to_string(np * cycles));
    }
    if (tree_mig != tree_expect) {
      Fail("tree+migrate", 0,
           "replication noting perturbed the control plane: " +
               std::to_string(tree_mig) + " msgs/cycle, expected " +
               std::to_string(tree_expect));
    }
  }

  // Fleet-telemetry row (protocol v11): the same tree geometry with the
  // metrics registry + sketch sections live on all 256 in-process ranks.
  // Asserts the sketch sections do not perturb the per-cycle control-
  // message shape and that the coordinator stored exactly one cumulative
  // sketch per direct source (local children + remote leaders) — the
  // O(hosts) fleet-state claim made mechanically checkable.  (Bucket
  // exactness is covered by the multi-process tests: all threads here
  // share one global registry, so per-rank dumps are not meaningful.)
  if (failures == 0) {
    GlobalMetrics().enabled.store(true, std::memory_order_relaxed);
    GlobalFleetTelemetry().enabled.store(true, std::memory_order_relaxed);
    const int64_t merged0 = GlobalMetrics().fleet_sketches_merged_total.load(
        std::memory_order_relaxed);
    int fleet_sources = -1;
    const int64_t tree_sk =
        RunPhase("tree+sketch", "on", np, cycles, &fleet_sources);
    const int64_t tree_expect = (np / hosts - 1) + (hosts - 1);
    if (tree_sk != tree_expect) {
      Fail("tree+sketch", 0,
           "sketch sections perturbed the control plane: " +
               std::to_string(tree_sk) + " msgs/cycle, expected " +
               std::to_string(tree_expect));
    }
    if (fleet_sources != tree_expect) {
      Fail("tree+sketch", 0,
           "coordinator stored " + std::to_string(fleet_sources) +
               " fleet sources, expected " + std::to_string(tree_expect));
    }
    const int64_t merged =
        GlobalMetrics().fleet_sketches_merged_total.load(
            std::memory_order_relaxed) -
        merged0;
    if (merged < tree_expect) {
      Fail("tree+sketch", 0,
           "fleet_sketches_merged_total advanced " + std::to_string(merged) +
               ", expected >= " + std::to_string(tree_expect));
    }
  }

  if (failures != 0) {
    std::printf("FAIL (%d)\n", failures);
    return 1;
  }
  std::printf("PASS\n");
  return 0;
}
