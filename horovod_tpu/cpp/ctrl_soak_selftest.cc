// Control-plane soak: np in-process ranks (threads + loopback sockets)
// driving the negotiation lock-step with CoreConfig.ctrl_only, which skips
// the O(n^2) data mesh / shm / hierarchy so one machine can hold np=1024.
//
// Default geometry is np=256 over 16 fake hosts (HOROVOD_HIER_FAKE_HOSTS);
// CTRL_SOAK_NP=1024 CTRL_SOAK_HOSTS=64 is the pod-scale acceptance row.
// The arm grid covers the v12 adaptive-depth tree end to end:
//
//   flat / tree       coordinator msgs/cycle drops O(n) -> O(fanout): flat
//                     is >= 8x tree, and tree inbound matches the model of
//                     ComputeCtrlTree exactly (auto depth).
//   tree+d2 / tree+d3 forced HOROVOD_CONTROL_TREE_DEPTH shapes: depth 2 is
//                     bit-identical to the v9 two-level tree, depth 3
//                     inserts super-leaders and keeps coordinator fan-in
//                     <= fanout + local slack.
//   tree+migrate      np concurrent NoteMigration writers against the live
//                     plane leave the msgs/cycle shape unperturbed.
//   tree+sketch       fleet-telemetry sketches at the auto depth: exactly
//                     one stored source per direct child, and the fleet
//                     sum stays within the replace-not-add bound.
//   tree+churn        tenant churn: every rank re-registers a fresh
//                     process set each cycle and retires last cycle's,
//                     with requests riding the churned set.
//   tree+evict        autopilot-style eviction mid-soak: one whole host
//                     (leader + workers) departs cleanly between cycles at
//                     depth 3; survivors renegotiate on a survivor set and
//                     finish — the BYE-releases-the-subtree contract.
//   chaos+*           fault-injected death at every tree level (worker,
//                     mid-level leader via the v12 super-recv site, super-
//                     leader, and the depth-2 host leader): every rank
//                     aborts bounded and survivors outside the dead branch
//                     name the exact culprit rank + host.
//
// Rendezvous runs with HOROVOD_RENDEZVOUS_ACCEPTORS=8 so the HELLO herd
// also soaks the sharded acceptor path.  Built with the sanitizer matrix
// (`make tsan_ctrl_soak_selftest` etc.) this proves the leader cycle,
// super-leader aggregate merge, abort relay, and counter paths race-free
// at scale.  CTRL_SOAK_ARMS=pod trims to the acceptance-critical arms
// (adaptive shape, sketch merge, mid-level death) for the TSan pod row.
// Run by tests/single/test_native_selftests.py and `make selftest`.

#include <sys/resource.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "fleet_telemetry.h"
#include "fault_injection.h"
#include "metrics.h"
#include "socket_controller.h"

namespace hvdtpu {
int GetLogLevel() { return 4; }  // errors only
void SetLogLevel(int) {}
}  // namespace hvdtpu

using namespace hvdtpu;

namespace {

int failures = 0;

void Fail(const char* phase, int rank, const std::string& what) {
  std::fprintf(stderr, "FAIL [%s] rank %d: %s\n", phase, rank, what.c_str());
  ++failures;
}

int FreePort() {
  Listener probe;
  if (!probe.Listen("127.0.0.1", 0)) return -1;
  return probe.port();
}

// When set, every rank notes one replication refresh per negotiation cycle
// — the soak's migration-aware row: np concurrent NoteMigration writers
// against the live control plane.
std::atomic<bool> g_migrate{false};
// When set, every rank registers a fresh process set at the top of each
// cycle, announces on it, and removes the previous cycle's set — the
// tenant-churn row (per-rank tables mutate symmetrically, so ids agree).
std::atomic<bool> g_churn{false};
// When set, every rank seeds one negotiation-wait observation before the
// first cycle, so fleet sketches carry real counts (the soak bypasses the
// core_api queue where the histogram is normally fed).
std::atomic<bool> g_observe{false};

// Mirror of ComputeCtrlTree's host grouping + clustering pass (pure
// function of the geometry), so every arm can compute the coordinator's
// expected fan-in and pick chaos targets without asking the controller.
struct TreeModel {
  std::vector<int> leaders;      // first rank of each fake host
  std::map<int, int> parent_of;  // non-root leader -> parent (0 = coord)
  int depth = 2;
  int coord_children = 0;  // host-0 workers + coordinator's agg children
};

TreeModel ModelTree(int np, int hosts, int fanout, int forced_depth) {
  TreeModel m;
  const int per = np / hosts;
  for (int h = 0; h < hosts; ++h) m.leaders.push_back(h * per);
  std::vector<int> top = m.leaders;
  int levels = 1;
  while (true) {
    const int non_root = static_cast<int>(top.size()) - 1;
    const bool grow = (forced_depth > 0)
                          ? (levels < forced_depth - 1 && non_root > 1)
                          : (non_root > fanout);
    if (!grow) break;
    const int n_clusters = (non_root + fanout - 1) / fanout;
    std::vector<int> next = {0};
    for (int c = 0; c < n_clusters; ++c) {
      const int lo = 1 + static_cast<int>(
                             static_cast<int64_t>(c) * non_root / n_clusters);
      const int hi = 1 + static_cast<int>(static_cast<int64_t>(c + 1) *
                                          non_root / n_clusters);
      const int head = top[lo];
      next.push_back(head);
      for (int i = lo + 1; i < hi; ++i) m.parent_of[top[i]] = head;
    }
    top.swap(next);
    ++levels;
  }
  for (size_t i = 1; i < top.size(); ++i) m.parent_of[top[i]] = 0;
  m.depth = levels + 1;
  m.coord_children = (per - 1) + (static_cast<int>(top.size()) - 1);
  return m;
}

void SetDepthEnv(int depth) {
  if (depth <= 0) {
    ::unsetenv("HOROVOD_CONTROL_TREE_DEPTH");
  } else {
    ::setenv("HOROVOD_CONTROL_TREE_DEPTH", std::to_string(depth).c_str(), 1);
  }
}

// Reusable rendezvous-style barrier: the main thread participates so it can
// snapshot the coordinator's counters while every rank thread is parked
// between negotiation phases (no cycle in flight).
class Barrier {
 public:
  explicit Barrier(int n) : n_(n) {}
  void Wait() {
    std::unique_lock<std::mutex> lk(mu_);
    const int gen = gen_;
    if (++count_ == n_) {
      count_ = 0;
      ++gen_;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] { return gen != gen_; });
    }
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  const int n_;
  int count_ = 0;
  int gen_ = 0;
};

struct Phase {
  Barrier init, start, done, exit_;
  explicit Phase(int n) : init(n), start(n), done(n), exit_(n) {}
};

// One lock-step allreduce negotiation on `ctl`; "" on success.
std::string OneCycle(SocketController* ctl, const std::string& name,
                     int psid) {
  TensorRequest req;
  req.name = name;
  req.op = OpType::ALLREDUCE;
  req.dtype = DataType::FLOAT32;
  req.nbytes = 4 * 16;
  req.shape = {16};
  req.process_set_id = psid;
  std::vector<TensorRequest> reqs{req};
  std::vector<Response> resps;
  Status s = ctl->ComputeResponses(reqs, &resps);
  if (!s.ok()) return s.reason;
  if (resps.size() != 1 || !resps[0].error.empty()) {
    return resps.empty() ? "no response" : "bad response: " + resps[0].error;
  }
  return "";
}

void SoakRank(const char* phase_name, int rank, int size, int port,
              int cycles, Phase* ph, SocketController** slot,
              std::string* err) {
  CoreConfig cfg;
  cfg.rank = rank;
  cfg.size = size;
  cfg.rendezvous_addr = "127.0.0.1";
  cfg.rendezvous_port = port;
  cfg.ctrl_only = true;
  SocketController ctl(cfg);
  *slot = &ctl;
  Status s = ctl.Initialize();
  if (!s.ok()) {
    *err = "init: " + s.reason;
    *slot = nullptr;
  }
  if (g_observe.load(std::memory_order_relaxed)) {
    GlobalMetrics().negotiation_wait_us.ObserveUs(100 + rank % 7);
  }
  ph->init.Wait();
  ph->start.Wait();
  if (err->empty()) {
    std::vector<int> world(size);
    for (int r = 0; r < size; ++r) world[r] = r;
    int prev_psid = -1;
    for (int cycle = 0; cycle < cycles; ++cycle) {
      int psid = 0;
      if (g_churn.load(std::memory_order_relaxed)) {
        // Tenant churn: register this cycle's set before announcing on it,
        // retire the previous cycle's after.  Every rank runs the same
        // sequence, so the per-rank tables assign identical ids.
        psid = ctl.process_sets().Add(world);
      }
      std::string e =
          OneCycle(&ctl, "soak" + std::to_string(cycle), psid);
      if (!e.empty()) {
        *err = "cycle " + std::to_string(cycle) + ": " + e;
        break;
      }
      if (g_churn.load(std::memory_order_relaxed)) {
        if (prev_psid > 0) ctl.process_sets().Remove(prev_psid);
        prev_psid = psid;
      }
      if (g_migrate.load(std::memory_order_relaxed)) {
        NoteMigration(kMigrateReplicate, 4 * 16, -1);
      }
    }
  }
  ph->done.Wait();
  ph->exit_.Wait();
  if (err->empty()) ctl.Farewell();
  ctl.Shutdown();
  *slot = nullptr;
}

// Runs one negotiation phase at `size` ranks and returns the coordinator's
// inbound control messages per cycle (measured between two full-quiescence
// barriers, so rendezvous and farewell traffic never pollute the number).
// `fleet_sources` / `fleet_sum_count`, when non-null, receive the
// coordinator's stored fleet-sketch source count and live fleet-sum
// negotiation count at the same quiescent point.
int64_t RunPhase(const char* name, const char* tree_mode, int size,
                 int cycles, int* fleet_sources = nullptr,
                 int64_t* fleet_sum_count = nullptr) {
  ::setenv("HOROVOD_CONTROL_TREE", tree_mode, 1);
  const int port = FreePort();
  if (port < 0) {
    Fail(name, -1, "no free port");
    return -1;
  }
  Phase ph(size + 1);
  std::vector<SocketController*> ctls(size, nullptr);
  std::vector<std::string> errs(size);
  std::vector<std::thread> threads;
  threads.reserve(size);
  for (int r = 0; r < size; ++r) {
    threads.emplace_back(SoakRank, name, r, size, port, cycles, &ph,
                         &ctls[r], &errs[r]);
  }
  ph.init.Wait();
  int64_t ms0 = 0, mr0 = 0, bs0 = 0, br0 = 0;
  if (ctls[0]) ctls[0]->CtrlPlaneStats(&ms0, &mr0, &bs0, &br0);
  ph.start.Wait();
  ph.done.Wait();
  int64_t ms1 = 0, mr1 = 0, bs1 = 0, br1 = 0;
  if (ctls[0]) ctls[0]->CtrlPlaneStats(&ms1, &mr1, &bs1, &br1);
  if (fleet_sources != nullptr && ctls[0]) {
    *fleet_sources = ctls[0]->FleetSourceCountForTest();
  }
  if (fleet_sum_count != nullptr && ctls[0]) {
    *fleet_sum_count = ctls[0]->FleetSumNegCountForTest();
  }
  ph.exit_.Wait();
  for (auto& t : threads) t.join();
  for (int r = 0; r < size; ++r) {
    if (!errs[r].empty()) Fail(name, r, errs[r]);
  }
  if (failures != 0) return -1;
  const int64_t recv_per_cycle = (mr1 - mr0) / cycles;
  std::printf(
      "[%s] np=%d cycles=%d coordinator: recv %lld msgs/cycle "
      "(%lld bytes/cycle), sent %lld msgs/cycle\n",
      name, size, cycles, static_cast<long long>(recv_per_cycle),
      static_cast<long long>((br1 - br0) / cycles),
      static_cast<long long>((ms1 - ms0) / cycles));
  return recv_per_cycle;
}

// ---------------------------------------------------------------------------
// Eviction arm: one whole fake host departs cleanly between cycles.
// ---------------------------------------------------------------------------

// Rank body for the eviction phase: everyone runs `pre` cycles on the
// global set; evicted ranks then Farewell (the autopilot's eviction is a
// clean departure) while survivors run `post` more cycles on a pre-agreed
// survivor process set.
void EvictRank(int rank, int size, int port, int pre, int post,
               int evict_host_lo, int evict_host_hi, Phase* ph,
               std::string* err) {
  CoreConfig cfg;
  cfg.rank = rank;
  cfg.size = size;
  cfg.rendezvous_addr = "127.0.0.1";
  cfg.rendezvous_port = port;
  cfg.ctrl_only = true;
  SocketController ctl(cfg);
  Status s = ctl.Initialize();
  if (!s.ok()) *err = "init: " + s.reason;
  const bool evicted = rank >= evict_host_lo && rank < evict_host_hi;
  int surv_psid = -1;
  if (err->empty()) {
    // Survivor set registered up front on EVERY rank (symmetric
    // registration is the process-set contract), so post-eviction cycles
    // have a set whose readiness never waits on departed ranks.
    std::vector<int> survivors;
    for (int r = 0; r < size; ++r) {
      if (r < evict_host_lo || r >= evict_host_hi) survivors.push_back(r);
    }
    surv_psid = ctl.process_sets().Add(survivors);
  }
  ph->init.Wait();
  ph->start.Wait();
  if (err->empty()) {
    for (int c = 0; c < pre && err->empty(); ++c) {
      std::string e = OneCycle(&ctl, "soak" + std::to_string(c), 0);
      if (!e.empty()) *err = "pre cycle " + std::to_string(c) + ": " + e;
    }
    if (err->empty() && evicted) {
      // Clean mid-soak departure: BYE up the tree.  The leader's own BYE
      // releases the whole subtree at the coordinator, so workers' BYEs
      // left unread by their departing leader cannot wedge survivors.
      ctl.Farewell();
    }
    if (!evicted) {
      for (int c = 0; c < post && err->empty(); ++c) {
        std::string e =
            OneCycle(&ctl, "surv" + std::to_string(c), surv_psid);
        if (!e.empty()) *err = "post cycle " + std::to_string(c) + ": " + e;
      }
    }
  }
  ph->done.Wait();
  ph->exit_.Wait();
  if (err->empty() && !evicted) ctl.Farewell();
  ctl.Shutdown();
}

void RunEvictPhase(const char* name, int size, int hosts, int evict_host) {
  ::setenv("HOROVOD_CONTROL_TREE", "on", 1);
  const int port = FreePort();
  if (port < 0) {
    Fail(name, -1, "no free port");
    return;
  }
  const int per = size / hosts;
  const int lo = evict_host * per, hi = lo + per;
  Phase ph(size + 1);
  std::vector<std::string> errs(size);
  std::vector<std::thread> threads;
  threads.reserve(size);
  for (int r = 0; r < size; ++r) {
    threads.emplace_back(EvictRank, r, size, port, /*pre=*/2, /*post=*/2,
                         lo, hi, &ph, &errs[r]);
  }
  ph.init.Wait();
  ph.start.Wait();
  ph.done.Wait();
  ph.exit_.Wait();
  for (auto& t : threads) t.join();
  for (int r = 0; r < size; ++r) {
    if (!errs[r].empty()) Fail(name, r, errs[r]);
  }
  if (failures == 0) {
    std::printf("[%s] np=%d evicted host %d (ranks %d..%d), survivors "
                "finished\n",
                name, size, evict_host, lo, hi - 1);
  }
}

// ---------------------------------------------------------------------------
// Chaos arms: fault-injected death at a chosen tree level.
// ---------------------------------------------------------------------------

struct ChaosOutcome {
  bool init_ok = false;
  bool completed = false;
  std::string reason;
  double handshake_s = 0;
};

void ChaosSoakRank(int rank, int size, int port, int cycles,
                   ChaosOutcome* out) {
  CoreConfig cfg;
  cfg.rank = rank;
  cfg.size = size;
  cfg.rendezvous_addr = "127.0.0.1";
  cfg.rendezvous_port = port;
  cfg.ctrl_only = true;
  SocketController ctl(cfg);
  Status s = ctl.Initialize();
  if (!s.ok()) {
    out->reason = "init: " + s.reason;
    return;
  }
  out->init_ok = true;
  for (int c = 0; s.ok() && c < cycles; ++c) {
    std::string e = OneCycle(&ctl, "soak" + std::to_string(c), 0);
    if (!e.empty()) s = Status::Error(StatusCode::ABORTED, e);
  }
  if (s.ok()) {
    ctl.Farewell();
    ctl.Shutdown();
    out->completed = true;
    return;
  }
  // Mirror core_api's failure path: one more ComputeResponses runs the
  // abort handshake, and the reason the Python layer would surface comes
  // from WaitAbortReason — both bounded by the abort-propagation budget.
  const double t0 = MonotonicSeconds();
  std::vector<TensorRequest> none;
  std::vector<Response> ignored;
  ctl.ComputeResponses(none, &ignored);
  out->reason = ctl.WaitAbortReason();
  if (out->reason.empty()) out->reason = s.reason;
  out->handshake_s = MonotonicSeconds() - t0;
  ctl.Shutdown();
}

// Arms `spec`, runs `size` ranks for `cycles`, and asserts: nobody
// completes, nobody hangs (abort handshake bounded), and `witness` — a
// rank outside the dead branch — names the exact culprit rank + host.
void RunChaosPhase(const char* name, int depth, const std::string& spec,
                   int size, int hosts, int cycles, int witness,
                   int culprit) {
  ::setenv("HOROVOD_CONTROL_TREE", "on", 1);
  SetDepthEnv(depth);
  ::setenv("HOROVOD_FAULT_INJECT", spec.c_str(), 1);
  std::string perr = InitFaultInjection();
  if (!perr.empty()) {
    Fail(name, -1, "spec error: " + perr);
    return;
  }
  const int port = FreePort();
  if (port < 0) {
    Fail(name, -1, "no free port");
    return;
  }
  std::vector<ChaosOutcome> out(size);
  std::vector<std::thread> threads;
  threads.reserve(size);
  for (int r = 0; r < size; ++r) {
    threads.emplace_back(ChaosSoakRank, r, size, port, cycles, &out[r]);
  }
  for (auto& t : threads) t.join();
  ::unsetenv("HOROVOD_FAULT_INJECT");
  InitFaultInjection();
  SetDepthEnv(0);
  // The configured propagation bound is 2 s (set in main); the slack on
  // top covers sanitizer + thousand-thread scheduler noise, same policy
  // as tests/parallel/test_ctrl_tree_np8.py.
  const double bound_s = 2.0 + 13.0;
  int aborted = 0;
  for (int r = 0; r < size; ++r) {
    if (out[r].completed) {
      Fail(name, r, "completed cleanly despite the injected fault");
    } else if (out[r].reason.empty()) {
      Fail(name, r, "aborted without a reason");
    } else if (out[r].init_ok && out[r].handshake_s > bound_s) {
      Fail(name, r,
           "abort handshake took " + std::to_string(out[r].handshake_s) +
               "s (bound " + std::to_string(bound_s) + "s)");
    } else {
      ++aborted;
    }
  }
  // Exact culprit attribution, checked on a rank whose only signal is the
  // coordinator's direct ABORT broadcast (the dead branch may latch its
  // leader's synthesized reason first, which is also correct but vaguer).
  const std::string want =
      "culprit rank " + std::to_string(culprit) + ", host fakehost-" +
      std::to_string(static_cast<int64_t>(culprit) * hosts / size);
  if (witness >= 0 && witness < size && out[witness].init_ok &&
      out[witness].reason.find(want) == std::string::npos) {
    Fail(name, witness,
         "witness reason does not name '" + want + "': " +
             out[witness].reason);
  }
  if (failures == 0) {
    std::printf("[%s] np=%d depth=%d: %d ranks aborted bounded, witness "
                "%d named culprit %d\n",
                name, size, depth, aborted, witness, culprit);
  }
}

}  // namespace

int main() {
  // np in-process ranks keep both ends of every control socket in one
  // process; don't depend on the caller's `ulimit -n`.
  struct rlimit rl;
  if (::getrlimit(RLIMIT_NOFILE, &rl) == 0 && rl.rlim_cur < rl.rlim_max) {
    rl.rlim_cur = rl.rlim_max;
    ::setrlimit(RLIMIT_NOFILE, &rl);
  }
  // A wedged abort path would otherwise hang `make selftest` forever; the
  // chaos arms' whole claim is that nothing ever blocks unbounded.
  ::alarm(1500);
  // CTRL_SOAK_NP / CTRL_SOAK_HOSTS select the geometry; CI runs both the
  // 256/16 default and the np=1024/64 pod-scale acceptance row.
  // CTRL_SOAK_ARMS=pod trims to the acceptance-critical arms for the
  // sanitizer pod rows.
  int np = 256;
  int hosts = 16;
  if (const char* env = ::getenv("CTRL_SOAK_NP")) np = std::atoi(env);
  if (const char* env = ::getenv("CTRL_SOAK_HOSTS")) hosts = std::atoi(env);
  const char* arms_env = ::getenv("CTRL_SOAK_ARMS");
  const bool pod_only = arms_env != nullptr && std::string(arms_env) == "pod";
  if (np < 16 || hosts < 2 || np % hosts != 0) {
    std::fprintf(stderr, "bad soak geometry np=%d hosts=%d\n", np, hosts);
    return 1;
  }
  ::setenv("HOROVOD_HIER_FAKE_HOSTS", std::to_string(hosts).c_str(), 1);
  ::setenv("HOROVOD_RENDEZVOUS_ACCEPTORS", "8", 1);
  ::setenv("HOROVOD_RENDEZVOUS_BACKOFF_BASE_MS", "10", 1);
  ::setenv("HOROVOD_ABORT_PROPAGATION_TIMEOUT", "2", 1);
  SetDepthEnv(0);

  const int cycles = 3;
  const int per = np / hosts;
  const int fanout = 32;  // mirror of the HOROVOD_CTRL_TREE_FANOUT default
  const TreeModel auto_model = ModelTree(np, hosts, fanout, 0);
  const TreeModel d2_model = ModelTree(np, hosts, fanout, 2);
  const TreeModel d3_model = ModelTree(np, hosts, fanout, 3);

  // --- flat vs adaptive tree: the O(n) -> O(fanout) acceptance bar -------
  if (!pod_only) {
    const int64_t flat = RunPhase("flat", "off", np, cycles);
    const int64_t tree = RunPhase("tree", "on", np, cycles);
    if (failures == 0 && (flat < 0 || tree <= 0)) {
      Fail("soak", -1, "phase produced no measurement");
    }
    if (failures == 0) {
      // Flat: one frame from each of the other np-1 ranks per cycle.
      if (flat < np - 1) {
        Fail("flat", 0,
             "coordinator saw " + std::to_string(flat) +
                 " msgs/cycle, expected >= " + std::to_string(np - 1));
      }
      if (tree != auto_model.coord_children) {
        Fail("tree", 0,
             "coordinator saw " + std::to_string(tree) +
                 " msgs/cycle, expected " +
                 std::to_string(auto_model.coord_children));
      }
      // The acceptance bar: O(n) -> O(fanout) is at least an 8x cut here.
      if (tree > 0 && flat < 8 * tree) {
        Fail("soak", -1,
             "flat/tree ratio " + std::to_string(flat) + "/" +
                 std::to_string(tree) + " is below the required 8x");
      }
    }
  } else {
    // Pod row: the adaptive shape assert without the flat baseline burn.
    const int64_t tree = RunPhase("tree", "on", np, cycles);
    if (failures == 0 && tree != auto_model.coord_children) {
      Fail("tree", 0,
           "coordinator saw " + std::to_string(tree) +
               " msgs/cycle, expected " +
               std::to_string(auto_model.coord_children));
    }
  }
  // At any geometry the adaptive tree must hold the tentpole fan-in bound:
  // coordinator inbound <= fanout clusters + its own host's workers.
  if (failures == 0 && auto_model.coord_children > fanout + (per - 1)) {
    Fail("tree", 0,
         "adaptive depth left coordinator fan-in " +
             std::to_string(auto_model.coord_children) + " above fanout " +
             std::to_string(fanout) + " + local " + std::to_string(per - 1));
  }

  // --- forced-depth shapes: d2 == the v9 tree, d3 inserts super-leaders --
  if (failures == 0 && !pod_only) {
    SetDepthEnv(2);
    const int64_t d2 = RunPhase("tree+d2", "on", np, cycles);
    if (d2 != d2_model.coord_children ||
        d2 != (per - 1) + (hosts - 1)) {
      Fail("tree+d2", 0,
           "depth-2 coordinator saw " + std::to_string(d2) +
               " msgs/cycle, expected the v9 shape " +
               std::to_string((per - 1) + (hosts - 1)));
    }
    SetDepthEnv(3);
    const int64_t d3 = RunPhase("tree+d3", "on", np, cycles);
    if (d3 != d3_model.coord_children) {
      Fail("tree+d3", 0,
           "depth-3 coordinator saw " + std::to_string(d3) +
               " msgs/cycle, expected " +
               std::to_string(d3_model.coord_children));
    }
    if (d3_model.depth >= 3 && d3 >= (per - 1) + (hosts - 1)) {
      Fail("tree+d3", 0,
           "super-leader layer did not reduce coordinator fan-in: " +
               std::to_string(d3) + " vs v9 " +
               std::to_string((per - 1) + (hosts - 1)));
    }
    SetDepthEnv(0);
  }

  // --- migration-aware row: forensic noting under the adaptive tree ------
  if (failures == 0 && !pod_only) {
    GlobalMetrics().enabled.store(true, std::memory_order_relaxed);
    const int64_t mig0 =
        GlobalMetrics().migrate_events_total.load(std::memory_order_relaxed);
    g_migrate.store(true, std::memory_order_relaxed);
    const int64_t tree_mig = RunPhase("tree+migrate", "on", np, cycles);
    g_migrate.store(false, std::memory_order_relaxed);
    const int64_t mig_delta =
        GlobalMetrics().migrate_events_total.load(std::memory_order_relaxed) -
        mig0;
    if (mig_delta < static_cast<int64_t>(np) * cycles) {
      Fail("tree+migrate", -1,
           "migrate_events_total advanced " + std::to_string(mig_delta) +
               ", expected >= " + std::to_string(np * cycles));
    }
    if (tree_mig != auto_model.coord_children) {
      Fail("tree+migrate", 0,
           "replication noting perturbed the control plane: " +
               std::to_string(tree_mig) + " msgs/cycle, expected " +
               std::to_string(auto_model.coord_children));
    }
  }

  // --- fleet-telemetry row (protocol v11 sketches at v12 depth) ----------
  // Asserts the sketch sections do not perturb the per-cycle shape, the
  // coordinator stored exactly one cumulative sketch per direct source
  // (subtree sums arrive pre-merged, so sources stay O(fanout) at any
  // depth), and the fleet sum respects the replace-not-add bound: all np
  // threads snapshot the SAME global registry, so the sum can only exceed
  // np x the registry's own count if some subtree was double-merged.
  // (Per-rank bucket exactness is covered by the multi-process parallel
  // tests, where every rank has its own registry.)
  if (failures == 0) {
    GlobalMetrics().enabled.store(true, std::memory_order_relaxed);
    GlobalFleetTelemetry().enabled.store(true, std::memory_order_relaxed);
    const int64_t merged0 = GlobalMetrics().fleet_sketches_merged_total.load(
        std::memory_order_relaxed);
    int fleet_sources = -1;
    int64_t fleet_sum = -1;
    g_observe.store(true, std::memory_order_relaxed);
    const int64_t tree_sk =
        RunPhase("tree+sketch", "on", np, cycles, &fleet_sources, &fleet_sum);
    g_observe.store(false, std::memory_order_relaxed);
    if (tree_sk != auto_model.coord_children) {
      Fail("tree+sketch", 0,
           "sketch sections perturbed the control plane: " +
               std::to_string(tree_sk) + " msgs/cycle, expected " +
               std::to_string(auto_model.coord_children));
    }
    if (fleet_sources != auto_model.coord_children) {
      Fail("tree+sketch", 0,
           "coordinator stored " + std::to_string(fleet_sources) +
               " fleet sources, expected " +
               std::to_string(auto_model.coord_children));
    }
    const int64_t merged =
        GlobalMetrics().fleet_sketches_merged_total.load(
            std::memory_order_relaxed) -
        merged0;
    if (merged < auto_model.coord_children) {
      Fail("tree+sketch", 0,
           "fleet_sketches_merged_total advanced " + std::to_string(merged) +
               ", expected >= " +
               std::to_string(auto_model.coord_children));
    }
    const int64_t reg_count =
        GlobalMetrics().negotiation_wait_us.count.load(
            std::memory_order_relaxed);
    if (fleet_sum <= 0 || fleet_sum > static_cast<int64_t>(np) * reg_count) {
      Fail("tree+sketch", 0,
           "fleet sum count " + std::to_string(fleet_sum) +
               " outside the replace-not-add bound (0, " +
               std::to_string(static_cast<int64_t>(np) * reg_count) + "]");
    }
  }

  // --- tenant churn: per-cycle process-set re-registration ---------------
  if (failures == 0 && !pod_only) {
    SetDepthEnv(3);
    g_churn.store(true, std::memory_order_relaxed);
    const int64_t churn = RunPhase("tree+churn", "on", np, cycles);
    g_churn.store(false, std::memory_order_relaxed);
    if (churn != d3_model.coord_children) {
      Fail("tree+churn", 0,
           "set churn perturbed the control plane: " + std::to_string(churn) +
               " msgs/cycle, expected " +
               std::to_string(d3_model.coord_children));
    }
    SetDepthEnv(0);
  }

  // --- chaos + eviction grid: deaths and departures at every level -------
  // Targets mirror ComputeCtrlTree: S = the first super-leader at forced
  // depth 3, L = the first host leader clustered under S, W = a worker on
  // L's host.  All must sit below the fault injector's 64 tracked-rank
  // slots so per-(site, rank) hit indices stay exact.
  int S = -1, L = -1, W = -1;
  for (const auto& kv : d3_model.parent_of) {
    if (kv.second > 0) {
      S = kv.second;
      L = kv.first;
      break;
    }
  }
  if (L >= 0 && per > 1) W = L + 1;
  const bool chaos_ok =
      d3_model.depth >= 3 && S > 0 && L > S && W > L && W < 63 && per > 1;
  if (failures == 0 && !chaos_ok) {
    Fail("chaos", -1,
         "geometry np=" + std::to_string(np) + " hosts=" +
             std::to_string(hosts) +
             " cannot place depth-3 chaos targets under the 64-slot limit");
  }
  if (failures == 0 && chaos_ok) {
    if (!pod_only) {
      // Depth 2: a host leader dies — detected by the coordinator's own
      // gather, culprit named directly (the v9 contract, re-proven at the
      // soak geometry after the v12 refactor).
      RunChaosPhase("chaos+d2+leader", 2,
                    "coordinator-recv:1:" + std::to_string(per) + ":drop",
                    np, hosts, cycles, /*witness=*/1, /*culprit=*/per);
      // Depth 3, leaf level: a worker dies; its host leader FINs up
      // through the super-leader chain.
      RunChaosPhase("chaos+d3+worker", 3,
                    "leader-recv:1:" + std::to_string(W) + ":drop", np,
                    hosts, cycles, /*witness=*/1, /*culprit=*/W);
      // Depth 3, top level: a super-leader dies; the coordinator's gather
      // detects it and the direct broadcast releases the orphan subtree.
      RunChaosPhase("chaos+d3+super", 3,
                    "coordinator-recv:1:" + std::to_string(S) + ":drop", np,
                    hosts, cycles, /*witness=*/1, /*culprit=*/S);
    }
    // Depth 3, mid level (the acceptance row): a clustered host leader
    // dies; its super-leader's gather trips the v12 super-recv site and
    // the FIN relays up with the culprit intact.
    RunChaosPhase("chaos+d3+leader", 3,
                  "super-recv:1:" + std::to_string(L) + ":drop", np, hosts,
                  cycles, /*witness=*/1, /*culprit=*/L);
    // Adaptive depth: the same mid-level death wherever auto placed the
    // super layer; at small host counts auto stays depth 2 and the death
    // degrades to the coordinator-detected leader case.
    if (auto_model.depth >= 3) {
      RunChaosPhase("chaos+adapt", 0,
                    "super-recv:1:" + std::to_string(L) + ":drop", np, hosts,
                    cycles, /*witness=*/1, /*culprit=*/L);
    } else if (!pod_only) {
      RunChaosPhase("chaos+adapt", 0,
                    "coordinator-recv:1:" + std::to_string(per) + ":drop",
                    np, hosts, cycles, /*witness=*/1, /*culprit=*/per);
    }
  }
  // Autopilot-style eviction at depth 3: the host of the first clustered
  // leader under S departs cleanly mid-soak; survivors finish on the
  // survivor set (the BYE-releases-the-subtree contract, clean twin of
  // the chaos+d3+leader death).
  if (failures == 0 && chaos_ok && !pod_only) {
    SetDepthEnv(3);
    RunEvictPhase("tree+evict", np, hosts, /*evict_host=*/L / per);
    SetDepthEnv(0);
  }

  if (failures != 0) {
    std::printf("FAIL (%d)\n", failures);
    return 1;
  }
  std::printf("PASS\n");
  return 0;
}
