"""Live cluster cockpit: a loopback HTTP endpoint streaming the coordinator's
causal step attribution (docs/observability.md, fifth pillar).

Rank 0 only, 127.0.0.1 only, off by default (HOROVOD_COCKPIT=1 enables) —
the same trust boundary as the autopilot policy channel: anything that can
reach the loopback interface of the coordinator host is already inside the
job's security perimeter.  Four routes:

  /metrics   Prometheus text exposition (the ``hvd_*`` families
             ``hvd.metrics_prometheus()`` renders), scrape-ready.
  /state     One JSON snapshot: elastic generation, per-tenant QoS
             accounting, straggler windows, migration counters, and the
             last-N per-step phase breakdowns with dominant-phase /
             dominant-rank attribution.
  /history   The fleet-telemetry plane's long-horizon view
             (fleethistory-v1): 1 s / 10 s / 60 s downsampled sample
             rings plus the anomaly sentinel's log — what
             ``hvd_top.py`` renders as sparklines.
  /events    Server-sent events: one ``data:`` line per completed step
             (summaries diffed from the fleet view) plus any instants
             published by the runtime (autopilot decisions, migrations,
             aborts).  Clients that lag are dropped, never blocked on.

The server takes plain callables (``metrics_fn``/``state_fn``) instead of a
HorovodContext so tests can drive it with a stub coordinator, and the
elastic driver can keep one port across re-formations: ``hvd_top.py``'s SSE
client simply reconnects to the same address when a generation replaces
rank 0's process.
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from .utils.logging import get_logger

log = get_logger()

# A lagging SSE client buffers this many events before being dropped: the
# cockpit must never apply backpressure to the training job.
_CLIENT_QUEUE_MAX = 256


class CockpitServer:
    """Loopback HTTP server for the live cockpit.

    ``metrics_fn() -> str`` renders the Prometheus exposition;
    ``state_fn() -> dict`` builds the /state snapshot (must contain a
    ``"steps"`` list of per-step dicts with a ``"step"`` key for the SSE
    differ to work).  ``port=0`` binds an ephemeral loopback port; pass the
    driver-assigned HOROVOD_COCKPIT_PORT to keep the address stable across
    elastic re-formations.
    """

    def __init__(self, metrics_fn: Callable[[], str],
                 state_fn: Callable[[], dict],
                 port: int = 0, host: str = "127.0.0.1",
                 poll_interval_s: float = 0.25,
                 history_fn: Optional[Callable[[], dict]] = None):
        self._metrics_fn = metrics_fn
        self._state_fn = state_fn
        self._history_fn = history_fn
        self._host = host
        self._port = port
        self._poll_interval_s = poll_interval_s
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._poll_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._clients_mu = threading.Lock()
        self._clients: List["queue.Queue[str]"] = []
        self._last_step_seen = -1

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        """Bind and serve; returns the bound port."""
        if self._httpd is not None:
            return self._port
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Route table lives in the closure so the handler stays stateless.
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    server._respond_text(self, server._safe_metrics(),
                                         "text/plain; version=0.0.4")
                elif path == "/state":
                    server._respond_text(
                        self, json.dumps(server._safe_state()),
                        "application/json")
                elif path == "/history":
                    server._respond_text(
                        self, json.dumps(server._safe_history()),
                        "application/json")
                elif path == "/events":
                    server._serve_sse(self)
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):  # noqa: D102
                pass  # stay out of the training job's stderr

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-cockpit",
            daemon=True)
        self._serve_thread.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="hvd-cockpit-poll", daemon=True)
        self._poll_thread.start()
        log.info("cockpit serving on http://%s:%d (/metrics /state /events)",
                 self._host, self._port)
        return self._port

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=2.0)
            self._serve_thread = None
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=2.0)
            self._poll_thread = None
        with self._clients_mu:
            clients, self._clients = self._clients, []
        for q in clients:
            try:
                q.put_nowait("")  # sentinel: wake the writer so it exits
            except queue.Full:
                pass

    @property
    def port(self) -> int:
        return self._port

    # -- event publication --------------------------------------------------
    def publish(self, event: Dict) -> None:
        """Push one instant (autopilot / migrate / abort / ...) to every
        connected SSE client.  Never blocks: a full client queue drops the
        event for that client only."""
        line = json.dumps(event)
        with self._clients_mu:
            clients = list(self._clients)
        for q in clients:
            try:
                q.put_nowait(line)
            except queue.Full:
                pass

    # -- internals ----------------------------------------------------------
    def _safe_metrics(self) -> str:
        try:
            return self._metrics_fn()
        except Exception as exc:  # noqa: BLE001 - surface, don't crash
            return f"# cockpit metrics error: {exc}\n"

    def _safe_state(self) -> dict:
        try:
            return self._state_fn()
        except Exception as exc:  # noqa: BLE001
            return {"error": str(exc)}

    def _safe_history(self) -> dict:
        # No history_fn (stub coordinators, plane disabled) serves {} —
        # hvd_top.py renders the dimmed panel, never an error page.
        if self._history_fn is None:
            return {}
        try:
            return self._history_fn() or {}
        except Exception as exc:  # noqa: BLE001
            return {"error": str(exc)}

    def _poll_loop(self) -> None:
        """Diff the fleet step list and publish a summary per new step."""
        while not self._stop.wait(self._poll_interval_s):
            with self._clients_mu:
                has_clients = bool(self._clients)
            if not has_clients:
                continue
            state = self._safe_state()
            for step in state.get("steps") or []:
                sid = step.get("step", -1)
                if sid > self._last_step_seen:
                    self._last_step_seen = sid
                    self.publish(dict(step, type="step"))

    def _respond_text(self, handler: BaseHTTPRequestHandler, body: str,
                      content_type: str) -> None:
        data = body.encode()
        handler.send_response(200)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    def _serve_sse(self, handler: BaseHTTPRequestHandler) -> None:
        q: "queue.Queue[str]" = queue.Queue(maxsize=_CLIENT_QUEUE_MAX)
        with self._clients_mu:
            self._clients.append(q)
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.send_header("Cache-Control", "no-cache")
            handler.end_headers()
            # An immediate hello so clients can tell "connected" from
            # "waiting for the first step".
            handler.wfile.write(b": cockpit stream open\n\n")
            handler.wfile.flush()
            while not self._stop.is_set():
                try:
                    line = q.get(timeout=1.0)
                except queue.Empty:
                    # Keep-alive comment: lets dead connections surface as
                    # write errors instead of lingering forever.
                    handler.wfile.write(b": keep-alive\n\n")
                    handler.wfile.flush()
                    continue
                if not line:  # stop() sentinel
                    break
                handler.wfile.write(b"data: " + line.encode() + b"\n\n")
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away; normal
        finally:
            with self._clients_mu:
                if q in self._clients:
                    self._clients.remove(q)


# Step-trace plane tag -> /state name (step_trace.h: -1 unknown, 0
# eager, 1 gspmd).  Old native payloads predate the tag entirely; their
# fleet records carry no "plane" key and degrade to "?" like -1 does.
_PLANE_NAMES = {0: "eager", 1: "gspmd"}


def _tag_steps_with_plane(fleet: List[dict]) -> List[dict]:
    """Normalize each fleet record's numeric plane tag to its name,
    tolerating records (old .so, old coordinator) without one."""
    out = []
    for f in fleet:
        f = dict(f or {})
        f["plane"] = _PLANE_NAMES.get(f.get("plane"), "?")
        out.append(f)
    return out


def build_state_fn(ctx) -> Callable[[], dict]:
    """The production /state builder over a HorovodContext: elastic
    generation, tenants, straggler windows, migration counters, and the
    fleet's last-N step breakdowns (rank 0's step-trace ring), each
    tagged with the data plane that ran it."""
    import os

    def state() -> dict:
        metrics = {}
        trace = {}
        try:
            metrics = ctx.core.metrics() or {}
        except Exception:  # noqa: BLE001 - snapshot must not crash
            pass
        try:
            trace = ctx.core.step_trace() or {}
        except Exception:  # noqa: BLE001
            pass
        try:
            gen = int(os.environ.get("HOROVOD_ELASTIC_GENERATION", "0"))
        except ValueError:
            gen = 0
        return {
            "schema": "cockpit-state-v1",
            "rank": ctx.cfg.rank,
            "world": ctx.cfg.size,
            "elastic_generation": gen,
            "tenants": metrics.get("tenants") or {},
            "straggler_report": metrics.get("straggler_report") or {},
            "cluster": metrics.get("cluster") or [],
            "migration": {
                k: metrics.get(k, 0)
                for k in ("migrate_events_total", "migrate_bytes_total",
                          "migrate_fallbacks_total")
            },
            "steps": _tag_steps_with_plane(trace.get("fleet") or []),
            "phases": trace.get("phases") or [],
        }

    return state


def maybe_start_cockpit(ctx) -> Optional[CockpitServer]:
    """Start the cockpit when configured (rank 0 + HOROVOD_COCKPIT on);
    returns None otherwise.  Failure to bind is a warning, never fatal —
    observability must not take down the job."""
    cfg = ctx.cfg
    if not getattr(cfg, "cockpit_enabled", False) or cfg.rank != 0:
        return None

    def metrics_text() -> str:
        from .utils.metrics import render_prometheus
        return render_prometheus(ctx.core.metrics() or {})

    def history() -> dict:
        return ctx.core.fleet_history() or {}

    server = CockpitServer(metrics_text, build_state_fn(ctx),
                           port=getattr(cfg, "cockpit_port", 0) or 0,
                           history_fn=history)
    try:
        server.start()
    except OSError as exc:
        log.warning("cockpit failed to bind 127.0.0.1:%s (%s); disabled",
                    cfg.cockpit_port, exc)
        return None
    return server
