"""Pipeline parallelism (GPipe-style) over a mesh axis.

Absent in the reference (SURVEY.md §2.7).  TPU-native design: every pp rank
holds one stage's weights; activations flow stage-to-stage with
``lax.ppermute`` hops on ICI while microbatches stream through, all inside
one compiled program (``lax.fori_loop`` over ticks — no host round trips).

The stage function must be shape-preserving ([mb, ...] -> [mb, ...]), the
standard shape for stacked transformer blocks.  Differentiable: ppermute
has a transpose, so ``jax.grad`` through ``gpipe`` yields pipelined
backward automatically.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.collectives import axis_size, ensure_varying


def gpipe(stage_fn: Callable, stage_params, x_microbatches,
          axis_name: str = "pp"):
    """Run ``stage_fn(stage_params, act)`` as a pipeline over the pp axis.

    Args:
      stage_fn: one pipeline stage, [mb, ...] -> [mb, ...].
      stage_params: THIS shard's stage weights (different per pp rank).
      x_microbatches: [n_micro, mb, ...] — the full input, meaningful on
        stage 0 (other ranks may pass the same array; it is ignored).
      axis_name: the pipeline mesh axis.

    Returns [n_micro, mb, ...]: the last stage's outputs, valid on the last
    pp rank (zeros elsewhere) — combine with a psum/ppermute or compute the
    loss on the last rank.
    """
    n_stages = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    total_ticks = n_micro + n_stages - 1
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    x_microbatches = ensure_varying(x_microbatches, axis_name)
    buf0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    out0 = jnp.zeros((n_micro,) + mb_shape, x_microbatches.dtype)
    buf0 = ensure_varying(buf0, axis_name)
    out0 = ensure_varying(out0, axis_name)

    def tick(t, carry):
        outputs, buf = carry
        # Stage 0 injects microbatch t (clamped; extra ticks recompute the
        # last microbatch and are discarded), later stages use the buffer
        # received from upstream.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inp = jnp.where(idx == 0,
                        lax.dynamic_index_in_dim(x_microbatches, mb_idx,
                                                 keepdims=False),
                        buf)
        out = stage_fn(stage_params, inp)
        # The last stage emits microbatch t-(n_stages-1) at tick t.
        emit_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        emit = (idx == n_stages - 1) & (t >= n_stages - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(emit, out,
                               lax.dynamic_index_in_dim(outputs, emit_idx,
                                                        keepdims=False)),
            emit_idx, axis=0)
        buf_next = lax.ppermute(out, axis_name, fwd_perm)
        return updated, buf_next

    outputs, _ = lax.fori_loop(0, total_ticks, tick, (out0, buf0))
    return outputs


def pipeline_stage_params(params_by_stage, axis_name: str = "pp"):
    """Select this rank's stage weights from a stacked pytree whose leaves
    have a leading n_stages dim (convenience for tests/checkpoints)."""
    idx = lax.axis_index(axis_name)
    return jax.tree_util.tree_map(
        lambda leaf: lax.dynamic_index_in_dim(leaf, idx, keepdims=False),
        params_by_stage)


def last_stage_value(x, axis_name: str = "pp"):
    """Broadcast the last pp rank's value to all ranks (one psum)."""
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    contribution = jnp.where(idx == n - 1, x, jnp.zeros_like(x))
    return lax.psum(contribution, axis_name)
