"""Ring attention: sequence-parallel exact attention over a mesh axis.

The reference has no sequence/context parallelism (SURVEY.md §2.7/§5 — its
only primitives are allreduce-family collectives), but long-context is a
first-class requirement of this framework.  This is the TPU-native design:
shard the sequence across a mesh axis, keep Q resident, and rotate K/V
shards around the ICI ring with ``lax.ppermute`` while accumulating the
softmax online (flash-attention style running max/sum), so the full
[S, S] score matrix never materialises and each hop's compute overlaps the
next hop's transfer.  Communication volume per device is O(S/n * H * D * n)
= one pass of K and V around the ring — exactly what ICI's torus is for.

All accumulation is fp32 regardless of activation dtype.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.collectives import axis_size


def _chunk_attention(q, k, v, scale, mask):
    """Attention stats for one (q-chunk, kv-chunk) pair.

    Returns (unnormalised context [B,Sq,H,D] fp32, running max m [B,H,Sq],
    sum l [B,H,Sq]) for online-softmax combination.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    m = jnp.max(logits, axis=-1)                      # [B,H,Sq]
    p = jnp.exp(logits - m[..., None])                # [B,H,Sq,Sk]
    l = jnp.sum(p, axis=-1)                           # [B,H,Sq]
    ctx = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return ctx, m, l


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None,
                   use_flash: bool = False,
                   block_size: int = 128,
                   interpret: bool = False):
    """Exact attention with the sequence dimension sharded over ``axis_name``.

    Args:
      q, k, v: [batch, seq_local, heads, head_dim] — this rank's sequence
        chunk (global sequence = axis_size * seq_local, chunk i holds
        positions [i*seq_local, (i+1)*seq_local)).
      axis_name: mesh axis carrying the sequence shards (the SP axis).
      causal: apply a causal mask over *global* positions.
      scale: logit scale; defaults to head_dim ** -0.5.
      use_flash: compute each hop's local chunk with the Pallas flash
        kernel (linear memory in seq_local) instead of the dense
        [Sq, Sk] einsum.  Opt-in for now (defaults off): semantics are
        fully covered by interpret-mode tests, but the compiled
        pallas-inside-switch-inside-scan composition has not yet been
        validated on hardware, and flipping every sp-model silently onto
        it would be reckless.  Flip the default after a hardware run.
      block_size: flash kernel block size (use_flash only).
      interpret: run the flash kernel in the Pallas interpreter (tests).

    Returns [batch, seq_local, heads, head_dim] in q.dtype.
    """
    n = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    seq_local = q.shape[1]
    head_dim = q.shape[-1]
    scale = head_dim ** -0.5 if scale is None else scale
    # Rotate K/V "upstream" so that at step i we hold chunk (my_idx - i) % n.
    perm = [(j, (j + 1) % n) for j in range(n)]

    b, _, h, d = q.shape
    acc0 = jnp.zeros((b, seq_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, seq_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, seq_local), jnp.float32)

    q_pos = my_idx * seq_local + jnp.arange(seq_local)  # global q positions

    def _flash_chunk(q, kc, vc, chunk_causal: bool):
        """Chunk stats via the Pallas kernel: (out, lse) is equivalent to
        the (ctx, m, l) triple with m := lse, l := 1 (sum of
        exp(logits - lse) is 1 by construction)."""
        from ..ops.flash_attention import flash_attention_with_lse

        out, lse = flash_attention_with_lse(
            q, kc, vc, causal=chunk_causal, scale=scale,
            block_q=block_size, block_k=block_size,
            interpret=interpret or None)
        return (out.astype(jnp.float32), lse, lse * 0 + 1.0)

    def _flash_cases(q, kc, vc):
        """Relative to this rank's chunk, a hop's K/V chunk is entirely in
        the past (full attention), the diagonal (causal within chunk), or
        entirely in the future (no contribution).  The branch index is
        data-dependent (src is traced), so lax.switch over three
        statically-compiled kernels.  The zero branch derives from q so
        all branches carry the same varying-manual-axes type."""
        zrow = jnp.sum(q.astype(jnp.float32), axis=-1) * 0   # [B, S, H]
        zrow = jnp.transpose(zrow, (0, 2, 1))                # [B, H, S]
        zero = (q.astype(jnp.float32) * 0, zrow - jnp.inf, zrow)
        return [
            lambda _: _flash_chunk(q, kc, vc, False),   # src < my_idx
            lambda _: _flash_chunk(q, kc, vc, True),    # src == my_idx
            lambda _: zero,                             # src > my_idx
        ]

    def body(i, carry):
        acc, m, l, kc, vc = carry
        src = (my_idx - i) % n  # whose chunk we currently hold
        if use_flash:
            if causal:
                branch = jnp.where(
                    src == my_idx, 1, jnp.where(src < my_idx, 0, 2))
                ctx, m_c, l_c = lax.switch(branch, _flash_cases(q, kc, vc),
                                           None)
            else:
                ctx, m_c, l_c = _flash_chunk(q, kc, vc, False)
        else:
            if causal:
                k_pos = src * seq_local + jnp.arange(seq_local)
                mask = q_pos[:, None] >= k_pos[None, :]        # [Sq, Sk]
                mask = mask[None, None, :, :]
            else:
                mask = None
            ctx, m_c, l_c = _chunk_attention(q, kc, vc, scale, mask)
        # Online-softmax merge of (acc, m, l) with the new chunk's stats.
        m_new = jnp.maximum(m, m_c)
        # With a fully-masked chunk m_c = -inf; guard exp(-inf - -inf).
        alpha = jnp.exp(jnp.where(m == -jnp.inf, -jnp.inf, m - m_new))
        beta = jnp.exp(jnp.where(m_c == -jnp.inf, -jnp.inf, m_c - m_new))
        alpha = jnp.nan_to_num(alpha)
        beta = jnp.nan_to_num(beta)
        l_new = l * alpha + l_c * beta
        # [B,H,S] -> [B,S,H,1] to scale the [B,S,H,D] accumulators.
        def bh(x):
            return jnp.transpose(x, (0, 2, 1))[..., None]
        acc_new = acc * bh(alpha) + ctx * bh(beta)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return acc_new, m_new, l_new, kc, vc

    # The zero-init accumulators are axis-invariant while the loop body
    # produces values varying over every mesh axis the inputs vary over;
    # align the carry's varying-manual-axes type up front (shard_map vma
    # rules for scan/fori carries).
    try:
        target_vma = tuple(jax.typeof(q).vma)
    except Exception:
        target_vma = (axis_name,)

    def _vary(x):
        try:
            vma = jax.typeof(x).vma
        except Exception:
            return x
        missing = tuple(a for a in target_vma if a not in vma)
        return lax.pcast(x, missing, to="varying") if missing else x

    carry0 = tuple(_vary(c) for c in (acc0, m0, l0, k, v))
    acc, m, l, _, _ = lax.fori_loop(0, n, body, carry0)
    denom = jnp.transpose(jnp.maximum(l, 1e-30), (0, 2, 1))[..., None]
    return (acc / denom).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None):
    """DeepSpeed-Ulysses-style sequence parallelism: all_to_all swaps the
    sharded dimension from sequence to heads, attention runs with the full
    sequence on heads/n heads, and a second all_to_all swaps back.

    Requires heads % axis_size == 0.  Two all_to_alls instead of a ring —
    cheaper when heads are plentiful and the axis is small.
    """
    n = axis_size(axis_name)
    if q.shape[2] % n != 0:
        raise ValueError(f"ulysses needs heads ({q.shape[2]}) divisible by "
                         f"axis size ({n})")
    head_dim = q.shape[-1]
    scale = head_dim ** -0.5 if scale is None else scale

    def to_full_seq(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_sharded_seq(x):  # [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qf, kf, vf = to_full_seq(q), to_full_seq(k), to_full_seq(v)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = qf.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(vf.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, vf)
    return to_sharded_seq(ctx)
