"""Expert parallelism: switch-routed mixture-of-experts over a mesh axis.

Absent in the reference (SURVEY.md §2.7 — its alltoall is the primitive EP
would need).  TPU-native design: one expert (or expert group) per ep rank;
top-1 (switch) routing with a fixed capacity per expert so every shape is
static; the token dispatch and return are each ONE ``lax.all_to_all`` on
ICI — the canonical MoE communication pattern.

Dropped tokens (over capacity) pass through with a zero expert output,
scaled by their gate as usual — the standard switch-transformer behavior.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.collectives import axis_size, ensure_varying


def switch_moe(x, router_kernel, expert_fn: Callable, axis_name: str = "ep",
               capacity_factor: float = 1.25):
    """Top-1 MoE layer with one expert per ep rank.

    Args:
      x: [tokens_local, d] — this shard's tokens.
      router_kernel: [d, n_experts] router weights (replicated).
      expert_fn: this rank's expert, [cap_total, d] -> [cap_total, d]
        (applied to the tokens routed to THIS rank's expert).
      axis_name: expert-parallel mesh axis; n_experts == axis size.
      capacity_factor: per-expert capacity = ceil(T/E * factor).

    Returns [tokens_local, d].
    """
    x = ensure_varying(x, axis_name)
    tokens, d = x.shape
    n_expert = axis_size(axis_name)
    capacity = int(-(-tokens * capacity_factor // n_expert))  # ceil

    logits = jnp.einsum("td,de->te", x.astype(jnp.float32), router_kernel)
    gates = jax.nn.softmax(logits, axis=-1)                 # [T, E]
    expert_idx = jnp.argmax(gates, axis=-1)                 # [T]
    gate = jnp.max(gates, axis=-1)                          # [T]

    # Position of each token within its expert's capacity bucket.
    onehot = jax.nn.one_hot(expert_idx, n_expert, dtype=jnp.int32)  # [T, E]
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1)        # [T, E]
    pos = jnp.take_along_axis(pos_in_expert, expert_idx[:, None],
                              axis=1)[:, 0]                 # [T]
    keep = pos < capacity

    # Scatter tokens into the dispatch buffer [E, C, d].
    dispatch = jnp.zeros((n_expert, capacity, d), x.dtype)
    safe_pos = jnp.where(keep, pos, 0)
    dispatch = dispatch.at[expert_idx, safe_pos].add(
        jnp.where(keep[:, None], x, 0))

    # One all_to_all: shard e of every rank -> rank e. Received layout:
    # [E_src, C, d] = each peer's tokens for THIS rank's expert.
    received = lax.all_to_all(dispatch, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)

    expert_out = expert_fn(received.reshape(n_expert * capacity, d))
    expert_out = expert_out.reshape(n_expert, capacity, d).astype(x.dtype)

    # Return trip: chunk s goes back to source rank s.
    returned = lax.all_to_all(expert_out, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)   # [E, C, d]

    # Gather each kept token's expert output back to token order.
    out = returned[expert_idx, safe_pos]                    # [T, d]
    out = jnp.where(keep[:, None], out, 0)
    return (out * gate[:, None].astype(x.dtype))


def moe_ffn(w_in_local, w_out_local, activation=jax.nn.gelu):
    """Build an expert_fn for :func:`switch_moe` from this rank's FFN
    weights ([d, hidden], [hidden, d])."""

    def fn(tokens):
        h = activation(jnp.einsum("td,dh->th", tokens, w_in_local))
        return jnp.einsum("th,hd->td", h, w_out_local)

    return fn


def load_balancing_loss(x, router_kernel, axis_name: str = "ep"):
    """Switch-transformer auxiliary load-balance loss: E * sum_e f_e * P_e
    (fraction of tokens routed to e times mean router prob of e)."""
    n_expert = axis_size(axis_name)
    logits = jnp.einsum("td,de->te", x.astype(jnp.float32), router_kernel)
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)
    frac = jnp.mean(jax.nn.one_hot(expert_idx, n_expert, dtype=jnp.float32),
                    axis=0)
    prob = jnp.mean(gates, axis=0)
    return n_expert * jnp.sum(frac * prob)
