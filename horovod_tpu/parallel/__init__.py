from .mesh import (  # noqa: F401
    build_global_mesh,
    global_mesh,
    set_global_mesh,
    mesh_axis_name,
    sub_mesh,
)
