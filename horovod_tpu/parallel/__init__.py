from .mesh import (  # noqa: F401
    build_global_mesh,
    build_mesh,
    global_mesh,
    set_global_mesh,
    mesh_axis_name,
    sub_mesh,
)
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
from .tensor_parallel import (  # noqa: F401
    column_parallel_dense, row_parallel_dense, tp_mlp,
    vocab_parallel_embedding, shard_kernel,
)
from .pipeline import gpipe, pipeline_stage_params, last_stage_value  # noqa: F401
from .moe import switch_moe, moe_ffn, load_balancing_loss  # noqa: F401
