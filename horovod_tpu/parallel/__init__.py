from .mesh import (  # noqa: F401
    build_global_mesh,
    build_mesh,
    global_mesh,
    set_global_mesh,
    mesh_axis_name,
    sub_mesh,
)
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
