"""Device-mesh management: the TPU-native substrate for all collectives.

Where the reference builds NCCL communicators per process set
(horovod/common/mpi/mpi_context.cc, ops/nccl_operations.cc; SURVEY.md §2.8),
the TPU build names an axis of a ``jax.sharding.Mesh`` and lets XLA lower
``psum``/``all_gather``/... onto ICI rings.  The global mesh has a single
data-parallel axis ``"hvd"`` by default; richer layouts (dp × tp × sp × ep)
are built with :func:`build_mesh` and consumed by ``horovod_tpu.parallel``'s
sharded-training helpers — which is how TP/SP/EP become cheap extensions of
the same substrate (SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

HVD_AXIS = "hvd"

_global_mesh = None


def build_global_mesh(axis_name: str = HVD_AXIS, devices=None):
    """Build (and remember) the 1-D global mesh over all visible devices."""
    import jax
    from jax.sharding import Mesh

    global _global_mesh
    if devices is None:
        devices = jax.devices()
    _global_mesh = Mesh(np.asarray(devices), (axis_name,))
    return _global_mesh


def build_mesh(axis_sizes: dict, devices=None):
    """Build an N-D mesh from ``{"dp": 2, "tp": 2, "sp": 2}``-style specs.

    Axis order follows insertion order; place the fastest-communicating axis
    last so it maps to the innermost ICI ring.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes)
    sizes = tuple(int(axis_sizes[n]) for n in names)
    n_needed = int(np.prod(sizes))
    if n_needed > len(devices):
        raise ValueError(f"mesh needs {n_needed} devices, have {len(devices)}")
    arr = np.asarray(devices[:n_needed]).reshape(sizes)
    return Mesh(arr, names)


def global_mesh():
    """The mesh built at hvd.init() (or None before init)."""
    return _global_mesh


def set_global_mesh(mesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def reset() -> None:
    global _global_mesh
    _global_mesh = None


def mesh_axis_name() -> str:
    if _global_mesh is not None:
        return _global_mesh.axis_names[0]
    return HVD_AXIS


def sub_mesh(ranks: Sequence[int], axis_name: Optional[str] = None):
    """Mesh over the devices owned by the given process ranks.

    TPU analog of a process-set communicator: collectives over this mesh
    stay within the subset's ICI domain.
    """
    import jax
    from jax.sharding import Mesh

    axis_name = axis_name or mesh_axis_name()
    devices = [d for d in jax.devices() if getattr(d, "process_index", 0) in ranks]
    if not devices:
        # Single-process simulation: treat local device i as "rank i"'s device.
        all_devices = jax.devices()
        devices = [all_devices[r] for r in ranks if r < len(all_devices)]
    if not devices:
        raise ValueError(f"no devices for ranks {ranks}")
    return Mesh(np.asarray(devices), (axis_name,))
