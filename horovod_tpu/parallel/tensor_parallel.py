"""Tensor (model) parallelism primitives over a mesh axis.

The reference has NO tensor parallelism (SURVEY.md §2.7) — its process sets
are the substrate users would hand-roll TP on.  On TPU the substrate is a
mesh axis, and these are the Megatron-style building blocks, written for
``shard_map``: each shard holds a slice of the weight, and the pair
column→row costs exactly one psum on ICI per MLP block.

Layout convention (scaling-book recipe):
- **column parallel**: kernel sharded on the OUTPUT dim; input replicated
  (or varying over data axes only); output varies over the tp axis.
- **row parallel**: kernel sharded on the INPUT dim; input is the
  column-parallel output (tp-sharded features); the matmul's partial sums
  are combined with one ``psum``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.collectives import axis_size, ensure_varying


def column_parallel_dense(x, kernel_local, bias_local=None,
                          axis_name: str = "tp",
                          gather_output: bool = False):
    """y_local = x @ W[:, shard] (+ b[shard]).

    Args:
      x: [..., d_in], replicated across the tp axis (invariant or varying —
        both accepted).
      kernel_local: [d_in, d_out / tp] — this shard's column slice.
      bias_local: [d_out / tp] or None.
      gather_output: all_gather the feature dim back to [..., d_out]
        (costs bandwidth; usually keep sharded and feed a row-parallel op).
    """
    x = ensure_varying(x, axis_name)
    y = jnp.einsum("...i,ij->...j", x, kernel_local,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    if bias_local is not None:
        y = y + bias_local
    if gather_output:
        y = lax.all_gather(y, axis_name, axis=-1, tiled=True)
    return y


def row_parallel_dense(x_local, kernel_local, bias=None,
                       axis_name: str = "tp"):
    """y = psum_tp(x_local @ W[shard, :]) (+ b).

    Args:
      x_local: [..., d_in / tp] — tp-sharded features (e.g. a column-parallel
        output).
      kernel_local: [d_in / tp, d_out] — this shard's row slice.
      bias: [d_out], logically replicated; added once AFTER the psum.
    """
    partial = jnp.einsum("...i,ij->...j", x_local, kernel_local,
                         preferred_element_type=jnp.float32)
    y = lax.psum(partial, axis_name).astype(x_local.dtype)
    if bias is not None:
        y = y + bias
    return y


def tp_mlp(x, w_in_local, w_out_local, b_in_local=None, b_out=None,
           axis_name: str = "tp", activation=jax.nn.gelu):
    """The canonical TP transformer MLP: column → act → row, one psum total."""
    h = column_parallel_dense(x, w_in_local, b_in_local, axis_name)
    h = activation(h)
    return row_parallel_dense(h, w_out_local, b_out, axis_name)


def vocab_parallel_embedding(ids, table_local, axis_name: str = "tp"):
    """Embedding with the vocab dim sharded: each shard looks up its own
    vocab range and the results are psum-combined (out-of-range rows
    contribute zeros)."""
    vocab_local = table_local.shape[0]
    start = lax.axis_index(axis_name) * vocab_local
    local_ids = ids - start
    in_range = (local_ids >= 0) & (local_ids < vocab_local)
    safe_ids = jnp.clip(local_ids, 0, vocab_local - 1)
    emb = jnp.take(table_local, safe_ids, axis=0)
    emb = jnp.where(in_range[..., None], emb, 0)
    return lax.psum(emb, axis_name)


def shard_kernel(kernel, axis_name: str, dim: int):
    """Slice a replicated kernel to this shard's piece along ``dim`` —
    convenience for loading non-TP checkpoints into TP layers."""
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if kernel.shape[dim] % n != 0:
        raise ValueError(
            f"shard_kernel: dim {dim} of shape {kernel.shape} is not "
            f"divisible by axis {axis_name!r} size {n}")
    size = kernel.shape[dim] // n
    return lax.dynamic_slice_in_dim(kernel, idx * size, size, axis=dim)
