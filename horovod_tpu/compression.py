"""Gradient compression (reference: horovod/torch/compression.py,
horovod/tensorflow/compression.py — Compression.none / Compression.fp16).

On TPU the natural compressed wire type is bfloat16 (same 8-bit exponent as
float32, so no loss-scaling is needed); ``Compression.fp16`` keeps the
reference's name/semantics and ``Compression.bf16`` is the TPU-preferred
variant.
"""

from __future__ import annotations

import numpy as np


def _astype(tensor, dtype):
    if isinstance(tensor, np.ndarray):
        return tensor.astype(dtype)
    import jax.numpy as jnp

    return tensor.astype(dtype) if hasattr(tensor, "astype") else jnp.asarray(
        tensor, dtype=dtype)


class Compressor:
    """Interface: compress before the wire, decompress after."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: str = "float16"

    @classmethod
    def compress(cls, tensor):
        dtype = getattr(tensor, "dtype", None)
        if dtype is not None and str(dtype) in ("float32", "float64"):
            return _astype(tensor, cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is None:
            return tensor
        return _astype(tensor, ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = "float16"


class BF16Compressor(_CastCompressor):
    wire_dtype = "bfloat16"


class Compression:
    """Namespace matching the reference's ``hvd.Compression``."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
