"""Gradient compression (reference: horovod/torch/compression.py,
horovod/tensorflow/compression.py — Compression.none / Compression.fp16).

On TPU the natural compressed wire type is bfloat16 (same 8-bit exponent as
float32, so no loss-scaling is needed); ``Compression.fp16`` keeps the
reference's name/semantics and ``Compression.bf16`` is the TPU-preferred
variant.
"""

from __future__ import annotations

import numpy as np


def _astype(tensor, dtype):
    if isinstance(tensor, np.ndarray):
        if dtype == "bfloat16":
            # numpy has no native bfloat16; ml_dtypes (a jax dependency)
            # registers one.
            import ml_dtypes

            return tensor.astype(ml_dtypes.bfloat16)
        return tensor.astype(dtype)
    import jax.numpy as jnp

    return tensor.astype(dtype) if hasattr(tensor, "astype") else jnp.asarray(
        tensor, dtype=dtype)


class Compressor:
    """Interface: compress before the wire, decompress after."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: str = "float16"

    @classmethod
    def compress(cls, tensor):
        dtype = getattr(tensor, "dtype", None)
        if dtype is None or str(dtype) not in ("float32", "float64"):
            return tensor, None
        wire = cls.wire_dtype
        if str(dtype) == "float64" and wire == "float16":
            # float16's 5-bit exponent silently overflows float64's range
            # (anything past 65504 becomes inf); bfloat16 keeps the fp32
            # exponent so only precision, not magnitude, is traded.
            wire = "bfloat16"
        return _astype(tensor, wire), dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is None:
            return tensor
        return _astype(tensor, ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = "float16"


class BF16Compressor(_CastCompressor):
    wire_dtype = "bfloat16"


class Compression:
    """Namespace matching the reference's ``hvd.Compression``."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
