"""BERT encoder, TPU-first flax implementation.

The reference's headline large-model benchmark is BERT-Large pretraining
with fp16 fused allreduce (BASELINE.json config 3; Horovod `examples/` has
the TF/torch BERT scripts).  This is the equivalent model for this
framework, shaped for the MXU:

- all projections are single fused matmuls over [hidden, 3*hidden]-style
  shapes (multiples of 128);
- bfloat16 activations, fp32 params, fp32 softmax accumulation;
- attention can run sequence-parallel over a mesh axis via
  ``horovod_tpu.parallel.ring_attention`` (pass ``sp_axis_name``) — the
  long-context path the reference lacks (SURVEY.md §5 "long-context").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024          # BERT-Large
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    sp_axis_name: Optional[str] = None  # sequence-parallel mesh axis
    sp_use_flash: bool = False          # flash kernel per ring hop


BERT_BASE = BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                       intermediate_size=3072)
BERT_LARGE = BertConfig()
BERT_TINY = BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                       num_heads=2, intermediate_size=512,
                       max_position_embeddings=128)


class SelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        # One fused QKV projection: [B, S, H] @ [H, 3H] keeps the MXU at a
        # single large matmul instead of three small ones.
        qkv = nn.DenseGeneral((3, cfg.num_heads, head_dim), dtype=cfg.dtype,
                              name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if cfg.sp_axis_name is not None:
            from ..parallel.ring_attention import ring_attention

            ctx = ring_attention(q, k, v, axis_name=cfg.sp_axis_name,
                                 causal=False,
                                 use_flash=cfg.sp_use_flash)
        else:
            scale = head_dim ** -0.5
            # fp32 logits/softmax regardless of activation dtype.
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                                preferred_element_type=jnp.float32) * scale
            if mask is not None:
                big_neg = jnp.finfo(jnp.float32).min
                logits = jnp.where(mask[:, None, None, :], logits, big_neg)
            probs = jax.nn.softmax(logits, axis=-1).astype(cfg.dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype,
                              name="out")(ctx)
        return out


class TransformerLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        cfg = self.config
        attn = SelfAttention(cfg, name="attention")(x, mask, deterministic)
        attn = nn.Dropout(cfg.dropout_rate)(attn, deterministic=deterministic)
        # Post-LN like original BERT; LN in fp32 for stability.
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(
            (x + attn).astype(jnp.float32)).astype(cfg.dtype)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="mlp_in")(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(h)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(
            (x + h).astype(jnp.float32)).astype(cfg.dtype)
        return x


class BertEncoder(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.config
        seq_len = input_ids.shape[-1]
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                     dtype=cfg.dtype, name="word_embeddings")(input_ids)
        if cfg.sp_axis_name is not None:
            # Sequence-parallel: this shard holds a contiguous chunk of the
            # global sequence; position ids are global.
            offset = jax.lax.axis_index(cfg.sp_axis_name) * seq_len
        else:
            offset = 0
        pos = (offset + jnp.arange(seq_len))[None, :]
        x = x + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                         dtype=cfg.dtype, name="position_embeddings")(pos)
        if token_type_ids is not None:
            x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                             dtype=cfg.dtype, name="token_type_embeddings")(
                token_type_ids)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_embed")(
            x.astype(jnp.float32)).astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = TransformerLayer(cfg, name=f"layer_{i}")(
                x, attention_mask, deterministic)
        return x


class BertForPreTraining(nn.Module):
    """Encoder + MLM head (the pretraining benchmark objective)."""

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.config
        hidden = BertEncoder(cfg, name="encoder")(
            input_ids, token_type_ids, attention_mask, deterministic)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_transform")(
            hidden)
        h = nn.gelu(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")(
            h.astype(jnp.float32))
        # Logits in fp32: [B, S, V] matmul feeds a stable softmax-xent.
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                          name="mlm_head")(h)
        return logits


def mlm_loss(logits, labels, label_weights):
    """Masked-LM cross-entropy: mean over positions where weight == 1."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    w = label_weights.astype(jnp.float32)
    return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)
