"""Inception V3, TPU-first flax implementation.

The reference's headline scaling number is Inception V3 at ≈90% efficiency
on 128 GPUs (BASELINE.md, Horovod paper arXiv:1802.05799); this reproduces
the model family so the same benchmark runs on TPU.  NHWC, bf16-capable,
BN with optional cross-replica stats (``bn_axis_name``).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         axis_name=self.bn_axis_name)(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    pool_features: int
    conv: Any

    @nn.compact
    def __call__(self, x, train=True):
        c = self.conv
        b1 = c(64, (1, 1))(x, train)
        b2 = c(48, (1, 1))(x, train)
        b2 = c(64, (5, 5))(b2, train)
        b3 = c(64, (1, 1))(x, train)
        b3 = c(96, (3, 3))(b3, train)
        b3 = c(96, (3, 3))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(self.pool_features, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    conv: Any

    @nn.compact
    def __call__(self, x, train=True):
        c = self.conv
        b1 = c(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = c(64, (1, 1))(x, train)
        b2 = c(96, (3, 3))(b2, train)
        b2 = c(96, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    conv: Any

    @nn.compact
    def __call__(self, x, train=True):
        c, c7 = self.conv, self.channels_7x7
        b1 = c(192, (1, 1))(x, train)
        b2 = c(c7, (1, 1))(x, train)
        b2 = c(c7, (1, 7))(b2, train)
        b2 = c(192, (7, 1))(b2, train)
        b3 = c(c7, (1, 1))(x, train)
        b3 = c(c7, (7, 1))(b3, train)
        b3 = c(c7, (1, 7))(b3, train)
        b3 = c(c7, (7, 1))(b3, train)
        b3 = c(192, (1, 7))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(192, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    conv: Any

    @nn.compact
    def __call__(self, x, train=True):
        c = self.conv
        b1 = c(192, (1, 1))(x, train)
        b1 = c(320, (3, 3), strides=(2, 2), padding="VALID")(b1, train)
        b2 = c(192, (1, 1))(x, train)
        b2 = c(192, (1, 7))(b2, train)
        b2 = c(192, (7, 1))(b2, train)
        b2 = c(192, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    conv: Any

    @nn.compact
    def __call__(self, x, train=True):
        c = self.conv
        b1 = c(320, (1, 1))(x, train)
        b2 = c(384, (1, 1))(x, train)
        b2 = jnp.concatenate([c(384, (1, 3))(b2, train),
                              c(384, (3, 1))(b2, train)], axis=-1)
        b3 = c(448, (1, 1))(x, train)
        b3 = c(384, (3, 3))(b3, train)
        b3 = jnp.concatenate([c(384, (1, 3))(b3, train),
                              c(384, (3, 1))(b3, train)], axis=-1)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = c(192, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    """Input [B, 299, 299, 3] (any H/W >= 75 works); logits fp32."""

    num_classes: int = 1000
    dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        x = jnp.asarray(x, self.dtype)
        x = conv(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = conv(32, (3, 3), padding="VALID")(x, train)
        x = conv(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(80, (1, 1), padding="VALID")(x, train)
        x = conv(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = InceptionA(32, conv)(x, train)
        x = InceptionA(64, conv)(x, train)
        x = InceptionA(64, conv)(x, train)
        x = InceptionB(conv)(x, train)
        x = InceptionC(128, conv)(x, train)
        x = InceptionC(160, conv)(x, train)
        x = InceptionC(160, conv)(x, train)
        x = InceptionC(192, conv)(x, train)
        x = InceptionD(conv)(x, train)
        x = InceptionE(conv)(x, train)
        x = InceptionE(conv)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))
