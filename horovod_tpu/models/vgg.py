"""VGG-16/19, TPU-first flax implementation.

The reference's benchmark suite measures VGG-16 alongside ResNet/Inception
(BASELINE.md: ~68% scaling efficiency — communication-bound because of the
~138M-parameter classifier) — reproducing the model family lets the same
comm-bound regime be measured on ICI.  bf16 activations, fp32 params,
NHWC convs on the MXU.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

_VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M")
_VGG19_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M")


class VGG(nn.Module):
    cfg: Sequence = _VGG16_CFG
    num_classes: int = 1000
    dtype: Any = jnp.float32
    classifier_width: int = 4096

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x, self.dtype)
        conv = functools.partial(nn.Conv, kernel_size=(3, 3),
                                 dtype=self.dtype, padding="SAME")
        i = 0
        for c in self.cfg:
            if c == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.relu(conv(int(c), name=f"conv_{i}")(x))
                i += 1
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.classifier_width, dtype=self.dtype,
                             name="fc1")(x))
        x = nn.Dropout(0.5)(x, deterministic=not train)
        x = nn.relu(nn.Dense(self.classifier_width, dtype=self.dtype,
                             name="fc2")(x))
        x = nn.Dropout(0.5)(x, deterministic=not train)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))


VGG16 = functools.partial(VGG, cfg=_VGG16_CFG)
VGG19 = functools.partial(VGG, cfg=_VGG19_CFG)
VGGTiny = functools.partial(
    VGG, cfg=(8, "M", 16, "M", 32, "M"), classifier_width=64)
