"""MNIST-scale MLP — the minimum end-to-end slice (BASELINE.json config 1;
reference analog: horovod `examples/*mnist*` scripts)."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (512, 256, 10)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features[:-1]):
            x = nn.relu(nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x))
        return nn.Dense(self.features[-1], dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))


def xent_loss(logits, labels):
    logp = jnp.take_along_axis(
        nn.log_softmax(logits, axis=-1), labels[:, None], axis=-1)
    return -logp.mean()
