"""Decoder-only (GPT-style) causal language model, TPU-first flax.

Extends the model-family coverage beyond the reference's benchmark pair
(ResNet/BERT — SURVEY.md §6) with the decoder architecture the long-context
requirement targets: causal attention runs through the Pallas flash kernel
on-chip, or ring attention over a sequence-parallel mesh axis
(``sp_axis_name``) for sequences longer than one chip's memory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # GPT-2 vocab padded to a multiple of 128
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    sp_axis_name: Optional[str] = None   # sequence-parallel mesh axis
    sp_use_flash: bool = False           # flash kernel per ring hop
    use_flash: bool = True               # Pallas kernel on TPU
    remat: bool = False                  # jax.checkpoint each block


GPT_SMALL = GPTConfig()
GPT_TINY = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                     num_heads=4, max_seq_len=256, use_flash=False,
                     dtype=jnp.float32)


class CausalSelfAttention(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        qkv = nn.DenseGeneral((3, cfg.num_heads, head_dim), dtype=cfg.dtype,
                              name="qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if cfg.sp_axis_name is not None:
            from ..parallel.ring_attention import ring_attention

            ctx = ring_attention(q, k, v, axis_name=cfg.sp_axis_name,
                                 causal=True,
                                 use_flash=cfg.sp_use_flash)
        elif cfg.use_flash:
            from ..ops.flash_attention import flash_attention

            ctx = flash_attention(q, k, v, causal=True)
        else:
            from ..ops.flash_attention import dense_attention

            ctx = dense_attention(q, k, v, causal=True)
        return nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, name="out")(ctx)


class GPTBlock(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        # Pre-LN (GPT-2 style); LN in fp32.
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_1")(
            x.astype(jnp.float32)).astype(cfg.dtype)
        x = x + nn.Dropout(cfg.dropout_rate)(
            CausalSelfAttention(cfg, name="attn")(h, deterministic),
            deterministic=deterministic)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_2")(
            x.astype(jnp.float32)).astype(cfg.dtype)
        m = nn.Dense(4 * cfg.hidden_size, dtype=cfg.dtype, name="mlp_in")(h)
        m = nn.gelu(m)
        m = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(m)
        return x + nn.Dropout(cfg.dropout_rate)(m,
                                                deterministic=deterministic)


class GPT(nn.Module):
    """Causal LM: returns next-token logits [B, S, V] (fp32)."""

    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True):
        cfg = self.config
        seq_len = input_ids.shape[-1]
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="wte")(input_ids)
        if cfg.sp_axis_name is not None:
            offset = jax.lax.axis_index(cfg.sp_axis_name) * seq_len
        else:
            offset = 0
        pos = (offset + jnp.arange(seq_len))[None, :]
        x = x + nn.Embed(cfg.max_seq_len, cfg.hidden_size, dtype=cfg.dtype,
                         name="wpe")(pos)
        block = GPTBlock
        if cfg.remat:
            block = nn.remat(GPTBlock, static_argnums=(2,))
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(
            x.astype(jnp.float32))
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          name="lm_head")(x)
        return logits


def lm_loss(logits, input_ids):
    """Next-token cross entropy (shifted), mean over positions."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    tgt = input_ids[:, 1:]
    ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return -ll.mean()
