"""ResNet family (v1.5), TPU-first flax implementation.

The reference benchmarks Horovod with ResNet-50/101 training scripts
(horovod `examples/` + `docs/benchmarks.rst`; SURVEY.md §6) — those scripts
are torch/TF models fed through ``hvd.DistributedOptimizer``.  This module is
the equivalent flagship model for this framework, written for the MXU:

- NHWC layout (XLA:TPU's native conv layout) with channel counts that are
  multiples of 128 in the deep stages, so convs tile cleanly onto the
  128x128 systolic array;
- bfloat16 activations / fp32 parameters (the standard TPU mixed-precision
  recipe) — pass ``dtype=jnp.bfloat16``;
- BatchNorm with optional cross-replica statistics: pass ``bn_axis_name`` to
  sync batch statistics over the data-parallel mesh axis via psum (the
  TPU-native equivalent of the reference's horovod/torch/sync_batch_norm.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    """Bottleneck residual block (ResNet-50/101/152), v1.5 variant:
    stride lives on the 3x3 conv, which is what the reference benchmark
    models use and what keeps the MXU busy."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale so blocks start as identity — the
        # standard large-batch trick (He et al.; also used by the Horovod
        # paper's training recipes).
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC images.

    Args:
      stage_sizes: blocks per stage, e.g. ``[3, 4, 6, 3]`` for ResNet-50.
      block_cls: :class:`ResNetBlock` or :class:`BottleneckResNetBlock`.
      num_classes: classifier width.
      dtype: activation dtype (``jnp.bfloat16`` on TPU).
      bn_axis_name: mesh axis for cross-replica (sync) BatchNorm, or None
        for per-replica statistics.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    act: Callable = nn.relu
    bn_axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            kernel_init=nn.initializers.variance_scaling(
                2.0, "fan_out", "normal"),
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train,
            momentum=self.bn_momentum, epsilon=self.bn_epsilon,
            dtype=self.dtype, axis_name=self.bn_axis_name,
        )
        x = jnp.asarray(x, self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** i, strides=strides,
                    conv=conv, norm=norm, act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Classifier head in fp32 for numerically stable softmax/loss.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckResNetBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckResNetBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckResNetBlock)

# Tiny variant for tests / CPU dry-runs: same topology, 1/4 width.
ResNetTiny = functools.partial(ResNet, stage_sizes=[1, 1, 1, 1],
                               block_cls=ResNetBlock, num_filters=16)
