"""Flagship models for the framework's benchmarks (SURVEY.md §6;
BASELINE.json configs 1-3): MNIST MLP, ResNet family, BERT family."""

from .mlp import MLP, xent_loss  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152, ResNetTiny,
)
from .bert import (  # noqa: F401
    BertConfig, BertEncoder, BertForPreTraining, mlm_loss,
    BERT_BASE, BERT_LARGE, BERT_TINY,
)
from .gpt import (  # noqa: F401
    GPT, GPTConfig, GPT_SMALL, GPT_TINY, lm_loss,
)
from .vgg import VGG, VGG16, VGG19, VGGTiny  # noqa: F401
from .inception import InceptionV3  # noqa: F401
