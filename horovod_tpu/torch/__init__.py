"""PyTorch binding: ``import horovod_tpu.torch as hvd``.

Reference: horovod/torch/__init__.py (SURVEY.md §2.3-2.4) — the full torch
public API (handle-based async collectives with true in-place semantics,
grad-hook DistributedOptimizer, broadcast_parameters/optimizer_state/
object, Compression, SyncBatchNorm, elastic TorchState/ElasticSampler)
over this framework's core runtime: the same C++ negotiation spine, fusion
buffer, response cache, and host TCP/shm data plane the JAX binding's eager
path uses.  Torch tensors in this build are CPU-resident, so the host data
plane is the natural (and reference-matching: CPU ops ran MPI/Gloo) home;
a torch program and a JAX program launched by the same ``horovodrun`` can
interoperate rank-for-rank.
"""

from __future__ import annotations

# Shared runtime surface (init/shutdown/rank/size/... are framework-neutral).
from .. import __version__  # noqa: F401
from ..basics import (cross_rank, cross_size, init, initialized,  # noqa: F401
                      is_homogeneous, is_initialized, local_rank, local_size,
                      mpi_built, mpi_enabled, mpi_threads_supported,
                      nccl_built, num_devices, rank, shutdown, size,
                      start_timeline, stop_timeline, tpu_built)
from ..process_sets import (ProcessSet, add_process_set,  # noqa: F401
                            global_process_set, remove_process_set)
from . import elastic  # noqa: F401
from .compression import Compression  # noqa: F401
from .functions import (allgather_object, broadcast_object,  # noqa: F401
                        broadcast_optimizer_state, broadcast_parameters)
from .mpi_ops import (Adasum, Average, Max, Min, Product, Sum,  # noqa: F401
                      allgather, allgather_async, allreduce, allreduce_,
                      allreduce_async, allreduce_async_, alltoall,
                      alltoall_async, barrier, broadcast, broadcast_,
                      broadcast_async, broadcast_async_, grouped_allgather,
                      grouped_allgather_async, grouped_allreduce,
                      grouped_allreduce_, grouped_allreduce_async,
                      grouped_allreduce_async_, grouped_reducescatter,
                      grouped_reducescatter_async, join, poll,
                      reducescatter, reducescatter_async, sparse_allreduce,
                      sparse_allreduce_async, sparse_synchronize,
                      synchronize)
from .optimizer import DistributedOptimizer  # noqa: F401
from .sync_batch_norm import SyncBatchNorm  # noqa: F401
