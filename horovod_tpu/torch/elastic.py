"""Elastic training state for torch models.

Reference: horovod/torch/elastic/state.py (TorchState with per-handler
model/optimizer sync) and horovod/torch/elastic/sampler.py; SURVEY.md §2.4,
§3.5.  The retry loop itself (``@hvd.elastic.run``) and the sampler are
shared with the JAX binding — elastic membership logic is framework-
agnostic; only the snapshot/broadcast of framework objects differs.
"""

from __future__ import annotations

import copy
from typing import Any, Dict

import torch

from ..elastic import run  # noqa: F401  (re-export: @hvd.elastic.run)
from ..elastic.state import ElasticSampler, ObjectState  # noqa: F401
from .functions import (broadcast_object, broadcast_optimizer_state,
                        broadcast_parameters)


def _sync_sampler(sampler: ElasticSampler, name: str) -> None:
    """Union the processed-index sets across ranks, then reshard the
    REMAINING samples over the (possibly new) world.

    This is the part a plain rank-0 broadcast gets wrong: every rank
    processed a DIFFERENT shard, so broadcasting one rank's set would put
    the others' already-trained samples back into the pool (reference:
    horovod/torch/elastic's sampler state handler performs the same
    union-allgather).

    Epoch authority is RANK 0, matching ObjectState.sync's broadcast of
    plain attrs (state.epoch) — two authorities would let the training
    loop run a mislabeled epoch.  Contributions from ranks at a DIFFERENT
    committed epoch are excluded from the union: their indices belong to
    another epoch's permutation, and unioning them would silently skip
    those samples for the whole epoch.  A rank ahead of rank 0 simply
    rolls back and repeats part of the epoch — elastic recovery repeats,
    never skips.
    """
    from .functions import allgather_object

    entries = allgather_object(
        (sampler.epoch, sorted(sampler.processed_indices)),
        name=f"elastic.{name}.state")
    epoch0 = entries[0][0]
    union: set = set()
    for ep, idxs in entries:
        if ep == epoch0:
            union.update(idxs)
    sampler.load_state_dict({"epoch": epoch0,
                             "processed_indices": sorted(union)})


class TorchState(ObjectState):
    """Elastic state over torch modules/optimizers plus scalar attributes.

    ``TorchState(model=model, optimizer=opt, epoch=0, batch=0)`` — module
    and optimizer snapshots are deep-copied state_dicts (host CPU memory,
    surviving any device teardown); ``sync()`` broadcasts rank 0's live
    state to all ranks after a rendezvous round.
    """

    def __init__(self, model: torch.nn.Module = None,
                 optimizer: torch.optim.Optimizer = None,
                 sampler: ElasticSampler = None, **kwargs):
        self._handled: Dict[str, Any] = {}
        if model is not None:
            self._handled["model"] = model
        if optimizer is not None:
            self._handled["optimizer"] = optimizer
        if sampler is not None:
            self._handled["sampler"] = sampler
        # Extra modules/optimizers/samplers may arrive as kwargs
        # (reference allows arbitrary names); route them by type — all
        # three expose the state_dict/load_state_dict snapshot interface.
        plain = {}
        for k, v in kwargs.items():
            if isinstance(v, (torch.nn.Module, torch.optim.Optimizer,
                              ElasticSampler)):
                self._handled[k] = v
            else:
                plain[k] = v
        self._handled_saved: Dict[str, Any] = {}
        super().__init__(**plain)

    def __getattr__(self, name: str):
        handled = self.__dict__.get("_handled", {})
        if name in handled:
            return handled[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value) -> None:
        # Reassigning a handled object (state.model = rebuilt_model in a
        # reset callback) must update the handler, not shadow it in the
        # instance dict — a shadowed module would train live while
        # save/restore/sync kept operating on the dead one.
        handled = self.__dict__.get("_handled")
        if handled is not None and name in handled:
            handled[name] = value
        else:
            super().__setattr__(name, value)

    # -- snapshots ----------------------------------------------------------
    def save(self) -> None:
        super().save()
        self._handled_saved = {
            k: copy.deepcopy(v.state_dict())
            for k, v in self._handled.items()}

    def restore(self) -> None:
        super().restore()
        for k, snap in self._handled_saved.items():
            self._handled[k].load_state_dict(copy.deepcopy(snap))

    # -- cross-rank sync ----------------------------------------------------
    def sync(self) -> None:
        for k, v in self._handled.items():
            if isinstance(v, torch.nn.Module):
                broadcast_parameters(v.state_dict(), root_rank=0)
            elif isinstance(v, ElasticSampler):
                _sync_sampler(v, k)
            else:
                broadcast_optimizer_state(v, root_rank=0)
        plain = self._public_attrs()
        if plain:
            synced = broadcast_object(plain, root_rank=0,
                                      name="elastic.torch_state")
            for k, v in synced.items():
                setattr(self, k, v)
        self.save()

    # -- migration payloads (horovod_tpu.elastic.migrate) -------------------
    # Handled objects live outside ObjectState._saved, so peer-shard
    # replication must carry their state_dicts explicitly — otherwise a
    # respawned rank adopting a replica would get the right epoch counter
    # but keep its fresh random-init model.
    def _migration_snapshot(self):
        payload = super()._migration_snapshot()
        payload["handled"] = self._handled_saved
        return payload

    def _migration_live(self):
        payload = super()._migration_live()
        payload["handled"] = {k: copy.deepcopy(v.state_dict())
                              for k, v in self._handled.items()}
        return payload

    def _migration_apply(self, payload) -> None:
        super()._migration_apply(payload)
        for k, snap in payload.get("handled", {}).items():
            if k in self._handled:
                self._handled[k].load_state_dict(copy.deepcopy(snap))
        self.save()
