"""Elastic training state for torch models.

Reference: horovod/torch/elastic/state.py (TorchState with per-handler
model/optimizer sync) and horovod/torch/elastic/sampler.py; SURVEY.md §2.4,
§3.5.  The retry loop itself (``@hvd.elastic.run``) and the sampler are
shared with the JAX binding — elastic membership logic is framework-
agnostic; only the snapshot/broadcast of framework objects differs.
"""

from __future__ import annotations

import copy
from typing import Any, Dict

import torch

from ..elastic import run  # noqa: F401  (re-export: @hvd.elastic.run)
from ..elastic.state import ElasticSampler, ObjectState  # noqa: F401
from .functions import (broadcast_object, broadcast_optimizer_state,
                        broadcast_parameters)


class TorchState(ObjectState):
    """Elastic state over torch modules/optimizers plus scalar attributes.

    ``TorchState(model=model, optimizer=opt, epoch=0, batch=0)`` — module
    and optimizer snapshots are deep-copied state_dicts (host CPU memory,
    surviving any device teardown); ``sync()`` broadcasts rank 0's live
    state to all ranks after a rendezvous round.
    """

    def __init__(self, model: torch.nn.Module = None,
                 optimizer: torch.optim.Optimizer = None, **kwargs):
        self._handled: Dict[str, Any] = {}
        if model is not None:
            self._handled["model"] = model
        if optimizer is not None:
            self._handled["optimizer"] = optimizer
        # Extra modules/optimizers may arrive as kwargs (reference allows
        # arbitrary names); route them by type.
        plain = {}
        for k, v in kwargs.items():
            if isinstance(v, (torch.nn.Module, torch.optim.Optimizer)):
                self._handled[k] = v
            else:
                plain[k] = v
        self._handled_saved: Dict[str, Any] = {}
        super().__init__(**plain)

    def __getattr__(self, name: str):
        handled = self.__dict__.get("_handled", {})
        if name in handled:
            return handled[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value) -> None:
        # Reassigning a handled object (state.model = rebuilt_model in a
        # reset callback) must update the handler, not shadow it in the
        # instance dict — a shadowed module would train live while
        # save/restore/sync kept operating on the dead one.
        handled = self.__dict__.get("_handled")
        if handled is not None and name in handled:
            handled[name] = value
        else:
            super().__setattr__(name, value)

    # -- snapshots ----------------------------------------------------------
    def save(self) -> None:
        super().save()
        self._handled_saved = {
            k: copy.deepcopy(v.state_dict())
            for k, v in self._handled.items()}

    def restore(self) -> None:
        super().restore()
        for k, snap in self._handled_saved.items():
            self._handled[k].load_state_dict(copy.deepcopy(snap))

    # -- cross-rank sync ----------------------------------------------------
    def sync(self) -> None:
        for k, v in self._handled.items():
            if isinstance(v, torch.nn.Module):
                broadcast_parameters(v.state_dict(), root_rank=0)
            else:
                broadcast_optimizer_state(v, root_rank=0)
        plain = self._public_attrs()
        if plain:
            synced = broadcast_object(plain, root_rank=0,
                                      name="elastic.torch_state")
            for k, v in synced.items():
                setattr(self, k, v)
        self.save()
