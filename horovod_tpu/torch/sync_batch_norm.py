"""Cross-rank synchronized BatchNorm for torch models.

Reference: horovod/torch/sync_batch_norm.py (SyncBatchNorm riding
hvd.allreduce for the stats); SURVEY.md §2.4.  Training-mode statistics are
the global batch's: each rank reduces [sum, sum-of-squares, count] with one
summed allreduce, normalizes with the global mean/var, and the backward
reduces the two per-channel gradient sums the chain rule needs.  Eval mode
uses running stats with no communication, and a world of one degrades to
ordinary BatchNorm exactly.
"""

from __future__ import annotations

from typing import Optional

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from ..process_sets import ProcessSet
from . import mpi_ops


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, x, weight, bias, eps, momentum, running_mean,
                running_var, process_set, name):
        # Channel-wise sums over every non-channel dim, globally reduced.
        # Stats accumulate in float32 regardless of input dtype: fp16 sums
        # and sum-of-squares overflow at ordinary batch sizes (count alone
        # exceeds fp16 range past 65504 elements/channel).
        dims = [0] + list(range(2, x.dim()))
        xf = x.float()
        local_count = x.numel() // x.size(1)
        stats = torch.cat([
            xf.sum(dims), (xf * xf).sum(dims),
            torch.tensor([float(local_count)], dtype=torch.float32)])
        stats = mpi_ops.allreduce(stats, op=mpi_ops.Sum,
                                  name=f"{name}.fwd",
                                  process_set=process_set)
        c = x.size(1)
        count = stats[-1].clamp_min(1.0)
        mean = stats[:c] / count
        var = stats[c:2 * c] / count - mean * mean
        var = var.clamp_min(0.0)

        if running_mean is not None:
            with torch.no_grad():
                # Unbiased var for running stats, biased for normalization
                # (torch BatchNorm semantics).
                n = float(count)
                unbiased = var * (n / max(n - 1.0, 1.0))
                running_mean.mul_(1 - momentum).add_(momentum * mean)
                running_var.mul_(1 - momentum).add_(momentum * unbiased)

        shape = [1, c] + [1] * (x.dim() - 2)
        inv_std = torch.rsqrt(var + eps)
        xhat = (xf - mean.view(shape)) * inv_std.view(shape)
        out = xhat
        if weight is not None:
            out = out * weight.view(shape).float()
        if bias is not None:
            out = out + bias.view(shape).float()
        ctx.save_for_backward(xhat, inv_std, weight, count)
        ctx.process_set = process_set
        ctx.name = name
        return out.to(x.dtype)

    @staticmethod
    def backward(ctx, grad_out):
        xhat, inv_std, weight, count = ctx.saved_tensors
        dims = [0] + list(range(2, grad_out.dim()))
        c = grad_out.size(1)
        shape = [1, c] + [1] * (grad_out.dim() - 2)

        go = grad_out.float()
        g = go if weight is None else go * weight.view(shape).float()
        # The two cross-rank sums the chain rule through global mean/var
        # needs; one fused allreduce.
        sums = torch.cat([g.sum(dims), (g * xhat).sum(dims)])
        sums = mpi_ops.allreduce(sums, op=mpi_ops.Sum,
                                 name=f"{ctx.name}.bwd",
                                 process_set=ctx.process_set)
        mean_g = (sums[:c] / count).view(shape)
        mean_gx = (sums[c:] / count).view(shape)
        grad_x = ((g - mean_g - xhat * mean_gx)
                  * inv_std.view(shape)).to(grad_out.dtype)

        grad_w = ((go * xhat).sum(dims).to(weight.dtype)
                  if weight is not None and ctx.needs_input_grad[1]
                  else None)
        grad_b = (go.sum(dims) if ctx.needs_input_grad[2] else None)
        return grad_x, grad_w, grad_b, None, None, None, None, None, None


class SyncBatchNorm(_BatchNorm):
    """Drop-in ``nn.BatchNorm*d`` replacement whose training statistics are
    computed over the global batch across all ranks of ``process_set``."""

    _instances = 0

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 track_running_stats: bool = True,
                 process_set: Optional[ProcessSet] = None,
                 name: Optional[str] = None):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)
        self._process_set = process_set
        # Collective names must match across ranks.  The default contract
        # is construction order (same model built the same way on every
        # rank — the assumption DistributedOptimizer's positional fallback
        # makes).  The counter is process-lifetime, so ranks with
        # ASYMMETRIC construction histories (one rank builds an extra
        # throwaway model, or an elastic rebuild on survivors vs a fresh
        # process on joiners) MUST pin ``name=`` explicitly — e.g. the
        # module's state-dict path — or the forward allreduce names
        # diverge and negotiation stalls.
        if name is not None:
            self._name = f"sync_bn.{name}"
        else:
            self._name = f"sync_bn.{SyncBatchNorm._instances}"
            SyncBatchNorm._instances += 1

    def _check_input_dim(self, x) -> None:
        if x.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {x.dim()}D)")

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        self._check_input_dim(x)
        from .. import basics

        world = (self._process_set.size() if self._process_set
                 else (basics.size() if basics.is_initialized() else 1))
        if not self.training or world == 1:
            return super().forward(x)
        if self.momentum is None:
            raise ValueError(
                "SyncBatchNorm requires a fixed momentum (cumulative "
                "moving average is not supported; reference restriction)")
        if self.track_running_stats and self.num_batches_tracked is not None:
            self.num_batches_tracked.add_(1)  # torch _BatchNorm parity
        return _SyncBatchNormFn.apply(
            x, self.weight, self.bias, self.eps, self.momentum,
            self.running_mean if self.track_running_stats else None,
            self.running_var if self.track_running_stats else None,
            self._process_set, self._name)
