"""Grad-hook DistributedOptimizer for torch models.

Reference: horovod/torch/optimizer.py — _DistributedOptimizer registers a
hook per parameter that fires when autograd finishes accumulating that
parameter's gradient and immediately enqueues an async in-place allreduce;
``step()`` synchronizes every outstanding handle and then runs the wrapped
optimizer.  That overlap of communication with the remainder of backward is
the Horovod paper's core trick, and it maps 1:1 onto this framework's eager
spine (negotiation + fusion happen in the background while backprop still
runs).  SURVEY.md §2.4, §3.3.

``backward_passes_per_step`` aggregates N backward passes locally before
reducing (reference: gradient accumulation for large effective batches);
the enqueued allreduce carries ``prescale_factor=1/N`` so the reduced
gradient is the average over passes as well as ranks.

Implementation note: like the reference, the factory builds a dynamic
subclass of the wrapped optimizer's own class, so the returned object
isinstance-checks as (e.g.) ``torch.optim.SGD`` and keeps working with LR
schedulers and other code that inspects the optimizer type.
"""

from __future__ import annotations

import contextlib
from typing import Iterable, Optional, Tuple

import torch

from ..process_sets import ProcessSet
from . import mpi_ops
from .compression import Compression


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 op=mpi_ops.Average,
                 gradient_predivide_factor: float = 1.0,
                 process_set: Optional[ProcessSet] = None,
                 sparse_as_dense: bool = False,
                 sparse_params=None,
                 num_groups: Optional[int] = None,
                 groups=None):
        super(self.__class__, self).__init__(params)

        if gradient_predivide_factor != 1.0 and op != mpi_ops.Average:
            raise ValueError(
                "gradient_predivide_factor requires op=Average")

        named_parameters = list(named_parameters or [])
        all_params = [p for group in self.param_groups
                      for p in group["params"]]
        if named_parameters:
            named = {id(p): name for name, p in named_parameters}
            dups = len(named_parameters) - len(
                {name for name, _ in named_parameters})
            if dups:
                raise ValueError("named_parameters contains duplicate names")
        else:
            named = {}
        # Names must MATCH across ranks for negotiation, so the fallback is
        # positional, not id()-based (reference uses the same scheme).
        self._param_names = {
            id(p): named.get(id(p), f"allreduce.noname.{i}")
            for i, p in enumerate(all_params)}
        self._p_by_id = {id(p): p for p in all_params}

        self._requires_update = [p for group in self.param_groups
                                 for p in group["params"]
                                 if p.requires_grad]
        self._compression = compression
        self._bpps = max(1, int(backward_passes_per_step))
        self._op = op
        self._predivide = float(gradient_predivide_factor)
        self._process_set = process_set
        self._handles: dict = {}  # param id -> (handle, compression ctx)
        self._passes: dict = {}  # param id -> accumulation count
        self._sparse_as_dense = bool(sparse_as_dense)
        # pids whose grads are sparse: learned from the first sparse grad
        # a hook sees, or DECLARED up front via sparse_params= (parameter
        # names).  Declaration matters when the first use of a sparse
        # embedding is data-dependent: a rank whose batch skipped it must
        # still contribute a zero-nnz SPARSE collective in synchronize()
        # — an undeclared skip would fill dense and negotiate a different
        # op than its peers (deadlock).
        name_to_pid = {n: pid for pid, n in self._param_names.items()}
        self._sparse_params: set = set()
        for n in (sparse_params or ()):
            if n not in name_to_pid:
                raise ValueError(
                    f"sparse_params entry {n!r} is not a known parameter "
                    f"name")
            self._sparse_params.add(name_to_pid[n])
        # Deterministic grouped fusion (reference: num_groups/groups args;
        # group_table.cc semantics): members of a group allreduce as ONE
        # atomic negotiation unit, enqueued only when every member's
        # gradient is locally ready.  groups= takes explicit lists of
        # params; num_groups= splits requires-grad params into contiguous
        # chunks in registration order (upstream's split_list scheme).
        # Sparse params are excluded — they ride sparse_allreduce
        # individually.
        if groups is not None and num_groups is not None:
            raise ValueError("specify either num_groups or groups, not both")
        self._group_of: dict = {}  # pid -> group index
        self._group_members: list = []  # group -> [pid] in fixed order
        self._group_fired: list = []  # group -> set of locally-ready pids
        if groups is not None:
            for members in groups:
                self._add_group([id(p) for p in members])
        elif num_groups:
            # Contiguous chunks in registration order (upstream's
            # split_list): late-firing groups can enqueue while backward
            # still computes earlier layers — a round-robin stride would
            # put a last-to-fire param in every group and serialize all
            # the fusion traffic to end-of-backward.
            groupable = [id(p) for p in self._requires_update
                         if id(p) not in self._sparse_params]
            n = min(max(1, int(num_groups)), max(1, len(groupable)))
            per, extra = divmod(len(groupable), n)
            off = 0
            for gi in range(n):
                take = per + (1 if gi < extra else 0)
                if take:
                    self._add_group(groupable[off:off + take])
                off += take
        self._should_sync = True
        self._hook_registered = []
        self._register_hooks(all_params)

    def _add_group(self, pids) -> None:
        g = len(self._group_members)
        updatable = {id(p) for p in self._requires_update}
        for pid in pids:
            if pid in self._group_of:
                raise ValueError("a parameter appears in multiple groups")
            if pid not in updatable:
                raise ValueError(
                    "groups= contains a tensor that is not a "
                    "requires-grad optimizer parameter — a frozen member "
                    "never fires its hook, so its group could never "
                    "complete")
            self._group_of[pid] = g
        self._group_members.append(list(pids))
        self._group_fired.append(set())

    # -- hooks --------------------------------------------------------------

    def _register_hooks(self, params: Iterable[torch.nn.Parameter]) -> None:
        for p in params:
            if p.requires_grad:
                h = p.register_post_accumulate_grad_hook(self._make_hook())
                self._hook_registered.append(h)

    def _make_hook(self):
        def hook(p: torch.nn.Parameter) -> None:
            pid = id(p)
            self._passes[pid] = self._passes.get(pid, 0) + 1
            if self._passes[pid] >= self._bpps:
                self._passes[pid] = 0
                self._allreduce_grad_async(p)

        return hook

    def _allreduce_grad_async(self, p: torch.nn.Parameter) -> None:
        pid = id(p)
        if pid in self._handles:
            # A second reduce before step() consumed the first means the
            # user ran more backward passes than backward_passes_per_step;
            # drain the stale handle so the new one wins (reference raises
            # in assert-mode, absorbs otherwise).  retire(), not
            # synchronize(): the stale op's in-place target IS p.grad,
            # which autograd has since re-accumulated — a write-back would
            # clobber the fresh gradient with the old reduction.
            stale = self._handles.pop(pid)
            if stale[0] == "sparse":
                for hh in stale[1][:2]:
                    mpi_ops.retire(hh)
            else:
                mpi_ops.retire(stale[0])
        if p.grad.is_sparse and self._sparse_as_dense:
            # Reference knob: densify sparse grads and ride the ordinary
            # dense allreduce (DistributedOptimizer(sparse_as_dense=True)).
            with torch.no_grad():
                p.grad = p.grad.to_dense()
        if p.grad.is_sparse and pid in self._group_of:
            # Sparse grads ride sparse_allreduce individually; drop the
            # param from its fusion group (layer-determined sparsity, so
            # every rank drops the same member).  The shrunk group may
            # now be complete — already-fired dense members must not be
            # stranded waiting on the departed one.
            g = self._group_of.pop(pid)
            self._group_members[g].remove(pid)
            self._group_fired[g].discard(pid)
            self._maybe_enqueue_group(g)
        if p.grad.is_sparse:
            # Embedding layers with sparse=True route through
            # sparse_allreduce (gather + re-accumulate) instead of
            # densifying.  Scaling for bpps happens on the values
            # locally; compression/predivide are dense-only features
            # (reference restriction).
            self._sparse_params.add(pid)
            if self._predivide != 1.0:
                raise ValueError(
                    "gradient_predivide_factor is not supported for "
                    "sparse gradients")
            grad = p.grad.coalesce()
            if self._bpps > 1:
                grad.values().div_(self._bpps)  # stays coalesced
            token = mpi_ops.sparse_allreduce_async(
                grad, name=self._param_names[pid], op=self._op,
                process_set=self._process_set)
            self._handles[pid] = ("sparse", token, None, p)
            return
        g = self._group_of.get(pid)
        if g is not None:
            # Extra backward after this group already enqueued: the whole
            # group's reductions are stale relative to the re-fired
            # member.  Retire every member's live handle so the group
            # re-enqueues coherently (unfired members are completed by
            # synchronize()'s fill-in, mirroring the per-tensor stale
            # path at the top of this function).
            for m in self._group_members[g]:
                if m in self._handles and m != pid:
                    mpi_ops.retire(self._handles.pop(m)[0])
            # Fusion group: enqueue only when every member's gradient is
            # locally ready; the whole group then negotiates atomically.
            self._group_fired[g].add(pid)
            self._maybe_enqueue_group(g)
            return
        op, prescale, postscale = self._dense_scale()
        compressed, ctx = self._compression.compress(p.grad)
        h = mpi_ops.allreduce_async_(
            compressed, name=self._param_names[pid], op=op,
            prescale_factor=prescale, postscale_factor=postscale,
            process_set=self._process_set)
        self._handles[pid] = (h, ctx, compressed, p)

    def _dense_scale(self):
        op, prescale, postscale = self._op, 1.0 / self._bpps, 1.0
        if self._predivide != 1.0:
            # Reference semantics: split the 1/size of Average into
            # pre/post parts around the summation for numerical range
            # control; op becomes Sum with explicit scaling.
            op = mpi_ops.Sum
            prescale /= self._predivide
            postscale = self._predivide / _set_size(self._process_set)
        return op, prescale, postscale

    def _maybe_enqueue_group(self, g: int) -> None:
        if self._group_members[g] and \
                len(self._group_fired[g]) == len(self._group_members[g]):
            self._enqueue_group(g)

    def _group_name(self, g: int) -> str:
        # Derived from the member PARAMETER names, not the group index:
        # two DistributedOptimizer instances in one process (GAN-style)
        # would otherwise emit colliding group keys for different tensor
        # sets, merging distinct groups in negotiation.  Member names are
        # cross-rank consistent, so the digest is too.
        import hashlib

        sig = ",".join(self._param_names[pid]
                       for pid in self._group_members[g])
        return "hvd.grouped." + hashlib.sha1(sig.encode()).hexdigest()[:12]

    def _enqueue_group(self, g: int) -> None:
        members = self._group_members[g]
        op, prescale, postscale = self._dense_scale()
        comp = [self._compression.compress(self._p_by_id[pid].grad)
                for pid in members]
        handles = mpi_ops.grouped_allreduce_async_(
            [t for t, _ in comp], name=self._group_name(g), op=op,
            prescale_factor=prescale, postscale_factor=postscale,
            process_set=self._process_set)
        for pid, h, (t, ctx) in zip(members, handles, comp):
            self._handles[pid] = (h, ctx, t, self._p_by_id[pid])
        self._group_fired[g].clear()

    # -- public surface (reference parity) ---------------------------------

    def synchronize(self) -> None:
        """Wait for every outstanding gradient allreduce and write the
        reduced (decompressed) gradients back into ``p.grad``.

        Parameters whose hook did NOT fire this round (data-dependent
        control flow skipped them, or backward_passes_per_step has not
        been reached) are reduced here with their current — possibly
        zero — gradient, so every rank enqueues the SAME collective set
        per step (the reference's missing-parameter handling; without it
        a rank that skipped a branch deadlocks the ranks that didn't).

        Handles are always cleared, even when a collective raises: the
        elastic retry loop catches the error, restores state, and re-runs
        the step — the optimizer must come back usable, not wedged on
        stale handles from the failed round."""
        for p in self._requires_update:
            pid = id(p)
            if pid in self._handles:
                continue
            g = self._group_of.get(pid)
            if g is not None and pid in self._group_fired[g]:
                # Fired but its group is still waiting on other members;
                # their fill-ins below complete the group.
                continue
            if p.grad is None:
                if pid in self._sparse_params:
                    # Zero-nnz contribution, matching the sparse
                    # collectives the other ranks enqueue under this
                    # name (a dense zeros fill would negotiate a
                    # different op and hang the job).
                    p.grad = torch.sparse_coo_tensor(
                        torch.zeros((1, 0), dtype=torch.int64),
                        torch.zeros((0,) + tuple(p.shape[1:]),
                                    dtype=p.dtype),
                        p.shape)
                else:
                    p.grad = torch.zeros_like(p)
            self._passes[pid] = 0
            self._allreduce_grad_async(p)
        entries = list(self._handles.items())
        try:
            for pid, (h, ctx, compressed, p) in entries:
                if h == "sparse":
                    p.grad = mpi_ops.sparse_synchronize(ctx)
                    continue
                reduced = mpi_ops.synchronize(h)  # in-place: `compressed`
                restored = self._compression.decompress(reduced, ctx)
                if restored.data_ptr() != p.grad.data_ptr():
                    with torch.no_grad():
                        p.grad.copy_(restored.to(p.grad.dtype))
        except BaseException:
            # Sweep the not-yet-synchronized handles out of the module
            # write-back table too — they hold strong gradient-tensor
            # references and mpi_ops.synchronize will never run for them.
            for _, (h, ctx, *_rest) in entries:
                if h == "sparse":
                    for hh in ctx[:2]:
                        mpi_ops.forget(hh)
                else:
                    mpi_ops.forget(h)
            raise
        finally:
            self._handles.clear()

    def set_backward_passes_per_step(self, passes: int) -> None:
        """Change the local-aggregation window (reference setter); resets
        the per-parameter accumulation counters."""
        self._bpps = max(1, int(passes))
        self._passes = {}

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Inside this context, ``step()`` skips the implicit synchronize —
        for callers that invoked :meth:`synchronize` manually (reference:
        optimizer.skip_synchronize)."""
        self._should_sync = False
        try:
            yield
        finally:
            self._should_sync = True

    def step(self, closure=None):
        if self._should_sync:
            self.synchronize()
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "zero_grad called with allreduces in flight; call step() "
                "or synchronize() first (reference raises the same way)")
        self._passes = {}
        for fired in self._group_fired:
            fired.clear()  # zeroed grads invalidate partial group fires
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def _set_size(process_set: Optional[ProcessSet]) -> int:
    from ..process_sets import effective_size

    return effective_size(process_set)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[
                             Iterable[Tuple[str, torch.nn.Parameter]]] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op=mpi_ops.Average,
                         gradient_predivide_factor: float = 1.0,
                         process_set: Optional[ProcessSet] = None,
                         sparse_as_dense: bool = False,
                         sparse_params=None,
                         num_groups: Optional[int] = None,
                         groups=None) -> torch.optim.Optimizer:
    """Wrap a torch optimizer so gradients are averaged across ranks during
    backward (reference factory: horovod/torch/optimizer.py
    DistributedOptimizer).

    ``sparse_as_dense=True`` densifies sparse gradients before the reduce
    (the reference knob); otherwise sparse grads ride
    :func:`sparse_allreduce`.  ``sparse_params=`` (parameter names)
    pre-declares sparse-gradient parameters so a rank whose batch skips
    the layer on the very first step still negotiates the sparse
    collective (see _DistributedOptimizer.__init__).

    ``num_groups=N`` (or explicit ``groups=[[params...], ...]``)
    partitions gradients into fusion groups that allreduce as atomic
    negotiation units — the reference's deterministic grouped fusion."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, gradient_predivide_factor,
               process_set, sparse_as_dense, sparse_params, num_groups,
               groups)
