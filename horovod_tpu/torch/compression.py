"""Gradient compression for the torch binding.

Reference: horovod/torch/compression.py (Compression.none / Compression.fp16);
SURVEY.md §2.4.  Same algebra as the JAX binding's compression module: the
compressor halves wire bytes by casting float32/float64 gradients to a
16-bit dtype before the allreduce and restoring the original dtype after.
``bf16`` is the TPU-native addition (wider exponent range than fp16 — the
dtype the rest of this framework prefers on the wire).
"""

from __future__ import annotations

import torch


class Compressor:
    @staticmethod
    def compress(tensor: torch.Tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor: torch.Tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: torch.dtype = torch.float16

    @classmethod
    def compress(cls, tensor: torch.Tensor):
        if tensor.dtype in (torch.float32, torch.float64):
            return tensor.to(cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor: torch.Tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = torch.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = torch.bfloat16


class Compression:
    """Namespace matching ``hvd.Compression.{none,fp16}`` (+ TPU bf16)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
