"""Parameter/optimizer-state/object broadcast for torch models.

Reference: horovod/torch/functions.py (broadcast_parameters,
broadcast_optimizer_state, broadcast_object); SURVEY.md §2.4, §3.3 (the
``hvd.broadcast_parameters(model.state_dict(), root_rank=0)`` idiom every
reference training script starts with).

Tensors broadcast in place through the grouped (atomic) negotiation path so
a model's full state crosses in as few fused cycles as possible; non-tensor
values ride the two-phase pickled-object broadcast.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Tuple, Union

import torch

from ..process_sets import ProcessSet
from . import mpi_ops


class _TensorPlaceholder:
    """Shape/dtype stand-in for a tensor inside the pickled phase-1
    optimizer-state structure (the tensor itself rides phase 2)."""

    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype


def broadcast_parameters(params: Union[dict, Iterable[Tuple[str, Any]]],
                         root_rank: int = 0,
                         process_set: Optional[ProcessSet] = None) -> None:
    """Broadcast model parameters from ``root_rank`` in place.

    Accepts ``model.state_dict()`` or ``model.named_parameters()`` exactly
    like the reference.
    """
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)

    handles = []
    for name, p in items:
        if p is None:
            continue
        if not isinstance(p, torch.Tensor):
            raise ValueError(
                f"broadcast_parameters got a non-tensor entry {name!r}; "
                "broadcast non-tensor state with broadcast_object")
        handles.append(mpi_ops.broadcast_async_(
            p, root_rank, name=f"broadcast.params.{name}",
            process_set=process_set))
    for h in handles:
        mpi_ops.synchronize(h)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0,
                              process_set: Optional[ProcessSet] = None
                              ) -> None:
    """Broadcast an optimizer's state from ``root_rank``.

    The reference walks state_dict broadcasting tensors natively and
    scalars via pickled callbacks.  Same split here: the (possibly empty on
    non-root!) state dict is replaced wholesale by rank 0's pickled
    structure first, then every tensor inside it is re-broadcast natively
    so large moment buffers do not ride the pickle path.
    """
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError(
            "cannot broadcast torch.optim.LBFGS state "
            "(reference has the same restriction)")

    from .. import basics

    state = optimizer.state_dict()
    # Phase 1: structure only (param groups, scalar state like step
    # counters).  Tensors are replaced by shape/dtype placeholders before
    # pickling — Adam moments are ~2x model size and ride phase 2's native
    # broadcast instead; non-root ranks (possibly with EMPTY state from a
    # fresh optimizer) materialize zeros of the right geometry to receive
    # into.
    def _strip(v):
        if isinstance(v, torch.Tensor):
            return _TensorPlaceholder(tuple(v.shape), v.dtype)
        if isinstance(v, dict):
            return {k: _strip(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(_strip(x) for x in v)
        return v

    def _fill(v):
        if isinstance(v, _TensorPlaceholder):
            return torch.zeros(v.shape, dtype=v.dtype)
        if isinstance(v, dict):
            return {k: _fill(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(_fill(x) for x in v)
        return v

    synced = broadcast_object(_strip(state), root_rank,
                              name="broadcast.opt_state.struct",
                              process_set=process_set)
    if basics.rank() != root_rank:
        optimizer.load_state_dict(_fill(synced))

    # Phase 2: native in-place broadcast of every tensor in the live state.
    handles = []
    for pid, pstate in sorted(optimizer.state_dict()["state"].items()):
        for key, value in sorted(pstate.items()):
            if isinstance(value, torch.Tensor):
                handles.append(mpi_ops.broadcast_async_(
                    value, root_rank,
                    name=f"broadcast.opt_state.{pid}.{key}",
                    process_set=process_set))
    for h in handles:
        mpi_ops.synchronize(h)


def allgather_object(obj: Any, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> list:
    """Gather one picklable object per rank into a rank-ordered list
    (reference: hvd.allgather_object).  Delegates to the framework-neutral
    core so wire names match a JAX rank's in mixed jobs."""
    from ..functions import allgather_object as _core_allgather_object

    return _core_allgather_object(obj, name=name, process_set=process_set)


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> Any:
    """Broadcast an arbitrary picklable object (two-phase: size then
    payload, the reference's protocol).

    Delegates to the framework-neutral core implementation so a torch rank
    and a JAX rank in the same job negotiate matching wire names — the
    object payload is numpy on the wire either way.
    """
    from ..functions import broadcast_object as _core_broadcast_object

    return _core_broadcast_object(obj, root_rank=root_rank, name=name,
                                  process_set=process_set)
