"""Torch-flavored collective ops over the same core runtime.

Reference analogs: horovod/torch/mpi_ops.py (allreduce/allreduce_async_/
synchronize/poll, the HandleManager pattern of handle_manager.cc) and
horovod/torch/adapter_v2.cc (TorchTensor bridging); SURVEY.md §2.3-2.4.

Torch tensors here are host-resident (CPU build), so every op rides the
eager spine — negotiation over the socket controller, fusion, response
cache, and the host TCP/shm data plane — exactly the path the reference's
CPU (MPI/Gloo) ops take.  Unlike the JAX binding, torch tensors are
mutable, so the in-place ``*_``` variants have true reference semantics:
the reduced result is written back into the input tensor's storage.

The handle contract matches the reference: ``*_async`` returns an int
handle; ``synchronize(handle)`` blocks and returns the output tensor
(writing in place first when the op was an in-place variant);
``poll(handle)`` is a non-blocking completion test.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np
import torch

from ..context import HorovodContext, register_shutdown_callback
from ..process_sets import ProcessSet, _resolve_psid
from ..wire import OpType, ReduceOp


def _torch_version_tuple() -> Tuple[int, int]:
    # "2.3.1+cpu" / "2.1.0a0+git..." -> (2, 3); unparseable -> assume new
    # enough rather than refusing a working nightly.
    parts = torch.__version__.split("+")[0].split(".")
    try:
        return int(parts[0]), int("".join(
            c for c in parts[1] if c.isdigit()) or 0)
    except (IndexError, ValueError):  # pragma: no cover - exotic builds
        return (999, 0)


_TORCH_VERSION = _torch_version_tuple()

# Hard floor: the optimizer binding is built on
# register_post_accumulate_grad_hook (torch >= 2.1).  Fail at import with
# the real reason instead of an AttributeError deep inside a training step.
if _TORCH_VERSION < (2, 1):
    raise ImportError(
        f"horovod_tpu.torch requires torch >= 2.1 "
        f"(register_post_accumulate_grad_hook); found {torch.__version__}")

# Soft floor: the zero-copy bf16 bridge bit-reinterprets through
# torch.uint16, which exists from torch 2.3.  Older torch falls back to a
# lossy float32 round-trip, same as the no-ml_dtypes path.
_BF16_VIEW_OK = _TORCH_VERSION >= (2, 3) and hasattr(torch, "uint16")

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None


def _to_numpy(tensor: torch.Tensor) -> np.ndarray:
    """Host numpy view of a torch tensor (zero-copy when contiguous).

    bfloat16 has no numpy native dtype; it crosses as a uint16
    bit-reinterpretation viewed as ml_dtypes.bfloat16, which the wire/data
    plane already reduce natively (16-bit reductions, wire.py dtype table).
    """
    t = tensor.detach()
    if not t.is_contiguous():
        t = t.contiguous()
    if t.dtype == torch.bfloat16:
        if _BF16 is None or not _BF16_VIEW_OK:
            return t.float().numpy()
        return t.view(torch.uint16).numpy().view(_BF16)
    return t.numpy()


def _from_numpy(arr: np.ndarray) -> torch.Tensor:
    arr = np.ascontiguousarray(arr)
    if _BF16 is not None and arr.dtype == _BF16:
        if not _BF16_VIEW_OK:
            return torch.from_numpy(
                arr.astype(np.float32)).to(torch.bfloat16)
        return torch.from_numpy(arr.view(np.uint16).copy()).view(
            torch.bfloat16)
    return torch.from_numpy(arr.copy())


def _write_back(target: torch.Tensor, arr: np.ndarray) -> torch.Tensor:
    out = _from_numpy(arr)
    if out.shape != target.shape:
        if out.numel() == target.numel():
            # The wire flattens 0-dim scalars to shape (1,); same payload.
            out = out.reshape(target.shape)
        else:
            # allgather/alltoall change dim 0; in-place parity is only
            # offered for shape-preserving ops, so this is an internal error.
            raise RuntimeError(
                f"in-place write-back shape mismatch: {out.shape} vs "
                f"{tuple(target.shape)}")
    # no_grad: in-place targets may be requires-grad leaves
    # (broadcast_parameters over named_parameters hands us nn.Parameters);
    # a tracked copy_ into a leaf raises in autograd.
    with torch.no_grad():
        target.copy_(out.to(target.dtype))
    return target


class _HandleTable:
    """Maps core handles to torch-side completion actions (the reference's
    handle_manager.cc role): the in-place target to write back into (None
    for out-of-place ops), plus the torch dtype the result must come back
    as — collectives preserve dtype, and the bf16 fallback path (no
    ml_dtypes: tensors cross as float32) would otherwise silently change
    the output dtype."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict = {}

    def register(self, handle: int, target: Optional[torch.Tensor],
                 want_dtype: Optional[torch.dtype] = None) -> int:
        with self._lock:
            self._entries[handle] = (target, want_dtype)
        return handle

    def pop(self, handle: int):
        with self._lock:
            return self._entries.pop(handle, (None, None))

    def sweep(self) -> List[int]:
        """Drop every outstanding entry, returning the swept handles."""
        with self._lock:
            handles = list(self._entries)
            self._entries.clear()
        return handles


_handles = _HandleTable()


def _sweep_on_shutdown() -> None:
    # Abort/shutdown sweep: outstanding async ops will never be
    # synchronized (the core failed them), so forget their torch-side
    # bookkeeping — the strong tensor references and in-place write-back
    # targets — or a post-abort hvd.init() in an elastic retry loop would
    # see stale handles from the dead job.
    _handles.sweep()


register_shutdown_callback(_sweep_on_shutdown)

# Reference-parity ReduceOp aliases (horovod.torch exposes these names).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
Adasum = ReduceOp.ADASUM


def _resolve_op(op: Optional[ReduceOp], average: Optional[bool]) -> ReduceOp:
    if average is not None:
        if op is not None:
            raise ValueError(
                "specify either op or the deprecated average=, not both")
        return ReduceOp.AVERAGE if average else ReduceOp.SUM
    return ReduceOp.AVERAGE if op is None else op


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------


def _allreduce_enqueue(tensor: torch.Tensor, name: Optional[str],
                       op: ReduceOp, prescale_factor: float,
                       postscale_factor: float,
                       process_set: Optional[ProcessSet],
                       inplace: bool) -> int:
    h = HorovodContext.instance().enqueue(
        _to_numpy(tensor), OpType.ALLREDUCE, name=name, reduce_op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=_resolve_psid(process_set))
    return _handles.register(h, tensor if inplace else None,
                             tensor.dtype)


def allreduce_async(tensor: torch.Tensor, average: Optional[bool] = None,
                    name: Optional[str] = None,
                    op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: Optional[ProcessSet] = None) -> int:
    return _allreduce_enqueue(tensor, name, _resolve_op(op, average),
                              prescale_factor, postscale_factor,
                              process_set, inplace=False)


def allreduce_async_(tensor: torch.Tensor, average: Optional[bool] = None,
                     name: Optional[str] = None,
                     op: Optional[ReduceOp] = None,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     process_set: Optional[ProcessSet] = None) -> int:
    """In-place async allreduce: ``synchronize`` writes the reduction back
    into ``tensor`` (reference: allreduce_async_ in torch/mpi_ops.py)."""
    return _allreduce_enqueue(tensor, name, _resolve_op(op, average),
                              prescale_factor, postscale_factor,
                              process_set, inplace=True)


def allreduce(tensor: torch.Tensor, average: Optional[bool] = None,
              name: Optional[str] = None, compression=None,
              op: Optional[ReduceOp] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    """Average (default) or otherwise reduce ``tensor`` across ranks,
    returning a new tensor."""
    from .compression import Compression

    compression = compression or Compression.none
    compressed, ctx = compression.compress(tensor)
    h = allreduce_async(compressed, average=average, name=name, op=op,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                        process_set=process_set)
    return compression.decompress(synchronize(h), ctx)


def allreduce_(tensor: torch.Tensor, average: Optional[bool] = None,
               name: Optional[str] = None, op: Optional[ReduceOp] = None,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0,
               process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    """In-place synchronous allreduce."""
    return synchronize(allreduce_async_(
        tensor, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set))


def _grouped_enqueue(tensors: Sequence[torch.Tensor], op_type: OpType,
                     name: Optional[str],
                     process_set: Optional[ProcessSet],
                     inplace: bool = False, **enqueue_kw) -> List[int]:
    """Shared grouped enqueue: one atomic negotiation group (coordinator
    gates all-or-nothing; reference: group_table.cc), per-member names
    derived from the group name (must MATCH across ranks)."""
    ctx = HorovodContext.instance()
    gkey = ctx.group_key_for(name)
    handles = []
    for i, t in enumerate(tensors):
        h = ctx.enqueue(_to_numpy(t), op_type,
                        name=f"{name}.{i}" if name else None,
                        process_set_id=_resolve_psid(process_set),
                        group_key=gkey, group_size=len(tensors),
                        **enqueue_kw)
        handles.append(_handles.register(h, t if inplace else None,
                                         t.dtype))
    return handles


def grouped_allreduce_async(tensors: Sequence[torch.Tensor],
                            average: Optional[bool] = None,
                            name: Optional[str] = None,
                            op: Optional[ReduceOp] = None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: Optional[ProcessSet] = None,
                            _inplace: bool = False) -> List[int]:
    return _grouped_enqueue(
        tensors, OpType.ALLREDUCE, name, process_set, inplace=_inplace,
        reduce_op=_resolve_op(op, average), prescale_factor=prescale_factor,
        postscale_factor=postscale_factor)


def grouped_allreduce_async_(tensors: Sequence[torch.Tensor],
                             average: Optional[bool] = None,
                             name: Optional[str] = None,
                             op: Optional[ReduceOp] = None,
                             prescale_factor: float = 1.0,
                             postscale_factor: float = 1.0,
                             process_set: Optional[ProcessSet] = None
                             ) -> List[int]:
    return grouped_allreduce_async(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set, _inplace=True)


def grouped_allreduce(tensors: Sequence[torch.Tensor],
                      average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Optional[ReduceOp] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set: Optional[ProcessSet] = None
                      ) -> List[torch.Tensor]:
    return [synchronize(h) for h in grouped_allreduce_async(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set)]


def grouped_allreduce_(tensors: Sequence[torch.Tensor],
                       average: Optional[bool] = None,
                       name: Optional[str] = None,
                       op: Optional[ReduceOp] = None,
                       prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0,
                       process_set: Optional[ProcessSet] = None
                       ) -> List[torch.Tensor]:
    return [synchronize(h) for h in grouped_allreduce_async_(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set)]


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------


def grouped_allgather_async(tensors: Sequence[torch.Tensor],
                            name: Optional[str] = None,
                            process_set: Optional[ProcessSet] = None
                            ) -> List[int]:
    """Allgather a list as one atomic negotiation group (reference:
    grouped_allgather, group_table.cc)."""
    return _grouped_enqueue(tensors, OpType.ALLGATHER, name, process_set)


def grouped_allgather(tensors: Sequence[torch.Tensor],
                      name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None
                      ) -> List[torch.Tensor]:
    return [synchronize(h) for h in grouped_allgather_async(
        tensors, name=name, process_set=process_set)]


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    h = HorovodContext.instance().enqueue(
        _to_numpy(tensor), OpType.ALLGATHER, name=name,
        process_set_id=_resolve_psid(process_set))
    return _handles.register(h, None, tensor.dtype)


def allgather(tensor: torch.Tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    """Concatenate each rank's tensor along dim 0 (ranks may differ in
    dim 0, reference semantics)."""
    return synchronize(allgather_async(tensor, name=name,
                                       process_set=process_set))


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    h = HorovodContext.instance().enqueue(
        _to_numpy(tensor), OpType.BROADCAST, name=name, root_rank=root_rank,
        process_set_id=_resolve_psid(process_set))
    return _handles.register(h, None, tensor.dtype)


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> int:
    h = HorovodContext.instance().enqueue(
        _to_numpy(tensor), OpType.BROADCAST, name=name, root_rank=root_rank,
        process_set_id=_resolve_psid(process_set))
    return _handles.register(h, tensor, tensor.dtype)


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(broadcast_async(tensor, root_rank, name=name,
                                       process_set=process_set))


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None,
               process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name=name,
                                        process_set=process_set))


# ---------------------------------------------------------------------------
# alltoall / reducescatter
# ---------------------------------------------------------------------------


def alltoall_async(tensor: torch.Tensor, splits=None,
                   name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    if splits is not None and isinstance(splits, torch.Tensor):
        splits = splits.numpy()
    h = HorovodContext.instance().enqueue(
        _to_numpy(tensor), OpType.ALLTOALL, name=name, splits=splits,
        process_set_id=_resolve_psid(process_set))
    return _handles.register(h, None, tensor.dtype)


def alltoall(tensor: torch.Tensor, splits=None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None
             ) -> Tuple[torch.Tensor, torch.Tensor]:
    """Distribute slices of dim 0 to all ranks; returns
    ``(received_tensor, received_splits)`` like the reference."""
    return synchronize(alltoall_async(tensor, splits=splits, name=name,
                                      process_set=process_set))


def reducescatter_async(tensor: torch.Tensor,
                        op: ReduceOp = ReduceOp.AVERAGE,
                        name: Optional[str] = None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0,
                        process_set: Optional[ProcessSet] = None) -> int:
    h = HorovodContext.instance().enqueue(
        _to_numpy(tensor), OpType.REDUCESCATTER, name=name, reduce_op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set_id=_resolve_psid(process_set))
    return _handles.register(h, None, tensor.dtype)


def reducescatter(tensor: torch.Tensor, op: ReduceOp = ReduceOp.AVERAGE,
                  name: Optional[str] = None,
                  prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0,
                  process_set: Optional[ProcessSet] = None) -> torch.Tensor:
    return synchronize(reducescatter_async(
        tensor, op=op, name=name, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, process_set=process_set))


def grouped_reducescatter_async(tensors: Sequence[torch.Tensor],
                                op: ReduceOp = ReduceOp.AVERAGE,
                                name: Optional[str] = None,
                                prescale_factor: float = 1.0,
                                postscale_factor: float = 1.0,
                                process_set: Optional[ProcessSet] = None
                                ) -> List[int]:
    """Reducescatter a list as one atomic negotiation group (reference:
    grouped_reducescatter, group_table.cc)."""
    return _grouped_enqueue(
        tensors, OpType.REDUCESCATTER, name, process_set, reduce_op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor)


def grouped_reducescatter(tensors: Sequence[torch.Tensor],
                          op: ReduceOp = ReduceOp.AVERAGE,
                          name: Optional[str] = None,
                          prescale_factor: float = 1.0,
                          postscale_factor: float = 1.0,
                          process_set: Optional[ProcessSet] = None
                          ) -> List[torch.Tensor]:
    return [synchronize(h) for h in grouped_reducescatter_async(
        tensors, op=op, name=name, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, process_set=process_set)]


# ---------------------------------------------------------------------------
# barrier / join / handles
# ---------------------------------------------------------------------------


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    from .. import mpi_ops as _jax_mpi_ops

    _jax_mpi_ops.barrier(process_set=process_set)


def join() -> int:
    from .. import mpi_ops as _jax_mpi_ops

    return _jax_mpi_ops.join()


def synchronize(handle: int):
    """Block until the op behind ``handle`` completes.  Writes in-place
    targets back into their original storage, converts eager results to
    torch, and passes the alltoall (tensor, splits) pair through."""
    # Pop before waiting: a raising collective (elastic failure, shutdown)
    # must not leak the table entry and its strong tensor reference.
    target, want_dtype = _handles.pop(handle)
    result = HorovodContext.instance().synchronize(handle)

    def _restore(t: torch.Tensor) -> torch.Tensor:
        # Collectives preserve dtype; the no-ml_dtypes bf16 fallback
        # crosses as float32 and must come back as bf16.
        return t if want_dtype in (None, t.dtype) else t.to(want_dtype)

    if isinstance(result, tuple):  # alltoall: (data, recv_splits)
        data, rsplits = result
        return (_restore(_from_numpy(np.asarray(data))),
                torch.from_numpy(np.asarray(rsplits).copy()))
    arr = np.asarray(result)
    if target is not None:
        return _write_back(target, arr)
    return _restore(_from_numpy(arr))


def sparse_allreduce_async(tensor: torch.Tensor,
                           name: Optional[str] = None,
                           op: Optional[ReduceOp] = None,
                           process_set: Optional[ProcessSet] = None):
    """Start a sparse COO allreduce; returns an opaque token for
    :func:`sparse_synchronize`.  Both underlying allgathers (indices,
    values) enqueue immediately and negotiate concurrently."""
    if not tensor.is_sparse:
        raise ValueError("sparse_allreduce requires a sparse COO tensor")
    rop = _resolve_op(op, None)
    if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("sparse_allreduce supports Sum and Average only")
    sp = tensor.coalesce()
    # Ragged allgather over dim 0: indices cross transposed to
    # (nnz, sparse_dim), values as (nnz, ...dense dims) — a rank with
    # zero touched rows contributes zero rows and still participates.
    # Unnamed calls ride the core's deterministic noname counter
    # (call-order contract, like every other unnamed collective).
    h_idx = allgather_async(sp.indices().t().contiguous(),
                            name=f"{name}.idx" if name else None,
                            process_set=process_set)
    h_vals = allgather_async(sp.values().contiguous(),
                             name=f"{name}.vals" if name else None,
                             process_set=process_set)
    return (h_idx, h_vals, tuple(sp.shape), rop, process_set)


def sparse_synchronize(token) -> torch.Tensor:
    """Finish a :func:`sparse_allreduce_async`: re-accumulate the gathered
    (indices, values) into a coalesced sparse tensor."""
    h_idx, h_vals, shape, rop, process_set = token
    try:
        idx = synchronize(h_idx)
    except BaseException:
        # Preserve the pop-before-wait invariant for BOTH halves: a
        # failing indices gather must not leak the values entry (elastic
        # retry loops would accumulate stale table entries per step).
        retire(h_vals)
        raise
    vals = synchronize(h_vals)
    out = torch.sparse_coo_tensor(idx.t(), vals, shape).coalesce()
    if rop == ReduceOp.AVERAGE:
        from ..process_sets import effective_size

        # In-place on the coalesced values: dividing by a scalar cannot
        # create duplicate indices, so no re-coalesce.
        out.values().div_(effective_size(process_set))
    return out


def sparse_allreduce(tensor: torch.Tensor, name: Optional[str] = None,
                     op: Optional[ReduceOp] = None,
                     process_set: Optional[ProcessSet] = None
                     ) -> torch.Tensor:
    """Allreduce a sparse COO tensor by gathering every rank's
    (indices, values) and re-accumulating — the reference's
    sparse_allreduce_async strategy (gradients of embedding layers with
    sparse=True), which beats densifying when the union of touched rows
    is small.  Returns a coalesced sparse tensor; Average (default)
    divides by the process-set size like the dense op."""
    return sparse_synchronize(sparse_allreduce_async(
        tensor, name=name, op=op, process_set=process_set))


def forget(handle: int) -> None:
    """Drop the torch-side bookkeeping for ``handle`` WITHOUT waiting on
    the core: releases the table entry's strong tensor reference and its
    in-place write-back.  For error-path sweeps where the core op already
    failed (or will be failed by shutdown) and ``synchronize`` will never
    run — unlike :func:`retire`, this never blocks.  Unknown handles are a
    no-op."""
    _handles.pop(handle)


def retire(handle: int) -> None:
    """Wait out the op behind ``handle`` and DISCARD its result: no
    in-place write-back, no conversion.  For draining a stale handle whose
    target buffer has since been reused (e.g. autograd re-accumulated into
    p.grad) — a normal synchronize would clobber the new contents with the
    old reduction.  Unknown/already-retired handles are a no-op."""
    _handles.pop(handle)
    try:
        HorovodContext.instance().synchronize(handle)
    except ValueError:
        pass


def poll(handle: int) -> bool:
    """True if the async op behind ``handle`` has completed.  A handle that
    was already synchronized (retired from the core's table) is complete by
    definition — reference poll semantics."""
    try:
        return HorovodContext.instance().poll(handle)
    except ValueError:
        return True
