"""TPU-pod host discovery for elastic training.

Reference analog (SURVEY.md §3.5, §5): the reference's elastic driver polls
a user discovery script for the live host set; on TPU pods the equivalent
signal lives in the GCE metadata server — the worker endpoint list from the
TPU environment attributes, and per-host preemption / maintenance events.
``TPUPodDiscovery`` is a ``HostDiscovery`` that serves exactly that, so
``horovodrun --min-np N --tpu-discovery`` rides preemptions the way the
reference rides discovery-script changes (BASELINE config 5).

The metadata base URL is overridable (HOROVOD_TPU_METADATA_URL) which is
also how the tests drive it against a local fake server.
"""

from __future__ import annotations

import os
import urllib.request
from typing import Dict, Optional

from .elastic_driver import HostDiscovery

_DEFAULT_METADATA = "http://metadata.google.internal"
_HEADERS = {"Metadata-Flavor": "Google"}


def _get(base: str, path: str, timeout: float = 2.0) -> Optional[str]:
    req = urllib.request.Request(base + path, headers=_HEADERS)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode().strip()
    except Exception:
        return None


class TPUPodDiscovery(HostDiscovery):
    """Live host set of a TPU pod from the metadata server.

    Worker endpoints come from the TPU environment attribute
    (``tpu-env`` -> WORKER_NETWORK_ENDPOINTS, the canonical source on TPU
    VMs) or, when absent, ``HOROVOD_TPU_WORKERS`` (comma-separated) as the
    static fallback.  A host is dropped while the metadata server reports
    it preempted or under a TERMINATE maintenance event.
    """

    def __init__(self, slots_per_host: int = 1,
                 metadata_url: Optional[str] = None):
        self.slots = max(slots_per_host, 1)
        self.base = (metadata_url
                     or os.environ.get("HOROVOD_TPU_METADATA_URL")
                     or _DEFAULT_METADATA)

    # -- worker set ---------------------------------------------------------
    def _workers(self) -> list:
        env_workers = os.environ.get("HOROVOD_TPU_WORKERS")
        if env_workers:
            return [w.strip() for w in env_workers.split(",") if w.strip()]
        tpu_env = _get(self.base, "/computeMetadata/v1/instance/attributes/"
                                  "tpu-env")
        if tpu_env:
            for line in tpu_env.splitlines():
                if line.startswith("WORKER_NETWORK_ENDPOINTS"):
                    # format: 'WORKER_NETWORK_ENDPOINTS: ip1,ip2,...'
                    # (each endpoint may be id:port:ip — take the last part)
                    _, _, value = line.partition(":")
                    out = []
                    for ep in value.strip().strip("'\"").split(","):
                        ep = ep.strip()
                        if ep:
                            out.append(ep.rsplit(":", 1)[-1])
                    return out
        return []

    def _host_healthy(self, host: str) -> bool:
        """TCP reachability probe: a preempted/terminated TPU-VM worker
        stops accepting connections, which is the only per-host signal the
        launcher can observe (the metadata server's preempted/
        maintenance-event endpoints describe the *requesting* instance
        only).  Probe port: HOROVOD_TPU_PROBE_PORT, default 22 (sshd is up
        on every live TPU VM)."""
        import socket as pysocket

        port = int(os.environ.get("HOROVOD_TPU_PROBE_PORT", "22"))
        try:
            conn = pysocket.create_connection((host, port), timeout=2.0)
            conn.close()
            return True
        except OSError:
            return False

    def self_preempted(self) -> bool:
        """Whether the *local* instance has been preempted / scheduled for
        termination (valid use of the instance-scoped metadata endpoints;
        workers can poll this to checkpoint before the axe falls)."""
        state = _get(self.base, "/computeMetadata/v1/instance/preempted")
        if state is not None and state.upper() == "TRUE":
            return True
        maint = _get(self.base,
                     "/computeMetadata/v1/instance/maintenance-event")
        return maint is not None and maint.upper().startswith("TERMINATE")

    def find_available_hosts(self) -> Dict[str, int]:
        # Probe concurrently: serial 2s timeouts would make a poll scale
        # with the number of DEAD hosts, slowing reaction exactly when a
        # preemption took out part of the pod.
        from concurrent.futures import ThreadPoolExecutor

        workers = self._workers()
        if not workers:
            return {}
        with ThreadPoolExecutor(max_workers=min(32, len(workers))) as ex:
            healthy = list(ex.map(self._host_healthy, workers))
        return {h: self.slots for h, ok in zip(workers, healthy) if ok}
