"""Programmatic launcher: hvd.run(fn, np=2) -> per-rank results.

Reference: horovod/runner/__init__.py run() (launches a pickled function on
every worker and gathers return values); SURVEY.md §2.5.  Used heavily by
tests/parallel to express multi-process collective tests as plain Python
functions.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from typing import Any, Callable, List, Optional

from .launch import WorkerProcesses
from .util import assign_ranks, find_free_port, HostSlots


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        np: int = 2, env: Optional[dict] = None, timeout: float = 300.0,
        stream_prefix: bool = True, use_mpi: Optional[bool] = None,
        use_gloo: Optional[bool] = None) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` local worker processes and
    return the per-rank results ordered by rank.

    Raises RuntimeError with the failing rank's traceback summary if any
    worker fails.  ``use_mpi``/``use_gloo`` are accepted for reference
    signature parity and ignored (there is one controller).
    """
    import cloudpickle

    kwargs = kwargs or {}
    # Pickle the function by value so workers don't need the caller's module
    # on their import path (test functions, notebooks, __main__).
    module = sys.modules.get(getattr(fn, "__module__", None))
    if module is not None and module.__name__ not in ("builtins",):
        try:
            cloudpickle.register_pickle_by_value(module)
        except Exception:
            pass
    with tempfile.TemporaryDirectory(prefix="hvd_run_") as tmp:
        payload = os.path.join(tmp, "payload.pkl")
        with open(payload, "wb") as f:
            cloudpickle.dump((fn, args, kwargs), f)

        assignments = assign_ranks([HostSlots("localhost", np)], np)
        port = find_free_port()
        base_env = dict(os.environ)
        if env:
            base_env.update(env)
        command = [sys.executable, "-m", "horovod_tpu.runner._exec_fn",
                   payload, tmp]
        workers = WorkerProcesses()
        workers.launch(assignments, command, base_env, "127.0.0.1", port,
                       stream_prefix=stream_prefix)
        try:
            exit_code = workers.wait()
        except KeyboardInterrupt:
            workers.terminate()
            raise

        results: List[Any] = []
        errors: List[str] = []
        for rank in range(np):
            path = os.path.join(tmp, f"result_{rank}.pkl")
            if not os.path.exists(path):
                errors.append(f"rank {rank}: no result (exit={exit_code})")
                results.append(None)
                continue
            with open(path, "rb") as f:
                status, value = pickle.load(f)
            if status == "ok":
                results.append(value)
            else:
                errors.append(f"rank {rank}: {value}")
                results.append(None)
        if errors:
            raise RuntimeError("horovod_tpu.run failed: " + "; ".join(errors))
        return results
