"""Pre-flight driver/task services: verify the cluster before launching.

Reference: horovod/runner/driver/driver_service.py (HorovodRunDriverService),
runner/task/task_service.py and common/util/network.py (SURVEY.md §2.5,
§3.4): before a single worker starts, the launcher drives a tiny task
service on every remote host which (a) proves the host is reachable and can
exec our interpreter, and (b) discovers which of the driver's network
addresses that host can route to — so multi-NIC machines pick a rendezvous
interface every worker can reach, and a dead host fails the launch in
seconds with its name attached instead of hanging the first collective.

Protocol (one line of signed JSON over TCP, HMAC per runner/util.py):
  task -> driver: {"host": h, "slots": n, "driver_addr": addr_it_reached,
                   "task_addrs": [...]}
  driver -> task: {"ok": true}
"""

from __future__ import annotations

import os
import shlex
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from .util import (local_hostnames, make_secret, signed_dumps, ssh_command,
                   verified_loads)


def local_addresses() -> List[str]:
    """Candidate IPv4 addresses of this machine, most-routable first
    (reference: network.get_local_host_addresses / driver_service's
    _get_common_interfaces)."""
    addrs: List[str] = []
    # The address that routes toward the outside world (no packet is sent).
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 9))
        addrs.append(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       socket.AF_INET):
            addrs.append(info[4][0])
    except OSError:
        pass
    addrs.append("127.0.0.1")
    out = []
    for a in addrs:
        if a not in out:
            out.append(a)
    return out


class DriverService:
    """Listens for task-probe registrations (reference:
    HorovodRunDriverService: register_task / wait_for_initial_registration)."""

    def __init__(self, secret: str):
        self.secret = secret
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._registrations: Dict[str, dict] = {}
        self._cv = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        self._sock.settimeout(0.5)
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(10.0)
            data = b""
            while not data.endswith(b"\n"):
                chunk = conn.recv(4096)
                if not chunk:
                    return
                data += chunk
            msg = verified_loads(data.decode().strip(), self.secret)
            if not isinstance(msg, dict) or "host" not in msg:
                return  # unverifiable or malformed: ignore (signed RPC)
            with self._cv:
                self._registrations[msg["host"]] = msg
                self._cv.notify_all()
            conn.sendall((signed_dumps({"ok": True}, self.secret) +
                          "\n").encode())
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def wait_for(self, hosts: Sequence[str], timeout: float) -> Dict[str, dict]:
        """Block until every host registered; raise naming the missing ones."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                missing = [h for h in hosts if h not in self._registrations]
                if not missing:
                    return dict(self._registrations)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        "pre-flight probe timed out after "
                        f"{timeout:.0f}s; unreachable host(s): "
                        + ", ".join(missing)
                        + (" (reachable: "
                           + ", ".join(sorted(self._registrations)) + ")"
                           if self._registrations else ""))
                self._cv.wait(min(remaining, 0.5))

    def close(self) -> None:
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


def run_task_probe(driver_addrs: Sequence[str], port: int, host: str,
                   secret: str, slots: int = 1,
                   timeout: float = 10.0) -> int:
    """Task side: test every driver candidate address (the NIC-matching
    handshake of the reference's driver/task services), then register over
    the first reachable one, reporting the full reachable set."""
    reachable: List[str] = []
    last_err = "no driver addresses given"
    for addr in driver_addrs:
        try:
            probe = socket.create_connection((addr, port), timeout=3.0)
            probe.close()
            reachable.append(addr)
        except OSError as exc:
            last_err = f"{addr}:{port}: {exc}"
    for addr in reachable:
        try:
            conn = socket.create_connection((addr, port), timeout=timeout)
        except OSError as exc:
            last_err = f"{addr}:{port}: {exc}"
            continue
        try:
            msg = {
                "host": host,
                "slots": slots,
                "driver_addr": addr,
                "reachable": reachable,
                "task_addrs": local_addresses(),
            }
            conn.sendall((signed_dumps(msg, secret) + "\n").encode())
            conn.settimeout(timeout)
            reply = b""
            while not reply.endswith(b"\n"):
                chunk = conn.recv(4096)
                if not chunk:
                    break
                reply += chunk
            try:
                ack = verified_loads(reply.decode().strip(), secret)
            except Exception:
                ack = None  # empty/garbled ack (e.g. rejected signature)
            if isinstance(ack, dict) and ack.get("ok"):
                return 0
            last_err = f"{addr}:{port}: bad ack"
        except OSError as exc:
            last_err = f"{addr}:{port}: {exc}"
        finally:
            try:
                conn.close()
            except OSError:
                pass
    print(f"task probe failed: {last_err}", file=sys.stderr)
    return 1


def _probe_command(host: str, driver_addrs: Sequence[str], port: int,
                   secret: str, slots: int,
                   ssh_port: Optional[int]) -> List[str]:
    """The exec'd probe command; remote hosts get it wrapped in ssh
    (mock point for the unit tests, reference §4 item 3)."""
    inner = [
        sys.executable, "-m", "horovod_tpu.runner.driver_service",
        "--driver-addrs", ",".join(driver_addrs), "--port", str(port),
        "--host", host, "--slots", str(slots),
    ]
    if host in local_hostnames():
        return inner
    ssh_cmd = ssh_command(ssh_port=ssh_port, connect_timeout=10)
    # The probe secret must NOT ride the ssh argv (visible in `ps` on both
    # ends); it ships over ssh stdin, same as the elastic spawn path.
    env = ""
    pypath = os.environ.get("PYTHONPATH", "")
    if pypath:
        env = f"PYTHONPATH={shlex.quote(pypath)} "
    remote = ("read -r HOROVOD_PROBE_SECRET; export HOROVOD_PROBE_SECRET; "
              f"cd {shlex.quote(os.getcwd())} && env {env}"
              + " ".join(shlex.quote(c) for c in inner))
    return ssh_cmd + [host, remote]


def preflight_probe(hosts: Sequence[object], ssh_port: Optional[int] = None,
                    timeout: float = 30.0,
                    exec_fn=None) -> Dict[str, object]:
    """Probe every host before launch.  Returns
    {"rendezvous_addr": <driver addr every host reached>,
     "registrations": {host: {...}}}.  Raises RuntimeError naming
    unreachable hosts.  `exec_fn(cmd, env)` spawns a probe process
    (injectable for tests; defaults to subprocess.Popen)."""
    secret = make_secret()
    driver = DriverService(secret)
    procs = []
    errlogs: Dict[str, List[str]] = {}
    try:
        addrs = local_addresses()
        hostnames = []
        for h in hosts:
            hostname = getattr(h, "hostname", h)
            slots = getattr(h, "slots", 1)
            hostnames.append(hostname)
            cmd = _probe_command(hostname, addrs, driver.port, secret, slots,
                                 ssh_port)
            env = dict(os.environ)
            env["HOROVOD_PROBE_SECRET"] = secret
            remote_probe = hostname not in local_hostnames()
            if exec_fn is not None:
                procs.append(exec_fn(cmd, env))
            else:
                proc = subprocess.Popen(
                    cmd, env=env, stdout=subprocess.DEVNULL,
                    stderr=subprocess.PIPE, text=True,
                    stdin=subprocess.PIPE if remote_probe else None)
                procs.append(proc)
                if remote_probe:
                    # Matching `read -r HOROVOD_PROBE_SECRET` in the
                    # remote command.
                    try:
                        proc.stdin.write(secret + "\n")
                        proc.stdin.flush()
                    except OSError:
                        pass
                # Drain stderr continuously: ssh banners/errors must neither
                # fill the pipe (blocking the probe) nor vanish — they are
                # the diagnosis when a host fails.
                log = errlogs.setdefault(hostname, [])

                def _drain(p=proc, log=log):
                    for line in iter(p.stderr.readline, ""):
                        log.append(line.rstrip())

                threading.Thread(target=_drain, daemon=True).start()
        try:
            regs = driver.wait_for(hostnames, timeout)
        except RuntimeError as exc:
            detail = "; ".join(
                f"{h}: {' | '.join(lines[-3:])}"
                for h, lines in errlogs.items() if lines)
            raise RuntimeError(
                str(exc) + (f" [probe stderr: {detail}]" if detail else "")
            ) from None
        # The rendezvous interface must be routable from every host.
        common = [a for a in addrs
                  if all(a in r.get("reachable", [r.get("driver_addr")])
                         for r in regs.values())]
        rendezvous = common[0] if common else \
            next(iter(regs.values()))["driver_addr"]
        return {"rendezvous_addr": rendezvous, "registrations": regs}
    finally:
        driver.close()
        for p in procs:
            try:
                if p.poll() is None:
                    p.wait(timeout=5)
            except Exception:
                try:
                    p.kill()
                except Exception:
                    pass


def _main() -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="horovod_tpu task probe")
    ap.add_argument("--driver-addrs", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", required=True)
    ap.add_argument("--slots", type=int, default=1)
    args = ap.parse_args()
    secret = os.environ.get("HOROVOD_PROBE_SECRET", "")
    return run_task_probe(args.driver_addrs.split(","), args.port, args.host,
                          secret, args.slots)


if __name__ == "__main__":
    sys.exit(_main())
