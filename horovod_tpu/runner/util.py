"""Launcher utilities (reference: horovod/runner/common/util/{hosts,network}.py)."""

from __future__ import annotations

import dataclasses
import os
import shlex
import socket
from typing import List


@dataclasses.dataclass
class HostSlots:
    hostname: str
    slots: int


def parse_hosts(hosts: str) -> List[HostSlots]:
    """Parse '-H host1:2,host2:4' (reference: hosts.parse_hosts)."""
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostSlots(name, int(slots)))
        else:
            out.append(HostSlots(part, 1))
    return out


def assign_ranks(hosts: List[HostSlots], np_: int):
    """Round-robin-free block assignment of ranks to host slots, returning
    a list of (rank, hostname, local_rank, local_size, cross_rank,
    cross_size) like the reference's rank allocation."""
    slots = []
    for h in hosts:
        for local_rank in range(h.slots):
            slots.append((h.hostname, local_rank))
    if np_ > len(slots):
        raise ValueError(
            f"requested -np {np_} exceeds available slots {len(slots)}")
    slots = slots[:np_]
    per_host: dict = {}
    for hostname, _ in slots:
        per_host[hostname] = per_host.get(hostname, 0) + 1
    host_order = list(dict.fromkeys(h for h, _ in slots))
    assignments = []
    for rank, (hostname, local_rank) in enumerate(slots):
        assignments.append({
            "rank": rank,
            "hostname": hostname,
            "local_rank": local_rank,
            "local_size": per_host[hostname],
            "cross_rank": host_order.index(hostname),
            "cross_size": len(host_order),
        })
    return assignments


def find_free_port(addr: str = "127.0.0.1") -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((addr, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def local_hostnames() -> List[str]:
    return ["localhost", "127.0.0.1", socket.gethostname()]


def host_hash() -> str:
    """Stable per-host identifier (reference: util/host_hash.py) — used to
    group ranks by physical host."""
    import hashlib

    return hashlib.md5(socket.gethostname().encode()).hexdigest()[:16]


def make_secret() -> str:
    """Random shared secret for signing coordinator RPCs (reference:
    common/util/secret.py)."""
    import secrets

    return secrets.token_hex(16)


def sign_message(secret: str, payload: str) -> str:
    """HMAC-SHA256 signature over a wire payload."""
    import hashlib
    import hmac

    return hmac.new(secret.encode(), payload.encode(),
                    hashlib.sha256).hexdigest()


def verify_message(secret: str, payload: str, signature: str) -> bool:
    import hmac

    return hmac.compare_digest(sign_message(secret, payload), signature)


def signed_dumps(obj, secret) -> str:
    """Serialize a coordinator message, HMAC-signing it when a shared
    secret is configured (reference: runner/common/util/secret.py — the
    driver/worker RPCs are signed so a stray connection can't join or
    reshape the job)."""
    import json

    payload = json.dumps(obj, sort_keys=True, separators=(",", ":"))
    if not secret:
        return payload
    return json.dumps({"p": payload, "sig": sign_message(secret, payload)},
                      separators=(",", ":"))


def verified_loads(line: str, secret):
    """Parse (and verify, when a secret is configured) a wire message;
    returns None for unverifiable messages."""
    import json

    msg = json.loads(line)
    if not secret:
        return msg
    if not (isinstance(msg, dict) and "p" in msg and "sig" in msg):
        return None
    if not verify_message(secret, msg["p"], msg["sig"]):
        return None
    return json.loads(msg["p"])


# Env prefixes both launchers forward to remote (ssh) workers.  The TPU_
# namespace is deliberately NOT a prefix here: a TPU-VM's environment
# carries instance-specific runtime vars (TPU_WORKER_ID, TPU_WORKER_
# HOSTNAMES, ...) that must not clobber the remote VM's own; only the
# pinning vars the launcher itself sets travel, by exact name.
FORWARD_ENV_PREFIXES = ("HOROVOD_", "PYTHONPATH", "PATH", "JAX_", "XLA_")
# TPU_VISIBLE_DEVICES is deliberately NOT forwarded: the launcher never
# sets it, so forwarding would impose the launcher host's own local pin on
# every remote VM.  Pin remote single-worker hosts on the host itself.
FORWARD_ENV_NAMES = ("TPU_VISIBLE_CHIPS", "TPU_CHIPS_PER_PROCESS_BOUNDS")


def forwardable_env(k: str) -> bool:
    return k.startswith(FORWARD_ENV_PREFIXES) or k in FORWARD_ENV_NAMES


def ssh_command(ssh_port=None, connect_timeout=None) -> List[str]:
    """Base argv used to exec on a remote host (invoked as
    ``ssh_command() + [host, remote_shell_string]``).

    ``HOROVOD_SSH_COMMAND`` replaces the ENTIRE base argv (shlex-split,
    used verbatim — no extra options are appended, including -p), which
    enables agent-less transports and lets integration tests exercise the
    real remote-spawn path without an sshd (a fake-ssh script that runs
    the command locally).  Default: ssh with host-key checking off, the
    reference's gloo_run ssh contract (SURVEY.md §2.5).
    """
    override = os.environ.get("HOROVOD_SSH_COMMAND")
    if override:
        # Warn only on the user-passed --ssh-port: connect_timeout is an
        # internal default on some call sites (driver_service preflight),
        # so warning on it alone would fire spuriously for every override
        # user.  The message still names both dropped option kinds.
        if ssh_port:
            import warnings

            warnings.warn(
                "HOROVOD_SSH_COMMAND is set; --ssh-port/-p (and any "
                "ConnectTimeout option) are ignored — bake them into the "
                "override command instead.")
        return shlex.split(override)
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if connect_timeout:
        cmd += ["-o", f"ConnectTimeout={int(connect_timeout)}"]
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    return cmd


def pin_tpu_chip(env: dict, local_rank: int, local_size: int,
                 force: bool = False) -> None:
    """Pin a co-located worker to its own TPU chip (libtpu is single-owner
    per chip — the GPU analog is the local-rank device pinning the
    reference's launcher relies on).

    With one worker on the host nothing is touched (the worker may use all
    chips, and an explicit user pin is honored) unless ``force`` is set —
    the elastic driver always pins, because a lone worker that claimed the
    whole host would collide with workers spawned by a later scale-up.
    With several co-located workers a single inherited ``TPU_VISIBLE_CHIPS``
    would hand every worker the same chip and crash all but the first
    claim, so it is overridden per worker.
    """
    if local_size <= 1 and not force:
        # A lone worker keeps all chips; its explicit pin (if any) is
        # honored as-is.
        return
    if "TPU_VISIBLE_CHIPS" in env or "TPU_VISIBLE_DEVICES" in env:
        import sys

        print(f"horovod_tpu: overriding inherited TPU chip pin for "
              f"local_rank {local_rank} (per-slot pinning is required "
              "here; an inherited global pin cannot be per-worker correct)",
              file=sys.stderr)
        env.pop("TPU_VISIBLE_DEVICES", None)
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
    env["TPU_VISIBLE_CHIPS"] = str(local_rank)
    env.setdefault("TPU_CHIPS_PER_PROCESS_BOUNDS", "1,1,1")
