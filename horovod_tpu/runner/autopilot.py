"""Fleet autopilot: closed-loop, metrics-driven eviction and elastic policy.

A driver-side policy engine that closes the loop the observability stack
opened: the coordinator already *attributes* stragglers (per-rank announce
lag vs. the fleet median, ``SocketController::MaybeStragglerReport``); this
module *acts* on the verdicts with zero human input.

Data path::

    coordinator (rank 0)                       driver
    ------------------------                   -----------------------------
    announce-lag histograms  --POLL-->         FleetAutopilot.observe()
    straggler windows/ranks  <--DECISION--     evict / scale_up / readmit
    flight type 13 + AUTOPILOT timeline        ElasticDriver.evict_host()

The policy channel is a newline-terminated text protocol over the
coordinator's LOOPBACK listener (``HOROVOD_AUTOPILOT_PORT``, assigned per
generation by the elastic driver): ``POLL`` returns a JSON status line
``{"v":1,"windows":N,"culprits":[rank...],"hosts":[key...],...}``;
``DECISION <action> <rank> <detail>`` records the decision natively (flight
recorder type 13, ``autopilot_decisions_total`` counter, an ``AUTOPILOT``
timeline instant) *before* the eviction tears the generation down.

Decision rules (documented in docs/elastic.md):

- **Evict**: a rank flagged in ``HOROVOD_AUTOPILOT_EVICT_WINDOWS``
  consecutive straggler report windows has its host fed to the elastic
  blacklist (expiring sentence with exponential backoff), never shrinking
  below ``HOROVOD_AUTOPILOT_MIN_NP`` and never evicting rank 0 (the
  coordinator is the measuring instrument).  A clean window (rank not
  flagged) resets its streak — transient noise never evicts.
- **Cooldown**: at most one eviction per ``HOROVOD_AUTOPILOT_COOLDOWN_SECS``
  so the fleet re-stabilises between decisions.
- **Scale up / readmit**: blacklist expiry and discovery growth already
  poke the elastic driver; the autopilot records them as decisions so the
  flight/timeline record names every fleet change.

All HOROVOD_AUTOPILOT* knobs are driver-side only — worker processes and
the native core never read them (the port rides the ctypes ABI).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import time
from typing import Dict, Optional

from ..utils.env import get_float, get_int

# Action codes — mirror of kAutopilotAct* in cpp/socket_controller.cc and
# the rendering table in tools/postmortem.py (keep the three in sync).
ACT_EVICT = 1
ACT_SCALE_UP = 2
ACT_READMIT = 3

ACTION_NAMES = {ACT_EVICT: "evict", ACT_SCALE_UP: "scale_up",
                ACT_READMIT: "readmit"}

DEFAULT_EVICT_WINDOWS = 3
DEFAULT_COOLDOWN_SECS = 60.0
POLL_INTERVAL_S = 1.0


class PolicyClient:
    """One-shot client for the coordinator's loopback policy channel."""

    def __init__(self, port: int, timeout: float = 2.0):
        self.port = port
        self.timeout = timeout

    def _roundtrip(self, line: str) -> Optional[dict]:
        try:
            with socket.create_connection(("127.0.0.1", self.port),
                                          timeout=self.timeout) as s:
                s.settimeout(self.timeout)
                s.sendall((line + "\n").encode())
                buf = b""
                while b"\n" not in buf:
                    chunk = s.recv(4096)
                    if not chunk:
                        return None
                    buf += chunk
                return json.loads(buf.split(b"\n", 1)[0].decode())
        except (OSError, ValueError):
            return None

    def poll(self) -> Optional[dict]:
        return self._roundtrip("POLL")

    def decision(self, action: int, rank: int, detail: str) -> bool:
        detail = detail.replace("\n", " ")
        reply = self._roundtrip(f"DECISION {action} {rank} {detail}")
        return bool(reply and reply.get("ok"))


class FleetAutopilot:
    """The policy loop.  ``observe()`` is the pure decision function
    (injectable clock, no sleeps — unit-testable); ``run()`` wires it to
    the live driver and coordinator."""

    def __init__(self, driver, clock=time.monotonic,
                 poll_interval: float = POLL_INTERVAL_S):
        self.driver = driver
        self.clock = clock
        self.poll_interval = poll_interval
        self.evict_windows = max(
            1, get_int("HOROVOD_AUTOPILOT_EVICT_WINDOWS",
                       DEFAULT_EVICT_WINDOWS))
        # Safety rail: never shrink below this.  Defaults to the job's
        # --min-np (the driver would abort below that anyway).
        self.min_np = max(1, get_int("HOROVOD_AUTOPILOT_MIN_NP",
                                     getattr(driver, "min_np", 1)))
        self.cooldown_s = get_float("HOROVOD_AUTOPILOT_COOLDOWN_SECS",
                                    DEFAULT_COOLDOWN_SECS)
        # rank -> consecutive flagged report windows
        self._streaks: Dict[int, int] = {}
        self._last_windows = 0
        # Highest sentinel anomaly seq already journaled (per generation):
        # the fleet-telemetry sentinel is advisory — it names the suspect
        # in the record *before* the eviction rule can fire, it never
        # evicts by itself.
        self._last_anomaly_seq = -1
        self._gen = -1
        self._last_evict_at: Optional[float] = None
        self._last_blacklist: Dict[str, float] = {}
        self._last_size = 0
        self._log_path = None
        pm_dir = os.environ.get("HOROVOD_POSTMORTEM_DIR")
        if pm_dir:
            self._log_path = os.path.join(pm_dir, "autopilot.jsonl")

    # -- decision core (pure; unit-tested without sleeps) --------------------
    def observe(self, status: dict, now: float) -> Optional[dict]:
        """Fold one POLL status into the streak state; return an eviction
        decision dict ``{"action", "rank", "host", "reason"}`` or None.

        ``status["windows"]`` counts straggler report windows since the
        coordinator started; the delta since the previous poll is how many
        NEW windows this poll covers (polling faster than the report
        interval must not inflate streaks).
        """
        windows = int(status.get("windows", 0))
        delta = windows - self._last_windows
        if delta < 0:  # new coordinator generation restarted the counter
            self._streaks.clear()
            delta = windows
        self._last_windows = windows
        if delta == 0:
            return None
        culprits = [int(r) for r in status.get("culprits", [])]
        hosts = [str(h) for h in status.get("hosts", [])]
        host_of = dict(zip(culprits, hosts))
        flagged = set(culprits)
        for r in list(self._streaks):
            if r not in flagged:
                # A clean window breaks the streak: transient noise (one
                # GC pause, one checkpoint write) never evicts.
                del self._streaks[r]
        for r in flagged:
            self._streaks[r] = self._streaks.get(r, 0) + delta
        for r, streak in sorted(self._streaks.items(),
                                key=lambda kv: -kv[1]):
            if streak < self.evict_windows:
                continue
            if r == 0:
                # The coordinator is the measuring instrument; its own lag
                # reads as everyone else being early.  Never self-evict.
                continue
            host = host_of.get(r)
            if not host:
                continue
            if (self._last_evict_at is not None
                    and now - self._last_evict_at < self.cooldown_s):
                return None
            slots = self.driver.live_slots_on(host)
            if self.driver.live_size() - slots < self.min_np:
                # Min-np rail: evicting would sink the job below the
                # floor; keep limping with the straggler instead.
                return None
            return {"action": ACT_EVICT, "rank": r, "host": host,
                    "reason": f"straggler for {streak} consecutive "
                              f"report windows"}
        return None

    def note_generation(self, gen: int) -> None:
        """Reset per-coordinator state when the generation turns over."""
        if gen != self._gen:
            self._gen = gen
            self._streaks.clear()
            self._last_windows = 0
            self._last_anomaly_seq = -1

    def note_anomalies(self, status: dict) -> int:
        """Journal NEW sentinel anomalies from a POLL status (diffed by
        ``seq``) as advisory ``"anomaly"`` rows in autopilot.jsonl.

        Advisory only: the sentinel fires within ~1-2 ticks of an
        inflection while the eviction rule needs ``evict_windows`` full
        straggler report windows, so the journal names the suspect rank
        strictly before any eviction decision.  Returns how many rows
        were written (pure state + journal; no policy-channel traffic).
        """
        fresh = 0
        for a in status.get("anomalies") or []:
            a = a or {}
            try:
                seq = int(a.get("seq", -1))
            except (TypeError, ValueError):
                continue
            if seq <= self._last_anomaly_seq:
                continue
            self._last_anomaly_seq = seq
            fresh += 1
            rank = int(a.get("rank", -1))
            detail = (f"sentinel {a.get('kind', '?')} seq={seq} "
                      f"value={a.get('value', 0)} "
                      f"baseline={a.get('baseline', 0)} "
                      f"score={a.get('score', 0)}")
            self._journal({"ts": time.time(), "generation": self._gen,
                           "action": "anomaly", "rank": rank,
                           "detail": detail})
            print(f"autopilot: anomaly rank={rank} {detail}",
                  file=sys.stderr)
        return fresh

    # -- recording -----------------------------------------------------------
    def _journal(self, row: dict) -> None:
        if self._log_path:
            try:
                with open(self._log_path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(row) + "\n")
            except OSError:
                pass

    def _record(self, client: Optional[PolicyClient], action: int,
                rank: int, detail: str) -> None:
        name = ACTION_NAMES.get(action, "unknown")
        if client is not None:
            # Record natively FIRST: the flight dump + timeline instant must
            # exist before an eviction tears the generation down.
            client.decision(action, rank, detail)
        self._journal({"ts": time.time(), "generation": self._gen,
                       "action": name, "rank": rank, "detail": detail})
        print(f"autopilot: {name} rank={rank} {detail}", file=sys.stderr)

    def _watch_fleet_changes(self, client: Optional[PolicyClient]) -> None:
        """Record blacklist expiries (readmit) and formation growth
        (scale_up) — the elastic machinery performs them; the autopilot
        names them in the record."""
        cur = dict(getattr(self.driver, "_blacklist", {}))
        for host in self._last_blacklist:
            if host not in cur:
                self._record(client, ACT_READMIT, -1,
                             f"blacklist expired for host {host}")
        self._last_blacklist = cur
        size = getattr(self.driver, "_formed_size", 0)
        if size > self._last_size and self._last_size > 0:
            self._record(client, ACT_SCALE_UP, -1,
                         f"fleet grew {self._last_size} -> {size}")
        if size:
            self._last_size = size

    # -- live loop -----------------------------------------------------------
    def run(self) -> None:
        while not self.driver._stop.is_set():
            time.sleep(self.poll_interval)
            gen, port = self.driver.policy_endpoint()
            self.note_generation(gen)
            client = PolicyClient(port) if port else None
            self._watch_fleet_changes(client)
            if client is None:
                continue
            status = client.poll()
            if not status:
                continue
            # Advisory sentinel anomalies journal first: the record names
            # the suspect rank before any eviction decision below.
            self.note_anomalies(status)
            decision = self.observe(status, self.clock())
            if decision is None:
                continue
            self._last_evict_at = self.clock()
            self._record(client, decision["action"], decision["rank"],
                         f"host {decision['host']}: {decision['reason']}")
            self.driver.evict_host(decision["host"], decision["reason"])
            # The generation is about to turn over; drop streaks now so a
            # stale rank numbering never feeds the next generation.
            self._streaks.clear()
            self._last_windows = 0
