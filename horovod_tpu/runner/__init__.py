from .run_api import run  # noqa: F401
