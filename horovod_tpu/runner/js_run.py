"""LSF / jsrun launch path.

Reference: horovod/runner/js_run.py (js_run) + runner/util/lsf.py
(LSFUtils) — on LSF-scheduled clusters `horovodrun` delegates process
placement to `jsrun` instead of ssh.  The TPU build keeps the same shape:
detect an LSF allocation from its environment, derive hosts/slots from
LSB_MCPU_HOSTS, and build the `jsrun` command line that launches one
resource set per worker with the usual HOROVOD_* env contract.
"""

from __future__ import annotations

import os
import shlex
from typing import Dict, List, Optional

from .util import FORWARD_ENV_PREFIXES


class LSFUtils:
    """Queries over the LSF allocation environment (reference:
    runner/util/lsf.py LSFUtils)."""

    @staticmethod
    def using_lsf() -> bool:
        return "LSB_JOBID" in os.environ

    @staticmethod
    def get_allocated_hosts(env: Optional[Dict[str, str]] = None
                            ) -> List[tuple]:
        """Allocated (host, slots), preferring LSB_DJOB_HOSTFILE (one line
        per slot — authoritative, the reference's source) and falling back
        to LSB_MCPU_HOSTS parsing with the batch-slot heuristic."""
        env = env if env is not None else os.environ
        hostfile = env.get("LSB_DJOB_HOSTFILE")
        if hostfile and os.path.exists(hostfile):
            counts: Dict[str, int] = {}
            order: List[str] = []
            with open(hostfile) as f:
                lines = [ln.strip() for ln in f if ln.strip()]
            # On LSF+jsrun clusters the first line is the batch/launch
            # node's slot, which jsrun never schedules on.  The file cannot
            # distinguish that from a genuine single-slot compute host, so
            # the skip is overridable: HOROVOD_LSF_INCLUDE_LAUNCH_HOST=1
            # keeps every line.
            include_launch = env.get(
                "HOROVOD_LSF_INCLUDE_LAUNCH_HOST") == "1"
            if not include_launch and len(lines) > 1 and \
                    lines.count(lines[0]) == 1:
                lines = lines[1:]
            for host in lines:
                if host not in counts:
                    order.append(host)
                counts[host] = counts.get(host, 0) + 1
            return [(h, counts[h]) for h in order]
        toks = env.get("LSB_MCPU_HOSTS", "").split()
        pairs = [(toks[i], int(toks[i + 1]))
                 for i in range(0, len(toks) - 1, 2)]
        # Heuristic fallback: a leading single-slot entry followed by
        # compute hosts is the batch node (ambiguous when a compute host
        # genuinely has one slot — provide LSB_DJOB_HOSTFILE for those).
        if len(pairs) > 2 and pairs[0][1] == 1 and \
                all(n > 1 for _, n in pairs[1:]):
            pairs = pairs[1:]
        return pairs

    @staticmethod
    def get_num_processes(env: Optional[Dict[str, str]] = None) -> int:
        return sum(n for _, n in LSFUtils.get_allocated_hosts(env))


def make_jsrun_command(num_proc: int, command: List[str],
                      env: Dict[str, str],
                      gpu_per_rs: int = 0,
                      launch_args: str = "") -> List[str]:
    """Build the jsrun invocation (reference: js_run.py js_run):
    one resource set per worker, one task each, env forwarded."""
    cmd = [
        "jsrun",
        "--nrs", str(num_proc),        # resource sets == workers
        "--tasks_per_rs", "1",
        "--cpu_per_rs", "ALL_CPUS" if num_proc == 1 else "1",
        "--launch_distribution", "packed",
    ]
    if gpu_per_rs:
        cmd += ["--gpu_per_rs", str(gpu_per_rs)]
    if launch_args:
        cmd += shlex.split(launch_args)
    # Prefixes only (not forwardable_env): a jsrun worker pins its own
    # TPU chips on its own host, so the launcher's TPU_* pins must not
    # ride along.
    env_str = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in env.items()
        if k.startswith(FORWARD_ENV_PREFIXES))
    wrapped = "env " + env_str + " " + \
        " ".join(shlex.quote(c) for c in command)
    cmd += ["sh", "-c", wrapped]
    return cmd


def js_run(args, command: List[str]) -> int:
    """Launch a job through jsrun inside an LSF allocation.  Rank/size come
    from jsrun's own placement (OMPI_COMM_WORLD_RANK et al. are translated
    by the worker-side env shim below)."""
    import random
    import subprocess

    num_proc = args.num_proc or LSFUtils.get_num_processes()
    # The coordinator is rank 0's worker process, which jsrun's packed
    # distribution places on the FIRST allocated compute host — not the
    # batch node this launcher runs on.  Advertise that host, with a port
    # picked from the dynamic range (it cannot be probed remotely; the
    # coordinator binds it and workers retry until it listens).
    hosts = LSFUtils.get_allocated_hosts()
    addr = hosts[0][0] if hosts else "127.0.0.1"
    port = random.randint(23000, 59000)
    env = dict(os.environ)
    env.update({
        "HOROVOD_SIZE": str(num_proc),
        "HOROVOD_CONTROLLER": "socket",
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr,
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
        # jsrun assigns ranks; the worker shim maps them to HOROVOD_RANK
        "HOROVOD_RANK_FROM_JSRUN": "1",
    })
    cmd = make_jsrun_command(num_proc, command, env)
    return subprocess.call(cmd, env=env)


def apply_jsrun_rank_env() -> None:
    """Worker-side shim: translate jsrun/OpenMPI rank env into the
    HOROVOD_* contract (called from Config.from_env when
    HOROVOD_RANK_FROM_JSRUN is set)."""
    if os.environ.get("HOROVOD_RANK_FROM_JSRUN") != "1":
        return
    for src, dst in (
        ("OMPI_COMM_WORLD_RANK", "HOROVOD_RANK"),
        ("OMPI_COMM_WORLD_LOCAL_RANK", "HOROVOD_LOCAL_RANK"),
        ("OMPI_COMM_WORLD_LOCAL_SIZE", "HOROVOD_LOCAL_SIZE"),
        ("JSM_NAMESPACE_RANK", "HOROVOD_RANK"),
        ("JSM_NAMESPACE_LOCAL_RANK", "HOROVOD_LOCAL_RANK"),
    ):
        if src in os.environ and dst not in os.environ:
            os.environ[dst] = os.environ[src]
