"""horovodrun-equivalent launcher.

Reference: horovod/runner/launch.py (parse_args/_run/run_commandline) +
gloo_run.py (launch_gloo: rendezvous env + one worker per slot);
SURVEY.md §2.5, §3.4.  The TPU build launches one worker process per slot
with the same env-var contract (HOROVOD_RANK/SIZE/LOCAL_RANK/...,
HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT), a socket-controller rendezvous instead
of Gloo's HTTP KV store, and ssh for remote hosts.

Usage:
    horovodrun -np 4 python train.py
    python -m horovod_tpu.runner.launch -np 2 -H hostA:1,hostB:1 python t.py
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys
import threading
from typing import Dict, List, Optional

from .util import (assign_ranks, find_free_port, forwardable_env,
                   local_hostnames, parse_hosts, pin_tpu_chip,
                   ssh_command)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="horovodrun",
        description="Launch a horovod_tpu distributed job.")
    p.add_argument("-np", "--num-proc", type=int, required=False,
                   help="Total number of worker processes.")
    p.add_argument("-H", "--hosts", default=None,
                   help="host1:slots,host2:slots (default: localhost:np)")
    p.add_argument("-p", "--ssh-port", type=int, default=None)
    p.add_argument("--network-interface", default=None,
                   help="accepted for reference parity; unused")
    p.add_argument("--start-timeout", type=int, default=60)
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--jax-distributed", action="store_true",
                   help="initialize jax.distributed in every worker so all "
                        "hosts' devices form one global mesh (multi-host "
                        "SPMD over DCN; TPU pods)")
    p.add_argument("--disable-cache", action="store_true",
                   help="disable the response cache")
    # Elastic flags (reference parity; driver in horovod_tpu.runner.elastic).
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None)
    p.add_argument("--tpu-discovery", action="store_true",
                   help="elastic host discovery from the TPU-VM metadata "
                        "server (worker endpoints + preemption events) "
                        "instead of a discovery script")
    p.add_argument("--slots-per-host", type=int, default=1,
                   help="slots per discovered host (elastic mode)")
    p.add_argument("--autopilot", action="store_true",
                   help="fleet autopilot: the driver polls the "
                        "coordinator's straggler verdicts and evicts "
                        "persistent offenders into the expiring elastic "
                        "blacklist, scaling back up when sentences lapse "
                        "(implies elastic mode and HOROVOD_METRICS=1; "
                        "decision rules and HOROVOD_AUTOPILOT_* knobs in "
                        "docs/elastic.md)")
    p.add_argument("--cockpit", action="store_true",
                   help="live cluster cockpit: rank 0 serves /metrics, "
                        "/state and /events (SSE) on a loopback port the "
                        "elastic driver keeps stable across re-formations; "
                        "watch it with tools/hvd_top.py "
                        "(docs/observability.md)")
    # Tuning flags mirroring the reference CLI -> env contract.
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--metrics-file", default=None,
                   help="periodic per-rank JSON metrics snapshots; a "
                        "literal {rank} in the path is substituted, "
                        "otherwise .<rank> is appended "
                        "(HOROVOD_METRICS_FILE; implies HOROVOD_METRICS)")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--hierarchical-allreduce", action="store_true",
                   help="compose shm-local reduce + leader-only cross-host "
                        "ring + shm-local broadcast when hosts hold "
                        "co-located ranks (HOROVOD_HIERARCHICAL_ALLREDUCE)")
    p.add_argument("--wire-compression", default=None,
                   help="codec for fp32 allreduce payloads: a bare codec "
                        "(none|bf16|int8) applies to cross-host ring hops, "
                        "or per-plane plane=codec assignments, e.g. "
                        "'host=bf16,device=int8' ('device=int8' enables the "
                        "in-jit int8 block-scaled ring); accumulation stays "
                        "fp32 (HOROVOD_WIRE_COMPRESSION)")
    p.add_argument("--data-plane", default=None,
                   choices=["auto", "eager", "gspmd"],
                   help="in-jit gradient-exchange plane for "
                        "DistributedOptimizer: 'eager' builds explicit "
                        "shard_map collectives, 'gspmd' annotates shardings "
                        "and lets XLA insert + overlap them, 'auto' adapts "
                        "per trace (HOROVOD_DATA_PLANE)")
    p.add_argument("--control-tree", default=None,
                   choices=["auto", "on", "off"],
                   help="leader-tree control plane (protocol v12): host "
                        "leaders aggregate worker cycle frames so the "
                        "coordinator handles O(fanout) messages instead of "
                        "O(ranks); auto engages on multi-host jobs with "
                        "np >= 8 (HOROVOD_CONTROL_TREE)")
    p.add_argument("--ctrl-tree-fanout", default=None, type=int,
                   metavar="N",
                   help="per-node fan-in bound of the adaptive-depth "
                        "leader tree (default 32, min 2): when a job spans "
                        "more hosts than this, mid-level super-leaders are "
                        "inserted until every node gathers at most N "
                        "aggregate links (HOROVOD_CTRL_TREE_FANOUT)")
    p.add_argument("--control-tree-depth", default=None, type=int,
                   metavar="D",
                   help="force an exact leader-tree level count instead of "
                        "the adaptive fanout rule: 2 pins the v9 two-level "
                        "shape, 3+ always inserts super-leader layers; 0 "
                        "or unset = adaptive (HOROVOD_CONTROL_TREE_DEPTH)")
    p.add_argument("--postmortem-dir", default=None, metavar="DIR",
                   help="crash-bundle directory: every rank dumps its "
                        "flight-recorder ring there on abort or fatal "
                        "signal, and the coordinator writes a merged "
                        "postmortem.json naming the culprit; a literal "
                        "{rank} in the path is substituted "
                        "(HOROVOD_POSTMORTEM_DIR; render with "
                        "tools/postmortem.py)")
    p.add_argument("--no-flight-recorder", action="store_true",
                   help="disable the always-on flight recorder "
                        "(HOROVOD_FLIGHT_RECORDER=off)")
    p.add_argument("--fault-inject", default=None, metavar="SPEC",
                   help="deterministic fault injection for chaos testing: "
                        "comma-separated site:cycle:rank:action[:arg] rules "
                        "exported to every worker as HOROVOD_FAULT_INJECT "
                        "(validated before any worker spawns; see "
                        "docs/observability.md)")
    p.add_argument("--stall-check-disable", action="store_true")
    p.add_argument("--stall-check-warning-time-seconds", type=float,
                   default=None)
    p.add_argument("--log-level", default=None)
    p.add_argument("--check-build", action="store_true")
    p.add_argument("--config-file", default=None,
                   help="YAML file of launcher parameters (reference "
                        "horovodrun --config-file layout); explicit CLI "
                        "flags win over file values")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="the training command")
    args = p.parse_args(argv)
    if args.config_file:
        _apply_config_file(args, p,
                           argv if argv is not None else sys.argv[1:])
    return args


def _apply_config_file(args: argparse.Namespace,
                       parser: argparse.ArgumentParser,
                       argv: List[str]) -> None:
    """Merge a YAML config file under explicit CLI flags (reference:
    runner/launch.py parse_args' --config-file handling: file values fill
    in, command line overrides)."""
    import yaml

    with open(args.config_file) as f:
        cfg = yaml.safe_load(f) or {}
    # Reference layout: flat keys plus nested timeline/autotune/stall-check.
    flat = {
        "verbose": cfg.get("verbose"),
        "num_proc": cfg.get("num-proc", cfg.get("np")),
        "hosts": cfg.get("hosts"),
        "ssh_port": cfg.get("ssh-port"),
        "start_timeout": cfg.get("start-timeout"),
        "network_interface": cfg.get("network-interface"),
        "fusion_threshold_mb": cfg.get("fusion-threshold-mb"),
        "cycle_time_ms": cfg.get("cycle-time-ms"),
        "cache_capacity": cfg.get("cache-capacity"),
        "min_np": cfg.get("min-np"),
        "max_np": cfg.get("max-np"),
        "host_discovery_script": cfg.get("host-discovery-script"),
        "slots_per_host": cfg.get("slots-per-host"),
        "log_level": cfg.get("log-level"),
        "wire_compression": cfg.get("wire-compression"),
        "data_plane": cfg.get("data-plane"),
        "control_tree": cfg.get("control-tree"),
        "ctrl_tree_fanout": cfg.get("ctrl-tree-fanout"),
        "control_tree_depth": cfg.get("control-tree-depth"),
    }
    tl = cfg.get("timeline") or {}
    flat["timeline_filename"] = tl.get("filename")
    flat["timeline_mark_cycles"] = tl.get("mark-cycles")
    mt = cfg.get("metrics") or {}
    flat["metrics_file"] = mt.get("file")
    pm = cfg.get("postmortem") or {}
    flat["postmortem_dir"] = pm.get("dir")
    at = cfg.get("autotune") or {}
    flat["autotune"] = at.get("enabled")
    flat["autotune_log_file"] = at.get("log-file")
    sc = cfg.get("stall-check") or {}
    flat["stall_check_disable"] = sc.get("disable")
    flat["stall_check_warning_time_seconds"] = sc.get(
        "warning-time-seconds")
    # Only fill values the user did not pass on the command line.  Presence
    # is detected from argv itself (comparing against parser defaults would
    # let the file override an explicitly-passed default value).  Only the
    # launcher's own flags — everything before the command remainder — are
    # scanned, so flags inside the training command don't confuse it.
    own_argv = argv[:len(argv) - len(args.command)]
    explicit = set()
    for action in parser._actions:
        if any(opt in own_argv for opt in action.option_strings):
            explicit.add(action.dest)
    for key, value in flat.items():
        if value is None or not hasattr(args, key) or key in explicit:
            continue
        setattr(args, key, value)


def _tuning_env(args: argparse.Namespace) -> Dict[str, str]:
    env = {}
    if args.fusion_threshold_mb is not None:
        env["HOROVOD_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024))
    if args.cycle_time_ms is not None:
        env["HOROVOD_CYCLE_TIME"] = str(args.cycle_time_ms)
    if args.cache_capacity is not None:
        env["HOROVOD_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.disable_cache:
        env["HOROVOD_CACHE_CAPACITY"] = "0"
    if args.timeline_filename:
        env["HOROVOD_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if args.metrics_file:
        env["HOROVOD_METRICS_FILE"] = args.metrics_file
    if args.autotune:
        env["HOROVOD_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        env["HOROVOD_AUTOTUNE_LOG"] = args.autotune_log_file
    if args.hierarchical_allreduce:
        env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    if args.wire_compression:
        env["HOROVOD_WIRE_COMPRESSION"] = args.wire_compression
    if args.data_plane:
        env["HOROVOD_DATA_PLANE"] = args.data_plane
    if args.control_tree:
        env["HOROVOD_CONTROL_TREE"] = args.control_tree
    if args.ctrl_tree_fanout is not None:
        env["HOROVOD_CTRL_TREE_FANOUT"] = str(args.ctrl_tree_fanout)
    if args.control_tree_depth is not None:
        env["HOROVOD_CONTROL_TREE_DEPTH"] = str(args.control_tree_depth)
    if args.postmortem_dir:
        env["HOROVOD_POSTMORTEM_DIR"] = args.postmortem_dir
    if args.no_flight_recorder:
        env["HOROVOD_FLIGHT_RECORDER"] = "off"
    if args.fault_inject:
        env["HOROVOD_FAULT_INJECT"] = args.fault_inject
    if args.stall_check_disable:
        env["HOROVOD_STALL_CHECK_DISABLE"] = "1"
    if args.stall_check_warning_time_seconds is not None:
        env["HOROVOD_STALL_CHECK_TIME_SECONDS"] = str(
            args.stall_check_warning_time_seconds)
    if args.log_level:
        env["HOROVOD_LOG_LEVEL"] = args.log_level
    if getattr(args, "autopilot", False):
        # Straggler attribution (the autopilot's input) lives behind the
        # metrics plane; the policy loop is useless without it.
        env["HOROVOD_METRICS"] = "1"
    if getattr(args, "cockpit", False):
        # The cockpit's /state straggler/tenant sections come from the
        # metrics plane too; the step-trace pillar is on by default.
        env["HOROVOD_COCKPIT"] = "1"
        env["HOROVOD_METRICS"] = "1"
    return env


def check_build(out=sys.stdout) -> None:
    import horovod_tpu as hvd

    from horovod_tpu.runtime import PROTOCOL_VERSION

    print("Horovod-TPU v%s (control protocol v%d):"
          % (hvd.__version__, PROTOCOL_VERSION), file=out)
    print("Available Frameworks:", file=out)
    print("    [X] JAX", file=out)
    try:
        # Probe the BINDING, not just torch: a broken torch install (or a
        # version the binding cannot work with) must show as unavailable
        # in the diagnostic users run to debug exactly that.
        import horovod_tpu.torch  # noqa: F401

        torch_ok = True
    except Exception:
        # Not just ImportError: a torch wheel broken at the shared-library
        # level raises OSError mid-import, and this diagnostic must report
        # "[ ] PyTorch" rather than die with a traceback.
        torch_ok = False
    print("    [%s] PyTorch (horovod_tpu.torch)" % ("X" if torch_ok else " "),
          file=out)
    print("Available Controllers:", file=out)
    print("    [X] TPU socket controller (gloo-equivalent)", file=out)
    print("    [%s] native C++ core" % ("X" if hvd.native_core_built() else " "),
          file=out)
    print("Available Data Planes:", file=out)
    print("    [X] XLA collectives over ICI (jit)", file=out)
    print("    [X] host TCP collectives (eager, multi-process)", file=out)


class WorkerProcesses:
    """Spawn and supervise one process per rank (reference: gloo_run's
    exec + the launcher's output streaming/exit handling)."""

    def __init__(self):
        self.procs: List[subprocess.Popen] = []
        self._lock = threading.Lock()
        self._failed_rank: Optional[int] = None

    def launch(self, assignments, command: List[str], base_env: Dict[str, str],
               rendezvous_addr: str, rendezvous_port: int,
               ssh_port: Optional[int] = None, verbose: bool = False,
               stream_prefix: bool = True):
        threads = []
        for a in assignments:
            env = dict(base_env)
            env.update({
                "HOROVOD_RANK": str(a["rank"]),
                "HOROVOD_SIZE": str(len(assignments)),
                "HOROVOD_LOCAL_RANK": str(a["local_rank"]),
                "HOROVOD_LOCAL_SIZE": str(a["local_size"]),
                "HOROVOD_CROSS_RANK": str(a["cross_rank"]),
                "HOROVOD_CROSS_SIZE": str(a["cross_size"]),
                "HOROVOD_CONTROLLER": "socket",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": rendezvous_addr,
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rendezvous_port),
            })
            pin_tpu_chip(env, a["local_rank"], a["local_size"])
            if a["hostname"] in local_hostnames():
                proc = subprocess.Popen(
                    command, env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True)
            else:  # remote launch over ssh with env forwarding
                env_str = " ".join(
                    f"{k}={shlex.quote(v)}" for k, v in env.items()
                    if forwardable_env(k))
                ssh_cmd = ssh_command(ssh_port=ssh_port)
                remote = f"cd {shlex.quote(os.getcwd())} && env {env_str} " + \
                    " ".join(shlex.quote(c) for c in command)
                proc = subprocess.Popen(
                    ssh_cmd + [a["hostname"], remote], stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True)
            self.procs.append(proc)
            t = threading.Thread(target=self._stream, daemon=True,
                                 args=(a["rank"], proc, stream_prefix))
            t.start()
            threads.append(t)
        return threads

    def _stream(self, rank: int, proc: subprocess.Popen, prefix: bool):
        for line in iter(proc.stdout.readline, ""):
            if prefix:
                sys.stdout.write(f"[{rank}]<stdout>: {line}")
            else:
                sys.stdout.write(line)
            sys.stdout.flush()

    def wait(self, kill_on_failure: bool = True) -> int:
        """Wait for all workers; on the first failure, terminate the rest
        (matching horovodrun's behavior)."""
        exit_code = 0
        pending = {i: p for i, p in enumerate(self.procs)}
        while pending:
            for rank, proc in list(pending.items()):
                rc = proc.poll()
                if rc is None:
                    continue
                del pending[rank]
                if rc != 0 and exit_code == 0:
                    exit_code = rc
                    self._failed_rank = rank
                    if kill_on_failure:
                        for other in pending.values():
                            try:
                                other.send_signal(signal.SIGTERM)
                            except OSError:
                                pass
            if pending:
                import time

                time.sleep(0.05)
        return exit_code

    def terminate(self):
        for p in self.procs:
            try:
                p.terminate()
            except OSError:
                pass


def _run(args: argparse.Namespace) -> int:
    if args.check_build:
        check_build()
        return 0
    if not args.autopilot:
        # Env-var spelling of --autopilot, for launchers driven from job
        # templates where editing argv is awkward.
        from ..utils.env import get_bool

        args.autopilot = get_bool("HOROVOD_AUTOPILOT", False)
    if not getattr(args, "cockpit", False):
        # Env-var spelling of --cockpit, same rationale as --autopilot.
        from ..utils.env import get_bool

        args.cockpit = get_bool("HOROVOD_COCKPIT", False)
    if not args.command:
        print("error: no command given", file=sys.stderr)
        return 2
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if args.fault_inject:
        # Pre-validate the spec against the native parser so a typo fails
        # here with one actionable message instead of failing hvd.init()
        # on every spawned worker at once.
        try:
            from .._core import check_fault_spec

            err = check_fault_spec(args.fault_inject)
        except Exception:
            err = ""  # no native core on the launch host; workers validate
        if err:
            print(f"error: --fault-inject: {err}", file=sys.stderr)
            return 2
    if args.host_discovery_script or args.tpu_discovery \
            or args.min_np is not None or args.autopilot:
        from .elastic_driver import run_elastic

        return run_elastic(args, command)
    # LSF allocation without explicit hosts: delegate placement to jsrun
    # (reference: launch.py routes to js_run on LSF clusters).
    from .js_run import LSFUtils, js_run

    if args.hosts is None and LSFUtils.using_lsf():
        return js_run(args, command)
    if args.num_proc is None:
        print("error: -np is required", file=sys.stderr)
        return 2

    hosts = parse_hosts(args.hosts) if args.hosts else [
        type("H", (), {"hostname": "localhost", "slots": args.num_proc})()]
    assignments = assign_ranks(hosts, args.num_proc)

    rendezvous_addr = "127.0.0.1"
    if any(a["hostname"] not in local_hostnames() for a in assignments):
        # Pre-flight probe (reference: driver/task services, SURVEY.md §2.5):
        # verify every host can exec us and find a mutually-routable
        # interface; fail fast with host names instead of hanging the first
        # collective.
        from .driver_service import preflight_probe

        probe = preflight_probe(hosts, ssh_port=args.ssh_port,
                                timeout=args.start_timeout)
        rendezvous_addr = probe["rendezvous_addr"]
        if args.verbose:
            print(f"pre-flight: all hosts reachable; rendezvous over "
                  f"{rendezvous_addr}", file=sys.stderr)
    rendezvous_port = find_free_port(
        "0.0.0.0" if rendezvous_addr != "127.0.0.1" else "127.0.0.1")

    base_env = dict(os.environ)
    base_env.update(_tuning_env(args))
    if args.jax_distributed:
        coord_port = find_free_port(
            "0.0.0.0" if rendezvous_addr != "127.0.0.1" else "127.0.0.1")
        base_env["HOROVOD_JAX_DISTRIBUTED"] = "1"
        base_env["HOROVOD_JAX_COORDINATOR"] = \
            f"{rendezvous_addr}:{coord_port}"

    workers = WorkerProcesses()
    workers.launch(assignments, command, base_env, rendezvous_addr,
                   rendezvous_port, args.ssh_port, args.verbose)
    try:
        return workers.wait()
    except KeyboardInterrupt:
        workers.terminate()
        return 130


def run_commandline(argv: Optional[List[str]] = None) -> int:
    return _run(parse_args(argv))


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
