"""Worker entry point for the programmatic run() API.

Reference analog: horovod/runner/__init__.py's _run_func path (a pickled
function shipped to each worker).  Invoked as:
    python -m horovod_tpu.runner._exec_fn <payload.pkl> <out_dir>
"""

import os
import sys
import traceback


def main() -> int:
    payload_path, out_dir = sys.argv[1], sys.argv[2]
    try:
        import cloudpickle

        with open(payload_path, "rb") as f:
            fn, args, kwargs = cloudpickle.load(f)
        result = fn(*args, **kwargs)
        status, value = "ok", result
    except BaseException as exc:  # noqa: BLE001 - report to parent
        traceback.print_exc()
        status, value = "error", f"{type(exc).__name__}: {exc}"
    # Read the rank only now: elastic workers learn it inside fn (the
    # driver assigns ranks per rendezvous round, not at spawn).
    rank = os.environ.get("HOROVOD_RANK") \
        or os.environ.get("HOROVOD_ELASTIC_WORKER_ID", "0").replace(":", "_")
    try:
        import cloudpickle

        with open(os.path.join(out_dir, f"result_{rank}.pkl"), "wb") as f:
            cloudpickle.dump((status, value), f)
    except Exception:
        traceback.print_exc()
        return 3
    return 0 if status == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
