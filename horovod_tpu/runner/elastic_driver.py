"""Elastic driver: host discovery, worker supervision, rendezvous rounds.

Reference analogs (SURVEY.md §2.5, §3.5): horovod/runner/elastic/driver.py
(ElasticDriver), discovery.py (HostDiscovery/HostDiscoveryScript),
registration.py (host blacklisting), worker.py (notification push).

Design: the driver runs a TCP coordinator server.  Each worker process
holds a persistent JSON-lines connection (horovod_tpu.elastic.client).
The driver forms *generations*: a generation is a set of live workers with
assigned ranks and a fresh rendezvous port for the socket controller.  On a
worker death or a discovery change, the driver pushes ``hosts_updated`` to
the surviving workers, waits for them to tear down and report ``ready``,
spawns replacements on available hosts (failed hosts are blacklisted), and
broadcasts the next generation's assignments.  On TPU pods the discovery
script is typically a queued-resources / metadata poll, so VM preemptions
walk the same path as the reference's GPU host failures.
"""

from __future__ import annotations

import collections
import json
import os
import shlex
import socket
import socketserver
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional

from .util import (forwardable_env, pin_tpu_chip,
                   find_free_port, local_hostnames, make_secret,
                   ssh_command,
                   signed_dumps, verified_loads)

# Defaults; overridable per job via HOROVOD_ELASTIC_* (reference analog:
# the elastic settings object carried from launch.py into the driver).


def _env_number(name: str, default, cast):
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except ValueError:
        print(f"horovod_tpu: ignoring malformed {name}={raw!r} "
              f"(using {default})", file=sys.stderr)
        return default


BLACKLIST_FAILURES = _env_number(
    "HOROVOD_ELASTIC_BLACKLIST_FAILURES", 2, int)
BLACKLIST_BASE_SECS = _env_number(
    "HOROVOD_ELASTIC_BLACKLIST_BASE_SECS", 60.0, float)
DISCOVERY_INTERVAL_S = _env_number(
    "HOROVOD_ELASTIC_DISCOVERY_INTERVAL", 1.0, float)
FAST_FAILURE_S = _env_number(
    "HOROVOD_ELASTIC_FAST_FAILURE_SECS", 15.0, float)


class HostDiscovery:
    """Interface: return the current host set as an ordered {host: slots}."""

    def find_available_hosts(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs a user script that prints one ``host`` or ``host:slots`` per
    line (reference: discovery.py HostDiscoveryScript)."""

    def __init__(self, script: str, default_slots: int = 1):
        self.script = script
        self.default_slots = default_slots

    def find_available_hosts(self) -> Dict[str, int]:
        try:
            out = subprocess.run(
                ["/bin/sh", "-c", self.script], capture_output=True,
                text=True, timeout=30)
        except subprocess.TimeoutExpired as exc:
            raise RuntimeError(
                f"host discovery script timed out after 30s: {exc}") from exc
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed rc={out.returncode}: "
                f"{out.stderr.strip()}")
        hosts: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" in line:
                h, s = line.rsplit(":", 1)
                hosts[h] = int(s)
            else:
                hosts[line] = self.default_slots
        return hosts


class FixedHosts(HostDiscovery):
    def __init__(self, hosts: Dict[str, int]):
        self.hosts = dict(hosts)

    def find_available_hosts(self) -> Dict[str, int]:
        return dict(self.hosts)


class _Worker:
    def __init__(self, host: str, slot: int, worker_id: str,
                 proc: subprocess.Popen, spawn_gen: int, secret: str):
        self.secret = secret
        self.host = host
        self.slot = slot
        self.worker_id = worker_id
        self.proc = proc
        self.spawn_gen = spawn_gen
        self.spawned_at = time.monotonic()
        self.conn = None                  # type: Optional[socket.socket]
        self.wfile = None
        self.registered = threading.Event()
        self.ready = threading.Event()    # ready for next generation
        self.rank: Optional[int] = None
        self.dead = False
        # Free ports probed ON THE WORKER'S HOST, refreshed with each ready
        # message: the rendezvous server and the per-generation
        # jax.distributed coordinator bind on rank 0's host, so only ports
        # probed there are meaningful (ADVICE r2: a driver-side
        # find_free_port may be occupied on the worker host).
        self.free_ports: List[int] = []

    def send(self, obj: dict) -> bool:
        if self.wfile is None:
            return False
        try:
            self.wfile.write(signed_dumps(obj, self.secret) + "\n")
            self.wfile.flush()
            return True
        except OSError:
            return False


class ElasticDriver:
    """Supervises an elastic job (reference: ElasticDriver)."""

    def __init__(self, discovery: HostDiscovery, command: List[str],
                 min_np: int, max_np: Optional[int],
                 base_env: Optional[Dict[str, str]] = None,
                 start_timeout: float = 120.0, verbose: bool = False,
                 ssh_port: Optional[int] = None, autopilot: bool = False,
                 cockpit: bool = False):
        self.discovery = discovery
        self.command = command
        self.min_np = min_np
        self.max_np = max_np
        self.base_env = dict(base_env or os.environ)
        self.start_timeout = start_timeout
        self.verbose = verbose
        self.ssh_port = ssh_port
        # Fleet autopilot: a driver thread polls the coordinator's loopback
        # policy channel for straggler verdicts and feeds persistent
        # offenders into evict_host() (see runner/autopilot.py).
        self.autopilot = autopilot
        self._policy_port: Optional[int] = None
        self._policy_gen = -1
        # Live cockpit (HOROVOD_COCKPIT): rank 0 serves /metrics, /state,
        # and /events on this loopback port.  Chosen ONCE and reused for
        # every generation, so an hvd_top.py SSE client simply reconnects
        # to the same address when a re-formation replaces rank 0.
        self.cockpit = cockpit
        self._cockpit_port: Optional[int] = None
        self._cockpit_gen = -1

        self._lock = threading.Lock()
        self._workers: Dict[str, _Worker] = {}      # worker_id -> worker
        # host -> blacklist expiry (monotonic).  Unlike the reference's
        # permanent blacklist (registration.py), entries EXPIRE with
        # exponential backoff: a preempted-and-restored TPU VM re-enters
        # the pool after BLACKLIST_BASE_SECS, while a host that keeps
        # crash-looping sits out 1x, 2x, 4x, ... the base (capped at 64x).
        self._blacklist: Dict[str, float] = {}
        self._blacklist_counts: Dict[str, int] = {}  # host -> times listed
        self._clock = time.monotonic  # injectable for expiry tests
        self._failures: Dict[str, List[float]] = {}  # host -> failure times
        self._generation = -1
        self._formed_size = 0     # size of the last formed generation
        self._last_target = None  # last successful discovery result
        # Shared secret signing every coordinator RPC (reference:
        # common/util/secret.py): a stray/malicious connection cannot
        # register as a worker or push host updates.
        self._secret = make_secret()
        self._reset_required = threading.Event()
        self._stop = threading.Event()
        self._exit_code: Optional[int] = None
        self._result_ready = threading.Event()
        self._coord_port = None
        self._server = None

    # -- coordinator server --------------------------------------------------
    def _start_server(self) -> None:
        driver = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                worker: Optional[_Worker] = None
                try:
                    for raw in self.rfile:
                        msg = verified_loads(raw.decode(), driver._secret)
                        if msg is None:
                            return  # unauthenticated peer: drop connection
                        t = msg.get("type")
                        if t == "register":
                            worker = driver._on_register(
                                msg, self.connection,
                                self.connection.makefile("w",
                                                         encoding="utf-8"))
                        elif t == "ready" and worker is not None:
                            ports = msg.get("ports")
                            if isinstance(ports, list):
                                worker.free_ports = [
                                    int(p) for p in ports[:4]]
                            worker.ready.set()
                            driver._poke()
                except (OSError, ValueError):
                    pass
                # connection lost: worker death is detected by the process
                # monitor; nothing to do here.

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(("0.0.0.0", 0), Handler)
        self._coord_port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         name="hvd-elastic-coord", daemon=True).start()

    def _on_register(self, msg: dict, conn, wfile) -> Optional[_Worker]:
        wid = msg.get("worker_id", "")
        with self._lock:
            w = self._workers.get(wid)
            if w is None:
                return None
            w.conn, w.wfile = conn, wfile
            w.registered.set()
            w.ready.set()   # registration == ready for first assignment
        self._poke()
        return w

    def _poke(self) -> None:
        self._reset_required.set()

    # -- worker spawning -----------------------------------------------------
    def _spawn(self, host: str, slot: int, gen: int,
               host_slots: int = 1) -> _Worker:
        wid = f"{host}:{slot}:{uuid.uuid4().hex[:8]}"
        env = dict(self.base_env)
        env.update({
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_ELASTIC_WORKER_ID": wid,
            "HOROVOD_ELASTIC_COORD_ADDR": self._coord_addr(host),
            "HOROVOD_ELASTIC_COORD_PORT": str(self._coord_port),
            "HOROVOD_ELASTIC_SECRET": self._secret,
            "HOROVOD_HOSTNAME": host,
        })
        # host_slots counts the slots assigned on this host in THIS
        # generation.  force=True: even a lone elastic worker is pinned to
        # its slot's chip — one that claimed the whole host would collide
        # with workers a later scale-up co-locates.
        pin_tpu_chip(env, slot, host_slots, force=True)
        if host in local_hostnames():
            proc = subprocess.Popen(
                self.command, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
        else:
            # The HMAC secret must NOT ride the ssh argv (visible in `ps`
            # on both ends); ship it over ssh stdin instead.
            env_str = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in env.items()
                if k != "HOROVOD_ELASTIC_SECRET"
                and forwardable_env(k))
            remote = ("read -r HOROVOD_ELASTIC_SECRET; "
                      "export HOROVOD_ELASTIC_SECRET; "
                      f"cd {shlex.quote(os.getcwd())} && env {env_str} " +
                      " ".join(shlex.quote(c) for c in self.command))
            proc = subprocess.Popen(
                ssh_command(ssh_port=self.ssh_port) + [host, remote],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            try:
                proc.stdin.write(self._secret + "\n")
                proc.stdin.flush()
            except OSError:
                pass
        w = _Worker(host, slot, wid, proc, gen, self._secret)
        # Table insert must precede the monitor/stream threads and any
        # chance of the worker registering, so _on_register finds it.
        with self._lock:
            self._workers[wid] = w
        threading.Thread(target=self._stream, args=(w,), daemon=True).start()
        threading.Thread(target=self._monitor, args=(w,), daemon=True).start()
        return w

    def _coord_addr(self, for_host: str) -> str:
        if for_host in local_hostnames():
            return "127.0.0.1"
        return socket.gethostbyname(socket.gethostname())

    def _stream(self, w: _Worker) -> None:
        for line in iter(w.proc.stdout.readline, ""):
            tag = f"[{w.rank if w.rank is not None else '?'}]"
            sys.stdout.write(f"{tag}<stdout>: {line}")
            sys.stdout.flush()

    def _blacklisted(self, host: str, now: Optional[float] = None) -> bool:
        """True while ``host`` is serving a blacklist sentence.  Expired
        entries are dropped on observation (the count persists, so a repeat
        offence doubles the next sentence)."""
        expiry = self._blacklist.get(host)
        if expiry is None:
            return False
        if (self._clock() if now is None else now) >= expiry:
            del self._blacklist[host]
            return False
        return True

    def _blacklist_host(self, host: str, now: float) -> float:
        """(Re-)blacklist ``host``; returns the sentence length in secs."""
        count = self._blacklist_counts.get(host, 0) + 1
        self._blacklist_counts[host] = count
        duration = BLACKLIST_BASE_SECS * (2 ** min(count - 1, 6))
        self._blacklist[host] = now + duration
        return duration

    def _monitor(self, w: _Worker) -> None:
        rc = w.proc.wait()
        now = self._clock()
        with self._lock:
            w.dead = True
            if rc == 0:
                # Normal completion: first clean exit ends the job.
                if self._exit_code is None:
                    self._exit_code = 0
                self._result_ready.set()
                return
            # Blacklist a host only on a crash *loop*: repeated workers that
            # die shortly after spawn (reference: registration.py blacklist).
            if now - w.spawned_at < FAST_FAILURE_S:
                self._failures.setdefault(w.host, []).append(now)
                recent = [t for t in self._failures[w.host]
                          if now - t < 4 * FAST_FAILURE_S]
                self._failures[w.host] = recent
                if (len(recent) >= BLACKLIST_FAILURES
                        and not self._blacklisted(w.host, now)):
                    duration = self._blacklist_host(w.host, now)
                    print(f"elastic driver: blacklisting host {w.host} "
                          f"after {len(recent)} fast failures "
                          f"(expires in {duration:.0f}s)",
                          file=sys.stderr)
        if self.verbose:
            print(f"elastic driver: worker {w.worker_id} exited rc={rc}",
                  file=sys.stderr)
        self._poke()

    # -- generations ---------------------------------------------------------
    def _target_hosts(self) -> Dict[str, int]:
        hosts = self.discovery.find_available_hosts()
        return {h: s for h, s in hosts.items() if not self._blacklisted(h)}

    def _form_generation(self) -> bool:
        """One rendezvous round.  Returns False if the job must abort."""
        gen = self._generation + 1
        try:
            target = self._target_hosts()
            self._last_target = target
        except RuntimeError as exc:
            # A transient discovery blip (metadata-poll timeout, script
            # hiccup) must not tear down a healthy job: reuse the last good
            # host set, matching _discovery_loop's tolerance.  Abort only if
            # discovery has never succeeded.  Re-apply the blacklist — it
            # may have grown since the snapshot was taken.
            prev = self._last_target
            print(f"elastic driver: discovery failed: {exc}"
                  + ("; reusing previous host set" if prev else ""),
                  file=sys.stderr)
            target = {h: s for h, s in (prev or {}).items()
                      if not self._blacklisted(h)}

        cap = self.max_np if self.max_np else sum(target.values())
        slots = []
        for h, s in target.items():
            for i in range(s):
                slots.append((h, i))
        slots = slots[:cap]

        # No-op guard: registrations/ready messages racing the previous
        # formation leave a stale poke behind.  If the already-formed
        # generation is intact — every one of its workers alive and running,
        # they exactly cover the target slots, and no unassigned live worker
        # is waiting — re-forming would interrupt training for nothing (and
        # under load the teardown/re-register round can blow the start
        # timeout).  `running` must equal the full formed size: survivors of
        # a shrunken host set still need the hosts_updated push even when
        # they happen to cover the new, smaller target.
        with self._lock:
            live = [w for w in self._workers.values() if not w.dead]
            running = [w for w in live
                       if w.rank is not None and not w.ready.is_set()]
        if (self._generation >= 0 and running
                and len(running) == len(live)
                and len(running) == self._formed_size
                and len(running) == len(slots)
                and {(w.host, w.slot) for w in running} == set(slots)):
            return True
        for w in live:
            if not w.ready.is_set():
                w.send({"type": "hosts_updated"})

        # Kill workers on hosts that left the set.
        for w in live:
            if w.host not in target or w.slot >= target.get(w.host, 0):
                w.send({"type": "shutdown"})
                try:
                    w.proc.terminate()
                except OSError:
                    pass

        # Spawn missing slots up to max_np.
        with self._lock:
            occupied = {(w.host, w.slot) for w in self._workers.values()
                        if not w.dead and w.host in target}
        slots_per_host = collections.Counter(h for h, _ in slots)
        for (h, i) in slots:
            if (h, i) not in occupied:
                self._spawn(h, i, gen, slots_per_host[h])

        # Wait for every expected worker to be ready (registered + torn
        # down), with a deadline.
        deadline = time.monotonic() + self.start_timeout
        while time.monotonic() < deadline and not self._stop.is_set():
            with self._lock:
                expected = [w for w in self._workers.values()
                            if not w.dead and (w.host, w.slot) in slots]
            if (len(expected) >= max(self.min_np, 1)
                    and all(w.ready.is_set() for w in expected)
                    and len(expected) == len(
                        {(w.host, w.slot) for w in expected})):
                break
            if self._result_ready.is_set():
                return False
            time.sleep(0.05)
        else:
            with self._lock:
                expected = [w for w in self._workers.values()
                            if not w.dead and w.ready.is_set()]
            if len(expected) < self.min_np:
                print("elastic driver: could not reach min_np="
                      f"{self.min_np} within {self.start_timeout}s",
                      file=sys.stderr)
                return False

        # Rank assignment: survivors first (stable low ranks so rank 0's
        # state persists across rounds), then new spawns; ties by host/slot.
        expected.sort(key=lambda w: (w.spawn_gen, w.host, w.slot))
        size = len(expected)
        if size < self.min_np:
            return False
        rdv_host = expected[0].host
        rdv_addr = "127.0.0.1" if rdv_host in local_hostnames() \
            else rdv_host
        # Both the rendezvous server and the per-generation jax.distributed
        # coordinator bind on rank 0's HOST, so prefer ports the rank-0
        # worker probed there (sent with its ready message); a driver-side
        # probe only proves the port is free on the driver.  Fall back for
        # the all-local case and for clients predating the ports field.
        r0_ports = list(expected[0].free_ports)
        if rdv_addr == "127.0.0.1":
            r0_ports = []  # driver shares the host; its own probe is valid
        rdv_port = (r0_ports.pop(0) if r0_ports else
                    find_free_port("0.0.0.0" if rdv_addr != "127.0.0.1"
                                   else "127.0.0.1"))
        # Fresh jax.distributed coordinator per generation, hosted by the
        # new rank 0: a static launch-time coordinator would (a) live on a
        # possibly-preempted host and (b) race the old coordinator's port
        # release on rank reassignment.  Workers apply it only when the job
        # runs with HOROVOD_JAX_DISTRIBUTED=1.
        jax_coord = "%s:%d" % (rdv_addr, r0_ports.pop(0) if r0_ports else
                               find_free_port(
                                   "0.0.0.0" if rdv_addr != "127.0.0.1"
                                   else "127.0.0.1"))
        # Autopilot policy channel: the coordinator (rank 0) opens a
        # LOOPBACK listener on this port, so the channel only works when
        # the driver shares rank 0's host (the single-controller pod
        # topology the autopilot targets).  Remote rank 0 → no port, the
        # autopilot idles for the generation.
        policy_port = None
        if self.autopilot and rdv_addr == "127.0.0.1":
            policy_port = (r0_ports.pop(0) if r0_ports
                           else find_free_port("127.0.0.1"))
        # Cockpit endpoint: same loopback trust boundary as the policy
        # channel, but the port is sticky across generations (picked on the
        # first local-rank-0 formation, reused after) so live SSE clients
        # survive a re-formation by reconnecting to the address they know.
        cockpit_port = None
        if self.cockpit and rdv_addr == "127.0.0.1":
            if self._cockpit_port is None:
                self._cockpit_port = (r0_ports.pop(0) if r0_ports
                                      else find_free_port("127.0.0.1"))
            cockpit_port = self._cockpit_port
        local_sizes = collections.Counter(w.host for w in expected)
        local_seen: Dict[str, int] = {}
        hosts_order = list(dict.fromkeys(w.host for w in expected))
        for rank, w in enumerate(expected):
            w.rank = rank
            w.ready.clear()
            lr = local_seen.get(w.host, 0)
            local_seen[w.host] = lr + 1
            w.send({
                "type": "assign", "generation": gen, "rank": rank,
                "size": size, "local_rank": lr,
                "local_size": local_sizes[w.host],
                "cross_rank": hosts_order.index(w.host),
                "cross_size": len(hosts_order),
                "rendezvous_addr": rdv_addr,
                "rendezvous_port": rdv_port,
                "jax_coordinator": jax_coord,
                "policy_port": policy_port,
                "cockpit_port": cockpit_port,
            })
        self._generation = gen
        self._formed_size = size
        self._policy_port = policy_port
        self._policy_gen = gen
        self._cockpit_gen = gen if cockpit_port is not None else -1
        if self.verbose:
            print(f"elastic driver: generation {gen} formed with {size} "
                  f"worker(s)", file=sys.stderr)
        return True

    # -- fleet autopilot hooks -----------------------------------------------
    def evict_host(self, host: str, reason: str = "") -> float:
        """Autopilot entry: sentence ``host`` to the elastic blacklist (the
        same expiring, exponentially-backed-off sentence a crash loop earns)
        and trigger a re-formation.  The shrink drops its workers; the
        sentence expiry re-admits the host via the discovery loop's poke.
        Returns the sentence length in seconds."""
        with self._lock:
            duration = self._blacklist_host(host, self._clock())
        print(f"elastic driver: autopilot evicted host {host}"
              f" ({reason or 'persistent straggler'}; "
              f"re-admitted in {duration:.0f}s)", file=sys.stderr)
        self._poke()
        return duration

    def live_slots_on(self, host: str) -> int:
        """Live (non-dead) workers currently on ``host``."""
        with self._lock:
            return sum(1 for w in self._workers.values()
                       if not w.dead and w.host == host)

    def live_size(self) -> int:
        with self._lock:
            return sum(1 for w in self._workers.values() if not w.dead)

    def policy_endpoint(self):
        """(generation, port) of the current coordinator's loopback policy
        listener, or (gen, None) when unavailable this generation."""
        return self._policy_gen, self._policy_port

    def cockpit_endpoint(self):
        """(generation, port) of the live cockpit on the current rank 0,
        or (gen, None) when the cockpit is off or rank 0 is remote.  The
        port is stable across generations by construction."""
        return self._cockpit_gen, (
            self._cockpit_port if self._cockpit_gen >= 0 else None)

    # -- main loop -----------------------------------------------------------
    def run(self) -> int:
        self._start_server()
        discovery_thread = threading.Thread(
            target=self._discovery_loop, daemon=True)
        discovery_thread.start()
        if self.autopilot:
            from .autopilot import FleetAutopilot

            self._autopilot = FleetAutopilot(self)
            threading.Thread(target=self._autopilot.run,
                             name="hvd-autopilot", daemon=True).start()
        self._reset_required.set()
        while not self._stop.is_set():
            if self._result_ready.is_set():
                break
            if self._reset_required.wait(timeout=0.2):
                self._reset_required.clear()
                # Debounce: let closely-spaced failures coalesce.
                time.sleep(0.1)
                if self._result_ready.is_set():
                    break
                if not self._form_generation():
                    if self._exit_code is None:
                        self._exit_code = 1
                    break
        self._shutdown_workers()
        if self._server:
            self._server.shutdown()
        return self._exit_code if self._exit_code is not None else 1

    def _discovery_loop(self) -> None:
        prev: Optional[Dict[str, int]] = None
        while not self._stop.is_set() and not self._result_ready.is_set():
            try:
                cur = self._target_hosts()
            except RuntimeError:
                cur = prev
            if prev is not None and cur != prev:
                self._poke()
            prev = cur
            time.sleep(DISCOVERY_INTERVAL_S)

    def _shutdown_workers(self) -> None:
        self._stop.set()
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if not w.dead:
                try:
                    w.proc.terminate()
                except OSError:
                    pass


def run_elastic(args, command: List[str]) -> int:
    """Entry from the launcher CLI (reference: launch.py _run_elastic)."""
    if getattr(args, "tpu_discovery", False):
        from .tpu_discovery import TPUPodDiscovery

        discovery: HostDiscovery = TPUPodDiscovery(args.slots_per_host)
    elif args.host_discovery_script:
        discovery = HostDiscoveryScript(
            args.host_discovery_script, args.slots_per_host)
    elif args.hosts:
        from .util import parse_hosts

        discovery = FixedHosts(
            {h.hostname: h.slots for h in parse_hosts(args.hosts)})
    else:
        discovery = FixedHosts({"localhost": args.num_proc or 1})
    min_np = args.min_np if args.min_np is not None else (args.num_proc or 1)
    max_np = args.max_np

    from .launch import _tuning_env

    base_env = dict(os.environ)
    base_env.update(_tuning_env(args))
    driver = ElasticDriver(discovery, command, min_np, max_np, base_env,
                           start_timeout=args.start_timeout,
                           verbose=args.verbose, ssh_port=args.ssh_port,
                           autopilot=getattr(args, "autopilot", False),
                           cockpit=getattr(args, "cockpit", False))
    return driver.run()
